"""Kernel-dispatcher contract: backend selection, fallbacks, engine identity.

Covers the dispatch layer itself (``ops/kernels/dispatch.py``) — the parity
of the kernels' MATH is ``test_kernel_parity.py``; here we pin WHICH lowering
runs and how the engine folds the choice into its program identity.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu.analysis import trace_primitive_counts
from metrics_tpu.ops.kernels import (
    BACKENDS,
    fold_rows_masked,
    histogram_accumulate,
    resolve_backend,
    segment_reduce_masked,
    set_default_backend,
    use_backend,
)
from metrics_tpu.ops.kernels.dispatch import MAX_HIST_LENGTH
from metrics_tpu.utils.exceptions import MetricsTPUUserError


def _has_pallas_call(fn, *args) -> bool:
    # the rule engine's trace helper builds a FRESH closure per call: JAX
    # caches traces by FUNCTION IDENTITY + avals, so re-tracing the same
    # function object under a different kernel backend would silently reuse
    # the first backend's jaxpr (the walk itself — recursing into pallas_call
    # kernel bodies — lives once in metrics_tpu/analysis/program.py)
    return trace_primitive_counts(fn, *args).get("pallas_call", 0) > 0


def test_resolution_rules():
    assert resolve_backend("xla") == "xla"
    assert resolve_backend("pallas") == "pallas"
    assert resolve_backend("pallas_interpret") == "pallas_interpret"
    # auto: platform-derived, never "auto" itself
    assert resolve_backend("auto") in ("pallas", "xla")
    if jax.default_backend() == "cpu":
        assert resolve_backend("auto") == "xla"
    with pytest.raises(ValueError, match="unknown kernel backend"):
        resolve_backend("triton")
    with pytest.raises(ValueError, match="unknown kernel backend"):
        with use_backend("nope"):
            pass  # pragma: no cover


def test_default_backend_setter_restores():
    old = resolve_backend()
    try:
        set_default_backend("xla")
        assert resolve_backend() == "xla"
        with use_backend("pallas_interpret"):
            assert resolve_backend() == "pallas_interpret"
        assert resolve_backend() == "xla"
    finally:
        set_default_backend("auto")
    assert resolve_backend() == old


def test_backend_decides_lowering():
    """The jaxpr proves which path traced: pallas_call present iff a Pallas
    backend is selected and the input is eligible."""
    state = jnp.zeros((4,), jnp.float32)
    rows = jnp.ones((16, 4), jnp.float32)
    mask = jnp.ones((16,), bool)

    def fold(s, r, m):
        return fold_rows_masked(s, r, m, "sum")

    with use_backend("xla"):
        assert not _has_pallas_call(fold, state, rows, mask)
    with use_backend("pallas_interpret"):
        assert _has_pallas_call(fold, state, rows, mask)


@pytest.mark.parametrize(
    "case",
    ["huge_feature_dim", "narrow_int_dtype", "long_histogram", "int_weights"],
)
def test_ineligible_inputs_fall_back_not_error(case):
    """Inputs the Pallas path cannot serve route to XLA under EVERY backend —
    the dispatcher degrades, it never raises."""
    with use_backend("pallas_interpret"):
        if case == "huge_feature_dim":
            # one row alone exceeds the VMEM block budget
            f = (1 << 19) // 4 + 128
            state = jnp.zeros((f,), jnp.float32)
            rows = jnp.zeros((4, f), jnp.float32)
            out = fold_rows_masked(state, rows, jnp.ones((4,), bool), "sum")
            assert not _has_pallas_call(
                lambda s, r, m: fold_rows_masked(s, r, m, "sum"), state, rows, jnp.ones((4,), bool)
            )
            assert out.shape == (f,)
        elif case == "narrow_int_dtype":
            # int8 sums PROMOTE under jnp — the XLA ref preserves that, the
            # Pallas path opts out rather than mismatching
            rows = jnp.ones((8, 2), jnp.int8)
            out = segment_reduce_masked(
                jnp.zeros((3, 2), jnp.int8), rows, jnp.ones((8,), bool),
                jnp.zeros((8,), jnp.int32), 3, "sum",
            )
            assert out.shape == (3, 2)
            assert int(out[0, 0]) == 8
        elif case == "long_histogram":
            idx = jnp.zeros((16,), jnp.int32)
            out = histogram_accumulate(idx, MAX_HIST_LENGTH + 1)
            assert int(out[0]) == 16
        else:  # integer weights keep XLA's exact integer accumulation
            idx = jnp.asarray([0, 1, 1, 2], jnp.int32)
            w = jnp.asarray([1, 2, 3, 4], jnp.int32)
            out = histogram_accumulate(idx, 3, weights=w)
            assert out.tolist() == [1, 5, 4]


def test_bincount_routes_through_dispatcher():
    from metrics_tpu.utils.data import _bincount

    x = jnp.asarray([0, 2, 2, 5, 9], jnp.int32)
    with use_backend("pallas_interpret"):
        assert _has_pallas_call(lambda v: _bincount(v, 10), x)
        got = _bincount(x, 10)
    with use_backend("xla"):
        assert not _has_pallas_call(lambda v: _bincount(v, 10), x)
        want = _bincount(x, 10)
    assert bool(jnp.all(got == want))
    assert bool(jnp.all(want == jnp.bincount(x, length=10)))


def test_confusion_family_parity_across_backends():
    from metrics_tpu.functional import calibration_error, confusion_matrix

    rng = np.random.RandomState(3)
    preds = jnp.asarray(rng.randint(0, 4, 64).astype(np.int32))
    target = jnp.asarray(rng.randint(0, 4, 64).astype(np.int32))
    probs = jnp.asarray(rng.dirichlet(np.ones(4), 64).astype(np.float32))
    with use_backend("xla"):
        cm_x = confusion_matrix(preds, target, num_classes=4)
        ce_x = calibration_error(probs, target, n_bins=10)
    with use_backend("pallas_interpret"):
        cm_p = confusion_matrix(preds, target, num_classes=4)
        ce_p = calibration_error(probs, target, n_bins=10)
    assert bool(jnp.all(cm_x == cm_p))  # integer counts: bit parity
    assert abs(float(ce_x) - float(ce_p)) < 1e-6


def test_engine_program_identity_includes_backend(tmp_path):
    """Two engines over the SAME metric/config but different kernel backends
    sharing one AotCache must compile disjoint program sets (a shared key
    would hand one engine the other's lowering)."""
    from metrics_tpu import Accuracy
    from metrics_tpu.engine import AotCache, EngineConfig, StreamingEngine

    cache = AotCache()
    misses = {}
    for kb in ("xla", "pallas_interpret"):
        e = StreamingEngine(Accuracy(), EngineConfig(buckets=(8,), kernel_backend=kb), aot_cache=cache)
        before = cache.misses
        with e:
            e.submit(np.random.rand(5).astype(np.float32), np.zeros(5, np.int32))
            float(e.result())
        misses[kb] = cache.misses - before
    assert misses["xla"] > 0 and misses["pallas_interpret"] > 0
    # and an invalid backend name fails at CONSTRUCTION time
    with pytest.raises(ValueError, match="unknown kernel backend"):
        StreamingEngine(Accuracy(), EngineConfig(buckets=(8,), kernel_backend="mosaic"))


def test_engine_pins_backend_at_construction():
    """kernel_backend=None inherits the selection ambient at CONSTRUCTION and
    pins it: a use_backend context active at result()/submit() time must not
    change the engine's lowering (update and compute programs would otherwise
    split across backends — they build on different threads)."""
    from metrics_tpu import Accuracy
    from metrics_tpu.engine import EngineConfig, StreamingEngine

    with use_backend("pallas_interpret"):
        e = StreamingEngine(Accuracy(), EngineConfig(buckets=(8,)))
    assert e._kernel_tag() == "pallas_interpret"
    with use_backend("xla"):  # ambient context later: no effect on the pin
        assert e._kernel_tag() == "pallas_interpret"
        with e:
            e.submit(np.random.rand(5).astype(np.float32), np.zeros(5, np.int32))
            float(e.result())
    # and the explicit config always wins over the ambient context
    with use_backend("pallas_interpret"):
        e2 = StreamingEngine(Accuracy(), EngineConfig(buckets=(8,), kernel_backend="xla"))
    assert e2._kernel_tag() == "xla"


def test_multistream_serves_on_interpret_backend():
    """MultiStreamEngine end-to-end on the interpret backend: per-stream
    results equal per-stream eager accumulation."""
    from metrics_tpu import Accuracy
    from metrics_tpu.engine import EngineConfig, MultiStreamEngine

    rng = np.random.RandomState(0)
    batches = [
        (s % 3, rng.rand(n).astype(np.float32), (rng.rand(n) > 0.5).astype(np.int32))
        for s, n in enumerate((5, 7, 8, 3, 6, 4))
    ]
    eager = {s: Accuracy() for s in range(3)}
    for s, p, t in batches:
        eager[s].update(p, t)
    engine = MultiStreamEngine(
        Accuracy(), num_streams=3,
        config=EngineConfig(buckets=(8, 16), kernel_backend="pallas_interpret"),
    )
    with engine:
        for s, p, t in batches:
            engine.submit(s, p, t)
        for s in range(3):
            assert abs(float(engine.result(s)) - float(eager[s].compute())) < 1e-6
