"""Attribution proof of the kernel fusion (ISSUE 4 acceptance).

Uses the PR-1 attribution layer (``metrics_tpu/ops/profiling.py`` /
``tools/profile_hlo.py``) plus direct jaxpr inspection to show what the
Pallas backend actually changes in the lowered update step:

* under ``xla``, the masked fold materializes identity-substituted
  ``(rows, *state)`` select/reduce intermediates and the segmented update
  lowers to ``scatter`` ops;
* under a Pallas backend, the fold/scatter work lives INSIDE ``pallas_call``
  eqns — no top-level ``reduce_*`` over row-stacked state deltas, no
  ``scatter`` at all. The streaming pass replaces the materialize-then-reduce
  pattern.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import Accuracy
from metrics_tpu.analysis import check_no_scatter_under_pallas, iter_eqns, primitive_names
from metrics_tpu.ops.kernels import use_backend
from metrics_tpu.ops.profiling import op_costs


def _eqn_names(fn, *args):
    # fresh closure per trace (kernel-backend contexts change the lowering);
    # the recursive walk lives once in metrics_tpu/analysis/program.py
    return primitive_names(jax.make_jaxpr(lambda *a: fn(*a))(*args))


def _outside_kernel_names(fn, *args):
    # primitive names OUTSIDE pallas_call kernel bodies: the analysis walk
    # descends into the kernels (paths carry 'pallas_call@'), so 'outside'
    # is every eqn whose path has no kernel ancestor
    jaxpr = jax.make_jaxpr(lambda *a: fn(*a))(*args)
    return [
        eqn.primitive.name
        for path, eqn in iter_eqns(jaxpr)
        if "pallas_call@" not in path.rsplit("/", 1)[0]
    ]


@pytest.fixture
def masked_inputs():
    rng = np.random.RandomState(0)
    n = 32
    m = Accuracy()
    state = m.init_state()
    preds = jnp.asarray(rng.rand(n).astype(np.float32))
    target = jnp.asarray((rng.rand(n) > 0.5).astype(np.int32))
    mask = jnp.asarray(rng.rand(n) > 0.3)
    return m, state, preds, target, mask


def test_masked_update_fusion_attribution(masked_inputs):
    m, state, preds, target, mask = masked_inputs

    def step_fn(s, p, t, mk):
        return m.update_state_masked(s, p, t, mask=mk)

    with use_backend("xla"):
        xla_names = _eqn_names(step_fn, state, preds, target, mask)
    with use_backend("pallas_interpret"):
        k_names = _eqn_names(step_fn, state, preds, target, mask)

    assert "pallas_call" not in xla_names
    n_leaves = len(state)
    # one fused kernel per state leaf; the fold's select/reduce pattern is
    # gone from the surrounding program (it lives inside the kernels now)
    assert k_names.count("pallas_call") == n_leaves
    with use_backend("pallas_interpret"):
        outside = [
            x for x in _outside_kernel_names(step_fn, state, preds, target, mask)
            if x != "pallas_call"
        ]
    # the vmapped per-row delta computation legitimately keeps row-shaped
    # elementwise work; what must vanish OUTSIDE the kernels is the fold
    # itself — reduce ops over the stacked deltas
    assert outside.count("reduce_sum") < xla_names.count("reduce_sum")


def test_segmented_update_scatter_free(masked_inputs):
    m, state, preds, target, mask = masked_inputs
    s_streams = 4
    stacked = jax.tree.map(
        lambda x: jnp.tile(jnp.asarray(x)[None], (s_streams,) + (1,) * jnp.ndim(x)), state
    )
    ids = jnp.asarray(np.random.RandomState(1).randint(0, s_streams, mask.shape[0]), jnp.int32)

    def step_fn(s, p, t, mk):
        return m.update_state_segmented(
            s, p, t, mask=mk, segment_ids=ids, num_segments=s_streams
        )

    with use_backend("xla"):
        xla_names = _eqn_names(step_fn, stacked, preds, target, mask)
        xla_jaxpr = jax.make_jaxpr(lambda *a: step_fn(*a))(stacked, preds, target, mask)
    with use_backend("pallas_interpret"):
        k_names = _eqn_names(step_fn, stacked, preds, target, mask)
        k_jaxpr = jax.make_jaxpr(lambda *a: step_fn(*a))(stacked, preds, target, mask)

    # the XLA lowering scatters into identity-filled bases (the rule FIRES on
    # it); the kernel path carries NO scatter anywhere in the program (the
    # no-scatter-under-pallas rule passes) — the PR-4 pin, now a named rule
    assert any(n.startswith("scatter") for n in xla_names)
    assert check_no_scatter_under_pallas(xla_jaxpr, where="xla-lowering") != []
    assert check_no_scatter_under_pallas(k_jaxpr, where="kernel-lowering") == []
    assert k_names.count("pallas_call") == len(state)


def test_profile_hlo_attribution_sees_through_kernel(masked_inputs):
    """The PR-1 attribution walk (``ops/profiling.py::op_costs``) descends
    INTO the pallas_call's kernel jaxpr: the Pallas lowering's cost rows carry
    kernel-interior primitives (``get``/``swap`` ref ops, ``program_id``) the
    XLA lowering cannot contain, while the total analytic FLOPs of the two
    lowerings stay comparable — the kernels MOVE the fold, they don't change
    the math. This is the per-kernel attribution hook the microbench's claims
    rest on (docs/benchmarking.md, "Kernel microbench")."""
    m, state, preds, target, mask = masked_inputs

    def step_fn(s, p, t, mk):
        return m.update_state_masked(s, p, t, mask=mk)

    with use_backend("xla"):
        xla_ops = op_costs(lambda *a: step_fn(*a), state, preds, target, mask)
    with use_backend("pallas_interpret"):
        k_ops = op_costs(lambda *a: step_fn(*a), state, preds, target, mask)
    xla_kinds = {o.kind for o in xla_ops}
    k_kinds = {o.kind for o in k_ops}
    assert {"get", "swap", "program_id"} & k_kinds, k_kinds
    assert not ({"get", "swap", "program_id"} & xla_kinds)
    fl_x = sum(o.flops for o in xla_ops)
    fl_k = sum(o.flops for o in k_ops)
    assert fl_x > 0 and 0.25 < fl_k / fl_x < 4.0
