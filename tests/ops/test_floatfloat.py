"""Float-float arithmetic (``metrics_tpu/ops/floatfloat.py``) vs numpy f64.

These ops only work if XLA compiles the error-term expressions verbatim (no
reassociation). Every test therefore runs the op *under jit* and checks the
recovered hi+lo value against a float64 oracle — if a backend ever turned on
fast-math, the compensated error would collapse to 0 and these fail loudly.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from metrics_tpu.ops import floatfloat as ff


def _pair_to_f64(p):
    return np.float64(np.asarray(p[0], np.float64)) + np.float64(np.asarray(p[1], np.float64))


def test_two_sum_exact_under_jit():
    a = np.float32(1e8)
    b = np.float32(1.2345)
    s, e = jax.jit(ff.two_sum)(jnp.float32(a), jnp.float32(b))
    assert np.float64(s) + np.float64(e) == np.float64(a) + np.float64(b)
    assert float(e) != 0.0  # the error term survived compilation


def test_two_prod_exact_under_jit():
    rng = np.random.RandomState(0)
    a = rng.randn(1000).astype(np.float32)
    b = rng.randn(1000).astype(np.float32)
    p, e = jax.jit(ff.two_prod)(jnp.asarray(a), jnp.asarray(b))
    exact = a.astype(np.float64) * b.astype(np.float64)
    np.testing.assert_array_equal(np.asarray(p, np.float64) + np.asarray(e, np.float64), exact)


def test_compensated_accumulation_beats_naive():
    """Summing 100k values spanning 12 decades: naive f32 ~1e-7 rel error,
    the pair stays at f64-rounding level."""
    rng = np.random.RandomState(1)
    xs = (rng.randn(200, 500) * np.logspace(-6, 6, 500)).astype(np.float32)
    exact = np.sum(xs.astype(np.float64))

    @jax.jit
    def run(batch_sums):
        def body(carry, v):
            return ff.ff_add_f32(carry, v), None
        init = (jnp.float32(0), jnp.float32(0))
        out, _ = jax.lax.scan(body, init, batch_sums)
        return out

    # pre-reduce each batch once in f32 so the accumulator's error is isolated
    # from per-batch reduction rounding (the oracle sums the same f32 values)
    batch_sums = jnp.sum(jnp.asarray(xs), axis=1)
    exact_of_batches = np.sum(np.asarray(batch_sums, np.float64))
    pair = run(batch_sums)
    naive = float(jnp.sum(batch_sums))
    err_pair = abs(_pair_to_f64(pair) - exact_of_batches) / abs(exact)
    err_naive = abs(naive - exact_of_batches) / abs(exact)
    assert err_pair < 1e-12, err_pair
    assert err_pair <= err_naive


@pytest.mark.parametrize("op,np_op", [
    (ff.ff_add, np.add), (ff.ff_sub, np.subtract), (ff.ff_mul, np.multiply),
])
def test_pair_ops_match_f64(op, np_op):
    rng = np.random.RandomState(2)
    # build genuine pairs (hi + small lo) so the ops must honour both halves
    x64 = rng.randn(1000) * 1e4
    y64 = rng.randn(1000)
    x = (jnp.asarray(x64, jnp.float32), jnp.asarray(x64 - np.float32(x64), jnp.float32))
    y = (jnp.asarray(y64, jnp.float32), jnp.asarray(y64 - np.float32(y64), jnp.float32))
    got = _pair_to_f64(jax.jit(op)(x, y))
    want = np_op(_pair_to_f64(x), _pair_to_f64(y))
    np.testing.assert_allclose(got, want, rtol=1e-13, atol=1e-10)


def test_ff_scale():
    rng = np.random.RandomState(3)
    x64 = rng.randn(100) * 1e6
    x = (jnp.asarray(x64, jnp.float32), jnp.asarray(x64 - np.float32(x64), jnp.float32))
    got = _pair_to_f64(jax.jit(ff.ff_scale)(x, jnp.float32(1.0 / 3.0)))
    want = _pair_to_f64(x) * np.float64(np.float32(1.0 / 3.0))
    np.testing.assert_allclose(got, want, rtol=1e-13)


def test_centered_chan_in_pairs_survives_offset():
    """The FID design in miniature: a variance with a large common offset.

    The *raw-moment* form (Σx² − n·μ²) is unrecoverable in f32 — even with a
    compensated accumulator, each per-batch f32 reduction of x²~1e4-magnitude
    values already rounds away the 1e-6-magnitude answer. The centered Chan
    combine keeps every accumulated quantity at O(variance), and pairs keep the
    thousands of combines drift-free: ~6 digits of the true variance survive."""
    rng = np.random.RandomState(4)
    n = 50000
    x = (rng.randn(n) * 1e-3 + 100.0).astype(np.float32)
    exact_var = np.var(x.astype(np.float64), ddof=1)

    @jax.jit
    def chan_var(batches):
        def body(carry, batch):
            mean_a, m2_a, n_a = carry
            bn = jnp.float32(batch.shape[0])
            bm = jnp.mean(batch)
            bm2 = jnp.sum((batch - bm) ** 2)
            nb = n_a + bn
            frac = bn / jnp.maximum(nb, 1.0)
            w = n_a * bn / jnp.maximum(nb, 1.0)
            delta = ff.ff_sub(ff.ff_from_f32(bm), mean_a)
            mean = ff.ff_add(mean_a, ff.ff_scale(delta, frac))
            m2 = ff.ff_add(ff.ff_add_f32(m2_a, bm2), ff.ff_scale(ff.ff_mul(delta, delta), w))
            return (mean, m2, nb), None

        init = ((jnp.float32(0),) * 2, (jnp.float32(0),) * 2, jnp.float32(0))
        (mean, m2, nn), _ = jax.lax.scan(body, init, batches)
        return ff.ff_to_f32(ff.ff_scale(m2, 1.0 / (nn - 1.0)))

    got = float(chan_var(jnp.asarray(x).reshape(500, -1)))
    naive = float(jnp.sum(jnp.asarray(x) ** 2) - n * jnp.mean(jnp.asarray(x)) ** 2) / (n - 1)
    # the pairs protect the ACCUMULATION; the per-batch f32 mean/sum round a
    # shade worse on accelerators (measured 1.03e-4 rel on v5e vs ~1e-5 CPU)
    from tests.helpers.testers import _on_accelerator

    bar = 5e-4 if _on_accelerator() else 1e-4
    assert abs(got - exact_var) / exact_var < bar, (got, exact_var)
    assert abs(got - exact_var) < abs(naive - exact_var)
