"""Per-op cost attribution: analytic FLOP/byte/tile math and table schema.

The attribution layer is what turns "the stem wastes the MXU" from an
assertion into a sorted table (ISSUE 1 tentpole; VERDICT r5 weak #1/#2), so
its own numbers need pinning: GEMM geometry for conv/dot, the 128-lane /
8-sublane structural tile efficiency, scan trip-count multiplication, group
aggregation, and the exact schema ``tools/profile_hlo.py`` emits.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.ops.profiling import (
    _mxu_efficiency,
    attribution_table,
    format_table,
    group_costs,
    op_costs,
    single_program_calibration,
)


def test_mxu_efficiency_tile_math():
    # full tiles -> 1.0
    assert _mxu_efficiency(8, 128, 128) == pytest.approx(1.0)
    assert _mxu_efficiency(16, 256, 512) == pytest.approx(1.0)
    # half-filled N lanes -> 0.5; compounding under-fill multiplies
    assert _mxu_efficiency(8, 128, 64) == pytest.approx(0.5)
    assert _mxu_efficiency(4, 64, 64) == pytest.approx(0.5 * 0.5 * 0.5)
    assert _mxu_efficiency(0, 128, 128) == 0.0


def test_dot_general_flops_and_geometry():
    a = jnp.zeros((32, 64))
    b = jnp.zeros((64, 128))
    ops = op_costs(lambda x, y: x @ y, a, b)
    dots = [o for o in ops if o.kind == "dot_general"]
    assert len(dots) == 1
    (dot,) = dots
    assert dot.flops == pytest.approx(2 * 32 * 64 * 128)
    assert tuple(dot.gemm_mkn) == (32, 64, 128)
    assert dot.mxu_util == pytest.approx(0.5)  # K=64 under-fills the 128 lanes
    # operands + result traffic in f32
    assert dot.bytes == pytest.approx(4 * (32 * 64 + 64 * 128 + 32 * 128))


def test_conv_flops_match_direct_count():
    x = jnp.zeros((2, 16, 16, 32))
    k = jnp.zeros((3, 3, 32, 64))

    def conv(x, k):
        return jax.lax.conv_general_dilated(
            x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )

    ops = op_costs(conv, x, k)
    convs = [o for o in ops if o.kind == "conv_general_dilated"]
    assert len(convs) == 1
    (c,) = convs
    m, kk, n = c.gemm_mkn
    assert (m, kk, n) == (2 * 16 * 16, 3 * 3 * 32, 64)
    assert c.flops == pytest.approx(2.0 * m * kk * n)


def test_scan_trip_count_multiplies():
    a = jnp.zeros((8, 8))

    def scanned(x):
        def body(carry, _):
            return carry @ x, ()

        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    ops = op_costs(scanned, a)
    total = sum(o.flops for o in ops if o.kind == "dot_general")
    assert total == pytest.approx(5 * 2 * 8 * 8 * 8)


def test_cond_branches_costed_as_max_branch():
    """Ops inside ``lax.cond`` must not be dropped; the walk takes the most
    expensive branch (exactly one executes, so that's the per-run bound)."""
    a = jnp.ones((64, 64))

    def f(x):
        return jax.lax.cond(
            x[0, 0] > 0.0,
            lambda v: (v @ v @ v).sum(),   # 2 dots
            lambda v: (v @ v).sum(),       # 1 dot
            x,
        )

    total = sum(o.flops for o in op_costs(f, a) if o.kind == "dot_general")
    assert total == pytest.approx(2 * 2 * 64**3)


def test_group_rows_schema_and_shares():
    a = jnp.zeros((16, 128))
    b = jnp.zeros((128, 128))
    rows = group_costs(op_costs(lambda x, y: jnp.tanh(x @ y), a, b))
    assert rows, "expected at least one group row"
    for row in rows:
        assert set(row) == {"name", "flops", "bytes", "flops_pct", "mxu_util", "ideal_time_share"}
    assert sum(r["flops_pct"] for r in rows) == pytest.approx(100.0)
    assert sum(r["ideal_time_share"] for r in rows) == pytest.approx(100.0)


def test_attribution_table_schema_and_xla_crosscheck():
    a = jnp.zeros((64, 256))
    b = jnp.zeros((256, 128))
    table = attribution_table(lambda x, y: x @ y, a, b)
    assert set(table) == {
        "total_flops", "total_bytes", "xla_cost_flops",
        "structural_mfu_ceiling", "rows", "ops",
    }
    assert table["total_flops"] == pytest.approx(2 * 64 * 256 * 128)
    # CPU backend exposes cost_analysis; the analytic count must agree closely
    if table["xla_cost_flops"] is not None:
        assert table["xla_cost_flops"] == pytest.approx(table["total_flops"], rel=0.01)
    assert 0 < table["structural_mfu_ceiling"] <= 1.0
    for op in table["ops"]:
        assert set(op) == {"name", "kind", "flops", "bytes", "out_shape", "mxu_util", "gemm_mkn"}
    md = format_table(table)
    assert md.splitlines()[0].startswith("| layer |")
    assert "structural MFU ceiling" in md


def test_single_program_calibration_schema_and_sanity():
    """The calibration must run on any backend (tiny matmul here) and return
    self-consistent fields: positive marginals, achieved = flops/work_s, and
    the ratio equal to achieved/ceiling — the (0, 1] guarantee itself is a
    same-accelerator property only a real device pool can exercise."""
    x = jnp.ones((16, 16), jnp.float32)

    def body(ops_, i):
        (v,) = ops_
        return jnp.sum(jnp.roll(v, i, axis=0) @ v)

    flops = 2.0 * 16**3
    out = single_program_calibration(
        body, (x,), flops_per_iter=flops,
        matmul_n=128, k_pair=(2, 6), m_pair=(2, 6), trials=2,
    )
    assert set(out) == {
        "work_s_per_iter", "matmul_s_per_iter", "in_program_matmul_tflops",
        "achieved_tflops", "mfu_vs_in_program_ceiling", "timings_s", "protocol",
    }
    assert out["work_s_per_iter"] > 0 and out["matmul_s_per_iter"] > 0
    assert out["achieved_tflops"] == pytest.approx(
        flops / out["work_s_per_iter"] / 1e12
    )
    assert out["mfu_vs_in_program_ceiling"] == pytest.approx(
        out["achieved_tflops"] / out["in_program_matmul_tflops"]
    )
    assert out["timings_s"]["k_pair"] == [2, 6]


def test_structural_ceiling_penalizes_narrow_gemms():
    wide = attribution_table(lambda x, y: x @ y, jnp.zeros((128, 128)), jnp.zeros((128, 128)))
    narrow = attribution_table(lambda x, y: x @ y, jnp.zeros((128, 32)), jnp.zeros((32, 32)))
    assert wide["structural_mfu_ceiling"] == pytest.approx(1.0)
    assert narrow["structural_mfu_ceiling"] == pytest.approx(0.25 * 0.25)
