"""Interpret-mode bit-parity of the Pallas kernel library vs the XLA path.

The ISSUE-4 acceptance matrix: every kernel (masked fold, masked segment
reduce, fused histogram) × dtypes × mask patterns × segment shapes, comparing
the ``pallas_interpret`` backend (the exact kernel logic, interpreted) against
the ``xla`` reference lowering. Int outputs must be BIT-exact; float outputs
within ULP-scale reassociation tolerance (the kernels reduce blocks in a
different association order than XLA's scatter/reduce — same class of
difference as any reduction re-order).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.ops.kernels import (
    fold_rows_masked,
    histogram_accumulate,
    segment_reduce_masked,
    use_backend,
)

_RTOL = 1e-6
_ATOL = 1e-5


def _maxerr(a, b) -> float:
    return float(np.max(np.abs(np.asarray(a, np.float64) - np.asarray(b, np.float64)))) if np.size(a) else 0.0


def _both(fn):
    with use_backend("xla"):
        want = fn()
    with use_backend("pallas_interpret"):
        got = fn()
    return want, got


def _mask(pattern: str, n: int, rng) -> np.ndarray:
    if pattern == "all":
        return np.ones(n, bool)
    if pattern == "none":
        return np.zeros(n, bool)
    if pattern == "first":
        m = np.zeros(n, bool)
        m[0] = True
        return m
    return rng.rand(n) > 0.5


_DTYPES = ("float32", "int32", "bfloat16", "int16")
_MASKS = ("all", "none", "random", "first")


def _rows_state(dtype: str, shape, rng):
    if dtype.startswith("int"):
        rows = np.asarray(rng.randint(-50, 50, shape), dtype)
        state = np.asarray(rng.randint(-50, 50, shape[1:]), dtype)
    else:
        rows = np.asarray(rng.randn(*shape), np.float32)
        state = np.asarray(rng.randn(*shape[1:]), np.float32)
    return jnp.asarray(rows, dtype), jnp.asarray(state, dtype)


@pytest.mark.parametrize("fx", ["sum", "min", "max"])
@pytest.mark.parametrize("dtype", _DTYPES)
@pytest.mark.parametrize("mask_pattern", _MASKS)
def test_fold_parity(fx, dtype, mask_pattern):
    rng = np.random.RandomState(hash((fx, dtype, mask_pattern)) % 2**31)
    for shape in ((13,), (37, 5), (8, 3, 4)):
        rows, state = _rows_state(dtype, shape, rng)
        mask = jnp.asarray(_mask(mask_pattern, shape[0], rng))
        want, got = _both(lambda: fold_rows_masked(state, rows, mask, fx))
        assert want.dtype == got.dtype and want.shape == got.shape
        if dtype.startswith("int"):
            assert bool(jnp.all(want == got)), f"{fx}/{dtype}/{mask_pattern}/{shape}"
        else:
            assert _maxerr(want, got) <= _ATOL + _RTOL * float(np.max(np.abs(np.asarray(want, np.float64))))


@pytest.mark.parametrize("fx", ["sum", "min", "max"])
@pytest.mark.parametrize("dtype", ["float32", "int32"])
@pytest.mark.parametrize("mask_pattern", _MASKS)
@pytest.mark.parametrize(
    "ids_pattern", ["random", "sorted", "reversed", "constant", "empty_segment"]
)
def test_segment_parity(fx, dtype, mask_pattern, ids_pattern):
    rng = np.random.RandomState(hash((fx, dtype, mask_pattern, ids_pattern)) % 2**31)
    n, s = 29, 5
    for trailing in ((), (4,)):
        rows, _ = _rows_state(dtype, (n,) + trailing, rng)
        state, _ = _rows_state(dtype, (s,) + trailing, rng)  # (S, *leaf) stream-stacked
        mask = jnp.asarray(_mask(mask_pattern, n, rng))
        if ids_pattern == "random":
            ids = rng.randint(0, s, n)
        elif ids_pattern == "sorted":
            ids = np.sort(rng.randint(0, s, n))
        elif ids_pattern == "reversed":
            ids = np.sort(rng.randint(0, s, n))[::-1].copy()
        elif ids_pattern == "constant":
            ids = np.full(n, 2)
        else:  # empty_segment: segment 0 receives no rows
            ids = rng.randint(1, s, n)
        ids = jnp.asarray(ids.astype(np.int32))
        want, got = _both(
            lambda: segment_reduce_masked(state, rows, mask, ids, s, fx)
        )
        assert want.dtype == got.dtype and want.shape == got.shape
        if dtype == "int32":
            assert bool(jnp.all(want == got))
        else:
            assert _maxerr(want, got) <= _ATOL + _RTOL * float(np.max(np.abs(np.asarray(want, np.float64))))


def test_segment_single_stream_degenerate():
    rng = np.random.RandomState(7)
    rows = jnp.asarray(rng.randn(17, 3).astype(np.float32))
    state = jnp.asarray(rng.randn(1, 3).astype(np.float32))
    mask = jnp.asarray(rng.rand(17) > 0.3)
    ids = jnp.zeros(17, jnp.int32)
    want, got = _both(lambda: segment_reduce_masked(state, rows, mask, ids, 1, "sum"))
    assert _maxerr(want, got) < 1e-5
    # S=1 must equal the plain masked fold
    fold = fold_rows_masked(state[0], rows, mask, "sum")
    assert _maxerr(got[0], fold) < 1e-5


@pytest.mark.parametrize("length", [1, 7, 128, 300])
@pytest.mark.parametrize("mask_pattern", _MASKS)
def test_histogram_counts_bit_parity(length, mask_pattern):
    rng = np.random.RandomState(hash((length, mask_pattern)) % 2**31)
    n = 211
    # out-of-range indices on both sides: negatives clip to bin 0, >= length
    # DROP — the seed's jnp.bincount semantics, which both backends must pin
    idx = jnp.asarray(rng.randint(-3, length + 3, n).astype(np.int32))
    mask = jnp.asarray(_mask(mask_pattern, n, rng))
    want, got = _both(lambda: histogram_accumulate(idx, length, mask=mask))
    assert got.dtype == want.dtype == jnp.int32
    assert bool(jnp.all(want == got))
    # unmasked counts == jnp.bincount on the RAW indices (no pre-clipping:
    # the dropped-high / clipped-low behavior is part of the contract)
    want_u, got_u = _both(lambda: histogram_accumulate(idx, length))
    assert bool(jnp.all(got_u == jnp.bincount(idx, length=length)))
    assert bool(jnp.all(want_u == got_u))


@pytest.mark.parametrize("k", [1, 3])
def test_histogram_weighted_parity(k):
    rng = np.random.RandomState(11)
    n, length = 157, 19
    idx = jnp.asarray(rng.randint(0, length, n).astype(np.int32))
    w = rng.rand(n, k).astype(np.float32)
    w = jnp.asarray(w[:, 0] if k == 1 else w)  # (N,) and (N, K) ranks both supported
    mask = jnp.asarray(rng.rand(n) > 0.5)
    want, got = _both(lambda: histogram_accumulate(idx, length, weights=w, mask=mask))
    assert want.shape == got.shape and want.dtype == got.dtype
    assert _maxerr(want, got) < 1e-4


def test_zero_rows_and_fallback_shapes():
    """Degenerate inputs route to the XLA path and still agree."""
    state = jnp.zeros((3,), jnp.float32)
    rows = jnp.zeros((0, 3), jnp.float32)
    mask = jnp.zeros((0,), bool)
    want, got = _both(lambda: fold_rows_masked(state, rows, mask, "sum"))
    assert _maxerr(want, got) == 0.0
    # bool dtype: unsupported by the Pallas path — dispatcher must fall back
    # to the XLA lowering, not error, under every backend (sum is the only
    # reduction the runtime ever applied to bool states)
    rows_b = jnp.asarray(np.random.RandomState(0).rand(6, 2) > 0.5)
    state_b = jnp.zeros((2,), rows_b.dtype)
    m = jnp.ones((6,), bool)
    want, got = _both(lambda: fold_rows_masked(state_b, rows_b, m, "sum"))
    assert bool(jnp.all(want == got))
