"""Compiled-Pallas (Mosaic) parity on real TPU hardware.

The interpret-mode suite (``test_kernel_parity.py``) proves the kernel LOGIC
on any backend; this file proves the COMPILED lowering on an actual TPU —
run with ``METRICS_TPU_TEST_PLATFORM=axon`` (or ``tpu``). Off-TPU the
conftest guard skips the whole module cleanly (marker ``requires_tpu``),
because Mosaic compilation does not exist on CPU and an error there would
read as a kernel bug.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu.ops.kernels import (
    fold_rows_masked,
    histogram_accumulate,
    megastep_fold,
    megastep_segment,
    segment_reduce_masked,
    use_backend,
)

pytestmark = pytest.mark.requires_tpu


def _pair(fn):
    with use_backend("xla"):
        want = fn()
    with use_backend("pallas"):
        got = fn()
    return np.asarray(want), np.asarray(got)


@pytest.mark.parametrize("fx", ["sum", "min", "max"])
@pytest.mark.parametrize("dtype", ["float32", "int32"])
def test_fold_compiled_parity(fx, dtype):
    rng = np.random.RandomState(0)
    if dtype == "int32":
        rows = jnp.asarray(rng.randint(-50, 50, (300, 7)).astype(np.int32))
        state = jnp.asarray(rng.randint(-50, 50, 7).astype(np.int32))
    else:
        rows = jnp.asarray(rng.randn(300, 7).astype(np.float32))
        state = jnp.asarray(rng.randn(7).astype(np.float32))
    mask = jnp.asarray(rng.rand(300) > 0.4)
    want, got = _pair(lambda: fold_rows_masked(state, rows, mask, fx))
    if dtype == "int32":
        assert (want == got).all()
    else:
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


@pytest.mark.parametrize("fx", ["sum", "min", "max"])
def test_segment_compiled_parity(fx):
    rng = np.random.RandomState(1)
    rows = jnp.asarray(rng.randn(300, 5).astype(np.float32))
    state = jnp.asarray(rng.randn(8, 5).astype(np.float32))
    mask = jnp.asarray(rng.rand(300) > 0.4)
    ids = jnp.asarray(rng.randint(0, 8, 300).astype(np.int32))
    want, got = _pair(lambda: segment_reduce_masked(state, rows, mask, ids, 8, fx))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_histogram_compiled_bit_parity():
    rng = np.random.RandomState(2)
    idx = jnp.asarray(rng.randint(-2, 40, 1000).astype(np.int32))
    want, got = _pair(lambda: histogram_accumulate(idx, 37))
    assert (want == got).all()


@pytest.mark.parametrize("dtype", ["float32", "int32"])
def test_megastep_fold_compiled_parity(dtype):
    """The whole-step megakernel (ISSUE 16), compiled through Mosaic, against
    the xla oracle — mixed per-column opcodes so the select body compiles."""
    rng = np.random.RandomState(3)
    n, f = 400, 24
    if dtype == "int32":
        rows = jnp.asarray(rng.randint(-50, 50, (n, f)).astype(np.int32))
        buf = jnp.asarray(rng.randint(-50, 50, f).astype(np.int32))
    else:
        rows = jnp.asarray(rng.randn(n, f).astype(np.float32))
        buf = jnp.asarray(rng.randn(f).astype(np.float32))
    mask = jnp.asarray(rng.rand(n) > 0.4)
    ops = rng.randint(0, 3, f).astype(np.int32)

    def run():
        return megastep_fold(buf, rows, mask, ops)

    with use_backend("xla"):
        want = np.asarray(run())
    with use_backend("megastep"):
        got = np.asarray(run())
    if dtype == "int32":
        assert (want == got).all()
    else:
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_megastep_segment_compiled_parity_with_q8():
    """The compiled segment megakernel with staged q8-resident slots must be
    bit-identical to host-decoding the staged slots first (the decode
    arithmetic contract), and float-close to the xla oracle."""
    rng = np.random.RandomState(4)
    n, s, f = 300, 8, 16
    rows = jnp.asarray(rng.randn(n, f).astype(np.float32))
    mask = jnp.asarray(rng.rand(n) > 0.4)
    ids = jnp.asarray(rng.randint(0, s, n).astype(np.int32))
    ops = rng.randint(0, 3, f).astype(np.int32)
    base = rng.randn(s, f).astype(np.float32)
    codes = rng.randint(-127, 128, (s, f)).astype(np.int8)
    scales = (rng.rand(s, f).astype(np.float32) * 0.1 + 1e-3).astype(np.float32)
    flags = np.zeros(s, np.int32)
    flags[:3] = 1
    qcol = np.zeros(f, bool)
    qcol[::2] = True
    decoded = base.copy()
    on = (flags[:, None] != 0) & qcol[None, :]
    decoded[on] = (codes.astype(np.float32) * scales)[on]
    with use_backend("megastep"):
        got = np.asarray(
            megastep_segment(
                jnp.asarray(base), rows, mask, ids, s, ops,
                q8=(flags, codes, scales, qcol),
            )
        )
        host = np.asarray(
            megastep_segment(jnp.asarray(decoded), rows, mask, ids, s, ops)
        )
    assert (got == host).all()
    with use_backend("xla"):
        want = np.asarray(
            megastep_segment(
                jnp.asarray(base), rows, mask, ids, s, ops,
                q8=(flags, codes, scales, qcol),
            )
        )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_compiled_hlo_contains_mosaic_kernel():
    """The compiled update program really lowers through Mosaic: its HLO
    carries the TPU custom-call the kernels compile to."""
    from metrics_tpu import Accuracy

    m = Accuracy()
    state = m.init_state()
    p = jnp.zeros((16,), jnp.float32)
    t = jnp.zeros((16,), jnp.int32)
    mask = jnp.ones((16,), bool)

    def step(s, pp, tt, mm):
        return m.update_state_masked(s, pp, tt, mask=mm)

    with use_backend("pallas"):
        compiled = jax.jit(step).lower(state, p, t, mask).compile()
    txt = "\n".join(compiled.as_text().splitlines())
    assert "tpu_custom_call" in txt or "custom-call" in txt
