"""Interpret-mode parity of the whole-step megakernel (ISSUE 16).

``megastep_fold`` / ``megastep_segment`` — one launch per arena dtype with a
per-column opcode row — against the ``xla_ref`` oracles, plus the contracts
that ride them: the q8 decode-on-touch seed is bit-identical to decoding
host-side first, an empty-mask step still decodes staged slots, the VMEM gate
and the histogram ``_HIST_EXACT_ROWS`` overflow guard really route to the
reference path (observed through the kernel fault hook, which fires only in
front of a Pallas launch), and bad opcode rows are rejected loudly.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.ops.kernels import (
    histogram_accumulate,
    kernel_fault_scope,
    megastep_fold,
    megastep_segment,
    use_backend,
)

_RTOL = 1e-6
_ATOL = 1e-5


def _maxerr(a, b) -> float:
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    return float(np.max(np.abs(a - b))) if a.size else 0.0


def _both(fn):
    with use_backend("xla"):
        want = fn()
    with use_backend("megastep_interpret"):
        got = fn()
    return want, got


def _mask(pattern: str, n: int, rng) -> np.ndarray:
    if pattern == "all":
        return np.ones(n, bool)
    if pattern == "none":
        return np.zeros(n, bool)
    if pattern == "first":
        m = np.zeros(n, bool)
        m[0] = True
        return m
    return rng.rand(n) > 0.5


def _buf_rows(dtype: str, n: int, f: int, rng):
    if dtype.startswith("int"):
        rows = rng.randint(-50, 50, (n, f)).astype(dtype)
        buf = rng.randint(-50, 50, f).astype(dtype)
    else:
        rows = rng.randn(n, f).astype(np.float32)
        buf = rng.randn(f).astype(np.float32)
    return jnp.asarray(buf, dtype), jnp.asarray(rows, dtype)


def _ops(pattern: str, f: int, rng) -> np.ndarray:
    if pattern == "sum":
        return np.zeros(f, np.int32)
    if pattern == "min":
        return np.ones(f, np.int32)
    if pattern == "max":
        return np.full(f, 2, np.int32)
    return rng.randint(0, 3, f).astype(np.int32)  # mixed per-column opcodes


_DTYPES = ("float32", "int32", "bfloat16")
_OPS = ("sum", "min", "max", "mixed")
_MASKS = ("all", "none", "random", "first")


@pytest.mark.parametrize("dtype", _DTYPES)
@pytest.mark.parametrize("op_pattern", _OPS)
@pytest.mark.parametrize("mask_pattern", ("all", "random"))
def test_megastep_fold_parity(dtype, op_pattern, mask_pattern):
    rng = np.random.RandomState(hash((dtype, op_pattern, mask_pattern)) % 2**31)
    for n, f in ((1, 1), (13, 9), (200, 33)):
        buf, rows = _buf_rows(dtype, n, f, rng)
        mask = jnp.asarray(_mask(mask_pattern, n, rng))
        ops = _ops(op_pattern, f, rng)
        want, got = _both(lambda: megastep_fold(buf, rows, mask, ops))
        assert want.dtype == got.dtype and want.shape == got.shape == (f,)
        if dtype.startswith("int"):
            assert bool(jnp.all(want == got)), f"{dtype}/{op_pattern}/{mask_pattern}"
        else:
            tol = _ATOL + _RTOL * float(np.max(np.abs(np.asarray(want, np.float64))))
            if dtype == "bfloat16":
                tol = max(tol, 1e-1)
            assert _maxerr(want, got) <= tol


@pytest.mark.parametrize("dtype", ("float32", "int32"))
@pytest.mark.parametrize("op_pattern", _OPS)
@pytest.mark.parametrize("mask_pattern", _MASKS)
def test_megastep_segment_parity(dtype, op_pattern, mask_pattern):
    rng = np.random.RandomState(hash((dtype, op_pattern, mask_pattern)) % 2**31)
    n, s, f = 29, 5, 11
    _, rows = _buf_rows(dtype, n, f, rng)
    if dtype.startswith("int"):
        bufs = jnp.asarray(rng.randint(-50, 50, (s, f)).astype(dtype))
    else:
        bufs = jnp.asarray(rng.randn(s, f).astype(np.float32), dtype)
    mask = jnp.asarray(_mask(mask_pattern, n, rng))
    ids = jnp.asarray(rng.randint(0, s, n).astype(np.int32))
    ops = _ops(op_pattern, f, rng)
    want, got = _both(lambda: megastep_segment(bufs, rows, mask, ids, s, ops))
    assert want.dtype == got.dtype and want.shape == got.shape == (s, f)
    if dtype.startswith("int"):
        assert bool(jnp.all(want == got))
    else:
        assert _maxerr(want, got) <= _ATOL + _RTOL * float(
            np.max(np.abs(np.asarray(want, np.float64)))
        )


def _q8_inputs(rng, s, f, n_staged, n_qcols):
    """A staged q8 payload plus the host-decoded equivalent state."""
    base = rng.randn(s, f).astype(np.float32)
    codes = rng.randint(-127, 128, (s, f)).astype(np.int8)
    scales = (rng.rand(s, f).astype(np.float32) * 0.1 + 1e-3).astype(np.float32)
    flags = np.zeros(s, np.int32)
    flags[rng.choice(s, size=n_staged, replace=False)] = 1
    qcol = np.zeros(f, bool)
    qcol[rng.choice(f, size=n_qcols, replace=False)] = True
    # the host-side decode the kernel seed must reproduce bit-for-bit:
    # int8 -> f32 convert (exact), one f32 multiply, one cast
    decoded = base.copy()
    on = (flags[:, None] != 0) & qcol[None, :]
    decoded[on] = (codes.astype(np.float32) * scales).astype(np.float32)[on]
    return base, decoded, (flags, codes, scales, qcol)


def test_megastep_segment_q8_decode_bit_identical_to_host_decode():
    """Decode-on-touch inside the grid == decoding host-side then running the
    same kernel without q8 — bit-identical, not merely close (the exactness
    contract the q8-resident chaos tests lean on)."""
    rng = np.random.RandomState(3)
    n, s, f = 23, 6, 10
    rows = jnp.asarray(rng.randn(n, f).astype(np.float32))
    mask = jnp.asarray(rng.rand(n) > 0.4)
    ids = jnp.asarray(rng.randint(0, s, n).astype(np.int32))
    ops = rng.randint(0, 3, f).astype(np.int32)
    base, decoded, q8 = _q8_inputs(rng, s, f, n_staged=3, n_qcols=4)
    with use_backend("megastep_interpret"):
        got = megastep_segment(jnp.asarray(base), rows, mask, ids, s, ops, q8=q8)
        want = megastep_segment(jnp.asarray(decoded), rows, mask, ids, s, ops)
    assert bool(jnp.all(got == want))
    # and the xla reference path performs the identical decode
    with use_backend("xla"):
        ref = megastep_segment(jnp.asarray(base), rows, mask, ids, s, ops, q8=q8)
        ref_dec = megastep_segment(jnp.asarray(decoded), rows, mask, ids, s, ops)
    assert bool(jnp.all(ref == ref_dec))


@pytest.mark.parametrize("backend", ("xla", "megastep_interpret"))
def test_megastep_empty_mask_still_decodes_staged_slots(backend):
    """A fully-masked (or zero-row) step must not leave stale quantized
    columns: the touch IS the page-in, so the decode happens regardless."""
    rng = np.random.RandomState(5)
    s, f = 4, 7
    base, decoded, q8 = _q8_inputs(rng, s, f, n_staged=2, n_qcols=3)
    ops = np.zeros(f, np.int32)
    with use_backend(backend):
        for n in (0, 9):
            rows = jnp.zeros((n, f), jnp.float32)
            mask = jnp.zeros((n,), bool)
            ids = jnp.zeros((n,), jnp.int32)
            got = megastep_segment(jnp.asarray(base), rows, mask, ids, s, ops, q8=q8)
            np.testing.assert_array_equal(np.asarray(got), decoded)


def test_megastep_zero_rows_without_q8_is_identity():
    rng = np.random.RandomState(6)
    buf = jnp.asarray(rng.randn(8).astype(np.float32))
    with use_backend("megastep_interpret"):
        out = megastep_fold(buf, jnp.zeros((0, 8)), jnp.zeros((0,), bool), np.zeros(8, np.int32))
        seg = megastep_segment(
            jnp.asarray(rng.randn(3, 8).astype(np.float32)),
            jnp.zeros((0, 8)), jnp.zeros((0,), bool), jnp.zeros((0,), jnp.int32),
            3, np.zeros(8, np.int32),
        )
    assert bool(jnp.all(out == buf))
    assert seg.shape == (3, 8)


def test_megastep_bad_opcodes_rejected():
    buf = jnp.zeros((4,), jnp.float32)
    rows = jnp.zeros((2, 4), jnp.float32)
    mask = jnp.ones((2,), bool)
    with pytest.raises(ValueError, match="opcode"):
        megastep_fold(buf, rows, mask, np.asarray([0, 1, 2, 7], np.int32))
    with pytest.raises(ValueError, match="columns"):
        megastep_fold(buf, rows, mask, np.zeros(3, np.int32))


def test_megastep_ineligible_inputs_fall_back_without_a_launch():
    """bool dtype and a VMEM-oversized (S, F) block take the reference path —
    no Pallas launch (the fault hook never fires) and parity holds."""
    calls = []
    rng = np.random.RandomState(9)
    rows_b = jnp.asarray(rng.rand(6, 3) > 0.5)
    buf_b = jnp.zeros((3,), bool)
    m = jnp.ones((6,), bool)
    with use_backend("megastep_interpret"), kernel_fault_scope(calls.append):
        got_b = megastep_fold(buf_b, rows_b, m, np.zeros(3, np.int32))
        # 64k segments x 33 f32 columns > the VMEM block budget
        big_s = 1 << 16
        got_big = megastep_segment(
            jnp.zeros((big_s, 33), jnp.float32),
            jnp.asarray(rng.randn(4, 33).astype(np.float32)),
            jnp.ones((4,), bool),
            jnp.asarray([0, 1, big_s - 1, 5], jnp.int32),
            big_s,
            np.zeros(33, np.int32),
        )
    assert calls == []  # the hook fires only in front of a Pallas launch
    with use_backend("xla"):
        want_b = megastep_fold(buf_b, rows_b, m, np.zeros(3, np.int32))
    assert bool(jnp.all(got_b == want_b))
    assert float(got_big[big_s - 1, 0]) != 0.0


# --------------------------------------------------- int8/bf16 MXU histogram


def test_histogram_bf16_weights_parity():
    """bf16 weights ride the MXU at their own width (f32 accumulation); only
    the final cast rounds — tolerance is bf16 resolution, not kernel error."""
    rng = np.random.RandomState(12)
    n, length = 333, 25
    idx = jnp.asarray(rng.randint(0, length, n).astype(np.int32))
    w = jnp.asarray(rng.rand(n).astype(np.float32), jnp.bfloat16)
    with use_backend("xla"):
        want = histogram_accumulate(idx, length, weights=w)
    with use_backend("pallas_interpret"):
        got = histogram_accumulate(idx, length, weights=w)
    assert got.dtype == want.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=0.05, atol=0.5
    )


def test_histogram_counts_use_the_mxu_kernel_and_are_exact():
    """Unweighted counts take the int8-one-hot MXU path (the hook observes
    the launch) and stay bit-equal to ``jnp.bincount``."""
    calls = []
    rng = np.random.RandomState(13)
    idx = jnp.asarray(rng.randint(-2, 40, 500).astype(np.int32))
    with use_backend("pallas_interpret"), kernel_fault_scope(calls.append):
        got = histogram_accumulate(idx, 37)
    assert "histogram" in calls
    assert got.dtype == jnp.int32
    assert bool(jnp.all(got == jnp.bincount(idx, length=37)))


def test_histogram_exact_rows_gate_falls_back(monkeypatch):
    """Past ``_HIST_EXACT_ROWS`` the f32 accumulation can no longer represent
    every integer count: the dispatcher must take the full-precision XLA
    scatter (no Pallas launch), under the megastep tier too."""
    from metrics_tpu.ops.kernels import dispatch

    monkeypatch.setattr(dispatch, "_HIST_EXACT_ROWS", 8)
    rng = np.random.RandomState(14)
    idx = jnp.asarray(rng.randint(0, 5, 64).astype(np.int32))  # 64 >= the gate
    for backend in ("pallas_interpret", "megastep_interpret"):
        calls = []
        with use_backend(backend), kernel_fault_scope(calls.append):
            got = histogram_accumulate(idx, 5)
        assert calls == [], backend
        assert bool(jnp.all(got == jnp.bincount(idx, length=5)))
    # below the gate the kernel serves again
    small = idx[:7]
    calls = []
    with use_backend("pallas_interpret"), kernel_fault_scope(calls.append):
        got = histogram_accumulate(small, 5)
    assert calls == ["histogram"]
    assert bool(jnp.all(got == jnp.bincount(small, length=5)))
