"""Independent torch mirror graphs of the embedded models, for verification.

These reproduce — in plain torch, with no dependency on torch-fidelity /
torchvision / lpips — the exact graphs the reference consumes:

* ``TorchFidInception``: the torch-fidelity FID-variant InceptionV3 (branch
  avg-pools with ``count_include_pad=False``, max-pool in the second
  InceptionE, 1008-way unbiased logits, ``(x-128)/128`` input scaling) that
  the reference loads via ``torchmetrics/image/fid.py:38-55``.
* ``TorchVggLpips`` / ``TorchAlexLpips``: the ``lpips`` package's feature
  stacks + scaling layer + unit normalisation + learned 1x1 heads that the
  reference embeds at ``torchmetrics/image/lpip_similarity.py:123``.

Two consumers:
* the graph-parity tests (``tests/tools/test_*_graph_parity.py``) share
  random weights through the converter and compare every tap;
* ``convert_weights.py --verify`` loads a REAL checkpoint into these mirrors
  and compares taps against the converted flax model — an end-to-end check
  the first user with network egress can run in one command.
"""
import torch
import torch.nn.functional as TF
from torch import nn as tnn

# ----------------------------------------------------------------- inception

class TConv(tnn.Module):
    """Conv + BatchNorm(eps=1e-3) + ReLU, the inception basic block."""

    def __init__(self, cin, cout, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = tnn.Conv2d(cin, cout, kernel, stride=stride, padding=padding, bias=False)
        self.bn = tnn.BatchNorm2d(cout, eps=0.001)

    def forward(self, x):
        return torch.relu(self.bn(self.conv(x)))


def _avg3(x):
    # the FID-variant branch pooling: 3x3 stride-1 SAME, border windows
    # normalised by the count of real pixels
    return TF.avg_pool2d(x, 3, stride=1, padding=1, count_include_pad=False)


class TInceptionA(tnn.Module):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.b1 = TConv(cin, 64, 1)
        self.b2a = TConv(cin, 48, 1)
        self.b2b = TConv(48, 64, 5, padding=2)
        self.b3a = TConv(cin, 64, 1)
        self.b3b = TConv(64, 96, 3, padding=1)
        self.b3c = TConv(96, 96, 3, padding=1)
        self.b4 = TConv(cin, pool_features, 1)

    def forward(self, x):
        return torch.cat(
            [self.b1(x), self.b2b(self.b2a(x)), self.b3c(self.b3b(self.b3a(x))), self.b4(_avg3(x))], 1
        )


class TInceptionB(tnn.Module):
    def __init__(self, cin):
        super().__init__()
        self.b1 = TConv(cin, 384, 3, stride=2)
        self.b2a = TConv(cin, 64, 1)
        self.b2b = TConv(64, 96, 3, padding=1)
        self.b2c = TConv(96, 96, 3, stride=2)

    def forward(self, x):
        return torch.cat([self.b1(x), self.b2c(self.b2b(self.b2a(x))), TF.max_pool2d(x, 3, stride=2)], 1)


class TInceptionC(tnn.Module):
    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = TConv(cin, 192, 1)
        self.b2a = TConv(cin, c7, 1)
        self.b2b = TConv(c7, c7, (1, 7), padding=(0, 3))
        self.b2c = TConv(c7, 192, (7, 1), padding=(3, 0))
        self.b3a = TConv(cin, c7, 1)
        self.b3b = TConv(c7, c7, (7, 1), padding=(3, 0))
        self.b3c = TConv(c7, c7, (1, 7), padding=(0, 3))
        self.b3d = TConv(c7, c7, (7, 1), padding=(3, 0))
        self.b3e = TConv(c7, 192, (1, 7), padding=(0, 3))
        self.b4 = TConv(cin, 192, 1)

    def forward(self, x):
        b2 = self.b2c(self.b2b(self.b2a(x)))
        b3 = self.b3e(self.b3d(self.b3c(self.b3b(self.b3a(x)))))
        return torch.cat([self.b1(x), b2, b3, self.b4(_avg3(x))], 1)


class TInceptionD(tnn.Module):
    def __init__(self, cin):
        super().__init__()
        self.b1a = TConv(cin, 192, 1)
        self.b1b = TConv(192, 320, 3, stride=2)
        self.b2a = TConv(cin, 192, 1)
        self.b2b = TConv(192, 192, (1, 7), padding=(0, 3))
        self.b2c = TConv(192, 192, (7, 1), padding=(3, 0))
        self.b2d = TConv(192, 192, 3, stride=2)

    def forward(self, x):
        b1 = self.b1b(self.b1a(x))
        b2 = self.b2d(self.b2c(self.b2b(self.b2a(x))))
        return torch.cat([b1, b2, TF.max_pool2d(x, 3, stride=2)], 1)


class TInceptionE(tnn.Module):
    def __init__(self, cin, pool_mode):
        super().__init__()
        self.pool_mode = pool_mode
        self.b1 = TConv(cin, 320, 1)
        self.b2a = TConv(cin, 384, 1)
        self.b2b = TConv(384, 384, (1, 3), padding=(0, 1))
        self.b2c = TConv(384, 384, (3, 1), padding=(1, 0))
        self.b3a = TConv(cin, 448, 1)
        self.b3b = TConv(448, 384, 3, padding=1)
        self.b3c = TConv(384, 384, (1, 3), padding=(0, 1))
        self.b3d = TConv(384, 384, (3, 1), padding=(1, 0))
        self.b4 = TConv(cin, 192, 1)

    def forward(self, x):
        b2 = self.b2a(x)
        b2 = torch.cat([self.b2b(b2), self.b2c(b2)], 1)
        b3 = self.b3b(self.b3a(x))
        b3 = torch.cat([self.b3c(b3), self.b3d(b3)], 1)
        if self.pool_mode == "max":
            pooled = TF.max_pool2d(x, 3, stride=1, padding=1)
        else:
            pooled = _avg3(x)
        return torch.cat([self.b1(x), b2, b3, self.b4(pooled)], 1)


class TorchFidInception(tnn.Module):
    """The torch-fidelity FID-variant InceptionV3, with the five feature taps the
    reference consumes (64/192/768/2048/logits_unbiased)."""

    def __init__(self, num_classes=1008):
        super().__init__()
        self.c1 = TConv(3, 32, 3, stride=2)
        self.c2 = TConv(32, 32, 3)
        self.c3 = TConv(32, 64, 3, padding=1)
        self.c4 = TConv(64, 80, 1)
        self.c5 = TConv(80, 192, 3)
        self.a1 = TInceptionA(192, 32)
        self.a2 = TInceptionA(256, 64)
        self.a3 = TInceptionA(288, 64)
        self.b = TInceptionB(288)
        self.m1 = TInceptionC(768, 128)
        self.m2 = TInceptionC(768, 160)
        self.m3 = TInceptionC(768, 160)
        self.m4 = TInceptionC(768, 192)
        self.d = TInceptionD(768)
        self.e1 = TInceptionE(1280, "avg")
        self.e2 = TInceptionE(2048, "max")
        self.fc = tnn.Linear(2048, num_classes)

    def forward(self, x):
        # torch-fidelity scaling: uint8-valued input -> (-1, 1)
        x = (x.float() - 128.0) / 128.0
        out = {}
        x = self.c3(self.c2(self.c1(x)))
        x = TF.max_pool2d(x, 3, stride=2)
        out["64"] = x.mean(dim=(2, 3))
        x = self.c5(self.c4(x))
        x = TF.max_pool2d(x, 3, stride=2)
        out["192"] = x.mean(dim=(2, 3))
        x = self.b(self.a3(self.a2(self.a1(x))))
        out["768"] = x.mean(dim=(2, 3))
        x = self.e2(self.e1(self.d(self.m4(self.m3(self.m2(self.m1(x)))))))
        pooled = x.mean(dim=(2, 3))
        out["2048"] = pooled
        out["logits_unbiased"] = pooled @ self.fc.weight.t()  # bias dropped, as the reference does
        return out


# --------------------------------------------------------------------- lpips

_SHIFT = torch.tensor([-0.030, -0.088, -0.188]).view(1, 3, 1, 1)
_SCALE = torch.tensor([0.458, 0.448, 0.450]).view(1, 3, 1, 1)


class TorchVggLpips(tnn.Module):
    """VGG16 LPIPS: five relu taps + per-channel linear heads."""

    CHANNELS = (64, 128, 256, 512, 512)

    def __init__(self):
        super().__init__()
        convs = []
        cin = 3
        for n_convs, ch in ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512)):
            block = []
            for _ in range(n_convs):
                block.append(tnn.Conv2d(cin, ch, 3, padding=1))
                cin = ch
            convs.append(tnn.ModuleList(block))
        self.blocks = tnn.ModuleList(convs)
        self.lins = tnn.ModuleList([tnn.Conv2d(c, 1, 1, bias=False) for c in self.CHANNELS])

    def taps(self, x):
        x = (x - _SHIFT) / _SCALE
        out = []
        for i, block in enumerate(self.blocks):
            if i:
                x = TF.max_pool2d(x, 2, stride=2)
            for conv in block:
                x = torch.relu(conv(x))
            out.append(x)
        return out

    def forward(self, a, b):
        return _lpips_torch(self.taps(a), self.taps(b), self.lins)


class TorchAlexLpips(tnn.Module):
    CHANNELS = (64, 192, 384, 256, 256)

    def __init__(self):
        super().__init__()
        self.c1 = tnn.Conv2d(3, 64, 11, stride=4, padding=2)
        self.c2 = tnn.Conv2d(64, 192, 5, padding=2)
        self.c3 = tnn.Conv2d(192, 384, 3, padding=1)
        self.c4 = tnn.Conv2d(384, 256, 3, padding=1)
        self.c5 = tnn.Conv2d(256, 256, 3, padding=1)
        self.lins = tnn.ModuleList([tnn.Conv2d(c, 1, 1, bias=False) for c in self.CHANNELS])

    def taps(self, x):
        x = (x - _SHIFT) / _SCALE
        t1 = torch.relu(self.c1(x))
        t2 = torch.relu(self.c2(TF.max_pool2d(t1, 3, stride=2)))
        t3 = torch.relu(self.c3(TF.max_pool2d(t2, 3, stride=2)))
        t4 = torch.relu(self.c4(t3))
        t5 = torch.relu(self.c5(t4))
        return [t1, t2, t3, t4, t5]

    def forward(self, a, b):
        return _lpips_torch(self.taps(a), self.taps(b), self.lins)


def _unit_normalize(t, eps=1e-10):
    return t / (torch.sqrt(torch.sum(t ** 2, dim=1, keepdim=True)) + eps)


def _lpips_torch(feats_a, feats_b, lins):
    total = 0.0
    for fa, fb, lin in zip(feats_a, feats_b, lins):
        diff = (_unit_normalize(fa) - _unit_normalize(fb)) ** 2
        total = total + lin(diff).mean(dim=(2, 3)).squeeze(1)
    return total


def save_lpips_style_state(tmodel, path):
    """Write the torch weights under the lpips package's state-dict names,
    including the ScalingLayer buffers a real ``lpips.LPIPS`` state dict
    carries (the converter must drop them)."""
    state = {"scaling_layer.shift": _SHIFT.clone(), "scaling_layer.scale": _SCALE.clone()}
    i = 0
    if isinstance(tmodel, TorchVggLpips):
        for block in tmodel.blocks:
            for conv in block:
                state[f"net.slice.conv{i}.weight"] = conv.weight.detach()
                state[f"net.slice.conv{i}.bias"] = conv.bias.detach()
                i += 1
    else:
        for conv in (tmodel.c1, tmodel.c2, tmodel.c3, tmodel.c4, tmodel.c5):
            state[f"net.slice.conv{i}.weight"] = conv.weight.detach()
            state[f"net.slice.conv{i}.bias"] = conv.bias.detach()
            i += 1
    for j, lin in enumerate(tmodel.lins):
        state[f"lin{j}.model.1.weight"] = lin.weight.detach()
    torch.save(state, path)


# ------------------------------------------------------- positional state load

def load_state_positional(module: tnn.Module, state: dict, drop=("num_batches_tracked",)) -> None:
    """Load a checkpoint whose KEYS use foreign names but whose DEFINITION
    ORDER matches ``module`` (both sides define the same architecture in the
    same order — the same invariant the converter's ordered zip relies on,
    and every assignment is shape-checked, so a misalignment cannot pass
    silently).

    Entries whose name contains any ``drop`` substring are skipped on both
    sides. A missing trailing entry on the checkpoint side (e.g. ``fc.bias``
    saved without a bias) zero-fills the module slot.
    """
    own = [(k, v) for k, v in module.state_dict().items() if not any(d in k for d in drop)]
    theirs = [(k, v) for k, v in state.items() if not any(d in k for d in drop)]
    if len(theirs) > len(own):
        raise ValueError(
            f"checkpoint has {len(theirs)} entries but the mirror graph has {len(own)}"
        )
    new_state = dict(module.state_dict())
    for i, (own_kv, their_kv) in enumerate(zip(own, theirs)):
        (ok, ov), (tk, tv) = own_kv, their_kv
        tv = torch.as_tensor(tv)
        if tuple(ov.shape) != tuple(tv.shape):
            raise ValueError(
                f"positional mismatch at entry {i}: mirror {ok} {tuple(ov.shape)} "
                f"vs checkpoint {tk} {tuple(tv.shape)}"
            )
        new_state[ok] = tv.to(ov.dtype)
    for ok, _ in own[len(theirs):]:
        new_state[ok] = torch.zeros_like(new_state[ok])
    module.load_state_dict(new_state)
