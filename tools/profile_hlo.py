#!/usr/bin/env python
"""Per-op cost attribution for the repo's compiled hot paths.

Thin CLI over ``metrics_tpu.ops.profiling``: lower a jitted target, walk its
jaxpr, and print a sorted per-layer cost table (FLOPs, bytes, structural MXU
tile efficiency, ideal-time share) cross-checked against XLA's own
``cost_analysis``. Runs on any backend — ``JAX_PLATFORMS=cpu`` works, the
geometry is platform-independent; pass ``--trace-dir`` on a real TPU to also
capture a ``jax.profiler`` trace with matching op names.

Targets:
  * ``inception`` — the embedded InceptionV3 forward that drives FID/IS/KID
    (the '2048' tap), optionally with the optimized flags;
  * ``accuracy``  — one compiled MetricCollection-style classification update
    (``Accuracy.update_state``);
  * ``all``       — both.

Examples::

    JAX_PLATFORMS=cpu python tools/profile_hlo.py --target inception --input-size 149
    JAX_PLATFORMS=cpu python tools/profile_hlo.py --target accuracy --json
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _inception_table(input_size: int, batch: int, depth: int, optimized: bool):
    import warnings

    import jax
    import jax.numpy as jnp

    from metrics_tpu.models.inception import (
        InceptionV3,
        fold_preprocess_into_params,
        pad_stem_params,
    )
    from metrics_tpu.ops import attribution_table

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        module = InceptionV3()
        x = jnp.zeros((batch, input_size, input_size, 3))
        params = jax.jit(module.init)(jax.random.PRNGKey(0), x)
    if optimized:
        opt = InceptionV3(preprocess_folded=True, stem_lanes=128)

        def fwd(p, imgs):
            return opt.apply(pad_stem_params(fold_preprocess_into_params(p)), imgs)["2048"]
    else:
        def fwd(p, imgs):
            return module.apply(p, imgs)["2048"]

    return attribution_table(fwd, params, x, depth=depth), (params, x, fwd)


def _accuracy_table(batch: int, num_classes: int, depth: int):
    import jax.numpy as jnp
    import numpy as np

    from metrics_tpu import Accuracy
    from metrics_tpu.ops import attribution_table

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(batch, num_classes).astype(np.float32))
    target = jnp.asarray(rng.randint(0, num_classes, batch))
    acc = Accuracy()
    state = acc.init_state()

    def update(s, p, t):
        return acc.update_state(s, p, t)

    return attribution_table(update, state, preds, target, depth=depth), (state, preds, target, update)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--target", choices=("inception", "accuracy", "all"), default="all")
    ap.add_argument("--input-size", type=int, default=299, help="inception spatial size")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--num-classes", type=int, default=10, help="accuracy target classes")
    ap.add_argument("--depth", type=int, default=2, help="name_stack grouping depth")
    ap.add_argument("--optimized", action="store_true",
                    help="profile the optimized inception path (folded preprocess + MXU-padded stem)")
    ap.add_argument("--json", action="store_true", help="emit the full table(s) as one JSON object")
    ap.add_argument("--trace-dir", default=None,
                    help="also run the target under jax.profiler.trace into this dir (measured path; real TPU)")
    args = ap.parse_args(argv)

    from metrics_tpu.ops import capture_trace, format_table

    out = {}
    if args.target in ("inception", "all"):
        table, (p, x, fwd) = _inception_table(args.input_size, args.batch, args.depth, args.optimized)
        out["inception"] = table
        if args.trace_dir:
            capture_trace(fwd, (p, x), args.trace_dir + "/inception")
    if args.target in ("accuracy", "all"):
        table, (state, preds, target, update) = _accuracy_table(args.batch, args.num_classes, args.depth)
        out["accuracy"] = table
        if args.trace_dir:
            capture_trace(update, (state, preds, target), args.trace_dir + "/accuracy")

    if args.json:
        print(json.dumps(out))
    else:
        for name, table in out.items():
            print(f"== {name} ==")
            print(format_table(table))
            print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
