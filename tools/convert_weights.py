"""Pretrained-weight conversion: torch checkpoints -> flax pytrees for the
embedded models (InceptionV3 for FID/IS/KID, BERT for BERTScore).

The reference obtains weights over the network at runtime (torch-fidelity for
InceptionV3, ``torchmetrics/image/fid.py:242``; HF hub for BERT,
``functional/text/bert.py:23,256``). This build is zero-egress, so conversion is
an offline step:

InceptionV3 (FID variant, 1008-way logits)::

    # on any machine with the torch-fidelity checkpoint downloaded:
    python tools/convert_weights.py inception pt_inception-2015-12-05.pth inception_flax.pkl
    # then:
    from metrics_tpu.models.inception import InceptionFeatureExtractor
    fid = FrechetInceptionDistance(params=InceptionFeatureExtractor.load_params("inception_flax.pkl"))

BERT (any HF bert-style encoder)::

    python tools/convert_weights.py bert /path/to/hf_torch_model /path/to/out_flax
    # then: BERTScore(model_name_or_path="/path/to/out_flax")

Conversion rules (tested numerically in ``tests/tools/test_convert.py``):
  * torch Conv2d weight ``(O, I, kH, kW)``    -> flax Conv kernel ``(kH, kW, I, O)``
  * torch Linear weight ``(O, I)``            -> flax Dense kernel ``(I, O)``
  * torch BatchNorm weight/bias              -> flax params scale/bias
  * torch BatchNorm running_mean/running_var -> flax batch_stats mean/var
  * ``num_batches_tracked`` is dropped

The Inception mapping is ORDER-based: torch state dicts preserve module
definition order, and the flax module mirrors torch-fidelity's definition order
exactly, so conv/bn groups zip one-to-one. Every leaf is shape-checked; a
mismatch raises with both names.
"""
import argparse
import os
import pickle
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# make the tool runnable from any cwd: the repo root is this file's parent dir
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# --------------------------------------------------------------------- leaf rules

def torch_conv_kernel(w: np.ndarray) -> np.ndarray:
    """(O, I, kH, kW) -> (kH, kW, I, O)."""
    return np.transpose(np.asarray(w), (2, 3, 1, 0))


def torch_linear_kernel(w: np.ndarray) -> np.ndarray:
    """(O, I) -> (I, O)."""
    return np.transpose(np.asarray(w), (1, 0))


# ----------------------------------------------------- ordered flax-tree traversal

def _natural_key(s: str):
    return [int(p) if p.isdigit() else p for p in re.split(r"(\d+)", s)]


def _walk(tree: Any, path: Tuple[str, ...] = ()) -> List[Tuple[Tuple[str, ...], np.ndarray]]:
    """Flatten a nested dict in module-definition order (natural sort of the
    auto-numbered flax names, so BasicConv2d_10 sorts after BasicConv2d_9)."""
    out: List[Tuple[Tuple[str, ...], np.ndarray]] = []
    if isinstance(tree, dict) or hasattr(tree, "items"):
        for k in sorted(tree.keys(), key=_natural_key):
            out.extend(_walk(tree[k], path + (k,)))
    else:
        out.append((path, np.asarray(tree)))
    return out


def _set_in(tree: Dict, path: Tuple[str, ...], value: np.ndarray) -> None:
    node = tree
    for k in path[:-1]:
        node = node[k]
    node[path[-1]] = value


def _to_mutable(tree: Any) -> Any:
    if hasattr(tree, "items"):
        return {k: _to_mutable(v) for k, v in tree.items()}
    return tree


# ------------------------------------------------------------- conv/bn stack zipper

def convert_conv_bn_model(
    torch_state: Dict[str, np.ndarray], flax_template: Dict[str, Any]
) -> Dict[str, Any]:
    """Fill a flax {'params', 'batch_stats'} template from a torch state dict of a
    conv/BN/linear stack with matching definition order.

    The torch dict is scanned in order; conv weights, bn 4-tuples and linear
    weights are matched against the template's ordered leaves per collection.
    """
    template = _to_mutable(flax_template)

    # ordered leaf slots, with the collection baked into the path
    param_leaves = [(("params",) + p, v) for p, v in _walk(template.get("params", {}))]
    stat_leaves = [(("batch_stats",) + p, v) for p, v in _walk(template.get("batch_stats", {}))]

    # conv and linear kernels zip as SEPARATE ordered streams: the flax walk is
    # name-sorted (Dense_0 sorts before InceptionA_0) while torch checkpoints
    # put the fc last — rank disambiguates where position cannot
    slots = {
        "conv_kernel": [(p, v) for p, v in param_leaves if p[-1] == "kernel" and np.ndim(v) == 4],
        "linear_kernel": [(p, v) for p, v in param_leaves if p[-1] == "kernel" and np.ndim(v) == 2],
        "scale": [(p, v) for p, v in param_leaves if p[-1] == "scale"],
        "bias": [(p, v) for p, v in param_leaves if p[-1] == "bias"],
        "mean": [(p, v) for p, v in stat_leaves if p[-1] == "mean"],
        "var": [(p, v) for p, v in stat_leaves if p[-1] == "var"],
    }
    cursor = {k: 0 for k in slots}

    def take(kind: str, torch_name: str, converted: np.ndarray) -> None:
        if cursor[kind] >= len(slots[kind]):
            raise ValueError(f"no {kind} slot left for torch entry {torch_name}")
        path, slot = slots[kind][cursor[kind]]
        cursor[kind] += 1
        if tuple(converted.shape) != tuple(np.shape(slot)):
            raise ValueError(
                f"shape mismatch: torch {torch_name} -> {converted.shape} "
                f"vs flax {'/'.join(path)} {np.shape(slot)}"
            )
        _set_in(template, path, converted)

    for name, value in torch_state.items():
        value = np.asarray(value)
        if name.endswith("num_batches_tracked"):
            continue
        if name.endswith(".weight") and value.ndim == 4:
            take("conv_kernel", name, torch_conv_kernel(value))
        elif name.endswith(".weight") and value.ndim == 2:
            take("linear_kernel", name, torch_linear_kernel(value))
        elif name.endswith(".weight") and value.ndim == 1:  # bn gamma
            take("scale", name, value)
        elif name.endswith(".bias"):
            take("bias", name, value)
        elif name.endswith(".running_mean"):
            take("mean", name, value)
        elif name.endswith(".running_var"):
            take("var", name, value)
        else:
            raise ValueError(f"unrecognised torch entry: {name} {value.shape}")
    unfilled = {k: f"{cursor[k]}/{len(slots[k])}" for k in slots if cursor[k] != len(slots[k])}
    if unfilled:
        raise ValueError(f"unfilled flax slots: {unfilled}")
    return template




def _cpu_device():
    """The host CPU device, or None on hosts where only an accelerator
    platform is registered (e.g. the axon test environment). Single source of
    truth for both _template_device and _verify_tol — the 1e-4 verify bar is
    only valid because the forward actually ran on CPU."""
    import jax

    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        return None


def _template_device():
    """Build init templates (and verify forwards) on CPU when available —
    keeps the offline tool off any accelerator; no-op context otherwise."""
    import contextlib

    import jax

    cpu = _cpu_device()
    return jax.default_device(cpu) if cpu is not None else contextlib.nullcontext()

# ------------------------------------------------------------------ inception entry

def convert_inception(torch_ckpt_path: str, out_path: str, num_classes: int = 1008) -> None:
    """torch-fidelity ``pt_inception`` checkpoint -> flax variables for
    ``metrics_tpu.models.inception.InceptionV3``."""
    import torch
    import jax
    import jax.numpy as jnp

    from metrics_tpu.models.inception import InceptionV3

    state = torch.load(torch_ckpt_path, map_location="cpu", weights_only=True)
    if hasattr(state, "state_dict"):
        state = state.state_dict()
    state_np = {k: v.numpy() for k, v in state.items()}

    # cheap sanity check BEFORE the (expensive) template init: the FID inception
    # has exactly 94 convs + 1 fc
    n_convs = sum(1 for v in state_np.values() if np.ndim(v) == 4)
    if n_convs != 94:
        raise ValueError(
            f"{torch_ckpt_path} does not look like a torch-fidelity InceptionV3 "
            f"checkpoint: found {n_convs} conv weights, expected 94"
        )

    module = InceptionV3(num_classes=num_classes)
    # conversion is an offline host step — build the template on CPU so it doesn't
    # hold (or wait for) an accelerator
    with _template_device():
        template = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 299, 299, 3)))
    # torch-fidelity's fc carries a bias the reference drops ('logits_unbiased');
    # our Dense is bias-free — drop it before the zip
    state_np = {k: v for k, v in state_np.items() if not re.search(r"fc\.bias$", k)}
    variables = convert_conv_bn_model(state_np, template)
    with open(out_path, "wb") as f:
        pickle.dump(variables, f)
    print(f"wrote {out_path}")


def _dedupe_lpips_lins(state_np: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Drop the duplicated linear-head entries a REAL ``lpips.LPIPS`` state dict
    carries: lpips 0.1.x registers the heads twice (``lin0..lin4`` attributes AND
    ``self.lins = ModuleList(...)``), and ``state_dict()`` does not dedupe shared
    submodules — so the checkpoint has both ``lin0.model.1.weight`` and
    ``lins.0.model.1.weight`` for the same tensor. Keep the ``lin{i}`` form."""
    has_lin = any(re.match(r"lin\d", k) for k in state_np)
    has_lins = any(k.startswith("lins.") for k in state_np)
    if has_lin and has_lins:
        state_np = {k: v for k, v in state_np.items() if not k.startswith("lins.")}
    return state_np


# ---------------------------------------------------------------------- lpips entry

def convert_lpips(torch_ckpt_path: str, out_path: str, net_type: str = "vgg") -> None:
    """``lpips.LPIPS(net=...)`` full state dict -> flax backbone variables plus
    per-layer linear weights.

    Produce the input offline on any machine with the ``lpips`` package::

        torch.save(lpips.LPIPS(net="vgg").state_dict(), "lpips_vgg.pth")

    The state dict carries the torchvision backbone under ``net.slice*`` and the
    learned per-channel 1x1 convs under ``lin*``/``lins.*``; the backbone convs
    zip order-based like the inception path, the lin weights are stored as five
    ``(C,)`` vectors (they multiply the normalized squared feature difference).
    """
    import torch
    import jax
    import jax.numpy as jnp

    from metrics_tpu.models.perceptual import _BACKBONES

    state = torch.load(torch_ckpt_path, map_location="cpu", weights_only=True)
    if hasattr(state, "state_dict"):
        state = state.state_dict()
    state_np = {k: v.numpy() for k, v in state.items()}
    state_np = _dedupe_lpips_lins(state_np)

    # split out the linear heads: lpips names them `lin0.model.1.weight` ..
    # (or `lins.0...` in some versions); everything else is the backbone
    # the ScalingLayer's shift/scale buffers are fixed constants baked into the
    # flax graph (perceptual.py _LPIPS_SHIFT/_LPIPS_SCALE) — drop them, like the
    # inception path drops fc.bias
    state_np = {k: v for k, v in state_np.items() if "scaling_layer" not in k}
    lin_items = sorted(
        ((k, v) for k, v in state_np.items() if re.search(r"\blins?[._]?\d", k)),
        key=lambda kv: _natural_key(kv[0]),
    )
    backbone = {k: v for k, v in state_np.items() if not re.search(r"\blins?[._]?\d", k)}
    if len(lin_items) != 5:
        raise ValueError(
            f"{torch_ckpt_path} does not look like a full lpips.LPIPS state dict: "
            f"found {len(lin_items)} linear-head tensors, expected 5"
        )
    weights = [np.asarray(v).reshape(-1) for _, v in lin_items]

    module = _BACKBONES[net_type]()
    with _template_device():
        template = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    variables = convert_conv_bn_model(backbone, template)
    payload = {"net_type": net_type, "variables": variables, "weights": weights}
    with open(out_path, "wb") as f:
        pickle.dump(payload, f)
    print(f"wrote {out_path}")


# ------------------------------------------------------------- verification kit

def _sha256(path: str) -> str:
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _manifest() -> Dict[str, Any]:
    import json

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "checkpoint_manifest.json")
    with open(path) as f:
        return json.load(f)


def _hash_report(kind: str, ckpt_path: str) -> Dict[str, Any]:
    """SHA-256 of the input checkpoint vs the manifest. Three outcomes:
    full-hash match, torch-hub 8-hex prefix match (the filename convention:
    ``...-6726825d.pth`` carries the sha256's first 8 hex chars), or
    'recorded' (no published hash to compare against — the computed value goes
    into the report for the manifest to adopt)."""
    digest = _sha256(ckpt_path)
    entry = _manifest().get(kind, {})
    out: Dict[str, Any] = {"sha256": digest, "manifest_entry": kind}
    expected = entry.get("sha256")
    prefix = entry.get("sha256_prefix")
    # a torch-hub-style hash suffix in the USER'S filename is also checkable
    m = re.search(r"-([0-9a-f]{8})\.pth$", os.path.basename(ckpt_path))
    if expected:
        out["hash_check"] = "match" if digest == expected else "MISMATCH"
    elif prefix:
        out["hash_check"] = "prefix_match" if digest.startswith(prefix) else "MISMATCH"
        out["expected_prefix"] = prefix
    elif m:
        out["hash_check"] = "prefix_match" if digest.startswith(m.group(1)) else "MISMATCH"
        out["expected_prefix"] = m.group(1) + " (from filename)"
    else:
        out["hash_check"] = "recorded"
    return out


def _verify_tol() -> float:
    """1e-4 scale-aware when a CPU backend exists (the verify forwards run
    under ``_template_device()``, which prefers CPU — the offline-tool norm);
    1e-3 on accelerator-only hosts, whose f32 convs run as multi-pass bf16 on
    the MXU and legitimately deviate a few 1e-4 from torch CPU."""
    return 1e-4 if _cpu_device() is not None else 1e-3


def _tap_report(pairs: Dict[str, Tuple[np.ndarray, np.ndarray]], tol: Optional[float] = None) -> Dict[str, Any]:
    """Scale-aware max deviation per tap: |flax - torch| / max(1, |torch|_inf)."""
    if tol is None:
        tol = _verify_tol()
    taps = {}
    ok = True
    for name, (got, expected) in pairs.items():
        scale = max(1.0, float(np.abs(expected).max()))
        dev = float(np.abs(np.asarray(got) - np.asarray(expected)).max()) / scale
        taps[name] = dev
        ok = ok and dev < tol
    return {"ok": ok, "tolerance": tol, "max_scaled_deviation_per_tap": taps}


def verify_inception(torch_ckpt_path: str, flax_pkl_path: str) -> Dict[str, Any]:
    """End-to-end conversion check needing NO pre-recorded fixture: load the
    real checkpoint into the independent torch mirror graph
    (``tools/torch_mirrors.TorchFidInception`` — the FID-variant the reference
    consumes, reimplemented in plain torch), run a fixed input through mirror
    and converted flax model, and compare all five taps.

    The mirror load is positional (definition order, every entry
    shape-checked) — the same order invariant the converter uses, but the
    FORWARD graphs are independent implementations, so pooling/BN/scaling/
    transpose mistakes cannot cancel out.
    """
    import torch

    from torch_mirrors import TorchFidInception, load_state_positional

    from metrics_tpu.models.inception import InceptionV3

    report = _hash_report("inception", torch_ckpt_path)

    state = torch.load(torch_ckpt_path, map_location="cpu", weights_only=True)
    if hasattr(state, "state_dict"):
        state = state.state_dict()
    tmodel = TorchFidInception()
    load_state_positional(tmodel, dict(state))
    tmodel.eval()

    with open(flax_pkl_path, "rb") as f:
        variables = pickle.load(f)
    module = InceptionV3()

    imgs = np.random.RandomState(20260731).randint(0, 256, size=(2, 299, 299, 3)).astype(np.uint8)
    with torch.no_grad():
        expected = tmodel(torch.from_numpy(np.transpose(imgs, (0, 3, 1, 2))))
    import jax
    import jax.numpy as jnp

    # jit (un-jitted flax apply dispatches each of the ~94 convs separately —
    # minutes over a tunnelled accelerator), on CPU when available so the
    # comparison against torch CPU is exact-grade (same as _verify_tol)
    with _template_device():
        got = jax.jit(module.apply)(variables, jnp.asarray(imgs))
    report.update(_tap_report({
        k: (got[k], expected[k].numpy()) for k in ("64", "192", "768", "2048", "logits_unbiased")
    }))
    return report


def verify_lpips(torch_ckpt_path: str, flax_pkl_path: str, net_type: str = "vgg") -> Dict[str, Any]:
    """Same contract as ``verify_inception`` for the LPIPS nets: real state
    dict -> independent torch mirror, fixed image pair, compare the five
    feature taps and the final LPIPS distances."""
    import torch

    from torch_mirrors import TorchAlexLpips, TorchVggLpips, load_state_positional

    from metrics_tpu.models.perceptual import LPIPSFeatureNet

    report = _hash_report(f"lpips_{net_type}", torch_ckpt_path)

    state = torch.load(torch_ckpt_path, map_location="cpu", weights_only=True)
    if hasattr(state, "state_dict"):
        state = state.state_dict()
    state = {k: v for k, v in state.items() if "scaling_layer" not in k}
    state = _dedupe_lpips_lins(state)
    tmodel = (TorchVggLpips if net_type == "vgg" else TorchAlexLpips)()
    load_state_positional(tmodel, state)
    tmodel.eval()

    net = LPIPSFeatureNet(net_type=net_type, params=flax_pkl_path)

    import jax.numpy as jnp

    size = 64 if net_type == "vgg" else 96
    rng = np.random.RandomState(20260731)
    a = (rng.rand(2, size, size, 3) * 2 - 1).astype(np.float32)
    b = (rng.rand(2, size, size, 3) * 2 - 1).astype(np.float32)
    a_t = torch.from_numpy(np.transpose(a, (0, 3, 1, 2)))
    b_t = torch.from_numpy(np.transpose(b, (0, 3, 1, 2)))

    with _template_device():
        taps_flax = net(jnp.asarray(a))
    with torch.no_grad():
        taps_torch = tmodel.taps(a_t)
        dist_torch = tmodel(a_t, b_t).numpy()
    from metrics_tpu.image.lpip_similarity import _lpips_from_features

    with _template_device():
        dist_flax = _lpips_from_features(taps_flax, net(jnp.asarray(b)), net.weights)
    pairs = {
        f"tap{i}": (g, np.transpose(e.numpy(), (0, 2, 3, 1)))
        for i, (g, e) in enumerate(zip(taps_flax, taps_torch))
    }
    pairs["lpips_distance"] = (np.asarray(dist_flax), dist_torch)
    report.update(_tap_report(pairs))
    return report


def verify_bert(torch_model_dir: str, flax_out_dir: str) -> Dict[str, Any]:
    """Compare torch vs converted-flax encoder hidden states on fixed tokens."""
    import torch
    from transformers import AutoConfig, AutoModel, FlaxAutoModel

    cfg = AutoConfig.from_pretrained(torch_model_dir)
    vocab = int(getattr(cfg, "vocab_size", 1000))
    rng = np.random.RandomState(20260731)
    ids = rng.randint(0, vocab, size=(2, 16)).astype(np.int64)
    mask = np.ones_like(ids)

    tmodel = AutoModel.from_pretrained(torch_model_dir).eval()
    with torch.no_grad():
        expected = tmodel(
            input_ids=torch.from_numpy(ids), attention_mask=torch.from_numpy(mask)
        ).last_hidden_state.numpy()
    fmodel = FlaxAutoModel.from_pretrained(flax_out_dir)
    with _template_device():
        got = np.asarray(fmodel(input_ids=ids, attention_mask=mask).last_hidden_state)
    report: Dict[str, Any] = {"manifest_entry": "bert", "hash_check": "directory (no single file hash)"}
    report.update(_tap_report({"last_hidden_state": (got, expected)}))
    return report


def _write_verify_report(report: Dict[str, Any], out_path: str) -> None:
    import json

    path = out_path.rstrip("/") + ".verify.json"
    with open(path, "w") as f:
        json.dump(report, f, indent=2)
    status = "PASS" if report.get("ok") and report.get("hash_check") != "MISMATCH" else "FAIL"
    print(f"verify: {status} -> {path}")
    if status == "FAIL":
        raise SystemExit(f"verification failed: {json.dumps(report)[:500]}")


# ----------------------------------------------------------------------- bert entry

def convert_bert(torch_model_dir: str, out_dir: str) -> None:
    """HF torch BERT checkpoint directory -> flax checkpoint directory.

    Rides transformers' own pt->flax converter (the same machinery HF uses for
    `from_pt=True`), entirely offline given a local torch checkpoint.
    """
    from transformers import AutoTokenizer, FlaxAutoModel

    model = FlaxAutoModel.from_pretrained(torch_model_dir, from_pt=True)
    model.save_pretrained(out_dir)
    try:
        AutoTokenizer.from_pretrained(torch_model_dir).save_pretrained(out_dir)
    except Exception:
        print("note: no tokenizer found next to the torch checkpoint; copy it separately")
    print(f"wrote {out_dir}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p1 = sub.add_parser("inception", help="torch-fidelity pt_inception*.pth -> flax pkl")
    p1.add_argument("torch_ckpt")
    p1.add_argument("out_pkl")
    p1.add_argument("--num-classes", type=int, default=1008)
    p2 = sub.add_parser("bert", help="HF torch model dir -> flax model dir")
    p2.add_argument("torch_model_dir")
    p2.add_argument("out_dir")
    p3 = sub.add_parser("lpips", help="lpips.LPIPS state dict -> flax pkl (backbone + lin weights)")
    p3.add_argument("torch_ckpt")
    p3.add_argument("out_pkl")
    p3.add_argument("--net-type", choices=("vgg", "alex"), default="vgg")
    for p in (p1, p2, p3):
        p.add_argument(
            "--verify", action="store_true",
            help="after converting: SHA-256 the input against tools/checkpoint_manifest.json "
                 "and forward-compare the converted flax model against an independent torch "
                 "mirror graph on a fixed input; writes <out>.verify.json, exits nonzero on "
                 "any deviation",
        )
    args = ap.parse_args()
    if args.cmd == "inception":
        convert_inception(args.torch_ckpt, args.out_pkl, args.num_classes)
        if args.verify:
            _write_verify_report(verify_inception(args.torch_ckpt, args.out_pkl), args.out_pkl)
    elif args.cmd == "lpips":
        convert_lpips(args.torch_ckpt, args.out_pkl, args.net_type)
        if args.verify:
            _write_verify_report(
                verify_lpips(args.torch_ckpt, args.out_pkl, args.net_type), args.out_pkl
            )
    else:
        convert_bert(args.torch_model_dir, args.out_dir)
        if args.verify:
            _write_verify_report(verify_bert(args.torch_model_dir, args.out_dir), args.out_dir)


if __name__ == "__main__":
    sys.exit(main())
