"""Regenerate docs/API.md: every public export with its first docstring line.

Usage: python tools/gen_api_docs.py [--out PATH]
"""
import inspect
import os
import sys
import warnings

os.environ.setdefault("JAX_PLATFORMS", "cpu")
warnings.filterwarnings("ignore")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import metrics_tpu  # noqa: E402
import metrics_tpu.functional as F  # noqa: E402

ORDER = [
    "metric", "collections", "aggregation", "classification", "regression",
    "image", "audio", "text", "retrieval", "detection", "wrappers", "parallel", "utils",
]
TITLES = {
    "metric": "Core runtime", "collections": "Collections", "aggregation": "Aggregation",
    "classification": "Classification", "regression": "Regression", "image": "Image",
    "audio": "Audio", "text": "Text", "retrieval": "Retrieval", "detection": "Detection",
    "wrappers": "Wrappers", "parallel": "Parallel / distributed", "utils": "Utilities",
}


def first_line(obj) -> str:
    # own docstring only — inspect.getdoc would inherit the base class's
    doc = obj.__dict__.get("__doc__") if isinstance(obj, type) else getattr(obj, "__doc__", None)
    line = inspect.cleandoc(doc).split("\n")[0].strip() if doc else ""
    line = line.replace("|", "\\|")  # keep markdown table cells intact
    if len(line) > 110:
        line = line[:110].rsplit(" ", 1)[0] + " …"
    return line


def main() -> None:
    lines = [
        "# API inventory", "",
        "*Every public export, with its first docstring line. Generated from the package*",
        "*(`python tools/gen_api_docs.py` regenerates; `tests/test_docs_examples.py` keeps docs executable).*", "",
    ]
    groups = {}
    for name in sorted(metrics_tpu.__all__):
        obj = getattr(metrics_tpu, name, None)
        if obj is None or name.startswith("__") or not (inspect.isclass(obj) or inspect.isfunction(obj) or callable(obj) and hasattr(obj, "__module__")):
            continue
        mod = getattr(obj, "__module__", "") or ""
        parts = mod.split(".")
        dom = parts[1] if mod.startswith("metrics_tpu.") and len(parts) > 1 else "core"
        groups.setdefault(dom, []).append((name, first_line(obj)))

    for dom in ORDER + sorted(set(groups) - set(ORDER)):
        if dom not in groups:
            continue
        lines += [f"## {TITLES.get(dom, dom)}", "", "| export | summary |", "|---|---|"]
        lines += [f"| `{name}` | {doc} |" for name, doc in groups[dom]]
        lines.append("")

    lines += ["## Functional API (`metrics_tpu.functional`)", "", "| function | summary |", "|---|---|"]
    for name in sorted(getattr(F, "__all__", dir(F))):
        obj = getattr(F, name, None)
        if callable(obj):
            lines.append(f"| `{name}` | {first_line(obj)} |")
    lines.append("")

    out = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "docs", "API.md")
    if "--out" in sys.argv:
        idx = sys.argv.index("--out")
        if idx + 1 >= len(sys.argv):
            sys.exit("usage: gen_api_docs.py [--out PATH]")
        out = sys.argv[idx + 1]
    with open(out, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
