"""Golden-tap regression kit: converter drift must fail a test.

The real pretrained checkpoints (torch-fidelity InceptionV3, lpips VGG, HF
BERT — see ``checkpoint_manifest.json``) cannot be fetched in this zero-egress
build, so converter correctness is proven structurally (graph-parity tests vs
torch mirrors). What those tests can't catch is *drift*: a converter change
that still zips shapes correctly but alters numerics would silently change
every future FID/LPIPS/BERTScore computed from converted weights.

This kit pins the whole conversion pipeline numerically:

* a SYNTHETIC deterministic checkpoint (seeded torch mirror) stands in for the
  real file; its identity is the sha256 over the state-dict values in key
  order (stable across torch serialization changes, unlike file bytes);
* the checkpoint goes through the REAL converter
  (``convert_weights.convert_conv_bn_model`` / transformers pt->flax);
* a fixed-seed input's feature taps through the converted flax model are the
  golden values, committed as small ``.npz`` files under
  ``tests/tools/golden/``.

``tests/tools/test_golden_taps.py`` regenerates the pipeline end-to-end and
compares against the committed goldens: any numeric change in the converter,
the flax model graphs, or the layout rules turns the test red. Regenerate
intentionally with ``python tools/golden_taps.py``.

Match: reference ``torchmetrics/image/fid.py:242`` (runtime download of the
hash-named checkpoint — its drift story is "the URL's hash changed").
"""
import hashlib
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

GOLDEN_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests", "tools", "golden"
)


def state_dict_sha256(state_np) -> str:
    """sha256 over (name, shape, f32 bytes) in key order — serialization-proof."""
    h = hashlib.sha256()
    for k in sorted(state_np):
        v = np.ascontiguousarray(np.asarray(state_np[k], dtype=np.float32))
        h.update(k.encode())
        h.update(str(v.shape).encode())
        h.update(v.tobytes())
    return h.hexdigest()


def build_inception_case():
    """(state_np, taps dict) — synthetic ckpt through the real converter."""
    import torch

    import jax
    import jax.numpy as jnp

    from convert_weights import _template_device, convert_conv_bn_model
    from torch_mirrors import TorchFidInception
    from metrics_tpu.models.inception import InceptionV3

    torch.manual_seed(20260731)
    tmodel = TorchFidInception()
    tmodel.train()
    with torch.no_grad():  # non-trivial BN running stats
        for _ in range(2):
            tmodel(torch.randint(0, 256, (2, 3, 299, 299), dtype=torch.uint8))
    tmodel.eval()
    state_np = {k: v.numpy() for k, v in tmodel.state_dict().items() if k != "fc.bias"}

    module = InceptionV3()
    with _template_device():
        template = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 299, 299, 3)))
    variables = convert_conv_bn_model(state_np, template)

    imgs = np.random.RandomState(42).randint(0, 256, size=(2, 299, 299, 3)).astype(np.uint8)
    got = jax.jit(module.apply)(variables, jnp.asarray(imgs))
    taps = {k: np.asarray(v, np.float32) for k, v in got.items()}
    return state_np, taps


def _build_lpips_case(net_type: str):
    """Synthetic lpips-style checkpoint through the real LPIPS converter.

    Goldens: per-tap channel means (drift-sensitive at every layer) plus the
    end-to-end LPIPS distances through the public metric.
    """
    import tempfile

    import torch

    import jax.numpy as jnp

    from convert_weights import convert_lpips
    from torch_mirrors import TorchAlexLpips, TorchVggLpips, save_lpips_style_state
    from metrics_tpu.models.perceptual import LPIPSFeatureNet
    from metrics_tpu.image.lpip_similarity import _lpips_from_features

    torch.manual_seed(20260731)
    tmodel = (TorchVggLpips if net_type == "vgg" else TorchAlexLpips)().eval()
    with torch.no_grad():  # non-negative lin heads, as lpips learns them
        for lin in tmodel.lins:
            lin.weight.abs_()
    state_np = {k: v.numpy() for k, v in tmodel.state_dict().items()}

    with tempfile.TemporaryDirectory() as tmp:
        pth = os.path.join(tmp, f"{net_type}_synth.pth")
        save_lpips_style_state(tmodel, pth)
        out = os.path.join(tmp, f"{net_type}_synth.pkl")
        convert_lpips(pth, out, net_type=net_type)
        net = LPIPSFeatureNet(net_type=net_type, params=out)

    rng = np.random.RandomState(7)
    a = jnp.asarray(rng.rand(2, 64, 64, 3).astype(np.float32) * 2 - 1)
    b = jnp.asarray(rng.rand(2, 64, 64, 3).astype(np.float32) * 2 - 1)
    taps_a, taps_b = net(a), net(b)
    golden = {
        f"tap{i}_chan_mean": np.asarray(jnp.mean(t, axis=(1, 2)), np.float32)
        for i, t in enumerate(taps_a)
    }
    golden["lpips"] = np.asarray(
        _lpips_from_features(taps_a, taps_b, net.weights), np.float32
    ).reshape(-1)
    return state_np, golden


def build_lpips_case():
    return _build_lpips_case("vgg")


def build_lpips_alex_case():
    return _build_lpips_case("alex")


def build_bert_case():
    """Synthetic tiny HF BERT torch checkpoint through the REAL pt->flax
    converter (``convert_weights.convert_bert`` rides transformers' own
    conversion — the exact pipeline real BERTScore weights take).

    Goldens: the converted flax encoder's last_hidden_state on fixed tokens
    (one full row + one partially-masked row, so attention-mask handling is
    pinned too).
    """
    import tempfile

    import torch
    from transformers import BertConfig, BertModel, FlaxAutoModel

    from convert_weights import convert_bert

    torch.manual_seed(20260731)
    cfg = BertConfig(
        vocab_size=120,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=64,
    )
    tmodel = BertModel(cfg).eval()
    state_np = {k: v.numpy() for k, v in tmodel.state_dict().items()}

    with tempfile.TemporaryDirectory() as tmp:
        tdir = os.path.join(tmp, "torch_ckpt")
        fdir = os.path.join(tmp, "flax_ckpt")
        tmodel.save_pretrained(tdir)
        convert_bert(tdir, fdir)
        fmodel = FlaxAutoModel.from_pretrained(fdir)

        rng = np.random.RandomState(42)
        ids = rng.randint(0, cfg.vocab_size, size=(2, 16)).astype(np.int32)
        mask = np.ones_like(ids)
        mask[1, 10:] = 0
        out = fmodel(input_ids=ids, attention_mask=mask).last_hidden_state
    golden = {
        "last_hidden_state_mean": np.asarray(out, np.float32).mean(axis=-1),
        "last_hidden_state_row0": np.asarray(out, np.float32)[0, 0],
    }
    return state_np, golden


def _pin_backend() -> None:
    """Match the config the test suite runs under (tests/conftest.py): CPU
    platform, highest matmul precision. Generation and verification must see
    the identical backend or the goldens pin the environment, not the code."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")


def generate(golden_dir: str = GOLDEN_DIR) -> None:
    _pin_backend()
    os.makedirs(golden_dir, exist_ok=True)
    for name, builder in (
        ("inception", build_inception_case),
        ("lpips_vgg", build_lpips_case),
        ("lpips_alex", build_lpips_alex_case),
        ("bert", build_bert_case),
    ):
        state_np, taps = builder()
        path = os.path.join(golden_dir, f"{name}_taps.npz")
        np.savez_compressed(path, ckpt_sha256=state_dict_sha256(state_np), **taps)
        print(f"wrote {path}: ckpt {state_dict_sha256(state_np)[:16]}…, "
              + ", ".join(f"{k}{v.shape}" for k, v in taps.items()))


if __name__ == "__main__":
    generate()
