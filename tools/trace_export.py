"""Validate and summarize flight-recorder artifacts (pure stdlib).

Usage::

    python tools/trace_export.py out/trace_chaos.json            # validate + summary
    python tools/trace_export.py out/trace_chaos.json --slowest 10
    python tools/trace_export.py --openmetrics out/obs_metrics.txt

Consumes the two exporter formats of ``metrics_tpu/engine/trace.py``:

* Chrome/Perfetto trace-event JSON (``StreamingEngine.export_trace``):
  :func:`validate_chrome_trace` checks the event schema (phases, required
  fields, metadata thread names) and :func:`validate_links` checks the
  coalesce contract — every megabatch span's ``links`` resolve to submit
  spans present in the document, and every submit span is absorbed by
  exactly one megabatch.
* OpenMetrics text (``StreamingEngine.metrics_text``): :func:`parse_openmetrics`
  parses the exposition and raises ``ValueError`` on malformed families —
  counters must sample ``_total``, histogram buckets must be cumulative with
  ascending ``le`` edges ending in ``+Inf``, ``_count`` must equal the
  ``+Inf`` bucket, and the document must end with ``# EOF``.

Like ``tools/engine_report.py``, deliberately jax-free: runs anywhere the
artifacts land. ``make obs-smoke`` and ``make chaos-smoke`` drive the
validators as CI gates.
"""
import argparse
import json
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

_PHASES = {"X", "i", "M", "s", "f"}

_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>[^ ]+)$"
)


# ----------------------------------------------------------- chrome trace JSON


def validate_chrome_trace(doc: Any) -> List[str]:
    """Schema-check one trace-event document; returns error strings
    (empty = valid)."""
    errors: List[str] = []
    if not isinstance(doc, dict) or not isinstance(doc.get("traceEvents"), list):
        return ["document must be an object with a 'traceEvents' list"]
    events = doc["traceEvents"]
    if not events:
        errors.append("traceEvents is empty")
    threads: Dict[int, str] = {}
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _PHASES:
            errors.append(f"{where}: unknown phase {ph!r} (expected one of {sorted(_PHASES)})")
            continue
        for key in ("name", "pid", "tid", "ts"):
            if key not in ev:
                errors.append(f"{where}: missing {key!r}")
        if not isinstance(ev.get("ts", 0), (int, float)) or ev.get("ts", 0) < 0:
            errors.append(f"{where}: ts must be a non-negative number")
        if ph == "X":
            if not isinstance(ev.get("dur"), (int, float)) or ev["dur"] < 0:
                errors.append(f"{where}: complete event needs a non-negative 'dur'")
            if not isinstance(ev.get("args", {}).get("trace"), str):
                errors.append(f"{where}: span is missing its args.trace id")
        elif ph == "i":
            if ev.get("s") not in ("t", "p", "g"):
                errors.append(f"{where}: instant event needs a scope 's' of t/p/g")
        elif ph == "M":
            if ev.get("name") == "thread_name":
                name = ev.get("args", {}).get("name")
                if not name:
                    errors.append(f"{where}: thread_name metadata without args.name")
                elif threads.get(ev.get("tid")) not in (None, name):
                    errors.append(f"{where}: tid {ev.get('tid')} renamed mid-document")
                else:
                    threads[ev.get("tid")] = name
        elif ph in ("s", "f"):
            if "id" not in ev:
                errors.append(f"{where}: flow event needs an 'id'")
    for ev in events:
        if isinstance(ev, dict) and ev.get("ph") in ("X", "i") and ev.get("tid") not in threads:
            errors.append(f"event {ev.get('name')!r} on tid {ev.get('tid')} has no thread_name metadata")
            break
    return errors


def _spans(doc: Dict[str, Any], name: Optional[str] = None) -> List[Dict[str, Any]]:
    return [
        ev for ev in doc.get("traceEvents", [])
        if isinstance(ev, dict) and ev.get("ph") == "X"
        and (name is None or ev.get("name") == name)
    ]


def validate_links(doc: Dict[str, Any]) -> List[str]:
    """The coalesce contract: every megabatch span's ``links`` resolve to
    submit spans in the document, and every submit span is absorbed by
    exactly ONE megabatch (groups partition the submit stream)."""
    errors: List[str] = []
    submit_tids = [ev["args"]["trace"] for ev in _spans(doc, "submit")]
    submit_set = set(submit_tids)  # membership is per-link on big traces
    absorbed: Dict[str, str] = {}
    for ev in _spans(doc, "coalesce"):
        gid = ev["args"].get("trace")
        links = ev["args"].get("links", [])
        if not links:
            errors.append(f"megabatch {gid} has no submit links")
            continue
        for link in links:
            if link not in submit_set:
                errors.append(f"megabatch {gid} links unknown submit trace {link!r}")
            elif link in absorbed:
                errors.append(
                    f"submit trace {link!r} absorbed twice ({absorbed[link]} and {gid})"
                )
            else:
                absorbed[link] = gid
    for tid in submit_tids:
        if tid not in absorbed:
            errors.append(f"submit trace {tid!r} was never absorbed by a megabatch span")
    return errors


def fault_sites(doc: Dict[str, Any]) -> Dict[str, int]:
    """Injected-fault firings by site from the ``fault`` instant events."""
    out: Dict[str, int] = {}
    for ev in doc.get("traceEvents", []):
        if isinstance(ev, dict) and ev.get("ph") == "i" and ev.get("name") == "fault":
            site = ev.get("args", {}).get("site")
            if site:
                out[site] = out.get(site, 0) + 1
    return out


def summarize(doc: Dict[str, Any], slowest: int = 5) -> str:
    """Slowest-N trace summary rendered from an exported trace document.

    The end-to-end definition (root = coalesce span else longest; total =
    root + queue waits) mirrors ``TraceRecorder.summary()`` — a deliberate
    second implementation (this tool runs where only the JSON lands), kept
    in lockstep by the parity pin in ``tests/engine/test_trace.py``."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for ev in _spans(doc):
        by_trace.setdefault(ev["args"]["trace"], []).append(ev)
    roots = []
    for trace, members in by_trace.items():
        if trace == "engine":
            continue
        root = next((m for m in members if m.get("name") == "coalesce"), None)
        if root is None:
            # a submit-only trace is no journey — it lives in the g-trace
            # that absorbed it (same rule as TraceRecorder.summary)
            non_submit = [m for m in members if m.get("name") != "submit"]
            if not non_submit:
                continue
            root = max(non_submit, key=lambda e: e.get("dur", 0))
        total = root.get("dur", 0) + sum(
            m.get("dur", 0) for m in members if m.get("name") == "queue_wait"
        )
        roots.append((total, root, members))
    roots.sort(key=lambda rm: -rm[0])
    lines = [f"── slowest {min(slowest, len(roots))} traces " + "─" * 36]
    for total, root, members in roots[:slowest]:
        parts = ", ".join(
            f"{m['name']} {m.get('dur', 0):,.0f}µs" for m in members if m is not root
        )
        links = root.get("args", {}).get("links")
        lines.append(
            f"  {root['args']['trace']:<8} {root['name']:<10} {total:>12,.1f}µs"
            + (f"  ← {len(links)} submits" if links else "")
            + (f"  [{parts}]" if parts else "")
        )
    return "\n".join(lines)


# ------------------------------------------------------------ openmetrics text


def parse_openmetrics(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse one OpenMetrics exposition into ``{family: {type, samples}}``.

    Raises ``ValueError`` on structural violations: no ``# EOF`` terminator,
    samples without a TYPE, counter samples not ending ``_total``, histogram
    buckets with non-ascending ``le`` edges or non-cumulative counts, missing
    ``+Inf`` bucket, or ``_count`` disagreeing with the ``+Inf`` bucket.
    """
    lines = text.splitlines()
    if not lines or lines[-1].strip() != "# EOF":
        raise ValueError("exposition must end with '# EOF'")
    families: Dict[str, Dict[str, Any]] = {}
    for ln, line in enumerate(lines[:-1], 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                families[parts[2]] = {"type": parts[3], "samples": []}
            continue
        m = _SAMPLE.match(line)
        if m is None:
            raise ValueError(f"line {ln}: unparseable sample {line!r}")
        name, labels_raw, value = m.group("name"), m.group("labels"), m.group("value")
        try:
            value_f = float(value)
        except ValueError:
            raise ValueError(f"line {ln}: non-numeric value {value!r}") from None
        labels: Dict[str, str] = {}
        for pair in (labels_raw or "").split(","):
            if not pair:
                continue
            if "=" not in pair:
                raise ValueError(f"line {ln}: malformed label {pair!r}")
            k, v = pair.split("=", 1)
            labels[k.strip()] = v.strip().strip('"')
        # longest match: family names may prefix one another (e.g. an `items`
        # counter next to an `items_per_s` gauge) — the sample belongs to the
        # most specific family, not the first declared
        family = max(
            (f for f in families if name == f or name.startswith(f + "_")),
            key=len, default=None,
        )
        if family is None:
            raise ValueError(f"line {ln}: sample {name!r} has no preceding TYPE")
        families[family]["samples"].append({"name": name, "labels": labels, "value": value_f})
    for family, info in families.items():
        if info["type"] == "counter":
            for s in info["samples"]:
                if not s["name"].endswith("_total"):
                    raise ValueError(
                        f"counter family {family!r} has sample {s['name']!r} "
                        "without the _total suffix"
                    )
        elif info["type"] == "histogram":
            buckets = [s for s in info["samples"] if s["name"] == family + "_bucket"]
            count = next((s for s in info["samples"] if s["name"] == family + "_count"), None)
            if not buckets or count is None:
                raise ValueError(f"histogram family {family!r} is missing buckets or _count")
            if buckets[-1]["labels"].get("le") != "+Inf":
                raise ValueError(f"histogram family {family!r} must end with le='+Inf'")
            prev_le, prev_n = float("-inf"), -1.0
            for b in buckets:
                le = b["labels"].get("le")
                le_f = float("inf") if le == "+Inf" else float(le)
                if le_f <= prev_le:
                    raise ValueError(f"histogram family {family!r}: le edges not ascending")
                if b["value"] < prev_n:
                    raise ValueError(f"histogram family {family!r}: bucket counts not cumulative")
                prev_le, prev_n = le_f, b["value"]
            if buckets[-1]["value"] != count["value"]:
                raise ValueError(
                    f"histogram family {family!r}: _count {count['value']} != "
                    f"+Inf bucket {buckets[-1]['value']}"
                )
    return families


# ------------------------------------------------------------------------- CLI


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace_json", nargs="?", help="Chrome/Perfetto trace-event JSON")
    ap.add_argument("--openmetrics", help="OpenMetrics text exposition to validate")
    ap.add_argument("--slowest", type=int, default=5, help="traces to summarize")
    args = ap.parse_args(argv)
    if not args.trace_json and not args.openmetrics:
        ap.error("give a trace JSON path and/or --openmetrics")
    rc = 0
    if args.trace_json:
        with open(args.trace_json) as f:
            doc = json.load(f)
        errors = validate_chrome_trace(doc) + validate_links(doc)
        for e in errors:
            print(f"INVALID: {e}")
            rc = 1
        if rc == 0:
            spans = _spans(doc)
            sites = fault_sites(doc)
            print(
                f"valid trace: {len(spans)} spans"
                + (f", fault sites: {', '.join(sorted(sites))}" if sites else "")
            )
            print(summarize(doc, args.slowest))
    if args.openmetrics:
        with open(args.openmetrics) as f:
            text = f.read()
        try:
            families = parse_openmetrics(text)
        except ValueError as e:
            print(f"INVALID: {e}")
            rc = 1
        else:
            n_hist = sum(1 for f_ in families.values() if f_["type"] == "histogram")
            print(f"valid openmetrics: {len(families)} families ({n_hist} histograms)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
