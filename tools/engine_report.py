"""Pretty-print streaming-engine telemetry JSON.

Usage::

    python tools/engine_report.py out/engine_telemetry.json [--steps N]
    python tools/engine_report.py out/engine_telemetry.json --json  # machine-readable

Reads the document written by ``StreamingEngine.export_telemetry`` (or
``python -m metrics_tpu.engine.smoke``) and renders the summary plus the tail
of the per-step ring — including the host-time attribution (``regime``:
dispatch-bound / pad-bound / device-bound / sync-bound / starved) that says
WHERE the dispatcher's wall time went, the coalescing ratio (submitted batches
per device step), and — for mesh engines — the collective share: per-step sync
latency under ``mesh_sync="step"`` vs boundary-merge time under
``mesh_sync="deferred"`` (the step-vs-deferred comparison) — and, when the
engine saw any fault activity (ISSUE 6), the fault block: injected faults by
site, recovery actions (retries, rollbacks, kernel demotions, coalesce
shrinks, watchdog expiries), the quarantine ledger totals, and snapshot
write-failure/restore-fallback counts. Engines running the ISSUE 11
self-defense layer additionally render the admission block (admitted/
rejected/shed by priority class, degradation-ladder level + transitions,
deferred stale reads) and the elastic-reshard row (count + the last
world→world transition and its replay cursor). Windowed engines (ISSUE 13)
render the windows block: policy tag, pane rotations, live panes + ring
cursor, ewma decays applied, and the drift-tracker row (pane evals, alarms).
Ragged engines (ISSUE 17) render the ragged-groups row: groups touched of
the declared universe, per-group capacity, ingest volume, and overflow
firings. Stream-sharded fleet hosts (ISSUE 20) add the fleet-tenancy row: the
hierarchical fold's per-leg bytes (intra-host exact vs cross-host wire) and
the pager-mirrored residency/spill gauges. Engines with an embedded-model
host attached (ISSUE 19,
``engine.model_host``) render one model-host row per host: model kind,
sharding mode + declared collective allowance, bucketed ingest volume, and
the closed program set (compiles vs hits).
When the engine ran with a flight recorder (``EngineConfig(trace=...)``,
PR 8) the document carries a ``trace`` section and the report renders the
trace/SLO block: spans recorded/dropped, latency histogram counts, and the
slowest-N trace ids with their per-span breakdown — the causal answer to
"which batch's journey produced the tail". ``--json`` emits the normalized
document (summary + recent steps + trace) as machine-readable JSON for
dashboards and scripts.
Pure stdlib — safe to run anywhere the JSON lands (no jax import, so it works
on a machine without the accelerator stack).
"""
import argparse
import json
import os
import sys


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.1f}" if abs(v) >= 10 else f"{v:.4g}"
    if isinstance(v, int):
        return f"{v:,}"
    return str(v)


def render(doc: dict, steps: int = 10, analysis: dict = None) -> str:
    s = doc.get("summary", {})
    cc = s.get("compile_cache", {})
    co = s.get("coalesce", {})
    lines = []
    lines.append("── streaming engine telemetry " + "─" * 30)
    rows = [
        ("steps", s.get("steps")),
        ("batches submitted", s.get("batches_submitted")),
        (
            "coalesced (megasteps)",
            f"{_fmt(co.get('batches_coalesced'))} ({_fmt(co.get('megasteps'))}), "
            f"{_fmt(co.get('batches_per_step_mean'))} batches/step",
        ),
        ("rows in / padded", f"{_fmt(s.get('rows_in'))} / {_fmt(s.get('rows_padded'))}"),
        ("padding waste", f"{100 * s.get('padding_waste_fraction', 0):.2f}%"),
        ("queue depth max", s.get("queue_depth_max")),
        ("ingest µs p50/p95", f"{_fmt(s.get('ingest_us', {}).get('p50'))} / {_fmt(s.get('ingest_us', {}).get('p95'))}"),
        (
            "blocked sync µs p50/p95 (n)",
            f"{_fmt(s.get('blocked_sync_us', {}).get('p50'))} / "
            f"{_fmt(s.get('blocked_sync_us', {}).get('p95'))} "
            f"({_fmt(s.get('blocked_sync_us', {}).get('count'))})",
        ),
        ("snapshots / resumes", f"{_fmt(s.get('snapshots'))} / {_fmt(s.get('resumes'))}"),
        ("compiled programs", cc.get("programs")),
        ("cache hits / misses", f"{_fmt(cc.get('hits'))} / {_fmt(cc.get('misses'))}"),
        ("compile seconds", cc.get("compile_seconds")),
        ("persistent cache entries", cc.get("persistent_cache_entries")),
    ]
    faults = s.get("faults")
    if faults:
        injected = faults.get("injected", {})
        inj_txt = (
            ", ".join(f"{k}×{v}" for k, v in sorted(injected.items())) if injected else "none"
        )
        recov = " · ".join(
            f"{label} {_fmt(faults.get(key))}"
            for label, key in (
                ("retries", "retries"),
                ("rollbacks", "rollbacks"),
                ("demotions", "kernel_demotions"),
                ("shrinks", "coalesce_shrinks"),
                ("watchdog", "watchdog_timeouts"),
            )
            if faults.get(key)
        )
        rows.append(("faults injected", inj_txt))
        rows.append(("recovery actions", recov or "none"))
        rows.append(
            (
                "quarantined (batches/rows)",
                f"{_fmt(faults.get('quarantined_batches'))} / "
                f"{_fmt(faults.get('quarantined_rows'))}",
            )
        )
        if faults.get("snapshot_failures") or faults.get("snapshot_fallbacks"):
            rows.append(
                (
                    "snapshot failures / fallbacks",
                    f"{_fmt(faults.get('snapshot_failures'))} / "
                    f"{_fmt(faults.get('snapshot_fallbacks'))}",
                )
            )
    kernels = s.get("kernels")
    if kernels:
        # megastep degradation verdicts (ISSUE 16): which dtypes/engines fell
        # back off the fused grid and WHY — keyed "engine:<reason>" /
        # "dtype.<key>:<reason>", counted once at construction. Engines that
        # never judged a fallback carry no block and render exactly as before.
        fb = kernels.get("fallbacks_by_reason", {})
        rows.append(
            (
                "kernel fallbacks",
                ", ".join(f"{k}×{v}" for k, v in sorted(fb.items())) if fb else "none",
            )
        )
    ms = s.get("mesh_sync")
    if ms:
        share = ms.get("collective_share")
        bound = "≤ " if ms.get("collective_share_is_upper_bound") else ""
        rows.insert(
            3,
            (
                "mesh sync",
                f"{ms.get('mode')} · collective share "
                f"{'-' if share is None else f'{bound}{100 * share:.1f}%'}"
                + (
                    f" ({_fmt(ms.get('merges'))} boundary merges, "
                    f"{_fmt(ms.get('merge_us_total'))} µs total)"
                    if ms.get("mode") == "deferred"
                    else " (per-step blocked sync: collective + in-step compute)"
                ),
            ),
        )
    admission = s.get("admission")
    if admission:
        def _by_prio(d):
            return (
                ", ".join(f"p{k}×{v}" for k, v in sorted(d.items())) if d else "none"
            )

        rows.append(
            (
                "admission (adm/rej/shed)",
                f"{_by_prio(admission.get('admitted_by_priority', {}))} / "
                f"{_by_prio(admission.get('rejected_by_priority', {}))} / "
                f"{_by_prio(admission.get('shed_by_priority', {}))}",
            )
        )
        rows.append(
            (
                "degradation ladder",
                f"level {_fmt(admission.get('ladder_level'))} · "
                f"{_fmt(admission.get('ladder_transitions'))} transitions · "
                f"{_fmt(admission.get('deferred_reads'))} deferred reads",
            )
        )
    windows = s.get("windows")
    if windows:
        drift = windows.get("drift") or {}
        rows.append(
            (
                "windows",
                f"{windows.get('policy')} · {_fmt(windows.get('pane_rotations'))} rotations"
                f" · {_fmt(windows.get('live_panes'))} live panes"
                f" (cursor {_fmt(windows.get('pane_cursor'))})"
                + (
                    f" · {_fmt(windows.get('ewma_decays'))} ewma decays"
                    if windows.get("ewma_decays")
                    else ""
                ),
            )
        )
        if drift:
            rows.append(
                (
                    "drift",
                    f"{_fmt(drift.get('evals'))} pane evals · "
                    f"{_fmt(drift.get('alarms'))} alarms",
                )
            )
    ragged = s.get("ragged")
    if ragged:
        # group-keyed serving section (ISSUE 17): the declared group
        # universe and capacity, the ingest volume, how many groups have
        # rows, and overflow firings (a nonzero count means some group's
        # TRUE row total exceeded capacity — the aggregate read raises).
        # Non-ragged documents carry no block and render exactly as before.
        rows.append(
            (
                "ragged groups",
                f"{_fmt(ragged.get('groups_touched'))} of "
                f"{_fmt(ragged.get('groups'))} touched"
                f" · capacity {_fmt(ragged.get('capacity'))}"
                f" · {_fmt(ragged.get('rows'))} rows in "
                f"{_fmt(ragged.get('batches'))} grouped batches"
                + (
                    f" · {_fmt(ragged.get('overflows'))} OVERFLOWS"
                    if ragged.get("overflows")
                    else ""
                ),
            )
        )
        if ragged.get("agg_device_reads") or ragged.get("agg_oracle_reads"):
            # aggregate-read paths (ISSUE 18): compiled device folds vs host
            # oracle replays, plus paged-sweep block dispatches under
            # group_shard (G-independent for a fixed touched population).
            rows.append(
                (
                    "ragged aggregate",
                    f"{_fmt(ragged.get('agg_device_reads'))} device · "
                    f"{_fmt(ragged.get('agg_oracle_reads'))} oracle"
                    + (
                        f" · {_fmt(ragged.get('agg_blocks'))} sweep blocks"
                        if ragged.get("agg_blocks")
                        else ""
                    ),
                )
            )
    fleet = s.get("fleet")
    if fleet:
        # per-host fleet section (ISSUE 15): which host of how many this
        # document came from, its stream ownership, its share of the shared
        # ingest plan, and the cross-host boundary traffic (folds, barrier
        # entries, snapshot cuts, per-fold sync payload bytes). A stats
        # document with NO fleet block — every single-process engine —
        # renders exactly as before.
        spb = fleet.get("sync_payload_bytes") or {}
        rows.append(
            (
                "fleet host",
                f"{_fmt(fleet.get('process_id'))} of {_fmt(fleet.get('num_hosts'))}"
                f" · {_fmt(fleet.get('streams_owned'))} streams owned"
                f" · ingested {_fmt(fleet.get('ingested'))}"
                f" / skipped {_fmt(fleet.get('skipped'))} plan batches",
            )
        )
        rows.append(
            (
                "fleet boundaries",
                f"{_fmt(fleet.get('merges'))} folds"
                f" ({_fmt(fleet.get('merge_us_total'))} µs total)"
                f" · {_fmt(fleet.get('barriers'))} barriers"
                f" · {_fmt(fleet.get('cuts'))} snapshot cuts"
                f" · sync payload {_fmt(spb.get('exact'))}B exact"
                f" / {_fmt(spb.get('quantized'))}B quantized",
            )
        )
        ten = fleet.get("tenancy") or {}
        if fleet.get("payload_intra_bytes") or any(ten.values()):
            # stream-sharded fleet tenancy (ISSUE 20): the hierarchical
            # fold's per-leg bytes (intra-host exact vs cross-host wire) and
            # the pager-mirrored residency gauges — the numbers that show
            # per-host device bytes staying flat while the stream universe
            # grows. Unsharded fleets carry zeros here and render as before.
            rows.append(
                (
                    "fleet tenancy",
                    f"fold legs {_fmt(fleet.get('payload_intra_bytes'))}B intra"
                    f" / {_fmt((spb.get('exact') or 0) + (spb.get('quantized') or 0))}B cross"
                    f" · resident {_fmt(ten.get('resident_rows'))}"
                    f" / spilled {_fmt(ten.get('spill_rows'))} rows"
                    f" ({_fmt(ten.get('spill_bytes'))}B host RAM)",
                )
            )
    hosts = doc.get("model_host") or s.get("model_host")
    if hosts:
        # embedded-model serving section (ISSUE 19): one row per attached
        # resident host — what model it serves, its sharding mode + declared
        # collective allowance, the bucketed/coalesced ingest volume, and the
        # closed program set (bucket_compiles is the host's LIFETIME compile
        # count; a steady-state host only ever grows bucket_hits). Documents
        # without an attached host carry no block and render exactly as before.
        for h in hosts:
            c = h.get("counters", {})
            rows.append(
                (
                    f"model host [{h.get('kind')}]",
                    f"{h.get('sharding')} · {h.get('precision')}"
                    f" · collectives {','.join(h.get('allowed_collectives') or []) or 'none'}"
                    f" · {_fmt(c.get('items'))} {h.get('unit')} in "
                    f"{_fmt(c.get('requests'))} requests"
                    f" ({_fmt(c.get('batches'))} device batches, "
                    f"{_fmt(c.get('coalesced_batches'))} coalesced)"
                    f" · programs {_fmt(c.get('bucket_compiles'))} compiled / "
                    f"{_fmt(c.get('bucket_hits'))} hits"
                    + (
                        f" · shared by {_fmt(c.get('shared_by'))} metrics"
                        if (c.get("shared_by") or 0) > 1
                        else ""
                    ),
                )
            )
    reshard = s.get("reshard")
    if reshard:
        last = reshard.get("last") or {}
        rows.append(
            (
                "elastic reshards",
                f"{_fmt(reshard.get('reshards'))}"
                + (
                    f" (last: world {last.get('from_world')}→{last.get('to_world')}"
                    f" at cursor {last.get('cursor')}"
                    f"{', auto' if last.get('auto') else ''})"
                    if last
                    else ""
                ),
            )
        )
    paging = s.get("paging")
    if paging:
        rate = paging.get("page_hit_rate")
        rows.insert(
            3,
            (
                "stream paging",
                f"hits {_fmt(paging.get('page_hits'))} / faults "
                f"{_fmt(paging.get('page_faults'))}"
                + (f" ({100 * rate:.1f}% hit rate)" if rate is not None else "")
                + f" · in {_fmt(paging.get('page_ins'))} / out {_fmt(paging.get('page_outs'))}"
                + f" · resident {_fmt(paging.get('resident_streams'))}"
                + f" / spilled {_fmt(paging.get('spilled_streams'))}"
                + f" · routed steps {_fmt(paging.get('routed_steps'))}",
            ),
        )
    shares = s.get("host_time_shares")
    if shares:
        rows.insert(
            3,
            (
                "host time shares",
                f"dispatch {100 * shares.get('dispatch', 0):.1f}% · "
                f"pad {100 * shares.get('pad', 0):.1f}% · "
                f"queue-wait {100 * shares.get('queue_wait', 0):.1f}% · "
                f"blocked-sync {100 * shares.get('blocked_sync', 0):.1f}%",
            ),
        )
        rows.insert(4, ("regime", shares.get("regime")))
    w = max(len(k) for k, _ in rows)
    for k, v in rows:
        lines.append(f"  {k:<{w}}  {_fmt(v)}")
    conc = (analysis or {}).get("concurrency")
    if conc:
        # the ISSUE 14 lock-contract audit (make analyze, concurrency plane):
        # say explicitly when this engine's module set was audited clean —
        # the operator reading a telemetry report should not have to know a
        # separate gate exists to learn the lock discipline held
        n_mod = len(conc.get("audited_modules", []))
        n_findings = len(conc.get("findings", []))
        secs = (analysis or {}).get("plane_seconds", {}).get("concurrency")
        lines.append("── concurrency audit " + "─" * 39)
        if conc.get("clean"):
            lines.append(
                f"  engine module set audited CLEAN: {n_mod} declared modules, "
                "lockset/lock-order/dispatch/check-then-act all quiet"
                + (f" ({secs:g}s)" if secs is not None else "")
            )
        else:
            lines.append(
                f"  {n_findings} concurrency finding(s) over {n_mod} declared "
                "modules — run `make analyze` for details"
            )
    tr = _trace_section(doc)
    if tr:
        lines.append("── trace / SLO " + "─" * 45)
        dropped = tr.get("dropped", 0)
        lines.append(
            f"  spans {_fmt(tr.get('spans'))} · events {_fmt(tr.get('events'))}"
            + (f" · DROPPED {_fmt(dropped)} (ring full)" if dropped else "")
        )
        hists = tr.get("histograms", {})
        for name, h in sorted(hists.items()):
            lines.append(
                f"  {name}: n={_fmt(h.get('count'))} sum={_fmt(h.get('sum'))}µs"
            )
        slowest = tr.get("slowest_traces", [])
        if slowest:
            lines.append(f"  slowest {len(slowest)} traces (id · root · end-to-end µs · breakdown):")
            for t in slowest:
                brk = ", ".join(
                    f"{k} {_fmt(v)}" for k, v in sorted(
                        t.get("breakdown", {}).items(), key=lambda kv: -kv[1]
                    )
                )
                extras = []
                if t.get("links"):
                    extras.append(f"{len(t['links'])} submits")
                if t.get("stream_ids"):
                    extras.append(f"streams {t['stream_ids']}")
                lines.append(
                    f"    {t.get('trace'):<8} {t.get('root'):<10} {_fmt(t.get('dur_us'))}"
                    + (f"  ({'; '.join(extras)})" if extras else "")
                    + (f"  [{brk}]" if brk else "")
                )
    recent = doc.get("recent_steps", [])[-steps:]
    if recent:
        lines.append(f"── last {len(recent)} steps " + "─" * 44)
        lines.append("  step  bucket  valid  coal  queue  ingest_us    pad_us   wait_us   sync_us")
        for r in recent:
            def _us(key):
                return f"{r[key]:>8.1f}" if key in r else "       -"

            lines.append(
                f"  {r.get('step', 0):>4}  {r.get('bucket', 0):>6}  {r.get('valid', 0):>5}"
                f"  {r.get('coalesced', 1):>4}  {r.get('queue_depth', 0):>5}"
                f"  {r.get('ingest_us', 0):>9.1f}  {_us('pad_us')}  {_us('queue_wait_us')}  {_us('sync_us')}"
            )
    return "\n".join(lines)


def _trace_section(doc: dict):
    """The flight-recorder summary — exported top-level since PR 8, but a
    live ``engine.telemetry()`` dict carries it inside the summary."""
    return doc.get("trace") or doc.get("summary", {}).get("trace")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("telemetry_json")
    ap.add_argument("--steps", type=int, default=10, help="step records to show")
    ap.add_argument(
        "--json", action="store_true",
        help="emit the normalized document (summary/recent_steps/trace) as JSON",
    )
    ap.add_argument(
        "--analysis", default=None,
        help="analysis_report.json from `make analyze` (default: the one "
        "next to the telemetry file, when present) — adds the concurrency-"
        "audit line saying whether the engine module set checked clean",
    )
    args = ap.parse_args(argv)
    with open(args.telemetry_json) as f:
        doc = json.load(f)
    analysis = None
    analysis_path = args.analysis
    if analysis_path is None:
        sibling = os.path.join(
            os.path.dirname(os.path.abspath(args.telemetry_json)), "analysis_report.json"
        )
        analysis_path = sibling if os.path.exists(sibling) else None
    if analysis_path:
        try:
            with open(analysis_path) as f:
                analysis = json.load(f)
        except (OSError, ValueError):
            analysis = None
    if args.json:
        out = {
            "summary": {k: v for k, v in doc.get("summary", {}).items() if k != "trace"},
            "recent_steps": doc.get("recent_steps", []),
        }
        tr = _trace_section(doc)
        if tr:
            out["trace"] = tr
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    print(render(doc, steps=args.steps, analysis=analysis))
    return 0


if __name__ == "__main__":
    sys.exit(main())
