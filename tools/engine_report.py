"""Pretty-print streaming-engine telemetry JSON.

Usage::

    python tools/engine_report.py out/engine_telemetry.json [--steps N]

Reads the document written by ``StreamingEngine.export_telemetry`` (or
``python -m metrics_tpu.engine.smoke``) and renders the summary plus the tail
of the per-step ring — including the host-time attribution (``regime``:
dispatch-bound / pad-bound / device-bound / sync-bound / starved) that says
WHERE the dispatcher's wall time went, the coalescing ratio (submitted batches
per device step), and — for mesh engines — the collective share: per-step sync
latency under ``mesh_sync="step"`` vs boundary-merge time under
``mesh_sync="deferred"`` (the step-vs-deferred comparison) — and, when the
engine saw any fault activity (ISSUE 6), the fault block: injected faults by
site, recovery actions (retries, rollbacks, kernel demotions, coalesce
shrinks, watchdog expiries), the quarantine ledger totals, and snapshot
write-failure/restore-fallback counts.
Pure stdlib — safe to run anywhere the JSON lands (no jax import, so it works
on a machine without the accelerator stack).
"""
import argparse
import json
import sys


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.1f}" if abs(v) >= 10 else f"{v:.4g}"
    if isinstance(v, int):
        return f"{v:,}"
    return str(v)


def render(doc: dict, steps: int = 10) -> str:
    s = doc.get("summary", {})
    cc = s.get("compile_cache", {})
    co = s.get("coalesce", {})
    lines = []
    lines.append("── streaming engine telemetry " + "─" * 30)
    rows = [
        ("steps", s.get("steps")),
        ("batches submitted", s.get("batches_submitted")),
        (
            "coalesced (megasteps)",
            f"{_fmt(co.get('batches_coalesced'))} ({_fmt(co.get('megasteps'))}), "
            f"{_fmt(co.get('batches_per_step_mean'))} batches/step",
        ),
        ("rows in / padded", f"{_fmt(s.get('rows_in'))} / {_fmt(s.get('rows_padded'))}"),
        ("padding waste", f"{100 * s.get('padding_waste_fraction', 0):.2f}%"),
        ("queue depth max", s.get("queue_depth_max")),
        ("ingest µs p50/p95", f"{_fmt(s.get('ingest_us', {}).get('p50'))} / {_fmt(s.get('ingest_us', {}).get('p95'))}"),
        (
            "blocked sync µs p50/p95 (n)",
            f"{_fmt(s.get('blocked_sync_us', {}).get('p50'))} / "
            f"{_fmt(s.get('blocked_sync_us', {}).get('p95'))} "
            f"({_fmt(s.get('blocked_sync_us', {}).get('count'))})",
        ),
        ("snapshots / resumes", f"{_fmt(s.get('snapshots'))} / {_fmt(s.get('resumes'))}"),
        ("compiled programs", cc.get("programs")),
        ("cache hits / misses", f"{_fmt(cc.get('hits'))} / {_fmt(cc.get('misses'))}"),
        ("compile seconds", cc.get("compile_seconds")),
        ("persistent cache entries", cc.get("persistent_cache_entries")),
    ]
    faults = s.get("faults")
    if faults:
        injected = faults.get("injected", {})
        inj_txt = (
            ", ".join(f"{k}×{v}" for k, v in sorted(injected.items())) if injected else "none"
        )
        recov = " · ".join(
            f"{label} {_fmt(faults.get(key))}"
            for label, key in (
                ("retries", "retries"),
                ("rollbacks", "rollbacks"),
                ("demotions", "kernel_demotions"),
                ("shrinks", "coalesce_shrinks"),
                ("watchdog", "watchdog_timeouts"),
            )
            if faults.get(key)
        )
        rows.append(("faults injected", inj_txt))
        rows.append(("recovery actions", recov or "none"))
        rows.append(
            (
                "quarantined (batches/rows)",
                f"{_fmt(faults.get('quarantined_batches'))} / "
                f"{_fmt(faults.get('quarantined_rows'))}",
            )
        )
        if faults.get("snapshot_failures") or faults.get("snapshot_fallbacks"):
            rows.append(
                (
                    "snapshot failures / fallbacks",
                    f"{_fmt(faults.get('snapshot_failures'))} / "
                    f"{_fmt(faults.get('snapshot_fallbacks'))}",
                )
            )
    ms = s.get("mesh_sync")
    if ms:
        share = ms.get("collective_share")
        bound = "≤ " if ms.get("collective_share_is_upper_bound") else ""
        rows.insert(
            3,
            (
                "mesh sync",
                f"{ms.get('mode')} · collective share "
                f"{'-' if share is None else f'{bound}{100 * share:.1f}%'}"
                + (
                    f" ({_fmt(ms.get('merges'))} boundary merges, "
                    f"{_fmt(ms.get('merge_us_total'))} µs total)"
                    if ms.get("mode") == "deferred"
                    else " (per-step blocked sync: collective + in-step compute)"
                ),
            ),
        )
    shares = s.get("host_time_shares")
    if shares:
        rows.insert(
            3,
            (
                "host time shares",
                f"dispatch {100 * shares.get('dispatch', 0):.1f}% · "
                f"pad {100 * shares.get('pad', 0):.1f}% · "
                f"queue-wait {100 * shares.get('queue_wait', 0):.1f}% · "
                f"blocked-sync {100 * shares.get('blocked_sync', 0):.1f}%",
            ),
        )
        rows.insert(4, ("regime", shares.get("regime")))
    w = max(len(k) for k, _ in rows)
    for k, v in rows:
        lines.append(f"  {k:<{w}}  {_fmt(v)}")
    recent = doc.get("recent_steps", [])[-steps:]
    if recent:
        lines.append(f"── last {len(recent)} steps " + "─" * 44)
        lines.append("  step  bucket  valid  coal  queue  ingest_us    pad_us   wait_us   sync_us")
        for r in recent:
            def _us(key):
                return f"{r[key]:>8.1f}" if key in r else "       -"

            lines.append(
                f"  {r.get('step', 0):>4}  {r.get('bucket', 0):>6}  {r.get('valid', 0):>5}"
                f"  {r.get('coalesced', 1):>4}  {r.get('queue_depth', 0):>5}"
                f"  {r.get('ingest_us', 0):>9.1f}  {_us('pad_us')}  {_us('queue_wait_us')}  {_us('sync_us')}"
            )
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("telemetry_json")
    ap.add_argument("--steps", type=int, default=10, help="step records to show")
    args = ap.parse_args()
    with open(args.telemetry_json) as f:
        doc = json.load(f)
    print(render(doc, steps=args.steps))
    return 0


if __name__ == "__main__":
    sys.exit(main())
