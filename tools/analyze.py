#!/usr/bin/env python
"""Static-analysis CI gate: ``python tools/analyze.py`` (== ``make analyze``).

Runs all three planes of ``metrics_tpu/analysis`` and exits nonzero on any
finding not covered by the committed baseline:

* **program plane** — the bootstrap engine matrix ({step, deferred} x
  {arena, per-leaf} x {single, multistream} x kernel backends
  {xla, pallas_interpret}) is built, driven, and audited by
  ``EngineAnalysis.check``: collective placement per sync mode, scatter-free
  Pallas lowerings, donation aliasing, arena fusion, host-constant
  fingerprint coverage, compile caps;
* **source plane** — the AST trace-hazard lint over ``metrics_tpu/``;
* **concurrency plane** — the per-class lock declarations
  (``analysis/rules/locks.py``) checked package-wide: lockset, lock-order
  (cycles + forbidden nestings), no-dispatch-under-lock, check-then-act.

Options:
    --plane {all,program,source,concurrency}   which plane(s) to run (default all)
    --json PATH                    also write the full report as JSON
    --baseline PATH                baseline file (default tools/analysis_baseline.json)
    --write-baseline               rewrite the baseline from current findings
                                   (each entry gets a TODO reason you must fill
                                   in — unexplained entries fail the gate)

Suppress a single source/concurrency-plane occurrence inline instead of
baselining: ``# analysis: disable=rule-id -- reason``. Rule catalog:
docs/analysis.md.
"""
import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--plane", choices=("all", "program", "source", "concurrency"), default="all"
    )
    ap.add_argument("--json", dest="json_path", default=None)
    ap.add_argument(
        "--baseline", default=os.path.join(_REPO, "tools", "analysis_baseline.json")
    )
    ap.add_argument("--write-baseline", action="store_true")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from metrics_tpu.analysis import (
        Baseline,
        check_concurrency_tree,
        check_source_tree,
    )
    from metrics_tpu.analysis.bootstrap import analyze_bootstrap_matrix
    from metrics_tpu.analysis.core import Report

    pkg = os.path.join(_REPO, "metrics_tpu")
    report = Report()
    timings = {}
    if args.plane in ("all", "source"):
        t0 = time.perf_counter()
        report.merge(check_source_tree(pkg))
        timings["source"] = time.perf_counter() - t0
    if args.plane in ("all", "concurrency"):
        t0 = time.perf_counter()
        report.merge(check_concurrency_tree(pkg))
        timings["concurrency"] = time.perf_counter() - t0
    if args.plane in ("all", "program"):
        t0 = time.perf_counter()
        report.merge(analyze_bootstrap_matrix())
        timings["program"] = time.perf_counter() - t0

    # the source plane's `lock-discipline` alias and the concurrency plane's
    # lockset rule share one implementation over the legacy state-lock
    # declarations — when both planes run, the same finding (identical key)
    # arrives twice; keep the first occurrence
    seen = set()
    deduped = []
    for f in report.findings:
        if f.key() not in seen:
            seen.add(f.key())
            deduped.append(f)
    report.findings = deduped

    baseline = Baseline.load(args.baseline)
    if args.write_baseline:
        baseline.entries = {
            f.key(): baseline.entries.get(f.key(), "TODO: explain why this is baselined")
            for f in report.findings
        }
        baseline.save(args.baseline)
        print(f"baseline rewritten: {len(baseline.entries)} entries -> {args.baseline}")

    new, old = baseline.filter(report.findings)
    unexplained = baseline.unexplained()

    if args.json_path:
        payload = report.to_json()
        payload["baselined"] = [f.key() for f in old]
        payload["new"] = [f.key() for f in new]
        payload["unexplained_baseline_entries"] = unexplained
        payload["plane_seconds"] = {k: round(v, 3) for k, v in timings.items()}
        # the concurrency block tools/engine_report.py reads: which engine
        # modules the lock-contract audit covered, and whether it came back
        # clean (zero findings across the four concurrency rules). Written
        # ONLY when the plane actually ran — a --plane source/program report
        # must not read as a clean audit that never executed
        if "concurrency" in timings:
            from metrics_tpu.analysis import CONCURRENCY_SPECS

            conc_rules = (
                "concurrency-lockset", "concurrency-lock-order",
                "concurrency-dispatch-under-lock", "concurrency-check-then-act",
                "concurrency-decl-unresolved", "lock-discipline",
            )
            conc_findings = [f.key() for f in report.findings if f.rule in conc_rules]
            payload["concurrency"] = {
                "audited_modules": sorted(CONCURRENCY_SPECS),
                "findings": conc_findings,
                "clean": not conc_findings,
            }
        os.makedirs(os.path.dirname(os.path.abspath(args.json_path)), exist_ok=True)
        with open(args.json_path, "w") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")

    for f in new:
        print(f.render())
    for n in report.notes:
        print(f"note: {n}")
    if old:
        print(f"baselined: {len(old)} finding(s) carried as explained debt")
    for k in unexplained:
        print(f"ERROR   baseline entry without a reason: {k}")

    ok = not new and not unexplained
    planes = args.plane if args.plane != "all" else "source+concurrency+program"
    timing_str = " ".join(f"{k}={v:.1f}s" for k, v in timings.items())
    print(f"plane timings: {timing_str}")
    print(
        f"analyze {'PASS' if ok else 'FAIL'}: planes={planes}, "
        f"findings={len(report.findings)} (new={len(new)}, baselined={len(old)}), "
        f"unexplained-baseline={len(unexplained)}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
