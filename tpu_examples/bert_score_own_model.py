"""BERTScore with a user-provided encoder.

Analogue of reference ``tm_examples/bert_score-own_model.py``: that example shows
BERTScore with a custom model + tokenizer; here the encoder is any callable
``(input_ids, attention_mask) -> (N, L, D)`` — a local HF Flax checkpoint, your own
flax module, or (below) a toy hash-embedding for demonstration.
"""
import os
import sys

# allow running as `python tpu_examples/<name>.py` from the repo root checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from typing import Dict

import jax.numpy as jnp
import numpy as np

from metrics_tpu import BERTScore

_MAX_LEN = 32
_DIM = 16


def own_tokenizer(sentences, max_length: int) -> Dict[str, np.ndarray]:
    """Whitespace tokenizer with a stable hash vocab (stands in for a BPE/WordPiece)."""
    ids = np.zeros((len(sentences), max_length), dtype=np.int32)
    mask = np.zeros((len(sentences), max_length), dtype=np.int32)
    for i, s in enumerate(sentences):
        toks = s.lower().split()[:max_length]
        for j, t in enumerate(toks):
            ids[i, j] = (hash(t) % 20000) + 1
        mask[i, : len(toks)] = 1
    return {"input_ids": ids, "attention_mask": mask}


def own_model(input_ids, attention_mask):
    """Deterministic pseudo-embeddings (replace with your flax encoder's apply)."""
    base = (input_ids[..., None] * jnp.arange(1, _DIM + 1)) % 211
    return jnp.sin(base.astype(jnp.float32))


def main() -> None:
    preds = ["hello there general kenobi", "the cat sat on the mat"]
    refs = ["hello there", "a cat sat on the mat"]

    metric = BERTScore(user_forward_fn=own_model, user_tokenizer=own_tokenizer, idf=True, max_length=_MAX_LEN)
    metric.update(preds, refs)
    print(metric.compute())


if __name__ == "__main__":
    main()
