"""Flagship integration example: metrics inside a data-parallel jitted train loop.

The analogue of the reference's Lightning integration
(``integrations/test_lightning.py``): metrics accumulate inside the compiled step
and sync with ONE fused collective bundle over the mesh — no eager hops, no per-metric
all_gathers.

Run (any host):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tpu_examples/data_parallel_metrics.py
"""
import os
import sys

# allow running as `python tpu_examples/<name>.py` from the repo root checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Accuracy, BinnedAveragePrecision, F1Score, MetricCollection

NUM_CLASSES = 10
BATCH = 64
STEPS = 20


def main() -> None:
    devices = np.asarray(jax.devices())
    mesh = Mesh(devices, ("dp",))
    print(f"mesh: {mesh}")

    metrics = MetricCollection(
        {
            "acc": Accuracy(),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "ap": BinnedAveragePrecision(num_classes=NUM_CLASSES, thresholds=50),
        }
    )

    # a toy "model": logits = W x, trained by SGD on random data
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(32, NUM_CLASSES).astype(np.float32) * 0.1)

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P("dp"), P("dp"), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    def train_step(w, x, y, metric_state):
        logits = x @ w
        probs = jax.nn.softmax(logits, axis=-1)
        onehot = jax.nn.one_hot(y, NUM_CLASSES)
        loss = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))
        grad = x.T @ (probs - onehot) / x.shape[0]
        # gradient + loss sync ride the same program as the metric updates
        grad = jax.lax.pmean(grad, "dp")
        loss = jax.lax.pmean(loss, "dp")
        metric_state = metrics.update_state(metric_state, probs, y)
        return w - 0.1 * grad, loss, metric_state

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False)
    def metrics_epoch_end(metric_state):
        # ONE fused psum bundle for every counter state of every metric
        return metrics.compute_synced(metric_state, "dp")

    state = metrics.init_state()
    for step in range(STEPS):
        x = jnp.asarray(np.random.RandomState(step).randn(BATCH, 32).astype(np.float32))
        y = jnp.asarray(np.random.RandomState(1000 + step).randint(0, NUM_CLASSES, BATCH))
        w, loss, state = train_step(w, x, y, state)

    values = metrics_epoch_end(state)
    for k, v in values.items():
        if isinstance(v, list):  # per-class outputs (e.g. binned AP)
            print(k, [round(float(np.asarray(x)), 4) for x in v])
        else:
            print(k, round(float(np.asarray(v)), 4))


if __name__ == "__main__":
    main()
