"""Detection mAP example. Analogue of reference ``tm_examples/detection_map.py``."""
import os
import sys

# allow running as `python tpu_examples/<name>.py` from the repo root checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from metrics_tpu import MAP


def main() -> None:
    preds = [
        dict(
            boxes=np.asarray([[258.0, 41.0, 606.0, 285.0]], dtype=np.float32),
            scores=np.asarray([0.536], dtype=np.float32),
            labels=np.asarray([0]),
        )
    ]
    target = [
        dict(
            boxes=np.asarray([[214.0, 41.0, 562.0, 285.0]], dtype=np.float32),
            labels=np.asarray([0]),
        )
    ]

    metric = MAP()
    metric.update(preds, target)
    result = metric.compute()
    for k, v in result.items():
        print(f"{k}: {np.asarray(v)}")


if __name__ == "__main__":
    main()
