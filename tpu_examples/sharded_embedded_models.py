"""Sharded embedded models: FID and BERTScore with the encoder over a mesh.

The BASELINE configs that matter at scale — "FID (InceptionV3 forward on TPU,
feature all_gather)" and "BERTScore with sharded embedding" (reference runs
these as a per-process model + NCCL feature gather,
``torchmetrics/image/fid.py:250-262`` / ``functional/text/bert.py:256-341``).
Here the model forward is ONE ``shard_map`` over the mesh's data axis: params
replicated, batch sharded, features all-gathered in-graph
(``metrics_tpu/parallel/embedded.py``). This example demonstrates both paths
on whatever devices are available (the 8-device virtual CPU mesh in tests),
and asserts sharded == single-device values — the invariant
``tests/parallel/test_sharded_embedded.py`` pins in CI.
"""
import os
import sys

# allow running as `python tpu_examples/<name>.py` from the repo root checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# honor JAX_PLATFORMS even on hosts whose sitecustomize force-registers a TPU
# plugin (env alone loses there) — the documented virtual-mesh invocation is
# XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from metrics_tpu import FrechetInceptionDistance
from metrics_tpu.functional import bert_score
from metrics_tpu.models.inception import InceptionFeatureExtractor

IMG = 75  # smallest size the InceptionV3 stride/pool stack accepts; use 299 for real FID


def sharded_fid(mesh: Mesh) -> None:
    # one shared random-init param set so sharded == single-device is checkable;
    # pass params=<converted torch-fidelity weights> for real FID values
    # the 768-d tap keeps this demo light on a virtual CPU mesh; production
    # runs use feature=2048 (or simply FrechetInceptionDistance(feature=2048, mesh=mesh))
    plain_ext = InceptionFeatureExtractor(feature="768", input_size=IMG)
    sharded_ext = InceptionFeatureExtractor(
        feature="768", params=plain_ext.params, input_size=IMG, mesh=mesh
    )
    fid_sharded = FrechetInceptionDistance(feature=sharded_ext, feature_dim=768)
    fid_single = FrechetInceptionDistance(feature=plain_ext, feature_dim=768)

    rng = np.random.RandomState(0)
    real = jnp.asarray((rng.rand(8, IMG, IMG, 3) * 255).astype(np.uint8))
    fake = jnp.asarray((rng.rand(8, IMG, IMG, 3) * 255).astype(np.uint8))
    for fid in (fid_sharded, fid_single):
        fid.update(real, real=True)   # inception fwd runs batch-parallel
        fid.update(fake, real=False)
    a, b = float(fid_sharded.compute()), float(fid_single.compute())
    assert abs(a - b) <= max(1e-4 * abs(b), 1e-4), (a, b)
    print(f"FID sharded over {mesh.devices.size} devices: {a:.4f} (single-device: {b:.4f})")


def sharded_bertscore(mesh: Mesh) -> None:
    # any encoder callable; real runs pass model_name_or_path=<local flax ckpt>
    # (its params ride as runtime args, replicated over the mesh)
    def encoder(ids, mask):
        emb = jnp.sin(ids[..., None].astype(jnp.float32) * jnp.arange(1.0, 17.0) / 7.0)
        return emb * mask[..., None].astype(jnp.float32)

    preds = [f"the cat tok{i} sat on the mat" for i in range(32)]
    refs = [f"a dog tok{i + 1} ran in the park" for i in range(32)]
    base = bert_score(preds, refs, user_forward_fn=encoder, max_length=16)
    shard = bert_score(preds, refs, user_forward_fn=encoder, max_length=16, mesh=mesh)
    np.testing.assert_allclose(shard["f1"], base["f1"], rtol=1e-5, atol=1e-6)
    print(f"BERTScore sharded over {mesh.devices.size} devices: "
          f"mean F1 {float(np.mean(shard['f1'])):.4f} (matches single-device)")


def main() -> None:
    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    sharded_bertscore(mesh)
    sharded_fid(mesh)


if __name__ == "__main__":
    main()
