"""Serving example: metrics as a streaming service on a device mesh.

The reference's contract is library-shaped — your loop calls ``update()``
synchronously and every new batch shape re-traces. This example runs the
engine's serving contract instead (docs/serving.md): ragged traffic flows into
a bounded queue, batches round to a CLOSED set of padded bucket shapes, each
bucket's update step is AOT-compiled once (with the state donated and, on a
mesh, batch rows sharded + deltas psum-merged in-step), periodic crash-safe
snapshots land on disk, and telemetry comes out as JSON. The last leg tours
DEFERRED mesh sync (``mesh_sync="deferred"``): shard-local states, a
collective-free steady step, and the merge riding one fused bundle at
``result()`` — which is what lets ``AUROC(capacity=N)``, refused by the
step-sync mesh path, serve on the mesh at all.

Run (any host):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tpu_examples/streaming_engine.py
"""
import os
import sys
import tempfile

# allow running as `python tpu_examples/<name>.py` from the repo root checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
from jax.sharding import Mesh

from metrics_tpu import Accuracy, F1Score, MeanSquaredError, MetricCollection
from metrics_tpu.engine import EngineConfig, MultiStreamEngine, StreamingEngine

BUCKETS = (64, 256)
N_BATCHES = 40
N_STREAMS = 4


def main() -> None:
    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    print(f"mesh: {mesh}")

    rng = np.random.RandomState(0)
    sizes = rng.randint(8, 257, size=N_BATCHES)
    # dyadic-rational preds (multiples of 1/64): every squared error and sum is
    # exactly representable in f32, so the exact-parity assertions below hold
    # under ANY grouping — bucketing, megabatch coalescing, shard psum order
    # (same convention as tests/engine/)
    traffic = [
        ((rng.randint(0, 65, size=n) / 64.0).astype(np.float32), (rng.rand(n) > 0.5).astype(np.int32))
        for n in sizes
    ]

    metrics = MetricCollection({"acc": Accuracy(), "f1": F1Score(), "mse": MeanSquaredError()})
    snapdir = tempfile.mkdtemp(prefix="engine_snaps_")
    engine = StreamingEngine(
        metrics,
        EngineConfig(
            buckets=BUCKETS, mesh=mesh, axis="dp",
            snapshot_every=10, snapshot_dir=snapdir,
        ),
    )

    with engine:
        for preds, target in traffic:           # ragged sizes, closed program set
            engine.submit(preds, target)        # blocks when the queue is full
        served = {k: float(v) for k, v in engine.result().items()}

    # the same traffic through the plain eager loop must agree exactly
    eager = MetricCollection({"acc": Accuracy(), "f1": F1Score(), "mse": MeanSquaredError()})
    for preds, target in traffic:
        eager.update(preds, target)
    reference = {k: float(v) for k, v in eager.compute().items()}

    tele = engine.telemetry()
    print(f"served  : {served}")
    print(f"eager   : {reference}")
    for k in served:
        assert served[k] == reference[k], (k, served[k], reference[k])
    assert tele["compile_cache"]["misses"] <= len(BUCKETS) + 1
    assert tele["snapshots"] == N_BATCHES // 10
    print(
        f"parity exact over {N_BATCHES} ragged batches ({tele['rows_in']} rows); "
        f"{tele['compile_cache']['misses']} compiled programs for {len(BUCKETS)} buckets, "
        f"padding waste {100 * tele['padding_waste_fraction']:.1f}%, "
        f"{tele['snapshots']} snapshots -> {snapdir}"
    )

    # ---- multi-stream serving: S independent accumulations, ONE executable
    # (single-device path; states stack on a stream axis, megabatch coalescing
    # merges queued batches ACROSS streams into shared steps — see
    # docs/serving.md "Multi-stream serving")
    ms = MultiStreamEngine(
        MetricCollection({"acc": Accuracy(), "f1": F1Score(), "mse": MeanSquaredError()}),
        num_streams=N_STREAMS,
        config=EngineConfig(buckets=BUCKETS, coalesce=8),
    )
    per_stream_eager = [
        MetricCollection({"acc": Accuracy(), "f1": F1Score(), "mse": MeanSquaredError()})
        for _ in range(N_STREAMS)
    ]
    with ms:
        for i, (preds, target) in enumerate(traffic):
            sid = i % N_STREAMS
            ms.submit(sid, preds, target)
            per_stream_eager[sid].update(preds, target)
        served_streams = {sid: {k: float(v) for k, v in r.items()} for sid, r in ms.results().items()}
    for sid in range(N_STREAMS):
        want = {k: float(v) for k, v in per_stream_eager[sid].compute().items()}
        assert served_streams[sid] == want, (sid, served_streams[sid], want)
    ms_tele = ms.telemetry()
    assert ms_tele["compile_cache"]["misses"] <= len(BUCKETS) + 1
    print(
        f"multi-stream: {N_STREAMS} streams exact in {ms_tele['steps']} device steps "
        f"for {ms_tele['batches_submitted']} submissions "
        f"({ms_tele['coalesce']['batches_per_step_mean']} batches/step coalesced), "
        f"{ms_tele['compile_cache']['misses']} compiled programs total"
    )

    # ---- deferred mesh sync: shard-local state, collective-free steady steps.
    # AUROC(capacity=N) keeps cat-written score buffers with no per-step delta
    # merge — the step-sync mesh path refuses it; under deferred sync each
    # shard folds its own rows and result()'s boundary merge all-gathers the
    # buffers (docs/serving.md "Mesh sync modes").
    from metrics_tpu import AUROC

    capacity = 8192
    deferred = StreamingEngine(
        AUROC(capacity=capacity),
        EngineConfig(buckets=BUCKETS, mesh=mesh, axis="dp", mesh_sync="deferred"),
    )
    with deferred:
        for preds, target in traffic:
            deferred.submit(preds, target)
        served_auroc = float(deferred.result())
    au_eager = AUROC(capacity=capacity)
    for preds, target in traffic:
        au_eager.update(preds, target)
    want_auroc = float(au_eager.compute())
    assert abs(served_auroc - want_auroc) < 1e-6, (served_auroc, want_auroc)
    d_tele = deferred.telemetry()
    assert d_tele["mesh_sync"]["mode"] == "deferred"
    print(
        f"deferred sync: AUROC(capacity={capacity}) on the mesh == eager "
        f"({served_auroc:.6f}); {d_tele['mesh_sync']['merges']} boundary merge(s), "
        f"collective share {d_tele['mesh_sync']['collective_share']}"
    )


if __name__ == "__main__":
    main()
