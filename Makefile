# Parity target: reference Makefile (test = pytest with coverage).
# Default flow runs the engine smoke check (seconds) before the full suite.
.PHONY: all test engine-smoke clean native bench

all: engine-smoke test

test:
	python -m pytest tests/ -q

# 1-device, tiny buckets: ragged-stream parity vs eager, compile budget, and
# warm-cache zero-compile assertion (metrics_tpu/engine/smoke.py). Telemetry
# lands in engine_telemetry.json; pretty-print: python tools/engine_report.py
engine-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.smoke engine_telemetry.json

native:
	g++ -O3 -shared -fPIC metrics_tpu/native/levenshtein.cpp -o metrics_tpu/native/_levenshtein.so

bench:
	python bench.py

clean:
	rm -rf .pytest_cache build dist *.egg-info
	find . -name __pycache__ -type d -exec rm -rf {} +
	rm -f metrics_tpu/native/_levenshtein.so engine_telemetry.json
