# Parity target: reference Makefile (test = pytest with coverage).
# Default flow runs the smoke checks (seconds) before the full suite.
# Sidecar artifacts (telemetry JSON, analysis reports) land under out/
# (gitignored) — never in the repo root.
.PHONY: all test engine-smoke kernels-smoke mesh-smoke streams-smoke chaos-smoke obs-smoke quant-smoke elastic-smoke windows-smoke fleet-smoke ragged-smoke model-smoke analyze clean native bench

all: engine-smoke kernels-smoke mesh-smoke streams-smoke chaos-smoke obs-smoke quant-smoke elastic-smoke windows-smoke fleet-smoke ragged-smoke model-smoke analyze test

test:
	python -m pytest tests/ -q

# 1-device, tiny buckets: ragged-stream parity vs eager, compile budget, and
# warm-cache zero-compile assertion (metrics_tpu/engine/smoke.py). Telemetry
# lands in out/engine_telemetry.json; pretty-print: python tools/engine_report.py
engine-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.smoke out/engine_telemetry.json

# Kernel-dispatcher gate, CPU-safe and tier-1-budget cheap: interpret-mode
# Pallas parity (fold/segment/histogram vs the XLA reference path) + backend
# dispatch sanity + cross-backend engine parity under one shared AotCache
# (metrics_tpu/ops/kernels/smoke.py). Compiled-TPU parity: tests marked
# requires_tpu (skipped cleanly off-TPU by the conftest guard).
kernels-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.ops.kernels.smoke

# Mesh-engine gate, CPU-safe (bootstraps an 8-device virtual CPU mesh when the
# host has fewer devices): step-sync AND deferred-sync parity vs eager,
# AUROC(capacity) on mesh under deferred sync == single device, compile caps,
# and the collective-placement contract — ZERO collectives in the deferred
# steady step's HLO, >=1 in the step-sync one (metrics_tpu/engine/mesh_smoke.py).
mesh-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.mesh_smoke

# Stream-sharding gate, CPU-safe (bootstraps the 8-device virtual mesh):
# S=64 Zipfian streams sharded over 8 shards behind a resident=2 paged arena
# (capacity 16 << S) must match an unsharded unpaged oracle bit-exactly, with
# zero steady compiles after warmup, ONE device computation per results(),
# collective-free routed-step HLO, and kill/resume past a spill with exact
# replay (metrics_tpu/engine/streams_smoke.py). Docs: docs/serving.md
# "Stream sharding & paging".
streams-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.streams_smoke

# Fault-tolerance gate, CPU-safe and seeded (metrics_tpu/engine/chaos_smoke.py):
# every injection point in engine/faults.py fires at least once — transactional
# rollback, quarantine ledger exactness, pallas→xla demotion, contained
# snapshot-write failure, corrupted-LATEST restore fallback with exact replay,
# deferred merge retry, dead-dispatcher submit(timeout=) — and the chaos run's
# result() is bit-identical to a fault-free run on the same traffic.
chaos-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.chaos_smoke out/chaos_telemetry.json

# Observability gate, CPU-safe (metrics_tpu/engine/obs_smoke.py): a traced
# coalescing run exports valid Perfetto trace-event JSON (every megabatch span
# links exactly the submit spans it absorbed) and a valid OpenMetrics
# exposition (histogram_accumulate-folded latency histograms, counts exact);
# the SAME seeded chaos plan runs twice and the canonical span sequences must
# be bit-identical (occurrence determinism); every fault site appears as a
# span event. Validators: tools/trace_export.py. Docs: docs/observability.md.
obs-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.obs_smoke out/trace_obs.json out/obs_metrics.txt

# Quantized-sync gate, CPU-safe (bootstraps the 8-device virtual mesh):
# block-scaled int8 sync on a float-heavy collection — >=3x sync payload
# reduction, quantized deferred engine within the per-metric bounded-error
# oracle (counts bit-exact), AOT keys distinct per sync_precision policy
# across one shared cache, zero steady compiles, policy audit clean, and
# kill/resume through a COMPRESSED snapshot (metrics_tpu/engine/
# quant_smoke.py). Docs: docs/distributed.md "Quantized sync".
quant-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.quant_smoke

# Overload/elasticity gate, CPU-safe (bootstraps the 8-device virtual mesh,
# metrics_tpu/engine/elastic_smoke.py): seeded Zipfian traffic with a mid-run
# HOT-SPOT SHIFT overloads a resident-capped stream-sharded engine — the
# overload detector trips on the spill rate, the degradation ladder walks
# widen-coalesce → defer-cold-reads → SHED (a shed-class submit raises the
# typed AdmissionRejected), an injected non-transient shard_loss auto-reshards
# world 4→2 in place (snapshot-through-the-restore-matrix), a manual
# reshard(world=4) grows back under traffic, the ladder de-escalates to level
# 0 with a spill-free tail, and every NON-shed stream's results() is
# bit-identical to a fault-free unsharded oracle. Docs: docs/serving.md
# "Overload & elasticity".
elastic-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.elastic_smoke

# Windowed-semantics gate, CPU-safe (bootstraps the 8-device virtual mesh,
# metrics_tpu/engine/windows_smoke.py): tumbling panes bit-exact vs a
# fresh-engine-per-pane oracle on a deferred mesh, sliding fold exact vs
# recompute, >=3 pane rotations with an AOT miss-counter delta of ZERO
# (rotation is a slot bump + cached init-fill, never a retrace), window x
# stream-shard parity through a real pane spill (Zipf streams, resident cap),
# kill/resume MID-RING with exact replay (pane cursor from snapshot
# provenance), and a seeded label-drift stream raising a deterministic drift
# alarm. Docs: docs/serving.md "Windowed metrics".
windows-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.windows_smoke

# Multi-host fleet gate (ISSUE 15, metrics_tpu/engine/fleet/harness.py):
# TWO real OS processes over jax.distributed with gloo CPU collectives —
# seeded Zipfian traffic split per host (sid % 2) serves bit-identical to a
# single-process oracle on BOTH hosts; same-seed double run bit-identical
# (per-stream results + per-host canonical span sequences); zero steady
# compiles after warmup; steady-step jaxpr/HLO collective-free via the
# analysis rules while the fleet boundary fold carries the cross-host
# collective; snapshot cuts ride the shared plan through the barrier
# protocol; kill host 1 mid-stream -> both hosts restore from the last
# CONSISTENT cut and replay to exact oracle parity. The tenancy phase
# (ISSUE 20) reruns the plan on STREAM-SHARDED hosts (3 resident slots vs 8
# home streams, Zipf traffic paging through host RAM) under a tumbling
# window rotating on the shared plan cursor at cut-aligned positions:
# bit-exact vs the windowed oracle through spills, zero steady compiles,
# leg-labeled (intra/cross) fold-payload + spill gauges exported, and a
# kill -> restore -> replay crossing a spill to exact parity. The parent
# bounds each round's wall time and kills any worker still alive when a
# round ends (orphan cleanup). Docs: docs/distributed.md "Multi-host
# serving" + "Fleet tenancy".
fleet-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.fleet.harness

# Ragged-serving gate (ISSUE 17), CPU-safe (bootstraps the 8-device virtual
# mesh, metrics_tpu/engine/ragged_smoke.py): RetrievalMAP group-keyed traffic
# through a deferred-mesh RaggedEngine bit-exact vs the eager oracle with
# ZERO steady compiles over reset+replay; detection MeanAveragePrecision
# served exact on every result key; kill/resume replay exact (and a
# non-ragged snapshot refused with the typed provenance message); windows +
# group_shard (the stream-shard pager at group grain) composition exact;
# plain-engine refusal typed; program audit clean. Docs: docs/serving.md
# "Ragged serving".
ragged-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.ragged_smoke

# Embedded-model serving gate (ISSUE 19), CPU-safe (bootstraps the 8-device
# virtual mesh, metrics_tpu/engine/model_smoke.py): single-device f32 host
# bit-exact vs the direct InceptionV3 forward; hybrid stem-tensor layout
# (128-lane tensor-parallel stem + data-parallel trunk, all_gather-only)
# float-parity vs single-device; pipeline-staged encoder (ppermute-only GPipe
# handoff) bit-exact vs sequential stages; FID+KID over the same weights
# share ONE resident model (params shared, not copied); zero steady compiles
# on warm replay; host-collectives-pinned audit clean; model_host_*
# OpenMetrics strict-parse; kill/resume with a host attached bit-identical.
# Docs: docs/serving.md "Embedded-model serving".
model-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.model_smoke out/model_telemetry.json

# Static-analysis gate, CPU-safe (metrics_tpu/analysis + tools/analyze.py):
# program plane audits the bootstrap engine matrix ({step,deferred} x
# {arena,per-leaf} x {single,multistream} x kernel backends xla+interpret) —
# collective placement, scatter-free Pallas lowerings, donation aliasing,
# arena fusion, host-constant fingerprint coverage, compile caps; source
# plane is the AST trace-hazard lint over metrics_tpu/. Exits nonzero on any
# finding not in tools/analysis_baseline.json. Rule catalog: docs/analysis.md.
analyze:
	JAX_PLATFORMS=cpu python tools/analyze.py --json out/analysis_report.json

native:
	g++ -O3 -shared -fPIC metrics_tpu/native/levenshtein.cpp -o metrics_tpu/native/_levenshtein.so

bench:
	python bench.py

clean:
	rm -rf .pytest_cache build dist *.egg-info out
	find . -name __pycache__ -type d -exec rm -rf {} +
	rm -f metrics_tpu/native/_levenshtein.so engine_telemetry.json chaos_telemetry.json
