# Parity target: reference Makefile (test = pytest with coverage).
# Default flow runs the smoke checks (seconds) before the full suite.
.PHONY: all test engine-smoke kernels-smoke mesh-smoke chaos-smoke clean native bench

all: engine-smoke kernels-smoke mesh-smoke chaos-smoke test

test:
	python -m pytest tests/ -q

# 1-device, tiny buckets: ragged-stream parity vs eager, compile budget, and
# warm-cache zero-compile assertion (metrics_tpu/engine/smoke.py). Telemetry
# lands in engine_telemetry.json; pretty-print: python tools/engine_report.py
engine-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.smoke engine_telemetry.json

# Kernel-dispatcher gate, CPU-safe and tier-1-budget cheap: interpret-mode
# Pallas parity (fold/segment/histogram vs the XLA reference path) + backend
# dispatch sanity + cross-backend engine parity under one shared AotCache
# (metrics_tpu/ops/kernels/smoke.py). Compiled-TPU parity: tests marked
# requires_tpu (skipped cleanly off-TPU by the conftest guard).
kernels-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.ops.kernels.smoke

# Mesh-engine gate, CPU-safe (bootstraps an 8-device virtual CPU mesh when the
# host has fewer devices): step-sync AND deferred-sync parity vs eager,
# AUROC(capacity) on mesh under deferred sync == single device, compile caps,
# and the collective-placement contract — ZERO collectives in the deferred
# steady step's HLO, >=1 in the step-sync one (metrics_tpu/engine/mesh_smoke.py).
mesh-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.mesh_smoke

# Fault-tolerance gate, CPU-safe and seeded (metrics_tpu/engine/chaos_smoke.py):
# every injection point in engine/faults.py fires at least once — transactional
# rollback, quarantine ledger exactness, pallas→xla demotion, contained
# snapshot-write failure, corrupted-LATEST restore fallback with exact replay,
# deferred merge retry, dead-dispatcher submit(timeout=) — and the chaos run's
# result() is bit-identical to a fault-free run on the same traffic.
chaos-smoke:
	JAX_PLATFORMS=cpu python -m metrics_tpu.engine.chaos_smoke chaos_telemetry.json

native:
	g++ -O3 -shared -fPIC metrics_tpu/native/levenshtein.cpp -o metrics_tpu/native/_levenshtein.so

bench:
	python bench.py

clean:
	rm -rf .pytest_cache build dist *.egg-info
	find . -name __pycache__ -type d -exec rm -rf {} +
	rm -f metrics_tpu/native/_levenshtein.so engine_telemetry.json chaos_telemetry.json
