# Parity target: reference Makefile (test = pytest with coverage).
.PHONY: test clean native bench

test:
	python -m pytest tests/ -q

native:
	g++ -O3 -shared -fPIC metrics_tpu/native/levenshtein.cpp -o metrics_tpu/native/_levenshtein.so

bench:
	python bench.py

clean:
	rm -rf .pytest_cache build dist *.egg-info
	find . -name __pycache__ -type d -exec rm -rf {} +
	rm -f metrics_tpu/native/_levenshtein.so
