"""Benchmark: fused MetricCollection update throughput on one chip.

Measures the headline north-star proxy (BASELINE.md): samples/sec/chip through a
``MetricCollection(Accuracy, F1, BinnedAveragePrecision)`` multiclass metric step —
the whole update path jit-compiled as ONE fused kernel with state carried on device.

``vs_baseline``: same collection, same data, through the reference implementation
(TorchMetrics v0.7 at /root/reference, torch CPU) — the reference has no TPU path, so
its CPU eager throughput IS its best number on this host. Ratio > 1 means faster.

Prints exactly one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import sys
import time

import numpy as np

NUM_CLASSES = 10
BATCH = 4096
WARMUP = 5
ITERS = 30


def _data():
    rng = np.random.RandomState(0)
    preds = rng.rand(BATCH, NUM_CLASSES).astype(np.float32)
    preds /= preds.sum(axis=1, keepdims=True)
    target = rng.randint(0, NUM_CLASSES, BATCH)
    return preds, target


def bench_tpu() -> float:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, BinnedAveragePrecision, F1Score, MetricCollection

    coll = MetricCollection(
        {
            "acc": Accuracy(),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "binned_ap": BinnedAveragePrecision(num_classes=NUM_CLASSES, thresholds=100),
        }
    )
    preds_np, target_np = _data()
    preds = jnp.asarray(preds_np)
    target = jnp.asarray(target_np)

    @jax.jit
    def step(state, p, t):
        return coll.update_state(state, p, t)

    state = coll.init_state()
    for _ in range(WARMUP):
        state = step(state, preds, target)
    jax.block_until_ready(jax.tree.leaves(state))

    state = coll.init_state()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        state = step(state, preds, target)
    jax.block_until_ready(jax.tree.leaves(state))
    dt = time.perf_counter() - t0
    # sanity: values are finite
    vals = coll.compute_from(state)
    assert np.isfinite(float(vals["acc"]))
    return ITERS * BATCH / dt


def bench_reference() -> float:
    try:
        sys.path.insert(0, "/root/reference")
        # the reference imports pkg_resources (removed in py3.12 setuptools); shim it
        if "pkg_resources" not in sys.modules:
            import types

            shim = types.ModuleType("pkg_resources")

            class DistributionNotFound(Exception):
                pass

            def get_distribution(name):
                raise DistributionNotFound(name)

            shim.DistributionNotFound = DistributionNotFound
            shim.get_distribution = get_distribution
            sys.modules["pkg_resources"] = shim
        import torch

        from torchmetrics import Accuracy as TAccuracy, F1Score as TF1, MetricCollection as TColl
        from torchmetrics import BinnedAveragePrecision as TBAP

        torch.set_num_threads(max(1, torch.get_num_threads()))
        coll = TColl(
            {
                "acc": TAccuracy(),
                "f1": TF1(num_classes=NUM_CLASSES, average="macro"),
                "binned_ap": TBAP(num_classes=NUM_CLASSES, thresholds=100),
            }
        )
        preds_np, target_np = _data()
        preds = torch.from_numpy(preds_np)
        target = torch.from_numpy(target_np)

        for _ in range(WARMUP):
            coll.update(preds, target)
        for m in coll.values():
            m.reset()
        t0 = time.perf_counter()
        for _ in range(ITERS):
            coll.update(preds, target)
        dt = time.perf_counter() - t0
        return ITERS * BATCH / dt
    except Exception:
        return float("nan")
    finally:
        if "/root/reference" in sys.path:
            sys.path.remove("/root/reference")


def main() -> None:
    tpu_throughput = bench_tpu()
    ref_throughput = bench_reference()
    vs = tpu_throughput / ref_throughput if np.isfinite(ref_throughput) and ref_throughput > 0 else None
    print(
        json.dumps(
            {
                "metric": "fused_collection_update_throughput",
                "value": round(tpu_throughput, 1),
                "unit": "samples/s/chip",
                "vs_baseline": round(vs, 3) if vs is not None else None,
            }
        )
    )


if __name__ == "__main__":
    main()
