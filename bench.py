"""Benchmark: the BASELINE.md north-star configs.

Primary line metric: fused MetricCollection update throughput (samples/s/chip),
``vs_baseline`` = ratio over the reference (TorchMetrics v0.7 at /root/reference,
torch CPU — the reference has no TPU path, so its CPU eager throughput IS its best
number on this host).

``extras`` carries the remaining north-star configs (VERDICT r1 next #2):
  * ``sync_latency_us``     — per-sync latency of a MetricCollection(Accuracy, F1,
    BinnedAveragePrecision) state sync on an 8-device mesh, fused collective
    bundle vs naive per-state collectives (vs_baseline = naive/fused speedup);
    measured in a subprocess on the virtual 8-device CPU mesh (the same topology
    the driver's multichip dryrun checks).
  * ``detection_map``       — MAP update+compute throughput (imgs/s), device
    greedy matching vs the reference's python loops (torch CPU, torchvision box
    ops shimmed).
  * ``bertscore``           — BERTScore throughput (pairs/s) with a local tiny
    BERT, flax encoder vs the reference HF-torch pipeline.
  * ``fid_update``          — FID inception-forward update throughput (imgs/s)
    on this chip with DEVICE-RESIDENT inputs (host->device transfer excluded —
    over the tunnelled TPU, re-shipping the batch each call measures the ~130ms
    RTT, not the chip), plus ``achieved_tflops``/``mfu``: the FLOP count comes
    from XLA's own cost analysis of the compiled inception forward (fallback:
    the analytic ~5.7 GMACs = 11.4 GFLOPs/img for InceptionV3 at 299x299), and
    peak FLOP/s from the device-kind table in ``_PEAK_FLOPS`` (bf16 peaks; the
    forward runs f32 so MFU-vs-bf16-peak is conservative). No baseline: the
    reference needs torch-fidelity, absent here.
  * ``bertscore`` carries the same ``achieved_tflops``/``mfu`` fields for its
    flax encoder forward.

Prints exactly ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extras"}.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

NUM_CLASSES = 10
# throughput config: batch large enough to saturate the chip — per-call cost on
# the tunnelled TPU is one dispatch round-trip + compute, so small batches
# measure launch latency, not update throughput (the same batch feeds the
# torch-CPU reference baseline)
BATCH = 65536
WARMUP = 5
ITERS = 30


from tests.helpers.reference_shims import (  # noqa: E402
    shim_pkg_resources as _shim_pkg_resources,
    shim_torchvision as _shim_torchvision,
)


def _with_reference(fn):
    """Run fn() with /root/reference importable; returns NaN on any failure.

    Both shims go in BEFORE the first ``torchmetrics`` import: the reference
    probes ``_TORCHVISION_AVAILABLE`` once at import time, so installing the
    torchvision shim later (as bench_map used to) leaves the flag False and
    the detection baseline dead.
    """
    try:
        _shim_pkg_resources()
        _shim_torchvision()
        sys.path.insert(0, "/root/reference")
        return fn()
    except Exception:
        return float("nan")
    finally:
        if "/root/reference" in sys.path:
            sys.path.remove("/root/reference")


def _data():
    rng = np.random.RandomState(0)
    preds = rng.rand(BATCH, NUM_CLASSES).astype(np.float32)
    preds /= preds.sum(axis=1, keepdims=True)
    target = rng.randint(0, NUM_CLASSES, BATCH)
    return preds, target


# ------------------------------------------------- config 1: fused update throughput

def bench_tpu() -> float:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, BinnedAveragePrecision, F1Score, MetricCollection

    coll = MetricCollection(
        {
            "acc": Accuracy(),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "binned_ap": BinnedAveragePrecision(num_classes=NUM_CLASSES, thresholds=100),
        }
    )
    preds_np, target_np = _data()
    preds = jnp.asarray(preds_np)
    target = jnp.asarray(target_np)

    @jax.jit
    def step(state, p, t):
        return coll.update_state(state, p, t)

    state = coll.init_state()
    for _ in range(WARMUP):
        state = step(state, preds, target)
    jax.block_until_ready(jax.tree.leaves(state))

    state = coll.init_state()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        state = step(state, preds, target)
    jax.block_until_ready(jax.tree.leaves(state))
    dt = time.perf_counter() - t0
    vals = coll.compute_from(state)
    assert np.isfinite(float(vals["acc"]))
    return ITERS * BATCH / dt


def bench_reference() -> float:
    def run():
        import torch

        from torchmetrics import Accuracy as TAccuracy, F1Score as TF1, MetricCollection as TColl
        from torchmetrics import BinnedAveragePrecision as TBAP

        coll = TColl(
            {
                "acc": TAccuracy(),
                "f1": TF1(num_classes=NUM_CLASSES, average="macro"),
                "binned_ap": TBAP(num_classes=NUM_CLASSES, thresholds=100),
            }
        )
        preds_np, target_np = _data()
        preds = torch.from_numpy(preds_np)
        target = torch.from_numpy(target_np)
        for _ in range(WARMUP):
            coll.update(preds, target)
        for m in coll.values():
            m.reset()
        t0 = time.perf_counter()
        for _ in range(ITERS):
            coll.update(preds, target)
        return ITERS * BATCH / (time.perf_counter() - t0)

    return _with_reference(run)


# ------------------------------------------------------- config 2: mesh sync latency

_SYNC_BENCH_CODE = r"""
import json, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from functools import partial
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import AUROC, Accuracy, BinnedAveragePrecision, F1Score, MetricCollection
from metrics_tpu.parallel.collectives import sync_axis_state

NUM_CLASSES = 10
# counters (psum bundle) + a static-capacity exact-curve metric (all_gather
# bundle) — the representative mixed-state collection. The device-count
# scaling runs (SYNC_BENCH_NO_GATHER) drop the gather metric: its payload is
# O(devices) by definition (every shard's buffer must travel), which would
# swamp the latency-scaling signal the 8->256 axis measures.
import os as _os
metrics = {
    "acc": Accuracy(),
    "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
    "binned_ap": BinnedAveragePrecision(num_classes=NUM_CLASSES, thresholds=100),
}
if _os.environ.get("SYNC_BENCH_NO_GATHER") != "1":
    metrics["auroc"] = AUROC(num_classes=NUM_CLASSES, capacity=256)
coll = MetricCollection(metrics)
rng = np.random.RandomState(0)
preds = jnp.asarray(rng.rand(1024, NUM_CLASSES).astype(np.float32))
target = jnp.asarray(rng.randint(0, NUM_CLASSES, 1024))
mesh = Mesh(np.asarray(jax.devices()), ("dp",))

def make(mode):
    # mode: "fused" | "naive" | "nosync" — nosync is the identical step minus
    # the sync, so (mode - nosync) isolates the sync cost from the update
    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(), check_vma=False)
    def step(p, t):
        state = coll.update_state(coll.init_state(), p, t)
        if mode == "fused":
            synced = coll.sync_states(state, "dp")
        elif mode == "naive":
            # one collective per state leaf (the reference's O(K*S) pattern)
            synced = {
                name: {
                    k: sync_axis_state(m._reductions[k], st[k], "dp")
                    for k in st
                }
                for (name, m), st in zip(coll.items(keep_base=True), state.values())
            }
        else:
            synced = state
        leaves = jax.tree.leaves(synced)
        return sum(jnp.sum(l) for l in leaves)

    return step

import re as _re
out = {}
fused_only = _os.environ.get("SYNC_BENCH_FUSED_ONLY") == "1"
modes = ("fused",) if fused_only else ("fused", "naive", "nosync")
steps = {m: make(m) for m in modes}
for step in steps.values():
    for _ in range(3):
        step(preds, target).block_until_ready()

def time_once(step, n):
    t0 = time.perf_counter()
    for _ in range(n):
        step(preds, target).block_until_ready()
    return (time.perf_counter() - t0) / n * 1e6

n = 20 if fused_only else 60
# interleave repeats so drift hits all modes equally; keep the per-mode median
import statistics
samples = {m: [] for m in modes}
for _ in range(1 if fused_only else 5):
    for m in modes:
        samples[m].append(time_once(steps[m], n))
for m in modes:
    out[{"fused": "fused_us", "naive": "naive_us", "nosync": "nosync_us"}[m]] = statistics.median(samples[m])
if not fused_only:
    out["fused_sync_only_us"] = max(out["fused_us"] - out["nosync_us"], 0.0)
    out["naive_sync_only_us"] = max(out["naive_us"] - out["nosync_us"], 0.0)

    # the north-star evidence: collectives in the COMPILED fused step, and the
    # payload bytes one sync moves per device
    hlo = steps["fused"].lower(preds, target).compile().as_text()
    out["collectives_per_sync"] = {
        "all_reduce": len(_re.findall(r"\ball-reduce(?:-start)?\(", hlo)),
        "all_gather": len(_re.findall(r"\ball-gather(?:-start)?\(", hlo)),
    }
    hlo_naive = steps["naive"].lower(preds, target).compile().as_text()
    out["collectives_per_sync_naive"] = {
        "all_reduce": len(_re.findall(r"\ball-reduce(?:-start)?\(", hlo_naive)),
        "all_gather": len(_re.findall(r"\ball-gather(?:-start)?\(", hlo_naive)),
    }
    state = coll.update_state(coll.init_state(), preds[:8], target[:8])
    out["sync_payload_bytes"] = int(sum(
        np.asarray(l).size * np.asarray(l).dtype.itemsize for l in jax.tree.leaves(state)
    ))
print(json.dumps(out))
"""


def _run_sync_bench(n_devices: int, fused_only: bool) -> dict:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={n_devices}"
    )
    env["JAX_PLATFORMS"] = "cpu"
    if fused_only:
        env["SYNC_BENCH_FUSED_ONLY"] = "1"
        env["SYNC_BENCH_NO_GATHER"] = "1"  # scaling axis: counter latency only
    else:
        env.pop("SYNC_BENCH_FUSED_ONLY", None)  # don't inherit a stale export
        env.pop("SYNC_BENCH_NO_GATHER", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SYNC_BENCH_CODE],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": f"sync bench timed out at {n_devices} devices"}
    if proc.returncode != 0:
        return {"error": proc.stderr[-500:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_sync_latency() -> dict:
    """Fused-vs-naive on the 8-device mesh + fused-latency scaling to 256
    virtual devices (the BASELINE.md 8->256-chip axis; virtual CPU devices
    timeshare the host, so the large-mesh numbers are upper bounds)."""
    out = _run_sync_bench(8, fused_only=False)
    if "fused_us" not in out:
        return out  # base run failed; don't burn time on the scaling extras
    scaling = {"8": round(out["fused_us"], 1)}
    for n in (64, 256):
        r = _run_sync_bench(n, fused_only=True)
        if "fused_us" in r:
            scaling[str(n)] = round(r["fused_us"], 1)
    out["fused_scaling_us_by_devices"] = scaling
    try:
        out["chip_bundle_overhead_us"] = round(_bench_chip_sync_overhead(), 1)
    except Exception as e:
        out["chip_bundle_overhead_us"] = {"error": str(e)[:200]}
    return out


def _bench_chip_sync_overhead() -> float:
    """The non-collective cost of one fused sync on the REAL chip: pack
    (concat), degenerate 1-device collective, unpack (slice/reshape), jitted.

    This anchors the latency model in docs/distributed.md: total sync time =
    this overhead + one all-reduce of the payload over ICI; one chip cannot
    run a real multi-chip collective, but it can prove the bundle itself adds
    only microseconds on top of the wire time.
    """
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import Accuracy, BinnedAveragePrecision, F1Score, MetricCollection

    coll = MetricCollection({
        "acc": Accuracy(),
        "f1": F1Score(num_classes=10, average="macro"),
        "binned_ap": BinnedAveragePrecision(num_classes=10, thresholds=100),
    })
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(), check_vma=False)
    def step(p, t):
        state = coll.update_state(coll.init_state(), p, t)
        synced = coll.sync_states(state, "dp")
        return sum(jnp.sum(l) for l in jax.tree.leaves(synced))

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(), check_vma=False)
    def step_nosync(p, t):
        state = coll.update_state(coll.init_state(), p, t)
        return sum(jnp.sum(l) for l in jax.tree.leaves(state))

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(1024, 10).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 10, 1024))
    for f in (step, step_nosync):
        for _ in range(3):
            f(preds, target).block_until_ready()
    times = {}
    for name, f in (("sync", step), ("nosync", step_nosync)):
        t0 = time.perf_counter()
        for _ in range(30):
            f(preds, target).block_until_ready()
        times[name] = (time.perf_counter() - t0) / 30 * 1e6
    return max(times["sync"] - times["nosync"], 0.0)


# -------------------------------------------------------------- config 3: detection

def _map_scenes(n_imgs=64, seed=0):
    """COCO-like random scenes (up to ~25 dets/img, 5 classes)."""
    rng = np.random.RandomState(seed)
    scenes = []
    for _ in range(n_imgs):
        n_pred, n_gt = rng.randint(8, 26), rng.randint(4, 16)
        def boxes(n):
            xy = rng.rand(n, 2).astype(np.float32) * 80
            wh = rng.rand(n, 2).astype(np.float32) * 60 + 5
            return np.concatenate([xy, xy + wh], axis=1)
        scenes.append((
            dict(boxes=boxes(n_pred), scores=rng.rand(n_pred).astype(np.float32),
                 labels=rng.randint(0, 5, n_pred)),
            dict(boxes=boxes(n_gt), labels=rng.randint(0, 5, n_gt)),
        ))
    return scenes


def bench_map() -> dict:
    from metrics_tpu import MAP

    scenes = _map_scenes()

    def run_ours():
        m = MAP()  # device matching
        for pred, tgt in scenes:
            m.update([pred], [tgt])
        r = m.compute()
        assert np.isfinite(float(r["map"]))

    run_ours()  # warmup/compile
    t0 = time.perf_counter()
    n = 3
    for _ in range(n):
        run_ours()
    ours = n * len(scenes) / (time.perf_counter() - t0)

    def run_ref():
        _shim_torchvision()
        import torch

        from torchmetrics.detection.map import MAP as TMAP

        def one():
            m = TMAP()
            for pred, tgt in scenes:
                m.update(
                    [{k: torch.from_numpy(np.asarray(v)) for k, v in pred.items()}],
                    [{k: torch.from_numpy(np.asarray(v)) for k, v in tgt.items()}],
                )
            m.compute()

        one()
        t0 = time.perf_counter()
        for _ in range(n):
            one()
        return n * len(scenes) / (time.perf_counter() - t0)

    ref = _with_reference(run_ref)
    return {
        "value": round(ours, 2),
        "unit": "imgs/s",
        "vs_baseline": round(ours / ref, 3) if np.isfinite(ref) and ref > 0 else None,
    }


# -------------------------------------------------------------- config 4: BERTScore

def _tiny_bert(tmp):
    import torch
    from transformers import BertConfig, BertModel, BertTokenizerFast

    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + [f"tok{i}" for i in range(60)] + [
        "the", "cat", "sat", "on", "mat", "a", "dog", "ran", "in", "park",
    ]
    vf = os.path.join(tmp, "vocab.txt")
    with open(vf, "w") as f:
        f.write("\n".join(vocab))
    cfg = BertConfig(vocab_size=len(vocab), hidden_size=128, num_hidden_layers=4,
                     num_attention_heads=4, intermediate_size=256, max_position_embeddings=64)
    torch.manual_seed(0)
    pt_dir = os.path.join(tmp, "pt")
    BertModel(cfg).eval().save_pretrained(pt_dir)
    BertTokenizerFast(vocab_file=vf).save_pretrained(pt_dir)
    return pt_dir


def bench_bertscore() -> dict:
    import tempfile

    from transformers import BertTokenizerFast

    # corpus-scale throughput: per-call cost on the tunnelled TPU is ONE
    # blocking round-trip (~130ms) + compute, so small corpora measure tunnel
    # latency, not throughput. 2048 pairs with 256 distinct sentences per side
    # (8 copies each — the shared-reference shape of real MT eval, which the
    # pipeline's dedup encoding exploits; the reference gets the same corpus).
    # Distinguishing words come from the tiny vocab's tokN entries so the
    # sentences stay DISTINCT after tokenization (out-of-vocab words would all
    # collapse to [UNK] and fake a fully-duplicated corpus).
    def _sentence(prefix, i):
        return f"{prefix} tok{i % 60} tok{(i // 60) % 60} sat on the mat"

    preds = [_sentence("the cat", i) for i in range(256)] * 8
    refs = [_sentence("a dog", i) for i in range(256)] * 8

    with tempfile.TemporaryDirectory() as tmp:
        pt_dir = _tiny_bert(tmp)
        tokenizer = BertTokenizerFast.from_pretrained(pt_dir)

        def user_tok(texts, max_length):
            return tokenizer(texts, padding="max_length", truncation=True,
                             max_length=max_length, return_tensors="np")

        from metrics_tpu.functional import bert_score as our_bert_score
        from transformers import FlaxAutoModel

        flax_model = FlaxAutoModel.from_pretrained(pt_dir, from_pt=True)
        # ONE encoder callable held across calls — bert_score's jit cache is
        # keyed on this object, so a fresh lambda per call would recompile.
        model_fn = lambda ids, mask: flax_model(input_ids=ids, attention_mask=mask).last_hidden_state

        def one_ours():
            our_bert_score(preds, refs, model=model_fn, user_tokenizer=user_tok,
                           max_length=32, batch_size=256)

        one_ours()
        t0 = time.perf_counter()
        n = 5
        for _ in range(n):
            one_ours()
        dt = time.perf_counter() - t0
        ours = n * len(preds) / dt

        # encoder MFU: the dedup pipeline encodes the 512 DISTINCT sentences
        # (2 batches of 256) per run — XLA's FLOP count for one encoder batch
        # x batches actually executed / wall time
        import jax.numpy as jnp
        enc = user_tok(list(dict.fromkeys(preds)), 32)
        ids, mask = jnp.asarray(enc["input_ids"]), jnp.asarray(enc["attention_mask"])
        flops_batch = _compiled_flops(model_fn, ids, mask)
        # per-PAIR flops so flops_per_item x value (pairs/s) = achieved flops:
        # each run encodes 512 distinct sentences (2 batches) for 2048 pairs
        mfu_fields = _mfu_fields(
            flops_batch * 2 / len(preds) if flops_batch else None, ours,
            "XLA cost_analysis, 2 encoder batches/run amortized over the "
            "2048-pair corpus (tiny 4-layer BERT: MFU is dispatch-bound, expected low)",
        )

        def run_ref():
            from torchmetrics.functional.text.bert import bert_score as ref_bert_score

            def one():
                ref_bert_score(preds, refs, model_name_or_path=pt_dir, max_length=32,
                               num_threads=0, verbose=False, lang="en")

            one()
            t0 = time.perf_counter()
            for _ in range(n):
                one()
            return n * len(preds) / (time.perf_counter() - t0)

        ref = _with_reference(run_ref)
    out = {
        "value": round(ours, 2),
        "unit": "pairs/s",
        "vs_baseline": round(ours / ref, 3) if np.isfinite(ref) and ref > 0 else None,
    }
    out.update(mfu_fields)
    return out


# --------------------------------------------- config 1: README Accuracy (CPU, 1 proc)

_README_ACC_CODE = r"""
import json, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from metrics_tpu import Accuracy

rng = np.random.RandomState(0)
preds = jnp.asarray(rng.rand(4096, 10).astype(np.float32))
target = jnp.asarray(rng.randint(0, 10, 4096))
acc = Accuracy()
for _ in range(5):
    acc(preds, target)
acc.reset()
t0 = time.perf_counter()
for _ in range(30):
    acc(preds, target)
v = float(acc.compute())
dt = time.perf_counter() - t0
assert 0 <= v <= 1
print(json.dumps({"sps": 30 * 4096 / dt}))
"""


def bench_readme_accuracy_cpu() -> dict:
    """BASELINE config 1: the README ``Accuracy()`` forward loop, CPU, single
    process — ours (stateful facade, delta-merge forward) vs the reference's
    double-update forward on torch CPU."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _README_ACC_CODE], env=env, capture_output=True,
            text=True, timeout=600, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        ours = json.loads(proc.stdout.strip().splitlines()[-1])["sps"] if proc.returncode == 0 else float("nan")
    except subprocess.TimeoutExpired:
        ours = float("nan")

    def run_ref():
        import torch

        from torchmetrics import Accuracy as TAccuracy

        rng = np.random.RandomState(0)
        preds = torch.from_numpy(rng.rand(4096, 10).astype(np.float32))
        target = torch.from_numpy(rng.randint(0, 10, 4096))
        acc = TAccuracy()
        for _ in range(5):
            acc(preds, target)
        acc.reset()
        t0 = time.perf_counter()
        for _ in range(30):
            acc(preds, target)
        acc.compute()
        return 30 * 4096 / (time.perf_counter() - t0)

    ref = _with_reference(run_ref)
    return {
        "value": round(ours, 1) if np.isfinite(ours) else None,
        "unit": "samples/s (CPU, forward loop)",
        "vs_baseline": round(ours / ref, 3) if np.isfinite(ours) and np.isfinite(ref) and ref > 0 else None,
    }


# -------------------------------------------------------------------- config 5: FID

# peak dense FLOP/s per JAX device, bf16 MXU (Cloud TPU published board numbers
# divided out; v2/v3 expose one device per CORE, v4+ one per chip). f32 peak is
# lower (f32 runs as multi-pass bf16 on the MXU), so mfu-vs-bf16-peak is a
# conservative lower bound on how busy the MXU actually is.
_PEAK_FLOPS = {
    "tpu v2": 22.5e12,   # 180 TF/board / 8 cores
    "tpu v3": 52.5e12,   # 420 TF/board / 8 cores
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,
    "tpu v5e": 197e12,
    "tpu v5": 459e12,    # v5p
    "tpu v6 lite": 918e12,
    "tpu v6e": 918e12,
}


def _peak_flops() -> "tuple[float, str] | tuple[None, str]":
    import jax

    kind = jax.devices()[0].device_kind.lower()
    # longest matching key wins ("tpu v5 lite" before "tpu v5")
    best = None
    for k, v in _PEAK_FLOPS.items():
        if k in kind and (best is None or len(k) > len(best[0])):
            best = (k, v)
    if best:
        return best[1], kind
    return None, kind


def _compiled_flops(fn, *args) -> "float | None":
    """XLA's own FLOP estimate for jit(fn)(*args); None when unavailable."""
    import jax

    try:
        cost = jax.jit(fn).lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", -1.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def _mfu_fields(flops_per_item: "float | None", items_per_s: float, model: str) -> dict:
    out = {}
    if flops_per_item is None:
        out["flop_model"] = f"{model}: XLA cost_analysis unavailable"
        return out
    achieved = flops_per_item * items_per_s
    out["achieved_tflops"] = round(achieved / 1e12, 3)
    out["flops_per_item"] = round(flops_per_item / 1e9, 3)  # GFLOPs
    peak, kind = _peak_flops()
    out["device_kind"] = kind
    if peak is not None:
        out["mfu"] = round(achieved / peak, 4)
        out["peak_tflops_bf16"] = round(peak / 1e12, 1)
    else:
        out["mfu"] = None
        out["note_mfu"] = "device kind not in peak table; achieved_tflops still valid"
    out["flop_model"] = model
    return out


def bench_fid() -> dict:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import FrechetInceptionDistance

    fid = FrechetInceptionDistance(feature=2048)
    rng = np.random.RandomState(0)
    B = 256
    # DEVICE-RESIDENT batch, shipped once — re-sending it per call over the
    # tunnelled TPU measures the link, not the chip (BENCH_r03's 42 imgs/s bug)
    imgs = jnp.asarray((rng.rand(B, 299, 299, 3) * 255).astype(np.uint8))
    jax.block_until_ready(imgs)

    # K chained updates inside ONE compiled fori_loop (the pattern real TPU
    # eval loops use, tests/image/test_fid_streaming.py): a single dispatch
    # whose wall time is pure device compute. Timing an eager python update
    # loop over the tunnelled remote device proved unreliable — per-call
    # dispatch/readiness effects swing the apparent imgs/s several-fold
    # between runs, in both directions.
    K = 10

    # FLOP model first: XLA's own count for the compiled inception forward
    # (per img); fallback = the standard analytic InceptionV3 count,
    # 5.7 GMACs * 2. Needed up front for the trial plausibility filter.
    flops_total = _compiled_flops(fid.inception, imgs)
    per_img = flops_total / B if flops_total else 2 * 5.71e9
    peak_flops, _ = _peak_flops()

    def run_epoch_trials(fid_obj):
        @jax.jit
        def epoch(state):
            def body(i, s):
                return fid_obj.update_state(s, imgs, real=False)

            return jax.lax.fori_loop(0, K, body, state)

        state = epoch(fid_obj.init_state())  # compile + warm
        jax.block_until_ready(jax.tree.leaves(state))
        ts = []
        for _ in range(6):
            t0 = time.perf_counter()
            state = epoch(fid_obj.init_state())
            jax.block_until_ready(jax.tree.leaves(state))
            rate = K * B / (time.perf_counter() - t0)
            # plausibility: a trial implying more FLOP/s than the chip's peak
            # measured a runtime glitch (readiness fired before execution —
            # observed sporadically over the tunnel), not the chip
            if peak_flops and rate * per_img > peak_flops:
                continue
            ts.append(rate)
            if len(ts) == 3:
                break
        return ts

    trials = run_epoch_trials(fid)
    if not trials:
        return {"error": "all FID epoch trials exceeded the device FLOP peak "
                         "(runtime readiness glitch); no valid measurement"}
    ours = float(np.median(trials))
    out = {"value": round(ours, 2), "unit": "imgs/s (compiled epoch loop, device-resident batch)",
           "vs_baseline": None, "trials": [round(t, 1) for t in trials],
           "note": "reference FID needs torch-fidelity (absent); ours-only"}
    out.update(_mfu_fields(
        per_img, ours,
        "XLA cost_analysis of compiled InceptionV3 fwd" if flops_total
        else "analytic InceptionV3 5.71 GMACs*2 (cost_analysis unavailable)"))

    # the TPU-first fast path: same epoch with the bf16 compute mode
    # (InceptionFeatureExtractor(compute_dtype=bfloat16); default stays f32
    # for strict parity — see models/inception.py)
    try:
        from metrics_tpu.models.inception import InceptionFeatureExtractor

        ext16 = InceptionFeatureExtractor(feature="2048", compute_dtype=jnp.bfloat16)
        fid16 = FrechetInceptionDistance(feature=ext16, feature_dim=2048)
        bf16_trials = run_epoch_trials(fid16)  # same protocol + filter as f32
        if bf16_trials:
            bf16_rate = float(np.median(bf16_trials))
            out["bf16_value"] = round(bf16_rate, 2)
            out["bf16_trials"] = [round(t, 1) for t in bf16_trials]
            if peak_flops and per_img:
                out["bf16_mfu"] = round(bf16_rate * per_img / peak_flops, 4)
        else:
            out["bf16_error"] = "all bf16 trials exceeded the device FLOP peak (runtime glitch)"
    except Exception as e:  # the f32 headline must survive a fast-path failure
        out["bf16_error"] = str(e)[:200]
    return out


# --------------------------------------------- config 6: retrieval grouped compute

def bench_retrieval() -> dict:
    """10k-query RetrievalMAP compute: the fused sort+segment device path vs the
    reference-style per-group host loop (``RetrievalMetric._compute_host`` —
    behaviorally identical to reference ``retrieval_metric.py:124-153``)."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import RetrievalMAP

    n_queries, docs_per = 10_000, 20
    rng = np.random.RandomState(0)
    indexes = np.repeat(np.arange(n_queries), docs_per)
    preds = rng.rand(n_queries * docs_per).astype(np.float32)
    target = rng.randint(0, 2, n_queries * docs_per)

    m = RetrievalMAP()
    m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))

    jax.block_until_ready(m.compute())  # compile
    m._computed = None  # drop the epoch cache so the timed run recomputes
    t0 = time.perf_counter()
    jax.block_until_ready(m.compute())
    device_s = time.perf_counter() - t0

    # the host loop is the reference algorithm: one python iteration + one
    # blocking device sync per query, so it is linear in query count and far
    # too slow to run at 10k over the TPU tunnel — time a subset, extrapolate
    sub_q = 300
    sub = slice(0, sub_q * docs_per)
    idx_c, p_c, t_c = jnp.asarray(indexes[sub]), jnp.asarray(preds[sub]), jnp.asarray(target[sub])
    m._compute_host(idx_c, p_c, t_c)  # warm caches
    t0 = time.perf_counter()
    m._compute_host(idx_c, p_c, t_c)
    host_s = (time.perf_counter() - t0) * (n_queries / sub_q)

    return {
        "value": round(n_queries / device_s, 1),
        "unit": "queries/s (10k-query MAP compute, fused segment path)",
        "host_loop_queries_per_s": round(n_queries / host_s, 1),
        "host_loop_note": f"host loop timed on {sub_q} queries, scaled linearly",
        "vs_baseline": round(host_s / device_s, 2),
    }


def main() -> None:
    tpu_throughput = bench_tpu()
    ref_throughput = bench_reference()
    vs = tpu_throughput / ref_throughput if np.isfinite(ref_throughput) and ref_throughput > 0 else None

    extras = {}
    try:
        sync = bench_sync_latency()
        if "fused_us" in sync:
            sync_only = sync.get("fused_sync_only_us")
            naive_only = sync.get("naive_sync_only_us")
            # fall back to full-step timings only as a PAIR (mismatched
            # quantities would corrupt the ratio), and only when the
            # subtraction wasn't computed at all — 0.0 is a legitimate value
            # (sync fully hidden by overlap); the ratio guard below handles it
            have_isolated = sync_only is not None and naive_only is not None
            value = sync_only if have_isolated else sync["fused_us"]
            naive_value = naive_only if have_isolated else sync["naive_us"]
            extras["sync_latency_us"] = {
                "value": round(value, 1),
                "unit": "us/sync (8-dev mesh, fused bundle{})".format(
                    ", update cost subtracted" if have_isolated else ", full step"
                ),
                "naive_us": round(naive_value, 1),
                "vs_baseline": round(naive_value / value, 3) if value > 0 else None,
                "full_step_fused_us": round(sync["fused_us"], 1),
                "collectives_per_sync": sync.get("collectives_per_sync"),
                "collectives_per_sync_naive": sync.get("collectives_per_sync_naive"),
                "sync_payload_bytes": sync.get("sync_payload_bytes"),
                "chip_bundle_overhead_us": sync.get("chip_bundle_overhead_us"),
                "fused_scaling_us_by_devices": sync.get("fused_scaling_us_by_devices", {}),
            }
        else:
            extras["sync_latency_us"] = sync
    except Exception as e:  # never lose the primary line
        extras["sync_latency_us"] = {"error": str(e)[:200]}
    for name, fn in (
        ("readme_accuracy_cpu", bench_readme_accuracy_cpu),
        ("detection_map", bench_map),
        ("bertscore", bench_bertscore),
        ("fid_update", bench_fid),
        ("retrieval_compute", bench_retrieval),
    ):
        # one retry: the tunnelled TPU occasionally drops a remote_compile
        # mid-stream; a transient reset must not cost the config its number
        errors = []
        for _ in (0, 1):
            try:
                extras[name] = fn()
                break
            except Exception as e:
                errors.append(str(e)[:200])
                extras[name] = {"error": errors[0], "retry_error": errors[-1]} if len(errors) > 1 else {"error": errors[0]}

    print(
        json.dumps(
            {
                "metric": "fused_collection_update_throughput",
                "value": round(tpu_throughput, 1),
                "unit": "samples/s/chip",
                "vs_baseline": round(vs, 3) if vs is not None else None,
                "extras": extras,
            }
        )
    )


if __name__ == "__main__":
    main()
