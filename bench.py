"""Benchmark: the BASELINE.md north-star configs.

Primary line metric: fused MetricCollection update throughput (samples/s/chip),
``vs_baseline`` = ratio over the reference (TorchMetrics v0.7 at /root/reference,
torch CPU — the reference has no TPU path, so its CPU eager throughput IS its best
number on this host).

``extras`` carries the remaining north-star configs (VERDICT r1 next #2):
  * ``sync_latency_us``     — per-sync latency of a MetricCollection(Accuracy, F1,
    BinnedAveragePrecision) state sync on an 8-device mesh, fused collective
    bundle vs naive per-state collectives (vs_baseline = naive/fused speedup);
    measured in a subprocess on the virtual 8-device CPU mesh (the same topology
    the driver's multichip dryrun checks).
  * ``detection_map``       — MAP update+compute throughput (imgs/s), device
    greedy matching vs the reference's python loops (torch CPU, torchvision box
    ops shimmed).
  * ``bertscore``           — BERTScore throughput (pairs/s) with a local tiny
    BERT, flax encoder vs the reference HF-torch pipeline.
  * ``fid_update``          — FID inception-forward update throughput (imgs/s)
    on this chip with DEVICE-RESIDENT inputs (host->device transfer excluded —
    over the tunnelled TPU, re-shipping the batch each call measures the ~130ms
    RTT, not the chip), plus ``achieved_tflops``/``mfu``: the FLOP count comes
    from XLA's own cost analysis of the compiled inception forward (fallback:
    the analytic ~5.7 GMACs = 11.4 GFLOPs/img for InceptionV3 at 299x299), and
    peak FLOP/s from the device-kind table in ``_PEAK_FLOPS`` (bf16 peaks; the
    forward runs f32 so MFU-vs-bf16-peak is conservative). No baseline: the
    reference needs torch-fidelity, absent here.
  * ``bertscore`` carries the same ``achieved_tflops``/``mfu`` fields for its
    flax encoder forward.

Prints exactly ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extras"}.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

NUM_CLASSES = 10
# throughput config: batch large enough to saturate the chip — per-call cost on
# the tunnelled TPU is one dispatch round-trip + compute, so small batches
# measure launch latency, not update throughput (the same batch feeds the
# torch-CPU reference baseline)
BATCH = 65536
WARMUP = 5
ITERS = 30


from tests.helpers.reference_shims import (  # noqa: E402
    shim_pkg_resources as _shim_pkg_resources,
    shim_torchvision as _shim_torchvision,
)


# ------------------------------------------------------- device calibration
#
# Two tunnelled-TPU measurement hazards, discovered in r5 and guarded here:
#   1. READINESS GLITCH: ``block_until_ready`` can return before execution
#      finishes (a pure-matmul probe "measured" 1.3 EFLOP/s). Every timed
#      region must therefore FETCH A VALUE (device->host) — a value cannot
#      arrive early — and subtract the measured dispatch+fetch round-trip.
#   2. LOOP-INVARIANT HOISTING: a fori_loop body whose inputs don't depend on
#      the iteration index gets its whole forward hoisted out by XLA — a
#      BERT-base epoch "ran" at 2.6x the chip's peak. Every epoch body must
#      make its input loop-variant (``jnp.roll(x, i)`` — same content, new
#      value) so K iterations mean K executions.
#
# ``_calibration()`` measures the round-trip and the chip's SUSTAINED bf16
# matmul rate (K-scaled 8192^3 chain, value-fetched: 174 TF/s on this v5e =
# 88% of the 197 nominal peak), so MFU can be reported against both the
# nominal table and reality.

_CALIB: dict = {}


def _measure_rtt() -> float:
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.float32(0.0)
    float(f(x))  # compile
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(f(x))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _measure_matmul_ceiling() -> "float | None":
    """Sustained bf16 matmul TF/s: marginal rate between K=16 and K=64 chained
    8192^3 dots (value-fetched; the K-difference cancels fixed overheads)."""
    import jax
    import jax.numpy as jnp

    n = 8192
    a = jnp.ones((n, n), jnp.bfloat16)
    b = jnp.ones((n, n), jnp.bfloat16) * jnp.bfloat16(1.0 / n)
    times = {}
    try:
        for k in (16, 64):
            @jax.jit
            def chain(a, b, k=k):
                def body(i, x):
                    return jax.lax.dot(x, b, preferred_element_type=jnp.bfloat16)

                return jax.lax.fori_loop(0, k, body, a)[0, 0]

            float(chain(a, b))
            best = None
            for _ in range(2):
                t0 = time.perf_counter()
                float(chain(a, b))
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            times[k] = best
    except Exception:
        return None
    marginal = (times[64] - times[16]) / 48.0
    if marginal <= 0:
        return None
    return 2 * n**3 / marginal / 1e12


def _calibration() -> dict:
    if not _CALIB:
        _CALIB["rtt_s"] = _measure_rtt()
        ceiling = _measure_matmul_ceiling()
        _CALIB["measured_matmul_tflops_bf16"] = (
            round(ceiling, 1) if ceiling is not None else None
        )
    return _CALIB


def _with_reference(fn):
    """Run fn() with /root/reference importable; returns NaN on any failure.

    Both shims go in BEFORE the first ``torchmetrics`` import: the reference
    probes ``_TORCHVISION_AVAILABLE`` once at import time, so installing the
    torchvision shim later (as bench_map used to) leaves the flag False and
    the detection baseline dead.
    """
    try:
        _shim_pkg_resources()
        _shim_torchvision()
        sys.path.insert(0, "/root/reference")
        return fn()
    except Exception:
        return float("nan")
    finally:
        if "/root/reference" in sys.path:
            sys.path.remove("/root/reference")


def _data():
    rng = np.random.RandomState(0)
    preds = rng.rand(BATCH, NUM_CLASSES).astype(np.float32)
    preds /= preds.sum(axis=1, keepdims=True)
    target = rng.randint(0, NUM_CLASSES, BATCH)
    return preds, target


# ------------------------------------------------- config 1: fused update throughput

def bench_tpu() -> "tuple[float, dict]":
    """Headline: compiled-epoch fused MetricCollection update throughput.

    r5 protocol change (VERDICT r4 weak #1): the r3/r4 headline was a python
    loop of 30 jitted step dispatches, single-trial — over the tunnelled TPU
    the per-dispatch readiness effects swung it ±20% between rounds (11.79M ->
    9.50M with no code cause). Now the ITERS updates run inside ONE
    ``lax.fori_loop`` epoch (the shape real TPU eval loops use), with the two
    tunnel guards from ``_calibration()``: loop-variant inputs (no hoisting)
    and value-fetched timing minus the measured round-trip. 3 trials, median;
    the old dispatch-loop figure is kept alongside for continuity.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, BinnedAveragePrecision, F1Score, MetricCollection

    coll = MetricCollection(
        {
            "acc": Accuracy(),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "binned_ap": BinnedAveragePrecision(num_classes=NUM_CLASSES, thresholds=100),
        }
    )
    preds_np, target_np = _data()
    preds = jnp.asarray(preds_np)
    target = jnp.asarray(target_np)
    _calibration()  # measure RTT + matmul ceiling before any timing

    def make_epoch(iters):
        @jax.jit
        def epoch(state, p, t):
            def body(i, s):
                # roll by the loop index: same content every iteration, but
                # the update's input is loop-variant so XLA cannot hoist it
                return coll.update_state(s, jnp.roll(p, i, axis=0), jnp.roll(t, i, axis=0))

            out = jax.lax.fori_loop(0, iters, body, state)
            # scalar rider: fetching it forces the whole epoch to have executed
            return out, jnp.sum(jax.tree.leaves(out)[0])

        return epoch

    # K-pair marginal (see bench_bertscore_base): per-update time is the
    # slope between two trip counts — immune to constant offsets and to the
    # tunnel's residual readiness anomalies
    K1, K2 = 10, ITERS + 10
    ep1, ep2 = make_epoch(K1), make_epoch(K2)
    state, probe = ep1(coll.init_state(), preds, target)  # compile + warm
    float(probe)
    state, probe = ep2(coll.init_state(), preds, target)
    float(probe)
    trials = []
    for _ in range(3):
        t0 = time.perf_counter()
        _, probe = ep1(coll.init_state(), preds, target)
        float(probe)
        dt1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        state, probe = ep2(coll.init_state(), preds, target)
        float(probe)
        dt2 = time.perf_counter() - t0
        trials.append((K2 - K1) * BATCH / max(dt2 - dt1, 1e-9))
    vals = coll.compute_from(state)
    assert np.isfinite(float(vals["acc"]))

    # the legacy figure: same updates as 30 separate jitted dispatches
    @jax.jit
    def step(state, p, t):
        return coll.update_state(state, p, t)

    state = coll.init_state()
    for _ in range(WARMUP):
        state = step(state, preds, target)
    jax.block_until_ready(jax.tree.leaves(state))
    state = coll.init_state()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        state = step(state, preds, target)
    jax.block_until_ready(jax.tree.leaves(state))
    dispatch_rate = ITERS * BATCH / (time.perf_counter() - t0)

    meta = {
        "trials": [round(t, 1) for t in sorted(trials)],
        "protocol": "compiled fori_loop epochs, loop-variant inputs, K-pair"
                    " marginal of value-fetched timings (constant offsets cancel;"
                    " r5+; r3/r4 used the dispatch loop)",
        "dispatch_loop_value": round(dispatch_rate, 1),
        "calibration": dict(_calibration(), rtt_s=round(_calibration()["rtt_s"], 4)),
    }
    return float(np.median(trials)), meta


def bench_reference() -> float:
    def run():
        import torch

        from torchmetrics import Accuracy as TAccuracy, F1Score as TF1, MetricCollection as TColl
        from torchmetrics import BinnedAveragePrecision as TBAP

        coll = TColl(
            {
                "acc": TAccuracy(),
                "f1": TF1(num_classes=NUM_CLASSES, average="macro"),
                "binned_ap": TBAP(num_classes=NUM_CLASSES, thresholds=100),
            }
        )
        preds_np, target_np = _data()
        preds = torch.from_numpy(preds_np)
        target = torch.from_numpy(target_np)
        for _ in range(WARMUP):
            coll.update(preds, target)
        for m in coll.values():
            m.reset()
        t0 = time.perf_counter()
        for _ in range(ITERS):
            coll.update(preds, target)
        return ITERS * BATCH / (time.perf_counter() - t0)

    return _with_reference(run)


# ------------------------------------------------------- config 2: mesh sync latency

_SYNC_BENCH_CODE = r"""
import json, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from functools import partial
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import AUROC, Accuracy, BinnedAveragePrecision, F1Score, MetricCollection
from metrics_tpu.parallel.collectives import sync_axis_state

NUM_CLASSES = 10
# counters (psum bundle) + a static-capacity exact-curve metric (all_gather
# bundle) — the representative mixed-state collection. The device-count
# scaling runs (SYNC_BENCH_NO_GATHER) drop the gather metric: its payload is
# O(devices) by definition (every shard's buffer must travel), which would
# swamp the latency-scaling signal the 8->256 axis measures.
import os as _os
metrics = {
    "acc": Accuracy(),
    "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
    "binned_ap": BinnedAveragePrecision(num_classes=NUM_CLASSES, thresholds=100),
}
if _os.environ.get("SYNC_BENCH_NO_GATHER") != "1":
    metrics["auroc"] = AUROC(num_classes=NUM_CLASSES, capacity=256)
coll = MetricCollection(metrics)
rng = np.random.RandomState(0)
preds = jnp.asarray(rng.rand(1024, NUM_CLASSES).astype(np.float32))
target = jnp.asarray(rng.randint(0, NUM_CLASSES, 1024))
mesh = Mesh(np.asarray(jax.devices()), ("dp",))

def make(mode):
    # mode: "fused" | "naive" | "nosync" | "noop" — nosync is the identical
    # step minus the sync, so (mode - nosync) isolates the sync cost from the
    # update; noop is an empty shard_map step, the pure dispatch/infeed floor
    # every other number rides on (subtract it to read the compute+collective
    # cost; on the timeshared virtual mesh the floor IS most of the time)
    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(), check_vma=False)
    def step(p, t):
        if mode == "noop":
            return jnp.float32(0.0)
        state = coll.update_state(coll.init_state(), p, t)
        if mode == "fused":
            synced = coll.sync_states(state, "dp")
        elif mode == "naive":
            # one collective per state leaf (the reference's O(K*S) pattern)
            synced = {
                name: {
                    k: sync_axis_state(m._reductions[k], st[k], "dp")
                    for k in st
                }
                for (name, m), st in zip(coll.items(keep_base=True), state.values())
            }
        else:
            synced = state
        leaves = jax.tree.leaves(synced)
        return sum(jnp.sum(l) for l in leaves)

    return step

import re as _re
out = {}
fused_only = _os.environ.get("SYNC_BENCH_FUSED_ONLY") == "1"
modes = ("fused",) if fused_only else ("fused", "naive", "nosync", "noop")
steps = {m: make(m) for m in modes}
for step in steps.values():
    for _ in range(3):
        step(preds, target).block_until_ready()

def time_once(step, n):
    t0 = time.perf_counter()
    for _ in range(n):
        step(preds, target).block_until_ready()
    return (time.perf_counter() - t0) / n * 1e6

# PINNED protocol (r6, VERDICT r5 weak #3): fixed iteration counts, modes
# interleaved so host drift hits all equally, per-mode median AND spread
# published (the virtual mesh timeshares one host — spread is the error bar
# the µs numbers must be read with).
N_INNER = 20 if fused_only else 60
N_REPEATS = 1 if fused_only else 5
import statistics
samples = {m: [] for m in modes}
for _ in range(N_REPEATS):
    for m in modes:
        samples[m].append(time_once(steps[m], N_INNER))
for m in modes:
    out[m + "_us"] = statistics.median(samples[m])
# spread keys mirror the median keys (noop is published as noop_floor below)
out["spread_us"] = {
    ("noop_floor" if m == "noop" else m): [min(samples[m]), max(samples[m])]
    for m in modes
}
out["protocol"] = (
    f"{N_REPEATS} interleaved repeats x {N_INNER} iters/mode, per-mode median;"
    " spread_us = [min, max] over repeats; noop_floor_us = empty shard_map floor"
)
if not fused_only:
    out["noop_floor_us"] = out.pop("noop_us")
    out["fused_sync_only_us"] = max(out["fused_us"] - out["nosync_us"], 0.0)
    out["naive_sync_only_us"] = max(out["naive_us"] - out["nosync_us"], 0.0)
    out["fused_minus_floor_us"] = max(out["fused_us"] - out["noop_floor_us"], 0.0)

    # the north-star evidence: collectives in the COMPILED fused step, and the
    # payload bytes one sync moves per device
    hlo = steps["fused"].lower(preds, target).compile().as_text()
    out["collectives_per_sync"] = {
        "all_reduce": len(_re.findall(r"\ball-reduce(?:-start)?\(", hlo)),
        "all_gather": len(_re.findall(r"\ball-gather(?:-start)?\(", hlo)),
    }
    hlo_naive = steps["naive"].lower(preds, target).compile().as_text()
    out["collectives_per_sync_naive"] = {
        "all_reduce": len(_re.findall(r"\ball-reduce(?:-start)?\(", hlo_naive)),
        "all_gather": len(_re.findall(r"\ball-gather(?:-start)?\(", hlo_naive)),
    }
    state = coll.update_state(coll.init_state(), preds[:8], target[:8])
    out["sync_payload_bytes"] = int(sum(
        np.asarray(l).size * np.asarray(l).dtype.itemsize for l in jax.tree.leaves(state)
    ))
print(json.dumps(out))
"""


# -------------------------------------------- config 2b: quantized sync payload

_SYNC_PAYLOAD_CODE = r"""
import json, os, statistics, tempfile, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from functools import partial
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Accuracy, BinnedAveragePrecision, MetricCollection
from metrics_tpu.engine import EngineConfig, MultiStreamEngine, StreamingEngine
from metrics_tpu.parallel.collectives import sync_payload_bytes

# the eligible float-heavy collection: BinnedAveragePrecision's (C, T) f32
# sum accumulators dominate; Accuracy's int counts pin the exact digit rider.
# thresholds=1001 keeps the DATA dominant over the per-leaf checkpoint
# metadata in the bytes-on-disk comparison (a ~100 KB/state payload).
def col(prec=None):
    c = MetricCollection({
        "acc": Accuracy(),
        "bap": BinnedAveragePrecision(num_classes=8, thresholds=1001),
    })
    if prec:
        c.set_sync_precision(prec)
    return c

out = {}
W = len(jax.devices())
info_q = col("q8_block").sync_leaf_info()
info_e = [(f, l, "exact") for f, l, _ in info_q]
b_e, b_q = sync_payload_bytes(info_e, W), sync_payload_bytes(info_q, W)
out["sync_payload_bytes"] = {
    "exact": b_e, "quantized": b_q, "ratio": round(b_e / max(1, b_q), 2),
}

mesh = Mesh(np.asarray(jax.devices()), ("dp",))
rng = np.random.RandomState(0)
batches = []
for n in (32, 32, 32, 32):
    p = rng.rand(n, 8).astype(np.float32)
    p /= p.sum(axis=1, keepdims=True)
    batches.append((p, rng.randint(0, 8, n)))

# ---- deferred boundary merge: us/sync, exact vs quantized, one warm program
# each, interleaved medians (ratios-in-one-run — both sides share this host)
dirs = {}
engines = {}
for tag, prec in (("exact", None), ("quantized", "q8_block")):
    dirs[tag] = tempfile.mkdtemp(prefix=f"sync_payload_{tag}_")
    eng = StreamingEngine(
        col(prec),
        EngineConfig(buckets=(32,), mesh=mesh, axis="dp", mesh_sync="deferred",
                     snapshot_dir=dirs[tag], compress_payloads=prec is not None),
    )
    eng.start()
    for b in batches:
        eng.submit(*b)
    eng.result()  # warm: compiles update/merge/compute
    engines[tag] = eng

N_INNER, N_REPEATS = 20, 3
samples = {t: [] for t in engines}
for _ in range(N_REPEATS):
    for tag, eng in engines.items():
        prog, state = eng._merge_program(), eng._state
        t0 = time.perf_counter()
        for _ in range(N_INNER):
            jax.block_until_ready(prog(state))
        samples[tag].append((time.perf_counter() - t0) / N_INNER * 1e6)
out["deferred_merge_us"] = {
    t: round(statistics.median(v), 1) for t, v in samples.items()
}
out["deferred_merge_us"]["spread_us"] = {
    t: [round(min(v), 1), round(max(v), 1)] for t, v in samples.items()
}

# ---- step-sync bundle: in-step sync cost, exact vs quantized vs nosync
# (subtract nosync to isolate the bundle), interleaved
coll_e, coll_q = col(), col("q8_block")
preds, target = jnp.asarray(batches[0][0]), jnp.asarray(batches[0][1])

def make(coll, sync):
    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(),
             check_vma=False)
    def step(p, t):
        state = coll.update_state(coll.init_state(), p, t)
        if sync:
            state = coll.sync_states(state, "dp")
        return sum(jnp.sum(jnp.asarray(l, jnp.float32)) for l in jax.tree.leaves(state))
    return step

steps = {"exact": make(coll_e, True), "quantized": make(coll_q, True),
         "nosync": make(coll_e, False)}
for s in steps.values():
    for _ in range(3):
        s(preds, target).block_until_ready()
samples = {t: [] for t in steps}
for _ in range(N_REPEATS):
    for tag, s in steps.items():
        t0 = time.perf_counter()
        for _ in range(N_INNER):
            s(preds, target).block_until_ready()
        samples[tag].append((time.perf_counter() - t0) / N_INNER * 1e6)
med = {t: statistics.median(v) for t, v in samples.items()}
out["step_sync_us"] = {
    "exact": round(med["exact"], 1),
    "quantized": round(med["quantized"], 1),
    "nosync": round(med["nosync"], 1),
    "exact_sync_only": round(max(med["exact"] - med["nosync"], 0.0), 1),
    "quantized_sync_only": round(max(med["quantized"] - med["nosync"], 0.0), 1),
}

# ---- snapshot footprint: payload array bytes (the codec's footprint — what
# scales host RAM and raw storage) plus bytes on disk for reference. The
# on-disk number also rides the checkpointer's own LOSSLESS compression,
# which flattens sparse/zero-heavy states for both policies — payload bytes
# are the durable codec fact.
def du(path):
    return sum(
        os.path.getsize(os.path.join(r, f))
        for r, _, fs in os.walk(path) for f in fs
    )

from metrics_tpu.engine.snapshot import load_snapshot

snap_disk, snap_payload = {}, {}
for tag, eng in engines.items():
    eng.snapshot()
    snap_disk[tag] = du(dirs[tag])
    state, _meta = load_snapshot(dirs[tag])
    total = 0
    for l in jax.tree.leaves(state):
        try:
            total += int(np.asarray(l).nbytes)
        except Exception:
            pass
    snap_payload[tag] = total
    eng.stop()
out["snapshot_payload_bytes"] = dict(
    snap_payload,
    ratio=round(snap_payload["exact"] / max(1, snap_payload["quantized"]), 2),
)
out["snapshot_disk_bytes"] = dict(
    snap_disk, note="includes the checkpointer's own lossless layer + metadata"
)

# ---- pager host-RAM bytes: stream-sharded engines behind a resident cap
# small enough that rows MUST spill; exact vs compressed spill stores
S = 64
def traffic():
    rows = []
    r = np.random.RandomState(1)
    for i in range(48):
        sid = (i % W) + W * ((i // W) % 6)
        n = 8
        p = r.rand(n, 8).astype(np.float32)
        p /= p.sum(axis=1, keepdims=True)
        rows.append((sid, p, r.randint(0, 8, n)))
    return rows

spill = {}
for tag, prec in (("exact", None), ("quantized", "q8_block")):
    eng = MultiStreamEngine(
        col(prec), num_streams=S,
        config=EngineConfig(buckets=(32,), mesh=mesh, axis="dp",
                            mesh_sync="deferred", coalesce=1,
                            compress_payloads=prec is not None),
        stream_shard=True, resident_streams=2,
    )
    with eng:
        for sid, p, t in traffic():
            eng.submit(sid, p, t)
        eng.flush()
        spill[tag] = eng._pager.spill_nbytes()
out["pager_spill_bytes"] = dict(
    spill, ratio=round(spill["exact"] / max(1, spill["quantized"]), 2)
)
out["protocol"] = (
    f"{N_REPEATS} interleaved repeats x {N_INNER} iters, per-mode median; both "
    "policies in ONE run on the 8-dev virtual mesh (ratios are the durable "
    "facts; absolute us timeshare one host); payload bytes analytic from "
    "fused_sync_plan; snapshot/pager bytes measured on disk / in host RAM. "
    "NOTE: on the virtual CPU mesh the quantized us/sync PAYS the encode/"
    "decode compute but saves no real link time (there is no interconnect) — "
    "the byte ratios are the bandwidth claim, the us columns its host-side "
    "overhead bound (docs/benchmarking.md, Sync payload r11)"
)
print(json.dumps(out))
"""


def bench_sync_payload() -> dict:
    """BENCH.sync_payload (r11): quantized vs exact sync payload — bytes per
    fused sync (deferred boundary merge AND step-sync bundle), us/sync for
    both policies in one run, plus snapshot bytes-on-disk and pager
    bytes-in-host-RAM. The r03–r05 trajectory reported a single exact
    ``sync_payload_bytes`` under ``sync_latency_us``; this entry adds the
    per-policy split and the reduction ratios the ISSUE-10 headline pins."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SYNC_PAYLOAD_CODE],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": "sync payload bench timed out"}
    if proc.returncode != 0:
        return {"error": proc.stderr[-500:]}
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    ratio = out.get("sync_payload_bytes", {}).get("ratio")
    out["vs_baseline"] = ratio  # headline: x-fold payload reduction
    return out


def _run_sync_bench(n_devices: int, fused_only: bool) -> dict:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={n_devices}"
    )
    env["JAX_PLATFORMS"] = "cpu"
    if fused_only:
        env["SYNC_BENCH_FUSED_ONLY"] = "1"
        env["SYNC_BENCH_NO_GATHER"] = "1"  # scaling axis: counter latency only
    else:
        env.pop("SYNC_BENCH_FUSED_ONLY", None)  # don't inherit a stale export
        env.pop("SYNC_BENCH_NO_GATHER", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SYNC_BENCH_CODE],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": f"sync bench timed out at {n_devices} devices"}
    if proc.returncode != 0:
        return {"error": proc.stderr[-500:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_sync_latency() -> dict:
    """Fused-vs-naive on the 8-device mesh + fused-latency scaling to 256
    virtual devices (the BASELINE.md 8->256-chip axis; virtual CPU devices
    timeshare the host, so the large-mesh numbers are upper bounds)."""
    out = _run_sync_bench(8, fused_only=False)
    if "fused_us" not in out:
        return out  # base run failed; don't burn time on the scaling extras
    scaling = {"8": round(out["fused_us"], 1)}
    for n in (64, 256):
        r = _run_sync_bench(n, fused_only=True)
        if "fused_us" in r:
            scaling[str(n)] = round(r["fused_us"], 1)
    # honest-by-construction: N virtual CPU devices timeshare ONE host, so
    # these µs prove the topology compiles and runs, not how fast a real
    # 64/256-chip sync is — the durable facts are the HLO collective counts
    # and payload bytes alongside (VERDICT r5 weak #3/#5)
    out["fused_scaling_us_by_devices"] = dict(
        scaling, liveness_only=True,
        note="virtual CPU mesh timeshares one host; topology liveness, not latency",
    )
    try:
        out["chip_bundle_overhead_us"] = _bench_chip_sync_overhead()
    except Exception as e:
        out["chip_bundle_overhead_us"] = {"error": str(e)[:200]}
    return out


def _bench_chip_sync_overhead() -> dict:
    """The non-collective cost of one fused sync on the REAL chip: pack
    (concat), degenerate 1-device collective, unpack (slice/reshape), jitted.

    This anchors the latency model in docs/distributed.md: total sync time =
    this overhead + one all-reduce of the payload over ICI; one chip cannot
    run a real multi-chip collective, but it can prove the bundle itself adds
    only microseconds on top of the wire time.

    r6 re-derivation (VERDICT r5 weak #3: the old back-to-back loops reported
    an exactly-0.0 overhead, i.e. the measurement collapsed into the dispatch
    noise): sync/nosync now run INTERLEAVED, the per-pair deltas are kept, and
    the result self-describes — median delta, both absolute medians, and the
    delta spread. A median delta below the spread means "unresolvable at this
    dispatch noise", which is reported as such instead of a fake 0.0.
    """
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import Accuracy, BinnedAveragePrecision, F1Score, MetricCollection

    coll = MetricCollection({
        "acc": Accuracy(),
        "f1": F1Score(num_classes=10, average="macro"),
        "binned_ap": BinnedAveragePrecision(num_classes=10, thresholds=100),
    })
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(), check_vma=False)
    def step(p, t):
        state = coll.update_state(coll.init_state(), p, t)
        synced = coll.sync_states(state, "dp")
        return sum(jnp.sum(l) for l in jax.tree.leaves(synced))

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(), check_vma=False)
    def step_nosync(p, t):
        state = coll.update_state(coll.init_state(), p, t)
        return sum(jnp.sum(l) for l in jax.tree.leaves(state))

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(1024, 10).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 10, 1024))
    for f in (step, step_nosync):
        for _ in range(3):
            f(preds, target).block_until_ready()

    def one(f, n=10):
        t0 = time.perf_counter()
        for _ in range(n):
            f(preds, target).block_until_ready()
        return (time.perf_counter() - t0) / n * 1e6

    deltas, syncs, nosyncs = [], [], []
    for _ in range(6):  # interleaved pairs: drift cancels within each pair
        s, ns = one(step), one(step_nosync)
        syncs.append(s)
        nosyncs.append(ns)
        deltas.append(s - ns)
    med = float(np.median(deltas))
    spread = float(np.max(deltas) - np.min(deltas))
    out = {
        "overhead_us": round(med, 2),
        "sync_us": round(float(np.median(syncs)), 1),
        "nosync_us": round(float(np.median(nosyncs)), 1),
        "delta_spread_us": round(spread, 2),
        "protocol": "6 interleaved (sync, nosync) pairs x 10 iters, median of per-pair deltas",
    }
    if med <= 0 or med < spread / 2:
        out["resolved"] = False
        out["note"] = (
            "bundle overhead is below this runtime's dispatch noise floor —"
            " an upper bound of ~spread/2 µs, not a measured zero"
        )
    else:
        out["resolved"] = True
    return out


# -------------------------------------------------------------- config 3: detection

def _map_scenes(n_imgs=64, seed=0):
    """COCO-like random scenes (up to ~25 dets/img, 5 classes)."""
    rng = np.random.RandomState(seed)
    scenes = []
    for _ in range(n_imgs):
        n_pred, n_gt = rng.randint(8, 26), rng.randint(4, 16)
        def boxes(n):
            xy = rng.rand(n, 2).astype(np.float32) * 80
            wh = rng.rand(n, 2).astype(np.float32) * 60 + 5
            return np.concatenate([xy, xy + wh], axis=1)
        scenes.append((
            dict(boxes=boxes(n_pred), scores=rng.rand(n_pred).astype(np.float32),
                 labels=rng.randint(0, 5, n_pred)),
            dict(boxes=boxes(n_gt), labels=rng.randint(0, 5, n_gt)),
        ))
    return scenes


def bench_map() -> dict:
    from metrics_tpu import MAP

    scenes = _map_scenes()

    def run_ours():
        m = MAP()  # device matching
        for pred, tgt in scenes:
            m.update([pred], [tgt])
        r = m.compute()
        assert np.isfinite(float(r["map"]))

    run_ours()  # warmup/compile
    t0 = time.perf_counter()
    n = 3
    for _ in range(n):
        run_ours()
    ours = n * len(scenes) / (time.perf_counter() - t0)

    def run_ref():
        _shim_torchvision()
        import torch

        from torchmetrics.detection.map import MAP as TMAP

        def one():
            m = TMAP()
            for pred, tgt in scenes:
                m.update(
                    [{k: torch.from_numpy(np.asarray(v)) for k, v in pred.items()}],
                    [{k: torch.from_numpy(np.asarray(v)) for k, v in tgt.items()}],
                )
            m.compute()

        one()
        t0 = time.perf_counter()
        for _ in range(n):
            one()
        return n * len(scenes) / (time.perf_counter() - t0)

    ref = _with_reference(run_ref)
    out = {
        "value": round(ours, 2),
        "unit": "imgs/s",
        "vs_baseline": round(ours / ref, 3) if np.isfinite(ref) and ref > 0 else None,
    }
    try:
        out["host_tail"] = _map_host_tail()
    except Exception as e:
        out["host_tail"] = {"error": str(e)[:200]}
    return out


def _map_host_tail() -> dict:
    """Fraction of MAP ``compute()`` spent in the host-numpy 101-point
    accumulation, at 1x and 10x detection density (VERDICT r4 next #8).

    The device path ends at ``_device_eval_imgs`` (jitted matching + one
    transfer); everything after is the host tail. Measured finding: the tail
    FRACTION SHRINKS as detections grow (matching work is superlinear in
    padded dets/img, accumulation is a single vectorized cumsum pass), so the
    host accumulation is not the at-scale serial tail and stays host-side —
    the decision the r4 docstring asserted, now with numbers attached.
    """
    from metrics_tpu import MAP

    out = {}
    for label, (n_imgs, lo, hi) in (("1x", (64, 8, 26)), ("10x", (64, 80, 260))):
        rng = np.random.RandomState(5)
        m = MAP()
        for _ in range(n_imgs):
            n_pred, n_gt = rng.randint(lo, hi), rng.randint(lo // 2 + 1, hi // 2 + 2)

            def boxes(n):
                xy = rng.rand(n, 2).astype(np.float32) * 80
                wh = rng.rand(n, 2).astype(np.float32) * 60 + 5
                return np.concatenate([xy, xy + wh], axis=1)

            m.update(
                [dict(boxes=boxes(n_pred), scores=rng.rand(n_pred).astype(np.float32),
                      labels=rng.randint(0, 5, n_pred))],
                [dict(boxes=boxes(n_gt), labels=rng.randint(0, 5, n_gt))],
            )
        m.compute()  # warm/compile
        classes = m._get_classes()
        t0 = time.perf_counter()
        m._device_eval_imgs(classes, m.max_detection_thresholds[-1])
        t_match = time.perf_counter() - t0
        t0 = time.perf_counter()
        m._calculate(classes)
        t_total = time.perf_counter() - t0
        out[label] = {
            "match_ms": round(t_match * 1e3, 1),
            "total_ms": round(t_total * 1e3, 1),
            "host_tail_frac": round(max(t_total - t_match, 0.0) / t_total, 3),
        }
    out["decision"] = (
        "host accumulation stays: its fraction falls with detection density "
        "(it is a vectorized cumsum; matching grows faster)"
    )
    return out


# -------------------------------------------------------------- config 4: BERTScore

def _tiny_bert(tmp):
    import torch
    from transformers import BertConfig, BertModel, BertTokenizerFast

    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + [f"tok{i}" for i in range(60)] + [
        "the", "cat", "sat", "on", "mat", "a", "dog", "ran", "in", "park",
    ]
    vf = os.path.join(tmp, "vocab.txt")
    with open(vf, "w") as f:
        f.write("\n".join(vocab))
    cfg = BertConfig(vocab_size=len(vocab), hidden_size=128, num_hidden_layers=4,
                     num_attention_heads=4, intermediate_size=256, max_position_embeddings=64)
    torch.manual_seed(0)
    pt_dir = os.path.join(tmp, "pt")
    BertModel(cfg).eval().save_pretrained(pt_dir)
    BertTokenizerFast(vocab_file=vf).save_pretrained(pt_dir)
    return pt_dir


def bench_bertscore() -> dict:
    import tempfile

    from transformers import BertTokenizerFast

    # corpus-scale throughput: per-call cost on the tunnelled TPU is ONE
    # blocking round-trip (~130ms) + compute, so small corpora measure tunnel
    # latency, not throughput. 2048 pairs with 256 distinct sentences per side
    # (8 copies each — the shared-reference shape of real MT eval, which the
    # pipeline's dedup encoding exploits; the reference gets the same corpus).
    # Distinguishing words come from the tiny vocab's tokN entries so the
    # sentences stay DISTINCT after tokenization (out-of-vocab words would all
    # collapse to [UNK] and fake a fully-duplicated corpus).
    def _sentence(prefix, i):
        return f"{prefix} tok{i % 60} tok{(i // 60) % 60} sat on the mat"

    preds = [_sentence("the cat", i) for i in range(256)] * 8
    refs = [_sentence("a dog", i) for i in range(256)] * 8

    with tempfile.TemporaryDirectory() as tmp:
        pt_dir = _tiny_bert(tmp)
        tokenizer = BertTokenizerFast.from_pretrained(pt_dir)

        def user_tok(texts, max_length):
            return tokenizer(texts, padding="max_length", truncation=True,
                             max_length=max_length, return_tensors="np")

        from metrics_tpu.functional import bert_score as our_bert_score
        from transformers import FlaxAutoModel

        flax_model = FlaxAutoModel.from_pretrained(pt_dir, from_pt=True)
        # ONE encoder callable held across calls — bert_score's jit cache is
        # keyed on this object, so a fresh lambda per call would recompile.
        model_fn = lambda ids, mask: flax_model(input_ids=ids, attention_mask=mask).last_hidden_state

        def one_ours():
            our_bert_score(preds, refs, model=model_fn, user_tokenizer=user_tok,
                           max_length=32, batch_size=256)

        one_ours()
        t0 = time.perf_counter()
        n = 5
        for _ in range(n):
            one_ours()
        dt = time.perf_counter() - t0
        ours = n * len(preds) / dt

        # encoder MFU: the dedup pipeline encodes the 512 DISTINCT sentences
        # (2 batches of 256) per run — XLA's FLOP count for one encoder batch
        # x batches actually executed / wall time
        import jax.numpy as jnp
        enc = user_tok(list(dict.fromkeys(preds)), 32)
        ids, mask = jnp.asarray(enc["input_ids"]), jnp.asarray(enc["attention_mask"])
        flops_batch = _compiled_flops(model_fn, ids, mask)
        # per-PAIR flops so flops_per_item x value (pairs/s) = achieved flops:
        # each run encodes 512 distinct sentences (2 batches) for 2048 pairs
        mfu_fields = _mfu_fields(
            flops_batch * 2 / len(preds) if flops_batch else None, ours,
            "XLA cost_analysis, 2 encoder batches/run amortized over the "
            "2048-pair corpus (tiny 4-layer BERT: MFU is dispatch-bound, expected low)",
        )

        def run_ref():
            from torchmetrics.functional.text.bert import bert_score as ref_bert_score

            def one():
                ref_bert_score(preds, refs, model_name_or_path=pt_dir, max_length=32,
                               num_threads=0, verbose=False, lang="en")

            one()
            t0 = time.perf_counter()
            for _ in range(n):
                one()
            return n * len(preds) / (time.perf_counter() - t0)

        ref = _with_reference(run_ref)
    out = {
        "value": round(ours, 2),
        "unit": "pairs/s",
        "vs_baseline": round(ours / ref, 3) if np.isfinite(ref) and ref > 0 else None,
    }
    out.update(mfu_fields)
    return out


# --------------------------------------- config 4b: BERTScore at BERT-base scale

def bench_bertscore_base() -> dict:
    """BERT-base (12 layers, hidden 768, heads 12, ff 3072) BERTScore on the
    chip — the configuration BASELINE.json actually names (VERDICT r4 next #2;
    the `bertscore` extra keeps the tiny-model dispatch-bound figure for
    continuity). Random init (no egress), bf16 compute: identical FLOPs and
    layout to converted pretrained weights.

    Two numbers:
      * ``value``: end-to-end bert_score pairs/s on a 2048-pair corpus
        (512 distinct sentences, dedup pipeline, max_length 128);
      * ``encoder_mfu``: MFU of the compiled encoder forward alone, measured
        with the FID-style compiled fori_loop epoch (dispatch-free).
    """
    import tempfile

    import jax
    import jax.numpy as jnp
    from transformers import BertConfig, BertTokenizerFast, FlaxBertModel

    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + [f"tok{i}" for i in range(60)] + [
        "the", "cat", "sat", "on", "mat", "a", "dog", "ran", "in", "park",
    ]
    cfg = BertConfig(vocab_size=len(vocab), hidden_size=768, num_hidden_layers=12,
                     num_attention_heads=12, intermediate_size=3072,
                     max_position_embeddings=512)
    # flax-native construction (no torch detour), bf16 compute / f32 params.
    # Init MUST NOT run eagerly: transformers executes it one op at a time —
    # a ~130ms tunnel round-trip per op, minutes for BERT-base (and
    # default_device(cpu) does not redirect it under the axon platform).
    # _do_init=False + ONE jitted module.init = one dispatch.
    fmodel = FlaxBertModel(cfg, dtype=jnp.bfloat16, _do_init=False)
    ids0 = jnp.zeros((1, 8), jnp.int32)

    @jax.jit
    def _init(rng):
        return fmodel.module.init(
            rng, ids0, jnp.ones_like(ids0), jnp.zeros_like(ids0), jnp.zeros_like(ids0)
        )["params"]

    params = _init(jax.random.PRNGKey(0))
    jax.block_until_ready(params)

    # params as runtime args + the prejitted flag: a closure capture would
    # inline all 110M weights into the HLO as constants (observed: HTTP 413
    # from the tunnel's remote-compile on a ~400MB program)
    @jax.jit
    def _fwd(p, ids, mask):
        return fmodel(input_ids=ids, attention_mask=mask, params=p).last_hidden_state

    def model_fn(ids, mask):
        return _fwd(params, ids, mask)

    model_fn._metrics_tpu_prejitted = True

    MAXLEN, ENC_BATCH = 128, 256

    with tempfile.TemporaryDirectory() as tmp:
        vf = os.path.join(tmp, "vocab.txt")
        with open(vf, "w") as f:
            f.write("\n".join(vocab))
        tokenizer = BertTokenizerFast(vocab_file=vf)

        def user_tok(texts, max_length):
            return tokenizer(texts, padding="max_length", truncation=True,
                             max_length=max_length, return_tensors="np")

        def _sentence(prefix, i):
            body = " ".join(f"tok{(i * 7 + j) % 60}" for j in range(24))
            return f"{prefix} {body} sat on the mat"

        preds = [_sentence("the cat", i) for i in range(256)] * 8
        refs = [_sentence("a dog", i) for i in range(256)] * 8

        from metrics_tpu.functional import bert_score as our_bert_score

        def one():
            our_bert_score(preds, refs, user_forward_fn=model_fn, user_tokenizer=user_tok,
                           max_length=MAXLEN, batch_size=ENC_BATCH)

        one()  # compile + warm
        trials = []
        for _ in range(3):
            t0 = time.perf_counter()
            one()
            trials.append(len(preds) / (time.perf_counter() - t0))
        pairs_per_s = float(np.median(trials))

        # Encoder MFU via SINGLE-PROGRAM calibration (r6, the structural fix
        # for r5's impossible encoder_mfu=1.40): the encoder epoch and the
        # matmul-ceiling chain run as dynamic-trip-count fori_loops inside ONE
        # compiled executable, so workload and ceiling provably execute on the
        # same accelerator — their K-pair marginal ratio is a utilization in
        # (0, 1] by construction, immune to the tunnel's heterogeneous pool
        # (protocol: metrics_tpu/ops/profiling.py::single_program_calibration,
        # docs/benchmarking.md "Attributed MFU protocol").
        from metrics_tpu.ops import single_program_calibration

        enc = user_tok(list(dict.fromkeys(preds)), MAXLEN)
        ids = jnp.asarray(enc["input_ids"][:ENC_BATCH])
        mask = jnp.asarray(enc["attention_mask"][:ENC_BATCH])
        jax.block_until_ready(ids)

        # the convention analytic transformer count (2 * encoder-GEMM-params *
        # tokens + attention score/value terms) — what MFU is defined over
        h, ff, layers = 768, 3072, 12
        analytic_per_sentence = (
            2.0 * MAXLEN * layers * (4 * h * h + 2 * h * ff)
            + 2.0 * layers * 2 * MAXLEN * MAXLEN * h
        )

        def encoder_body(ops_, i):
            p, ids_, mask_ = ops_
            # loop-variant batch (rolled: same tokens, new value) — an
            # invariant batch lets XLA hoist the forward out of the loop
            return jnp.sum(
                fmodel(input_ids=jnp.roll(ids_, i, axis=0), attention_mask=mask_,
                       params=p).last_hidden_state.astype(jnp.float32)
            )

        calib = single_program_calibration(
            encoder_body, (params, ids, mask),
            flops_per_iter=analytic_per_sentence * ENC_BATCH,
        )
        sent_per_s = ENC_BATCH / calib["work_s_per_iter"]
    out = {
        "value": round(pairs_per_s, 2),
        "unit": "pairs/s (end-to-end bert_score, BERT-base encoder, bf16, 2048-pair corpus)",
        "trials": [round(t, 1) for t in sorted(trials)],
        "vs_baseline": None,
        "note": "reference needs downloaded HF weights (no egress here); random-init"
                " BERT-base has identical FLOPs/layout",
        "encoder_sentences_per_s": round(sent_per_s, 1),
        # the headline utilization: in (0, 1] by construction (same-program
        # ceiling). r5's encoder_epoch_vs_dispatch_anomaly flag is GONE — the
        # failure mode it flagged (ceiling and workload on different chips of a
        # heterogeneous pool) is structurally impossible in this protocol.
        "encoder_mfu": round(min(calib["mfu_vs_in_program_ceiling"], 1.0), 4),
        "encoder_achieved_tflops": round(calib["achieved_tflops"], 3),
        "in_program_matmul_tflops": round(calib["in_program_matmul_tflops"], 1),
        "calibration_timings_s": calib["timings_s"],
        "flop_model": (
            "analytic transformer FLOPs (2*GEMM-params*tokens + attention);"
            " single-program K-pair calibration — see docs/benchmarking.md"
        ),
        "protocol": calib["protocol"],
    }
    # continuity fields: MFU against the nominal device table (comparable
    # across reports; can exceed the in-program figure when the nominal peak
    # under-states the accelerator actually serving the program)
    peak, kind = _peak_flops()
    out["device_kind"] = kind
    if peak is not None:
        out["encoder_mfu_vs_nominal_peak"] = round(calib["achieved_tflops"] * 1e12 / peak, 4)
    if calib["mfu_vs_in_program_ceiling"] > 1.0:
        # timing noise can nudge the marginal ratio past 1 even with a shared
        # executable; publish the raw ratio instead of silently clamping
        out["encoder_mfu_raw_ratio"] = round(calib["mfu_vs_in_program_ceiling"], 4)
    return out


# -------------------------------------- config 7: sharded embedded-model parity

_SHARDED_EMBEDDED_CODE = r"""
import json, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from metrics_tpu.models.inception import InceptionFeatureExtractor
from metrics_tpu.image.fid import FID
from metrics_tpu.functional import bert_score

mesh = Mesh(np.asarray(jax.devices()), ("dp",))
out = {"devices": len(jax.devices())}

# --- FID: InceptionV3 forward under shard_map (batch-parallel, feature gather)
IMG, B = 75, 32
plain = InceptionFeatureExtractor(feature="2048", input_size=IMG)
shard = InceptionFeatureExtractor(feature="2048", params=plain.params, input_size=IMG, mesh=mesh)
rng = np.random.RandomState(0)
imgs = jnp.asarray((rng.rand(B, IMG, IMG, 3) * 255).astype(np.uint8))
f_plain = np.asarray(plain(imgs))
t0 = time.perf_counter()
f_shard = np.asarray(shard(imgs))
out["fid_forward_parity_max_abs"] = float(np.max(np.abs(f_shard - f_plain)))
fid = FID(feature=shard, feature_dim=2048)
fid.update(imgs, real=True)
fid.update(jnp.asarray((rng.rand(B, IMG, IMG, 3) * 255).astype(np.uint8)), real=False)
t0 = time.perf_counter()
fid.update(imgs, real=True)
out["fid_sharded_update_imgs_per_s"] = round(B / (time.perf_counter() - t0), 2)
out["fid_value_finite"] = bool(np.isfinite(float(fid.compute())))

# --- BERTScore: encoder under shard_map
def enc(ids, mask):
    freqs = jnp.arange(1, 65, dtype=jnp.float32) / 7.0
    emb = jnp.sin(ids[..., None].astype(jnp.float32) * freqs)
    return emb * mask[..., None].astype(jnp.float32)

preds = [f"the cat tok{i} sat" for i in range(128)]
refs = [f"a dog tok{i+1} ran" for i in range(128)]
base = bert_score(preds, refs, user_forward_fn=enc, max_length=16)
t0 = time.perf_counter()
got = bert_score(preds, refs, user_forward_fn=enc, max_length=16, mesh=mesh)
out["bertscore_sharded_pairs_per_s"] = round(len(preds) / (time.perf_counter() - t0), 1)
out["bertscore_parity_max_abs"] = float(max(
    np.max(np.abs(np.asarray(got[k]) - np.asarray(base[k])))
    for k in ("precision", "recall", "f1")))
print(json.dumps(out))
"""


def bench_sharded_embedded() -> dict:
    """The sharded embedded-model path (VERDICT r4 next #1) executed on the
    8-device virtual mesh: InceptionV3 and a BERTScore encoder run
    batch-parallel under ``shard_map`` (params replicated, features gathered
    in-graph), with sharded == single-device parity reported. Virtual CPU
    devices timeshare the host, so the rates prove liveness, not speedup;
    parity and the compiled sharding are the point (mesh tests:
    ``tests/parallel/test_sharded_embedded.py``)."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SHARDED_EMBEDDED_CODE], env=env,
            capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": "sharded embedded bench timed out"}
    if proc.returncode != 0:
        return {"error": proc.stderr[-500:]}
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    ok = (out.get("fid_forward_parity_max_abs", 1) < 1e-3
          and out.get("bertscore_parity_max_abs", 1) < 1e-5
          and out.get("fid_value_finite"))
    out["parity_ok"] = bool(ok)
    # honest-by-construction: the *_per_s rates above come from 8 virtual CPU
    # devices timesharing ONE host — they prove the sharded program compiles
    # and runs, never a speedup; the parity deltas are the durable facts
    out["liveness_only"] = True
    out["note"] = "virtual CPU mesh timeshares one host; rates are topology liveness, not speedup"
    return out


# ------------------------------------------------ streaming engine steady state (r6)

def bench_engine_steady_state() -> dict:
    """Streaming-engine steady state (ISSUE 2): ragged traffic through the
    AOT-compiled bucketed pipeline on the current backend.

    PINNED protocol: buckets (256, 1024); a fixed-seed stream of 60 ragged
    batches (uniform 32..1024 rows); one warmup stream (pays all compiles),
    then 3 timed repeat streams over the SAME data via ``engine.reset()`` —
    each timed stream must compile NOTHING (asserted; that zero is the
    steady-state serving claim). Reported rate = median rows/s over the 3
    trials with (max-min)/median spread.

    The rate is the host dispatcher's — pad + upload + async dispatch — and on
    a CPU backend (or through the tunnelled-TPU RTT) it is host-noise-bound,
    so it carries ``liveness_only``. The durable facts are the compile-cache
    counters, the padding-waste fraction, and the zero-compile steady state.

    Since r7 the engine's serving defaults include state arenas and megabatch
    coalescing (ISSUE 3) — this entry measures the engine AS SHIPPED; the
    before/after dispatch-amortization ladder is ``engine_dispatch``.
    """
    import time as _time

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import EngineConfig, StreamingEngine

    buckets = (256, 1024)
    n_batches, trials = 60, 3
    rng = np.random.RandomState(20260801)
    sizes = rng.randint(32, 1025, size=n_batches)
    batches = [
        (rng.rand(n).astype(np.float32), (rng.rand(n) > 0.5).astype(np.int32))
        for n in sizes
    ]
    rows_total = int(sum(sizes))

    engine = StreamingEngine(
        MetricCollection([Accuracy(), MeanSquaredError()]),
        EngineConfig(buckets=buckets, telemetry_capacity=256),
    )

    def stream_once() -> float:
        t0 = _time.perf_counter()
        for p, t in batches:
            engine.submit(p, t)
        engine.flush()
        return _time.perf_counter() - t0

    with engine:
        stream_once()     # warmup: all update-program compiles happen here
        engine.result()   # ...and the compute program's
        warm_misses = engine.aot_cache.misses
        times = []
        for _ in range(trials):
            engine.reset()
            times.append(stream_once())
        value = {k: float(v) for k, v in engine.result().items()}
        steady_compiles = engine.aot_cache.misses - warm_misses
        if steady_compiles:
            # fail LOUDLY rather than publish a rate that silently includes
            # compile time — the zero here is the entry's whole claim
            raise RuntimeError(
                f"engine steady state compiled {steady_compiles} programs; "
                "the closed-program contract is broken (AotCache keying regression?)"
            )

    times.sort()
    med = times[len(times) // 2]
    tele = engine.telemetry()
    return {
        "rows_per_s": round(rows_total / med, 1),
        "spread_frac": round((times[-1] - times[0]) / med, 3),
        "trials": trials,
        "batches_per_stream": n_batches,
        "rows_per_stream": rows_total,
        "buckets": list(buckets),
        "padding_waste_fraction": tele["padding_waste_fraction"],
        "compiles_warmup": warm_misses,
        "compiles_steady_state": steady_compiles,  # MUST be 0: the serving claim
        "steady_state_zero_compiles": steady_compiles == 0,
        "queue_depth_max": tele["queue_depth_max"],
        "result_finite": all(np.isfinite(v) for v in value.values()),
        "protocol": (
            "fixed-seed 60-batch ragged stream; 1 warmup stream pays all "
            "compiles, 3 timed repeat streams via reset(); median rows/s, "
            "(max-min)/median spread; zero steady-state compiles asserted"
        ),
        # host dispatcher rate (pad+upload+dispatch): host-noise-bound on CPU
        # and RTT-bound through the TPU tunnel — never a chip-throughput claim
        "liveness_only": True,
        "note": "rate is the host dispatcher's; durable facts are zero steady-state compiles + padding waste",
    }


def bench_engine_dispatch() -> dict:
    """Dispatch-amortized serving (ISSUE 3): steady-state steps/s and
    samples/s at SMALL batches (≤ 64 rows), where per-step host dispatch —
    not device compute — dominates, measured across the three stacked
    optimizations in ONE run:

    * ``baseline``  — PR 2 path: per-leaf state pytree, one dispatch per
      submitted batch (use_arena=False, coalesce=1);
    * ``arena``     — + packed per-dtype state arenas (fewer donated args);
    * ``coalesce``  — + megabatch coalescing (K submissions, one dispatch);
    * ``multistream`` — 8 independent streams served by ONE MultiStreamEngine
      (same total rows, cross-stream megabatches) vs the baseline's
      one-engine-per-stream cost model;
    * ``per_leaf_kernel`` / ``megastep`` (ISSUE 16, TPU only) — the coalesced
      arena engine with PR 4 per-leaf Pallas kernels vs the whole-step fused
      tier; ``speedup_megastep_vs_per_leaf`` is the device-bound small-batch
      acceptance ratio (>=1.5x). Off-TPU compiled Pallas cannot execute, so
      both rungs are reported skipped — the CPU gate for the megastep path is
      interpret parity + the zero-compile/jaxpr pins (kernels-smoke), never a
      timing.

    PINNED protocol (docs/benchmarking.md): fixed-seed 192-batch stream of
    uniform 16..64-row batches against buckets (64, 512) — every batch is
    distinct data, so nothing is loop-invariant; per config one warmup stream
    pays all compiles, then 3 timed repeat streams via ``reset()``, each ended
    by a flush + a host fetch of the computed value (value-fetched timing);
    median samples/s with (max-min)/median spread; zero steady-state compiles
    asserted per config. Rates are the host dispatcher's (host-noise-bound on
    CPU, RTT-bound through the TPU tunnel) → ``liveness_only``; the RATIOS
    between configs are the durable facts — all four share one process, one
    backend, one data stream.
    """
    import time as _time

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import EngineConfig, MultiStreamEngine, StreamingEngine

    buckets = (64, 512)
    n_batches, trials, n_streams = 192, 3, 8
    rng = np.random.RandomState(20260803)
    sizes = rng.randint(16, 65, size=n_batches)
    batches = [
        (rng.rand(n).astype(np.float32), (rng.rand(n) > 0.5).astype(np.int32))
        for n in sizes
    ]
    rows_total = int(sum(sizes))

    def _col():
        return MetricCollection([Accuracy(), MeanSquaredError()])

    def _measure(engine, submit):
        def stream_once() -> float:
            t0 = _time.perf_counter()
            for i, b in enumerate(batches):
                submit(engine, i, b)
            engine.flush()
            # value-fetched: a host scalar that data-depends on the state
            res = engine.result(0) if isinstance(engine, MultiStreamEngine) else engine.result()
            float(next(iter(res.values())) if isinstance(res, dict) else res)
            return _time.perf_counter() - t0

        with engine:
            stream_once()  # warmup: every compile happens here
            warm_misses = engine.aot_cache.misses
            trials_run = []  # (time, steps): coalescing can group differently per trial
            for _ in range(trials):
                engine.reset()
                dt = stream_once()
                trials_run.append((dt, engine.steps))
            steady_compiles = engine.aot_cache.misses - warm_misses
            if steady_compiles:
                raise RuntimeError(
                    f"engine_dispatch steady state compiled {steady_compiles} programs; "
                    "the closed-program contract is broken"
                )
            tele = engine.telemetry()
        trials_run.sort()
        times = [t for t, _ in trials_run]
        # median TRIAL: its own (time, steps) pair, so steps/s is internally
        # consistent even when opportunistic grouping varies across trials
        med, steps_per_stream = trials_run[len(trials_run) // 2]
        shares = tele.get("host_time_shares", {})
        return {
            "samples_per_s": round(rows_total / med, 1),
            "steps_per_s": round(steps_per_stream / med, 1),
            "steps_per_stream": steps_per_stream,
            "spread_frac": round((times[-1] - times[0]) / med, 3),
            "padding_waste_fraction": tele["padding_waste_fraction"],
            "batches_per_step_mean": tele["coalesce"]["batches_per_step_mean"],
            "compiles_steady_state": steady_compiles,
            "regime": shares.get("regime"),
            "dispatch_share": shares.get("dispatch"),
        }

    def _single(engine, _i, b):
        engine.submit(*b)

    def _multi(engine, i, b):
        engine.submit(i % n_streams, *b)

    cfg = lambda **kw: EngineConfig(  # noqa: E731
        buckets=buckets, max_queue=n_batches + 1, telemetry_capacity=512, **kw
    )
    out = {
        "baseline": _measure(StreamingEngine(_col(), cfg(use_arena=False, coalesce=1)), _single),
        "arena": _measure(StreamingEngine(_col(), cfg(use_arena=True, coalesce=1)), _single),
        "coalesce": _measure(StreamingEngine(_col(), cfg(use_arena=True, coalesce=16)), _single),
        "multistream": _measure(
            MultiStreamEngine(_col(), num_streams=n_streams, config=cfg(coalesce=16)), _multi
        ),
    }
    # megastep vs per-leaf kernels (ISSUE 16): same ladder, same data — the
    # device-bound small-batch claim. Compiled Pallas only exists on TPU.
    from metrics_tpu.ops.kernels import resolve_backend

    if resolve_backend("auto") == "pallas":
        out["per_leaf_kernel"] = _measure(
            StreamingEngine(_col(), cfg(coalesce=16, kernel_backend="pallas")), _single
        )
        out["megastep"] = _measure(
            StreamingEngine(_col(), cfg(coalesce=16, kernel_backend="megastep")), _single
        )
        out["speedup_megastep_vs_per_leaf"] = round(
            out["megastep"]["samples_per_s"] / out["per_leaf_kernel"]["samples_per_s"], 3
        )
        out["meets_1p5x_bar"] = out["speedup_megastep_vs_per_leaf"] >= 1.5
    else:
        out["megastep"] = {
            "skipped": "compiled Pallas needs a TPU backend; the megastep CPU "
            "gate is interpret parity + zero-compile/jaxpr pins (kernels-smoke)"
        }
    base_sps = out["baseline"]["samples_per_s"]
    return {
        **out,
        # the acceptance ratio: full stack (arena+coalescing) vs the
        # uncoalesced per-leaf-pytree path, same run, same data
        "speedup_arena": round(out["arena"]["samples_per_s"] / base_sps, 3),
        "speedup_arena_plus_coalesce": round(out["coalesce"]["samples_per_s"] / base_sps, 3),
        # multistream marginal: what 8 streams cost through ONE engine vs what
        # the baseline engine achieves on the same rows for one stream (an
        # 8-engine deployment would also multiply threads/programs/memory)
        "speedup_multistream_vs_baseline": round(out["multistream"]["samples_per_s"] / base_sps, 3),
        "coalesce_marginal_over_arena": round(
            out["coalesce"]["samples_per_s"] / out["arena"]["samples_per_s"], 3
        ),
        "rows_per_stream": rows_total,
        "batches_per_stream": n_batches,
        "batch_rows_range": [16, 64],
        "buckets": list(buckets),
        "trials": trials,
        "num_streams": n_streams,
        "protocol": (
            "fixed-seed 192-batch stream, 16..64 rows/batch, buckets (64,512); per "
            "config: 1 warmup stream pays all compiles, 3 timed repeat streams via "
            "reset(), value-fetched; median samples/s, (max-min)/median spread; zero "
            "steady-state compiles asserted per config"
        ),
        "liveness_only": True,
        "note": (
            "rates are the host dispatcher's; the durable facts are the config "
            "RATIOS (shared process/backend/data) + zero steady-state compiles"
        ),
    }


# ------------------------------------------- config: engine mesh dispatch (r8)

def bench_engine_mesh_dispatch() -> dict:
    """Mesh steady state (ISSUE 5): step-sync vs deferred-sync engine rate on
    the 8-device mesh, in ONE subprocess run (``metrics_tpu/engine/mesh_bench``
    owns the pinned protocol — interleaved stream pairs, value-fetched, zero
    steady compiles asserted per mode; docs/benchmarking.md "Mesh steady state
    (r8)"). Runs on the virtual 8-device CPU mesh (the same topology the
    driver's multichip dryrun checks) → absolute rates carry ``liveness_only``;
    the durable facts are the step-vs-deferred ratios: the engine-level
    aggregate and ``steady_step_latency`` — the per-step executable latency
    pair, which isolates the in-step collective deferred sync deletes."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "metrics_tpu.engine.mesh_bench"],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": "engine_mesh_dispatch timed out"}
    if proc.returncode != 0:
        return {"error": proc.stderr[-500:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


# ------------------------------------------- config: stream capacity (r10)

def bench_stream_capacity() -> dict:
    """Stream-sharded multi-tenant capacity (ISSUE 9): S=10^4 Zipfian streams
    on the 8-device virtual mesh behind a resident=16/shard paged arena, in
    ONE subprocess run (``metrics_tpu/engine/stream_bench`` owns the pinned
    protocol — ratios-in-one-run; docs/benchmarking.md "Stream capacity
    (r10)"). Absolute rates carry ``liveness_only``; the durable facts:
    per-shard resident state is (world, resident, n) rows exactly, the
    same-S unsharded deferred engine carries S/resident x the device bytes
    (measured, not modeled), zero steady compiles after warmup, and the
    p50/p99 ``result()`` pair under the Zipfian law."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "metrics_tpu.engine.stream_bench"],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": "stream_capacity timed out"}
    if proc.returncode != 0:
        return {"error": proc.stderr[-500:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


# ------------------------------------------------- config: fleet sync (r16)

def bench_fleet_sync() -> dict:
    """Multi-host fleet boundary sync (ISSUE 15): 2 real OS processes over
    ``jax.distributed`` (gloo CPU collectives), in ONE subprocess run
    (``metrics_tpu/engine/fleet/fleet_bench`` owns the protocol — both
    ``sync_precision`` policies measured by the same worker in one runtime,
    ratios-in-one-run). Reports the fleet fold latency pair (exact vs
    ``q8_block``), the analytic per-fold payload bytes + ratio, and
    streams-per-host at 2 hosts. Loopback sockets, no interconnect → every
    rate carries ``liveness_only``; the durable facts are the payload ratio
    and the single-collective fold."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # workers are single-device CPU processes
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "metrics_tpu.engine.fleet.fleet_bench"],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": "fleet_sync timed out"}
    if proc.returncode != 0:
        return {"error": proc.stderr[-500:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


# ---------------------------------------------- config: fleet tenancy (r20)

def bench_fleet_tenancy() -> dict:
    """Fleet-scale tenancy (ISSUE 20): stream-sharded windowed fleet hosts
    swept over a 16x stream-count range with a fixed resident arena —
    device-resident bytes per host must stay FLAT while host-RAM spill rows
    grow — plus the hierarchical fold's per-leg byte accounting at 2 hosts
    (exact vs ``q8_block``, from the engine's own ``_fleet_leaf_info``) and
    the ``q8_sum_error_bound`` oracle asserted on the real post-traffic
    state. Single-process protocol (``fleet_bench tenancy`` owns it): the
    residency and byte facts are analytic/deterministic, no interconnect
    involved, so nothing here is a rate at all."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "metrics_tpu.engine.fleet.fleet_bench",
             "tenancy"],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": "fleet_tenancy timed out"}
    if proc.returncode != 0:
        return {"error": proc.stderr[-500:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


# ---------------------------------------------- config: ragged serving (r17)

def bench_ragged_serving() -> dict:
    """Group-keyed ragged serving (ISSUE 17): G=512 Zipfian query groups of
    retrieval traffic through a deferred-mesh ``RaggedEngine``, in ONE
    subprocess run (``metrics_tpu/engine/ragged_bench`` owns the pinned
    protocol — queries/s over the ingest+aggregate wall, the eager host-loop
    baseline measured in the same process, ratios-in-one-run). Absolute
    rates on the virtual mesh carry ``liveness_only``; the durable facts are
    the ASSERTED zero steady-state compiles over a reset()+replay, the
    served/eager value agreement, and the Zipf hot-group capacity shape."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "metrics_tpu.engine.ragged_bench"],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": "ragged_serving timed out"}
    if proc.returncode != 0:
        return {"error": proc.stderr[-500:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


# --------------------------------------------- config: model serving (r19)

def bench_model_serving() -> dict:
    """Embedded-model serving (ISSUE 19): imgs/s (InceptionV3 features) and
    pairs/s (text-encoder forwards) through the resident ``ModelHost`` vs the
    monolithic per-metric forward, in ONE subprocess run
    (``metrics_tpu/engine/model_bench`` owns the pinned protocol —
    fixed-seed ragged streams, warmup pays every compile, interleaved timed
    passes, zero steady compiles asserted HARD on the host path, MFU
    attribution from the PR 1 cost walk over the served bucket program).
    CPU rates carry ``liveness_only``; the durable facts are the
    host-vs-monolithic ratios, the closed program set (one program per
    bucket vs one per distinct raw shape), and the compile assertion."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "metrics_tpu.engine.model_bench"],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": "model_serving timed out"}
    if proc.returncode != 0:
        return {"error": proc.stderr[-500:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


# ---------------------------------------------- config: tracing overhead (r9)

def bench_obs_overhead() -> dict:
    """Flight-recorder overhead (ISSUE 8): the steady-step marginal of
    tracing enabled vs disabled, with the disabled-path ≤1% guard.

    PINNED protocol: buckets (256,), coalesce off (no grouping ambiguity);
    a fixed-seed stream of 40 uniform 256-row batches (zero padding, one
    step per batch); per config one warmup stream (pays every compile), then
    5 timed repeat streams via ``reset()``, A/B-interleaved per trial so
    host drift hits both configs alike; per-step wall = median stream time /
    batches. Host-noise-bound on CPU → rates carry ``liveness_only``; the
    durable fact is the guard.

    The disabled path's contract is "zero work beyond a None check per
    consult site" — there is no no-plumbing twin to measure against at
    runtime, and NO off/on timing comparison can detect work leaking onto
    the off path (unconditional leaked work runs in both configs, cancels
    in the A/B, and INFLATES this guard's denominator). So two guards are
    asserted, each covering what the other cannot:

    * **cost-model bound** — the measured cost of one attribute-load +
      ``is not None`` test (timeit, 1e6 reps) times the consult sites per
      steady step (8: submit, id-pop, group, pad, and the step body's
      aot/step/sync/histogram gates) must be ≤1% of the measured
      disabled-path step wall: the contract's by-construction cost is
      negligible.
    * **structural leak guard** — a short disabled-path run under a
      per-thread call profiler: NOTHING from ``metrics_tpu/engine/trace.py``
      may execute while tracing is off. This is the fireable detector for
      recorder machinery reached past a missing ``None`` gate.

    The ENABLED marginal (≈6 span records + 2 histogram appends per step)
    is reported, not asserted.
    """
    import time as _time
    import timeit as _timeit

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import EngineConfig, StreamingEngine, TraceRecorder

    n_batches, trials, rows = 40, 5, 256
    rng = np.random.RandomState(20260803)
    batches = [
        (rng.rand(rows).astype(np.float32), (rng.rand(rows) > 0.5).astype(np.int32))
        for _ in range(n_batches)
    ]

    def make(trace):
        return StreamingEngine(
            MetricCollection([Accuracy(), MeanSquaredError()]),
            EngineConfig(buckets=(rows,), coalesce=1, telemetry_capacity=64, trace=trace),
        )

    # one recorder for all enabled streams: ring eviction is part of the
    # steady-state cost being measured, and the capacity bound keeps memory flat
    engine_off = make(None).start()
    engine_on = make(TraceRecorder(capacity=4096)).start()

    def stream_once(engine) -> float:
        t0 = _time.perf_counter()
        for p, t in batches:
            engine.submit(p, t)
        engine.flush()
        return _time.perf_counter() - t0

    try:
        for engine in (engine_off, engine_on):
            stream_once(engine)  # warmup: every compile lands here
            engine.result()
        times_off, times_on = [], []
        for _ in range(trials):  # interleaved A/B: drift hits both alike
            engine_off.reset()
            times_off.append(stream_once(engine_off))
            engine_on.reset()
            times_on.append(stream_once(engine_on))
    finally:
        engine_off.stop()
        engine_on.stop()

    times_off.sort()
    times_on.sort()
    med_off, med_on = times_off[trials // 2], times_on[trials // 2]
    step_us_off = med_off / n_batches * 1e6
    step_us_on = med_on / n_batches * 1e6
    marginal = (med_on - med_off) / med_off
    spread_off = (times_off[-1] - times_off[0]) / med_off

    # first-principles disabled-path guard: per-check cost x sites per step
    class _Gate:
        pass

    gate = _Gate()
    gate._trace = None
    reps = 1_000_000
    per_check_us = (
        _timeit.timeit("tr = gate._trace\nif tr is not None:\n    pass",
                       globals={"gate": gate}, number=reps)
        / reps * 1e6
    )
    # 8 trace consult sites + the admission check per submit + the ladder
    # check per group (ISSUE 11) — both None-gated exactly like tracing —
    # plus the window layer's gates (ISSUE 13): the pane-prepend check per
    # padded step and the two rotation-cadence gates per group
    sites_per_step = 13
    disabled_frac = per_check_us * sites_per_step / step_us_off
    if disabled_frac > 0.01:
        # the cost-model bound: the by-construction cost of the contract
        # (one None check per consult site) must be negligible. This bound
        # alone cannot catch work LEAKING onto the off path — leaked work
        # inflates step_us_off and shrinks this fraction — which is what
        # the structural guard below exists for.
        raise RuntimeError(
            f"disabled-path tracing overhead {disabled_frac:.2%} of a "
            f"{step_us_off:.0f}µs steady step exceeds the 1% guard "
            f"({sites_per_step} sites x {per_check_us:.4f}µs/check)"
        )

    # structural leak guard: with tracing off AND no admission policy/
    # ladder/window/drift configured, no code from the trace, admission,
    # windows, or tracker modules may run on the hot path (the ISSUE 13
    # disabled-path contract extends PR 8's and PR 11's: one `is not None`
    # check per site, nothing else). A per-thread
    # call profiler (armed BEFORE the probe engine spawns its dispatcher
    # thread) watches a short off-path stream; any call into either module
    # is a leak past a missing None gate.
    import sys as _sys
    import threading as _threading

    from metrics_tpu.engine import admission as _admission_mod
    from metrics_tpu.engine import trace as _trace_mod
    from metrics_tpu.engine import tracker as _tracker_mod
    from metrics_tpu.engine import windows as _windows_mod

    _watched_files = {
        _trace_mod.__file__, _admission_mod.__file__,
        _windows_mod.__file__, _tracker_mod.__file__,
    }
    leaks: list = []

    def _profiler(frame, event, arg):
        if event == "call" and frame.f_code.co_filename in _watched_files:
            leaks.append(frame.f_code.co_name)

    probe = make(None)
    _threading.setprofile(_profiler)
    _sys.setprofile(_profiler)
    try:
        probe.start()
        for p, t in batches[:5]:
            probe.submit(p, t)
        probe.flush()
    finally:
        _sys.setprofile(None)
        _threading.setprofile(None)
        probe.stop()
    if leaks:
        raise RuntimeError(
            "disabled-path hot path executed trace/admission/window-module code: "
            f"{sorted(set(leaks))[:5]} — work leaked past a None gate"
        )

    return {
        "steady_step_us_disabled": round(step_us_off, 1),
        "steady_step_us_enabled": round(step_us_on, 1),
        "enabled_marginal_frac": round(marginal, 4),
        "disabled_guard_frac": round(disabled_frac, 6),
        "disabled_guard_ok": True,  # both guards asserted above; False never returns
        "structural_leak_guard_ok": True,
        "none_check_us": round(per_check_us, 5),
        "consult_sites_per_step": sites_per_step,
        "trials": trials,
        "batches_per_stream": n_batches,
        "spread_frac_disabled": round(spread_off, 3),
        "protocol": (
            "fixed-seed 40x256-row stream, buckets (256,), coalesce off; 1 "
            "warmup + 5 timed repeat streams per config, A/B interleaved; "
            "median per-step wall; asserted guards: (1) cost model - measured "
            "None-check cost x 13 sites (trace + admission/ladder + window "
            "gates) <= 1% of the disabled step; (2) structural - a profiled "
            "off-path run executes zero trace-, admission-, window-, or "
            "tracker-module code (timing A/B cannot see leaked "
            "unconditional work)"
        ),
        # host dispatcher walls on CPU: noise-bound — the guards are the claim
        "liveness_only": True,
        "note": "durable fact: tracing off = None checks only (cost model + structural guard asserted); enabled marginal reported",
    }


# ------------------------------------------------ config: kernel microbench (r7)

def bench_kernel_microbench() -> dict:
    """ISSUE 4: the three streaming-update Pallas kernels vs the XLA reference
    path, each ratio measured IN ONE RUN (same process, same backend, same
    data) under the r5/r7 pinned protocol:

    * per kernel and per path, the workload runs as a dynamic-trip-count
      ``fori_loop`` epoch inside ONE AOT-compiled executable with loop-variant
      inputs (``jnp.roll`` by the iteration index — same content, new value,
      nothing hoistable); the SAME executable serves both K values, so the
      K-pair marginal ``(t(K2) - t(K1)) / (K2 - K1)`` cancels dispatch/RTT
      and measures pure per-iteration device time;
    * both paths are compiled ahead of time via ``lower().compile()`` and
      only those executables are invoked in the timed region — steady-state
      compiles are zero BY CONSTRUCTION, asserted via the jit cache-miss
      counters where available;
    * timing is value-fetched (the epoch's final state is fetched to host);
    * per kernel, 3 trial pairs → median marginal + (max-min)/median spread,
      and the two paths' outputs are parity-checked in the same run.

    Workloads (sized for the serving regime the kernels target):
    ``fold_sum`` — masked row-delta fold, 16k rows x 256 lanes f32;
    ``segment_min`` — masked segment-min into 32 streams (XLA lowers this to
    a serialized scatter-min, the kernel to a compare-select sweep);
    ``histogram_counts`` — 256k-row bincount into 256 bins (XLA scatter-add
    vs the kernel's one-hot MXU contraction);
    ``megastep_fold`` (ISSUE 16) — the whole-arena fused fold (ONE launch
    folds an 8-leaf packed arena with a mixed sum/min/max opcode row) against
    the PR 4 shape of the same update: 8 per-leaf ``fold_rows_masked``
    launches + the XLA concatenate re-pack. Both forms run on the SAME
    backend in one run, so ``fused_vs_per_leaf`` is the launch-amortization
    ratio the megastep tier claims (off-TPU both compile to XLA, where the
    ratio only shows XLA's own fusion — the device claim needs the TPU run).

    Off-TPU the compiled-Pallas path does not exist: the entry measures the
    XLA path alone and says so (``kernel_path_skipped``) — interpret mode is
    a correctness tool, timing it would be noise.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu.ops.kernels import (
        fold_rows_masked,
        histogram_accumulate,
        resolve_backend,
        segment_reduce_masked,
        use_backend,
    )

    on_tpu = resolve_backend("auto") == "pallas"
    k_pair = (4, 16)
    trials = 3
    rng = np.random.RandomState(20260803)

    def _epoch_time(compiled, args, k: int) -> float:
        best = None
        for _ in range(2):
            t0 = time.perf_counter()
            out = compiled(*args, jnp.int32(k))
            np.asarray(jax.tree_util.tree_leaves(out)[0])  # value-fetched
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    def _measure_paths(make_epoch, args, abstract_args):
        """Compile the epoch under each backend (fresh closure per backend —
        JAX caches traces by function identity) and K-pair-time both."""
        paths = {"xla": "xla"}
        if on_tpu:
            paths["kernel"] = "pallas"
        compiled, outputs = {}, {}
        k_abs = jax.ShapeDtypeStruct((), jnp.int32)
        for name, backend in paths.items():
            epoch = make_epoch()  # fresh function object per backend
            with use_backend(backend):
                compiled[name] = jax.jit(epoch).lower(*abstract_args, k_abs).compile()
            outputs[name] = np.asarray(
                jax.tree_util.tree_leaves(compiled[name](*args, jnp.int32(1)))[0]
            )
        result = {}
        for name, prog in compiled.items():
            _epoch_time(prog, args, k_pair[0])  # warm
            marginals = []
            for _ in range(trials):
                t1 = _epoch_time(prog, args, k_pair[0])
                t2 = _epoch_time(prog, args, k_pair[1])
                marginals.append((t2 - t1) / (k_pair[1] - k_pair[0]))
            marginals.sort()
            med = marginals[len(marginals) // 2]
            result[name] = {
                "per_iter_us": round(med * 1e6, 1),
                "spread_frac": round((marginals[-1] - marginals[0]) / max(med, 1e-12), 3),
            }
        if "kernel" in result:
            result["speedup_vs_xla"] = round(
                result["xla"]["per_iter_us"] / max(result["kernel"]["per_iter_us"], 1e-9), 3
            )
            err = float(
                np.max(np.abs(outputs["kernel"].astype(np.float64) - outputs["xla"].astype(np.float64)))
            )
            scale = float(np.max(np.abs(outputs["xla"].astype(np.float64)))) or 1.0
            result["parity_max_rel_err"] = round(err / scale, 9)
        return result

    out = {"backend": jax.default_backend(), "k_pair": list(k_pair), "trials": trials}

    # -- fold_sum: masked row-delta fold, (16384, 256) f32
    n, f = 16384, 256
    rows = jnp.asarray(rng.randn(n, f).astype(np.float32))
    state = jnp.asarray(rng.randn(f).astype(np.float32))
    mask = jnp.asarray(rng.rand(n) > 0.25)

    def make_fold_epoch():
        def epoch(st, rws, mk, k):
            def body(i, acc):
                return fold_rows_masked(acc, jnp.roll(rws, i, axis=0), mk, "sum")

            return jax.lax.fori_loop(0, k, body, st)

        return epoch

    try:
        out["fold_sum"] = _measure_paths(
            make_fold_epoch, (state, rows, mask),
            tuple(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in (state, rows, mask)),
        )
    except Exception as e:  # one kernel's failure must not cost the others
        out["fold_sum"] = {"error": str(e)[:200]}

    # -- segment_min: (16384, 8) rows into 32 streams
    n, f, s = 16384, 8, 32
    rows_s = jnp.asarray(rng.randn(n, f).astype(np.float32))
    state_s = jnp.asarray(rng.randn(s, f).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, s, n).astype(np.int32))
    mask_s = jnp.asarray(rng.rand(n) > 0.25)

    def make_segment_epoch():
        def epoch(st, rws, mk, sid, k):
            def body(i, acc):
                return segment_reduce_masked(
                    acc, jnp.roll(rws, i, axis=0), mk, jnp.roll(sid, i), s, "min"
                )

            return jax.lax.fori_loop(0, k, body, st)

        return epoch

    try:
        out["segment_min"] = _measure_paths(
            make_segment_epoch,
            (state_s, rows_s, mask_s, ids),
            tuple(
                jax.ShapeDtypeStruct(x.shape, x.dtype)
                for x in (state_s, rows_s, mask_s, ids)
            ),
        )
    except Exception as e:
        out["segment_min"] = {"error": str(e)[:200]}

    # -- histogram_counts: 262144 indices into 256 bins
    n, length = 1 << 18, 256
    idx = jnp.asarray(rng.randint(0, length, n).astype(np.int32))

    def make_hist_epoch():
        def epoch(ix, k):
            def body(i, acc):
                return acc + histogram_accumulate(jnp.roll(ix, i), length)

            return jax.lax.fori_loop(0, k, body, jnp.zeros((length,), jnp.int32))

        return epoch

    try:
        out["histogram_counts"] = _measure_paths(
            make_hist_epoch, (idx,), (jax.ShapeDtypeStruct(idx.shape, idx.dtype),)
        )
    except Exception as e:
        out["histogram_counts"] = {"error": str(e)[:200]}

    # -- megastep_fold (ISSUE 16): fused whole-arena fold vs 8 per-leaf folds,
    #    (16384, 8x32) packed f32 arena, mixed per-leaf reductions
    from metrics_tpu.ops.kernels import megastep_fold

    n, n_leaves, f_leaf = 16384, 8, 32
    f_total = n_leaves * f_leaf
    rows_m = jnp.asarray(rng.randn(n, f_total).astype(np.float32))
    state_m = jnp.asarray(rng.randn(f_total).astype(np.float32))
    mask_m = jnp.asarray(rng.rand(n) > 0.25)
    leaf_ops = [("sum", "min", "max")[j % 3] for j in range(n_leaves)]
    op_row = np.repeat(np.asarray([j % 3 for j in range(n_leaves)], np.int32), f_leaf)

    def make_fused_epoch():
        def epoch(st, rws, mk, k):
            def body(i, acc):
                return megastep_fold(acc, jnp.roll(rws, i, axis=0), mk, op_row)

            return jax.lax.fori_loop(0, k, body, st)

        return epoch

    def make_per_leaf_epoch():
        # the PR 4 shape: one kernel launch per leaf + an XLA concatenate pack
        def epoch(st, rws, mk, k):
            def body(i, acc):
                r = jnp.roll(rws, i, axis=0)
                parts = [
                    fold_rows_masked(
                        acc[j * f_leaf:(j + 1) * f_leaf],
                        r[:, j * f_leaf:(j + 1) * f_leaf],
                        mk,
                        leaf_ops[j],
                    )
                    for j in range(n_leaves)
                ]
                return jnp.concatenate(parts)

            return jax.lax.fori_loop(0, k, body, st)

        return epoch

    try:
        backend_m = "pallas" if on_tpu else "xla"
        abstract_m = tuple(
            jax.ShapeDtypeStruct(x.shape, x.dtype) for x in (state_m, rows_m, mask_m)
        )
        k_abs = jax.ShapeDtypeStruct((), jnp.int32)
        mega = {"backend_measured": backend_m}
        outs_m = {}
        for name, mk_ep in (("fused", make_fused_epoch), ("per_leaf", make_per_leaf_epoch)):
            with use_backend(backend_m):
                prog = jax.jit(mk_ep()).lower(*abstract_m, k_abs).compile()
            outs_m[name] = np.asarray(prog(state_m, rows_m, mask_m, jnp.int32(1)))
            _epoch_time(prog, (state_m, rows_m, mask_m), k_pair[0])  # warm
            marginals = []
            for _ in range(trials):
                t1 = _epoch_time(prog, (state_m, rows_m, mask_m), k_pair[0])
                t2 = _epoch_time(prog, (state_m, rows_m, mask_m), k_pair[1])
                marginals.append((t2 - t1) / (k_pair[1] - k_pair[0]))
            marginals.sort()
            med = marginals[len(marginals) // 2]
            mega[name] = {
                "per_iter_us": round(med * 1e6, 1),
                "spread_frac": round((marginals[-1] - marginals[0]) / max(med, 1e-12), 3),
            }
        err = float(np.max(np.abs(
            outs_m["fused"].astype(np.float64) - outs_m["per_leaf"].astype(np.float64)
        )))
        scale = float(np.max(np.abs(outs_m["per_leaf"].astype(np.float64)))) or 1.0
        mega["parity_max_rel_err"] = round(err / scale, 9)
        mega["fused_vs_per_leaf"] = round(
            mega["per_leaf"]["per_iter_us"] / max(mega["fused"]["per_iter_us"], 1e-9), 3
        )
        if not on_tpu:
            mega["note"] = (
                "both forms compiled to XLA off-TPU; the fused form's XLA twin "
                "computes every reduction then selects per column, so a ratio "
                "below 1 here is expected and is NOT the megastep "
                "launch-amortization claim (that ratio is TPU-only)"
            )
        out["megastep_fold"] = mega
    except Exception as e:
        out["megastep_fold"] = {"error": str(e)[:200]}

    speedups = [
        v.get("speedup_vs_xla")
        for v in out.values()
        if isinstance(v, dict) and v.get("speedup_vs_xla") is not None
    ]
    if speedups:
        out["best_speedup_vs_xla"] = max(speedups)
        out["meets_1p5x_bar"] = max(speedups) >= 1.5
    else:
        out["kernel_path_skipped"] = (
            "compiled Pallas needs a TPU backend; XLA path measured alone "
            "(interpret mode is a correctness tool, not a perf claim)"
        )
        out["liveness_only"] = True
    out["protocol"] = (
        "per kernel+path: ONE AOT executable, dynamic-trip fori_loop epoch, "
        "loop-variant (rolled) inputs, value-fetched timing; K-pair marginal "
        f"(t({k_pair[1]})-t({k_pair[0]}))/{k_pair[1] - k_pair[0]} cancels dispatch/"
        "RTT; 3 trial pairs, median + spread; both paths in one run, parity "
        "checked on the same inputs; zero steady compiles by construction "
        "(only precompiled executables run in the timed region)"
    )
    return out


# --------------------------------------------- config 1: README Accuracy (CPU, 1 proc)

_README_ACC_CODE = r"""
import json, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from metrics_tpu import Accuracy

rng = np.random.RandomState(0)
preds = jnp.asarray(rng.rand(4096, 10).astype(np.float32))
target = jnp.asarray(rng.randint(0, 10, 4096))
acc = Accuracy()
for _ in range(5):
    acc(preds, target)
acc.reset()
t0 = time.perf_counter()
for _ in range(30):
    acc(preds, target)
v = float(acc.compute())
dt = time.perf_counter() - t0
assert 0 <= v <= 1
print(json.dumps({"sps": 30 * 4096 / dt}))
"""


def bench_readme_accuracy_cpu() -> dict:
    """BASELINE config 1: the README ``Accuracy()`` forward loop, CPU, single
    process — ours (stateful facade, delta-merge forward) vs the reference's
    double-update forward on torch CPU."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _README_ACC_CODE], env=env, capture_output=True,
            text=True, timeout=600, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        ours = json.loads(proc.stdout.strip().splitlines()[-1])["sps"] if proc.returncode == 0 else float("nan")
    except subprocess.TimeoutExpired:
        ours = float("nan")

    def run_ref():
        import torch

        from torchmetrics import Accuracy as TAccuracy

        rng = np.random.RandomState(0)
        preds = torch.from_numpy(rng.rand(4096, 10).astype(np.float32))
        target = torch.from_numpy(rng.randint(0, 10, 4096))
        acc = TAccuracy()
        for _ in range(5):
            acc(preds, target)
        acc.reset()
        t0 = time.perf_counter()
        for _ in range(30):
            acc(preds, target)
        acc.compute()
        return 30 * 4096 / (time.perf_counter() - t0)

    ref = _with_reference(run_ref)
    return {
        "value": round(ours, 1) if np.isfinite(ours) else None,
        "unit": "samples/s (CPU, forward loop)",
        "vs_baseline": round(ours / ref, 3) if np.isfinite(ours) and np.isfinite(ref) and ref > 0 else None,
    }


# -------------------------------------------------------------------- config 5: FID

# peak dense FLOP/s per JAX device, bf16 MXU (Cloud TPU published board numbers
# divided out; v2/v3 expose one device per CORE, v4+ one per chip). f32 peak is
# lower (f32 runs as multi-pass bf16 on the MXU), so mfu-vs-bf16-peak is a
# conservative lower bound on how busy the MXU actually is.
_PEAK_FLOPS = {
    "tpu v2": 22.5e12,   # 180 TF/board / 8 cores
    "tpu v3": 52.5e12,   # 420 TF/board / 8 cores
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,
    "tpu v5e": 197e12,
    "tpu v5": 459e12,    # v5p
    "tpu v6 lite": 918e12,
    "tpu v6e": 918e12,
}


def _peak_flops() -> "tuple[float, str] | tuple[None, str]":
    import jax

    kind = jax.devices()[0].device_kind.lower()
    # longest matching key wins ("tpu v5 lite" before "tpu v5")
    best = None
    for k, v in _PEAK_FLOPS.items():
        if k in kind and (best is None or len(k) > len(best[0])):
            best = (k, v)
    if best:
        return best[1], kind
    return None, kind


def _compiled_flops(fn, *args) -> "float | None":
    """XLA's own FLOP estimate for jit(fn)(*args); None when unavailable."""
    import jax

    try:
        cost = jax.jit(fn).lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", -1.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def _mfu_fields(flops_per_item: "float | None", items_per_s: float, model: str) -> dict:
    out = {}
    if flops_per_item is None:
        out["flop_model"] = f"{model}: XLA cost_analysis unavailable"
        return out
    achieved = flops_per_item * items_per_s
    out["achieved_tflops"] = round(achieved / 1e12, 3)
    out["flops_per_item"] = round(flops_per_item / 1e9, 3)  # GFLOPs
    peak, kind = _peak_flops()
    out["device_kind"] = kind
    if peak is not None:
        out["mfu"] = round(achieved / peak, 4)
        out["peak_tflops_bf16"] = round(peak / 1e12, 1)
    else:
        out["mfu"] = None
        out["note_mfu"] = "device kind not in peak table; achieved_tflops still valid"
    measured = _CALIB.get("measured_matmul_tflops_bf16")
    if measured:
        ratio = achieved / (measured * 1e12)
        if ratio <= 1.0:
            # fraction of what the chip DEMONSTRABLY sustains on pure bf16
            # matmul (the honest roofline; the table peak is the nominal one)
            out["mfu_vs_measured_matmul"] = round(ratio, 4)
        else:
            # A utilization > 1 is physically impossible (VERDICT r5 flagged
            # exactly this) — and here it is also NOT a utilization: the
            # ceiling was calibrated in a SEPARATE executable, and the bench
            # tunnel can route executables to a heterogeneous accelerator
            # pool, so workload and ceiling may have hit different chips. The
            # r5-protocol attribution (loop-variant epochs, value-fetched
            # timing, K-pair marginals) is preserved on both sides; the ratio
            # is published as measured-vs-model with the gap explained, never
            # as an impossible "mfu_*" figure. Same-chip-by-construction MFU
            # lives in single_program_calibration (bertscore_base).
            out["measured_vs_model_ratio"] = round(ratio, 4)
            out["measured_vs_model_note"] = (
                "achieved rate (FLOP model x items/s) exceeds this process's "
                f"calibrated bf16 matmul ceiling ({measured:.1f} TF/s); ceiling and "
                "workload ran as separate executables, which the tunnel may route "
                "to different accelerators of a heterogeneous pool — ratio is "
                "measured-vs-model attribution, not a utilization; see "
                "docs/benchmarking.md 'Attributed MFU protocol'"
            )
    out["flop_model"] = model
    return out


def bench_fid() -> dict:
    from functools import partial

    import jax
    import jax.numpy as jnp

    from metrics_tpu import FrechetInceptionDistance
    from metrics_tpu.models.inception import (
        InceptionV3,
        fold_preprocess_into_params,
        pad_stem_params,
    )

    rng = np.random.RandomState(0)
    B = 256
    # DEVICE-RESIDENT batch, shipped once — re-sending it per call over the
    # tunnelled TPU measures the link, not the chip (BENCH_r03's 42 imgs/s bug)
    imgs = jnp.asarray((rng.rand(B, 299, 299, 3) * 255).astype(np.uint8))
    jax.block_until_ready(imgs)

    # K chained updates inside ONE compiled fori_loop (the pattern real TPU
    # eval loops use, tests/image/test_fid_streaming.py): a single dispatch
    # whose wall time is pure device compute. Timing an eager python update
    # loop over the tunnelled remote device proved unreliable — per-call
    # dispatch/readiness effects swing the apparent imgs/s several-fold
    # between runs, in both directions.
    K = 10

    # Inception params enter the epoch as RUNTIME ARGUMENTS via a trace-time
    # holder: a closure capture would inline all 23M weights into the program
    # as constants (~95MB of HLO — the batch-1024 sweep hit the tunnel's
    # remote-compile 413 size limit exactly this way in the first r5 run).
    module_f32 = InceptionV3()
    params = jax.jit(module_f32.init)(jax.random.PRNGKey(0), jnp.zeros((1, 299, 299, 3)))
    jax.block_until_ready(params)

    def make_fid(compute_dtype=None, optimized=False):
        # optimized = the profiler-directed forward (tools/profile_hlo.py, r6):
        # the (x-128)/128 preprocess folded into conv0's params and the
        # <=96-channel stem convs zero-padded to the 128-lane MXU width — both
        # exact param-space rewrites (tests/image/test_inception_mxu_opt.py).
        # The transforms run on the CANONICAL params inside the compiled
        # epoch; they are loop-invariant pads/sums XLA hoists out of the loop.
        if optimized:
            module = InceptionV3(
                compute_dtype=compute_dtype, preprocess_folded=True, stem_lanes=128
            )
        elif compute_dtype is None:
            module = module_f32
        else:
            module = InceptionV3(compute_dtype=compute_dtype)
        holder = {}

        def extract(x):
            p = holder["p"]
            if optimized:
                p = pad_stem_params(fold_preprocess_into_params(p))
            return module.apply(p, x)["2048"].astype(jnp.float32)

        return FrechetInceptionDistance(feature=extract, feature_dim=2048), holder

    # FLOP model: XLA's own count for the compiled inception forward (params
    # as args — small program); fallback = the standard analytic InceptionV3
    # count, 5.7 GMACs * 2. Needed up front for the trial plausibility filter.
    flops_total = _compiled_flops(
        lambda p, x: module_f32.apply(p, x)["2048"], params, imgs
    )
    per_img = flops_total / B if flops_total else 2 * 5.71e9
    peak_flops, _ = _peak_flops()

    rtt = _calibration()["rtt_s"]

    def run_epoch_trials(fid_obj, holder, batch_imgs=None):
        ep_imgs = imgs if batch_imgs is None else batch_imgs
        ep_b = ep_imgs.shape[0]

        # DONATE the streaming-stat state: FID's float-float covariance
        # buffers are 4 x 2048^2 f32 (~67 MB) per distribution — donation lets
        # XLA merge in place instead of double-buffering every iteration
        # (CPU doesn't implement donation and warns, so gate on backend)
        donate = (2,) if jax.default_backend() != "cpu" else ()

        @partial(jax.jit, donate_argnums=donate)
        def epoch(p, batch, state):
            # params AND the image batch are runtime args — closed over, both
            # become HLO constants (23M params + a 274MB uint8 batch at 1024:
            # instant 413 on the tunnel's remote-compile)
            holder["p"] = p  # trace-time rebind

            def body(i, s):
                # loop-variant batch (rolled: same images, new order) — an
                # invariant batch lets XLA hoist the whole inception forward
                # out of the loop (observed on BERT: 2.6x-over-peak "rates")
                return fid_obj.update_state(s, jnp.roll(batch, i, axis=0), real=False)

            out = jax.lax.fori_loop(0, K, body, state)
            return out, out["fake_n"]  # scalar rider: fetch == epoch executed

        state, probe = epoch(params, ep_imgs, fid_obj.init_state())  # compile + warm
        float(probe)
        ts = []
        for _ in range(6):
            t0 = time.perf_counter()
            state, probe = epoch(params, ep_imgs, fid_obj.init_state())
            float(probe)
            rate = K * ep_b / max(time.perf_counter() - t0 - rtt, 1e-9)
            # plausibility: a trial implying more FLOP/s than the chip's peak
            # measured a runtime glitch, not the chip
            if peak_flops and rate * per_img > peak_flops:
                continue
            ts.append(rate)
            if len(ts) == 3:
                break
        return ts

    fid, fid_holder = make_fid()
    trials = run_epoch_trials(fid, fid_holder)
    if not trials:
        return {"error": "all FID epoch trials exceeded the device FLOP peak "
                         "(runtime readiness glitch); no valid measurement"}
    ours = float(np.median(trials))
    out = {"value": round(ours, 2), "unit": "imgs/s (compiled epoch loop, device-resident batch)",
           "vs_baseline": None, "trials": [round(t, 1) for t in trials],
           "note": "reference FID needs torch-fidelity (absent); ours-only"}
    out.update(_mfu_fields(
        per_img, ours,
        "XLA cost_analysis of compiled InceptionV3 fwd" if flops_total
        else "analytic InceptionV3 5.71 GMACs*2 (cost_analysis unavailable)"))

    # the TPU-first fast path: bf16 compute + the profiler-directed forward
    # (folded preprocess, MXU-padded stem — r6; the per-fusion table that
    # picked these targets is in docs/benchmarking.md). bf16 halves activation
    # HBM so larger device-resident batches fit; the padded stem lifts the
    # graph's structural MXU ceiling (reported below, analytic trace-only).
    try:
        fid16, holder16 = make_fid(compute_dtype=jnp.bfloat16, optimized=True)
        try:
            from metrics_tpu.ops import structural_mfu_ceiling

            mod16_plain = InceptionV3(compute_dtype=jnp.bfloat16)
            mod16_opt = InceptionV3(
                compute_dtype=jnp.bfloat16, preprocess_folded=True, stem_lanes=128
            )
            probe = jnp.zeros((B, 299, 299, 3), jnp.uint8)
            out["bf16_structural_ceiling_plain"] = round(structural_mfu_ceiling(
                lambda p, x: mod16_plain.apply(p, x)["2048"], params, probe
            ), 4)
            out["bf16_structural_ceiling_optimized"] = round(structural_mfu_ceiling(
                lambda p, x: mod16_opt.apply(
                    pad_stem_params(fold_preprocess_into_params(p)), x
                )["2048"],
                params, probe,
            ), 4)
        except Exception as e:  # attribution is advisory; never kill the bench
            out["bf16_structural_ceiling_error"] = str(e)[:200]
        by_batch = {}
        best_rate, best_trials, best_b = None, None, None
        # batch 1024: bf16 halves activation HBM so the larger device-resident
        # batch fits. Each batch size costs an inception-epoch compile (~3 min
        # over the tunnel), so one point; the one-off r5 sweep measured
        # 256: 6888, 512: 6970, so throughput is near-flat in batch and 1024
        # is the headroom case.
        for b16 in (1024,):
            if b16 == B:
                imgs16 = imgs
            else:
                imgs16 = jnp.asarray((rng.rand(b16, 299, 299, 3) * 255).astype(np.uint8))
                jax.block_until_ready(imgs16)
            try:
                trials16 = run_epoch_trials(fid16, holder16, imgs16)
            except Exception as e:  # OOM at the largest batch must not kill the sweep
                by_batch[str(b16)] = f"error: {str(e)[:120]}"
                continue
            if not trials16:
                by_batch[str(b16)] = "all trials exceeded the FLOP peak (runtime glitch)"
                continue
            rate = float(np.median(trials16))
            by_batch[str(b16)] = round(rate, 1)
            if best_rate is None or rate > best_rate:
                best_rate, best_trials, best_b = rate, trials16, b16
        if best_rate is not None:
            out["bf16_value"] = round(best_rate, 2)
            out["bf16_trials"] = [round(t, 1) for t in best_trials]
            out["bf16_batch"] = best_b
            out["bf16_by_batch"] = by_batch
            if peak_flops and per_img:
                out["bf16_mfu"] = round(best_rate * per_img / peak_flops, 4)
            measured = _CALIB.get("measured_matmul_tflops_bf16")
            if measured and per_img:
                ratio = best_rate * per_img / (measured * 1e12)
                if ratio <= 1.0:
                    out["bf16_mfu_vs_measured_matmul"] = round(ratio, 4)
                else:  # impossible utilization → measured-vs-model (see _mfu_fields)
                    out["bf16_measured_vs_model_ratio"] = round(ratio, 4)
                    out["bf16_measured_vs_model_note"] = (
                        "exceeds the separately-calibrated ceiling; heterogeneous "
                        "tunnel pool — attribution ratio, not a utilization"
                    )
            out["bf16_note"] = (
                "r5: larger bf16 batch + honest timing protocol (loop-variant "
                "inputs, RTT-subtracted value fetch). Remaining gap to peak is "
                "structural: inception's early layers have <=96 channels vs the "
                "MXU's 128 lanes and VALID-padded odd spatial dims, so conv "
                "tiling waste is inherent; the chip's own sustained matmul "
                "ceiling is ~88% of nominal peak, so mfu-vs-measured is the "
                "fair utilization figure"
            )
        else:
            out["bf16_error"] = f"no valid bf16 measurement: {by_batch}"
    except Exception as e:  # the f32 headline must survive a fast-path failure
        out["bf16_error"] = str(e)[:200]
    return out


# --------------------------------------------- config 6: retrieval grouped compute

def bench_retrieval() -> dict:
    """10k-query RetrievalMAP compute: the fused sort+segment device path vs the
    reference-style per-group host loop (``RetrievalMetric._compute_host`` —
    behaviorally identical to reference ``retrieval_metric.py:124-153``)."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import RetrievalMAP

    n_queries, docs_per = 10_000, 20
    rng = np.random.RandomState(0)
    indexes = np.repeat(np.arange(n_queries), docs_per)
    preds = rng.rand(n_queries * docs_per).astype(np.float32)
    target = rng.randint(0, 2, n_queries * docs_per)

    m = RetrievalMAP()
    m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))

    jax.block_until_ready(m.compute())  # compile
    m._computed = None  # drop the epoch cache so the timed run recomputes
    t0 = time.perf_counter()
    jax.block_until_ready(m.compute())
    device_s = time.perf_counter() - t0

    # the host loop is the reference algorithm: one python iteration + one
    # blocking device sync per query, so it is linear in query count and far
    # too slow to run at 10k over the TPU tunnel — time a subset, extrapolate
    sub_q = 100
    sub = slice(0, sub_q * docs_per)
    idx_c, p_c, t_c = jnp.asarray(indexes[sub]), jnp.asarray(preds[sub]), jnp.asarray(target[sub])
    m._compute_host(idx_c, p_c, t_c)  # warm caches
    t0 = time.perf_counter()
    m._compute_host(idx_c, p_c, t_c)
    host_s = (time.perf_counter() - t0) * (n_queries / sub_q)

    return {
        "value": round(n_queries / device_s, 1),
        "unit": "queries/s (10k-query MAP compute, fused segment path)",
        "host_loop_queries_per_s": round(n_queries / host_s, 1),
        "host_loop_note": f"host loop timed on {sub_q} queries, scaled linearly",
        "vs_baseline": round(host_s / device_s, 2),
    }


def _t(label: str, t0: float) -> None:
    print(f"[bench-timing] {label}: {time.perf_counter() - t0:.1f}s", file=sys.stderr)


def main() -> None:
    t0 = time.perf_counter()
    tpu_throughput, tpu_meta = bench_tpu()
    _t("headline", t0)
    t0 = time.perf_counter()
    ref_throughput = bench_reference()
    _t("reference", t0)
    vs = tpu_throughput / ref_throughput if np.isfinite(ref_throughput) and ref_throughput > 0 else None

    extras = {"headline": tpu_meta}
    t0 = time.perf_counter()
    try:
        sync = bench_sync_latency()
        if "fused_us" in sync:
            sync_only = sync.get("fused_sync_only_us")
            naive_only = sync.get("naive_sync_only_us")
            # fall back to full-step timings only as a PAIR (mismatched
            # quantities would corrupt the ratio), and only when the
            # subtraction wasn't computed at all — 0.0 is a legitimate value
            # (sync fully hidden by overlap); the ratio guard below handles it
            have_isolated = sync_only is not None and naive_only is not None
            value = sync_only if have_isolated else sync["fused_us"]
            naive_value = naive_only if have_isolated else sync["naive_us"]
            extras["sync_latency_us"] = {
                "value": round(value, 1),
                "unit": "us/sync (8-dev mesh, fused bundle{})".format(
                    ", update cost subtracted" if have_isolated else ", full step"
                ),
                "naive_us": round(naive_value, 1),
                "vs_baseline": round(naive_value / value, 3) if value > 0 else None,
                "full_step_fused_us": round(sync["fused_us"], 1),
                "noop_shard_map_floor_us": (
                    round(sync["noop_floor_us"], 1) if "noop_floor_us" in sync else None
                ),
                "fused_minus_floor_us": (
                    round(sync["fused_minus_floor_us"], 1) if "fused_minus_floor_us" in sync else None
                ),
                "spread_us": sync.get("spread_us"),
                "protocol": sync.get("protocol"),
                "collectives_per_sync": sync.get("collectives_per_sync"),
                "collectives_per_sync_naive": sync.get("collectives_per_sync_naive"),
                "sync_payload_bytes": sync.get("sync_payload_bytes"),
                "chip_bundle_overhead_us": sync.get("chip_bundle_overhead_us"),
                "fused_scaling_us_by_devices": sync.get("fused_scaling_us_by_devices", {}),
            }
        else:
            extras["sync_latency_us"] = sync
    except Exception as e:  # never lose the primary line
        extras["sync_latency_us"] = {"error": str(e)[:200]}
    _t("sync_latency", t0)
    for name, fn in (
        ("sync_payload", bench_sync_payload),
        ("readme_accuracy_cpu", bench_readme_accuracy_cpu),
        ("detection_map", bench_map),
        ("bertscore", bench_bertscore),
        ("bertscore_base", bench_bertscore_base),
        ("fid_update", bench_fid),
        ("retrieval_compute", bench_retrieval),
        ("sharded_embedded", bench_sharded_embedded),
        ("engine_steady_state", bench_engine_steady_state),
        ("engine_dispatch", bench_engine_dispatch),
        ("engine_mesh_dispatch", bench_engine_mesh_dispatch),
        ("stream_capacity", bench_stream_capacity),
        ("fleet_sync", bench_fleet_sync),
        ("fleet_tenancy", bench_fleet_tenancy),
        ("ragged_serving", bench_ragged_serving),
        ("model_serving", bench_model_serving),
        ("obs_overhead", bench_obs_overhead),
        ("kernel_microbench", bench_kernel_microbench),
    ):
        # one retry: the tunnelled TPU occasionally drops a remote_compile
        # mid-stream; a transient reset must not cost the config its number
        errors = []
        t0 = time.perf_counter()
        for _ in (0, 1):
            try:
                extras[name] = fn()
                break
            except Exception as e:
                errors.append(str(e)[:200])
                extras[name] = {"error": errors[0], "retry_error": errors[-1]} if len(errors) > 1 else {"error": errors[0]}
        _t(name, t0)

    print(
        json.dumps(
            {
                "metric": "fused_collection_update_throughput",
                "value": round(tpu_throughput, 1),
                "unit": "samples/s/chip",
                "vs_baseline": round(vs, 3) if vs is not None else None,
                "extras": extras,
            }
        )
    )


if __name__ == "__main__":
    main()
