"""Pallas TPU kernel: fused masked row-delta reduction.

The generic masked update (``Metric.update_state_masked``, delta strategy)
vmaps the subclass update into row-stacked state deltas ``(N, *leaf)`` and
folds them into the carried state with the reduction's identity substituted
for masked rows. XLA's generic lowering materializes the identity-substituted
``(N, *leaf)`` intermediate (broadcast + select) before the reduce; this
kernel streams the rows through VMEM in blocks and folds each block into the
revisited ``(1, F)`` accumulator on the VPU, so HBM sees the stacked deltas
once and the state once — the select/reduce intermediate never exists.

Grid: one dimension over row blocks; the output block is revisited and
accumulated across grid steps (seeded with the carried state at step 0 —
TPU grids execute sequentially, which this accumulation relies on).
"""
import functools

import jax
import jax.numpy as jnp

from metrics_tpu.ops.kernels.common import reduce_identity

Array = jax.Array


def _fold_kernel(state_ref, mask_ref, rows_ref, out_ref, *, fx):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _seed():
        out_ref[:] = state_ref[:]

    rows = rows_ref[:]  # (blk, F)
    m = mask_ref[:] != 0  # (blk, 1) — int mask: bool blocks don't tile well
    if fx == "sum":
        red = jnp.sum(jnp.where(m, rows, jnp.zeros_like(rows)), axis=0, keepdims=True)
        out_ref[:] = out_ref[:] + red
    elif fx == "min":
        ident = reduce_identity(rows.dtype, "min")
        red = jnp.min(jnp.where(m, rows, ident), axis=0, keepdims=True)
        out_ref[:] = jnp.minimum(out_ref[:], red)
    else:
        ident = reduce_identity(rows.dtype, "max")
        red = jnp.max(jnp.where(m, rows, ident), axis=0, keepdims=True)
        out_ref[:] = jnp.maximum(out_ref[:], red)


def fold_rows_pallas(
    state2d: Array,
    rows2d: Array,
    mask_i32: Array,
    fx: str,
    block_n: int,
    interpret: bool,
) -> Array:
    """``(1, F) state ⊕ masked-reduce((N, F) rows)`` in one streaming pass.

    Caller (the dispatcher) canonicalizes shapes: ``state2d`` is ``(1, F)``,
    ``rows2d`` is ``(N, F)``, ``mask_i32`` is ``(N, 1)`` int32 0/1, and
    ``block_n`` already fits the VMEM budget. Rows are padded here to a block
    multiple with mask 0 (identity rows — inert under every reduction).
    """
    from jax.experimental import pallas as pl

    n, f = rows2d.shape
    block_n = min(block_n, max(n, 1))
    n_pad = (-n) % block_n
    if n_pad:
        rows2d = jnp.pad(rows2d, ((0, n_pad), (0, 0)))
        mask_i32 = jnp.pad(mask_i32, ((0, n_pad), (0, 0)))
    grid = (rows2d.shape[0] // block_n,)
    return pl.pallas_call(
        functools.partial(_fold_kernel, fx=fx),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, f), lambda i: (0, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, f), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, f), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, f), rows2d.dtype),
        interpret=interpret,
    )(state2d, mask_i32, rows2d)
