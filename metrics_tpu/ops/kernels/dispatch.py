"""Runtime backend dispatch for the streaming-update kernel library.

Three primitives dominate the engine's device step (ISSUE 4):

* :func:`fold_rows_masked` — fused masked row-delta reduction
  (``Metric.update_state_masked``, delta strategy);
* :func:`segment_reduce_masked` — masked segment sum/min/max
  (``Metric.update_state_segmented`` / ``MultiStreamEngine``);
* :func:`histogram_accumulate` — fused masked/weighted fixed-length bincount
  (``utils/data.py::_bincount``, the confusion-matrix family,
  ``calibration_error``, ``ops/binned_update.py``).

Each dispatches over a BACKEND chosen at trace time (the decision depends
only on configuration and the JAX platform, never on traced values, so the
dispatch is jit/shard_map-safe):

========================  =====================================================
``"pallas"``              compiled Pallas kernels (TPU)
``"pallas_interpret"``    the same kernels under ``interpret=True`` — bit-level
                          kernel-logic parity testing on CPU CI
``"megastep"``            the whole-step megakernel tier (ISSUE 16): engines
                          fuse the entire masked collection update into ONE
                          Pallas grid per arena dtype
                          (:func:`megastep_fold`/:func:`megastep_segment`);
                          the three per-leaf primitives behave exactly as
                          under ``"pallas"`` (they are the per-leaf fallback
                          for arena dtypes the megakernel cannot take)
``"megastep_interpret"``  the megastep tier under ``interpret=True`` (CPU CI);
                          an engine whose LAYOUT cannot take the megastep path
                          at all raises instead of silently degrading, so
                          parity tests can never test the wrong path
                          (per-dtype ineligibility still falls back per-leaf
                          — that is the megakernel contract, not an error)
``"xla"``                 the pre-kernel XLA lowerings (``kernels/xla_ref.py``)
                          — always available, the reference path
``"auto"``                ``"pallas"`` on TPU platforms, ``"xla"`` elsewhere
                          (never ``"megastep"`` — the megakernel is opt-in)
========================  =====================================================

Selection, most specific wins:

1. :func:`use_backend` context manager (per-trace; the engine wraps program
   builds in it — ``EngineConfig.kernel_backend``);
2. :func:`set_default_backend` (process-wide);
3. the ``METRICS_TPU_KERNEL_BACKEND`` environment variable, read at import;
4. ``"auto"``.

Inputs a Pallas path cannot serve (unsupported dtype, feature dim too big for
a VMEM block, histogram too long/too tall for exact f32 accumulation) fall
back to the XLA path silently — the dispatcher degrades, it never errors.

Trace-caching caveat: the backend choice is a trace-time constant, and JAX
caches traces by FUNCTION IDENTITY + input avals — re-tracing the SAME
function object under a different backend reuses the earlier jaxpr. Build a
fresh closure per backend when you need both lowerings of one computation
(the engine does: every program build constructs its own step closure).
Under ``"pallas"`` a trace-time kernel failure also falls back (same policy
as ``ops/binned_update.py``); under ``"pallas_interpret"`` it raises, so CPU
parity tests can never silently test the wrong path.
"""
import contextlib
import os
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.ops.kernels.common import (
    REDUCE_OPS,
    VMEM_BLOCK_BYTES,
    as_2d_rows,
    block_rows,
    supported_dtype,
)
from metrics_tpu.ops.kernels.pallas_fold import fold_rows_pallas
from metrics_tpu.ops.kernels.pallas_hist import histogram_pallas
from metrics_tpu.ops.kernels.pallas_megastep import (
    megastep_fold_pallas,
    megastep_segment_pallas,
)
from metrics_tpu.ops.kernels.pallas_segment import segment_reduce_pallas
from metrics_tpu.ops.kernels.xla_ref import (
    fold_rows_ref,
    histogram_ref,
    megastep_fold_ref,
    megastep_segment_ref,
    segment_reduce_ref,
)

Array = jax.Array

BACKENDS = ("auto", "pallas", "pallas_interpret", "megastep", "megastep_interpret", "xla")

#: backends that request the whole-step megakernel engine path
MEGASTEP_BACKENDS = ("megastep", "megastep_interpret")
BACKEND_ENV_VAR = "METRICS_TPU_KERNEL_BACKEND"

# histograms longer than this keep the XLA path: the kernel's (blk, L) one-hot
# block would crowd VMEM and the O(N*L) compare work loses to the scatter
MAX_HIST_LENGTH = 8192
# integer-count exactness bound for the f32 MXU accumulation (2**24)
_HIST_EXACT_ROWS = 1 << 24

_tls = threading.local()


def _validate(name: str) -> str:
    if name not in BACKENDS:
        raise ValueError(
            f"unknown kernel backend {name!r}; expected one of {BACKENDS}"
        )
    return name


def _env_default() -> str:
    """The env-var default, degrading to ``"auto"`` on an unknown name — a
    typo'd environment must not make the whole package unimportable."""
    raw = os.environ.get(BACKEND_ENV_VAR, "auto") or "auto"
    if raw not in BACKENDS:
        import warnings

        warnings.warn(
            f"{BACKEND_ENV_VAR}={raw!r} is not one of {BACKENDS}; using 'auto'",
            stacklevel=2,
        )
        return "auto"
    return raw


_default_backend = _env_default()


def set_default_backend(name: str) -> None:
    """Set the process-wide default backend (overrides the env var)."""
    global _default_backend
    _default_backend = _validate(name)


def current_backend() -> str:
    """The configured (possibly ``"auto"``) backend in effect on this thread."""
    override = getattr(_tls, "stack", None)
    if override:
        return override[-1]
    return _default_backend


def _maybe_kernel_fault(kernel: str) -> None:
    """Trace-time kernel fault hook (chaos harness): called inside each
    primitive's kernel ``try`` block, so a raised fault follows the
    dispatcher's EXISTING failure policy — silent fallback to the XLA
    reference path under ``pallas``, a loud raise under
    ``pallas_interpret`` (parity tests must never silently test the wrong
    lowering). No hook installed (the default) costs one thread-local read."""
    hook = getattr(_tls, "fault_hook", None)
    if hook is not None:
        hook(kernel)


@contextlib.contextmanager
def kernel_fault_scope(hook: Optional[callable]):
    """Install a thread-local trace-time kernel fault hook: ``hook(kernel_
    name)`` runs before every Pallas kernel call traced in this scope and
    may raise to simulate a kernel failure (``engine/faults.py`` chaos
    plans use this to prove the per-call degradation path — distinct from
    the engine-level ``kernel`` site, which exercises the pallas→xla
    DEMOTION of a whole engine)."""
    prev = getattr(_tls, "fault_hook", None)
    _tls.fault_hook = hook
    try:
        yield
    finally:
        _tls.fault_hook = prev


@contextlib.contextmanager
def use_backend(name: Optional[str]):
    """Scoped backend override (thread-local). ``None`` is a no-op passthrough
    — callers with an optional config value can always wrap."""
    if name is None:
        yield
        return
    _validate(name)
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(name)
    try:
        yield
    finally:
        stack.pop()


def resolve_backend(name: Optional[str] = None) -> str:
    """Concrete backend for ``name`` (default: the ambient selection):
    ``"auto"`` resolves to ``"pallas"`` on TPU platforms and ``"xla"``
    everywhere else. The answer depends only on config + platform, so calling
    this inside a trace is safe (it is a trace-time constant)."""
    name = _validate(name if name is not None else current_backend())
    if name != "auto":
        return name
    try:
        on_tpu = jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover - backend init failure
        on_tpu = False
    return "pallas" if on_tpu else "xla"


def _pallas_or_none(backend: Optional[str]) -> Optional[bool]:
    """None → take the XLA path; else the kernel's ``interpret`` flag.

    The megastep tier maps onto the pallas kernels for the three per-leaf
    primitives (``megastep`` → compiled, ``megastep_interpret`` →
    ``interpret=True``): per-leaf calls under a megastep scope ARE the
    per-dtype fallback path, and they must exercise the same lowering class
    the megakernel would."""
    resolved = resolve_backend(backend)
    if resolved == "xla":
        return None
    return resolved in ("pallas_interpret", "megastep_interpret")


# ------------------------------------------------------------------ primitives


def fold_rows_masked(
    state: Array, rows: Array, mask: Array, fx: str, backend: Optional[str] = None
) -> Array:
    """Fused masked row-delta reduction.

    ``rows`` is the row-stacked delta ``(N, *leaf)``, ``state`` the carried
    leaf ``(*leaf)``, ``mask`` ``(N,)``; rows where ``mask`` is False
    contribute the reduction identity. Returns the new leaf.
    """
    if fx not in REDUCE_OPS:
        raise ValueError(f"fold_rows_masked supports {REDUCE_OPS}, got {fx!r}")
    state = jnp.asarray(state)
    rows = jnp.asarray(rows, state.dtype)
    interpret = _pallas_or_none(backend)
    n = int(rows.shape[0])
    if interpret is None or n == 0 or not supported_dtype(rows.dtype):
        return fold_rows_ref(state, rows, mask, fx)
    rows2d, trailing = as_2d_rows(rows, n)
    f = int(rows2d.shape[1])
    blk = block_rows(f * rows2d.dtype.itemsize)
    if blk is None:
        return fold_rows_ref(state, rows, mask, fx)
    mask_i32 = jnp.reshape(jnp.asarray(mask, bool).astype(jnp.int32), (n, 1))
    state2d = jnp.reshape(state, (1, f))
    try:
        _maybe_kernel_fault("fold_rows")
        out = fold_rows_pallas(state2d, rows2d, mask_i32, fx, blk, interpret)
    except Exception:
        if interpret:  # parity tests must see kernel failures, not a fallback
            raise
        return fold_rows_ref(state, rows, mask, fx)
    return jnp.reshape(out, trailing)


def segment_reduce_masked(
    state: Array,
    rows: Array,
    mask: Array,
    segment_ids: Array,
    num_segments: int,
    fx: str,
    backend: Optional[str] = None,
) -> Array:
    """Masked segment sum/min/max: each row folds into the stream row
    addressed by ``segment_ids`` (masked rows fold into nothing).

    ``state`` is stream-stacked ``(num_segments, *leaf)``; returns its
    updated value.
    """
    if fx not in REDUCE_OPS:
        raise ValueError(f"segment_reduce_masked supports {REDUCE_OPS}, got {fx!r}")
    state = jnp.asarray(state)
    rows = jnp.asarray(rows, state.dtype)
    interpret = _pallas_or_none(backend)
    n = int(rows.shape[0])
    if interpret is None or n == 0 or not supported_dtype(rows.dtype):
        return segment_reduce_ref(state, rows, mask, segment_ids, num_segments, fx)
    rows2d, trailing = as_2d_rows(rows, n)
    f = int(rows2d.shape[1])
    itemsize = rows2d.dtype.itemsize
    blk = block_rows(f * itemsize)
    # the (S, F) stream state lives in VMEM whole as the revisited block
    if blk is None or num_segments * f * itemsize > VMEM_BLOCK_BYTES:
        return segment_reduce_ref(state, rows, mask, segment_ids, num_segments, fx)
    ids_i32 = jnp.reshape(jnp.asarray(segment_ids, jnp.int32), (n, 1))
    mask_i32 = jnp.reshape(jnp.asarray(mask, bool).astype(jnp.int32), (n, 1))
    state2d = jnp.reshape(state, (num_segments, f))
    try:
        _maybe_kernel_fault("segment_reduce")
        out = segment_reduce_pallas(
            state2d, rows2d, ids_i32, mask_i32, fx, num_segments, blk, interpret
        )
    except Exception:
        if interpret:
            raise
        return segment_reduce_ref(state, rows, mask, segment_ids, num_segments, fx)
    return jnp.reshape(out, (num_segments,) + trailing)


def _op_row_info(op_row, f: int):
    """Canonicalize a HOST opcode row: ``(1, f)`` int32 device constant plus
    the shared reduction name when every column agrees (the kernels then skip
    the per-column select). The opcode row is static plan metadata
    (``engine/megastep.py``) — never a traced value."""
    op_np = np.asarray(op_row, np.int32).reshape(-1)
    if op_np.shape[0] != f:
        raise ValueError(f"opcode row has {op_np.shape[0]} columns, arena has {f}")
    uniq = {int(x) for x in np.unique(op_np)} if op_np.size else {0}
    if not uniq <= {0, 1, 2}:
        raise ValueError(f"megastep opcodes must index {REDUCE_OPS}, got {sorted(uniq)}")
    uniform = REDUCE_OPS[next(iter(uniq))] if len(uniq) == 1 else None
    return jnp.reshape(jnp.asarray(op_np, jnp.int32), (1, f)), uniform


def megastep_fold(
    state_buf: Array, rows: Array, mask: Array, op_row, backend: Optional[str] = None
) -> Array:
    """Whole-arena masked fold: ONE launch folds every leaf of a dtype.

    ``state_buf`` is a packed arena buffer ``(F,)`` (every same-dtype leaf
    raveled and concatenated, per :class:`~metrics_tpu.engine.arena
    .ArenaLayout`), ``rows`` the column-aligned packed row deltas ``(N, F)``,
    ``mask`` ``(N,)``, and ``op_row`` a HOST ``(F,)`` int32 opcode row (each
    column's reduction, indices into ``REDUCE_OPS``). Returns the new buffer.
    """
    state = jnp.asarray(state_buf)
    rows = jnp.asarray(rows, state.dtype)
    n = int(rows.shape[0])
    if n == 0:
        return state
    f = int(rows.shape[1])
    op2d, uniform = _op_row_info(op_row, f)
    state2d = jnp.reshape(state, (1, f))
    interpret = _pallas_or_none(backend)
    if interpret is None or not supported_dtype(rows.dtype):
        return jnp.reshape(megastep_fold_ref(state2d, rows, mask, op2d), state.shape)
    blk = block_rows(f * rows.dtype.itemsize)
    if blk is None:
        return jnp.reshape(megastep_fold_ref(state2d, rows, mask, op2d), state.shape)
    mask_i32 = jnp.reshape(jnp.asarray(mask, bool).astype(jnp.int32), (n, 1))
    try:
        _maybe_kernel_fault("megastep_fold")
        out = megastep_fold_pallas(state2d, rows, mask_i32, op2d, uniform, blk, interpret)
    except Exception:
        if interpret:  # parity tests must see kernel failures, not a fallback
            raise
        return jnp.reshape(megastep_fold_ref(state2d, rows, mask, op2d), state.shape)
    return jnp.reshape(out, state.shape)


def megastep_segment(
    state_buf: Array,
    rows: Array,
    mask: Array,
    segment_ids: Array,
    num_segments: int,
    op_row,
    q8=None,
    backend: Optional[str] = None,
) -> Array:
    """Whole-arena masked segment reduce: one launch scatters every leaf of a
    dtype into the addressed stream slots.

    ``state_buf`` is the slot-stacked arena buffer ``(S, F)`` (pager slot ids
    ARE the segment ids), ``rows`` the packed deltas ``(N, F)``, ``op_row``
    the per-column opcode row. ``q8``, when given, is ``(flags (S,), codes
    (S, F) int8, scales (S, F) f32, qcol (F,) bool)`` — q8-resident cold slots
    whose quantized columns decode on touch inside the grid (and inside the
    reference path alike, so a fallback never skips the decode).
    """
    state = jnp.asarray(state_buf)
    rows = jnp.asarray(rows, state.dtype)
    n = int(rows.shape[0])
    f = int(state.shape[-1])
    op2d, uniform = _op_row_info(op_row, f)
    q8c = None
    if q8 is not None:
        flags, codes, scales, qcol = q8
        q8c = (
            jnp.reshape(jnp.asarray(flags, jnp.int32), (num_segments, 1)),
            jnp.asarray(codes, jnp.int8),
            jnp.asarray(scales, jnp.float32),
            jnp.reshape(jnp.asarray(np.asarray(qcol, bool), jnp.int32), (1, f)),
        )
    if n == 0:
        # no rows fold in, but staged q8 slots still decode (the touch IS the
        # page-in; an empty-mask step must not leave stale quantized columns)
        if q8c is None:
            return state
        return megastep_segment_ref(
            state, jnp.zeros((0, f), state.dtype), jnp.zeros((0,), bool),
            jnp.zeros((0,), jnp.int32), num_segments, op2d, q8c,
        )
    interpret = _pallas_or_none(backend)
    itemsize = rows.dtype.itemsize
    blk = block_rows(f * itemsize)
    if (
        interpret is None
        or not supported_dtype(rows.dtype)
        or blk is None
        or num_segments * f * itemsize > VMEM_BLOCK_BYTES
    ):
        return megastep_segment_ref(state, rows, mask, segment_ids, num_segments, op2d, q8c)
    ids_i32 = jnp.reshape(jnp.asarray(segment_ids, jnp.int32), (n, 1))
    mask_i32 = jnp.reshape(jnp.asarray(mask, bool).astype(jnp.int32), (n, 1))
    try:
        _maybe_kernel_fault("megastep_segment")
        out = megastep_segment_pallas(
            state, rows, ids_i32, mask_i32, op2d, uniform, num_segments, blk,
            interpret, q8c,
        )
    except Exception:
        if interpret:
            raise
        return megastep_segment_ref(state, rows, mask, segment_ids, num_segments, op2d, q8c)
    return out


def histogram_accumulate(
    indices: Array,
    length: int,
    weights: Optional[Array] = None,
    mask: Optional[Array] = None,
    backend: Optional[str] = None,
) -> Array:
    """Fused masked/weighted fixed-length bincount.

    ``jnp.bincount(x, length=length)`` semantics — negative indices clip to
    bin 0, indices ``>= length`` are dropped — extended with optional per-row
    ``weights`` (``(N,)`` or ``(N, K)`` columns summed per bin in one pass)
    and an optional row ``mask``. Returns int32 counts (no weights) or the
    weights-dtype sums, shape ``(length,)`` / ``(length, K)`` matching the
    weights' rank.
    """
    length = int(length)
    idx = jnp.asarray(indices)
    n = int(idx.shape[0]) if idx.ndim else 0
    interpret = _pallas_or_none(backend)
    w = None if weights is None else jnp.asarray(weights)
    # the explicit overflow guard: past _HIST_EXACT_ROWS rows the f32 (and a
    # fortiori the low-precision MXU) accumulation can no longer represent
    # every integer count, so the whole call falls back to the full-precision
    # XLA scatter path — exactness is a gate, never a best effort
    pallas_ok = (
        interpret is not None
        and 0 < n < _HIST_EXACT_ROWS
        and idx.ndim == 1
        and 0 < length <= MAX_HIST_LENGTH
        and (
            w is None
            or (w.dtype in (jnp.float32, jnp.bfloat16) and w.ndim in (1, 2))
        )
        and block_rows(length * 4) is not None
    )
    if not pallas_ok:
        return histogram_ref(idx, length, weights=weights, mask=mask)
    # jnp.bincount semantics: clip negatives to 0; >= length stays OUT of
    # range — the kernel's exact-match one-hot drops it, like scatter does
    idx_i32 = jnp.reshape(jnp.maximum(idx.astype(jnp.int32), 0), (n, 1))
    if w is None:
        # unweighted counts ride the int8 MXU path: the ones column and the
        # one-hot are both int8, the per-block contraction accumulates int32
        # (exact), and the cross-block f32 accumulation is exact under the
        # row-count gate above
        cols = jnp.ones((n, 1), jnp.int8)
        squeeze, out_dtype = True, jnp.int32
    else:
        # bf16 weights keep their width into the MXU (f32 accumulation; the
        # products are exact because one-hot entries are 0/1) — only the
        # result cast back to bf16 rounds, same as the reference's own sums
        squeeze = w.ndim == 1
        out_dtype = w.dtype
        cols = jnp.reshape(w, (n, -1))
    if mask is not None:
        m = jnp.reshape(jnp.asarray(mask, bool), (n, 1))
        cols = jnp.where(m, cols, jnp.zeros_like(cols))
    # the (blk, L) one-hot block dominates the kernel's VMEM working set
    blk = block_rows(max(length, cols.shape[1]) * 4)
    try:
        _maybe_kernel_fault("histogram")
        out = histogram_pallas(idx_i32, cols, length, blk, interpret)
    except Exception:
        if interpret:
            raise
        return histogram_ref(idx, length, weights=weights, mask=mask)
    out = out.astype(out_dtype)
    return out[:, 0] if squeeze else out
