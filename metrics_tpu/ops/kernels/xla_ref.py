"""XLA reference lowerings of the three streaming-update primitives.

These are the EXACT formulations the Metric runtime shipped before the Pallas
library existed (``Metric._masked_reduce_into`` / ``_segment_reduce_into`` and
``jnp.bincount``/``segment_sum`` call sites), hoisted here so they serve two
jobs at once:

* the always-available dispatch target (``kernels/dispatch.py`` backend
  ``"xla"``, and the silent fallback for shapes/dtypes the Pallas paths do
  not take);
* the parity oracle every Pallas kernel is tested against
  (``tests/ops/test_kernel_parity.py``) — int/bool states bit-exact, float
  states within reassociation tolerance.

Semantics notes:

* masked-out rows contribute the reduction's identity element
  (``common.reduce_identity``), exactly as the vmapped masked path always
  substituted;
* histogram indices follow ``jnp.bincount(x, length=L)`` semantics exactly,
  kept uniform across backends: negatives CLIP to bin 0 (``x.clip(0)`` in
  jnp's own lowering), indices ``>= length`` are DROPPED (scatter
  out-of-bounds drop) — the seed behavior ``_bincount`` always had.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.ops.kernels.common import combine, reduce_identity

Array = jax.Array


def fold_rows_ref(state: Array, rows: Array, mask: Array, fx: str) -> Array:
    """Masked row fold: ``combine(state, reduce(where(mask, rows, identity)))``."""
    m = jnp.reshape(mask, (mask.shape[0],) + (1,) * (rows.ndim - 1))
    if fx == "sum":
        return state + jnp.sum(jnp.where(m, rows, jnp.zeros_like(rows)), axis=0)
    ident = reduce_identity(rows.dtype, fx)
    if fx == "min":
        return jnp.minimum(state, jnp.min(jnp.where(m, rows, ident), axis=0))
    return jnp.maximum(state, jnp.max(jnp.where(m, rows, ident), axis=0))


def segment_reduce_ref(
    state: Array,
    rows: Array,
    mask: Array,
    segment_ids: Array,
    num_segments: int,
    fx: str,
) -> Array:
    """Masked segment reduce via ``.at[ids].op`` on an identity-filled base."""
    m = jnp.reshape(mask, (mask.shape[0],) + (1,) * (rows.ndim - 1))
    if fx == "sum":
        seg = jnp.zeros((num_segments,) + rows.shape[1:], rows.dtype)
        seg = seg.at[segment_ids].add(jnp.where(m, rows, jnp.zeros_like(rows)))
        return state + seg
    ident = reduce_identity(rows.dtype, fx)
    seg = jnp.full((num_segments,) + rows.shape[1:], ident, rows.dtype)
    if fx == "min":
        seg = seg.at[segment_ids].min(jnp.where(m, rows, ident))
    else:
        seg = seg.at[segment_ids].max(jnp.where(m, rows, ident))
    return combine(state, seg, fx)


def histogram_ref(
    indices: Array,
    length: int,
    weights: Optional[Array] = None,
    mask: Optional[Array] = None,
) -> Array:
    """Weighted/masked fixed-length bincount, ``jnp.bincount`` semantics
    (negatives clip to bin 0, indices >= length drop — segment_sum's
    out-of-bounds scatter drop reproduces that exactly).

    ``weights`` None → int32 counts (``jnp.bincount`` exactly); ``weights``
    ``(N,)`` or ``(N, K)`` → per-column weighted sums, shape ``(length,)`` or
    ``(length, K)``, in the weights' dtype.
    """
    idx = jnp.maximum(jnp.asarray(indices, jnp.int32), 0)
    if weights is None:
        w = jnp.ones(idx.shape, jnp.int32)
        if mask is not None:
            w = jnp.where(jnp.asarray(mask, bool), w, 0)
        return jax.ops.segment_sum(w, idx, num_segments=length).astype(jnp.int32)
    w = jnp.asarray(weights)
    if mask is not None:
        m = jnp.reshape(jnp.asarray(mask, bool), (idx.shape[0],) + (1,) * (w.ndim - 1))
        w = jnp.where(m, w, jnp.zeros_like(w))
    return jax.ops.segment_sum(w, idx, num_segments=length)
