"""XLA reference lowerings of the three streaming-update primitives.

These are the EXACT formulations the Metric runtime shipped before the Pallas
library existed (``Metric._masked_reduce_into`` / ``_segment_reduce_into`` and
``jnp.bincount``/``segment_sum`` call sites), hoisted here so they serve two
jobs at once:

* the always-available dispatch target (``kernels/dispatch.py`` backend
  ``"xla"``, and the silent fallback for shapes/dtypes the Pallas paths do
  not take);
* the parity oracle every Pallas kernel is tested against
  (``tests/ops/test_kernel_parity.py``) — int/bool states bit-exact, float
  states within reassociation tolerance.

Semantics notes:

* masked-out rows contribute the reduction's identity element
  (``common.reduce_identity``), exactly as the vmapped masked path always
  substituted;
* histogram indices follow ``jnp.bincount(x, length=L)`` semantics exactly,
  kept uniform across backends: negatives CLIP to bin 0 (``x.clip(0)`` in
  jnp's own lowering), indices ``>= length`` are DROPPED (scatter
  out-of-bounds drop) — the seed behavior ``_bincount`` always had.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.ops.kernels.common import combine, reduce_identity

Array = jax.Array


def fold_rows_ref(state: Array, rows: Array, mask: Array, fx: str) -> Array:
    """Masked row fold: ``combine(state, reduce(where(mask, rows, identity)))``."""
    m = jnp.reshape(mask, (mask.shape[0],) + (1,) * (rows.ndim - 1))
    if fx == "sum":
        return state + jnp.sum(jnp.where(m, rows, jnp.zeros_like(rows)), axis=0)
    ident = reduce_identity(rows.dtype, fx)
    if fx == "min":
        return jnp.minimum(state, jnp.min(jnp.where(m, rows, ident), axis=0))
    return jnp.maximum(state, jnp.max(jnp.where(m, rows, ident), axis=0))


def segment_reduce_ref(
    state: Array,
    rows: Array,
    mask: Array,
    segment_ids: Array,
    num_segments: int,
    fx: str,
) -> Array:
    """Masked segment reduce via ``.at[ids].op`` on an identity-filled base."""
    m = jnp.reshape(mask, (mask.shape[0],) + (1,) * (rows.ndim - 1))
    if fx == "sum":
        seg = jnp.zeros((num_segments,) + rows.shape[1:], rows.dtype)
        seg = seg.at[segment_ids].add(jnp.where(m, rows, jnp.zeros_like(rows)))
        return state + seg
    ident = reduce_identity(rows.dtype, fx)
    seg = jnp.full((num_segments,) + rows.shape[1:], ident, rows.dtype)
    if fx == "min":
        seg = seg.at[segment_ids].min(jnp.where(m, rows, ident))
    else:
        seg = seg.at[segment_ids].max(jnp.where(m, rows, ident))
    return combine(state, seg, fx)


def _op_select(op_row: Array, state: Array, s: Array, mn: Array, mx: Array) -> Array:
    """Per-column combine select for the megastep forms (op 0=sum 1=min 2=max)."""
    op = jnp.reshape(jnp.asarray(op_row, jnp.int32), (1, -1))
    return jnp.where(
        op == 0,
        state + s,
        jnp.where(op == 1, jnp.minimum(state, mn), jnp.maximum(state, mx)),
    )


def megastep_fold_ref(state2d: Array, rows2d: Array, mask: Array, op_row: Array) -> Array:
    """Whole-arena masked row fold with PER-COLUMN reductions: every column of
    the packed ``(N, F)`` delta matrix folds into the ``(1, F)`` arena row
    under its own opcode (``ops/kernels/pallas_megastep.py``'s oracle)."""
    m = jnp.reshape(jnp.asarray(mask, bool), (rows2d.shape[0], 1))
    s = jnp.sum(jnp.where(m, rows2d, jnp.zeros_like(rows2d)), axis=0, keepdims=True)
    mn = jnp.min(
        jnp.where(m, rows2d, reduce_identity(rows2d.dtype, "min")), axis=0, keepdims=True
    )
    mx = jnp.max(
        jnp.where(m, rows2d, reduce_identity(rows2d.dtype, "max")), axis=0, keepdims=True
    )
    return _op_select(op_row, state2d, s, mn, mx)


def megastep_segment_ref(
    state2d: Array,
    rows2d: Array,
    mask: Array,
    segment_ids: Array,
    num_segments: int,
    op_row: Array,
    q8=None,
) -> Array:
    """Whole-arena masked segment reduce with per-column reductions; with
    ``q8 = (flags, codes, scales, qcol)`` the flagged slots' quantized columns
    are decoded (``codes * scales``) before any row folds in — the same
    decode-on-touch the Pallas megastep seed performs."""
    if q8 is not None:
        flags, codes, scales, qcol = q8
        staged = (jnp.reshape(jnp.asarray(flags, jnp.int32), (-1, 1)) != 0) & (
            jnp.reshape(jnp.asarray(qcol, jnp.int32), (1, -1)) != 0
        )
        dec = (
            jnp.asarray(codes).astype(jnp.float32) * jnp.asarray(scales, jnp.float32)
        ).astype(state2d.dtype)
        state2d = jnp.where(staged, dec, state2d)
    m = jnp.reshape(jnp.asarray(mask, bool), (rows2d.shape[0], 1))
    ids = jnp.asarray(segment_ids, jnp.int32)
    s = (
        jnp.zeros((num_segments,) + rows2d.shape[1:], rows2d.dtype)
        .at[ids]
        .add(jnp.where(m, rows2d, jnp.zeros_like(rows2d)))
    )
    ident_mn = reduce_identity(rows2d.dtype, "min")
    mn = (
        jnp.full((num_segments,) + rows2d.shape[1:], ident_mn, rows2d.dtype)
        .at[ids]
        .min(jnp.where(m, rows2d, ident_mn))
    )
    ident_mx = reduce_identity(rows2d.dtype, "max")
    mx = (
        jnp.full((num_segments,) + rows2d.shape[1:], ident_mx, rows2d.dtype)
        .at[ids]
        .max(jnp.where(m, rows2d, ident_mx))
    )
    return _op_select(op_row, state2d, s, mn, mx)


def histogram_ref(
    indices: Array,
    length: int,
    weights: Optional[Array] = None,
    mask: Optional[Array] = None,
) -> Array:
    """Weighted/masked fixed-length bincount, ``jnp.bincount`` semantics
    (negatives clip to bin 0, indices >= length drop — segment_sum's
    out-of-bounds scatter drop reproduces that exactly).

    ``weights`` None → int32 counts (``jnp.bincount`` exactly); ``weights``
    ``(N,)`` or ``(N, K)`` → per-column weighted sums, shape ``(length,)`` or
    ``(length, K)``, in the weights' dtype.
    """
    idx = jnp.maximum(jnp.asarray(indices, jnp.int32), 0)
    if weights is None:
        w = jnp.ones(idx.shape, jnp.int32)
        if mask is not None:
            w = jnp.where(jnp.asarray(mask, bool), w, 0)
        return jax.ops.segment_sum(w, idx, num_segments=length).astype(jnp.int32)
    w = jnp.asarray(weights)
    if mask is not None:
        m = jnp.reshape(jnp.asarray(mask, bool), (idx.shape[0],) + (1,) * (w.ndim - 1))
        w = jnp.where(m, w, jnp.zeros_like(w))
    return jax.ops.segment_sum(w, idx, num_segments=length)
