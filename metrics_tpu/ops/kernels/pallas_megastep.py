"""Pallas TPU kernels: the whole-step megakernel (one grid per arena dtype).

PR 4 fused three per-leaf primitives; PR 3 packed state into per-dtype arena
buffers. This module combines them: the engine packs every leaf's row-stacked
delta into ONE ``(N, F)`` matrix per dtype (columns laid out exactly like the
arena buffer, per :class:`~metrics_tpu.engine.arena.ArenaLayout`), and a
single grid folds the whole matrix into the revisited ``(1, F)`` (or
stream-stacked ``(S, F)``) arena block. Which reduction applies is a PER
COLUMN property — each leaf's ``dist_reduce_fx`` — carried as a static
``(1, F)`` int32 opcode row (0=sum, 1=min, 2=max, indices into
``common.REDUCE_OPS``): the kernel computes the masked block reduction under
every opcode's identity and compare-selects per column, so mixed-reduction
dtypes still take one launch. When every column shares one reduction (the
common case — a counter-only float arena is all-sum) the specialized body
skips the select entirely and matches ``pallas_fold``/``pallas_segment``
op-for-op.

The segment form additionally decodes q8_block-RESIDENT cold rows on touch:
slots the pager seated in compressed form arrive as int8 codes + per-element
f32 scales + a per-slot staged flag, and the seed step substitutes
``codes * scales`` for the (stale) quantized columns of flagged slots before
any row folds in — the decode never materializes in HBM, and the arithmetic
(`int8 -> f32` conversion, one f32 multiply) is bit-identical to the host
codec's ``_decode_blocks``.

Grids are one-dimensional over row blocks; outputs are revisited and
accumulated across the sequential TPU grid steps (seeded at step 0 — the
same sequential-execution reliance as ``pallas_fold``).
"""
import functools

import jax
import jax.numpy as jnp

from metrics_tpu.ops.kernels.common import reduce_identity

Array = jax.Array


def _masked_reductions(rows, m):
    """The three masked block reductions, each under its own identity."""
    s = jnp.sum(jnp.where(m, rows, jnp.zeros_like(rows)), axis=0, keepdims=True)
    mn = jnp.min(
        jnp.where(m, rows, reduce_identity(rows.dtype, "min")), axis=0, keepdims=True
    )
    mx = jnp.max(
        jnp.where(m, rows, reduce_identity(rows.dtype, "max")), axis=0, keepdims=True
    )
    return s, mn, mx


def _select_combine(acc, op, s, mn, mx):
    """Per-column opcode select of the three combined accumulators."""
    return jnp.where(
        op == 0,
        acc + s,
        jnp.where(op == 1, jnp.minimum(acc, mn), jnp.maximum(acc, mx)),
    )


def _mega_fold_kernel(state_ref, op_ref, mask_ref, rows_ref, out_ref, *, uniform):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _seed():
        out_ref[:] = state_ref[:]

    rows = rows_ref[:]  # (blk, F) — the whole dtype's packed delta columns
    m = mask_ref[:] != 0  # (blk, 1)
    if uniform == "sum":
        red = jnp.sum(jnp.where(m, rows, jnp.zeros_like(rows)), axis=0, keepdims=True)
        out_ref[:] = out_ref[:] + red
    elif uniform == "min":
        ident = reduce_identity(rows.dtype, "min")
        red = jnp.min(jnp.where(m, rows, ident), axis=0, keepdims=True)
        out_ref[:] = jnp.minimum(out_ref[:], red)
    elif uniform == "max":
        ident = reduce_identity(rows.dtype, "max")
        red = jnp.max(jnp.where(m, rows, ident), axis=0, keepdims=True)
        out_ref[:] = jnp.maximum(out_ref[:], red)
    else:
        s, mn, mx = _masked_reductions(rows, m)
        out_ref[:] = _select_combine(out_ref[:], op_ref[:], s, mn, mx)


def megastep_fold_pallas(
    state2d: Array,
    rows2d: Array,
    mask_i32: Array,
    op_row: Array,
    uniform,
    block_n: int,
    interpret: bool,
) -> Array:
    """``(1, F) arena ⊕ per-column masked-reduce((N, F) packed deltas)``.

    Caller (the dispatcher) canonicalizes: ``state2d`` ``(1, F)``, ``rows2d``
    ``(N, F)``, ``mask_i32`` ``(N, 1)`` int32, ``op_row`` ``(1, F)`` int32
    opcodes; ``uniform`` is the single shared reduction name or None for the
    per-column select body. Rows pad to a block multiple with mask 0.
    """
    from jax.experimental import pallas as pl

    n, f = rows2d.shape
    block_n = min(block_n, max(n, 1))
    n_pad = (-n) % block_n
    if n_pad:
        rows2d = jnp.pad(rows2d, ((0, n_pad), (0, 0)))
        mask_i32 = jnp.pad(mask_i32, ((0, n_pad), (0, 0)))
    grid = (rows2d.shape[0] // block_n,)
    return pl.pallas_call(
        functools.partial(_mega_fold_kernel, uniform=uniform),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, f), lambda i: (0, 0)),
            pl.BlockSpec((1, f), lambda i: (0, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, f), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, f), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, f), rows2d.dtype),
        interpret=interpret,
    )(state2d, op_row, mask_i32, rows2d)


def _mega_segment_body(op_ref, ids_ref, mask_ref, rows_ref, out_ref, num_segments, uniform):
    from jax.experimental import pallas as pl

    rows = rows_ref[:]  # (blk, F)
    ids = ids_ref[:]  # (blk, 1) int32
    m = mask_ref[:] != 0  # (blk, 1)

    def body(s, _):
        sel = m & (ids == s)
        if uniform == "sum":
            red = jnp.sum(jnp.where(sel, rows, jnp.zeros_like(rows)), axis=0)
            out_ref[pl.ds(s, 1), :] = out_ref[pl.ds(s, 1), :] + red[None, :]
        elif uniform == "min":
            ident = reduce_identity(rows.dtype, "min")
            red = jnp.min(jnp.where(sel, rows, ident), axis=0)
            out_ref[pl.ds(s, 1), :] = jnp.minimum(out_ref[pl.ds(s, 1), :], red[None, :])
        elif uniform == "max":
            ident = reduce_identity(rows.dtype, "max")
            red = jnp.max(jnp.where(sel, rows, ident), axis=0)
            out_ref[pl.ds(s, 1), :] = jnp.maximum(out_ref[pl.ds(s, 1), :], red[None, :])
        else:
            sm = jnp.sum(jnp.where(sel, rows, jnp.zeros_like(rows)), axis=0, keepdims=True)
            mn = jnp.min(
                jnp.where(sel, rows, reduce_identity(rows.dtype, "min")),
                axis=0,
                keepdims=True,
            )
            mx = jnp.max(
                jnp.where(sel, rows, reduce_identity(rows.dtype, "max")),
                axis=0,
                keepdims=True,
            )
            out_ref[pl.ds(s, 1), :] = _select_combine(
                out_ref[pl.ds(s, 1), :], op_ref[:], sm, mn, mx
            )
        return 0

    jax.lax.fori_loop(0, num_segments, body, 0)


def _mega_segment_kernel(
    state_ref, op_ref, ids_ref, mask_ref, rows_ref, out_ref, *, num_segments, uniform
):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _seed():
        out_ref[:] = state_ref[:]

    _mega_segment_body(op_ref, ids_ref, mask_ref, rows_ref, out_ref, num_segments, uniform)


def _mega_segment_q8_kernel(
    state_ref,
    op_ref,
    qcol_ref,
    flags_ref,
    codes_ref,
    scales_ref,
    ids_ref,
    mask_ref,
    rows_ref,
    out_ref,
    *,
    num_segments,
    uniform,
):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _seed():
        base = state_ref[:]  # (S, F)
        # decode-on-touch: flagged slots' quantized columns hold stale bits —
        # their true value is codes * scales in f32 then cast to the arena
        # dtype, the host codec's _decode_blocks arithmetic EXACTLY (int8 ->
        # f32 conversion is exact, one f32 mul, one cast — so a chaos run
        # that decodes host-side instead is bit-identical)
        dec = (codes_ref[:].astype(jnp.float32) * scales_ref[:]).astype(base.dtype)
        staged = (flags_ref[:] != 0) & (qcol_ref[:] != 0)  # (S,1) & (1,F) -> (S,F)
        out_ref[:] = jnp.where(staged, dec, base)

    _mega_segment_body(op_ref, ids_ref, mask_ref, rows_ref, out_ref, num_segments, uniform)


def megastep_segment_pallas(
    state2d: Array,
    rows2d: Array,
    ids_i32: Array,
    mask_i32: Array,
    op_row: Array,
    uniform,
    num_segments: int,
    block_n: int,
    interpret: bool,
    q8=None,
) -> Array:
    """``(S, F) arena ⊕ per-column segment-reduce((N, F) packed deltas)``.

    ``q8``, when given, is ``(flags (S, 1) i32, codes (S, F) i8, scales
    (S, F) f32, qcol (1, F) i32)`` — the staged compressed-resident slots the
    seed step decodes on touch. Pad rows carry mask 0 (their ids address
    nothing).
    """
    from jax.experimental import pallas as pl

    n, f = rows2d.shape
    block_n = min(block_n, max(n, 1))
    n_pad = (-n) % block_n
    if n_pad:
        rows2d = jnp.pad(rows2d, ((0, n_pad), (0, 0)))
        ids_i32 = jnp.pad(ids_i32, ((0, n_pad), (0, 0)))
        mask_i32 = jnp.pad(mask_i32, ((0, n_pad), (0, 0)))
    grid = (rows2d.shape[0] // block_n,)
    whole = lambda i: (0, 0)  # noqa: E731 - revisited whole-array blocks
    if q8 is None:
        return pl.pallas_call(
            functools.partial(
                _mega_segment_kernel, num_segments=num_segments, uniform=uniform
            ),
            grid=grid,
            in_specs=[
                pl.BlockSpec((num_segments, f), whole),
                pl.BlockSpec((1, f), whole),
                pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
                pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
                pl.BlockSpec((block_n, f), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((num_segments, f), whole),
            out_shape=jax.ShapeDtypeStruct((num_segments, f), rows2d.dtype),
            interpret=interpret,
        )(state2d, op_row, ids_i32, mask_i32, rows2d)
    flags, codes, scales, qcol = q8
    return pl.pallas_call(
        functools.partial(
            _mega_segment_q8_kernel, num_segments=num_segments, uniform=uniform
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((num_segments, f), whole),
            pl.BlockSpec((1, f), whole),
            pl.BlockSpec((1, f), whole),
            pl.BlockSpec((num_segments, 1), whole),
            pl.BlockSpec((num_segments, f), whole),
            pl.BlockSpec((num_segments, f), whole),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, f), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((num_segments, f), whole),
        out_shape=jax.ShapeDtypeStruct((num_segments, f), rows2d.dtype),
        interpret=interpret,
    )(state2d, op_row, qcol, flags, codes, scales, ids_i32, mask_i32, rows2d)
