"""Pallas TPU kernel: masked segment sum/min/max (the multi-stream scatter).

``Metric.update_state_segmented`` routes each batch row's delta into the
stream row addressed by ``segment_ids``. The XLA reference path is
``.at[ids].add/min/max`` on an identity-filled base — a scatter, which TPUs
serialize row by row (and for min/max cannot even sort-and-segment). This
kernel keeps the whole ``(S, F)`` stream state resident in VMEM as the
revisited output block and streams the batch rows through in blocks; for each
stream ``s`` it reduces the block under ``mask & (ids == s)`` on the VPU — a
compare-select-reduce per stream instead of N serialized scatter updates.
O(S·N·F) VPU work, zero scatters; for the engine's regime (S ≤ a few dozen
streams, row blocks in VMEM) that trade is the win.

Grid: one dimension over row blocks; the ``(S, F)`` output is revisited and
accumulated across the sequential grid steps (seeded with the carried state
at step 0).
"""
import functools

import jax
import jax.numpy as jnp

from metrics_tpu.ops.kernels.common import reduce_identity

Array = jax.Array


def _segment_kernel(state_ref, ids_ref, mask_ref, rows_ref, out_ref, *, fx, num_segments):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _seed():
        out_ref[:] = state_ref[:]

    rows = rows_ref[:]  # (blk, F)
    ids = ids_ref[:]  # (blk, 1) int32
    m = mask_ref[:] != 0  # (blk, 1)

    def body(s, _):
        sel = m & (ids == s)
        if fx == "sum":
            red = jnp.sum(jnp.where(sel, rows, jnp.zeros_like(rows)), axis=0)
            out_ref[pl.ds(s, 1), :] = out_ref[pl.ds(s, 1), :] + red[None, :]
        elif fx == "min":
            ident = reduce_identity(rows.dtype, "min")
            red = jnp.min(jnp.where(sel, rows, ident), axis=0)
            out_ref[pl.ds(s, 1), :] = jnp.minimum(out_ref[pl.ds(s, 1), :], red[None, :])
        else:
            ident = reduce_identity(rows.dtype, "max")
            red = jnp.max(jnp.where(sel, rows, ident), axis=0)
            out_ref[pl.ds(s, 1), :] = jnp.maximum(out_ref[pl.ds(s, 1), :], red[None, :])
        return 0

    jax.lax.fori_loop(0, num_segments, body, 0)


def segment_reduce_pallas(
    state2d: Array,
    rows2d: Array,
    ids_i32: Array,
    mask_i32: Array,
    fx: str,
    num_segments: int,
    block_n: int,
    interpret: bool,
) -> Array:
    """``(S, F) state ⊕ segment-reduce((N, F) rows by (N, 1) ids)``.

    Caller canonicalizes: ``state2d`` ``(S, F)``, ``rows2d`` ``(N, F)``,
    ``ids_i32``/``mask_i32`` ``(N, 1)`` int32, blocks pre-sized for VMEM.
    Pad rows carry mask 0, so their (arbitrary) ids address nothing.
    """
    from jax.experimental import pallas as pl

    n, f = rows2d.shape
    block_n = min(block_n, max(n, 1))
    n_pad = (-n) % block_n
    if n_pad:
        rows2d = jnp.pad(rows2d, ((0, n_pad), (0, 0)))
        ids_i32 = jnp.pad(ids_i32, ((0, n_pad), (0, 0)))
        mask_i32 = jnp.pad(mask_i32, ((0, n_pad), (0, 0)))
    grid = (rows2d.shape[0] // block_n,)
    return pl.pallas_call(
        functools.partial(_segment_kernel, fx=fx, num_segments=num_segments),
        grid=grid,
        in_specs=[
            pl.BlockSpec((num_segments, f), lambda i: (0, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, f), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((num_segments, f), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((num_segments, f), rows2d.dtype),
        interpret=interpret,
    )(state2d, ids_i32, mask_i32, rows2d)
