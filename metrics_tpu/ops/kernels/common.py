"""Shared pieces of the streaming-update kernel library.

The reduction identities live here (not in ``metric.py``) so both the XLA
reference lowerings and the Pallas kernel bodies fold masked-out rows with
the SAME element — the bit-parity contract between backends depends on the
two paths substituting identical identities.
"""
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

#: the reductions the kernel library implements — exactly the set
#: ``Metric._MASKED_FX`` serves through the delta masked/segmented paths
REDUCE_OPS = ("sum", "min", "max")

# VMEM working-set budget for the dominant per-grid-step block. Conservative:
# ~16 MB/core total, shared with Mosaic's own double buffering and the
# revisited accumulator block, so the row block gets half a MiB.
VMEM_BLOCK_BYTES = 1 << 19

# smallest/largest row-block sizes the kernels tile with; multiples of 8 so
# fp32 sublanes stay aligned (guide: min tile (8, 128))
_MIN_BLOCK_ROWS = 8
_MAX_BLOCK_ROWS = 1024


def reduce_identity(dtype: Any, fx: str) -> Any:
    """The identity element of sum/min/max over ``dtype`` (masked rows reduce
    to it). Matches the substitution the vmapped XLA path has always used."""
    if fx == "sum":
        return jnp.zeros((), dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf if fx == "min" else -jnp.inf, dtype)
    if jnp.dtype(dtype) == jnp.bool_:
        # min over bool is AND (identity True), max is OR (identity False) —
        # the megastep oracle evaluates every opcode's base even for dtypes
        # the kernels never take, so bool must not crash here
        return jnp.asarray(fx == "min", dtype)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if fx == "min" else info.min, dtype)


def combine(a: Array, b: Array, fx: str) -> Array:
    """Fold two partial reductions (the between-blocks combine)."""
    if fx == "sum":
        return a + b
    if fx == "min":
        return jnp.minimum(a, b)
    return jnp.maximum(a, b)


def stack_reduce(stacked: Array, fx: str) -> Array:
    """Fold a STATIC leading stack axis with ``fx``, dtype-preserving.

    The deferred-sync mesh merge (``Metric.merge_stacked_states``) folds the
    per-shard local states along their stack axis with the same pairwise
    combine the kernels use between blocks — a sequential fold rather than
    ``jnp.sum`` so small-int and bool dtypes never promote (``jnp.sum`` of an
    int16 stack returns int32; a merge must return the state's own dtype)."""
    stacked = jnp.asarray(stacked)
    out = stacked[0]
    for i in range(1, stacked.shape[0]):
        out = combine(out, stacked[i], fx)
    return out


def supported_dtype(dtype: Any) -> bool:
    """Dtypes the Pallas paths handle: f32/bf16 floats and 32-bit ints.

    Everything else routes to the XLA reference path — the dispatcher falls
    back, never errors. Sub-32-bit ints are excluded on purpose: ``jnp.sum``
    PROMOTES them (int16 rows sum to int32 — the pre-kernel runtime behavior),
    and bit-parity means reproducing that promotion, which a fixed-dtype
    kernel ref cannot; no metric state uses them. bool likewise (sum promotes
    to int32).
    """
    d = jnp.dtype(dtype)
    if jnp.issubdtype(d, jnp.floating):
        return d in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16))
    return jnp.issubdtype(d, jnp.integer) and d.itemsize == 4


def block_rows(row_bytes: int, budget: int = VMEM_BLOCK_BYTES) -> Optional[int]:
    """Row-block size whose (rows, features) VMEM tile fits ``budget``, or
    None when even the minimum block would not fit (the dispatcher then takes
    the XLA path — huge feature dims are exactly where the generic lowering
    is already memory-bound anyway)."""
    if row_bytes <= 0:
        return None
    blk = budget // row_bytes
    if blk < _MIN_BLOCK_ROWS:
        return None
    return int(min(_MAX_BLOCK_ROWS, (blk // _MIN_BLOCK_ROWS) * _MIN_BLOCK_ROWS))


def as_2d_rows(rows: Array, n_rows: int) -> Tuple[Array, Tuple[int, ...]]:
    """Collapse ``(N, *leaf)`` to the kernels' canonical ``(N, F)`` layout.

    Returns the reshaped array and the trailing leaf shape (for the inverse
    reshape of the reduced output). F is at least 1 (scalar leaves become one
    lane)."""
    trailing = tuple(int(d) for d in rows.shape[1:])
    f = 1
    for d in trailing:
        f *= d
    return jnp.reshape(rows, (n_rows, f)), trailing
