"""Pallas kernel library for the streaming-update hot path (ISSUE 4).

Three primitives behind a runtime backend dispatcher — see
``kernels/dispatch.py`` for the dispatch contract and ``docs/serving.md``
("Kernel dispatcher") for the serving-side story:

* :func:`fold_rows_masked` — fused masked row-delta reduction;
* :func:`segment_reduce_masked` — masked segment sum/min/max;
* :func:`histogram_accumulate` — fused masked/weighted bincount.

The whole-step megakernel tier (ISSUE 16) adds :func:`megastep_fold` /
:func:`megastep_segment` — ONE launch per arena dtype with per-column
reduction opcodes (``engine/megastep.py`` builds the plan; backends
``"megastep"`` / ``"megastep_interpret"``).

Smoke gate: ``make kernels-smoke`` (``metrics_tpu/ops/kernels/smoke.py``).
"""
from metrics_tpu.ops.kernels.common import REDUCE_OPS, reduce_identity, stack_reduce
from metrics_tpu.ops.kernels.dispatch import (
    BACKEND_ENV_VAR,
    BACKENDS,
    MAX_HIST_LENGTH,
    MEGASTEP_BACKENDS,
    current_backend,
    fold_rows_masked,
    histogram_accumulate,
    kernel_fault_scope,
    megastep_fold,
    megastep_segment,
    resolve_backend,
    segment_reduce_masked,
    set_default_backend,
    use_backend,
)

__all__ = [
    "BACKENDS",
    "BACKEND_ENV_VAR",
    "MAX_HIST_LENGTH",
    "MEGASTEP_BACKENDS",
    "REDUCE_OPS",
    "current_backend",
    "fold_rows_masked",
    "histogram_accumulate",
    "kernel_fault_scope",
    "megastep_fold",
    "megastep_segment",
    "reduce_identity",
    "resolve_backend",
    "segment_reduce_masked",
    "set_default_backend",
    "stack_reduce",
    "use_backend",
]
