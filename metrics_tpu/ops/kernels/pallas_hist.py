"""Pallas TPU kernel: fused masked/weighted histogram (bincount) accumulate.

The confusion-matrix family funnels through fixed-length bincounts
(``utils/data.py::_bincount``, ``confusion_matrix``'s ``target*C + preds``
mapping, ``calibration_error``'s three per-bin sums, the binned curves) —
all scatter-adds of ones/weights under XLA, which TPUs serialize. This kernel
reformulates the scatter as a compare + MXU contraction: each row block
builds its one-hot membership matrix ``(blk, L)`` against a lane iota and
contracts it with the weight columns ``(blk, K)`` on the MXU, accumulating
``(L, K)`` partial histograms into the revisited output across sequential
grid steps. One pass over the indices, zero scatters.

Exactness: one-hot entries are exactly 0/1, so every product is exact and the
f32 accumulation is exact while column totals stay below 2**24 — the
dispatcher enforces that bound for integer counts (row count < 2**24) and
routes bigger inputs to the XLA path. Float weights see only the usual sum
reassociation (same class of difference as any XLA reduction re-order).

Semantics match ``jnp.bincount(x, length=L)``: negative indices clip to bin
0 (the dispatcher pre-clips), indices ``>= L`` match no bin and drop —
exactly the scatter's out-of-bounds drop.
"""
import functools

import jax
import jax.numpy as jnp

Array = jax.Array


def _hist_kernel(idx_ref, w_ref, out_ref, *, length, compute_dtype):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    idx = idx_ref[:]  # (blk, 1) int32, negatives pre-clipped to 0; >=L drops
    w = w_ref[:]  # (blk, K), mask/pad already folded in as zeros
    blk = idx.shape[0]
    bins = jax.lax.broadcasted_iota(jnp.int32, (blk, length), 1)
    # the MXU ingests the one-hot at the weights' own width — int8 for counts
    # (EQuARX-style low-precision contraction, int32 per-block accumulation),
    # bf16 for bf16 weights — and every product is exact (one-hot entries are
    # 0/1), so the f32 cross-block accumulation bound is the ONLY exactness
    # condition either way
    onehot = (idx == bins).astype(compute_dtype)  # (blk, L)
    preferred = jnp.int32 if compute_dtype == jnp.int8 else jnp.float32
    contrib = jax.lax.dot_general(  # (L, K): contract the block dim on the MXU
        onehot, w, (((0,), (0,)), ((), ())), preferred_element_type=preferred
    )
    out_ref[:] = out_ref[:] + contrib.astype(jnp.float32)


def histogram_pallas(
    idx_i32: Array,
    weights: Array,
    length: int,
    block_n: int,
    interpret: bool,
) -> Array:
    """``(L, K)`` f32 histogram of pre-clipped ``(N, 1)`` indices with
    ``(N, K)`` weight columns (masked/pad rows carry zero weight).

    The weights' dtype picks the MXU input width: int8 (the dispatcher's
    unweighted-counts path), bf16, or f32. Accumulation is f32 regardless
    (``preferred_element_type``), so integer counts stay exact under the
    dispatcher's ``N < 2**24`` bound on every width.
    """
    from jax.experimental import pallas as pl

    n, k = weights.shape
    block_n = min(block_n, max(n, 1))
    n_pad = (-n) % block_n
    if n_pad:
        idx_i32 = jnp.pad(idx_i32, ((0, n_pad), (0, 0)))
        weights = jnp.pad(weights, ((0, n_pad), (0, 0)))
    grid = (weights.shape[0] // block_n,)
    return pl.pallas_call(
        functools.partial(_hist_kernel, length=length, compute_dtype=weights.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, k), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((length, k), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((length, k), jnp.float32),
        interpret=interpret,
    )(idx_i32, weights)
