"""Kernel-library smoke check: ``python -m metrics_tpu.ops.kernels.smoke``.

The CI-shaped, CPU-safe proof of the kernel dispatcher's claims, in seconds
(``make kernels-smoke``):

1. interpret-mode parity — all three Pallas kernels (masked fold, masked
   segment reduce, fused histogram) reproduce the XLA reference path on the
   same inputs: bit-exact for int states, reassociation-tolerance for floats;
2. dispatch sanity — ``"auto"`` resolves to ``"xla"`` off-TPU, ``use_backend``
   overrides scope correctly and restores on exit, unknown names raise;
3. engine integration — a ``StreamingEngine`` with
   ``kernel_backend="pallas_interpret"`` serves a ragged stream to the same
   values as the ``"xla"`` engine, inside the same compile cap
   (≤ len(buckets) update programs + 1 compute), and the two engines' program
   keys never collide in a SHARED AotCache (backend is part of the identity);
4. megastep phase (ISSUE 16) — the whole-step fused tier
   (``kernel_backend="megastep_interpret"``) serves the same stream to the
   same values under the SAME shared cache, replaying the stream compiles
   ZERO new programs (steady state is compile-free), and the traced step's
   fused-grid launch count equals the eligible dtype count for two
   collections with different LEAF counts — the O(dtypes) pin, constant in
   leaves.

Exits nonzero on any violated claim. Compiled-Pallas (real TPU) parity lives
in ``tests/ops/test_kernels_tpu.py``, marked ``requires_tpu``.
"""
import os
import sys

import numpy as np


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    from metrics_tpu.ops.kernels import (
        fold_rows_masked,
        histogram_accumulate,
        resolve_backend,
        segment_reduce_masked,
        use_backend,
    )

    ok = True

    def check(name: str, cond: bool) -> None:
        nonlocal ok
        if not cond:
            print(f"FAIL: {name}")
            ok = False

    def maxerr(a, b) -> float:  # host f64 compare: no jax x64 flag needed
        return float(np.max(np.abs(np.asarray(a, np.float64) - np.asarray(b, np.float64))))

    rng = np.random.RandomState(0)
    n, f, s_streams, length = 53, 6, 5, 17
    rows_f = jnp.asarray(rng.randn(n, f).astype(np.float32))
    rows_i = jnp.asarray(rng.randint(-40, 40, (n, f)).astype(np.int32))
    state_f = jnp.asarray(rng.randn(f).astype(np.float32))
    state_i = jnp.asarray(rng.randint(-40, 40, (f,)).astype(np.int32))
    mask = jnp.asarray(rng.rand(n) > 0.35)
    ids = jnp.asarray(rng.randint(0, s_streams, (n,)).astype(np.int32))
    idx = jnp.asarray(rng.randint(-2, length + 2, (n,)).astype(np.int32))  # OOR: low clips, high drops
    weights = jnp.asarray(rng.rand(n, 3).astype(np.float32))

    # 1. interpret parity vs the XLA reference path
    for fx in ("sum", "min", "max"):
        for state, rows, exact in ((state_f, rows_f, False), (state_i, rows_i, True)):
            with use_backend("xla"):
                want = fold_rows_masked(state, rows, mask, fx)
            with use_backend("pallas_interpret"):
                got = fold_rows_masked(state, rows, mask, fx)
            err = maxerr(got, want)
            check(f"fold {fx} parity ({rows.dtype})", err == 0.0 if exact else err < 1e-4)

            st = jnp.tile(state[None], (s_streams, 1))
            with use_backend("xla"):
                want = segment_reduce_masked(st, rows, mask, ids, s_streams, fx)
            with use_backend("pallas_interpret"):
                got = segment_reduce_masked(st, rows, mask, ids, s_streams, fx)
            err = maxerr(got, want)
            check(f"segment {fx} parity ({rows.dtype})", err == 0.0 if exact else err < 1e-4)

    with use_backend("xla"):
        want_c = histogram_accumulate(idx, length)
        want_w = histogram_accumulate(idx, length, weights=weights, mask=mask)
    with use_backend("pallas_interpret"):
        got_c = histogram_accumulate(idx, length)
        got_w = histogram_accumulate(idx, length, weights=weights, mask=mask)
    check("histogram counts bit-parity", bool(jnp.all(got_c == want_c)))
    check("histogram == jnp.bincount on raw OOR indices", bool(jnp.all(got_c == jnp.bincount(idx, length=length))))
    check("histogram weighted parity", maxerr(got_w, want_w) < 1e-4)

    # 2. dispatch sanity
    check("auto resolves off-TPU to xla", resolve_backend("auto") in ("xla", "pallas"))
    if jax.default_backend() not in ("tpu", "axon"):
        check("auto == xla on CPU", resolve_backend("auto") == "xla")
    with use_backend("pallas_interpret"):
        check("use_backend overrides", resolve_backend() == "pallas_interpret")
        with use_backend("xla"):
            check("use_backend nests", resolve_backend() == "xla")
        check("use_backend unwinds", resolve_backend() == "pallas_interpret")
    try:
        resolve_backend("mosaic")
        check("unknown backend raises", False)
    except ValueError:
        pass

    # 3. engine integration under a SHARED cache: parity, compile cap, no
    #    cross-backend program collisions
    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import AotCache, EngineConfig, StreamingEngine

    buckets = (8, 32)
    batches = [
        (rng.rand(k).astype(np.float32), (rng.rand(k) > 0.5).astype(np.int32))
        for k in (5, 17, 8, 32, 3)
    ]
    cache = AotCache()
    results, misses = {}, {}
    for kb in ("xla", "pallas_interpret"):
        engine = StreamingEngine(
            MetricCollection([Accuracy(), MeanSquaredError()]),
            EngineConfig(buckets=buckets, kernel_backend=kb),
            aot_cache=cache,
        )
        before = cache.misses
        with engine:
            for p, t in batches:
                engine.submit(p, t)
            results[kb] = {k: float(v) for k, v in engine.result().items()}
        misses[kb] = cache.misses - before
    check(
        "engine parity across kernel backends",
        all(abs(results["xla"][k] - results["pallas_interpret"][k]) < 1e-6 for k in results["xla"]),
    )
    for kb, m in misses.items():
        check(f"compile cap with kernel_backend={kb}", 0 < m <= len(buckets) + 1)
    # if the second engine had collided with the first's executables it would
    # have compiled nothing — distinct backends MUST compile their own set
    check("backends never share executables", misses["pallas_interpret"] > 0)

    # 4. megastep phase (ISSUE 16): fused-tier parity under the same shared
    #    cache, zero steady compiles, O(dtypes) launch pin constant in leaves
    engine = StreamingEngine(
        MetricCollection([Accuracy(), MeanSquaredError()]),
        EngineConfig(buckets=buckets, kernel_backend="megastep_interpret"),
        aot_cache=cache,
    )
    before = cache.misses
    with engine:
        for p, t in batches:
            engine.submit(p, t)
        first_pass = {k: float(v) for k, v in engine.result().items()}
        check(
            "megastep parity vs xla engine",
            all(abs(first_pass[k] - results["xla"][k]) < 1e-6 for k in first_pass),
        )
        check("megastep compiles its own set", cache.misses > before)
        warm = cache.misses
        for p, t in batches:  # replay: every bucket shape already compiled
            engine.submit(p, t)
        engine.result()
        check("megastep zero steady compiles", cache.misses == warm)
        check("megastep no fallbacks for the delta collection",
              engine.stats.kernel_fallbacks_by_reason() == {})

    from metrics_tpu.classification import ConfusionMatrix
    from metrics_tpu.engine.megastep import flat_reductions
    from metrics_tpu.ops.kernels import use_backend as _ub

    def _mega_launches(coll):
        """(fused-grid launches, eligible dtypes, state leaves) of the traced
        masked step — the jaxpr op-count regression pin."""
        eng = StreamingEngine(
            coll, EngineConfig(buckets=(8,), kernel_backend="megastep_interpret"),
            aot_cache=cache,
        )
        plan = eng._megastep_plan
        arena = {
            k: jnp.zeros((sz,), jnp.dtype(k))
            for k, sz in plan.layout.buffer_sizes().items()
        }
        args = (
            jnp.zeros((8,), jnp.float32), jnp.zeros((8,), jnp.int32),
            jnp.ones((8,), bool),
        )

        def step(arena, p, t, m):
            with _ub("megastep_interpret"):
                return plan.apply_masked(arena, (p, t), {}, m)

        jaxpr = jax.make_jaxpr(step)(arena, *args)

        def walk(jx):
            names = []
            for eqn in jx.eqns:
                if eqn.primitive.name == "pallas_call":
                    names.append(str(eqn.params.get("name_and_src_info", "")))
                for v in eqn.params.values():
                    if hasattr(v, "eqns"):
                        names.extend(walk(v))
                    elif hasattr(v, "jaxpr"):
                        names.extend(walk(v.jaxpr))
            return names

        mega = [nm for nm in walk(jaxpr.jaxpr) if "_mega_" in nm]
        return len(mega), len(plan.eligible_keys()), len(flat_reductions(coll))

    small = _mega_launches(MetricCollection([Accuracy(), MeanSquaredError()]))
    large = _mega_launches(MetricCollection(
        [Accuracy(), MeanSquaredError(), ConfusionMatrix(num_classes=3)]
    ))
    check("megastep one grid per dtype (small)", small[0] == small[1])
    check("megastep one grid per dtype (large)", large[0] == large[1])
    check("megastep pin covers more leaves", large[2] > small[2])
    check(
        "megastep launch count constant in leaves",
        large[0] == small[0] and large[1] == small[1],
    )

    if ok:
        print(
            "kernels-smoke PASS: interpret-mode parity (fold/segment/histogram, "
            "int bit-exact + float tolerance), dispatch sanity, engine parity "
            f"across backends (compile caps {misses}), megastep fused tier "
            f"(zero steady compiles, {small[0]} grids for {small[2]} -> "
            f"{large[2]} leaves)"
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
