"""Pallas TPU kernel for the binned PR-curve update.

The binned curve metrics accumulate TP/FP/FN counts of shape (C, T) from a batch of
probabilities (N, C) against T fixed thresholds
(``metrics_tpu/classification/binned_precision_recall.py``). The jnp formulation
broadcasts an (N, C, T) boolean intermediate; for corpus-scale N and fine threshold
grids that intermediate is pure HBM traffic. This kernel streams N in blocks through
VMEM and loops the (small) threshold axis on the VPU, so HBM sees only the (N, C)
inputs once and the (T, C) outputs — O(N*C + T*C) instead of O(N*C*T).

Grid: one dimension over N-blocks; outputs are revisited and accumulated across grid
steps (zeroed at step 0).

Measured on v5e: XLA's own fusion of the jnp formulation already avoids materialising
the (N, C, T) intermediate at the benchmark sizes (compare+reduce fuse into one
kernel), so the Pallas path is parity rather than a win there — it exists as the
guaranteed-streaming fallback for extreme (N*C*T) configurations and as the template
that the collection-update megakernel grew from
(``ops/kernels/pallas_megastep.py``, ISSUE 16: one grid per arena dtype fusing
every leaf's masked fold, the segment scatter, and the arena re-pack).
"""
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def binned_counts_jnp(preds: Array, target_bool: Array, thresholds: Array) -> Tuple[Array, Array, Array]:
    """Reference jnp path: returns (TPs, FPs, FNs) each (C, T)."""
    t3 = target_bool[:, :, None]
    p3 = preds[:, :, None] >= thresholds[None, None, :]
    tps = jnp.sum(t3 & p3, axis=0).astype(jnp.float32)
    fps = jnp.sum(~t3 & p3, axis=0).astype(jnp.float32)
    fns = jnp.sum(t3 & ~p3, axis=0).astype(jnp.float32)
    return tps, fps, fns


def _binned_kernel(thr_ref, preds_ref, target_ref, tp_ref, fp_ref, fn_ref):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        tp_ref[:] = jnp.zeros_like(tp_ref)
        fp_ref[:] = jnp.zeros_like(fp_ref)
        fn_ref[:] = jnp.zeros_like(fn_ref)

    preds = preds_ref[:]          # (N_blk, C) f32
    target = target_ref[:]        # (N_blk, C) f32 in {0, 1}
    num_t = thr_ref.shape[0]

    def body(t, _):
        thr = thr_ref[t]
        mask = (preds >= thr).astype(jnp.float32)
        tp = jnp.sum(target * mask, axis=0)
        fp = jnp.sum((1.0 - target) * mask, axis=0)
        fn = jnp.sum(target * (1.0 - mask), axis=0)
        tp_ref[pl.ds(t, 1), :] += tp[None, :]
        fp_ref[pl.ds(t, 1), :] += fp[None, :]
        fn_ref[pl.ds(t, 1), :] += fn[None, :]
        return 0

    jax.lax.fori_loop(0, num_t, body, 0)


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def binned_counts_pallas(
    preds: Array,
    target_bool: Array,
    thresholds: Array,
    block_n: int = 1024,
    interpret: bool = False,
) -> Tuple[Array, Array, Array]:
    """Pallas path: returns (TPs, FPs, FNs) each (C, T). Compiled on TPU;
    ``interpret=True`` runs the same kernel logic anywhere (CPU parity)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, c = preds.shape
    t = thresholds.shape[0]
    block_n = min(block_n, n)
    n_pad = (-n) % block_n
    if n_pad:
        # padded rows carry target=0 and preds=-inf: contribute nothing
        preds = jnp.pad(preds, ((0, n_pad), (0, 0)), constant_values=-jnp.inf)
        target_bool = jnp.pad(target_bool, ((0, n_pad), (0, 0)))
    target_f = target_bool.astype(jnp.float32)
    grid = (preds.shape[0] // block_n,)

    out_shape = [jax.ShapeDtypeStruct((t, c), jnp.float32)] * 3
    tp, fp, fn = pl.pallas_call(
        _binned_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # thresholds, full
            pl.BlockSpec((block_n, c), lambda i: (i, 0)),
            pl.BlockSpec((block_n, c), lambda i: (i, 0)),
        ],
        out_specs=[pl.BlockSpec((t, c), lambda i: (0, 0))] * 3,
        out_shape=out_shape,
        interpret=interpret,
    )(thresholds, preds.astype(jnp.float32), target_f)
    return tp.T, fp.T, fn.T


def binned_counts(preds: Array, target_bool: Array, thresholds: Array) -> Tuple[Array, Array, Array]:
    """Dispatch through the kernel-backend selection (``ops/kernels/dispatch``).

    Selection order, most specific wins: the :func:`use_backend` context
    (what ``EngineConfig.kernel_backend`` installs around program builds) >
    :func:`set_default_backend` > the ``METRICS_TPU_KERNEL_BACKEND``
    environment variable > ``"auto"`` (Pallas on TPU, XLA elsewhere). The
    ``megastep``/``megastep_interpret`` tier (ISSUE 16) takes the SAME Pallas
    lowering here — this kernel is a per-metric primitive, not an arena leaf,
    so the megakernel never absorbs it; interpret variants run
    ``interpret=True`` and re-raise kernel failures so CPU parity tests can
    never silently test the wrong path.

    Runnable example (CPU-safe)::

        from metrics_tpu.ops.kernels import use_backend
        with use_backend("pallas_interpret"):     # or "megastep_interpret"
            tp, fp, fn = binned_counts(preds, target_bool, thresholds)

    The backend decision is made at trace time (it depends only on
    configuration and the platform, never on traced values), so this is safe
    to call inside jit/shard_map — the Pallas path lowers with the
    surrounding computation on TPU.
    """
    from metrics_tpu.ops.kernels import resolve_backend

    backend = resolve_backend()
    interpret = backend in ("pallas_interpret", "megastep_interpret")
    if backend != "xla" and preds.ndim == 2:
        try:
            return binned_counts_pallas(
                preds, target_bool, thresholds, interpret=interpret
            )
        except Exception:
            if interpret:
                raise  # CPU parity tests must see kernel failures
            # Catches eager-mode and trace-time failures only. When called under an
            # outer jit, a Mosaic *compile* failure surfaces when the outer jit
            # compiles — outside this try. That's accepted: the kernel's shapes are
            # the metric's static (block_n, C)/(T, C) tiles, validated on TPU CI.
            pass
    return binned_counts_jnp(preds, target_bool, thresholds)
