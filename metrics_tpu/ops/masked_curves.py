"""Masked exact curve-metric kernels for static-capacity states.

SURVEY §7.1: exact AUROC/AP keep ``(buffer[capacity], count)`` states so the
whole metric — update, mesh sync (fixed-shape cat all_gather), compute — runs
inside one jit/shard_map region. The kernels here compute EXACT (sort-based,
tie-aware) values over a buffer where only ``valid`` entries are real:

* ``masked_binary_auroc`` — Mann-Whitney U with average-rank tie handling,
  algebraically identical to trapezoidal ROC integration (what sklearn's
  ``roc_auc_score`` and the eager path compute);
* ``masked_binary_average_precision`` — step integration at distinct
  thresholds (sklearn's ``average_precision_score`` definition).

Everything is static-shape: one sort + segment reductions, no host round-trip.
Degenerate inputs (single-class) return NaN — in-trace code cannot raise, and
NaN is the documented sentinel the eager path's error maps to.
"""
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _tie_segments(s: Array) -> Tuple[Array, Array]:
    """(group-start mask, segment ids) for runs of equal values in sorted ``s``."""
    start = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    return start, jnp.cumsum(start) - 1


def _desc_sorted(scores: Array, labels: Array, valid: Array) -> Tuple[Array, Array, Array]:
    """Descending-score sort with invalid entries last: returns (scores,
    valid, positive-indicator), each sorted, as f32/bool/f32."""
    keys = jnp.where(valid, scores.astype(jnp.float32), -jnp.inf)
    order = jnp.argsort(-keys, stable=True)
    v = valid[order]
    t = jnp.where(v, (labels[order] > 0).astype(jnp.float32), 0.0)
    return keys[order], v, t


def _masked_average_ranks(scores: Array, valid: Array) -> Array:
    """1-based average ranks (ascending) among valid entries; 0 for invalid.

    Ties (equal scores among valid entries) receive the mean of the positions
    they span — the correction ``roc_auc_score`` applies.
    """
    n = scores.shape[0]
    keys = jnp.where(valid, scores, jnp.inf)  # invalid sort last
    order = jnp.argsort(keys, stable=True)
    s = keys[order]
    v = valid[order]
    pos = jnp.arange(1, n + 1, dtype=jnp.float32)
    _, seg = _tie_segments(s)
    sum_pos = jax.ops.segment_sum(jnp.where(v, pos, 0.0), seg, num_segments=n)
    cnt = jax.ops.segment_sum(v.astype(jnp.float32), seg, num_segments=n)
    avg = sum_pos / jnp.maximum(cnt, 1.0)
    ranks_sorted = jnp.where(v, avg[seg], 0.0)
    return jnp.zeros(n, jnp.float32).at[order].set(ranks_sorted)


def masked_binary_auroc(scores: Array, labels: Array, valid: Array) -> Array:
    """Exact binary AUROC over the valid entries of a capacity buffer.

    ``AUROC = (sum of positive ranks - P(P+1)/2) / (P * N)`` — the Mann-Whitney
    statistic; NaN when either class is absent.
    """
    valid = valid.astype(bool)
    pos = valid & (labels > 0)
    ranks = _masked_average_ranks(scores.astype(jnp.float32), valid)
    p = jnp.sum(pos.astype(jnp.float32))
    nn = jnp.sum(valid.astype(jnp.float32)) - p
    s_pos = jnp.sum(jnp.where(pos, ranks, 0.0))
    denom = p * nn
    return jnp.where(denom > 0, (s_pos - p * (p + 1) / 2) / jnp.maximum(denom, 1.0), jnp.nan)


def masked_binary_average_precision(scores: Array, labels: Array, valid: Array) -> Array:
    """Exact binary average precision (step integration at distinct thresholds)
    over the valid entries of a capacity buffer. NaN when no positives."""
    n = scores.shape[0]
    valid = valid.astype(bool)
    s, v, t = _desc_sorted(scores, labels, valid)
    tp = jnp.cumsum(t)
    fp = jnp.cumsum(jnp.where(v, 1.0 - t, 0.0))
    # distinct-threshold runs; evaluate precision at each run END
    _, seg = _tie_segments(s)
    run_tp = jax.ops.segment_sum(t, seg, num_segments=n)[seg]  # per-position: its run's TP
    end = jnp.concatenate([s[1:] != s[:-1], jnp.ones((1,), bool)])
    prec = tp / jnp.maximum(tp + fp, 1.0)
    contrib = jnp.where(end & v, run_tp * prec, 0.0)
    p_total = jnp.sum(t)
    return jnp.where(p_total > 0, jnp.sum(contrib) / jnp.maximum(p_total, 1.0), jnp.nan)


def _masked_clf_curve(scores: Array, labels: Array, valid: Array) -> Tuple[Array, Array, Array]:
    """Per-position cumulative ``(fps, tps, thresholds)`` in descending-score
    order over the valid entries of a capacity buffer — the static-shape
    ``_binary_clf_curve``.

    The classic curve emits one point per DISTINCT threshold (data-dependent
    length). Here every buffer slot emits a point, with tie-group interiors
    linearly interpolated between the group's endpoints in COUNT space
    (fps/tps). For ROC that makes the interior points collinear with the
    dedup'd curve (fpr/tpr are linear in the counts), so trapezoid integration
    is identical; PR precision is a ratio of counts, so its interiors follow
    the count-interpolated curve while group endpoints stay exact. Invalid
    slots repeat the final totals with the lowest valid threshold.
    """
    n = scores.shape[0]
    f32 = jnp.float32
    s, v_bool, t = _desc_sorted(scores, labels, valid)
    v = v_bool.astype(f32)
    w = v - t  # negatives
    tps_raw = jnp.cumsum(t)
    fps_raw = jnp.cumsum(w)
    pos = jnp.arange(n)
    start, seg = _tie_segments(s)
    seg_start = jax.lax.cummax(jnp.where(start, pos, 0))
    sum_seg = partial(jax.ops.segment_sum, segment_ids=seg, num_segments=n)
    grp_tp = sum_seg(t)[seg]
    grp_fp = sum_seg(w)[seg]
    grp_len = sum_seg(jnp.ones_like(t))[seg]
    tp_end = jax.ops.segment_max(tps_raw, seg, num_segments=n)[seg]
    fp_end = jax.ops.segment_max(fps_raw, seg, num_segments=n)[seg]
    frac = (pos - seg_start + 1).astype(f32) / jnp.maximum(grp_len, 1.0)
    tps = (tp_end - grp_tp) + frac * grp_tp
    fps = (fp_end - grp_fp) + frac * grp_fp
    lowest = jnp.min(jnp.where(valid, scores.astype(f32), jnp.inf))
    thresholds = jnp.where(jnp.isfinite(s), s, lowest)
    return fps, tps, thresholds


def masked_binary_roc(scores: Array, labels: Array, valid: Array) -> Tuple[Array, Array, Array]:
    """Static-shape exact ROC: ``(fpr, tpr, thresholds)``, each ``(n+1,)``.

    Point order and the prepended origin follow the eager path
    (``functional/classification/roc.py``); a class with no positives (or no
    negatives) yields a zero tpr (fpr) like the reference, without the eager
    warning (in-trace code cannot warn).
    """
    fps, tps, thresholds = _masked_clf_curve(scores, labels, valid)
    tps = jnp.concatenate([jnp.zeros(1, tps.dtype), tps])
    fps = jnp.concatenate([jnp.zeros(1, fps.dtype), fps])
    thresholds = jnp.concatenate([thresholds[0:1] + 1, thresholds])
    fpr = jnp.where(fps[-1] > 0, fps / jnp.maximum(fps[-1], 1.0), jnp.zeros_like(fps))
    tpr = jnp.where(tps[-1] > 0, tps / jnp.maximum(tps[-1], 1.0), jnp.zeros_like(tps))
    return fpr, tpr, thresholds


def masked_binary_pr_curve(scores: Array, labels: Array, valid: Array) -> Tuple[Array, Array, Array]:
    """Static-shape exact PR curve: ``(precision, recall, thresholds)`` of
    lengths ``(n+1, n+1, n)`` in the eager path's layout — recall
    non-increasing, thresholds ascending, final ``(precision=1, recall=0)``
    point appended (reference ``precision_recall_curve.py`` reverses the
    descending-score scan the same way).

    Tie-group ENDPOINTS are exact (they are the classic distinct-threshold
    points); tie-group interiors interpolate the cumulative counts linearly —
    the standard PR count-interpolation, which is NOT a straight line in
    (recall, precision) space. Step/AP integration from the endpoints is
    unchanged; a trapezoid over all points follows the count-interpolated
    curve, not the chord between endpoints. Points past the first full-recall
    position (which the eager path slices off at ``last_ind``) and padding
    slots all REPEAT the full-recall endpoint, so the point set matches the
    classic curve's.
    """
    n = scores.shape[0]
    fps, tps, thresholds = _masked_clf_curve(scores, labels, valid)
    p_total_raw = tps[-1]
    # clamp everything past the first full-recall point to that point — the
    # eager path cuts the arrays there; static shapes repeat instead
    first_full = jnp.argmax(tps >= p_total_raw)
    after = jnp.arange(n) > first_full
    keep = p_total_raw > 0
    fps = jnp.where(after & keep, fps[first_full], fps)
    tps = jnp.where(after & keep, p_total_raw, tps)
    thresholds = jnp.where(after & keep, thresholds[first_full], thresholds)
    precision = tps / jnp.maximum(tps + fps, 1e-38)
    p_total = tps[-1]
    recall = jnp.where(p_total > 0, tps / jnp.maximum(p_total, 1.0), jnp.ones_like(tps))
    precision = jnp.concatenate([precision[::-1], jnp.ones(1, precision.dtype)])
    recall = jnp.concatenate([recall[::-1], jnp.zeros(1, recall.dtype)])
    return precision, recall, thresholds[::-1]


def average_per_class(per_class: Array, support: Array, average: Optional[str]) -> Array:
    """Average a per-class metric vector, ignoring NaN (unobserved) classes —
    the same tolerance the eager path applies (nanmean / NaN-zeroed weights)."""
    if average in ("none", None):
        return per_class
    if average == "macro":
        return jnp.nanmean(per_class)
    if average != "weighted":
        raise ValueError(f"unknown average for capacity mode: {average}")
    w = jnp.where(jnp.isnan(per_class), 0.0, support.astype(jnp.float32))
    vals = jnp.where(jnp.isnan(per_class), 0.0, per_class)
    total_w = jnp.sum(w)
    # all classes degenerate -> NaN sentinel (like macro's nanmean), not a
    # confident-looking 0.0
    return jnp.where(total_w > 0, jnp.sum(vals * w) / jnp.maximum(total_w, 1.0), jnp.nan)


@partial(jax.jit, static_argnames=("average",))
def masked_multilabel_auroc(probs: Array, labels: Array, valid: Array, average: Optional[str] = "macro") -> Array:
    """Per-column AUROC over (capacity, C) probabilities and binary labels
    (one-hot for multiclass OVR — identical layout)."""
    per_class = jax.vmap(
        lambda p_col, t_col: masked_binary_auroc(p_col, t_col, valid), in_axes=(1, 1)
    )(probs, labels)
    support = jnp.sum(jnp.where(valid[:, None], labels, 0), axis=0)
    return average_per_class(per_class, support, average)


@partial(jax.jit, static_argnames=("average",))
def masked_multilabel_average_precision(
    probs: Array, labels: Array, valid: Array, average: Optional[str] = "macro"
) -> Array:
    """Per-column AP over (capacity, C) probabilities and binary labels."""
    per_class = jax.vmap(
        lambda p_col, t_col: masked_binary_average_precision(p_col, t_col, valid), in_axes=(1, 1)
    )(probs, labels)
    support = jnp.sum(jnp.where(valid[:, None], labels, 0), axis=0)
    return average_per_class(per_class, support, average)
