"""Per-op cost attribution + single-program MFU calibration.

Perf claims in this repo must be *attributed, not asserted* (VERDICT r5 weak
#1/#2: an "impossible" encoder MFU and an unprofiled conv-tiling explanation
both survived a round because no per-op breakdown existed). Two tools fix that:

**Attribution** (``op_costs`` / ``attribution_table``): walk the jaxpr of any
jittable function, cost every primitive analytically from its avals (FLOPs,
bytes moved, and a structural MXU-tile efficiency for convs/dots), and group by
the flax ``name_stack`` — so "the stem wastes the MXU" becomes a sorted table
with per-layer numbers. The analytic total is cross-checked against XLA's own
``cost_analysis`` on the compiled module. Works on any backend (the FLOP
geometry is platform-independent); on a real TPU, ``capture_trace`` wraps the
same call in a ``jax.profiler`` trace so measured per-fusion times can be read
in TensorBoard against the same op names.

**Calibration** (``single_program_calibration``): the r5 bench reported
``encoder_mfu: 1.40`` because the matmul-ceiling probe and the encoder epoch
compiled as separate executables that a heterogeneous accelerator pool could
route to different chips. Here both run as dynamic-trip-count ``fori_loop``s
inside ONE compiled program, so the K-pair marginals for workload and ceiling
provably hit the same accelerator and their ratio is a utilization in (0, 1]
by construction (published MFU methodology — e.g. arXiv:2204.06514 — measures
ceiling and workload under one attribution protocol; this is that protocol
compressed into one executable).

MXU structural model (see /opt-style TPU docs: 128x128 systolic MXU, (8, 128)
f32 / (16, 128) bf16 vregs): a conv/dot is a GEMM with M = batch x out-spatial,
K = reduction, N = output features; the array pads N and K to multiples of 128
and M to the sublane tile, so the structural efficiency is
``(M/ceil8(M)) * (K/ceil128(K)) * (N/ceil128(N))`` — an upper bound on
achievable MFU for that op, not a measurement.
"""
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_MXU_LANES = 128      # systolic array width: output-feature (N) and reduction (K) dims
_SUBLANE = 8          # f32 sublane tile for the M dim


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


def _mxu_efficiency(m: int, k: int, n: int) -> float:
    """Structural (tile-padding) efficiency of an MxKxN GEMM on the MXU."""
    if min(m, k, n) <= 0:
        return 0.0
    return (
        (m / _ceil_to(m, _SUBLANE))
        * (k / _ceil_to(k, _MXU_LANES))
        * (n / _ceil_to(n, _MXU_LANES))
    )


@dataclass
class OpCost:
    """Analytic cost of one jaxpr equation."""

    name: str                     # flax name_stack path ("InceptionV3/BasicConv2d_0/Conv_0")
    kind: str                     # primitive name ("conv_general_dilated", "dot_general", ...)
    flops: float                  # 2*MACs for conv/dot, 1/elem for pointwise, 0 unknown
    bytes: float                  # operands + results, a traffic lower bound
    out_shape: Tuple[int, ...]
    mxu_util: Optional[float] = None   # structural tile efficiency for conv/dot, else None
    gemm_mkn: Optional[Tuple[int, int, int]] = None


def _aval_bytes(aval: Any) -> float:
    try:
        return float(np.prod(aval.shape, dtype=np.int64)) * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0.0


def _numel(aval: Any) -> float:
    try:
        return float(np.prod(aval.shape, dtype=np.int64))
    except Exception:
        return 0.0


# pointwise/reduce primitives costed at 1 flop per output/input element; anything
# not listed here and not conv/dot is carried with flops=0 (bytes still count)
_POINTWISE = {
    "add", "sub", "mul", "div", "max", "min", "exp", "log", "tanh", "logistic",
    "rsqrt", "sqrt", "pow", "integer_pow", "neg", "abs", "floor", "ceil",
    "select_n", "clamp", "erf", "erf_inv", "sign", "cos", "sin", "atan2",
}
_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and", "reduce_or", "argmax", "argmin"}


def _cost_conv(eqn: Any) -> Tuple[float, Optional[Tuple[int, int, int]]]:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    dnums = eqn.params["dimension_numbers"]
    fgc = int(eqn.params.get("feature_group_count", 1))
    bgc = int(eqn.params.get("batch_group_count", 1))
    rhs_spec = dnums.rhs_spec  # (out_features, in_features/fgc, *spatial)
    out_spec = dnums.out_spec  # (batch, features, *spatial)
    k_spatial = [rhs.shape[d] for d in rhs_spec[2:]]
    cin_per_group = rhs.shape[rhs_spec[1]]
    cout = out.shape[out_spec[1]]
    batch = out.shape[out_spec[0]]
    out_spatial = [out.shape[d] for d in out_spec[2:]]
    k = cin_per_group * int(np.prod(k_spatial, dtype=np.int64))
    m = batch * int(np.prod(out_spatial, dtype=np.int64))
    n = max(cout // max(fgc * bgc, 1), 1)
    # grouped convs run fgc independent GEMMs of n lanes each; total MACs is
    # m*k*n*groups but tile efficiency is per-group
    groups = max(fgc * bgc, 1)
    flops = 2.0 * m * k * n * groups
    return flops, (m, k, n)


def _cost_dot(eqn: Any) -> Tuple[float, Optional[Tuple[int, int, int]]]:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = int(np.prod([lhs.shape[d] for d in lb], dtype=np.int64)) or 1
    k = int(np.prod([lhs.shape[d] for d in lc], dtype=np.int64)) or 1
    m = int(np.prod([s for d, s in enumerate(lhs.shape) if d not in tuple(lc) + tuple(lb)], dtype=np.int64)) or 1
    n = int(np.prod([s for d, s in enumerate(rhs.shape) if d not in tuple(rc) + tuple(rb)], dtype=np.int64)) or 1
    return 2.0 * batch * m * k * n, (batch * m, k, n)


_SUBJAXPR_TRIP_PARAMS = ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr")


def eqn_subjaxprs(
    eqn: Any, keys: Optional[Sequence[str]] = None
) -> List[Tuple[str, Any]]:
    """Every sub-jaxpr one equation carries, as ``(param_tag, jaxpr)`` pairs.

    THE sub-program discovery primitive shared by the cost walk below and the
    static-analysis rule engine (``metrics_tpu/analysis/program.py``): it sees
    through ``pjit``/``custom_jvp`` (``jaxpr``/``call_jaxpr``), ``scan``/
    ``while`` bodies, ``cond`` branches (tag ``branches[i]``) and
    ``pallas_call`` kernel bodies (a raw ``Jaxpr`` under the ``jaxpr`` param),
    normalizing ``ClosedJaxpr`` vs raw ``Jaxpr`` so callers always receive an
    object with ``.eqns``. ``keys`` restricts discovery to specific param
    names (the cost walk passes ``_SUBJAXPR_TRIP_PARAMS`` to keep its totals
    pinned; the analysis walker passes None to miss nothing).
    """
    out: List[Tuple[str, Any]] = []
    for key, val in eqn.params.items():
        if keys is not None and key not in keys:
            continue
        vals = val if isinstance(val, (list, tuple)) else [val]
        for j, v in enumerate(vals):
            tag = f"{key}[{j}]" if isinstance(val, (list, tuple)) else key
            inner = getattr(v, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                out.append((tag, inner))
            elif hasattr(v, "eqns"):
                out.append((tag, v))
    return out


def _walk(jaxpr: Any, prefix: str, out: List[OpCost], trip: float) -> None:
    for eqn in jaxpr.eqns:
        name = str(getattr(eqn.source_info, "name_stack", "") or "")
        full = f"{prefix}/{name}" if prefix and name else (prefix or name)
        kind = eqn.primitive.name

        # recurse into sub-jaxprs (pjit, custom_jvp, scan/while bodies, ...);
        # cond carries its alternatives under "branches" — cost the most
        # expensive branch (a per-execution upper bound: exactly one runs),
        # never drop them silently
        if kind == "cond" and eqn.params.get("branches"):
            candidates = []
            for br in eqn.params["branches"]:
                inner = br.jaxpr if hasattr(br, "jaxpr") else br
                rows: List[OpCost] = []
                _walk(inner, full, rows, trip)
                candidates.append(rows)
            out.extend(max(candidates, key=lambda rows: sum(o.flops for o in rows)))
            continue
        sub = eqn_subjaxprs(eqn, keys=_SUBJAXPR_TRIP_PARAMS)
        if sub:
            # loop bodies execute `length` times when the trip count is static
            inner_trip = trip
            if kind == "scan":
                inner_trip = trip * float(eqn.params.get("length", 1))
            for _, inner in sub:
                _walk(inner, full, out, inner_trip)
            continue

        out_aval = eqn.outvars[0].aval if eqn.outvars else None
        byt = sum(_aval_bytes(v.aval) for v in eqn.invars if hasattr(v, "aval"))
        byt += sum(_aval_bytes(v.aval) for v in eqn.outvars)
        flops, mkn, util = 0.0, None, None
        if kind == "conv_general_dilated":
            flops, mkn = _cost_conv(eqn)
        elif kind == "dot_general":
            flops, mkn = _cost_dot(eqn)
        elif kind in _POINTWISE and out_aval is not None:
            flops = _numel(out_aval)
        elif kind in _REDUCE:
            flops = sum(_numel(v.aval) for v in eqn.invars if hasattr(v, "aval"))
        if mkn is not None:
            util = _mxu_efficiency(*mkn)
        if flops or byt:
            out.append(OpCost(
                name=full, kind=kind, flops=flops * trip, bytes=byt * trip,
                out_shape=tuple(out_aval.shape) if out_aval is not None else (),
                mxu_util=util, gemm_mkn=mkn,
            ))


def op_costs(fn: Callable, *args: Any, **kwargs: Any) -> List[OpCost]:
    """Analytic per-primitive costs of ``fn(*args)``, sorted by FLOPs desc.

    Loop (``scan``) bodies are multiplied by their static trip count; ``while``
    bodies are counted once (trip count is data-dependent — the caller scales).
    """
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    out: List[OpCost] = []
    _walk(jaxpr.jaxpr, "", out, 1.0)
    out.sort(key=lambda o: o.flops, reverse=True)
    return out


def group_costs(ops: Sequence[OpCost], depth: int = 2) -> List[Dict[str, Any]]:
    """Aggregate ``op_costs`` rows by the first ``depth`` name_stack segments.

    Each group row carries the structural ceiling ingredients: ``flops``,
    ``bytes``, ``flops_pct``, the FLOP-weighted mean ``mxu_util`` over its
    conv/dot ops, and ``ideal_time_share`` — the group's share of
    ``sum(flops_i / util_i)`` over the conv/dot (MXU) ops ONLY, i.e. of the
    best-case MXU-cycle budget (a low-FLOP / low-util group can still
    dominate the ceiling; pure-pointwise groups show 0). The same
    denominator as ``structural_mfu_ceiling``, so the per-row shares and the
    headline ceiling describe one budget.
    """
    groups: Dict[str, Dict[str, float]] = {}
    for op in ops:
        key = "/".join([s for s in op.name.split("/") if s][:depth]) or "<top>"
        g = groups.setdefault(
            key, {"flops": 0.0, "bytes": 0.0, "wutil": 0.0, "wflops": 0.0, "cycles": 0.0}
        )
        g["flops"] += op.flops
        g["bytes"] += op.bytes
        if op.mxu_util is not None and op.flops > 0:
            g["wutil"] += op.mxu_util * op.flops
            g["wflops"] += op.flops
            # tile waste inflates the cycle cost: an op at util 0.25 burns 4x
            # its useful flops in MXU cycles
            g["cycles"] += op.flops / max(op.mxu_util, 1e-6)
    total_flops = sum(g["flops"] for g in groups.values()) or 1.0
    total_cycles = sum(g["cycles"] for g in groups.values()) or 1.0
    rows = []
    for key, g in groups.items():
        util = (g["wutil"] / g["wflops"]) if g["wflops"] else None
        rows.append({
            "name": key,
            "flops": g["flops"],
            "bytes": g["bytes"],
            "flops_pct": 100.0 * g["flops"] / total_flops,
            "mxu_util": util,
            "ideal_time_share": 100.0 * g["cycles"] / total_cycles,
        })
    rows.sort(key=lambda r: r["ideal_time_share"], reverse=True)
    return rows


def attribution_table(fn: Callable, *args: Any, depth: int = 2, **kwargs: Any) -> Dict[str, Any]:
    """The full attribution bundle for one jitted callable.

    Returns ``{"total_flops", "total_bytes", "xla_cost_flops",
    "structural_mfu_ceiling", "rows": [group rows], "ops": [top op rows]}``.
    ``xla_cost_flops`` is XLA's own count for the compiled module (None when
    the backend doesn't expose it) — the cross-check that the analytic walk
    did not miss a dominant op. ``structural_mfu_ceiling`` is
    ``total_flops / total_ideal_cycles``: the best MFU this graph can reach on
    a 128-lane MXU given its shapes, independent of any software quality.
    """
    ops = op_costs(fn, *args, **kwargs)
    rows = group_costs(ops, depth=depth)
    total_flops = sum(o.flops for o in ops)
    total_bytes = sum(o.bytes for o in ops)
    # structural ceiling over the conv/dot (MXU) work only
    mxu_flops = sum(o.flops for o in ops if o.mxu_util is not None)
    mxu_cycles = sum(o.flops / max(o.mxu_util, 1e-6) for o in ops if o.mxu_util is not None)
    ceiling = (mxu_flops / mxu_cycles) if mxu_cycles else None
    xla_flops = None
    try:
        cost = jax.jit(fn).lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        f = float(cost.get("flops", -1.0))
        xla_flops = f if f > 0 else None
    except Exception:
        pass
    return {
        "total_flops": total_flops,
        "total_bytes": total_bytes,
        "xla_cost_flops": xla_flops,
        "structural_mfu_ceiling": ceiling,
        "rows": rows,
        "ops": [
            {
                "name": o.name, "kind": o.kind, "flops": o.flops, "bytes": o.bytes,
                "out_shape": list(o.out_shape), "mxu_util": o.mxu_util,
                "gemm_mkn": list(o.gemm_mkn) if o.gemm_mkn else None,
            }
            for o in ops[:64]
        ],
    }


def structural_mfu_ceiling(fn: Callable, *args: Any, **kwargs: Any) -> Optional[float]:
    """Best MFU the graph's conv/dot shapes permit on a 128-lane MXU.

    Trace-only (``make_jaxpr``, no compile) — cheap enough to run inline in a
    bench over a tunnelled device. Same number as
    ``attribution_table(...)["structural_mfu_ceiling"]``.
    """
    ops = op_costs(fn, *args, **kwargs)
    mxu_flops = sum(o.flops for o in ops if o.mxu_util is not None)
    mxu_cycles = sum(o.flops / max(o.mxu_util, 1e-6) for o in ops if o.mxu_util is not None)
    return (mxu_flops / mxu_cycles) if mxu_cycles else None


def format_table(table: Dict[str, Any], top: int = 25) -> str:
    """Render an ``attribution_table`` as a markdown table (docs/bench logs)."""
    lines = [
        "| layer | GFLOPs | % FLOPs | MXU util (est) | % ideal time | MB moved |",
        "|---|---|---|---|---|---|",
    ]
    for r in table["rows"][:top]:
        util = f"{r['mxu_util']:.2f}" if r["mxu_util"] is not None else "—"
        lines.append(
            f"| {r['name']} | {r['flops'] / 1e9:.3f} | {r['flops_pct']:.1f} | {util} "
            f"| {r['ideal_time_share']:.1f} | {r['bytes'] / 1e6:.1f} |"
        )
    total = table["total_flops"]
    xla = table["xla_cost_flops"]
    ceiling = table["structural_mfu_ceiling"]
    lines.append(
        f"\nTotal: {total / 1e9:.3f} GFLOPs analytic"
        + (f" (XLA cost_analysis: {xla / 1e9:.3f})" if xla else " (XLA cost_analysis unavailable)")
        + (f"; structural MFU ceiling on a 128-lane MXU: {ceiling:.3f}" if ceiling else "")
    )
    return "\n".join(lines)


def capture_trace(fn: Callable, args: Sequence[Any], outdir: str, iters: int = 3) -> str:
    """Run ``fn(*args)`` under a ``jax.profiler`` trace (real-TPU measured path).

    The analytic table above *estimates*; on hardware this records the actual
    per-fusion timeline (open ``outdir`` in TensorBoard / xprof; fusion names
    match the jaxpr name_stack paths). Returns ``outdir``.
    """
    jfn = jax.jit(fn)
    jax.block_until_ready(jfn(*args))  # compile outside the trace
    with jax.profiler.trace(outdir):
        for _ in range(iters):
            out = jfn(*args)
        jax.block_until_ready(out)
    return outdir


# --------------------------------------------------------------------------
# single-program MFU calibration


def single_program_calibration(
    body_fn: Callable[[Any, Array], Array],
    operands: Any,
    flops_per_iter: float,
    *,
    matmul_n: int = 8192,
    matmul_dtype: Any = jnp.bfloat16,
    k_pair: Tuple[int, int] = (4, 20),
    m_pair: Tuple[int, int] = (4, 20),
    trials: int = 3,
    timer: Callable[[], float] = time.perf_counter,
) -> Dict[str, Any]:
    """Measure a workload's FLOP rate and the matmul ceiling in ONE executable.

    ``body_fn(operands, i) -> scalar`` is one workload iteration (it must make
    its inputs loop-variant via ``i`` — e.g. ``jnp.roll(x, i)`` — or XLA hoists
    it; ``operands`` are threaded as runtime arguments so model params never
    become HLO constants). The program runs ``k_work`` workload iterations and
    ``k_mm`` chained ``matmul_n^3`` dots, both as *dynamic* trip counts, and
    returns a scalar data-depending on both loops (value-fetched timing). One
    executable serves all timings, so:

    * K-pair marginals cancel every constant offset (dispatch, transfer,
      runtime readiness quirks), and
    * workload and ceiling provably execute on the same accelerator — their
      ratio (``mfu_vs_in_program_ceiling``) is a genuine utilization in
      (0, 1] by construction, immune to heterogeneous device pools.

    Returns seconds-per-iter marginals, the in-program matmul TF/s, achieved
    workload TF/s, and the utilization ratio.
    """
    n = int(matmul_n)
    a = jnp.ones((n, n), matmul_dtype)
    b = jnp.ones((n, n), matmul_dtype) * jnp.asarray(1.0 / n, matmul_dtype)

    @jax.jit
    def prog(ops_, a_, b_, k_work, k_mm):
        def wbody(i, acc):
            return acc + body_fn(ops_, i).astype(jnp.float32)

        acc = jax.lax.fori_loop(0, k_work, wbody, jnp.float32(0.0))

        def mbody(i, x):
            return jax.lax.dot(x, b_, preferred_element_type=matmul_dtype)

        mm = jax.lax.fori_loop(0, k_mm, mbody, a_)
        return acc + mm[0, 0].astype(jnp.float32)

    def run(k_work: int, k_mm: int) -> float:
        return float(prog(operands, a, b, jnp.int32(k_work), jnp.int32(k_mm)))

    # compile + warm every trip-count combination once (same executable —
    # dynamic trip counts — but the first run also pays autotuning/paging)
    for kw, km in ((k_pair[0], 0), (k_pair[1], 0), (0, m_pair[0]), (0, m_pair[1])):
        run(kw, km)

    def timed(k_work: int, k_mm: int) -> float:
        best = None
        for _ in range(trials):
            t0 = timer()
            run(k_work, k_mm)
            dt = timer() - t0
            best = dt if best is None else min(best, dt)
        return best

    t_w1, t_w2 = timed(k_pair[0], 0), timed(k_pair[1], 0)
    t_m1, t_m2 = timed(0, m_pair[0]), timed(0, m_pair[1])
    work_s = max((t_w2 - t_w1) / (k_pair[1] - k_pair[0]), 1e-12)
    mm_s = max((t_m2 - t_m1) / (m_pair[1] - m_pair[0]), 1e-12)
    mm_flops = 2.0 * float(n) ** 3
    ceiling_tflops = mm_flops / mm_s / 1e12
    achieved_tflops = flops_per_iter / work_s / 1e12
    return {
        "work_s_per_iter": work_s,
        "matmul_s_per_iter": mm_s,
        "in_program_matmul_tflops": ceiling_tflops,
        "achieved_tflops": achieved_tflops,
        "mfu_vs_in_program_ceiling": achieved_tflops / ceiling_tflops,
        "timings_s": {
            "work": [t_w1, t_w2], "matmul": [t_m1, t_m2],
            "k_pair": list(k_pair), "m_pair": list(m_pair),
        },
        "protocol": (
            "single-program calibration: workload and matmul-ceiling fori_loops "
            "with dynamic trip counts in ONE executable; K-pair marginals of "
            "value-fetched timings (offsets cancel; same accelerator by construction)"
        ),
    }
