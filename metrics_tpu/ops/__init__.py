"""Hot-path device ops (XLA/Pallas) shared across metric families."""
from metrics_tpu.ops.sqrtm import psd_sqrt, sqrtm_newton_schulz, trace_sqrtm_product
