"""Hot-path device ops (XLA/Pallas) shared across metric families."""
from metrics_tpu.ops.kernels import (
    fold_rows_masked,
    histogram_accumulate,
    resolve_backend,
    segment_reduce_masked,
    set_default_backend,
    use_backend,
)
from metrics_tpu.ops.profiling import (
    attribution_table,
    capture_trace,
    format_table,
    op_costs,
    single_program_calibration,
    structural_mfu_ceiling,
)
from metrics_tpu.ops.sqrtm import psd_sqrt, sqrtm_newton_schulz, trace_sqrtm_product
