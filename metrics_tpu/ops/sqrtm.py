"""On-device matrix square root — no CPU/scipy escape.

The reference computes FID's ``sqrtm(sigma1 @ sigma2)`` by falling off the device to
``scipy.linalg.sqrtm`` in float64 (``torchmetrics/image/fid.py:68-70``). Here the
needed quantity — ``trace(sqrtm(sigma1 @ sigma2))`` for symmetric PSD covariances —
is computed entirely on device via two Hermitian eigendecompositions:

    trace sqrt(S1 S2) = sum sqrt(eig(S1^(1/2) S2 S1^(1/2)))

which is exact for PSD inputs, maps to XLA's native eigh, and keeps every FLOP on
the TPU. A Newton-Schulz iteration is also provided for full-matrix square roots
(differentiable, matmul-only — MXU-friendly).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def psd_sqrt(mat: Array, eps: float = 1e-12) -> Array:
    """Symmetric PSD matrix square root via eigh."""
    vals, vecs = jnp.linalg.eigh(mat)
    vals = jnp.clip(vals, 0.0, None)
    return (vecs * jnp.sqrt(vals + eps)) @ vecs.T


def trace_sqrtm_product(sigma1: Array, sigma2: Array) -> Array:
    """trace(sqrtm(sigma1 @ sigma2)) for symmetric PSD sigma1, sigma2 (on device)."""
    s1_half = psd_sqrt(sigma1)
    m = s1_half @ sigma2 @ s1_half
    m = (m + m.T) / 2  # re-symmetrise against fp error
    vals = jnp.linalg.eigvalsh(m)
    return jnp.sum(jnp.sqrt(jnp.clip(vals, 0.0, None)))


def sqrtm_newton_schulz(mat: Array, num_iters: int = 50) -> Tuple[Array, Array]:
    """Full matrix square root by Newton-Schulz iteration (matmul-only).

    Returns (sqrt(mat), error_estimate). Converges for matrices with spectral radius
    < 1 after normalisation; good to ~1e-5 relative in f32.
    """
    dim = mat.shape[0]
    norm = jnp.linalg.norm(mat)
    y = mat / norm
    eye = jnp.eye(dim, dtype=mat.dtype)
    z = eye

    def body(_, carry):
        y, z = carry
        t = 0.5 * (3.0 * eye - z @ y)
        return y @ t, t @ z

    y, z = jax.lax.fori_loop(0, num_iters, body, (y, z))
    sqrt_mat = y * jnp.sqrt(norm)
    err = jnp.linalg.norm(sqrt_mat @ sqrt_mat - mat) / jnp.maximum(jnp.linalg.norm(mat), 1e-12)
    return sqrt_mat, err
