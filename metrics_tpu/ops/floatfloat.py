"""Float-float (double-float) arithmetic: ~48-bit precision from f32 pairs, inside jit.

The reference computes FID statistics in float64 (``torchmetrics/image/fid.py:269``)
— trivially available on CUDA, but on TPU f64 exists only as a slow global-flag
emulation, and *inside a jitted graph* a library cannot open an x64 island at all.
This module provides the TPU-native answer: error-free transformations (Knuth
2Sum, Veltkamp split + Dekker 2Prod) represent a value as an unevaluated f32 pair
``(hi, lo)`` with ``hi + lo`` carrying ~48 significant bits. All ops are branch-free
elementwise f32 arithmetic — they vectorise, shard, and fuse like any other XLA op,
and work identically on CPU/TPU backends.

Verified against numpy f64 in ``tests/ops/test_floatfloat.py``. XLA does not
reassociate IEEE float ops by default, so the error terms survive compilation
(empirically checked on the TPU backend as part of the test suite).

Used by the streaming FID/IS statistics (``metrics_tpu/image/fid.py``) where the
raw-moment form ``cov = (Σxxᵀ - n·μμᵀ)/(n-1)`` hits catastrophic cancellation in
plain f32 whenever features carry a large common offset.
"""
from typing import Tuple

import jax.numpy as jnp

Pair = Tuple[jnp.ndarray, jnp.ndarray]

_SPLIT_FACTOR = 4097.0  # 2**12 + 1: Veltkamp split constant for f32 (24-bit mantissa)


def two_sum(a, b) -> Pair:
    """Knuth branch-free 2Sum: s + e == a + b exactly (any magnitude order)."""
    s = a + b
    bp = s - a
    ap = s - bp
    return s, (a - ap) + (b - bp)


def _veltkamp_split(a) -> Pair:
    c = a * jnp.float32(_SPLIT_FACTOR)
    hi = c - (c - a)
    return hi, a - hi


def two_prod(a, b) -> Pair:
    """Dekker 2Prod: p + e == a * b exactly (no FMA required)."""
    p = a * b
    ah, al = _veltkamp_split(a)
    bh, bl = _veltkamp_split(b)
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


def ff_add(x: Pair, y: Pair) -> Pair:
    """Pair + pair (Dekker add2: ~accurate to the pair format's full width)."""
    s, e = two_sum(x[0], y[0])
    e = e + (x[1] + y[1])
    hi, lo = two_sum(s, e)
    return hi, lo


def ff_add_f32(x: Pair, v) -> Pair:
    """Pair + plain f32 (compensated accumulate step)."""
    s, e = two_sum(x[0], v)
    e = e + x[1]
    hi, lo = two_sum(s, e)
    return hi, lo


def ff_neg(x: Pair) -> Pair:
    return -x[0], -x[1]


def ff_sub(x: Pair, y: Pair) -> Pair:
    return ff_add(x, ff_neg(y))


def ff_mul(x: Pair, y: Pair) -> Pair:
    """Pair * pair."""
    p, e = two_prod(x[0], y[0])
    e = e + (x[0] * y[1] + x[1] * y[0])
    hi, lo = two_sum(p, e)
    return hi, lo


def ff_scale(x: Pair, c) -> Pair:
    """Pair * plain f32 scalar/array."""
    p, e = two_prod(x[0], c)
    e = e + x[1] * c
    hi, lo = two_sum(p, e)
    return hi, lo


def ff_to_f32(x: Pair):
    return x[0] + x[1]


def ff_from_f32(v) -> Pair:
    return v, jnp.zeros_like(v)


def ff_to_f64(x: Pair):
    """Recover the ~48-bit value; only meaningful inside an x64 context."""
    return x[0].astype(jnp.float64) + x[1].astype(jnp.float64)
