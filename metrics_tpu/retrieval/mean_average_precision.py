"""RetrievalMAP.

Parity: reference ``torchmetrics/retrieval/mean_average_precision.py:20``.
"""
import jax

from metrics_tpu.functional.retrieval.average_precision import retrieval_average_precision
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric

Array = jax.Array


class RetrievalMAP(RetrievalMetric):
    """Mean average precision over queries."""

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_average_precision(preds, target)
