"""RetrievalMAP.

Parity: reference ``torchmetrics/retrieval/mean_average_precision.py:20``.
"""
import jax

from metrics_tpu.functional.retrieval.average_precision import retrieval_average_precision
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric

Array = jax.Array


class RetrievalMAP(RetrievalMetric):
    """Mean average precision over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalMAP
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.9, 0.7, 0.6, 0.1, 0.8])
        >>> target = jnp.asarray([1, 0, 1, 0, 0, 1])
        >>> metric = RetrievalMAP()
        >>> print(f"{float(metric(preds, target, indexes=indexes)):.4f}")
        0.7917
    """

    _segment_kind = "map"

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_average_precision(preds, target)
