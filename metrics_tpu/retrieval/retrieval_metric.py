"""RetrievalMetric template base.

Parity: reference ``torchmetrics/retrieval/retrieval_metric.py:27`` (states :107-109,
grouped compute :124-153, empty_target_action error/skip/pos/neg). Subclasses only
override ``_metric``.

TPU note: states are gathered cat-lists; per-query compute groups via a single sort
of the query ids (``get_group_indexes``), each group's ``_metric`` is jnp on device.
"""
from abc import ABC, abstractmethod
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.utils.checks import _check_retrieval_inputs
from metrics_tpu.utils.data import dim_zero_cat, get_group_indexes

Array = jax.Array


class RetrievalMetric(Metric, ABC):
    """Base for retrieval metrics: per-query ``_metric`` averaged over queries."""

    indexes: List[Array]
    preds: List[Array]
    target: List[Array]
    higher_is_better = True

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False
        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index
        self.add_state("indexes", default=[], dist_reduce_fx=None)
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target,
            allow_non_binary_target=self.allow_non_binary_target, ignore_index=self.ignore_index,
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def _is_empty_query(self, mini_target: Array) -> bool:
        return not float(jnp.sum(mini_target))

    def compute(self) -> Array:
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)

        res = []
        groups = get_group_indexes(indexes)
        for group in groups:
            mini_preds = preds[group]
            mini_target = target[group]
            if self._is_empty_query(mini_target):
                if self.empty_target_action == "error":
                    raise ValueError("`compute` method was provided with a query with no positive target.")
                if self.empty_target_action == "pos":
                    res.append(jnp.asarray(1.0))
                elif self.empty_target_action == "neg":
                    res.append(jnp.asarray(0.0))
            else:
                res.append(self._metric(mini_preds, mini_target))
        return jnp.mean(jnp.stack(res)) if res else jnp.asarray(0.0)

    @abstractmethod
    def _metric(self, preds: Array, target: Array) -> Array:
        """Compute the metric for a single query's (preds, target)."""
