"""RetrievalMetric template base.

Parity: reference ``torchmetrics/retrieval/retrieval_metric.py:27`` (states :107-109,
grouped compute :124-153, empty_target_action error/skip/pos/neg). Subclasses only
override ``_metric``.

TPU note: the built-in subclasses compute DEVICE-NATIVE — one stable lexsort
groups every query's documents, per-query metrics are ``jax.ops.segment_*``
reductions, and a single scalar crosses back to the host
(``functional/retrieval/_segment.py``; the reference loops Python over query
groups with one device sync each, ``retrieval_metric.py:124-153``). Subclasses
that override ``_metric`` with custom logic transparently fall back to the
same per-group host loop the reference uses (``_compute_host``), which also
serves as the tested oracle for the segment path.
"""
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import GroupedAggregateSpec, GroupedField, GroupedUpdateSpec, Metric
from metrics_tpu.utils.checks import _check_retrieval_inputs
from metrics_tpu.utils.data import dim_zero_cat, get_group_indexes

Array = jax.Array


class RetrievalMetric(Metric, ABC):
    """Base for retrieval metrics: per-query ``_metric`` averaged over queries."""

    indexes: List[Array]
    preds: List[Array]
    target: List[Array]
    higher_is_better = True

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False
        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index
        self.add_state("indexes", default=[], dist_reduce_fx=None)
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target,
            allow_non_binary_target=self.allow_non_binary_target, ignore_index=self.ignore_index,
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def _is_empty_query(self, mini_target: Array) -> bool:
        return not float(jnp.sum(mini_target))

    # set on built-in subclasses to route compute through the fused
    # sort+segment device path; None (or a user override of _metric /
    # _is_empty_query) selects the reference-style host loop
    _segment_kind: Optional[str] = None

    def _segment_dispatch(self) -> Optional[str]:
        """The segment-engine kind to use, or None for the host loop.

        A subclass that overrides ``_metric`` (or ``_is_empty_query``) without
        declaring its own ``_segment_kind`` must get the host loop — the class
        that OWNS the override decides, not an inherited kind.
        """
        mro = type(self).__mro__
        metric_owner = next(c for c in mro if "_metric" in c.__dict__)
        kind = metric_owner.__dict__.get("_segment_kind")
        if kind is None:
            return None
        empty_owner = next(c for c in mro if "_is_empty_query" in c.__dict__)
        if empty_owner is not RetrievalMetric and "_segment_kind" not in empty_owner.__dict__:
            return None
        return kind

    def compute(self) -> Array:
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)

        kind = self._segment_dispatch()
        if kind is not None and indexes.shape[0] > 0:
            from metrics_tpu.functional.retrieval._segment import segment_retrieval_mean

            return segment_retrieval_mean(
                preds, target, indexes,
                kind=kind, k=getattr(self, "k", None),
                empty_target_action=self.empty_target_action,
            )
        return self._compute_host(indexes, preds, target)

    # ----------------------------------------------- ragged serving (ISSUE 17)
    #
    # A query id IS a group key: built-in retrieval metrics (those with a
    # segment kind) declare grouped state so RaggedEngine can serve them —
    # per-query (preds, target) rows land in capacity buffers, the per-group
    # read runs grouped_query_score (byte-identical per-kind math), and the
    # aggregate read rebuilds THESE eager list states and runs compute().

    # per-group row budget for engine serving; subclasses/users may override
    # the attribute (or pass capacity= to RaggedEngine) to fit their corpus
    grouped_capacity: int = 256

    def grouped_update_spec(self) -> Optional[GroupedUpdateSpec]:
        if self._segment_dispatch() is None:
            # custom-_metric subclasses need the host loop per group — the
            # engine cannot run arbitrary Python per group
            return None
        return GroupedUpdateSpec(
            fields=(
                GroupedField("preds", (), jnp.float32),
                GroupedField("target", (), jnp.float32),
            ),
            capacity=int(self.grouped_capacity),
        )

    def grouped_encode(self, preds: Array, target: Array, indexes: Array) -> Tuple[Any, ...]:
        """Flatten one eager ``update`` call to ``(group_ids, preds, target)``
        rows — the SAME validation/coercion as ``update`` (shape agreement,
        integer indexes, eager ``ignore_index`` row filtering), so the engine
        ingests exactly the rows the eager metric would append."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target,
            allow_non_binary_target=self.allow_non_binary_target, ignore_index=self.ignore_index,
        )
        return (
            np.asarray(indexes, np.int32),
            np.asarray(preds, np.float32),
            np.asarray(target, np.float32),
        )

    def grouped_group_value(self, fields: Dict[str, Array], count: Array, capacity: int) -> Array:
        from metrics_tpu.functional.retrieval._segment import grouped_query_score

        return grouped_query_score(
            fields["preds"], fields["target"], count,
            kind=self._segment_dispatch(), k=getattr(self, "k", None),
            empty_target_action=self.empty_target_action,
        )

    def grouped_aggregate_spec(self) -> Optional[GroupedAggregateSpec]:
        """Built-in retrieval aggregates fold on device (ISSUE 18): the
        corpus-level ``result()`` is a masked mean of independent per-query
        scores, so the engine batches the per-group read over the stacked
        buffers and folds with the masked row kernels.  Custom-``_metric``
        subclasses (no segment kind) stay on the host oracle."""
        if self._segment_dispatch() is None:
            return None
        return GroupedAggregateSpec(kind="fold")

    def grouped_batch_scores(
        self, counts: Array, fields: Dict[str, Array], capacity: int
    ) -> Dict[str, Array]:
        """Traced, batched per-group scores for the device aggregate:
        ``{"value", "keep", "flag"}``, each ``(G,)`` (see
        :func:`~metrics_tpu.functional.retrieval._segment
        .batched_group_scores`)."""
        from metrics_tpu.functional.retrieval._segment import batched_group_scores

        value, keep, flag = batched_group_scores(
            fields["preds"], fields["target"], counts,
            kind=self._segment_dispatch(), k=getattr(self, "k", None),
            empty_target_action=self.empty_target_action,
        )
        return {"value": value, "keep": keep, "flag": flag}

    def grouped_aggregate_finish(self, value: float, kept: int, flagged: int) -> Array:
        """Host finish of the device fold: raise the deferred value check for
        ``empty_target_action="error"`` corpora (same type + message as the
        eager path), else return the folded mean."""
        if flagged:
            from metrics_tpu.utils.checks import _CODE_EMPTY_QUERY_RETRIEVAL, deferred_message

            raise ValueError(deferred_message(_CODE_EMPTY_QUERY_RETRIEVAL))
        return jnp.asarray(value, jnp.float32)

    def grouped_finalize(
        self, counts: Any, fields: Dict[str, Any], group_ids: Any
    ) -> Dict[str, Any]:
        """Rebuild the eager list states from reconstructed per-group rows:
        one (indexes, preds, target) part per non-empty group, in group-id
        order. Queries with no rows never existed (exactly the eager
        semantics); a corpus with no rows at all yields one empty part so
        ``dim_zero_cat`` still sees arrays."""
        counts = np.asarray(counts)
        idx_parts: List[Array] = []
        pred_parts: List[Array] = []
        tgt_parts: List[Array] = []
        for gid in np.asarray(group_ids):
            c = int(counts[gid])
            if c == 0:
                continue
            idx_parts.append(jnp.full((c,), int(gid), jnp.int32))
            pred_parts.append(jnp.asarray(fields["preds"][gid][:c], jnp.float32))
            tgt_parts.append(jnp.asarray(fields["target"][gid][:c], jnp.float32))
        if not idx_parts:
            idx_parts = [jnp.zeros((0,), jnp.int32)]
            pred_parts = [jnp.zeros((0,), jnp.float32)]
            tgt_parts = [jnp.zeros((0,), jnp.float32)]
        return {"indexes": idx_parts, "preds": pred_parts, "target": tgt_parts}

    def _compute_host(self, indexes: Array, preds: Array, target: Array) -> Array:
        """Reference-parity per-group host loop (oracle + custom-subclass path)."""
        res = []
        groups = get_group_indexes(indexes)
        for group in groups:
            mini_preds = preds[group]
            mini_target = target[group]
            if self._is_empty_query(mini_target):
                if self.empty_target_action == "error":
                    raise ValueError("`compute` method was provided with a query with no positive target.")
                if self.empty_target_action == "pos":
                    res.append(jnp.asarray(1.0))
                elif self.empty_target_action == "neg":
                    res.append(jnp.asarray(0.0))
            else:
                res.append(self._metric(mini_preds, mini_target))
        return jnp.mean(jnp.stack(res)) if res else jnp.asarray(0.0)

    @abstractmethod
    def _metric(self, preds: Array, target: Array) -> Array:
        """Compute the metric for a single query's (preds, target)."""
