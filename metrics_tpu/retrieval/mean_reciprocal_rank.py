"""RetrievalMRR.

Parity: reference ``torchmetrics/retrieval/mean_reciprocal_rank.py:20``.
"""
import jax

from metrics_tpu.functional.retrieval.reciprocal_rank import retrieval_reciprocal_rank
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric

Array = jax.Array


class RetrievalMRR(RetrievalMetric):
    """Mean reciprocal rank over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalMRR
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.9, 0.7, 0.6, 0.1, 0.8])
        >>> target = jnp.asarray([1, 0, 1, 0, 0, 1])
        >>> metric = RetrievalMRR()
        >>> print(f"{float(metric(preds, target, indexes=indexes)):.4f}")
        0.7500
    """

    _segment_kind = "mrr"

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_reciprocal_rank(preds, target)
