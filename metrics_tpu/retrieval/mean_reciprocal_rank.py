"""RetrievalMRR.

Parity: reference ``torchmetrics/retrieval/mean_reciprocal_rank.py:20``.
"""
import jax

from metrics_tpu.functional.retrieval.reciprocal_rank import retrieval_reciprocal_rank
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric

Array = jax.Array


class RetrievalMRR(RetrievalMetric):
    """Mean reciprocal rank over queries."""

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_reciprocal_rank(preds, target)
