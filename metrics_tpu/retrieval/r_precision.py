"""RetrievalRPrecision.

Parity: reference ``torchmetrics/retrieval/retrieval_r_precision.py:20``.
"""
import jax

from metrics_tpu.functional.retrieval.r_precision import retrieval_r_precision
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric

Array = jax.Array


class RetrievalRPrecision(RetrievalMetric):
    """R-precision averaged over queries."""

    _segment_kind = "r_precision"

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_r_precision(preds, target)
