"""RetrievalFallOut.

Parity: reference ``torchmetrics/retrieval/retrieval_fallout.py:24`` — lower is
better, and "empty" means a query with no NEGATIVE targets (inverted default).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.retrieval.fall_out import retrieval_fall_out
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric

Array = jax.Array


class RetrievalFallOut(RetrievalMetric):
    """Fall-out@k averaged over queries."""

    higher_is_better = False

    def __init__(
        self,
        empty_target_action: str = "pos",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k

    def _is_empty_query(self, mini_target: Array) -> bool:
        # a query is degenerate when it has no negative targets
        return not float(jnp.sum(1 - mini_target))

    _segment_kind = "fall_out"

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_fall_out(preds, target, k=self.k)
