"""RetrievalNormalizedDCG.

Parity: reference ``torchmetrics/retrieval/retrieval_ndcg.py:22`` (graded relevance
allowed).
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.retrieval.ndcg import retrieval_normalized_dcg
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric

Array = jax.Array


class RetrievalNormalizedDCG(RetrievalMetric):
    """nDCG@k averaged over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalNormalizedDCG
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.9, 0.7, 0.6, 0.1, 0.8])
        >>> target = jnp.asarray([1, 0, 1, 0, 0, 1])
        >>> metric = RetrievalNormalizedDCG()
        >>> print(f"{float(metric(preds, target, indexes=indexes)):.4f}")
        0.8467
    """

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k
        self.allow_non_binary_target = True

    _segment_kind = "ndcg"

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_normalized_dcg(preds, target, k=self.k)
