"""LPIPS — learned perceptual image patch similarity.

Parity: reference ``torchmetrics/image/lpip_similarity.py:41`` (wraps the ``lpips``
package's pretrained AlexNet/VGG nets :30). No pretrained perceptual net is shippable
in this zero-egress build, so the metric takes a pluggable ``net`` callable:
``net(imgs) -> list of (N, Hi, Wi, Ci) feature maps`` (e.g. a Flax VGG with converted
LPIPS weights). The LPIPS math on top — per-layer unit-normalisation, squared
difference, spatial mean, layer sum — is implemented here and is the on-device part.
"""
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric

Array = jax.Array


def _normalize_tensor(feat: Array, eps: float = 1e-10) -> Array:
    norm = jnp.sqrt(jnp.sum(feat ** 2, axis=-1, keepdims=True))
    return feat / (norm + eps)


def _lpips_from_features(feats_a: List[Array], feats_b: List[Array], weights: Optional[List[Array]] = None) -> Array:
    """Per-sample LPIPS distance given per-layer feature maps (NHWC)."""
    total = None
    for i, (fa, fb) in enumerate(zip(feats_a, feats_b)):
        diff = (_normalize_tensor(fa) - _normalize_tensor(fb)) ** 2
        if weights is not None:
            diff = diff * weights[i]
        layer = jnp.mean(jnp.sum(diff, axis=-1), axis=(1, 2))  # channel-weighted, spatial mean
        total = layer if total is None else total + layer
    return total


class LPIPS(Metric):
    """Learned perceptual image patch similarity over a pluggable feature net."""

    is_differentiable = True
    higher_is_better = False

    def __init__(
        self,
        net: Optional[Callable[[Array], List[Array]]] = None,
        net_type: str = "alex",
        reduction: str = "mean",
        weights: Optional[List[Array]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_net_type = ("vgg", "alex", "squeeze")
        if net is None and net_type not in valid_net_type:
            raise ValueError(f"Argument `net_type` must be one of {valid_net_type}, but got {net_type}.")
        if net is None:
            raise ModuleNotFoundError(
                "LPIPS requires a pretrained perceptual network. This build has no network egress;"
                " pass `net=` a callable mapping images (N,H,W,C) to a list of feature maps"
                " (e.g. a Flax VGG16 with converted LPIPS weights)."
            )
        self.net = net
        self.weights = weights
        valid_reduction = ("mean", "sum")
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        self.reduction = reduction

        self.add_state("sum_scores", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, img1: Array, img2: Array) -> None:
        feats_a = self.net(img1)
        feats_b = self.net(img2)
        loss = _lpips_from_features(feats_a, feats_b, self.weights)
        self.sum_scores = self.sum_scores + jnp.sum(loss)
        self.total = self.total + loss.shape[0]

    def compute(self) -> Array:
        if self.reduction == "mean":
            return self.sum_scores / self.total
        return self.sum_scores
