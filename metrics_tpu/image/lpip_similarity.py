"""LPIPS — learned perceptual image patch similarity.

Parity: reference ``torchmetrics/image/lpip_similarity.py:41`` (wraps the ``lpips``
package's pretrained AlexNet/VGG nets :30). The backbone lives in
``metrics_tpu/models/perceptual.py`` as Flax VGG16/AlexNet graphs mirroring the
``lpips`` package's slicing (scaling layer, five relu taps, learned per-channel
linear weights); pretrained weights arrive offline via
``python tools/convert_weights.py lpips`` (this build has no egress). The LPIPS
math on top — per-layer unit-normalisation, squared difference, linear
weighting, spatial mean, layer sum — runs fully on device. A raw ``net``
callable remains pluggable for custom feature stacks.
"""
from typing import Any, Callable, List, Optional, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric

Array = jax.Array


def _normalize_tensor(feat: Array, eps: float = 1e-10) -> Array:
    norm = jnp.sqrt(jnp.sum(feat ** 2, axis=-1, keepdims=True))
    return feat / (norm + eps)


def _lpips_from_features(feats_a: List[Array], feats_b: List[Array], weights: Optional[List[Array]] = None) -> Array:
    """Per-sample LPIPS distance given per-layer feature maps (NHWC)."""
    total = None
    for i, (fa, fb) in enumerate(zip(feats_a, feats_b)):
        diff = (_normalize_tensor(fa) - _normalize_tensor(fb)) ** 2
        if weights is not None:
            diff = diff * weights[i]
        layer = jnp.mean(jnp.sum(diff, axis=-1), axis=(1, 2))  # channel-weighted, spatial mean
        total = layer if total is None else total + layer
    return total


class LPIPS(Metric):
    """Learned perceptual image patch similarity (built-in VGG16/AlexNet backbones).

    Args:
        net: optional custom callable ``imgs -> list of (N, Hi, Wi, Ci) feature
            maps``; overrides the built-in backbones.
        net_type: ``'vgg'`` or ``'alex'`` selects the built-in Flax backbone
            (``'squeeze'`` needs a custom ``net``).
        reduction: ``'mean'`` or ``'sum'`` over the batch.
        weights: optional per-layer channel weight vectors (the learned LPIPS
            linear heads); defaults to the converted checkpoint's.
        params: converted checkpoint for the built-in backbone — a path or the
            loaded payload from ``python tools/convert_weights.py lpips``.

    Example::

        # offline, with the lpips package: torch.save(lpips.LPIPS(net="vgg").state_dict(), "l.pth")
        # python tools/convert_weights.py lpips l.pth lpips_vgg.pkl --net-type vgg
        metric = LPIPS(net_type="vgg", params="lpips_vgg.pkl")
    """

    is_differentiable = True
    higher_is_better = False

    def __init__(
        self,
        net: Optional[Callable[[Array], List[Array]]] = None,
        net_type: str = "alex",
        reduction: str = "mean",
        weights: Optional[List[Array]] = None,
        params: Optional[Any] = None,
        check_value_range: Union[bool, str] = "first",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_net_type = ("vgg", "alex", "squeeze")
        if net is None and net_type not in valid_net_type:
            raise ValueError(f"Argument `net_type` must be one of {valid_net_type}, but got {net_type}.")
        self._builtin_net = net is None
        if net is None:
            if net_type == "squeeze":
                raise ModuleNotFoundError(
                    "The built-in LPIPS backbones are 'vgg' and 'alex'; for 'squeeze' pass"
                    " `net=` a callable mapping images (N,H,W,C) to a list of feature maps."
                )
            from metrics_tpu.models.perceptual import LPIPSFeatureNet

            feature_net = LPIPSFeatureNet(net_type=net_type, params=params)
            net = feature_net
            if weights is None:
                weights = feature_net.weights
        self.net = net
        self.weights = weights
        if check_value_range != "first":
            # canonicalize truthy/falsy scalars (1, np.True_, ...) so the
            # `is True` tests in _validate_imgs can't silently miss them
            if check_value_range in (True, False):
                check_value_range = bool(check_value_range)
            else:
                raise ValueError(
                    f"Argument `check_value_range` must be True, False or 'first', got {check_value_range}"
                )
        # the eager [-1,1] check is one blocking device fetch (~130ms over a
        # tunnelled TPU) — by default pay it once, not per batch
        self.check_value_range = check_value_range
        self._range_checked = False
        valid_reduction = ("mean", "sum")
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        self.reduction = reduction

        self.add_state("sum_scores", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _validate_imgs(self, img1: Array, img2: Array) -> None:
        """Reference contract (``lpip_similarity.py:36-38,140-146``): 4-d image
        batches with a 3-wide channel axis, values in [-1, 1]. Shape checks run
        always; the value check is eager-only (skipped under trace, matching the
        input layer's convention) and costs one blocking device fetch, so by
        default (``check_value_range="first"``) it runs on the first update
        only (``True`` = every update, ``False`` = never)."""
        from metrics_tpu.utils.checks import _is_tracer

        for name, img in (("img1", img1), ("img2", img2)):
            shape = jnp.shape(img)
            if len(shape) != 4 or (shape[1] != 3 and shape[-1] != 3):
                raise ValueError(
                    f"Expected `{name}` to be a 4-d batch with a 3-channel axis, got shape {shape}"
                )
        check = self.check_value_range is True or (
            self.check_value_range == "first" and not self._range_checked
        )
        if check and not (_is_tracer(img1) or _is_tracer(img2)):
            import numpy as np

            bounds = np.asarray(
                jnp.stack([jnp.min(img1), jnp.max(img1), jnp.min(img2), jnp.max(img2)])
            )
            lo1, hi1, lo2, hi2 = (float(v) for v in bounds)
            if lo1 < -1.0 or hi1 > 1.0 or lo2 < -1.0 or hi2 > 1.0:
                raise ValueError(
                    "Expected both input arguments to be normalized tensors (all values in"
                    f" range [-1,1]), but `img1` spans [{lo1}, {hi1}] and `img2` spans"
                    f" [{lo2}, {hi2}]"
                )
            # only a PASSED check retires the first-update probe: a caught
            # failure must not disable checking for later batches
            self._range_checked = True

    def reset(self) -> None:
        super().reset()
        self._range_checked = False

    def update(self, img1: Array, img2: Array) -> None:
        if self._builtin_net:
            # the [-1, 1] 3-channel contract belongs to the built-in
            # scaling-layer backbones; custom nets keep their own conventions
            self._validate_imgs(img1, img2)
        feats_a = self.net(img1)
        feats_b = self.net(img2)
        loss = _lpips_from_features(feats_a, feats_b, self.weights)
        self.sum_scores = self.sum_scores + jnp.sum(loss)
        self.total = self.total + loss.shape[0]

    def compute(self) -> Array:
        if self.reduction == "mean":
            return self.sum_scores / self.total
        return self.sum_scores
