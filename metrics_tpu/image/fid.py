"""Frechet Inception Distance — fully on-device (no scipy/CPU escape).

Parity: reference ``torchmetrics/image/fid.py:125`` (feature lists :248-249, update
:250-262, compute :264-281, _compute_fid :95-122, MatrixSquareRoot CPU escape
:58-92). TPU-native differences:
  * ``trace(sqrtm(S1 S2))`` is computed with two on-device eighs
    (``metrics_tpu/ops/sqrtm.trace_sqrtm_product``) instead of scipy's sqrtm on the
    host — exact for PSD covariances, no device->host transfer.
  * the inception forward is a Flax module under the caller's mesh (sharding the
    batch shards the forward); weights load from a converted checkpoint (no egress).
  * the reference's float64 compute (``fid.py:269``) maps to x64 when enabled,
    otherwise the covariance accumulates in f32 with mean-subtracted features (the
    numerically dangerous term) — tested to ~1e-3 relative against numpy f64.
"""
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.ops.sqrtm import trace_sqrtm_product
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array, eps: float = 1e-6) -> Array:
    """FID between two Gaussians. Parity: reference ``fid.py:95-122``."""
    diff = mu1 - mu2
    tr_covmean = trace_sqrtm_product(sigma1, sigma2)
    # singular-product fallback (reference adds eps to the diagonals)
    offset = jnp.eye(sigma1.shape[0], dtype=sigma1.dtype) * eps
    tr_covmean = jnp.where(
        jnp.isfinite(tr_covmean),
        tr_covmean,
        trace_sqrtm_product(sigma1 + offset, sigma2 + offset),
    )
    return diff @ diff + jnp.trace(sigma1) + jnp.trace(sigma2) - 2 * tr_covmean


def _mean_cov(features: Array) -> Any:
    n = features.shape[0]
    mean = jnp.mean(features, axis=0)
    diff = features - mean
    cov = (diff.T @ diff) / (n - 1)
    return mean, cov


class FID(Metric):
    """Frechet Inception Distance.

    Args:
        feature: an int/str naming an inception tap (64/192/768/2048) or a callable
            ``imgs -> (N, d)`` feature extractor.
        params: optional flax params for the built-in InceptionV3 (converted
            pretrained weights; random init otherwise).

    Pretrained weights (the reference downloads them at runtime via torch-fidelity,
    ``fid.py:242``; this build converts them offline — conversion numerically
    verified in ``tests/tools/test_convert.py``)::

        # once, anywhere with the torch-fidelity checkpoint:
        python tools/convert_weights.py inception pt_inception-2015-12-05.pth inception_flax.pkl
        # then:
        from metrics_tpu.models.inception import InceptionFeatureExtractor
        fid = FrechetInceptionDistance(
            params=InceptionFeatureExtractor.load_params("inception_flax.pkl"))
    """

    is_differentiable = False
    higher_is_better = False

    def __init__(
        self,
        feature: Union[int, str, Callable] = 2048,
        params: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if callable(feature):
            self.inception = feature
        else:
            valid_int_input = ("64", "192", "768", "2048")
            if str(feature) not in valid_int_input:
                raise ValueError(
                    f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
                )
            from metrics_tpu.models.inception import InceptionFeatureExtractor

            self.inception = InceptionFeatureExtractor(feature=str(feature), params=params)

        self.add_state("real_features", default=[], dist_reduce_fx=None)
        self.add_state("fake_features", default=[], dist_reduce_fx=None)

    def update(self, imgs: Array, real: bool) -> None:
        """Extract features and append to the matching distribution's buffer."""
        features = self.inception(imgs)
        if real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def compute(self) -> Array:
        real_features = dim_zero_cat(self.real_features)
        fake_features = dim_zero_cat(self.fake_features)
        orig_dtype = real_features.dtype
        if jax.config.jax_enable_x64:
            real_features = real_features.astype(jnp.float64)
            fake_features = fake_features.astype(jnp.float64)
        mean1, cov1 = _mean_cov(real_features)
        mean2, cov2 = _mean_cov(fake_features)
        return _compute_fid(mean1, cov1, mean2, cov2).astype(orig_dtype)


FrechetInceptionDistance = FID
