"""Frechet Inception Distance — fully on-device (no scipy/CPU escape).

Parity: reference ``torchmetrics/image/fid.py:125`` (feature lists :248-249, update
:250-262, compute :264-281, _compute_fid :95-122, MatrixSquareRoot CPU escape
:58-92). TPU-native differences:
  * ``trace(sqrtm(S1 S2))`` is computed with two on-device eighs
    (``metrics_tpu/ops/sqrtm.trace_sqrtm_product``) instead of scipy's sqrtm on the
    host — exact for PSD covariances, no device->host transfer.
  * the inception forward is a Flax module under the caller's mesh (sharding the
    batch shards the forward); weights load from a converted checkpoint (no egress).
  * the reference's float64 compute (``fid.py:269``) runs as a scoped ON-DEVICE
    x64 island at compute time (``jax.enable_x64`` around the mean/cov/sqrtm —
    emulated f64 on TPU, native on CPU): eager computes match numpy f64 to
    ~1e-6 relative on CPU even for ill-conditioned features
    (``tests/image/test_fid_precision.py``). On the TPU backend the island
    removes the f32 accumulation error but the emulated f64 ``eigh`` carries
    ~1e-11*||C|| absolute eigenvalue error (measured; numpy is ~1e-16), which
    adversarially-conditioned spectra can amplify to ~1e-3 of the final FID —
    real inception covariances are far tamer. Under jit (where an island
    cannot open) the f32 path runs.
"""
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.ops.sqrtm import trace_sqrtm_product
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array, eps: float = 1e-6) -> Array:
    """FID between two Gaussians. Parity: reference ``fid.py:95-122``."""
    diff = mu1 - mu2
    tr_covmean = trace_sqrtm_product(sigma1, sigma2)
    # singular-product fallback (reference adds eps to the diagonals)
    offset = jnp.eye(sigma1.shape[0], dtype=sigma1.dtype) * eps
    tr_covmean = jnp.where(
        jnp.isfinite(tr_covmean),
        tr_covmean,
        trace_sqrtm_product(sigma1 + offset, sigma2 + offset),
    )
    return diff @ diff + jnp.trace(sigma1) + jnp.trace(sigma2) - 2 * tr_covmean


def _mean_cov(features: Array) -> Any:
    n = features.shape[0]
    mean = jnp.mean(features, axis=0)
    diff = features - mean
    cov = (diff.T @ diff) / (n - 1)
    return mean, cov


class FID(Metric):
    """Frechet Inception Distance.

    Args:
        feature: an int/str naming an inception tap (64/192/768/2048) or a callable
            ``imgs -> (N, d)`` feature extractor.
        params: optional flax params for the built-in InceptionV3 (converted
            pretrained weights; random init otherwise).

    Pretrained weights (the reference downloads them at runtime via torch-fidelity,
    ``fid.py:242``; this build converts them offline — conversion numerically
    verified in ``tests/tools/test_convert.py``)::

        # once, anywhere with the torch-fidelity checkpoint:
        python tools/convert_weights.py inception pt_inception-2015-12-05.pth inception_flax.pkl
        # then:
        from metrics_tpu.models.inception import InceptionFeatureExtractor
        fid = FrechetInceptionDistance(
            params=InceptionFeatureExtractor.load_params("inception_flax.pkl"))
    """

    is_differentiable = False
    higher_is_better = False

    def __init__(
        self,
        feature: Union[int, str, Callable] = 2048,
        params: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if callable(feature):
            self.inception = feature
        else:
            valid_int_input = ("64", "192", "768", "2048")
            if str(feature) not in valid_int_input:
                raise ValueError(
                    f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
                )
            from metrics_tpu.models.inception import InceptionFeatureExtractor

            self.inception = InceptionFeatureExtractor(feature=str(feature), params=params)

        self.add_state("real_features", default=[], dist_reduce_fx=None)
        self.add_state("fake_features", default=[], dist_reduce_fx=None)

    def update(self, imgs: Array, real: bool) -> None:
        """Extract features and append to the matching distribution's buffer."""
        features = self.inception(imgs)
        if real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def compute(self) -> Array:
        from metrics_tpu.utils.checks import _is_tracer

        real_features = dim_zero_cat(self.real_features)
        fake_features = dim_zero_cat(self.fake_features)
        orig_dtype = real_features.dtype
        if not jax.config.jax_enable_x64 and not (
            _is_tracer(real_features) or _is_tracer(fake_features)
        ):
            # the reference's f64 contract (fid.py:269), on device: a scoped
            # x64 island around the numerically dangerous mean/cov/eigh-sqrtm
            try:
                import numpy as np

                r_np, f_np = np.asarray(real_features), np.asarray(fake_features)
                with jax.enable_x64(True):
                    mean1, cov1 = _mean_cov(jnp.asarray(r_np, jnp.float64))
                    mean2, cov2 = _mean_cov(jnp.asarray(f_np, jnp.float64))
                    out = np.asarray(_compute_fid(mean1, cov1, mean2, cov2))
                return jnp.asarray(out, orig_dtype)
            except Exception as e:  # pragma: no cover - backend without f64
                # a LOUD fallback: silently returning the f32 result would let
                # the documented f64 parity rot invisibly
                rank_zero_warn(
                    f"FID's on-device f64 island failed ({type(e).__name__}: {str(e)[:120]});"
                    " falling back to the f32 path (~1e-3 relative on ill-conditioned"
                    " features).", UserWarning,
                )
        if jax.config.jax_enable_x64:
            real_features = real_features.astype(jnp.float64)
            fake_features = fake_features.astype(jnp.float64)
        mean1, cov1 = _mean_cov(real_features)
        mean2, cov2 = _mean_cov(fake_features)
        return _compute_fid(mean1, cov1, mean2, cov2).astype(orig_dtype)


FrechetInceptionDistance = FID
