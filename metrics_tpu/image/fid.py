"""Frechet Inception Distance — streaming, constant-memory, fully on-device.

Parity: reference ``torchmetrics/image/fid.py:125`` (feature lists :248-249, update
:250-262, compute :264-281, _compute_fid :95-122, MatrixSquareRoot CPU escape
:58-92). TPU-native differences:
  * **streaming statistics instead of feature lists**: the reference appends every
    feature batch to an unbounded python list (``fid.py:248-249``; its own docs warn
    about the memory cost at :224-228). Mean and covariance are linear statistics,
    so this build keeps a centered Chan/Welford triple ``(μ, M2=Σ(x−μ)(x−μ)ᵀ, n)``
    per distribution — O(d²) memory regardless of dataset size, batch-wise Chan
    combine on update, Chan fold across devices at sync (the pattern proven in
    ``regression/pearson.py``), compute inside a jitted graph. A 1M-image epoch
    runs in one compiled loop with flat memory (``tests/image/test_fid_streaming.py``).
  * **centered + float-float accumulation**: the naive raw-moment form
    ``Σxxᵀ − n·μμᵀ`` is catastrophically cancellative; centering keeps every
    accumulated magnitude at O(variance), and the running (μ, M2) are stored as
    compensated f32 pairs (``metrics_tpu/ops/floatfloat.py``, ~48 significant
    bits) so thousands of batch combines add no visible drift. The f64 contract
    (reference ``fid.py:269``) therefore holds *under jit* — not just in the
    eager x64 island.
  * ``trace(sqrtm(S1 S2))`` is computed with two on-device eighs
    (``metrics_tpu/ops/sqrtm.trace_sqrtm_product``) instead of scipy's sqrtm on the
    host — exact for PSD covariances, no device->host transfer.
  * the inception forward is a Flax module under the caller's mesh (sharding the
    batch shards the forward); weights load from a converted checkpoint (no egress).
  * eager compute still opens the scoped ON-DEVICE x64 island (emulated f64 on
    TPU, native on CPU) and recovers the pairs' full ~48 bits first: eager computes
    match numpy f64 to ~1e-6 relative on CPU even for ill-conditioned features
    (``tests/image/test_fid_precision.py``).

The sample counters are f32 (exact below 2²⁴ ≈ 16.7M samples per distribution —
above that the count itself rounds; the statistics stay finite).
"""
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.ops import floatfloat as ff
from metrics_tpu.ops.sqrtm import trace_sqrtm_product
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array

Pair = Tuple[Array, Array]


def _fid_from_stats(diff: Array, sigma1: Array, sigma2: Array, eps: float = 1e-6) -> Array:
    """FID from mean-difference + covariances. Parity: reference ``fid.py:95-122``."""
    tr_covmean = trace_sqrtm_product(sigma1, sigma2)
    # singular-product fallback (reference adds eps to the diagonals)
    offset = jnp.eye(sigma1.shape[0], dtype=sigma1.dtype) * eps
    tr_covmean = jnp.where(
        jnp.isfinite(tr_covmean),
        tr_covmean,
        trace_sqrtm_product(sigma1 + offset, sigma2 + offset),
    )
    return diff @ diff + jnp.trace(sigma1) + jnp.trace(sigma2) - 2 * tr_covmean


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array, eps: float = 1e-6) -> Array:
    return _fid_from_stats(mu1 - mu2, sigma1, sigma2, eps)


def _mean_cov(features: Array) -> Any:
    n = features.shape[0]
    mean = jnp.mean(features, axis=0)
    diff = features - mean
    cov = (diff.T @ diff) / (n - 1)
    return mean, cov


def _chan_combine(
    mean_a: Pair, m2_a: Pair, n_a: Array, mean_b: Pair, m2_b: Pair, n_b: Array
) -> Tuple[Pair, Pair, Array]:
    """Chan parallel combine of two centered statistic triples, in pair arithmetic.

    μ = μa + (nb/n)·δ,  M2 = M2a + M2b + (na·nb/n)·δδᵀ,  δ = μb − μa.
    Every term is O(variance)-scaled — no cancellation — and the pairs keep the
    running stats at ~48 bits across thousands of combines. ``n == 0`` operands
    are handled branch-free (weights become 0/1).
    """
    n = n_a + n_b
    safe_n = jnp.maximum(n, 1.0)
    frac_b = n_b / safe_n
    w = n_a * n_b / safe_n
    delta = ff.ff_sub(mean_b, mean_a)
    mean = ff.ff_add(mean_a, ff.ff_scale(delta, frac_b))
    d_col = (delta[0][:, None], delta[1][:, None])
    d_row = (delta[0][None, :], delta[1][None, :])
    m2 = ff.ff_add(ff.ff_add(m2_a, m2_b), ff.ff_scale(ff.ff_mul(d_col, d_row), w))
    return mean, m2, n


class FID(Metric):
    """Frechet Inception Distance with streaming constant-memory statistics.

    Args:
        feature: an int/str naming an inception tap (64/192/768/2048) or a callable
            ``imgs -> (N, d)`` feature extractor.
        params: optional flax params for the built-in InceptionV3 (converted
            pretrained weights; random init otherwise).
        feature_dim: the feature dimension ``d`` — required for streaming mode when
            ``feature`` is a callable (inferred automatically for the named taps).
        streaming: accumulate ``(μ, M2, n)`` instead of feature lists. Default
            True whenever the feature dimension is known; a callable ``feature``
            without ``feature_dim`` falls back to list mode.

    Pretrained weights (the reference downloads them at runtime via torch-fidelity,
    ``fid.py:242``; this build converts them offline — conversion numerically
    verified in ``tests/tools/test_convert.py``)::

        # once, anywhere with the torch-fidelity checkpoint:
        python tools/convert_weights.py inception pt_inception-2015-12-05.pth inception_flax.pkl
        # then:
        from metrics_tpu.models.inception import InceptionFeatureExtractor
        fid = FrechetInceptionDistance(
            params=InceptionFeatureExtractor.load_params("inception_flax.pkl"))
    """

    is_differentiable = False
    higher_is_better = False

    def __init__(
        self,
        feature: Union[int, str, Callable] = 2048,
        params: Optional[Any] = None,
        feature_dim: Optional[int] = None,
        streaming: Optional[bool] = None,
        mesh: Optional[Any] = None,
        mesh_axis: Any = "dp",
        model_host: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        from metrics_tpu.models.inception import resolve_feature_extractor

        # mesh: run the inception forward batch-parallel over the mesh's data
        # axis (params replicated) — the sharded embedded-model path
        # (parallel/embedded.py); IS/KID share the same ctor logic.
        # model_host: serve the forward from a shared resident ModelHost
        # (bucketed, coalesced, AOT-cached; engine/model_host.py) — metrics
        # with the same (tap, params, mesh, precision) share one model copy.
        self.inception, builtin_dim = resolve_feature_extractor(
            "FID", feature, params, mesh, mesh_axis, ("64", "192", "768", "2048"),
            model_host=model_host,
        )
        self.model_host = getattr(self.inception, "model_host", None)
        if feature_dim is None:
            feature_dim = builtin_dim

        if streaming is None:
            streaming = feature_dim is not None
        if streaming and feature_dim is None:
            raise ValueError(
                "FID(streaming=True) with a callable `feature` needs `feature_dim=` "
                "(the extractor's output width) to allocate the statistic buffers."
            )
        self.streaming = bool(streaming)
        self.feature_dim = feature_dim

        if self.streaming:
            # streaming stats merge jointly (Chan formula over the whole triple),
            # so forward() must snapshot/restore rather than delta-merge leaf-wise;
            # instance-level so list mode keeps the single-update forward path
            self.full_state_update = True
            d = int(feature_dim)
            zeros_d = jnp.zeros((d,), jnp.float32)
            zeros_dd = jnp.zeros((d, d), jnp.float32)
            for dist in ("real", "fake"):
                # None-reduction: sync gathers (world, ...)-stacked stats which
                # compute() folds with the Chan formula (the Pearson pattern)
                self.add_state(f"{dist}_mean_hi", default=zeros_d, dist_reduce_fx=None)
                self.add_state(f"{dist}_mean_lo", default=zeros_d, dist_reduce_fx=None)
                self.add_state(f"{dist}_m2_hi", default=zeros_dd, dist_reduce_fx=None)
                self.add_state(f"{dist}_m2_lo", default=zeros_dd, dist_reduce_fx=None)
                self.add_state(f"{dist}_n", default=jnp.asarray(0.0, jnp.float32), dist_reduce_fx=None)
        else:
            self.add_state("real_features", default=[], dist_reduce_fx=None)
            self.add_state("fake_features", default=[], dist_reduce_fx=None)

    def update(self, imgs: Array, real: bool) -> None:
        """Extract features and fold them into the matching distribution's statistics."""
        features = self.inception(imgs)
        if not self.streaming:
            if real:
                self.real_features.append(features)
            else:
                self.fake_features.append(features)
            return

        features = jnp.asarray(features, jnp.float32)
        bn = jnp.float32(features.shape[0])
        bm = jnp.mean(features, axis=0)
        centered = features - bm
        # f32 matmuls lower to bf16 passes on the MXU by default — the statistic
        # accumulators need the full f32 product
        bm2 = jnp.matmul(centered.T, centered, precision=jax.lax.Precision.HIGHEST)

        dist = "real" if real else "fake"
        mean, m2, n = self._triple(dist)
        mean, m2, n = _chan_combine(mean, m2, n, ff.ff_from_f32(bm), ff.ff_from_f32(bm2), bn)
        self._set_triple(dist, mean, m2, n)

    def _triple(self, dist: str) -> Tuple[Pair, Pair, Array]:
        return (
            (getattr(self, f"{dist}_mean_hi"), getattr(self, f"{dist}_mean_lo")),
            (getattr(self, f"{dist}_m2_hi"), getattr(self, f"{dist}_m2_lo")),
            getattr(self, f"{dist}_n"),
        )

    def _set_triple(self, dist: str, mean: Pair, m2: Pair, n: Array) -> None:
        setattr(self, f"{dist}_mean_hi", mean[0])
        setattr(self, f"{dist}_mean_lo", mean[1])
        setattr(self, f"{dist}_m2_hi", m2[0])
        setattr(self, f"{dist}_m2_lo", m2[1])
        setattr(self, f"{dist}_n", n)

    def _folded_triple(self, dist: str) -> Tuple[Pair, Pair, Array]:
        """The distribution's (μ, M2, n); post-sync (world, ...)-stacked stats are
        folded with the Chan formula over the static world dimension."""
        mean, m2, n = self._triple(dist)
        if m2[0].ndim == 3:  # stacked: (world, d, d)
            world = m2[0].shape[0]
            fmean = (mean[0][0], mean[1][0])
            fm2 = (m2[0][0], m2[1][0])
            fn = n[0]
            for i in range(1, world):
                fmean, fm2, fn = _chan_combine(
                    fmean, fm2, fn, (mean[0][i], mean[1][i]), (m2[0][i], m2[1][i]), n[i]
                )
            return fmean, fm2, fn
        return mean, m2, n

    def _compute_streaming(self) -> Array:
        from metrics_tpu.utils.checks import _is_tracer

        r_mean, r_m2, r_n = self._folded_triple("real")
        f_mean, f_m2, f_n = self._folded_triple("fake")
        tracing = _is_tracer(r_m2[0]) or _is_tracer(f_m2[0])
        # a covariance needs n >= 2; under-filled distributions must read NaN
        # (the list path's empty-cat mean), not a spuriously perfect 0.0
        enough = jnp.minimum(r_n, f_n) >= 2.0

        if not jax.config.jax_enable_x64 and not tracing:
            # eager: recover the pairs' full width inside the on-device x64 island
            # (reference's f64 contract, fid.py:269)
            try:
                import numpy as np

                host = jax.tree_util.tree_map(
                    np.asarray, (r_mean, r_m2, r_n, f_mean, f_m2, f_n)
                )
                with jax.enable_x64(True):
                    hr_mean, hr_m2, hr_n, hf_mean, hf_m2, hf_n = jax.tree_util.tree_map(
                        jnp.asarray, host
                    )
                    mu1 = ff.ff_to_f64(hr_mean)
                    cov1 = ff.ff_to_f64(hr_m2) / (hr_n.astype(jnp.float64) - 1.0)
                    mu2 = ff.ff_to_f64(hf_mean)
                    cov2 = ff.ff_to_f64(hf_m2) / (hf_n.astype(jnp.float64) - 1.0)
                    out = np.asarray(
                        jnp.where(enough, _compute_fid(mu1, cov1, mu2, cov2), jnp.nan)
                    )
                return jnp.asarray(out, jnp.float32)
            except Exception as e:  # pragma: no cover - backend without f64
                rank_zero_warn(
                    f"FID's on-device f64 island failed ({type(e).__name__}: {str(e)[:120]});"
                    " falling back to the in-trace float-float path.", UserWarning,
                )

        # in-trace (or x64-globally-on): pair arithmetic keeps the stats at ~48
        # bits; the final f32 rounding only loses what f32 cannot represent of
        # the *result*
        diff = ff.ff_to_f32(ff.ff_sub(r_mean, f_mean))
        cov1 = ff.ff_to_f32(ff.ff_scale(r_m2, 1.0 / jnp.maximum(r_n - 1.0, 1.0)))
        cov2 = ff.ff_to_f32(ff.ff_scale(f_m2, 1.0 / jnp.maximum(f_n - 1.0, 1.0)))
        return jnp.where(enough, _fid_from_stats(diff, cov1, cov2), jnp.nan)

    def compute(self) -> Array:
        if self.streaming:
            return self._compute_streaming()

        from metrics_tpu.utils.checks import _is_tracer

        real_features = dim_zero_cat(self.real_features)
        fake_features = dim_zero_cat(self.fake_features)
        orig_dtype = real_features.dtype
        if not jax.config.jax_enable_x64 and not (
            _is_tracer(real_features) or _is_tracer(fake_features)
        ):
            # the reference's f64 contract (fid.py:269), on device: a scoped
            # x64 island around the numerically dangerous mean/cov/eigh-sqrtm
            try:
                import numpy as np

                r_np, f_np = np.asarray(real_features), np.asarray(fake_features)
                with jax.enable_x64(True):
                    mean1, cov1 = _mean_cov(jnp.asarray(r_np, jnp.float64))
                    mean2, cov2 = _mean_cov(jnp.asarray(f_np, jnp.float64))
                    out = np.asarray(_compute_fid(mean1, cov1, mean2, cov2))
                return jnp.asarray(out, orig_dtype)
            except Exception as e:  # pragma: no cover - backend without f64
                # a LOUD fallback: silently returning the f32 result would let
                # the documented f64 parity rot invisibly
                rank_zero_warn(
                    f"FID's on-device f64 island failed ({type(e).__name__}: {str(e)[:120]});"
                    " falling back to the f32 path (~1e-3 relative on ill-conditioned"
                    " features).", UserWarning,
                )
        if jax.config.jax_enable_x64:
            real_features = real_features.astype(jnp.float64)
            fake_features = fake_features.astype(jnp.float64)
        mean1, cov1 = _mean_cov(real_features)
        mean2, cov2 = _mean_cov(fake_features)
        return _compute_fid(mean1, cov1, mean2, cov2).astype(orig_dtype)


FrechetInceptionDistance = FID
