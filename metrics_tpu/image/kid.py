"""Kernel Inception Distance.

Parity: reference ``torchmetrics/image/kid.py:65`` (maximum_mean_discrepancy :27,
poly_kernel :48, poly_mmd :55, states :235-236, compute :252-280). The per-subset
sampling runs with a host RNG (eval-time), each MMD evaluation is an MXU matmul.
"""
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


def maximum_mean_discrepancy(k_xx: Array, k_xy: Array, k_yy: Array) -> Array:
    m = k_xx.shape[0]
    diag_x = jnp.diag(k_xx)
    diag_y = jnp.diag(k_yy)
    kt_xx_sum = jnp.sum(k_xx) - jnp.sum(diag_x)
    kt_yy_sum = jnp.sum(k_yy) - jnp.sum(diag_y)
    k_xy_sum = jnp.sum(k_xy)
    value = (kt_xx_sum + kt_yy_sum) / (m * (m - 1))
    value = value - 2 * k_xy_sum / (m ** 2)
    return value


def poly_kernel(f1: Array, f2: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0) -> Array:
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (f1 @ f2.T * gamma + coef) ** degree


def poly_mmd(
    f_real: Array, f_fake: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0
) -> Array:
    k_11 = poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = poly_kernel(f_real, f_fake, degree, gamma, coef)
    return maximum_mean_discrepancy(k_11, k_12, k_22)


class KID(Metric):
    """Kernel Inception Distance: polynomial-kernel MMD over inception features."""

    is_differentiable = False
    higher_is_better = False

    def __init__(
        self,
        feature: Union[int, str, Callable] = 2048,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        params: Optional[Any] = None,
        seed: Optional[int] = None,
        mesh: Optional[Any] = None,
        mesh_axis: Any = "dp",
        model_host: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        from metrics_tpu.models.inception import resolve_feature_extractor

        # model_host: share a resident serving host with other metrics on the
        # same (tap, params fingerprint) — see engine/model_host.py.
        self.inception, _ = resolve_feature_extractor(
            "KID", feature, params, mesh, mesh_axis, ("64", "192", "768", "2048"),
            model_host=model_host,
        )
        self.model_host = getattr(self.inception, "model_host", None)

        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        self.subsets = subsets
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        self.subset_size = subset_size
        if not (isinstance(degree, int) and degree > 0):
            raise ValueError("Argument `degree` expected to be integer larger than 0")
        self.degree = degree
        if gamma is not None and not (isinstance(gamma, float) and gamma > 0):
            raise ValueError("Argument `gamma` expected to be `None` or float larger than 0")
        self.gamma = gamma
        if not (isinstance(coef, float) and coef > 0):
            raise ValueError("Argument `coef` expected to be float larger than 0")
        self.coef = coef
        self._rng = np.random.RandomState(seed)

        self.add_state("real_features", default=[], dist_reduce_fx=None)
        self.add_state("fake_features", default=[], dist_reduce_fx=None)

    def update(self, imgs: Array, real: bool) -> None:
        features = self.inception(imgs)
        if real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def compute(self) -> Tuple[Array, Array]:
        """Returns (mean, std) of MMD over random subsets. Parity: ``:252-280``."""
        real_features = dim_zero_cat(self.real_features)
        fake_features = dim_zero_cat(self.fake_features)

        n_samples_real = real_features.shape[0]
        if n_samples_real < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")
        n_samples_fake = fake_features.shape[0]
        if n_samples_fake < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")

        kid_scores_ = []
        for _ in range(self.subsets):
            # subset_size == n takes every sample: use the identity permutation
            # so the subset MMD is a deterministic function of the features
            # (float reassociation across shuffled orders would jitter scores
            # that are mathematically identical) — every subset then scores the
            # same and std is exactly 0
            if self.subset_size == n_samples_real:
                f_real = real_features
            else:
                perm = self._rng.permutation(n_samples_real)[: self.subset_size]
                f_real = real_features[jnp.asarray(perm)]
            if self.subset_size == n_samples_fake:
                f_fake = fake_features
            else:
                perm = self._rng.permutation(n_samples_fake)[: self.subset_size]
                f_fake = fake_features[jnp.asarray(perm)]
            kid_scores_.append(poly_mmd(f_real, f_fake, self.degree, self.gamma, self.coef))
        kid_scores = jnp.stack(kid_scores_)
        return jnp.mean(kid_scores), jnp.std(kid_scores)


KernelInceptionDistance = KID
