from metrics_tpu.image.fid import FID, FrechetInceptionDistance
from metrics_tpu.image.inception import IS, InceptionScore
from metrics_tpu.image.kid import KID, KernelInceptionDistance
from metrics_tpu.image.lpip_similarity import LPIPS
from metrics_tpu.image.psnr import PSNR
from metrics_tpu.image.ssim import SSIM, MultiScaleStructuralSimilarityIndexMeasure
