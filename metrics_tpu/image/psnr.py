"""PSNR module metric.

Parity: reference ``torchmetrics/image/psnr.py:24`` (states :94-110: sum/cat depending
on ``dim``; min/max reduce for inferred data_range).
"""
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.psnr import _psnr_compute, _psnr_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class PSNR(Metric):
    """Peak signal-to-noise ratio.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PSNR
        >>> preds = jnp.asarray([[0.0, 1.0], [1.0, 0.0]])
        >>> target = jnp.asarray([[0.0, 1.0], [1.0, 1.0]])
        >>> psnr = PSNR(data_range=1.0)
        >>> print(f"{float(psnr(preds, target)):.4f}")
        6.0206
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        data_range: Optional[float] = None,
        base: float = 10.0,
        reduction: str = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if dim is None and reduction != "elementwise_mean":
            rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")

        if dim is None:
            self.add_state("sum_squared_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
            self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")
        else:
            self.add_state("sum_squared_error", default=[], dist_reduce_fx="cat")
            self.add_state("total", default=[], dist_reduce_fx="cat")

        if data_range is None:
            if dim is not None:
                raise ValueError("The `data_range` must be given when `dim` is not None.")
            self.data_range = None
            self.add_state("min_target", default=jnp.asarray(jnp.inf), dist_reduce_fx="min")
            self.add_state("max_target", default=jnp.asarray(-jnp.inf), dist_reduce_fx="max")
        else:
            self.add_state("data_range", default=jnp.asarray(float(data_range)), dist_reduce_fx="mean")
        self.base = base
        self.reduction = reduction
        self.dim = tuple(dim) if isinstance(dim, Sequence) else dim

    def update(self, preds: Array, target: Array) -> None:
        preds, target = jnp.asarray(preds), jnp.asarray(target)
        sum_squared_error, n_obs = _psnr_update(preds, target, dim=self.dim)
        if self.dim is None:
            if self.data_range is None:
                # keep running min/max to infer the data range at compute
                self.min_target = jnp.minimum(jnp.min(target), self.min_target)
                self.max_target = jnp.maximum(jnp.max(target), self.max_target)
            self.sum_squared_error = self.sum_squared_error + sum_squared_error
            self.total = self.total + n_obs
        else:
            self.sum_squared_error.append(jnp.ravel(sum_squared_error))
            self.total.append(jnp.ravel(n_obs))

    def compute(self) -> Array:
        if self.data_range is not None:
            data_range = self.data_range
        else:
            data_range = self.max_target - self.min_target
        if self.dim is None:
            sum_squared_error = self.sum_squared_error
            total = self.total
        else:
            sum_squared_error = dim_zero_cat(self.sum_squared_error)
            total = dim_zero_cat(self.total)
        return _psnr_compute(sum_squared_error, total, data_range, base=self.base, reduction=self.reduction)
