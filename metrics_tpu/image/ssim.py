"""SSIM and MultiScaleSSIM module metrics.

Parity: reference ``torchmetrics/image/ssim.py:27`` (cat states :82-83) and ``:111``
(MS-SSIM, states :179).
"""
from typing import Any, Optional, Sequence, Tuple

import jax

from metrics_tpu.functional.image.ms_ssim import _multiscale_ssim_compute
from metrics_tpu.functional.image.ssim import _ssim_compute, _ssim_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class SSIM(Metric):
    """Structural similarity index measure.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SSIM
        >>> preds = jnp.arange(256.0).reshape(1, 1, 16, 16) / 255.0
        >>> target = preds * 0.9
        >>> ssim = SSIM()
        >>> print(f"{float(ssim(preds, target)):.4f}")
        0.9893
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: str = "elementwise_mean",
        data_range: Optional[float] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        rank_zero_warn(
            "Metric `SSIM` will save all targets and predictions in buffer."
            " For large datasets this may lead to large memory footprint."
        )
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")
        self.kernel_size = tuple(kernel_size)
        self.sigma = tuple(sigma)
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.reduction = reduction

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _ssim_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _ssim_compute(
            preds, target, self.kernel_size, self.sigma, self.reduction, self.data_range, self.k1, self.k2
        )


class MultiScaleStructuralSimilarityIndexMeasure(Metric):
    """Multi-scale SSIM."""

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: str = "elementwise_mean",
        data_range: Optional[float] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
        normalize: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        rank_zero_warn(
            "Metric `MS_SSIM` will save all targets and predictions in buffer."
            " For large datasets this may lead to large memory footprint."
        )
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")
        self.kernel_size = tuple(kernel_size)
        self.sigma = tuple(sigma)
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.reduction = reduction
        if not isinstance(betas, tuple) or not all(isinstance(beta, float) for beta in betas):
            raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
        self.betas = betas
        if normalize and normalize not in ("relu", "simple"):
            raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
        self.normalize = normalize

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _ssim_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _multiscale_ssim_compute(
            preds, target, self.kernel_size, self.sigma, self.reduction, self.data_range,
            self.k1, self.k2, self.betas, self.normalize,
        )
