"""Inception Score.

Parity: reference ``torchmetrics/image/inception.py:26`` (logits features, KL-based
score over splits, compute :160-200). TPU-native addition: ``streaming=True``
replaces the unbounded feature list with per-split accumulable statistics —
the split-KL decomposes exactly as

    KL_s = ( Σ_{i∈s} Σ_y p_iy·log p_iy  −  Σ_y (Σ_{i∈s} p_iy)·log m_sy ) / n_s,
    m_sy = (Σ_{i∈s} p_iy) / n_s,

so a ``(Σp, Σ p·logp, n)`` triple per split is sufficient: O(splits·C) memory
regardless of dataset size, pure-psum sync, in-trace compute. Samples are
assigned to splits by a counter-derived PRNG stream (``jax.random.fold_in`` on
the running sample count), replacing the reference's gather-everything-then-
permute (``inception.py:171``): statistically identical, jit-pure, and
deterministic for a fixed seed + update sequence.
"""
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.ops import floatfloat as ff
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class IS(Metric):
    """Inception Score: exp of mean split-KL between p(y|x) and p(y).

    Args:
        feature: an int/str naming an inception tap or a callable ``imgs -> (N, C)``
            logits extractor.
        splits: number of splits for the mean/std estimate.
        params: optional flax params for the built-in InceptionV3.
        seed: RNG seed for split assignment.
        streaming: accumulate per-split statistics instead of a feature list —
            constant memory, jit-compatible compute. Split *membership* then comes
            from a counter-derived PRNG stream instead of a full permutation at
            compute time, so per-seed values differ from list mode (the score
            distribution is identical; the reference itself documents the
            shuffle-dependence of IS). Default False (list-mode parity).
        feature_dim: logits width ``C`` — required for streaming with a callable
            ``feature`` (inferred for the named taps).
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        feature: Union[int, str, Callable] = "logits_unbiased",
        splits: int = 10,
        params: Optional[Any] = None,
        seed: Optional[int] = None,
        streaming: bool = False,
        feature_dim: Optional[int] = None,
        mesh: Optional[Any] = None,
        mesh_axis: Any = "dp",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        from metrics_tpu.models.inception import resolve_feature_extractor

        self.inception, builtin_dim = resolve_feature_extractor(
            "InceptionScore", feature, params, mesh, mesh_axis,
            ("logits_unbiased", "64", "192", "768", "2048"),
        )
        if feature_dim is None:
            feature_dim = builtin_dim

        self.splits = splits
        # seed=None matches list mode's run-to-run randomised shuffle: draw a
        # fresh assignment seed instead of silently pinning 0
        self._seed = int(np.random.randint(0, 2**31 - 1)) if seed is None else int(seed)
        self._rng = np.random.RandomState(seed)
        self.streaming = bool(streaming)
        if self.streaming:
            # forward() must snapshot/restore, not delta-merge: the counter-derived
            # assignment key reads sum(split_n), which a zeroed delta state would
            # freeze at fold_in(seed, 0) for every batch
            self.full_state_update = True
            if feature_dim is None:
                raise ValueError(
                    "InceptionScore(streaming=True) with a callable `feature` needs "
                    "`feature_dim=` (the logits width) to allocate the statistic buffers."
                )
            c = int(feature_dim)
            zeros_sc = jnp.zeros((splits, c), jnp.float32)
            zeros_s = jnp.zeros((splits,), jnp.float32)
            self.add_state("prob_sum_hi", default=zeros_sc, dist_reduce_fx="sum")
            self.add_state("prob_sum_lo", default=zeros_sc, dist_reduce_fx="sum")
            self.add_state("plogp_sum_hi", default=zeros_s, dist_reduce_fx="sum")
            self.add_state("plogp_sum_lo", default=zeros_s, dist_reduce_fx="sum")
            self.add_state("split_n", default=zeros_s, dist_reduce_fx="sum")
        else:
            self.add_state("features", default=[], dist_reduce_fx=None)

    def update(self, imgs: Array) -> None:
        features = self.inception(imgs)
        if not self.streaming:
            self.features.append(features)
            return

        features = jnp.asarray(features, jnp.float32)
        prob = jax.nn.softmax(features, axis=1)
        log_prob = jax.nn.log_softmax(features, axis=1)
        # counter-derived assignment: pure under jit, deterministic per seed+order
        n_seen = jnp.sum(self.split_n).astype(jnp.int32)
        key = jax.random.fold_in(jax.random.PRNGKey(self._seed), n_seen)
        assign = jax.random.randint(key, (features.shape[0],), 0, self.splits)
        onehot = jax.nn.one_hot(assign, self.splits, dtype=jnp.float32)  # (N, S)
        batch_prob = jnp.matmul(onehot.T, prob, precision=jax.lax.Precision.HIGHEST)
        batch_plogp = jnp.matmul(
            onehot.T, jnp.sum(prob * log_prob, axis=1), precision=jax.lax.Precision.HIGHEST
        )
        p = ff.ff_add_f32((self.prob_sum_hi, self.prob_sum_lo), batch_prob)
        pl = ff.ff_add_f32((self.plogp_sum_hi, self.plogp_sum_lo), batch_plogp)
        self.prob_sum_hi, self.prob_sum_lo = p
        self.plogp_sum_hi, self.plogp_sum_lo = pl
        self.split_n = self.split_n + jnp.sum(onehot, axis=0)

    def compute(self) -> Tuple[Array, Array]:
        if self.streaming:
            prob_sum = self.prob_sum_hi + self.prob_sum_lo  # (S, C)
            plogp_sum = self.plogp_sum_hi + self.plogp_sum_lo  # (S,)
            n_s = self.split_n  # (S,)
            # random assignment can leave a split empty at small N (list mode's
            # array_split cannot): mask empty splits out of the mean/std instead
            # of letting the 0/0 poison the score
            valid = n_s > 0
            safe_n = jnp.maximum(n_s, 1.0)
            m_p = prob_sum / safe_n[:, None]
            cross = jnp.sum(prob_sum * jnp.log(jnp.maximum(m_p, 1e-38)), axis=1)
            kl = jnp.exp((plogp_sum - cross) / safe_n)
            k = jnp.sum(valid)
            mean = jnp.sum(jnp.where(valid, kl, 0.0)) / k
            var = jnp.sum(jnp.where(valid, (kl - mean) ** 2, 0.0)) / jnp.maximum(k - 1, 1)
            return mean, jnp.sqrt(var)

        features = dim_zero_cat(self.features)
        idx = jnp.asarray(self._rng.permutation(features.shape[0]))
        features = features[idx]

        prob = jax.nn.softmax(features, axis=1)
        log_prob = jax.nn.log_softmax(features, axis=1)

        prob_chunks = jnp.array_split(prob, self.splits, axis=0)
        log_prob_chunks = jnp.array_split(log_prob, self.splits, axis=0)

        kl_ = []
        for p, log_p in zip(prob_chunks, log_prob_chunks):
            m_p = jnp.mean(p, axis=0, keepdims=True)
            kl = p * (log_p - jnp.log(m_p))
            kl_.append(jnp.exp(jnp.mean(jnp.sum(kl, axis=1))))
        kl = jnp.stack(kl_)
        return jnp.mean(kl), jnp.std(kl, ddof=1)


InceptionScore = IS
