"""Inception Score.

Parity: reference ``torchmetrics/image/inception.py:26`` (logits features, KL-based
score over splits, compute :160-200).
"""
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class IS(Metric):
    """Inception Score: exp of mean split-KL between p(y|x) and p(y)."""

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        feature: Union[int, str, Callable] = "logits_unbiased",
        splits: int = 10,
        params: Optional[Any] = None,
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if callable(feature):
            self.inception = feature
        else:
            valid_input = ("logits_unbiased", "64", "192", "768", "2048")
            if str(feature) not in valid_input:
                raise ValueError(
                    f"Input to argument `feature` must be one of {valid_input}, but got {feature}."
                )
            from metrics_tpu.models.inception import InceptionFeatureExtractor

            self.inception = InceptionFeatureExtractor(feature=str(feature), params=params)

        self.splits = splits
        self._rng = np.random.RandomState(seed)
        self.add_state("features", default=[], dist_reduce_fx=None)

    def update(self, imgs: Array) -> None:
        features = self.inception(imgs)
        self.features.append(features)

    def compute(self) -> Tuple[Array, Array]:
        features = dim_zero_cat(self.features)
        idx = jnp.asarray(self._rng.permutation(features.shape[0]))
        features = features[idx]

        prob = jax.nn.softmax(features, axis=1)
        log_prob = jax.nn.log_softmax(features, axis=1)

        prob_chunks = jnp.array_split(prob, self.splits, axis=0)
        log_prob_chunks = jnp.array_split(log_prob, self.splits, axis=0)

        kl_ = []
        for p, log_p in zip(prob_chunks, log_prob_chunks):
            m_p = jnp.mean(p, axis=0, keepdims=True)
            kl = p * (log_p - jnp.log(m_p))
            kl_.append(jnp.exp(jnp.mean(jnp.sum(kl, axis=1))))
        kl = jnp.stack(kl_)
        return jnp.mean(kl), jnp.std(kl, ddof=1)


InceptionScore = IS
