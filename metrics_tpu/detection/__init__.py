from metrics_tpu.detection.map import MAP, MeanAveragePrecision
