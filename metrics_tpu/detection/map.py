"""Mean Average Precision / Recall for object detection (COCO semantics).

Parity: reference ``torchmetrics/detection/map.py:132`` — same contract end to end:
dict-of-tensors input validation (:82), 5 gather-list states (:269-273), per-image
per-class IoU matrices (:343), greedy IoU-threshold matching with crowd/area-ignore
handling (:378-491), 101-point interpolated precision (:616), ``_summarize`` (:493)
and a ``COCOMetricResults`` dict of 12+ entries with per-class options (:683).

TPU split (``matching="device"``, the default): all (image, class) pairs are padded
into one batch, and IoU + the greedy per-detection threshold matching run as ONE
jitted device call — a ``lax.scan`` over score-sorted detections carrying the
matched-gt mask, vmapped over IoU thresholds, area ranges, and pairs — followed by a
single device→host transfer. The reference's per-image python loops
(``map.py:343,378-491``) and round 1's per-image host transfers are gone. The
host-side numpy matcher is kept as ``matching="host"`` — it is the parity oracle
(``tests/detection/test_map_device.py`` asserts both paths agree bit-for-bit on the
final metrics). The 101-point interpolation/accumulation stays host-side numpy: it
is O(total detections) once per compute, data-dependent, and — measured, not
asserted — NOT the at-scale serial tail: its fraction of ``compute()`` falls as
detection density grows (~43% at ~17 dets/img -> ~4% at ~1700 on the same
corpus; the vectorized cumsum pass grows slower than the padded matching).
``bench.py`` re-measures this on-chip each round (``detection_map.host_tail``).
"""
from collections import OrderedDict
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from metrics_tpu.metric import GroupedAggregateSpec, GroupedField, GroupedUpdateSpec, Metric

Array = jax.Array


class BaseMetricResults(dict):
    """Dict with attribute access. Parity: reference ``map.py:31-46``."""

    def __getattr__(self, key: str):
        if key in self:
            return self[key]
        raise AttributeError(f"No such attribute: {key}")

    def __setattr__(self, key: str, value) -> None:
        self[key] = value

    def __delattr__(self, key: str) -> None:
        if key in self:
            del self[key]


class MAPMetricResults(BaseMetricResults):
    __slots__ = ("map", "map_50", "map_75", "map_small", "map_medium", "map_large")


class MARMetricResults(BaseMetricResults):
    __slots__ = ("mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large")


class COCOMetricResults(BaseMetricResults):
    __slots__ = (
        "map", "map_50", "map_75", "map_small", "map_medium", "map_large",
        "mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large",
        "map_per_class", "mar_100_per_class",
    )


def box_convert(boxes: Array, in_fmt: str, out_fmt: str = "xyxy") -> Array:
    """Convert between xyxy / xywh / cxcywh box formats."""
    boxes = jnp.asarray(boxes, dtype=jnp.float32).reshape(-1, 4)
    if in_fmt == out_fmt:
        return boxes
    if in_fmt == "xywh":
        x, y, w, h = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
        return jnp.stack([x, y, x + w, y + h], axis=1)
    if in_fmt == "cxcywh":
        cx, cy, w, h = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
        return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=1)
    if in_fmt == "xyxy":
        return boxes
    raise ValueError(f"Unsupported box format {in_fmt}")


def box_area(boxes: Array) -> Array:
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def box_iou(boxes1: Array, boxes2: Array) -> Array:
    """Pairwise IoU of two (N,4)/(M,4) xyxy box sets — one broadcast kernel."""
    area1 = box_area(boxes1)
    area2 = box_area(boxes2)
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _input_validator(preds: Sequence[Dict[str, Any]], targets: Sequence[Dict[str, Any]]) -> None:
    """Parity: reference ``map.py:82-122``."""
    if not isinstance(preds, Sequence):
        raise ValueError("Expected argument `preds` to be of type List")
    if not isinstance(targets, Sequence):
        raise ValueError("Expected argument `target` to be of type List")
    if len(preds) != len(targets):
        raise ValueError("Expected argument `preds` and `target` to have the same length")

    for k in ("boxes", "scores", "labels"):
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in ("boxes", "labels"):
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")
    for item in targets:
        if np.shape(item["boxes"])[0] != np.shape(item["labels"])[0]:
            raise ValueError("Input boxes and labels of sample in targets have a different length")
    for item in preds:
        if not (np.shape(item["boxes"])[0] == np.shape(item["scores"])[0] == np.shape(item["labels"])[0]):
            raise ValueError("Input boxes, scores and labels of sample in predictions have a different length")


def _fix_empty_tensors(boxes: Array) -> Array:
    if boxes.size == 0 and boxes.ndim == 1:
        return boxes.reshape(-1, 4)
    return boxes


def _box_convert_np(boxes: Any, in_fmt: str) -> np.ndarray:
    """Host-side box conversion for update(): detection states are STAGED ON HOST
    (numpy) so per-image updates cost zero device round-trips; the whole padded
    batch ships to the device once per compute (``_match_all_pairs``)."""
    boxes = np.asarray(boxes, dtype=np.float32).reshape(-1, 4)
    if in_fmt == "xyxy":
        return boxes
    if in_fmt == "xywh":
        x, y, w, h = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
        return np.stack([x, y, x + w, y + h], axis=1)
    if in_fmt == "cxcywh":
        cx, cy, w, h = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
        return np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=1)
    raise ValueError(f"Unsupported box format {in_fmt}")


def _bucket(n: int, mult: int) -> int:
    """Round up to a multiple of ``mult`` — bounds the number of distinct
    compiled shapes without the 2x padding waste of pow2 bucketing."""
    return ((n + mult - 1) // mult) * mult


def _pr_accumulate(
    det_scores: np.ndarray,  # (N,) corpus det scores, image-major
    det_matches: np.ndarray,  # (T, N) bool
    det_ignore: np.ndarray,  # (T, N) bool
    npig: int,
    rec_thresholds: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The 101-point PR accumulation for ONE (class, area, max_det) cell.

    Exactly the reference inner math (``map.py:616``), float64 numpy, shared
    by ``_calculate`` (eager states) and ``grouped_corpus_finish`` (ragged
    device bundle) so the two paths cannot drift. Inputs are the
    corpus-concatenated per-detection columns in image order; the global
    mergesort by descending score happens here. Returns
    ``(recall (T,), precision (T, R), scores (T, R))``.
    """
    nb_rec_thrs = len(rec_thresholds)
    inds = np.argsort(-det_scores, kind="mergesort")
    det_scores_sorted = det_scores[inds]
    det_matches = det_matches[:, inds]
    det_ignore = det_ignore[:, inds]
    tps = det_matches & ~det_ignore
    fps = ~det_matches & ~det_ignore
    tp_sum = np.cumsum(tps, axis=1, dtype=np.float64)
    fp_sum = np.cumsum(fps, axis=1, dtype=np.float64)
    nb_iou_thrs = det_matches.shape[0]
    recall_out = np.zeros(nb_iou_thrs)
    prec_out = np.zeros((nb_iou_thrs, nb_rec_thrs))
    score_out = np.zeros((nb_iou_thrs, nb_rec_thrs))
    for idx_thr, (tp, fp) in enumerate(zip(tp_sum, fp_sum)):
        nd = len(tp)
        rc = tp / npig
        pr = tp / (fp + tp + np.finfo(np.float64).eps)
        recall_out[idx_thr] = rc[-1] if nd else 0
        # remove zigzags (right-to-left running max) for AUC
        pr = np.maximum.accumulate(pr[::-1])[::-1]
        inds_rc = np.searchsorted(rc, rec_thresholds, side="left")
        prec_at = np.zeros(nb_rec_thrs)
        score_at = np.zeros(nb_rec_thrs)
        valid = inds_rc < nd
        prec_at[valid] = pr[inds_rc[valid]]
        score_at[valid] = det_scores_sorted[inds_rc[valid]]
        prec_out[idx_thr] = prec_at
        score_out[idx_thr] = score_at
    return recall_out, prec_out, score_out


def _greedy_match_single(
    iou: Array,  # (D, G) det-gt IoU
    det_valid: Array,  # (D,) bool
    gt_valid: Array,  # (G,) bool
    gt_ignore: Array,  # (G,) bool (area-ignored)
    thresholds: Array,  # (T,)
) -> Tuple[Array, Array]:
    """COCO greedy matching for one (image, class, area) cell, all thresholds.

    Replicates the reference loop (``map.py:378-451``) exactly:
      * detections visit gts in score order (the scan);
      * a det prefers the best-IoU *unmatched, non-ignored* gt with IoU >= thr,
        falling back to ignored gts only when no regular gt qualifies (the
        reference's sorted-gts + break rule);
      * IoU ties pick the later gt index (the reference's non-strict `<` compare).

    Returns (det_matches (T, D) bool, match_idx (T, D) int32, -1 = unmatched).
    """
    num_gt = iou.shape[1]
    gt_idx = jnp.arange(num_gt)

    def per_threshold(thr):
        thr_eff = jnp.minimum(thr, 1.0 - 1e-10)

        def step(gt_matched, inp):
            iou_row, dvalid = inp
            cand = gt_valid & (~gt_matched) & (iou_row >= thr_eff)
            regular = cand & (~gt_ignore)
            pool = jnp.where(jnp.any(regular), regular, cand)
            masked = jnp.where(pool, iou_row, -jnp.inf)
            best = jnp.max(masked)
            match = jnp.max(jnp.where(pool & (masked == best), gt_idx, -1))
            matched = (match >= 0) & dvalid
            gt_matched = gt_matched | (matched & (gt_idx == match))
            return gt_matched, (matched, jnp.where(matched, match, -1))

        _, (dm, mi) = lax.scan(step, jnp.zeros(num_gt, bool), (iou, det_valid))
        return dm, mi.astype(jnp.int32)

    return jax.vmap(per_threshold)(thresholds)


@partial(jax.jit, static_argnames=())
def _match_all_pairs(
    det_boxes: Array,  # (P, D, 4) score-sorted
    det_valid: Array,  # (P, D)
    gt_boxes: Array,  # (P, G, 4)
    gt_valid: Array,  # (P, G)
    thresholds: Array,  # (T,)
    area_ranges: Array,  # (A, 2)
) -> Array:
    """One fused device call: IoU + greedy matching for every (image, class) pair
    and every area range.

    Returns ONE packed uint8 array ``(P, 2*A*T*D + A*G)``: det_matches
    ``(P, A, T, D)``, det_ignore ``(P, A, T, D)``, and gt_ignore ``(P, A, G)``
    flattened and concatenated along axis 1 — the host link is round-trip-bound,
    so the three outputs cross in one transfer (unpacked by the caller,
    ``_device_eval_imgs``).
    """
    ious = jax.vmap(box_iou)(det_boxes, gt_boxes)  # (P, D, G)
    ious = jnp.where(det_valid[:, :, None] & gt_valid[:, None, :], ious, 0.0)

    gt_areas = jax.vmap(box_area)(gt_boxes)  # (P, G)
    det_areas = jax.vmap(box_area)(det_boxes)  # (P, D)
    lo, hi = area_ranges[:, 0], area_ranges[:, 1]
    gt_ign = (gt_areas[:, None, :] < lo[None, :, None]) | (gt_areas[:, None, :] > hi[None, :, None])
    det_area_out = (det_areas[:, None, :] < lo[None, :, None]) | (det_areas[:, None, :] > hi[None, :, None])

    def per_pair(iou, dvalid, gvalid, g_ign_areas):
        def per_area(g_ign):
            return _greedy_match_single(iou, dvalid, gvalid, g_ign, thresholds)

        return jax.vmap(per_area)(g_ign_areas)  # (A, T, D) x2

    dm, mi = jax.vmap(per_pair)(ious, det_valid, gt_valid, gt_ign)  # (P, A, T, D)
    # det_ignore: matched an area-ignored gt, or unmatched and outside the range
    num_t = mi.shape[2]
    gt_ign_b = jnp.broadcast_to(gt_ign[:, :, None, :], gt_ign.shape[:2] + (num_t, gt_ign.shape[2]))
    matched_gt_ign = jnp.take_along_axis(gt_ign_b, jnp.clip(mi, 0, None), axis=3)
    det_ignore = jnp.where(dm, matched_gt_ign, det_area_out[:, :, None, :])
    gt_ign_valid = gt_ign & gt_valid[:, None, :]
    # pack the three boolean outputs into ONE (P, x) uint8 buffer: the host
    # link is round-trip-latency bound (axon tunnel), so one transfer instead
    # of three is a direct ~2x win on small evals
    packed = jnp.concatenate(
        [
            dm.astype(jnp.uint8).reshape(dm.shape[0], -1),
            det_ignore.astype(jnp.uint8).reshape(det_ignore.shape[0], -1),
            gt_ign_valid.astype(jnp.uint8).reshape(gt_ign_valid.shape[0], -1),
        ],
        axis=1,
    )
    return packed


class MAP(Metric):
    """COCO mean average precision/recall for object detection."""

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        matching: str = "device",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        if matching not in ("device", "host"):
            raise ValueError("Expected argument `matching` to be 'device' or 'host'")
        self.matching = matching
        self.box_format = box_format
        self.iou_thresholds = list(iou_thresholds) if iou_thresholds is not None else list(
            np.round(np.arange(0.5, 1.0, 0.05), 2)
        )
        self.rec_thresholds = list(rec_thresholds) if rec_thresholds is not None else list(
            np.round(np.linspace(0.0, 1.00, int(np.round((1.00 - 0.0) / 0.01)) + 1), 2)
        )
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        self.bbox_area_ranges = OrderedDict(
            all=(0.0, 1e10),
            small=(0.0, 32.0 ** 2),
            medium=(32.0 ** 2, 96.0 ** 2),
            large=(96.0 ** 2, 1e10),
        )
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics

        self.add_state("detection_boxes", default=[], dist_reduce_fx=None)
        self.add_state("detection_scores", default=[], dist_reduce_fx=None)
        self.add_state("detection_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_boxes", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)

    def update(self, preds: List[Dict[str, Array]], target: List[Dict[str, Array]]) -> None:
        """Add one batch of per-image detection/groundtruth dicts."""
        _input_validator(preds, target)
        for item in preds:
            self.detection_boxes.append(_box_convert_np(item["boxes"], self.box_format))
            self.detection_labels.append(np.ravel(np.asarray(item["labels"])))
            self.detection_scores.append(np.ravel(np.asarray(item["scores"])))
        for item in target:
            self.groundtruth_boxes.append(_box_convert_np(item["boxes"], self.box_format))
            self.groundtruth_labels.append(np.ravel(np.asarray(item["labels"])))

    # ----------------------------------------------- ragged serving (ISSUE 17)
    #
    # An image id IS a group key: detection rows (boxes) and groundtruth rows
    # share one per-image capacity buffer, discriminated by an ``is_gt`` flag
    # column. The aggregate read rebuilds the five eager list states per image
    # (in image-id order) and runs the unmodified eager ``compute`` — the
    # COCO matching/accumulation code never learns about serving. Note the
    # semantic shift the group key buys: eager ``update`` identifies images
    # POSITIONALLY (every call appends new images), while ragged ingestion
    # accumulates rows UNDER an explicit image id across calls.

    # per-image row budget (dets + gts share it); override the attribute or
    # pass capacity= to RaggedEngine for denser scenes
    grouped_capacity: int = 128

    def grouped_update_spec(self) -> Optional[GroupedUpdateSpec]:
        return GroupedUpdateSpec(
            fields=(
                GroupedField("box", (4,), jnp.float32),
                GroupedField("score", (), jnp.float32),
                GroupedField("label", (), jnp.int32),
                GroupedField("is_gt", (), jnp.int32),
            ),
            capacity=int(self.grouped_capacity),
        )

    def grouped_encode(
        self,
        preds: List[Dict[str, Array]],
        target: List[Dict[str, Array]],
        image_ids: Sequence[int],
    ) -> Tuple[Any, ...]:
        """Flatten one eager ``update`` call to per-row arrays keyed by image
        id: each image contributes its detection rows (xyxy box, score, label,
        is_gt=0) then its groundtruth rows (xyxy box, score 0, label, is_gt=1),
        validated exactly like ``update`` (``_input_validator`` + the same
        ``_box_convert_np`` coercion)."""
        _input_validator(preds, target)
        if len(image_ids) != len(preds):
            raise ValueError(
                "Expected `image_ids` to list one group key per image "
                f"(got {len(image_ids)} ids for {len(preds)} images)"
            )
        gids: List[np.ndarray] = []
        boxes: List[np.ndarray] = []
        scores: List[np.ndarray] = []
        labels: List[np.ndarray] = []
        is_gt: List[np.ndarray] = []
        for gid, p, t in zip(image_ids, preds, target):
            db = _box_convert_np(p["boxes"], self.box_format)
            gb = _box_convert_np(t["boxes"], self.box_format)
            nd, ng = db.shape[0], gb.shape[0]
            gids.append(np.full(nd + ng, int(gid), np.int32))
            boxes.append(db)
            boxes.append(gb)
            scores.append(np.ravel(np.asarray(p["scores"])).astype(np.float32))
            scores.append(np.zeros(ng, np.float32))
            labels.append(np.ravel(np.asarray(p["labels"])).astype(np.int32))
            labels.append(np.ravel(np.asarray(t["labels"])).astype(np.int32))
            is_gt.append(np.zeros(nd, np.int32))
            is_gt.append(np.ones(ng, np.int32))
        return (
            np.concatenate(gids) if gids else np.zeros(0, np.int32),
            np.concatenate(boxes) if boxes else np.zeros((0, 4), np.float32),
            np.concatenate(scores) if scores else np.zeros(0, np.float32),
            np.concatenate(labels) if labels else np.zeros(0, np.int32),
            np.concatenate(is_gt) if is_gt else np.zeros(0, np.int32),
        )

    def grouped_group_value(
        self, fields: Dict[str, Array], count: Array, capacity: int
    ) -> Dict[str, Array]:
        """Traced per-image occupancy read (``result(image_id)``): detection
        and groundtruth row counts in this image's buffer. The COCO value
        itself is corpus-level (class axes, global score ranking), so the
        per-group read reports the ingested shape, not a per-image AP."""
        count = jnp.asarray(count, jnp.int32)
        valid = jnp.arange(capacity) < jnp.minimum(count, capacity)
        gt = jnp.asarray(fields["is_gt"], jnp.int32) == 1
        return {
            "detections": jnp.sum((valid & ~gt).astype(jnp.int32)),
            "groundtruths": jnp.sum((valid & gt).astype(jnp.int32)),
        }

    def grouped_finalize(
        self,
        counts: np.ndarray,
        fields: Dict[str, np.ndarray],
        group_ids: np.ndarray,
    ) -> Dict[str, Any]:
        """Rebuild the five eager list states from reconstructed per-image
        rows, one entry per non-empty image in image-id order (rows keep
        submission order per image; ``is_gt`` splits the shared buffer).
        Images with no rows contribute nothing — exactly the eager no-op an
        empty (no dets, no gts) image is."""
        counts = np.asarray(counts)
        state: Dict[str, List[np.ndarray]] = {
            "detection_boxes": [],
            "detection_scores": [],
            "detection_labels": [],
            "groundtruth_boxes": [],
            "groundtruth_labels": [],
        }
        for gid in np.asarray(group_ids):
            c = int(counts[gid])
            if c == 0:
                continue
            gt = np.asarray(fields["is_gt"][gid][:c]) == 1
            box = np.asarray(fields["box"][gid][:c], np.float32)
            state["detection_boxes"].append(box[~gt])
            state["detection_scores"].append(
                np.asarray(fields["score"][gid][:c], np.float32)[~gt]
            )
            state["detection_labels"].append(
                np.asarray(fields["label"][gid][:c], np.int32)[~gt]
            )
            state["groundtruth_boxes"].append(box[gt])
            state["groundtruth_labels"].append(
                np.asarray(fields["label"][gid][:c], np.int32)[gt]
            )
        return state

    # -------------------------------------- corpus device aggregate (ISSUE 18)
    #
    # COCO's aggregate is CORPUS-level (global score ranking, class axes), so
    # the ragged engine's per-group fold does not apply. Instead the metric
    # plans the device pass off host-cheap vectors (counts + the label
    # buffer), ONE compiled program computes greedy matches for every
    # (image, class, area, threshold) cell straight from the stacked
    # ``(G, capacity)`` buffers, and one transfer ships the match bundle; the
    # host keeps only the O(total detections) PR interpolation — the same
    # split ``matching="device"`` already uses for eager states, minus the
    # per-image host packing loop.

    def grouped_aggregate_spec(self) -> Optional[GroupedAggregateSpec]:
        if self.matching != "device":
            return None  # host matcher = the parity oracle; replay eagerly
        return GroupedAggregateSpec(kind="corpus")

    def grouped_corpus_scan_fields(self) -> Tuple[str, ...]:
        """Buffers the host plan needs: the class universe comes from the
        label column (dets and gts both contribute, as ``_get_classes``)."""
        return ("label",)

    def grouped_corpus_plan(
        self, counts: np.ndarray, scan: Dict[str, np.ndarray]
    ) -> Optional[Dict[str, Any]]:
        """Host-side plan for the device pass: the distinct-label class list
        (padded to a bucket of 4 so nearby corpora share one compiled
        program) and a device-memory budget check. ``None`` declines —
        empty corpus, or match-bundle footprint past ~2^26 elements — and
        the engine reroutes to the host oracle."""
        counts = np.asarray(counts)
        label = np.asarray(scan["label"])
        num_groups, cap = label.shape
        valid = np.arange(cap)[None, :] < np.minimum(counts, cap)[:, None]
        labels = label[valid]
        if labels.size == 0:
            return None
        classes = np.unique(labels).astype(np.int32)  # unique() sorts
        c_pad = _bucket(int(classes.size), 4)
        nb_areas = len(self.bbox_area_ranges)
        nb_thrs = len(self.iou_thresholds)
        footprint = max(
            num_groups * c_pad * nb_areas * nb_thrs * cap,  # match bundle
            num_groups * cap * cap,  # per-image IoU block
        )
        if footprint > (1 << 26):
            return None
        classes_padded = np.zeros(c_pad, np.int32)
        classes_padded[: classes.size] = classes
        return {
            "classes_padded": classes_padded,
            "n_classes": int(classes.size),
            "c_pad": c_pad,
        }

    def grouped_corpus_audit_classes(self) -> int:
        """Class bucket the analysis audit traces the corpus program at."""
        return 4

    def grouped_corpus_device(
        self,
        counts: Array,
        fields: Dict[str, Array],
        classes: Array,
        cls_valid: Array,
        capacity: int,
    ) -> Dict[str, Array]:
        """Traced corpus match bundle from the stacked ragged buffers.

        Per image: one stable descending-score sort of the det rows (gt and
        pad rows sink) and one ``(capacity, capacity)`` IoU block against the
        ORIGINAL-order rows; per class: validity masks + in-class ranks over
        the shared sort — filter-after-stable-sort gives exactly
        ``_img_class_arrays``'s sort-after-filter order — then
        ``_greedy_match_single`` vmapped over (class, image, area), the same
        matcher ``_match_all_pairs`` runs. Everything the host finish needs
        crosses in one transfer:

        * ``scores`` ``(G*cap,)`` — sorted det scores, image-major;
        * ``det_valid`` ``(C, G*cap)`` / ``rank`` ``(C, G*cap)`` — class
          membership and 1-based in-class rank (the ``max_det`` slice is a
          host-side ``rank <= m`` mask);
        * ``dm`` / ``dign`` ``(C, A, T, G*cap)`` — match / ignore flags;
        * ``npig`` ``(C, A)`` — non-area-ignored gt totals;
        * ``n_gt`` / ``n_det`` ``(C,)`` — the eval-exists guard.
        """
        cap = int(capacity)
        counts = jnp.asarray(counts, jnp.int32)
        box = jnp.asarray(fields["box"], jnp.float32)  # (G, cap, 4)
        score = jnp.asarray(fields["score"], jnp.float32)  # (G, cap)
        label = jnp.asarray(fields["label"], jnp.int32)  # (G, cap)
        is_gt = jnp.asarray(fields["is_gt"], jnp.int32) == 1  # (G, cap)
        valid = jnp.arange(cap)[None, :] < jnp.minimum(counts, cap)[:, None]
        det_row = valid & ~is_gt
        gt_row = valid & is_gt

        order = jnp.argsort(jnp.where(det_row, -score, jnp.inf), axis=1, stable=True)
        s_score = jnp.take_along_axis(score, order, axis=1)
        s_label = jnp.take_along_axis(label, order, axis=1)
        s_det = jnp.take_along_axis(det_row, order, axis=1)
        s_box = jnp.take_along_axis(box, order[..., None], axis=1)

        ious = jax.vmap(box_iou)(s_box, box)  # (G, cap det, cap gt)

        thresholds = jnp.asarray(self.iou_thresholds, jnp.float32)  # (T,)
        area_ranges = jnp.asarray(
            [list(r) for r in self.bbox_area_ranges.values()], jnp.float32
        )  # (A, 2)
        lo, hi = area_ranges[:, 0], area_ranges[:, 1]
        gt_areas = jax.vmap(box_area)(box)  # (G, cap) original order
        det_areas = jax.vmap(box_area)(s_box)  # (G, cap) sorted order
        gt_area_out = (gt_areas[:, None, :] < lo[None, :, None]) | (
            gt_areas[:, None, :] > hi[None, :, None]
        )  # (G, A, cap)
        det_area_out = (det_areas[:, None, :] < lo[None, :, None]) | (
            det_areas[:, None, :] > hi[None, :, None]
        )  # (G, A, cap)
        max_det = int(self.max_detection_thresholds[-1])

        def per_class(cls: Array, cvalid: Array):
            det_c = s_det & (s_label == cls) & cvalid  # (G, cap)
            rank = jnp.cumsum(det_c.astype(jnp.int32), axis=1)  # 1-based where det_c
            active = det_c & (rank <= max_det)
            gt_c = gt_row & (label == cls) & cvalid  # (G, cap) original order

            def per_image(iou, dvalid, gvalid, g_area_out):
                def per_area(g_ign):
                    return _greedy_match_single(iou, dvalid, gvalid, g_ign, thresholds)

                return jax.vmap(per_area)(g_area_out)  # (A, T, cap) x2

            dm, mi = jax.vmap(per_image)(ious, active, gt_c, gt_area_out)  # (G, A, T, cap)
            num_t = thresholds.shape[0]
            gt_ign_b = jnp.broadcast_to(
                gt_area_out[:, :, None, :],
                gt_area_out.shape[:2] + (num_t, gt_area_out.shape[2]),
            )
            matched_gt_ign = jnp.take_along_axis(gt_ign_b, jnp.clip(mi, 0, None), axis=3)
            dign = jnp.where(dm, matched_gt_ign, det_area_out[:, :, None, :])
            npig = jnp.sum(
                (gt_c[:, None, :] & ~gt_area_out).astype(jnp.int32), axis=(0, 2)
            )  # (A,)
            return (
                active,
                rank,
                jnp.transpose(dm, (1, 2, 0, 3)),  # (A, T, G, cap): image-major tail
                jnp.transpose(dign, (1, 2, 0, 3)),
                npig,
                jnp.sum(gt_c.astype(jnp.int32)),
                jnp.sum(det_c.astype(jnp.int32)),
            )

        active, rank, dm, dign, npig, n_gt, n_det = jax.vmap(per_class)(
            jnp.asarray(classes, jnp.int32), jnp.asarray(cls_valid, bool)
        )
        c_pad = active.shape[0]
        return {
            "scores": s_score.reshape(-1),
            "det_valid": active.reshape(c_pad, -1).astype(jnp.uint8),
            "rank": rank.reshape(c_pad, -1),
            "dm": dm.reshape(dm.shape[:3] + (-1,)).astype(jnp.uint8),
            "dign": dign.reshape(dign.shape[:3] + (-1,)).astype(jnp.uint8),
            "npig": npig,
            "n_gt": n_gt,
            "n_det": n_det,
        }

    def grouped_corpus_finish(
        self, bundle: Dict[str, np.ndarray], plan: Dict[str, Any]
    ) -> dict:
        """Host finish of the device bundle: per (class, area, max_det) the
        ``rank <= m`` mask selects the eval's detections and the SAME
        ``_pr_accumulate`` / ``_results_from_tensors`` / ``_finish_compute``
        helpers the eager path runs produce the final ``COCOMetricResults``
        — the accumulation code cannot drift between paths."""
        nb_classes = int(plan["n_classes"])
        scores = np.asarray(bundle["scores"])
        det_valid = np.asarray(bundle["det_valid"]).astype(bool)
        rank = np.asarray(bundle["rank"])
        dm = np.asarray(bundle["dm"]).astype(bool)
        dign = np.asarray(bundle["dign"]).astype(bool)
        npig = np.asarray(bundle["npig"])
        n_gt = np.asarray(bundle["n_gt"])
        n_det = np.asarray(bundle["n_det"])
        nb_iou_thrs = len(self.iou_thresholds)
        nb_rec_thrs = len(self.rec_thresholds)
        nb_bbox_areas = len(self.bbox_area_ranges)
        nb_max_det_thrs = len(self.max_detection_thresholds)
        precision = -np.ones((nb_iou_thrs, nb_rec_thrs, nb_classes, nb_bbox_areas, nb_max_det_thrs))
        recall = -np.ones((nb_iou_thrs, nb_classes, nb_bbox_areas, nb_max_det_thrs))
        score_tensor = -np.ones((nb_iou_thrs, nb_rec_thrs, nb_classes, nb_bbox_areas, nb_max_det_thrs))
        rec_thresholds = np.asarray(self.rec_thresholds)

        for idx_cls in range(nb_classes):
            if n_gt[idx_cls] == 0 and n_det[idx_cls] == 0:
                continue  # no (image, class) eval exists — cells stay -1
            for idx_area in range(nb_bbox_areas):
                if int(npig[idx_cls, idx_area]) == 0:
                    continue
                for idx_mdet, max_det in enumerate(self.max_detection_thresholds):
                    sel = det_valid[idx_cls] & (rank[idx_cls] <= max_det)
                    rec_t, prec_t, score_t = _pr_accumulate(
                        scores[sel],
                        dm[idx_cls, idx_area][:, sel],
                        dign[idx_cls, idx_area][:, sel],
                        int(npig[idx_cls, idx_area]),
                        rec_thresholds,
                    )
                    recall[:, idx_cls, idx_area, idx_mdet] = rec_t
                    precision[:, :, idx_cls, idx_area, idx_mdet] = prec_t
                    score_tensor[:, :, idx_cls, idx_area, idx_mdet] = score_t

        overall, map_metrics, mar_metrics = self._results_from_tensors(
            precision, recall, score_tensor, nb_classes
        )
        # the eager path returns through _wrap_compute's scalar squeeze —
        # apply the same normalization so both reads have identical leaves
        from metrics_tpu.metric import _squeeze_if_scalar

        return _squeeze_if_scalar(
            self._finish_compute(overall, map_metrics, mar_metrics, nb_classes)
        )

    # ------------------------------------------------------------------ internals

    def _get_classes(self) -> List[int]:
        if len(self.detection_labels) > 0 or len(self.groundtruth_labels) > 0:
            all_labels = np.concatenate(
                [np.asarray(x) for x in (self.detection_labels + self.groundtruth_labels)]
            )
            return sorted(set(int(x) for x in all_labels))
        return []

    def _img_class_arrays(self, idx: int, class_id: int, max_det: int):
        """Per-image per-class (gt, det, scores) sorted the COCO way (numpy)."""
        gt = np.asarray(self.groundtruth_boxes[idx])
        det = np.asarray(self.detection_boxes[idx])
        gt_mask = np.asarray(self.groundtruth_labels[idx]) == class_id
        det_mask = np.asarray(self.detection_labels[idx]) == class_id
        gt = gt[gt_mask]
        det = det[det_mask]
        scores = np.asarray(self.detection_scores[idx])[det_mask]
        dtind = np.argsort(-scores, kind="stable")[:max_det]
        return gt, det[dtind], scores[dtind]

    def _evaluate_image(
        self, idx: int, class_id: int, area_range: Tuple[float, float], max_det: int, ious: Dict
    ) -> Optional[Dict]:
        """Greedy matching for one (image, class). Parity: reference ``:378-451``."""
        gt, det, scores_sorted = self._img_class_arrays(idx, class_id, max_det)
        if len(gt) == 0 and len(det) == 0:
            return None

        gt2 = np.asarray(gt).reshape(-1, 4)
        areas = (gt2[:, 2] - gt2[:, 0]) * (gt2[:, 3] - gt2[:, 1]) if len(gt) else np.zeros(0)
        ignore_area = (areas < area_range[0]) | (areas > area_range[1])
        gtind = np.argsort(ignore_area.astype(np.uint8), kind="stable")  # ignored gts last
        gt = gt[gtind]
        gt_ignore = ignore_area[gtind]

        iou_mat = ious[(idx, class_id)]
        iou_mat = iou_mat[:, gtind] if iou_mat.size else iou_mat

        nb_iou_thrs = len(self.iou_thresholds)
        nb_gt, nb_det = len(gt), len(det)
        gt_matches = np.zeros((nb_iou_thrs, nb_gt), dtype=bool)
        det_matches = np.zeros((nb_iou_thrs, nb_det), dtype=bool)
        det_ignore = np.zeros((nb_iou_thrs, nb_det), dtype=bool)

        if iou_mat.size > 0:
            for idx_iou, thr in enumerate(self.iou_thresholds):
                for idx_det in range(nb_det):
                    best_iou = min(thr, 1 - 1e-10)
                    match_id = -1
                    for idx_gt in range(nb_gt):
                        if gt_matches[idx_iou, idx_gt]:
                            continue
                        # once matched to a regular gt, never trade down to an ignored one
                        if match_id > -1 and not gt_ignore[match_id] and gt_ignore[idx_gt]:
                            break
                        if iou_mat[idx_det, idx_gt] < best_iou:
                            continue
                        best_iou = iou_mat[idx_det, idx_gt]
                        match_id = idx_gt
                    if match_id != -1:
                        det_ignore[idx_iou, idx_det] = gt_ignore[match_id]
                        det_matches[idx_iou, idx_det] = True
                        gt_matches[idx_iou, match_id] = True

        # unmatched detections outside the area range are ignored
        det2 = np.asarray(det).reshape(-1, 4)
        det_areas = (det2[:, 2] - det2[:, 0]) * (det2[:, 3] - det2[:, 1]) if nb_det else np.zeros(0)
        det_ignore_area = (det_areas < area_range[0]) | (det_areas > area_range[1])
        det_ignore = det_ignore | (~det_matches & det_ignore_area[None, :])

        return {
            "dtMatches": det_matches,
            "gtMatches": gt_matches,
            "dtScores": scores_sorted,
            "gtIgnore": gt_ignore,
            "dtIgnore": det_ignore,
        }

    def _summarize(
        self,
        results: Dict,
        avg_prec: bool = True,
        iou_threshold: Optional[float] = None,
        area_range: str = "all",
        max_dets: int = 100,
    ) -> Array:
        area_idx = list(self.bbox_area_ranges.keys()).index(area_range)
        mdet_idx = self.max_detection_thresholds.index(max_dets)
        if avg_prec:
            prec = results["precision"]
            if iou_threshold is not None:
                thr = self.iou_thresholds.index(iou_threshold)
                prec = prec[thr:thr + 1]
            prec = prec[:, :, :, area_idx, mdet_idx]
        else:
            prec = results["recall"]
            if iou_threshold is not None:
                thr = self.iou_thresholds.index(iou_threshold)
                prec = prec[thr:thr + 1]
            prec = prec[:, :, area_idx, mdet_idx]
        valid = prec[prec > -1]
        return jnp.asarray(-1.0) if valid.size == 0 else jnp.asarray(float(np.mean(valid)))

    def _device_eval_imgs(self, class_ids: List[int], max_detections: int) -> List[Optional[Dict]]:
        """All (image, class) matching in one jitted call + ONE host transfer.

        Produces the same ``[class, area, image]``-ordered eval-dict list as the
        host path (``_evaluate_image``), so the accumulation is shared.
        """
        img_ids = list(range(len(self.groundtruth_boxes)))
        area_ranges = list(self.bbox_area_ranges.values())
        nb_areas = len(area_ranges)

        # host: slice/sort the ragged states into padded (P, D/G) batches.
        # Only NON-EMPTY (class, image) pairs are packed — at COCO scale most
        # images contain a handful of the C classes, so packing all C*N pairs
        # would blow device memory up by ~C x for no output change.
        pairs: List[Tuple[int, int]] = [(c, i) for c in range(len(class_ids)) for i in img_ids]
        per_pair = [
            self._img_class_arrays(i, class_ids[c], max_detections) for c, i in pairs
        ]
        nd_all = np.asarray([len(det) for _, det, _ in per_pair])
        ng_all = np.asarray([len(gt) for gt, _, _ in per_pair])
        keep = np.flatnonzero((nd_all > 0) | (ng_all > 0))
        # row[p] = packed-batch row of pair p, -1 for empty pairs
        row = -np.ones(len(pairs), np.int64)
        row[keep] = np.arange(len(keep))
        nd, ng = nd_all[keep], ng_all[keep]
        # bucket padded dims: growing datasets / periodic compute() calls then
        # reuse the compiled matcher instead of paying an XLA recompile for
        # every new max-count (padding is free semantically — the valid masks
        # and the row map already ignore it)
        dim_d = _bucket(max(1, int(nd.max(initial=0))), 8)
        dim_g = _bucket(max(1, int(ng.max(initial=0))), 8)
        n_rows = _bucket(max(1, len(keep)), 64)

        det_boxes = np.zeros((n_rows, dim_d, 4), np.float32)
        det_scores = np.zeros((n_rows, dim_d), np.float32)
        gt_boxes = np.zeros((n_rows, dim_g, 4), np.float32)
        for r, p in enumerate(keep):
            gt, det, scores = per_pair[p]
            det_boxes[r, : len(det)] = det.reshape(-1, 4)
            det_scores[r, : len(det)] = scores
            gt_boxes[r, : len(gt)] = gt.reshape(-1, 4)
        nd_padded = np.zeros(n_rows, nd.dtype)
        nd_padded[: len(keep)] = nd
        ng_padded = np.zeros(n_rows, ng.dtype)
        ng_padded[: len(keep)] = ng
        det_valid = np.arange(dim_d)[None, :] < nd_padded[:, None]
        gt_valid = np.arange(dim_g)[None, :] < ng_padded[:, None]

        packed = _match_all_pairs(
            jnp.asarray(det_boxes),
            jnp.asarray(det_valid),
            jnp.asarray(gt_boxes),
            jnp.asarray(gt_valid),
            jnp.asarray(self.iou_thresholds, dtype=jnp.float32),
            jnp.asarray([list(r) for r in area_ranges], dtype=jnp.float32),
        )
        # the single device -> host transfer (pad rows sliced off on device);
        # unpack the uint8 bundle
        packed = np.asarray(packed[: len(keep)])
        num_t = len(self.iou_thresholds)
        sz_d = nb_areas * num_t * dim_d
        dm = packed[:, :sz_d].reshape(-1, nb_areas, num_t, dim_d).astype(bool)
        det_ignore = packed[:, sz_d:2 * sz_d].reshape(-1, nb_areas, num_t, dim_d).astype(bool)
        gt_ign = packed[:, 2 * sz_d:].reshape(-1, nb_areas, dim_g).astype(bool)

        eval_imgs: List[Optional[Dict]] = []
        nb_imgs = len(img_ids)
        for idx_cls in range(len(class_ids)):
            for idx_area in range(nb_areas):
                for idx_img in range(nb_imgs):
                    r = int(row[idx_cls * nb_imgs + idx_img])
                    if r < 0:  # empty pair: no dets, no gt
                        eval_imgs.append(None)
                        continue
                    n_det, n_gt = int(nd[r]), int(ng[r])
                    eval_imgs.append(
                        {
                            "dtMatches": dm[r, idx_area, :, :n_det],
                            "dtScores": det_scores[r, :n_det],
                            "gtIgnore": gt_ign[r, idx_area, :n_gt],
                            "dtIgnore": det_ignore[r, idx_area, :, :n_det],
                        }
                    )
        return eval_imgs

    def _calculate(self, class_ids: List[int]) -> Tuple[Dict, MAPMetricResults, MARMetricResults]:
        img_ids = list(range(len(self.groundtruth_boxes)))
        max_detections = self.max_detection_thresholds[-1]
        area_ranges = list(self.bbox_area_ranges.values())

        if self.matching == "device" and class_ids:
            eval_imgs = self._device_eval_imgs(class_ids, max_detections)
        else:
            # host oracle path: per-image IoU + python greedy matching
            ious = {}
            for idx in img_ids:
                for class_id in class_ids:
                    gt, det, _ = self._img_class_arrays(idx, class_id, max_detections)
                    if len(gt) and len(det):
                        ious[(idx, class_id)] = np.asarray(
                            box_iou(jnp.asarray(det.reshape(-1, 4)), jnp.asarray(gt.reshape(-1, 4)))
                        )
                    else:
                        ious[(idx, class_id)] = np.zeros((len(det), len(gt)))

            eval_imgs = [
                self._evaluate_image(img_id, class_id, area, max_detections, ious)
                for class_id in class_ids
                for area in area_ranges
                for img_id in img_ids
            ]

        nb_iou_thrs = len(self.iou_thresholds)
        nb_rec_thrs = len(self.rec_thresholds)
        nb_classes = len(class_ids)
        nb_bbox_areas = len(self.bbox_area_ranges)
        nb_max_det_thrs = len(self.max_detection_thresholds)
        nb_imgs = len(img_ids)
        precision = -np.ones((nb_iou_thrs, nb_rec_thrs, nb_classes, nb_bbox_areas, nb_max_det_thrs))
        recall = -np.ones((nb_iou_thrs, nb_classes, nb_bbox_areas, nb_max_det_thrs))
        scores = -np.ones((nb_iou_thrs, nb_rec_thrs, nb_classes, nb_bbox_areas, nb_max_det_thrs))
        rec_thresholds = np.asarray(self.rec_thresholds)

        for idx_cls in range(nb_classes):
            for idx_area in range(nb_bbox_areas):
                for idx_mdet, max_det in enumerate(self.max_detection_thresholds):
                    base = idx_cls * nb_bbox_areas * nb_imgs + idx_area * nb_imgs
                    evals = [eval_imgs[base + i] for i in range(nb_imgs)]
                    evals = [e for e in evals if e is not None]
                    if not evals:
                        continue
                    det_scores = np.concatenate([e["dtScores"][:max_det] for e in evals])
                    det_matches = np.concatenate([e["dtMatches"][:, :max_det] for e in evals], axis=1)
                    det_ignore = np.concatenate([e["dtIgnore"][:, :max_det] for e in evals], axis=1)
                    gt_ignore = np.concatenate([e["gtIgnore"] for e in evals])
                    npig = int(np.count_nonzero(~gt_ignore))
                    if npig == 0:
                        continue
                    rec_t, prec_t, score_t = _pr_accumulate(
                        det_scores, det_matches, det_ignore, npig, rec_thresholds
                    )
                    recall[:, idx_cls, idx_area, idx_mdet] = rec_t
                    precision[:, :, idx_cls, idx_area, idx_mdet] = prec_t
                    scores[:, :, idx_cls, idx_area, idx_mdet] = score_t

        return self._results_from_tensors(precision, recall, scores, nb_classes)

    def _results_from_tensors(
        self,
        precision: np.ndarray,
        recall: np.ndarray,
        scores: np.ndarray,
        nb_classes: int,
    ) -> Tuple[Dict, MAPMetricResults, MARMetricResults]:
        """Summarize the accumulated PR tensors — shared tail of
        ``_calculate`` and ``grouped_corpus_finish``."""
        results = {
            "dimensions": [
                len(self.iou_thresholds), len(self.rec_thresholds), nb_classes,
                len(self.bbox_area_ranges), len(self.max_detection_thresholds),
            ],
            "precision": precision,
            "recall": recall,
            "scores": scores,
        }

        map_metrics = MAPMetricResults()
        map_metrics.map = self._summarize(results, True)
        last_max_det = self.max_detection_thresholds[-1]
        map_metrics.map_50 = self._summarize(results, True, iou_threshold=0.5, max_dets=last_max_det)
        map_metrics.map_75 = self._summarize(results, True, iou_threshold=0.75, max_dets=last_max_det)
        map_metrics.map_small = self._summarize(results, True, area_range="small", max_dets=last_max_det)
        map_metrics.map_medium = self._summarize(results, True, area_range="medium", max_dets=last_max_det)
        map_metrics.map_large = self._summarize(results, True, area_range="large", max_dets=last_max_det)

        mar_metrics = MARMetricResults()
        for max_det in self.max_detection_thresholds:
            mar_metrics[f"mar_{max_det}"] = self._summarize(results, False, max_dets=max_det)
        mar_metrics.mar_small = self._summarize(results, False, area_range="small", max_dets=last_max_det)
        mar_metrics.mar_medium = self._summarize(results, False, area_range="medium", max_dets=last_max_det)
        mar_metrics.mar_large = self._summarize(results, False, area_range="large", max_dets=last_max_det)

        return results, map_metrics, mar_metrics

    def compute(self) -> dict:
        """Compute the COCO metric dict (map, map_50, ..., per-class options)."""
        classes = self._get_classes()
        overall, map_metrics, mar_metrics = self._calculate(classes)
        return self._finish_compute(overall, map_metrics, mar_metrics, len(classes))

    def _finish_compute(
        self,
        overall: Dict,
        map_metrics: MAPMetricResults,
        mar_metrics: MARMetricResults,
        nb_classes: int,
    ) -> dict:
        """Assemble the final ``COCOMetricResults`` (incl. per-class slices) —
        shared tail of ``compute`` and ``grouped_corpus_finish``."""
        map_per_class_values = jnp.asarray([-1.0])
        mar_max_dets_per_class_values = jnp.asarray([-1.0])
        if self.class_metrics:
            # Per-class summaries come from slicing the class axis of the
            # ALREADY-computed precision/recall tensors — each class's
            # matching and accumulation is independent, so this is exactly
            # equivalent to re-running _calculate([class_id]) per class
            # without repeating the matching C times.
            map_per_class_list = []
            mar_per_class_list = []
            last_max_det = self.max_detection_thresholds[-1]
            for idx_cls in range(nb_classes):
                cls_results = {
                    "precision": overall["precision"][:, :, idx_cls:idx_cls + 1],
                    "recall": overall["recall"][:, idx_cls:idx_cls + 1],
                }
                map_per_class_list.append(self._summarize(cls_results, True))
                mar_per_class_list.append(
                    self._summarize(cls_results, False, max_dets=last_max_det)
                )
            map_per_class_values = jnp.stack(map_per_class_list)
            mar_max_dets_per_class_values = jnp.stack(mar_per_class_list)

        metrics = COCOMetricResults()
        metrics.update(map_metrics)
        metrics.update(mar_metrics)
        metrics.map_per_class = map_per_class_values
        metrics[f"mar_{self.max_detection_thresholds[-1]}_per_class"] = mar_max_dets_per_class_values
        return metrics


MeanAveragePrecision = MAP
