"""BLEU score.

Parity: reference ``torchmetrics/functional/text/bleu.py`` (_count_ngram :25,
_bleu_score_update :48, _bleu_score_compute :104, bleu_score :148). N-gram counting
is host-side (strings); the accumulated numerator/denominator/length counters are
device sum-states.
"""
from collections import Counter
from typing import Callable, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _count_ngram(ngram_input_list: Sequence[str], n_gram: int) -> Counter:
    ngram_counter: Counter = Counter()
    for i in range(1, n_gram + 1):
        for j in range(len(ngram_input_list) - i + 1):
            ngram_counter[tuple(ngram_input_list[j:i + j])] += 1
    return ngram_counter


def _tokenize_fn(sentence: str) -> Sequence[str]:
    return sentence.split()


def _bleu_score_update(
    translate_corpus: Sequence[str],
    reference_corpus: Sequence[Sequence[str]],
    numerator: Array,
    denominator: Array,
    trans_len: Array,
    ref_len: Array,
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[Array, Array, Array, Array]:
    """Accumulate clipped n-gram matches. Returns (trans_len, ref_len, numerator,
    denominator) — the counters are returned (not mutated) for the functional style."""
    reference_corpus_ = [[tokenizer(line) if line else [] for line in reference] for reference in reference_corpus]
    translate_corpus_ = [tokenizer(line) if line else [] for line in translate_corpus]

    num_np = np.zeros(n_gram)
    den_np = np.zeros(n_gram)
    t_len = 0
    r_len = 0
    for translation, references in zip(translate_corpus_, reference_corpus_):
        t_len += len(translation)
        ref_len_list = [len(ref) for ref in references]
        ref_len_diff = [abs(len(translation) - x) for x in ref_len_list]
        r_len += ref_len_list[ref_len_diff.index(min(ref_len_diff))]
        translation_counter = _count_ngram(translation, n_gram)
        reference_counter: Counter = Counter()
        for ref in references:
            reference_counter |= _count_ngram(ref, n_gram)
        ngram_counter_clip = translation_counter & reference_counter
        for counter_clip in ngram_counter_clip:
            num_np[len(counter_clip) - 1] += ngram_counter_clip[counter_clip]
        for counter in translation_counter:
            den_np[len(counter) - 1] += translation_counter[counter]
    return (
        trans_len + t_len,
        ref_len + r_len,
        numerator + jnp.asarray(num_np, dtype=jnp.float32),
        denominator + jnp.asarray(den_np, dtype=jnp.float32),
    )


def _bleu_score_compute(
    trans_len: Array,
    ref_len: Array,
    numerator: Array,
    denominator: Array,
    n_gram: int = 4,
    smooth: bool = False,
) -> Array:
    if float(jnp.min(numerator)) == 0.0:
        return jnp.asarray(0.0)
    if smooth:
        precision_scores = (numerator + 1.0) / (denominator + 1.0)
        precision_scores = precision_scores.at[0].set(numerator[0] / denominator[0])
    else:
        precision_scores = numerator / denominator
    log_precision_scores = (1.0 / n_gram) * jnp.log(precision_scores)
    geometric_mean = jnp.exp(jnp.sum(log_precision_scores))
    brevity_penalty = jnp.where(trans_len > ref_len, 1.0, jnp.exp(1 - ref_len / trans_len))
    return brevity_penalty * geometric_mean


def bleu_score(
    translate_corpus: Union[str, Sequence[str]],
    reference_corpus: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
) -> Array:
    """Corpus BLEU with uniform n-gram weights and brevity penalty.

    Example:
        >>> from metrics_tpu.functional import bleu_score
        >>> score = bleu_score(['the cat sat on the mat'], [['a cat sat on the mat']])
        >>> print(f"{float(score):.4f}")
        0.7598
    """
    translate_corpus_ = [translate_corpus] if isinstance(translate_corpus, str) else translate_corpus
    reference_corpus_ = [
        [reference_text] if isinstance(reference_text, str) else reference_text
        for reference_text in reference_corpus
    ]
    if len(translate_corpus_) != len(reference_corpus_):
        raise ValueError(f"Corpus has different size {len(translate_corpus_)} != {len(reference_corpus_)}")

    numerator = jnp.zeros(n_gram)
    denominator = jnp.zeros(n_gram)
    trans_len = jnp.asarray(0.0)
    ref_len = jnp.asarray(0.0)
    trans_len, ref_len, numerator, denominator = _bleu_score_update(
        translate_corpus_, reference_corpus_, numerator, denominator, trans_len, ref_len, n_gram
    )
    return _bleu_score_compute(trans_len, ref_len, numerator, denominator, n_gram, smooth)
