"""ROUGE score (rouge1/rouge2/rougeL/rougeLsum).

Parity: reference ``torchmetrics/functional/text/rouge.py`` (380 LoC; the reference
wraps the ``rouge_score``/nltk packages — here the n-gram overlap and LCS math is
implemented natively so the metric works without optional deps; Porter stemming is
available when nltk is present, matching the reference's ``use_stemmer`` knob).
"""
import re
from collections import Counter
from typing import Dict, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utils.imports import _NLTK_AVAILABLE

Array = jax.Array

ALLOWED_ROUGE_KEYS = ("rouge1", "rouge2", "rouge3", "rouge4", "rouge5", "rouge6", "rouge7", "rouge8", "rouge9",
                      "rougeL", "rougeLsum")


def _tokenize(text: str, stemmer=None) -> List[str]:
    text = re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = re.split(r"\s+", text)
    if stemmer:
        tokens = [stemmer.stem(x) if len(x) > 3 else x for x in tokens]
    return [x for x in tokens if re.match(r"^[a-z0-9]+$", x)]


def _pr_f(matches: float, pred_len: int, target_len: int) -> Dict[str, float]:
    precision = matches / pred_len if pred_len > 0 else 0.0
    recall = matches / target_len if target_len > 0 else 0.0
    if precision + recall > 0:
        fmeasure = 2 * precision * recall / (precision + recall)
    else:
        fmeasure = 0.0
    return {"precision": precision, "recall": recall, "fmeasure": fmeasure}


def _rouge_n_score(pred: List[str], target: List[str], n_gram: int) -> Dict[str, float]:
    def _ngrams(tokens: List[str]) -> Counter:
        return Counter(tuple(tokens[i:i + n_gram]) for i in range(len(tokens) - n_gram + 1))

    pred_ngrams, target_ngrams = _ngrams(pred), _ngrams(target)
    pred_len = sum(pred_ngrams.values())
    target_len = sum(target_ngrams.values())
    hits = sum((pred_ngrams & target_ngrams).values())
    return _pr_f(hits, pred_len, target_len)


def _lcs(pred: List[str], target: List[str]) -> int:
    """Longest common subsequence length (two-row DP)."""
    if not pred or not target:
        return 0
    prev = [0] * (len(target) + 1)
    for p in pred:
        cur = [0] * (len(target) + 1)
        for j, t in enumerate(target, 1):
            cur[j] = prev[j - 1] + 1 if p == t else max(prev[j], cur[j - 1])
        prev = cur
    return prev[-1]


def _rouge_l_score(pred: List[str], target: List[str]) -> Dict[str, float]:
    lcs = _lcs(pred, target)
    return _pr_f(lcs, len(pred), len(target))


def _split_sentences(text: str) -> List[str]:
    # rougeLsum semantics (rouge_score default): sentences are newline-separated
    return [s for s in text.split("\n") if s]


def _rouge_lsum_score(pred: str, target: str, stemmer=None) -> Dict[str, float]:
    """Summary-level ROUGE-L: union-LCS per target sentence, hits clipped by token
    frequency in both summaries (rouge_score semantics)."""
    pred_sents = [_tokenize(s, stemmer) for s in _split_sentences(pred)]
    target_sents = [_tokenize(s, stemmer) for s in _split_sentences(target)]
    pred_len = sum(len(s) for s in pred_sents)
    target_len = sum(len(s) for s in target_sents)

    def _union_lcs_tokens(t_sent: List[str]) -> List[str]:
        union: set = set()
        for p_sent in pred_sents:
            n, m = len(p_sent), len(t_sent)
            dp = [[0] * (m + 1) for _ in range(n + 1)]
            for i in range(1, n + 1):
                for j in range(1, m + 1):
                    dp[i][j] = dp[i - 1][j - 1] + 1 if p_sent[i - 1] == t_sent[j - 1] else max(
                        dp[i - 1][j], dp[i][j - 1]
                    )
            i, j = n, m
            while i > 0 and j > 0:
                if p_sent[i - 1] == t_sent[j - 1]:
                    union.add(j - 1)
                    i, j = i - 1, j - 1
                elif dp[i - 1][j] >= dp[i][j - 1]:
                    i -= 1
                else:
                    j -= 1
        return [t_sent[j] for j in union]

    pred_counts = Counter(tok for s in pred_sents for tok in s)
    target_counts = Counter(tok for s in target_sents for tok in s)
    hits = 0
    for t_sent in target_sents:
        for tok in _union_lcs_tokens(t_sent):
            if pred_counts[tok] > 0 and target_counts[tok] > 0:
                hits += 1
                pred_counts[tok] -= 1
                target_counts[tok] -= 1
    return _pr_f(hits, pred_len, target_len)


def _rouge_score_update(
    preds: Sequence[str],
    targets: Sequence[str],
    rouge_keys_values: Sequence[Union[int, str]],
    accumulate: str = "best",
    stemmer=None,
) -> Dict[Union[int, str], List[Dict[str, Array]]]:
    results: Dict[Union[int, str], List[Dict[str, Array]]] = {k: [] for k in rouge_keys_values}
    for pred_raw, target_raw in zip(preds, targets):
        target_list = [target_raw] if isinstance(target_raw, str) else list(target_raw)
        pred_toks = _tokenize(pred_raw, stemmer)
        per_key_scores: Dict[Union[int, str], List[Dict[str, float]]] = {k: [] for k in rouge_keys_values}
        for tgt_raw in target_list:
            tgt_toks = _tokenize(tgt_raw, stemmer)
            for key in rouge_keys_values:
                if key == "L":
                    score = _rouge_l_score(pred_toks, tgt_toks)
                elif key == "Lsum":
                    score = _rouge_lsum_score(pred_raw, tgt_raw, stemmer)
                else:
                    score = _rouge_n_score(pred_toks, tgt_toks, int(key))
                per_key_scores[key].append(score)
        for key in rouge_keys_values:
            if accumulate == "best":
                best = max(per_key_scores[key], key=lambda s: s["fmeasure"])
            else:  # avg
                best = {
                    m: sum(s[m] for s in per_key_scores[key]) / len(per_key_scores[key])
                    for m in ("precision", "recall", "fmeasure")
                }
            results[key].append({m: jnp.asarray(v) for m, v in best.items()})
    return results


def _rouge_score_compute(sentence_results: Dict[str, List[Array]]) -> Dict[str, Array]:
    return {k: jnp.mean(jnp.stack(v)) for k, v in sentence_results.items() if v}


def rouge_score(
    preds: Union[str, Sequence[str]],
    targets: Union[str, Sequence[str], Sequence[Sequence[str]]],
    use_stemmer: bool = False,
    accumulate: str = "best",
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, Array]:
    """ROUGE-N / ROUGE-L / ROUGE-Lsum with precision/recall/fmeasure per key."""
    if use_stemmer and not _NLTK_AVAILABLE:
        raise ModuleNotFoundError("Stemming requires that `nltk` is installed.")
    stemmer = None
    if use_stemmer:
        import nltk

        stemmer = nltk.stem.porter.PorterStemmer()

    if isinstance(rouge_keys, str):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS:
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {ALLOWED_ROUGE_KEYS}")
    rouge_keys_values = [key[5:] if key.startswith("rouge") and not key[5:].isdigit() else key[5:] for key in rouge_keys]
    rouge_keys_values = [v if not v.isdigit() else int(v) for v in rouge_keys_values]

    preds_ = [preds] if isinstance(preds, str) else list(preds)
    targets_ = [targets] if isinstance(targets, str) else list(targets)
    sentence_results = _rouge_score_update(preds_, targets_, rouge_keys_values, accumulate, stemmer)

    output: Dict[str, List[Array]] = {
        f"rouge{k}_{m}": [] for k in rouge_keys_values for m in ("precision", "recall", "fmeasure")
    }
    for key, scores in sentence_results.items():
        for score in scores:
            for m, v in score.items():
                output[f"rouge{key}_{m}"].append(v)
    return _rouge_score_compute(output)
