"""Text helpers: edit distance (native C++ fast path) and n-gram counting.

Parity: reference ``torchmetrics/functional/text/helper.py`` (_edit_distance; the
446-LoC `_LevenshteinEditDistance` cache/trace machinery exists there to serve TER).
WER/CER/MER use the plain DP distance; TER scores shift candidates with the
beam-limited tercom variant (``edit_distance_beam_i32`` — the distance sacrebleu
actually uses, required for oracle parity). Both hot loops run natively, see
``metrics_tpu/native/levenshtein.cpp``.
"""
import ctypes
import os
import subprocess
from collections import Counter
from typing import List, Optional, Sequence, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "_levenshtein.so")
_CPP_PATH = os.path.join(_NATIVE_DIR, "levenshtein.cpp")

_lib: Optional[ctypes.CDLL] = None
_native_failed = False


def _load_native() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the native Levenshtein kernel; None on failure."""
    global _lib, _native_failed
    if _lib is not None or _native_failed:
        return _lib
    try:
        if not os.path.exists(_SO_PATH) or os.path.getmtime(_SO_PATH) < os.path.getmtime(_CPP_PATH):
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", _CPP_PATH, "-o", _SO_PATH],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_SO_PATH)
        lib.edit_distance_i32.restype = ctypes.c_int64
        lib.edit_distance_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
        ]
        lib.edit_distance_beam_i32.restype = ctypes.c_int64
        lib.edit_distance_beam_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.edit_distance_batch_i32.restype = None
        lib.edit_distance_batch_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        _lib = lib
    except Exception:
        _native_failed = True
        _lib = None
    return _lib


def _tokens_to_ids(seqs_a: Sequence[Sequence], seqs_b: Sequence[Sequence]) -> Tuple[np.ndarray, ...]:
    """Map arbitrary hashable tokens to int32 ids, packed with offsets."""
    vocab: dict = {}

    def _ids(seq):
        out = np.empty(len(seq), dtype=np.int32)
        for i, tok in enumerate(seq):
            out[i] = vocab.setdefault(tok, len(vocab))
        return out

    a_list = [_ids(s) for s in seqs_a]
    b_list = [_ids(s) for s in seqs_b]
    a_off = np.zeros(len(a_list) + 1, dtype=np.int64)
    b_off = np.zeros(len(b_list) + 1, dtype=np.int64)
    np.cumsum([len(x) for x in a_list], out=a_off[1:])
    np.cumsum([len(x) for x in b_list], out=b_off[1:])
    a_data = np.concatenate(a_list) if a_list else np.zeros(0, dtype=np.int32)
    b_data = np.concatenate(b_list) if b_list else np.zeros(0, dtype=np.int32)
    return a_data.astype(np.int32), a_off, b_data.astype(np.int32), b_off


def _edit_distance_py(prediction_tokens: List, reference_tokens: List) -> int:
    """Plain DP edit distance (python fallback). Parity: reference helper."""
    n, m = len(prediction_tokens), len(reference_tokens)
    if n == 0:
        return m
    if m == 0:
        return n
    prev = list(range(m + 1))
    for i in range(1, n + 1):
        cur = [i] + [0] * m
        a = prediction_tokens[i - 1]
        for j in range(1, m + 1):
            cur[j] = min(prev[j - 1] + (a != reference_tokens[j - 1]), prev[j] + 1, cur[j - 1] + 1)
        prev = cur
    return prev[m]


def _edit_distance(prediction_tokens: List, reference_tokens: List) -> int:
    """Edit distance between two token sequences (native when available)."""
    lib = _load_native()
    if lib is None:
        return _edit_distance_py(prediction_tokens, reference_tokens)
    a_data, a_off, b_data, b_off = _tokens_to_ids([prediction_tokens], [reference_tokens])
    return int(
        lib.edit_distance_i32(
            a_data.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(prediction_tokens),
            b_data.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(reference_tokens),
        )
    )


def _edit_distance_ids(a_ids: "np.ndarray", b_ids: "np.ndarray", beam: Optional[int] = None) -> int:
    """Edit distance on pre-mapped int32 id arrays — the zero-allocation hot
    path for search loops (TER shift scoring) that evaluate many candidate
    sequences against one reference. ``beam`` enables tercom's beam-limited
    variant (pruned to the pseudo-diagonal; the distance sacrebleu actually
    scores with — parity requires it, exactness doesn't)."""
    lib = _load_native()
    if lib is None:
        if beam is None:
            return _edit_distance_py(list(a_ids), list(b_ids))
        return _edit_distance_beam_py(list(a_ids), list(b_ids), beam)
    a = a_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    b = b_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    if beam is None:
        return int(lib.edit_distance_i32(a, len(a_ids), b, len(b_ids)))
    return int(lib.edit_distance_beam_i32(a, len(a_ids), b, len(b_ids), beam))


def _edit_distance_beam_py(a: List, b: List, beam_width: int) -> int:
    """Python fallback twin of the native beam-limited distance."""
    import math

    n, m = len(a), len(b)
    if n == 0:
        return m
    if m == 0:
        return n
    ratio = m / n
    beam = math.ceil(ratio / 2 + beam_width) if beam_width < ratio / 2 else beam_width
    INF = 1 << 40
    prev = list(range(m + 1))
    for i in range(1, n + 1):
        cur = [INF] * (m + 1)
        diag = math.floor(i * ratio)
        lo = max(0, diag - beam)
        hi = m + 1 if i == n else min(m + 1, diag + beam)
        ai = a[i - 1]
        for j in range(lo, hi):
            if j == 0:
                cur[0] = prev[0] + 1
                continue
            cur[j] = min(prev[j - 1] + (ai != b[j - 1]), prev[j] + 1, cur[j - 1] + 1)
        prev = cur
    return prev[m]


def _edit_distance_batch(preds: Sequence[Sequence], refs: Sequence[Sequence]) -> np.ndarray:
    """Edit distances for a whole corpus in one native call."""
    lib = _load_native()
    if lib is None:
        return np.asarray([_edit_distance_py(list(p), list(r)) for p, r in zip(preds, refs)], dtype=np.int64)
    a_data, a_off, b_data, b_off = _tokens_to_ids(preds, refs)
    out = np.empty(len(preds), dtype=np.int64)
    lib.edit_distance_batch_i32(
        a_data.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        a_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        b_data.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        b_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(preds),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def _ngram_counts(tokens: Sequence, n_gram: int) -> Counter:
    """Counter of all 1..n_gram grams."""
    counts: Counter = Counter()
    for n in range(1, n_gram + 1):
        for i in range(len(tokens) - n + 1):
            counts[tuple(tokens[i:i + n])] += 1
    return counts


def _resolve_corpus_aliases(fn_name, preds, targets, hypothesis_corpus, reference_corpus):
    """Accept the reference's keyword names (``hypothesis_corpus``/
    ``reference_corpus``) as aliases of ``preds``/``targets``; double
    specification raises like an ordinary duplicate keyword would."""
    if hypothesis_corpus is not None:
        if preds is not None:
            raise TypeError(f"{fn_name}() got multiple values for the hypothesis corpus (`preds` and `hypothesis_corpus`).")
        preds = hypothesis_corpus
    if reference_corpus is not None:
        if targets is not None:
            raise TypeError(f"{fn_name}() got multiple values for the reference corpus (`targets` and `reference_corpus`).")
        targets = reference_corpus
    if preds is None or targets is None:
        raise ValueError(f"{fn_name} requires both a hypothesis (`preds`) and a reference (`targets`) corpus.")
    return preds, targets


def _canonicalize_corpora(preds, targets):
    """Canonicalize to (hypotheses: List[str], references: List[List[str]]).

    Parity: reference ``helper.py:_validate_inputs`` — a flat reference list
    with a SINGLE hypothesis means several references for that hypothesis;
    with many hypotheses it means one reference each; mismatched corpus sizes
    raise. An empty reference set scores against the empty string (zero
    matches) instead of crashing.
    """
    hyps = [preds] if isinstance(preds, str) else list(preds)
    if isinstance(targets, str):
        refs = [[targets]]
    else:
        targets = list(targets)  # materialize once — generators must not be consumed twice
        if all(isinstance(r, str) for r in targets):
            refs = [targets] if len(hyps) == 1 else [[r] for r in targets]
        else:
            refs = [[t] if isinstance(t, str) else list(t) for t in targets]
    # stricter than the reference guard (``helper.py:350`` skips the check when a
    # reference group is empty — silently zip-truncating mismatched corpora);
    # matched corpora behave identically
    if len(refs) != len(hyps):
        raise ValueError(f"Corpus has different size {len(refs)} != {len(hyps)}")
    refs = [r if r else [""] for r in refs]
    return hyps, refs
