"""Character error rate.

Parity: reference ``torchmetrics/functional/text/cer.py``.
"""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distance_batch

Array = jax.Array


def _cer_update(predictions: Union[str, List[str]], references: Union[str, List[str]]) -> Tuple[Array, Array]:
    if isinstance(predictions, str):
        predictions = [predictions]
    if isinstance(references, str):
        references = [references]
    errors = _edit_distance_batch([list(p) for p in predictions], [list(r) for r in references]).sum()
    total = sum(len(r) for r in references)
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def _cer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def char_error_rate(predictions: Union[str, List[str]], references: Union[str, List[str]]) -> Array:
    """CER = character edit operations / reference characters."""
    errors, total = _cer_update(predictions, references)
    return _cer_compute(errors, total)
