from metrics_tpu.functional.text.bert import bert_score
from metrics_tpu.functional.text.bleu import bleu_score
from metrics_tpu.functional.text.cer import char_error_rate
from metrics_tpu.functional.text.chrf import chrf_score
from metrics_tpu.functional.text.mer import match_error_rate
from metrics_tpu.functional.text.rouge import rouge_score
from metrics_tpu.functional.text.sacre_bleu import sacre_bleu_score
from metrics_tpu.functional.text.squad import squad
from metrics_tpu.functional.text.ter import translation_edit_rate
from metrics_tpu.functional.text.wer import wer, word_error_rate
from metrics_tpu.functional.text.wil import word_information_lost
from metrics_tpu.functional.text.wip import word_information_preserved
