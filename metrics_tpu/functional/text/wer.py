"""Word error rate.

Parity: reference ``torchmetrics/functional/text/wer.py``. Host-side tokenization +
native batch edit distance producing device counter deltas (the host/device split
from SURVEY.md §7.3).
"""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distance_batch
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


def _wer_update(predictions: Union[str, List[str]], references: Union[str, List[str]]) -> Tuple[Array, Array]:
    if isinstance(predictions, str):
        predictions = [predictions]
    if isinstance(references, str):
        references = [references]
    pred_tokens = [p.split() for p in predictions]
    ref_tokens = [r.split() for r in references]
    errors = _edit_distance_batch(pred_tokens, ref_tokens).sum()
    total = sum(len(r) for r in ref_tokens)
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def _wer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def word_error_rate(predictions: Union[str, List[str]], references: Union[str, List[str]]) -> Array:
    """WER = edit operations / reference words.

    Example:
        >>> from metrics_tpu.functional import word_error_rate
        >>> score = word_error_rate(['hello there world'], ['hello there word'])
        >>> print(f"{float(score):.4f}")
        0.3333
    """
    errors, total = _wer_update(predictions, references)
    return _wer_compute(errors, total)


def wer(predictions: Union[str, List[str]], references: Union[str, List[str]]) -> Array:
    """Deprecated alias of word_error_rate."""
    rank_zero_warn("`wer` was renamed to `word_error_rate` and it will be removed.", DeprecationWarning)
    return word_error_rate(predictions, references)
