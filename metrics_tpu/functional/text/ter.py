"""Translation edit rate (TER).

Parity: reference ``torchmetrics/functional/text/ter.py`` (626 LoC; tercom-style
normalisation + greedy shift search over the beam of possible block moves, each
scored by Levenshtein distance — the distance kernel runs natively, see
``metrics_tpu/native/levenshtein.cpp``).
"""
import math
import re
import unicodedata
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _canonicalize_corpora, _edit_distance_ids, _resolve_corpus_aliases

Array = jax.Array

_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50


def _normalize_general_and_western(sentence: str) -> str:
    rules = (
        (r"\n-", ""),
        (r"\n", " "),
        (r"&quot;", '"'),
        (r"&amp;", "&"),
        (r"&lt;", "<"),
        (r"&gt;", ">"),
        (r"([{-~\[-` -&(-+:-@/])", r" \1 "),
        (r"'s ", " 's "),
        (r"'s$", " 's"),
        (r"([^0-9])([\.,])", r"\1 \2 "),
        (r"([\.,])([^0-9])", r" \1 \2"),
        (r"([0-9])(-)", r"\1 \2 "),
    )
    for pattern, replacement in rules:
        sentence = re.sub(pattern, replacement, sentence)
    return sentence


_ASIAN_PUNCTUATION = r"([\u3001\u3002\u3008-\u3011\u3014-\u301f\uff61-\uff65\u30fb])"
_FULL_WIDTH_PUNCTUATION = r"([\uff0e\uff0c\uff1f\uff1a\uff1b\uff01\uff02\uff08\uff09])"


def _normalize_asian(sentence: str) -> str:
    """Split CJK ideographs/kana down to character level (tercom asian mode)."""
    # CJK Unified Ideographs (+ext A), strokes/radicals, compatibility blocks
    sentence = re.sub(r"([\u4e00-\u9fff\u3400-\u4dbf])", r" \1 ", sentence)
    sentence = re.sub(r"([\u31c0-\u31ef\u2e80-\u2eff])", r" \1 ", sentence)
    sentence = re.sub(r"([\u3300-\u33ff\uf900-\ufaff\ufe30-\ufe4f])", r" \1 ", sentence)
    sentence = re.sub(r"([\u3200-\u3f22])", r" \1 ", sentence)
    # hiragana / katakana / katakana phonetic extensions, as runs
    sentence = re.sub(r"(^|^[\u3040-\u309f])([\u3040-\u309f]+)(?=$|^[\u3040-\u309f])", r"\1 \2 ", sentence)
    sentence = re.sub(r"(^|^[\u30a0-\u30ff])([\u30a0-\u30ff]+)(?=$|^[\u30a0-\u30ff])", r"\1 \2 ", sentence)
    sentence = re.sub(r"(^|^[\u31f0-\u31ff])([\u31f0-\u31ff]+)(?=$|^[\u31f0-\u31ff])", r"\1 \2 ", sentence)
    sentence = re.sub(_ASIAN_PUNCTUATION, r" \1 ", sentence)
    sentence = re.sub(_FULL_WIDTH_PUNCTUATION, r" \1 ", sentence)
    return sentence


def _remove_punct(sentence: str) -> str:
    # tercom removes only this specific set — NOT all of string.punctuation
    # (hyphens/apostrophes stay; sacrebleu tokenizer_ter._remove_punct)
    return re.sub(r"[\.,\?:;!\"\(\)]", "", sentence)


def _remove_asian_punct(sentence: str) -> str:
    sentence = re.sub(_ASIAN_PUNCTUATION, "", sentence)
    sentence = re.sub(_FULL_WIDTH_PUNCTUATION, "", sentence)
    return sentence


def _preprocess_sentence(
    sentence: str, lowercase: bool, normalize: bool, no_punctuation: bool, asian_support: bool = False
) -> List[str]:
    sentence = sentence.rstrip()
    if lowercase:
        sentence = sentence.lower()
    if normalize:
        sentence = _normalize_general_and_western(sentence)
        if asian_support:
            sentence = _normalize_asian(sentence)
    if no_punctuation:
        sentence = _remove_punct(sentence)
        if asian_support:
            sentence = _remove_asian_punct(sentence)
    return sentence.split()


_MAX_SHIFT_CANDIDATES = 1000
_BEAM_WIDTH = 25


def _align_hyp_to_ref(hyp: List[str], ref: List[str]):
    """Beam-limited Levenshtein DP with an op trace, using tercom's
    tie-preference (match/substitute, then consume-hypothesis, then
    consume-reference). Returns ``(alignment, hyp_errors, ref_errors)`` where
    ``alignment[ref_pos] = hyp_pos`` for every reference position (the position
    a deleted reference word maps to is the last consumed hypothesis index) and
    the error lists flag non-match positions. Tercom's shift destinations are
    defined in terms of this alignment (reference ``ter.py:343-375`` /
    ``helper.py:398-446``); the beam matches tercom's pruning for very long
    sentences (``helper.py:131-137``)."""
    H, R = len(hyp), len(ref)
    INF = 1 << 30
    # rolling cost rows + one op byte-row per i: the beam visits only a narrow
    # band per row, so a full (H+1)x(R+1) tuple table would waste quadratic
    # memory on exactly the long sentences the beam exists for.
    # op codes: '=' match / 'S' substitute (both advance both), 'H' consume
    # hypothesis word only, 'R' consume reference word only
    prev = list(range(R + 1))
    op_rows = [bytearray(b"R" * (R + 1))]
    op_rows[0][0] = ord(" ")
    ratio = R / H if H else 1.0
    beam = math.ceil(ratio / 2 + _BEAM_WIDTH) if _BEAM_WIDTH < ratio / 2 else _BEAM_WIDTH
    for i in range(1, H + 1):
        cur = [INF] * (R + 1)
        ops_row = bytearray(b" " * (R + 1))
        diag = math.floor(i * ratio)
        lo = max(0, diag - beam)
        hi = R + 1 if i == H else min(R + 1, diag + beam)
        for j in range(lo, hi):
            if j == 0:
                cur[0] = prev[0] + 1
                ops_row[0] = ord("H")
                continue
            if hyp[i - 1] == ref[j - 1]:
                cost, op = prev[j - 1], ord("=")
            else:
                cost, op = prev[j - 1] + 1, ord("S")
            if prev[j] + 1 < cost:
                cost, op = prev[j] + 1, ord("H")
            if cur[j - 1] + 1 < cost:
                cost, op = cur[j - 1] + 1, ord("R")
            cur[j] = cost
            ops_row[j] = op
        prev = cur
        op_rows.append(ops_row)
    ops: List[str] = []
    i, j = H, R
    while i > 0 or j > 0:
        op = chr(op_rows[i][j])
        ops.append(op)
        if op in ("=", "S"):
            i, j = i - 1, j - 1
        elif op == "H":
            i -= 1
        else:
            j -= 1
    ops.reverse()
    alignment = {}
    hyp_errors: List[int] = []
    ref_errors: List[int] = []
    hp = rp = -1
    for op in ops:
        if op in ("=", "S"):
            hp += 1
            rp += 1
            alignment[rp] = hp
            err = 0 if op == "=" else 1
            hyp_errors.append(err)
            ref_errors.append(err)
        elif op == "H":
            hp += 1
            hyp_errors.append(1)
        else:  # R: reference word with no hypothesis counterpart
            rp += 1
            alignment[rp] = hp
            ref_errors.append(1)
    return alignment, hyp_errors, ref_errors


def _apply_shift(words: List[str], start: int, length: int, target: int) -> List[str]:
    """Move ``words[start:start+length]`` so it lands at ``target`` using
    tercom's three placement cases (before / after / within the moved region —
    reference ``ter.py:285-320``)."""
    block = words[start:start + length]
    if target < start:
        return words[:target] + block + words[target:start] + words[start + length:]
    if target > start + length:
        return words[:start] + words[start + length:target] + block + words[target:]
    return words[:start] + words[start + length:length + target] + block + words[length + target:]


def _ter_sentence(pred_words: List[str], ref_words: List[str]) -> float:
    """Shifts + edits for one hypothesis against one reference (tercom
    semantics — reference ``ter.py:323-446``, itself following sacrebleu's
    lib_ter). Candidate blocks are equal word spans (length 1..10, start
    offset ≤ 50) where both sides contain an error and the block is not
    already aligned in place; destinations come from the current alignment;
    candidates rank by (edit gain, block length, earliest start, earliest
    target); the search stops after 1000 candidates or when no shift helps."""
    if len(ref_words) == 0:
        # an empty reference costs one deletion per hypothesis word — the
        # reference reaches the same number because its 0-edit shortcut
        # (``ter.py:419-420``) keys on the empty HYPOTHESIS (its caller swaps
        # arguments at ``ter.py:469``); sacrebleu agrees. Returning 0 here
        # would let an empty string in a multi-reference group win best-of-min
        # and silently score the pair perfect.
        return float(len(pred_words))

    # map words to int ids once — the shift loop scores up to 1000 candidate
    # sequences per round, so per-candidate token hashing would dominate
    import numpy as np

    vocab: dict = {}
    current: List[int] = [vocab.setdefault(w, len(vocab)) for w in pred_words]
    ref_words = [vocab.setdefault(w, len(vocab)) for w in ref_words]
    ref_arr = np.asarray(ref_words, dtype=np.int32)

    def _dist(words: List[int]) -> int:
        # the beamed distance is what tercom/sacrebleu score with — parity
        # over exactness (the beam binds only on far-offset degenerate pairs)
        return _edit_distance_ids(np.asarray(words, dtype=np.int32), ref_arr, beam=_BEAM_WIDTH)

    num_shifts = 0
    checked = 0

    while True:
        base_dist = _dist(current)
        alignment, hyp_errors, ref_errors = _align_hyp_to_ref(current, ref_words)
        best = None  # (gain, length, -hyp_start, -target, shifted_words)
        stop = False
        for hyp_start in range(len(current)):
            if stop:
                break
            for ref_start in range(len(ref_words)):
                if abs(ref_start - hyp_start) > _MAX_SHIFT_DIST:
                    continue
                for length in range(1, _MAX_SHIFT_SIZE + 1):  # sacrebleu allows 10-word blocks
                    if (hyp_start + length > len(current) or ref_start + length > len(ref_words)
                            or current[hyp_start + length - 1] != ref_words[ref_start + length - 1]):
                        break
                    # corner cases (reference ``ter.py:245-283``): the block must
                    # contain an error on both sides and not already sit where
                    # the alignment puts it
                    if (sum(hyp_errors[hyp_start:hyp_start + length]) != 0
                            and sum(ref_errors[ref_start:ref_start + length]) != 0
                            and not (hyp_start <= alignment[ref_start] < hyp_start + length)):
                        prev_target = -1
                        for offset in range(-1, length):
                            if ref_start + offset == -1:
                                target = 0
                            elif ref_start + offset in alignment:
                                target = alignment[ref_start + offset] + 1
                            else:
                                break  # past the end of the reference
                            if target == prev_target:
                                continue
                            prev_target = target
                            shifted = _apply_shift(current, hyp_start, length, target)
                            candidate = (
                                base_dist - _dist(shifted),  # biggest gain
                                length,                                          # longest block
                                -hyp_start,                                      # earliest start
                                -target,                                         # earliest target
                                shifted,
                            )
                            checked += 1
                            if best is None or candidate > best:
                                best = candidate
                    if checked >= _MAX_SHIFT_CANDIDATES:
                        stop = True
                        break
                    if hyp_start + length == len(current) or ref_start + length == len(ref_words):
                        break
                if stop:
                    break
        if best is None or checked >= _MAX_SHIFT_CANDIDATES or best[0] <= 0:
            break
        num_shifts += 1
        current = best[4]

    # every break path leaves `current` unchanged since base_dist was computed
    return float(num_shifts + base_dist)


def _ter_update(
    preds: Sequence[str],
    targets: Sequence[Sequence[str]],
    total_num_edits: Array,
    total_ref_len: Array,
    lowercase: bool = True,
    normalize: bool = False,
    no_punctuation: bool = False,
    sentence_scores: Optional[List[Array]] = None,
    asian_support: bool = False,
) -> Tuple[Array, Array]:
    edits_sum = 0.0
    ref_len_sum = 0.0
    for pred, refs in zip(preds, targets):
        pred_words = _preprocess_sentence(pred, lowercase, normalize, no_punctuation, asian_support)
        # multi-reference (reference ``ter.py:448-475``): the BEST (lowest) edit
        # count over all references, normalized by the AVERAGE reference length
        best_edits = None
        ref_len_total = 0.0
        for ref in refs:
            ref_words = _preprocess_sentence(ref, lowercase, normalize, no_punctuation, asian_support)
            edits = _ter_sentence(pred_words, ref_words)
            ref_len_total += len(ref_words)
            if best_edits is None or edits < best_edits:
                best_edits = edits
        avg_ref_len = ref_len_total / len(refs)
        edits_sum += best_edits
        ref_len_sum += avg_ref_len
        if sentence_scores is not None:
            # reference ``ter.py:488-495`` zero-length rule
            if avg_ref_len > 0 and best_edits > 0:
                s = best_edits / avg_ref_len
            elif avg_ref_len == 0 and best_edits > 0:
                s = 1.0
            else:
                s = 0.0
            sentence_scores.append(jnp.asarray(s))
    return total_num_edits + edits_sum, total_ref_len + ref_len_sum


def _ter_compute(total_num_edits: Array, total_ref_len: Array) -> Array:
    # reference ``ter.py:488-495``: zero reference length scores 1 when edits
    # remain, 0 when the hypothesis is empty too
    return jnp.where(
        total_ref_len > 0,
        total_num_edits / jnp.maximum(total_ref_len, 1e-38),
        jnp.where(total_num_edits > 0, 1.0, 0.0),
    )


def translation_edit_rate(
    preds: Union[str, Sequence[str], None] = None,
    targets: Union[str, Sequence[str], Sequence[Sequence[str]], None] = None,
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
    *,
    hypothesis_corpus: Union[str, Sequence[str], None] = None,
    reference_corpus: Union[str, Sequence[str], Sequence[Sequence[str]], None] = None,
) -> Union[Array, Tuple[Array, Array]]:
    """Corpus TER = (shifts + edits) / reference length. Parity: reference API
    (``ter.py:560``) — its keyword names ``hypothesis_corpus``/``reference_corpus``
    are accepted as aliases of ``preds``/``targets`` (same positional order), and
    multi-reference corpora follow the reference's ``_validate_inputs`` shapes."""
    preds, targets = _resolve_corpus_aliases("translation_edit_rate", preds, targets, hypothesis_corpus, reference_corpus)
    preds_, targets_ = _canonicalize_corpora(preds, targets)

    total_num_edits = jnp.asarray(0.0)
    total_ref_len = jnp.asarray(0.0)
    sentence_scores: Optional[List[Array]] = [] if return_sentence_level_score else None
    total_num_edits, total_ref_len = _ter_update(
        preds_, targets_, total_num_edits, total_ref_len, lowercase, normalize, no_punctuation, sentence_scores,
        asian_support,
    )
    score = _ter_compute(total_num_edits, total_ref_len)
    if return_sentence_level_score:
        return score, jnp.stack(sentence_scores)
    return score
