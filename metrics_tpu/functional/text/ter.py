"""Translation edit rate (TER).

Parity: reference ``torchmetrics/functional/text/ter.py`` (626 LoC; tercom-style
normalisation + greedy shift search over the beam of possible block moves, each
scored by Levenshtein distance — the distance kernel runs natively, see
``metrics_tpu/native/levenshtein.cpp``).
"""
import re
import unicodedata
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _canonicalize_corpora, _edit_distance, _resolve_corpus_aliases

Array = jax.Array

_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50


def _normalize_general_and_western(sentence: str) -> str:
    rules = (
        (r"\n-", ""),
        (r"\n", " "),
        (r"&quot;", '"'),
        (r"&amp;", "&"),
        (r"&lt;", "<"),
        (r"&gt;", ">"),
        (r"([{-~\[-` -&(-+:-@/])", r" \1 "),
        (r"'s ", " 's "),
        (r"'s$", " 's"),
        (r"([^0-9])([\.,])", r"\1 \2 "),
        (r"([\.,])([^0-9])", r" \1 \2"),
        (r"([0-9])(-)", r"\1 \2 "),
    )
    for pattern, replacement in rules:
        sentence = re.sub(pattern, replacement, sentence)
    return sentence


_ASIAN_PUNCTUATION = r"([\u3001\u3002\u3008-\u3011\u3014-\u301f\uff61-\uff65\u30fb])"
_FULL_WIDTH_PUNCTUATION = r"([\uff0e\uff0c\uff1f\uff1a\uff1b\uff01\uff02\uff08\uff09])"


def _normalize_asian(sentence: str) -> str:
    """Split CJK ideographs/kana down to character level (tercom asian mode)."""
    # CJK Unified Ideographs (+ext A), strokes/radicals, compatibility blocks
    sentence = re.sub(r"([\u4e00-\u9fff\u3400-\u4dbf])", r" \1 ", sentence)
    sentence = re.sub(r"([\u31c0-\u31ef\u2e80-\u2eff])", r" \1 ", sentence)
    sentence = re.sub(r"([\u3300-\u33ff\uf900-\ufaff\ufe30-\ufe4f])", r" \1 ", sentence)
    sentence = re.sub(r"([\u3200-\u3f22])", r" \1 ", sentence)
    # hiragana / katakana / katakana phonetic extensions, as runs
    sentence = re.sub(r"(^|^[\u3040-\u309f])([\u3040-\u309f]+)(?=$|^[\u3040-\u309f])", r"\1 \2 ", sentence)
    sentence = re.sub(r"(^|^[\u30a0-\u30ff])([\u30a0-\u30ff]+)(?=$|^[\u30a0-\u30ff])", r"\1 \2 ", sentence)
    sentence = re.sub(r"(^|^[\u31f0-\u31ff])([\u31f0-\u31ff]+)(?=$|^[\u31f0-\u31ff])", r"\1 \2 ", sentence)
    sentence = re.sub(_ASIAN_PUNCTUATION, r" \1 ", sentence)
    sentence = re.sub(_FULL_WIDTH_PUNCTUATION, r" \1 ", sentence)
    return sentence


def _remove_punct(sentence: str) -> str:
    # tercom removes only this specific set — NOT all of string.punctuation
    # (hyphens/apostrophes stay; sacrebleu tokenizer_ter._remove_punct)
    return re.sub(r"[\.,\?:;!\"\(\)]", "", sentence)


def _remove_asian_punct(sentence: str) -> str:
    sentence = re.sub(_ASIAN_PUNCTUATION, "", sentence)
    sentence = re.sub(_FULL_WIDTH_PUNCTUATION, "", sentence)
    return sentence


def _preprocess_sentence(
    sentence: str, lowercase: bool, normalize: bool, no_punctuation: bool, asian_support: bool = False
) -> List[str]:
    sentence = sentence.rstrip()
    if lowercase:
        sentence = sentence.lower()
    if normalize:
        sentence = _normalize_general_and_western(sentence)
        if asian_support:
            sentence = _normalize_asian(sentence)
    if no_punctuation:
        sentence = _remove_punct(sentence)
        if asian_support:
            sentence = _remove_asian_punct(sentence)
    return sentence.split()


def _find_shifted_sequences(words: List[str]) -> dict:
    """All contiguous subsequences (up to _MAX_SHIFT_SIZE) -> start positions."""
    seqs: dict = {}
    for start in range(len(words)):
        for length in range(1, min(_MAX_SHIFT_SIZE, len(words) - start) + 1):
            seqs.setdefault(tuple(words[start:start + length]), []).append((start, length))
    return seqs


def _shift_words(words: List[str], start: int, length: int, dest: int) -> List[str]:
    block = words[start:start + length]
    rest = words[:start] + words[start + length:]
    # dest is the index in `rest` the block is inserted before
    return rest[:dest] + block + rest[dest:]


def _ter_sentence(pred_words: List[str], ref_words: List[str]) -> float:
    """Shifts + edits for one hypothesis against one reference (greedy tercom)."""
    if len(ref_words) == 0:
        return float(len(pred_words))

    num_shifts = 0
    current = list(pred_words)
    current_dist = _edit_distance(current, ref_words)
    ref_seqs = _find_shifted_sequences(ref_words)

    while current_dist > 0:
        best_dist = current_dist
        best_words: Optional[List[str]] = None
        # try moving every (start, length) block of the hypothesis that also occurs
        # in the reference to each occurrence position
        for start in range(len(current)):
            for length in range(1, min(_MAX_SHIFT_SIZE, len(current) - start) + 1):
                block = tuple(current[start:start + length])
                if block not in ref_seqs:
                    continue
                for dest, _ in ref_seqs[block]:
                    if abs(dest - start) > _MAX_SHIFT_DIST:
                        continue
                    shifted = _shift_words(current, start, length, min(dest, len(current) - length))
                    d = _edit_distance(shifted, ref_words)
                    if d < best_dist:
                        best_dist = d
                        best_words = shifted
        if best_words is None:
            break
        num_shifts += 1
        current = best_words
        current_dist = best_dist

    return float(num_shifts + current_dist)


def _ter_update(
    preds: Sequence[str],
    targets: Sequence[Sequence[str]],
    total_num_edits: Array,
    total_ref_len: Array,
    lowercase: bool = True,
    normalize: bool = False,
    no_punctuation: bool = False,
    sentence_scores: Optional[List[Array]] = None,
    asian_support: bool = False,
) -> Tuple[Array, Array]:
    edits_sum = 0.0
    ref_len_sum = 0.0
    for pred, refs in zip(preds, targets):
        pred_words = _preprocess_sentence(pred, lowercase, normalize, no_punctuation, asian_support)
        # multi-reference (reference ``ter.py:448-475``): the BEST (lowest) edit
        # count over all references, normalized by the AVERAGE reference length
        best_edits = None
        ref_len_total = 0.0
        for ref in refs:
            ref_words = _preprocess_sentence(ref, lowercase, normalize, no_punctuation, asian_support)
            edits = _ter_sentence(pred_words, ref_words)
            ref_len_total += len(ref_words)
            if best_edits is None or edits < best_edits:
                best_edits = edits
        avg_ref_len = ref_len_total / len(refs)
        edits_sum += best_edits
        ref_len_sum += avg_ref_len
        if sentence_scores is not None:
            # reference ``ter.py:488-495`` zero-length rule
            if avg_ref_len > 0 and best_edits > 0:
                s = best_edits / avg_ref_len
            elif avg_ref_len == 0 and best_edits > 0:
                s = 1.0
            else:
                s = 0.0
            sentence_scores.append(jnp.asarray(s))
    return total_num_edits + edits_sum, total_ref_len + ref_len_sum


def _ter_compute(total_num_edits: Array, total_ref_len: Array) -> Array:
    # reference ``ter.py:488-495``: zero reference length scores 1 when edits
    # remain, 0 when the hypothesis is empty too
    return jnp.where(
        total_ref_len > 0,
        total_num_edits / jnp.maximum(total_ref_len, 1e-38),
        jnp.where(total_num_edits > 0, 1.0, 0.0),
    )


def translation_edit_rate(
    preds: Union[str, Sequence[str], None] = None,
    targets: Union[str, Sequence[str], Sequence[Sequence[str]], None] = None,
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
    *,
    hypothesis_corpus: Union[str, Sequence[str], None] = None,
    reference_corpus: Union[str, Sequence[str], Sequence[Sequence[str]], None] = None,
) -> Union[Array, Tuple[Array, Array]]:
    """Corpus TER = (shifts + edits) / reference length. Parity: reference API
    (``ter.py:560``) — its keyword names ``hypothesis_corpus``/``reference_corpus``
    are accepted as aliases of ``preds``/``targets`` (same positional order), and
    multi-reference corpora follow the reference's ``_validate_inputs`` shapes."""
    preds, targets = _resolve_corpus_aliases("translation_edit_rate", preds, targets, hypothesis_corpus, reference_corpus)
    preds_, targets_ = _canonicalize_corpora(preds, targets)

    total_num_edits = jnp.asarray(0.0)
    total_ref_len = jnp.asarray(0.0)
    sentence_scores: Optional[List[Array]] = [] if return_sentence_level_score else None
    total_num_edits, total_ref_len = _ter_update(
        preds_, targets_, total_num_edits, total_ref_len, lowercase, normalize, no_punctuation, sentence_scores,
        asian_support,
    )
    score = _ter_compute(total_num_edits, total_ref_len)
    if return_sentence_level_score:
        return score, jnp.stack(sentence_scores)
    return score
