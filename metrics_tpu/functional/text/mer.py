"""Match error rate.

Parity: reference ``torchmetrics/functional/text/mer.py``.
"""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distance_batch

Array = jax.Array


def _mer_update(predictions: Union[str, List[str]], references: Union[str, List[str]]) -> Tuple[Array, Array]:
    if isinstance(predictions, str):
        predictions = [predictions]
    if isinstance(references, str):
        references = [references]
    pred_tokens = [p.split() for p in predictions]
    ref_tokens = [r.split() for r in references]
    errors = _edit_distance_batch(pred_tokens, ref_tokens).sum()
    total = sum(max(len(r), len(p)) for p, r in zip(pred_tokens, ref_tokens))
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def _mer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def match_error_rate(predictions: Union[str, List[str]], references: Union[str, List[str]]) -> Array:
    """MER = edit operations / max(reference, prediction) length."""
    errors, total = _mer_update(predictions, references)
    return _mer_compute(errors, total)
