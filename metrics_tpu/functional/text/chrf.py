"""chrF / chrF++ score.

Parity: reference ``torchmetrics/functional/text/chrf.py`` (704 LoC; the sacrebleu
chrF algorithm: character n-grams up to ``n_char_order`` plus optional word n-grams
up to ``n_word_order``, combined with an F-beta over averaged per-order precision and
recall). States are per-order matching/pred/ref count tensors, all sum-reducible.
"""
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _canonicalize_corpora, _resolve_corpus_aliases

Array = jax.Array

_EPS_SMOOTHING = 1e-16


def _prepare_text(text: str, lowercase: bool, whitespace: bool) -> str:
    if lowercase:
        text = text.lower()
    if not whitespace:
        text = "".join(text.split())
    return text


def _char_ngrams(text: str, n: int) -> Counter:
    return Counter(text[i:i + n] for i in range(len(text) - n + 1))


def _word_ngrams(words: List[str], n: int) -> Counter:
    return Counter(tuple(words[i:i + n]) for i in range(len(words) - n + 1))


def _sentence_counts(
    text: str, n_char_order: int, n_word_order: int, lowercase: bool, whitespace: bool
) -> Tuple[List[Counter], List[Counter]]:
    prepared = _prepare_text(text, lowercase, whitespace)
    char_counts = [_char_ngrams(prepared, n) for n in range(1, n_char_order + 1)]
    words = text.lower().split() if lowercase else text.split()
    word_counts = [_word_ngrams(words, n) for n in range(1, n_word_order + 1)]
    return char_counts, word_counts


def _matching(pred: Counter, ref: Counter) -> int:
    return sum((pred & ref).values())


def _chrf_score_from_totals(
    matching: Array, total_pred: Array, total_ref: Array, beta: float
) -> Array:
    """F-beta over per-order precision/recall averages (sacrebleu semantics)."""
    precision = jnp.where(total_pred > 0, matching / jnp.maximum(total_pred, 1), 0.0)
    recall = jnp.where(total_ref > 0, matching / jnp.maximum(total_ref, 1), 0.0)
    # sacrebleu effective-order smoothing: an order counts only when BOTH sides
    # produced n-grams of that order (short references drop the high orders)
    order_mask = (total_pred > 0) & (total_ref > 0)
    n_eff = jnp.maximum(jnp.sum(order_mask), 1)
    avg_precision = jnp.sum(jnp.where(order_mask, precision, 0.0)) / n_eff
    avg_recall = jnp.sum(jnp.where(order_mask, recall, 0.0)) / n_eff
    beta2 = beta ** 2
    denom = beta2 * avg_precision + avg_recall
    f_score = jnp.where(
        denom > 0, (1 + beta2) * avg_precision * avg_recall / jnp.maximum(denom, _EPS_SMOOTHING), 0.0
    )
    return f_score


def _chrf_score_np(matching, total_pred, total_ref, beta: float) -> float:
    """Host-side twin of :func:`_chrf_score_from_totals` for best-reference
    selection — plain numpy, no device dispatch in the corpus hot loop."""
    import numpy as np

    precision = np.where(total_pred > 0, matching / np.maximum(total_pred, 1), 0.0)
    recall = np.where(total_ref > 0, matching / np.maximum(total_ref, 1), 0.0)
    order_mask = (total_pred > 0) & (total_ref > 0)
    n_eff = max(int(order_mask.sum()), 1)
    avg_precision = float(precision[order_mask].sum()) / n_eff
    avg_recall = float(recall[order_mask].sum()) / n_eff
    beta2 = beta ** 2
    denom = beta2 * avg_precision + avg_recall
    if denom <= 0:
        return 0.0
    return (1 + beta2) * avg_precision * avg_recall / max(denom, _EPS_SMOOTHING)


def _chrf_update(
    preds: Sequence[str],
    targets: Sequence[str],
    matching: Array,
    total_pred: Array,
    total_ref: Array,
    n_char_order: int = 6,
    n_word_order: int = 2,
    lowercase: bool = False,
    whitespace: bool = False,
    beta: float = 2.0,
    sentence_scores: Optional[List[Array]] = None,
) -> Tuple[Array, Array, Array]:
    """Accumulate per-order n-gram statistics over a batch of sentence pairs."""
    n_order = n_char_order + n_word_order
    import numpy as np

    m_np = np.zeros(n_order)
    p_np = np.zeros(n_order)
    r_np = np.zeros(n_order)
    for pred, refs in zip(preds, targets):
        p_char, p_word = _sentence_counts(pred, n_char_order, n_word_order, lowercase, whitespace)
        # multi-reference: evaluate every reference and keep the statistics of
        # the best-matching one (reference ``chrf.py:313-375``); the common
        # single-reference case skips the selection scoring entirely
        cands = []
        for ref in ([refs] if isinstance(refs, str) else list(refs)):
            r_char, r_word = _sentence_counts(ref, n_char_order, n_word_order, lowercase, whitespace)
            cand_m = np.zeros(n_order)
            cand_p = np.zeros(n_order)
            cand_r = np.zeros(n_order)
            for i, (pc, rc) in enumerate(list(zip(p_char, r_char)) + list(zip(p_word, r_word))):
                cand_m[i] = _matching(pc, rc)
                # sacrebleu: a hypothesis n-gram count only stands when the
                # reference produced ANY n-gram of that order
                cand_p[i] = sum(pc.values()) if rc else 0
                cand_r[i] = sum(rc.values())
            cands.append((cand_m, cand_p, cand_r))
        if len(cands) == 1:
            sent_m, sent_p, sent_r = cands[0]
        else:  # first-wins ties, like sacrebleu's strict > comparison
            sent_m, sent_p, sent_r = max(cands, key=lambda c: _chrf_score_np(c[0], c[1], c[2], beta))
        m_np += sent_m
        p_np += sent_p
        r_np += sent_r
        if sentence_scores is not None:
            sentence_scores.append(jnp.asarray(_chrf_score_np(sent_m, sent_p, sent_r, beta)))
    return (
        matching + jnp.asarray(m_np, dtype=jnp.float32),
        total_pred + jnp.asarray(p_np, dtype=jnp.float32),
        total_ref + jnp.asarray(r_np, dtype=jnp.float32),
    )


def _chrf_compute(matching: Array, total_pred: Array, total_ref: Array, beta: float = 2.0) -> Array:
    return _chrf_score_from_totals(matching, total_pred, total_ref, beta)


def chrf_score(
    preds: Union[str, Sequence[str], None] = None,
    targets: Union[str, Sequence[str], Sequence[Sequence[str]], None] = None,
    n_char_order: int = 6,
    n_word_order: int = 2,
    beta: float = 2.0,
    lowercase: bool = False,
    whitespace: bool = False,
    return_sentence_level_score: bool = False,
    *,
    hypothesis_corpus: Union[str, Sequence[str], None] = None,
    reference_corpus: Union[str, Sequence[str], Sequence[Sequence[str]], None] = None,
) -> Union[Array, Tuple[Array, Array]]:
    """Corpus chrF (chrF++ with word n-grams). Parity: reference ``chrf_score``
    (``chrf.py:588``) — its keyword names ``hypothesis_corpus``/``reference_corpus``
    are accepted as aliases of ``preds``/``targets`` (same positional order), and
    multi-reference corpora follow the reference's ``_validate_inputs`` shapes."""
    preds, targets = _resolve_corpus_aliases("chrf_score", preds, targets, hypothesis_corpus, reference_corpus)
    if not isinstance(n_char_order, int) or n_char_order < 1:
        raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
    if not isinstance(n_word_order, int) or n_word_order < 0:
        raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
    if beta < 0:
        raise ValueError("Expected argument `beta` to be greater than 0.")

    preds_, targets_ = _canonicalize_corpora(preds, targets)

    n_order = n_char_order + n_word_order
    matching = jnp.zeros(n_order)
    total_pred = jnp.zeros(n_order)
    total_ref = jnp.zeros(n_order)
    sentence_scores: Optional[List[Array]] = [] if return_sentence_level_score else None
    matching, total_pred, total_ref = _chrf_update(
        preds_, targets_, matching, total_pred, total_ref, n_char_order, n_word_order,
        lowercase, whitespace, beta, sentence_scores,
    )
    score = _chrf_compute(matching, total_pred, total_ref, beta)
    if return_sentence_level_score:
        return score, jnp.stack(sentence_scores)
    return score
