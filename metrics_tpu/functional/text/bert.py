"""BERTScore: contextual-embedding similarity with greedy matching.

Parity: reference ``torchmetrics/functional/text/bert.py`` (651 LoC: TextDataset +
DataLoader host loop :134-341, IDF weighting :182, greedy cosine matching :342-376,
bert_score :452). TPU-native differences:
  * the encoder is pluggable — a HF Flax model from a *local* path, or any
    ``user_forward_fn(input_ids, attention_mask) -> (N, L, D)`` (this build has no
    egress, so there is no silent weight download); the forward is jitted and runs
    under the caller's mesh (shard the batch to shard the encoder).
  * matching is one batched einsum (L_p x L_r similarity per pair) + masked max —
    MXU work, no python token loops.
"""
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _simple_whitespace_tokenizer(sentences: List[str], max_length: int) -> Dict[str, np.ndarray]:
    """Fallback host tokenizer: whitespace tokens hashed into ids (no vocab file)."""
    ids = np.zeros((len(sentences), max_length), dtype=np.int32)
    mask = np.zeros((len(sentences), max_length), dtype=np.int32)
    for i, s in enumerate(sentences):
        toks = s.split()[:max_length]
        for j, t in enumerate(toks):
            ids[i, j] = (hash(t) % 30000) + 1
        mask[i, : len(toks)] = 1
    return {"input_ids": ids, "attention_mask": mask}


def _get_tokens_idf(target_ids: np.ndarray, target_mask: np.ndarray) -> Dict[int, float]:
    """IDF over the reference corpus. Parity: reference ``bert.py:182-206``."""
    num_docs = target_ids.shape[0]
    doc_freq: Counter = Counter()
    for row, m in zip(target_ids, target_mask):
        doc_freq.update(set(int(t) for t, mm in zip(row, m) if mm))
    return {tok: float(np.log((num_docs + 1) / (df + 1))) for tok, df in doc_freq.items()}


def _idf_weights(ids: np.ndarray, mask: np.ndarray, idf_map: Dict[int, float]) -> np.ndarray:
    w = np.zeros(ids.shape, dtype=np.float32)
    for i in range(ids.shape[0]):
        for j in range(ids.shape[1]):
            if mask[i, j]:
                w[i, j] = idf_map.get(int(ids[i, j]), float(np.log((1 + 1) / 1)))
    return w


def _bert_score_from_embeddings(
    pred_emb: Array,
    pred_mask: Array,
    target_emb: Array,
    target_mask: Array,
    pred_weights: Optional[Array] = None,
    target_weights: Optional[Array] = None,
) -> Tuple[Array, Array, Array]:
    """Greedy-matching P/R/F1 per sentence pair. Parity: ``bert.py:342-376``."""
    pred_norm = pred_emb / jnp.clip(jnp.linalg.norm(pred_emb, axis=-1, keepdims=True), 1e-12, None)
    target_norm = target_emb / jnp.clip(jnp.linalg.norm(target_emb, axis=-1, keepdims=True), 1e-12, None)
    sim = jnp.einsum("nld,nmd->nlm", pred_norm, target_norm)  # (N, L_pred, L_tgt)
    pair_mask = pred_mask[:, :, None] * target_mask[:, None, :]
    sim = jnp.where(pair_mask > 0, sim, -jnp.inf)

    best_for_pred = jnp.max(sim, axis=2)  # (N, L_pred)
    best_for_target = jnp.max(sim, axis=1)  # (N, L_tgt)
    best_for_pred = jnp.where(pred_mask > 0, best_for_pred, 0.0)
    best_for_target = jnp.where(target_mask > 0, best_for_target, 0.0)

    pw = pred_weights if pred_weights is not None else pred_mask.astype(best_for_pred.dtype)
    tw = target_weights if target_weights is not None else target_mask.astype(best_for_target.dtype)
    pw = pw * (pred_mask > 0)
    tw = tw * (target_mask > 0)

    precision = jnp.sum(best_for_pred * pw, axis=1) / jnp.clip(jnp.sum(pw, axis=1), 1e-12, None)
    recall = jnp.sum(best_for_target * tw, axis=1) / jnp.clip(jnp.sum(tw, axis=1), 1e-12, None)
    f1 = 2 * precision * recall / jnp.clip(precision + recall, 1e-12, None)
    return precision, recall, f1


def bert_score(
    predictions: List[str],
    references: List[str],
    model_name_or_path: Optional[str] = None,
    num_layers: Optional[int] = None,
    all_layers: bool = False,
    model: Optional[Any] = None,
    user_tokenizer: Optional[Any] = None,
    user_forward_fn: Optional[Callable] = None,
    verbose: bool = False,
    idf: bool = False,
    device: Optional[str] = None,
    max_length: int = 128,
    batch_size: int = 64,
    num_threads: int = 4,
    return_hash: bool = False,
    lang: str = "en",
    rescale_with_baseline: bool = False,
    baseline_path: Optional[str] = None,
) -> Dict[str, Union[List[float], str]]:
    """Compute BERTScore P/R/F1 per sentence pair.

    The encoder resolves in priority order: ``user_forward_fn`` (ids, mask) -> emb;
    ``model`` (a flax module apply-able on (ids, mask)); ``model_name_or_path`` (a
    LOCAL HF Flax checkpoint). Tokenization uses ``user_tokenizer`` (HF-compatible,
    ``__call__`` returning input_ids/attention_mask) or a whitespace fallback.

    To use a pretrained torch BERT offline, convert it once
    (``python tools/convert_weights.py bert <torch_dir> <flax_dir>``) and pass
    ``model_name_or_path=<flax_dir>`` with its tokenizer — the full local pipeline
    is exercised in ``tests/text/test_bert_e2e.py``.
    """
    if len(predictions) != len(references):
        raise ValueError("Number of predicted and reference sentences must be the same!")
    if rescale_with_baseline and baseline_path is None:
        raise ValueError("Baseline rescaling requires a local `baseline_path` csv (no downloads in this build).")

    # ---- tokenize (host)
    if user_tokenizer is not None:
        enc_pred = user_tokenizer(predictions, max_length)
        enc_tgt = user_tokenizer(references, max_length)
    else:
        enc_pred = _simple_whitespace_tokenizer(predictions, max_length)
        enc_tgt = _simple_whitespace_tokenizer(references, max_length)
    pred_ids, pred_mask = np.asarray(enc_pred["input_ids"]), np.asarray(enc_pred["attention_mask"])
    tgt_ids, tgt_mask = np.asarray(enc_tgt["input_ids"]), np.asarray(enc_tgt["attention_mask"])

    # ---- resolve encoder
    forward = user_forward_fn
    if forward is None and model is not None:
        forward = lambda ids, mask: model(ids, mask)
    if forward is None and model_name_or_path is not None:
        from transformers import FlaxAutoModel

        hf_model = FlaxAutoModel.from_pretrained(model_name_or_path)
        forward = lambda ids, mask: hf_model(input_ids=ids, attention_mask=mask).last_hidden_state
    if forward is None:
        raise ValueError(
            "BERTScore needs an encoder: pass `user_forward_fn`, `model`, or a local `model_name_or_path`"
            " (this build cannot download pretrained weights)."
        )

    # ---- embed in batches (device)
    def _embed(ids: np.ndarray, mask: np.ndarray) -> Array:
        outs = []
        for i in range(0, ids.shape[0], batch_size):
            outs.append(jnp.asarray(forward(jnp.asarray(ids[i:i + batch_size]), jnp.asarray(mask[i:i + batch_size]))))
        return jnp.concatenate(outs, axis=0)

    pred_emb = _embed(pred_ids, pred_mask)
    tgt_emb = _embed(tgt_ids, tgt_mask)

    pred_w = tgt_w = None
    if idf:
        idf_map = _get_tokens_idf(tgt_ids, tgt_mask)
        pred_w = jnp.asarray(_idf_weights(pred_ids, pred_mask, idf_map))
        tgt_w = jnp.asarray(_idf_weights(tgt_ids, tgt_mask, idf_map))

    precision, recall, f1 = _bert_score_from_embeddings(
        pred_emb, jnp.asarray(pred_mask), tgt_emb, jnp.asarray(tgt_mask), pred_w, tgt_w
    )

    if rescale_with_baseline:
        baseline = np.loadtxt(baseline_path, delimiter=",", skiprows=1)[num_layers or -1][1:]
        precision = (precision - baseline[0]) / (1 - baseline[0])
        recall = (recall - baseline[1]) / (1 - baseline[1])
        f1 = (f1 - baseline[2]) / (1 - baseline[2])

    output: Dict[str, Union[List[float], str]] = {
        "precision": [float(x) for x in np.asarray(precision)],
        "recall": [float(x) for x in np.asarray(recall)],
        "f1": [float(x) for x in np.asarray(f1)],
    }
    if return_hash:
        output["hash"] = f"metrics_tpu-bert_score-{model_name_or_path}"
    return output
