"""BERTScore: contextual-embedding similarity with greedy matching.

Parity: reference ``torchmetrics/functional/text/bert.py`` (651 LoC: TextDataset +
DataLoader host loop :134-341, IDF weighting :182, greedy cosine matching :342-376,
bert_score :452). TPU-native differences:
  * the encoder is pluggable — a HF Flax model from a *local* path, or any
    ``user_forward_fn(input_ids, attention_mask) -> (N, L, D)`` (this build has no
    egress, so there is no silent weight download); the forward is jitted and runs
    under the caller's mesh (shard the batch to shard the encoder).
  * matching is one batched einsum (L_p x L_r similarity per pair) + masked max —
    MXU work, no python token loops.
"""
import os
import zlib
from collections import Counter, OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array

# jitted-forward cache keyed on the user's encoder object so repeated
# bert_score calls reuse the compiled forward instead of re-tracing (or worse,
# running the flax encoder op-by-op). The cached closure necessarily keeps its
# encoder alive, so the cache is a bounded LRU (a WeakKeyDictionary could never
# evict: value -> fn -> key is a strong cycle).
_JIT_FORWARD_CACHE_MAX = 8
_JIT_FORWARD_CACHE: "OrderedDict[int, Tuple[Any, Callable]]" = OrderedDict()
# loaded-from-path flax models: bounded the same way (a checkpoint sweep would
# otherwise pin every model in memory forever)
_LOADED_MODEL_CACHE_MAX = 4
_LOADED_MODEL_CACHE: "OrderedDict[str, Any]" = OrderedDict()

# failures that mean "this callable cannot run under jit" (numpy/torch inside);
# anything else (OOM, bad shapes, ...) propagates to the caller
_TRACE_ERRORS = (jax.errors.JAXTypeError, TypeError, AttributeError)


def _jit_with_eager_fallback(fn: Callable) -> Callable:
    """jit ``fn``; if it is not traceable (an encoder computing in numpy/torch),
    warn once and permanently fall back to the eager callable.

    The warning fires only after the eager retry SUCCEEDS — a genuine bug in
    the encoder (typo -> AttributeError, bad signature -> TypeError) raises the
    same exception eagerly, which then propagates without a misleading
    "not jit-traceable" message."""
    jfn = jax.jit(fn)
    state = {"jit_ok": True, "warn_pending": False}

    def wrapped(ids, mask):
        if state["jit_ok"]:
            try:
                return jfn(ids, mask)
            except _TRACE_ERRORS:
                state["jit_ok"] = False
                state["warn_pending"] = True
        out = fn(ids, mask)
        if state["warn_pending"]:
            state["warn_pending"] = False
            rank_zero_warn(
                "BERTScore encoder is not jit-traceable; running it eagerly. "
                "Pass a jnp-based forward for compiled execution."
            )
        return out

    return wrapped


def _is_prejitted(fn: Callable) -> bool:
    """True for callables that handle their own compilation — a ``jax.jit``
    product, or anything flagged ``_metrics_tpu_prejitted``. Re-jitting such a
    callable would inline it and bake its closed-over params into the HLO as
    literal constants — for a BERT-base encoder that is a ~400 MB program
    (observed as an HTTP 413 from a remote-compile service)."""
    if getattr(fn, "_metrics_tpu_prejitted", False):
        return True
    try:
        return isinstance(fn, jax.stages.Wrapped)
    except AttributeError:  # pragma: no cover - older jax without jax.stages
        return False


def _is_hf_flax_model(model: Any) -> bool:
    """True only for genuine HF Flax models (``FlaxPreTrainedModel``).

    The old duck-typed ``hasattr(model, "params") and hasattr(model,
    "config")`` check hijacked ANY callable carrying those attribute names —
    a custom encoder with its own ``.params`` pytree would silently be called
    with HF keyword conventions (``model(input_ids=..., attention_mask=...,
    params=...)``) instead of its documented ``model(ids, mask)`` signature.
    With transformers importable the check is a real ``isinstance``; without
    it nothing can be an HF model, so everything keeps the generic path.
    """
    from metrics_tpu.utils.imports import _TRANSFORMERS_AVAILABLE

    if not _TRANSFORMERS_AVAILABLE:
        return False
    try:
        from transformers import FlaxPreTrainedModel
    except ImportError:
        # transformers installed without Flax support (no flax extra, or the
        # >=5 line where the Flax classes are gone): nothing can be an HF
        # Flax model, and callables must keep their generic path
        return False

    return isinstance(model, FlaxPreTrainedModel)


def _cache_get(key: Any, pins: Tuple) -> Optional[Callable]:
    """LRU hit iff every pinned object is still the same identity."""
    hit = _JIT_FORWARD_CACHE.get(key)
    if hit is not None and len(hit[0]) == len(pins) and all(
        a is b for a, b in zip(hit[0], pins)
    ):
        _JIT_FORWARD_CACHE.move_to_end(key)
        return hit[1]
    return None


def _cache_put(key: Any, pins: Tuple, compiled: Callable) -> Callable:
    # the pinned objects keep their ids from being recycled while cached
    _JIT_FORWARD_CACHE[key] = (pins, compiled)
    _JIT_FORWARD_CACHE.move_to_end(key)
    while len(_JIT_FORWARD_CACHE) > _JIT_FORWARD_CACHE_MAX:
        _JIT_FORWARD_CACHE.popitem(last=False)
    return compiled


def _jitted_forward(key_obj: Any, fn: Callable) -> Callable:
    """Bounded-LRU lookup of the compiled forward for this encoder object."""
    key = id(key_obj)
    hit = _cache_get(key, (key_obj,))
    if hit is not None:
        return hit
    compiled = fn if _is_prejitted(fn) else _jit_with_eager_fallback(fn)
    return _cache_put(key, (key_obj,), compiled)


def _simple_whitespace_tokenizer(sentences: List[str], max_length: int) -> Dict[str, np.ndarray]:
    """Fallback host tokenizer: whitespace tokens hashed into ids (no vocab file).

    Uses crc32, NOT the builtin ``hash`` — python string hashing is salted per
    process, which would give the same text different ids on different hosts
    (inconsistent metric values under multi-host sync) and on every rerun.
    """
    ids = np.zeros((len(sentences), max_length), dtype=np.int32)
    mask = np.zeros((len(sentences), max_length), dtype=np.int32)
    for i, s in enumerate(sentences):
        toks = s.split()[:max_length]
        for j, t in enumerate(toks):
            ids[i, j] = (zlib.crc32(t.encode("utf-8")) % 30000) + 1
        mask[i, : len(toks)] = 1
    return {"input_ids": ids, "attention_mask": mask}


def _strip_special_positions(mask: np.ndarray) -> np.ndarray:
    """Zero the [CLS] (first) and [SEP] (last real) positions of each row.

    Parity: reference ``bert.py:84-98,324`` — the greedy matching and the idf
    weighting exclude the special tokens (they are not part of either
    sentence's content); the encoder itself still attends to them. Uses the
    reference's exact ``cumsum(mask - 0.1).argmax`` trick for the last real
    position (an all-pad row resolves to position 0, already zeroed)."""
    out = np.asarray(mask).copy()
    if out.shape[1] == 0:
        return out
    last = np.cumsum(out - 0.1, axis=-1).argmax(-1)
    out[np.arange(out.shape[0]), last] = 0
    out[:, 0] = 0
    return out


def _get_tokens_idf(target_ids: np.ndarray, target_mask: np.ndarray) -> Dict[int, float]:
    """IDF over the reference corpus. Parity: reference ``bert.py:182-206``."""
    num_docs = target_ids.shape[0]
    doc_freq: Counter = Counter()
    for row, m in zip(target_ids, target_mask):
        doc_freq.update(set(int(t) for t, mm in zip(row, m) if mm))
    return {tok: float(np.log((num_docs + 1) / (df + 1))) for tok, df in doc_freq.items()}


def _idf_weights(ids: np.ndarray, mask: np.ndarray, idf_map: Dict[int, float]) -> np.ndarray:
    w = np.zeros(ids.shape, dtype=np.float32)
    for i in range(ids.shape[0]):
        for j in range(ids.shape[1]):
            if mask[i, j]:
                w[i, j] = idf_map.get(int(ids[i, j]), float(np.log((1 + 1) / 1)))
    return w


def _bert_score_from_embeddings(
    pred_emb: Array,
    pred_mask: Array,
    target_emb: Array,
    target_mask: Array,
    pred_weights: Optional[Array] = None,
    target_weights: Optional[Array] = None,
) -> Tuple[Array, Array, Array]:
    """Greedy-matching P/R/F1 per sentence pair. Parity: ``bert.py:342-376``."""
    pred_norm = pred_emb / jnp.clip(jnp.linalg.norm(pred_emb, axis=-1, keepdims=True), 1e-12, None)
    target_norm = target_emb / jnp.clip(jnp.linalg.norm(target_emb, axis=-1, keepdims=True), 1e-12, None)
    sim = jnp.einsum("nld,nmd->nlm", pred_norm, target_norm)  # (N, L_pred, L_tgt)
    pair_mask = pred_mask[:, :, None] * target_mask[:, None, :]
    sim = jnp.where(pair_mask > 0, sim, -jnp.inf)

    best_for_pred = jnp.max(sim, axis=2)  # (N, L_pred)
    best_for_target = jnp.max(sim, axis=1)  # (N, L_tgt)
    best_for_pred = jnp.where(pred_mask > 0, best_for_pred, 0.0)
    best_for_target = jnp.where(target_mask > 0, best_for_target, 0.0)

    pw = pred_weights if pred_weights is not None else pred_mask.astype(best_for_pred.dtype)
    tw = target_weights if target_weights is not None else target_mask.astype(best_for_target.dtype)
    pw = pw * (pred_mask > 0)
    tw = tw * (target_mask > 0)

    precision = jnp.sum(best_for_pred * pw, axis=1) / jnp.clip(jnp.sum(pw, axis=1), 1e-12, None)
    recall = jnp.sum(best_for_target * tw, axis=1) / jnp.clip(jnp.sum(tw, axis=1), 1e-12, None)
    f1 = 2 * precision * recall / jnp.clip(precision + recall, 1e-12, None)
    return precision, recall, f1


def _resolve_forward(
    user_forward_fn: Optional[Callable],
    model: Optional[Any],
    model_name_or_path: Optional[str],
    mesh: Optional[Any] = None,
    mesh_axis: Any = "dp",
) -> Callable:
    """Resolve the encoder callable (priority: fn > model > local path) and
    return its jit-compiled, cached form. Shared by the functional and the
    module APIs.

    ``mesh``: run the encoder batch-parallel under ``shard_map`` over the
    mesh's ``mesh_axis`` (ids/mask batch-sharded, params replicated via the
    encoder closure) — the sharded embedded-model path the reference drives
    with a DataLoader + per-process model (``bert.py:256-341``). The compiled
    cache is keyed on (encoder, mesh, axis) so the same encoder can serve both
    layouts without retracing.
    """
    def _wrap(key_obj: Any, fn: Callable) -> Callable:
        if mesh is not None and _is_prejitted(fn):
            # prejitted callables own their compilation AND sharding —
            # re-wrapping would bake their closed-over params into the program
            # as constants. We cannot shard them, so say so (the image metrics
            # raise for the analogous unshardeable-feature case).
            rank_zero_warn(
                "bert_score: the encoder is already jit-compiled, so `mesh=` is "
                "ignored. Shard it yourself with "
                "metrics_tpu.parallel.shard_batch_forward, or pass an unjitted "
                "callable / a local model path."
            )
        if mesh is None or _is_prejitted(fn):
            return _jitted_forward(key_obj, fn)
        from metrics_tpu.parallel.embedded import shard_batch_forward

        key = (id(key_obj), id(mesh), str(mesh_axis))
        hit = _cache_get(key, (key_obj, mesh))
        if hit is not None:
            return hit
        # gather inside the compiled forward (out_axis=None): embeddings leave
        # replicated, so the host-side batching/concat path stays collective-free
        compiled = shard_batch_forward(fn, mesh, mesh_axis, out_axis=None)
        return _cache_put(key, (key_obj, mesh), compiled)

    def _wrap_hf_style(hf_model: Any) -> Callable:
        """HF Flax models: params enter as RUNTIME ARGUMENTS, never via
        closure — a closure capture would inline the whole weight pytree into
        the compiled program as constants (~4 bytes/param of HLO: hundreds of
        MB for a base-size encoder, and a hard 413 on remote-compile
        services). Cached under a mesh-aware key (the same model can serve
        both layouts)."""
        key = (id(hf_model), id(mesh) if mesh is not None else None, str(mesh_axis))
        hit = _cache_get(key, (hf_model, mesh))
        if hit is not None:
            return hit

        def hf_fwd(p, ids, mask):
            return hf_model(input_ids=ids, attention_mask=mask, params=p).last_hidden_state

        if mesh is None:
            jfn = jax.jit(hf_fwd)
        else:
            from metrics_tpu.parallel.embedded import shard_batch_forward

            jfn = shard_batch_forward(
                hf_fwd, mesh, mesh_axis, out_axis=None, replicated_argnums=(0,)
            )

        def forward(ids, mask):
            return jfn(hf_model.params, ids, mask)

        forward._metrics_tpu_prejitted = True
        return _cache_put(key, (hf_model, mesh), forward)

    if user_forward_fn is not None:
        return _wrap(user_forward_fn, user_forward_fn)
    if model is not None:
        if _is_prejitted(model):
            return _wrap(model, model)  # owns its compilation; used as-is
        if _is_hf_flax_model(model):
            # an HF Flax model object passed directly: same params-as-args
            # wiring as the model_name_or_path branch
            return _wrap_hf_style(model)
        return _wrap(model, lambda ids, mask: model(ids, mask))
    if model_name_or_path is not None:
        from transformers import FlaxAutoModel

        hit = _LOADED_MODEL_CACHE.get(model_name_or_path)
        if hit is None:
            hit = FlaxAutoModel.from_pretrained(model_name_or_path)
            _LOADED_MODEL_CACHE[model_name_or_path] = hit
            _LOADED_MODEL_CACHE.move_to_end(model_name_or_path)
            while len(_LOADED_MODEL_CACHE) > _LOADED_MODEL_CACHE_MAX:
                _LOADED_MODEL_CACHE.popitem(last=False)
        return _wrap_hf_style(hit)
    raise ValueError(
        "BERTScore needs an encoder: pass `user_forward_fn`, `model`, or a local `model_name_or_path`"
        " (this build cannot download pretrained weights)."
    )


def _score_tokenized(
    forward: Callable,
    pred_ids: np.ndarray,
    pred_mask: np.ndarray,
    tgt_ids: np.ndarray,
    tgt_mask: np.ndarray,
    idf: bool,
    batch_size: int,
    dedup: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    strip_special: bool = True,
) -> np.ndarray:
    """Embed + match pre-tokenized pred/ref batches; returns (3, N) numpy P/R/F1.

    ``strip_special``: exclude [CLS]/[SEP] positions from matching and idf
    (reference contract, ``bert.py:324``); the whitespace fallback tokenizer
    adds no special tokens, so its path turns this off.

    When preds and refs share padding geometry (max_length padding — the
    default), one fused pass over the concatenation keeps the encoder batches
    full; a tokenizer padding each side to its own longest length falls back to
    per-side embedding (the matching einsum handles L_pred != L_ref). Either
    way the post-encoder gather/split/matching runs as ONE compiled call whose
    (3, N) stack crosses to the host in ONE transfer — eagerly that path costs
    ~10 dispatch round-trips.

    Duplicate token rows (shared references, repeated candidates — the norm in
    MT eval where K systems score against one reference set) are encoded ONCE
    when fewer than half the rows are distinct. ``bert_score`` passes its
    text-level structure as ``dedup=(u_ids, u_mask, inverse)``; the
    pre-tokenized module path discovers row duplicates itself via
    ``np.unique``. Encoder dispatches are async — they pipeline behind the
    host prep of later chunks without blocking.
    """
    def _embed(ids: np.ndarray, mask: np.ndarray) -> List[Array]:
        outs = []
        for i in range(0, ids.shape[0], batch_size):
            out = forward(jnp.asarray(ids[i:i + batch_size]), jnp.asarray(mask[i:i + batch_size]))
            # eager-fallback encoders may hand back numpy/torch buffers
            outs.append(out if isinstance(out, jax.Array) else jnp.asarray(np.asarray(out)))
        return outs

    # matching/idf masks exclude special tokens; the ENCODER still receives
    # the full attention masks (it must attend to [CLS]/[SEP])
    pred_mmask = _strip_special_positions(pred_mask) if strip_special else pred_mask
    tgt_mmask = _strip_special_positions(tgt_mask) if strip_special else tgt_mask

    pred_w = tgt_w = None
    if idf:
        idf_map = _get_tokens_idf(tgt_ids, tgt_mask)
        pred_w = jnp.asarray(_idf_weights(pred_ids, pred_mmask, idf_map))
        tgt_w = jnp.asarray(_idf_weights(tgt_ids, tgt_mmask, idf_map))

    if pred_ids.shape[1] == tgt_ids.shape[1]:
        n_rows = pred_ids.shape[0] + tgt_ids.shape[0]
        length = pred_ids.shape[1]
        if dedup is None:
            # pre-tokenized entry (the module path): discover row duplicates
            all_ids = np.concatenate([pred_ids, tgt_ids], axis=0)
            all_mask = np.concatenate([pred_mask, tgt_mask], axis=0)
            key = np.concatenate([all_ids, all_mask], axis=1)
            uniq, inverse = np.unique(key, axis=0, return_inverse=True)
            u_ids, u_mask = uniq[:, :length], uniq[:, length:]
        else:
            u_ids, u_mask, inverse = dedup
        if u_ids.shape[0] <= n_rows // 2:
            # pad the unique set to whole encoder chunks: every chunk shares
            # one compiled shape (the pad rows are never gathered back)
            pad = (-u_ids.shape[0]) % min(batch_size, max(u_ids.shape[0], 1))
            if pad:
                u_ids = np.concatenate([u_ids, np.zeros((pad, length), u_ids.dtype)])
                u_mask = np.concatenate([u_mask, np.zeros((pad, length), u_mask.dtype)])
            outs = _embed(u_ids, u_mask)
            inverse = np.asarray(inverse, dtype=np.int32)
        else:
            outs = _embed(np.concatenate([pred_ids, tgt_ids], axis=0),
                          np.concatenate([pred_mask, tgt_mask], axis=0))
            inverse = np.arange(n_rows, dtype=np.int32)
        prf = _score_embeddings_packed(
            tuple(outs), jnp.asarray(inverse),
            jnp.asarray(pred_mmask), jnp.asarray(tgt_mmask), pred_w, tgt_w,
        )
    else:
        pred_emb = jnp.concatenate(_embed(pred_ids, pred_mask), axis=0)
        tgt_emb = jnp.concatenate(_embed(tgt_ids, tgt_mask), axis=0)
        prf = _score_embeddings_unfused(
            pred_emb, jnp.asarray(pred_mmask), tgt_emb, jnp.asarray(tgt_mmask), pred_w, tgt_w
        )
    return np.asarray(prf)


@jax.jit
def _score_embeddings_unfused(
    pred_emb: Array,
    pred_mask: Array,
    target_emb: Array,
    target_mask: Array,
    pred_weights: Optional[Array],
    target_weights: Optional[Array],
) -> Array:
    """Matching + result stacking for per-side embeddings (L_pred != L_ref)."""
    p, r, f1 = _bert_score_from_embeddings(
        pred_emb, pred_mask, target_emb, target_mask, pred_weights, target_weights
    )
    return jnp.stack([p, r, f1])


@jax.jit
def _score_embeddings_packed(
    emb_batches: Tuple[Array, ...],
    inverse: Array,
    pred_mask: Array,
    target_mask: Array,
    pred_weights: Optional[Array],
    target_weights: Optional[Array],
) -> Array:
    """Fuse gather/split/matching into one compiled call returning (3, N).

    ``inverse`` maps each pred/ref row to its embedding row — an identity
    arange for a fully-unique corpus (XLA folds the identity gather away), or
    the dedup mapping when distinct rows were encoded once.
    """
    emb_u = jnp.concatenate(emb_batches, axis=0) if len(emb_batches) > 1 else emb_batches[0]
    all_emb = emb_u[inverse]
    n_pred = pred_mask.shape[0]
    p, r, f1 = _bert_score_from_embeddings(
        all_emb[:n_pred], pred_mask, all_emb[n_pred:], target_mask, pred_weights, target_weights
    )
    return jnp.stack([p, r, f1])


def _resolve_baseline_path(
    rescale_with_baseline: bool, baseline_path: Optional[str], baseline_url: Optional[str]
) -> Optional[str]:
    """Reference API parity (``bert.py:384-411`` fetches the CSV from a url):
    this build has no network egress, so ``baseline_url`` is honored only when
    it is a local file (optionally ``file://``-prefixed). Like the reference,
    both knobs are ignored entirely unless rescaling is enabled."""
    if not rescale_with_baseline:
        return None
    if baseline_url is not None and baseline_path is None:
        local = baseline_url[7:] if baseline_url.startswith("file://") else baseline_url
        if not os.path.exists(local):
            raise ValueError(
                "`baseline_url` cannot be downloaded in this build; pass a local csv via "
                "`baseline_path` (or a file:// url)."
            )
        baseline_path = local
    if baseline_path is None:
        raise ValueError("Baseline rescaling requires a local `baseline_path` csv (no downloads in this build).")
    if not os.path.exists(baseline_path):
        raise ValueError(f"Baseline csv not found: {baseline_path!r}")
    return baseline_path


def _load_baseline_row(baseline_path: str, num_layers: Optional[int]) -> np.ndarray:
    table = np.atleast_2d(np.loadtxt(baseline_path, delimiter=",", skiprows=1))
    row = num_layers if num_layers is not None else -1
    if row >= table.shape[0]:
        raise ValueError(
            f"Baseline csv {baseline_path!r} has {table.shape[0]} rows; no row for num_layers={num_layers}."
        )
    return table[row][1:]


def _apply_baseline(precision, recall, f1, baseline: np.ndarray):
    precision = (precision - baseline[0]) / (1 - baseline[0])
    recall = (recall - baseline[1]) / (1 - baseline[1])
    f1 = (f1 - baseline[2]) / (1 - baseline[2])
    return precision, recall, f1


def bert_score(
    predictions: List[str],
    references: List[str],
    model_name_or_path: Optional[str] = None,
    num_layers: Optional[int] = None,
    all_layers: bool = False,
    model: Optional[Any] = None,
    user_tokenizer: Optional[Any] = None,
    user_forward_fn: Optional[Callable] = None,
    verbose: bool = False,
    idf: bool = False,
    device: Optional[str] = None,
    max_length: int = 128,
    batch_size: int = 64,
    num_threads: int = 4,
    return_hash: bool = False,
    lang: str = "en",
    rescale_with_baseline: bool = False,
    baseline_path: Optional[str] = None,
    baseline_url: Optional[str] = None,
    mesh: Optional[Any] = None,
    mesh_axis: Any = "dp",
) -> Dict[str, Union[List[float], str]]:
    """Compute BERTScore P/R/F1 per sentence pair.

    The encoder resolves in priority order: ``user_forward_fn`` (ids, mask) -> emb;
    ``model`` (a flax module apply-able on (ids, mask)); ``model_name_or_path`` (a
    LOCAL HF Flax checkpoint). Tokenization uses ``user_tokenizer`` (HF-compatible,
    ``__call__`` returning input_ids/attention_mask) or a whitespace fallback.

    To use a pretrained torch BERT offline, convert it once
    (``python tools/convert_weights.py bert <torch_dir> <flax_dir>``) and pass
    ``model_name_or_path=<flax_dir>`` with its tokenizer — the full local pipeline
    is exercised in ``tests/text/test_bert_e2e.py``.

    ``mesh=`` shards the encoder batch over the mesh's ``mesh_axis`` (params
    replicated) so the embedding forward scales data-parallel; sharded ==
    single-device parity is proven in ``tests/parallel/test_sharded_embedded.py``.
    """
    if len(predictions) != len(references):
        raise ValueError("Number of predicted and reference sentences must be the same!")
    baseline_path = _resolve_baseline_path(rescale_with_baseline, baseline_path, baseline_url)

    # ---- tokenize (host): each DISTINCT sentence once — corpora with shared
    # references / repeated candidates pay the tokenizer per unique text, and
    # one pooled call gives both sides a common padded geometry (fused path)
    texts = list(predictions) + list(references)
    uniq_of: Dict[str, int] = {}
    inverse = np.empty(len(texts), dtype=np.int64)
    uniq_texts: List[str] = []
    for i, s in enumerate(texts):
        j = uniq_of.setdefault(s, len(uniq_texts))
        if j == len(uniq_texts):
            uniq_texts.append(s)
        inverse[i] = j
    if user_tokenizer is not None:
        enc = user_tokenizer(uniq_texts, max_length)
    else:
        enc = _simple_whitespace_tokenizer(uniq_texts, max_length)
    ids_u, mask_u = np.asarray(enc["input_ids"]), np.asarray(enc["attention_mask"])
    n = len(predictions)
    pred_ids, pred_mask = ids_u[inverse[:n]], mask_u[inverse[:n]]
    tgt_ids, tgt_mask = ids_u[inverse[n:]], mask_u[inverse[n:]]

    forward = _resolve_forward(user_forward_fn, model, model_name_or_path, mesh, mesh_axis)
    precision, recall, f1 = _score_tokenized(
        forward, pred_ids, pred_mask, tgt_ids, tgt_mask, idf=idf, batch_size=batch_size,
        dedup=(ids_u, mask_u, inverse),  # text-level structure, computed above
        # the whitespace fallback adds no [CLS]/[SEP]; real tokenizers do
        strip_special=user_tokenizer is not None,
    )

    if rescale_with_baseline:
        precision, recall, f1 = _apply_baseline(
            precision, recall, f1, _load_baseline_row(baseline_path, num_layers)
        )

    output: Dict[str, Union[List[float], str]] = {
        "precision": [float(x) for x in precision],
        "recall": [float(x) for x in recall],
        "f1": [float(x) for x in f1],
    }
    if return_hash:
        output["hash"] = f"metrics_tpu-bert_score-{model_name_or_path}"
    return output
