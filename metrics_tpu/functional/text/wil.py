"""Word information lost.

Parity: reference ``torchmetrics/functional/text/wil.py`` (including the
errors-minus-total trick standing in for the hit count, squared away in compute).
"""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distance_batch

Array = jax.Array


def _wil_update(
    predictions: Union[str, List[str]], references: Union[str, List[str]]
) -> Tuple[Array, Array, Array]:
    if isinstance(predictions, str):
        predictions = [predictions]
    if isinstance(references, str):
        references = [references]
    pred_tokens = [p.split() for p in predictions]
    ref_tokens = [r.split() for r in references]
    errors = float(_edit_distance_batch(pred_tokens, ref_tokens).sum())
    reference_total = float(sum(len(r) for r in ref_tokens))
    prediction_total = float(sum(len(p) for p in pred_tokens))
    total = float(sum(max(len(r), len(p)) for p, r in zip(pred_tokens, ref_tokens)))
    return jnp.asarray(errors - total), jnp.asarray(reference_total), jnp.asarray(prediction_total)


def _wil_compute(errors: Array, reference_total: Array, prediction_total: Array) -> Array:
    return 1 - ((errors / reference_total) * (errors / prediction_total))


def word_information_lost(predictions: Union[str, List[str]], references: Union[str, List[str]]) -> Array:
    """WIL = 1 - (H/N_ref)(H/N_pred)."""
    errors, reference_total, prediction_total = _wil_update(predictions, references)
    return _wil_compute(errors, reference_total, prediction_total)
