"""SacreBLEU: BLEU with canonical tokenizers (13a / intl / char / zh / ja).

Parity: reference ``torchmetrics/functional/text/sacre_bleu.py`` (361 LoC;
_SacreBLEUTokenizer with the mteval-v13a and international tokenizers). zh/ja
tokenizers require external segmenters (mecab) and are gated like the reference.
"""
import re
from functools import partial
from typing import Sequence, Union

import jax

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update, bleu_score
from metrics_tpu.utils.imports import _REGEX_AVAILABLE

Array = jax.Array

AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char")


class _SacreBLEUTokenizer:
    """Canonical sacrebleu tokenizers. Parity: reference ``sacre_bleu.py:45-200``."""

    _REGEX_13A = (
        # language-independent part of mteval-v13a
        (re.compile(r"<skipped>"), ""),
        (re.compile(r"-\n"), ""),
        (re.compile(r"\n"), " "),
        (re.compile(r"&quot;"), '"'),
        (re.compile(r"&amp;"), "&"),
        (re.compile(r"&lt;"), "<"),
        (re.compile(r"&gt;"), ">"),
    )
    _REGEX_13A_TOK = (
        (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
        (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
        (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
        (re.compile(r"([0-9])(-)"), r"\1 \2 "),
    )

    def __init__(self, tokenize: str = "13a", lowercase: bool = False) -> None:
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
        self.tokenize_name = tokenize
        self.lowercase = lowercase
        if tokenize == "intl" and not _REGEX_AVAILABLE:
            raise ModuleNotFoundError(
                "`intl` tokenization requires the `regex` package (unicode property classes)."
            )
        if tokenize == "zh":
            raise ModuleNotFoundError(
                "`zh` tokenization requires a Chinese segmenter which is not available in this build."
            )

    def __call__(self, line: str) -> Sequence[str]:
        if self.lowercase:
            line = line.lower()
        if self.tokenize_name == "none":
            return line.split()
        if self.tokenize_name == "13a":
            return self._tokenize_13a(line)
        if self.tokenize_name == "char":
            return self._tokenize_char(line)
        if self.tokenize_name == "intl":
            return self._tokenize_intl(line)
        raise ValueError(f"Unsupported tokenizer {self.tokenize_name}")

    @classmethod
    def _tokenize_13a(cls, line: str) -> Sequence[str]:
        for pattern, replacement in cls._REGEX_13A:
            line = pattern.sub(replacement, line)
        norm = f" {line} "
        for pattern, replacement in cls._REGEX_13A_TOK:
            norm = pattern.sub(replacement, norm)
        return norm.split()

    @staticmethod
    def _tokenize_char(line: str) -> Sequence[str]:
        # every char is a token; whitespace chars drop out (sacrebleu semantics)
        return [ch for ch in line if not ch.isspace()]

    @staticmethod
    def _tokenize_intl(line: str) -> Sequence[str]:
        import regex

        line = regex.sub(r"(\p{P})(\P{N})", r" \1 \2", line)
        line = regex.sub(r"(\P{N})(\p{P})", r"\1 \2 ", line)
        return line.split()


def sacre_bleu_score(
    translate_corpus: Sequence[str],
    reference_corpus: Sequence[Sequence[str]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
) -> Array:
    """BLEU with a sacrebleu tokenizer. Parity: reference ``sacre_bleu_score:220+``."""
    import jax.numpy as jnp

    tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)
    translate_corpus_ = [translate_corpus] if isinstance(translate_corpus, str) else list(translate_corpus)
    reference_corpus_ = [
        [ref] if isinstance(ref, str) else list(ref) for ref in reference_corpus
    ]
    if len(translate_corpus_) != len(reference_corpus_):
        raise ValueError(f"Corpus has different size {len(translate_corpus_)} != {len(reference_corpus_)}")

    numerator = jnp.zeros(n_gram)
    denominator = jnp.zeros(n_gram)
    trans_len = jnp.asarray(0.0)
    ref_len = jnp.asarray(0.0)
    trans_len, ref_len, numerator, denominator = _bleu_score_update(
        translate_corpus_, reference_corpus_, numerator, denominator, trans_len, ref_len, n_gram,
        tokenizer=tokenizer,
    )
    return _bleu_score_compute(trans_len, ref_len, numerator, denominator, n_gram, smooth)
