"""SacreBLEU: BLEU with canonical tokenizers (13a / intl / char / zh).

Parity: reference ``torchmetrics/functional/text/sacre_bleu.py`` (361 LoC;
_SacreBLEUTokenizer with the mteval-v13a, international, and zh tokenizers).
``zh`` needs no external segmenter: each CJK character (by unicode block) is
split out as its own token and the non-Chinese remainder goes through the 13a
regexes (reference ``sacre_bleu.py:203-229``). Only ``ja-mecab`` (which does
need mecab) is out of scope.
"""
import re
from functools import partial
from typing import Sequence, Union

import jax

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update, bleu_score
from metrics_tpu.utils.imports import _REGEX_AVAILABLE

Array = jax.Array

AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char")


class _SacreBLEUTokenizer:
    """Canonical sacrebleu tokenizers. Parity: reference ``sacre_bleu.py:45-200``."""

    _REGEX_13A = (
        # language-independent part of mteval-v13a
        (re.compile(r"<skipped>"), ""),
        (re.compile(r"-\n"), ""),
        (re.compile(r"\n"), " "),
        (re.compile(r"&quot;"), '"'),
        (re.compile(r"&amp;"), "&"),
        (re.compile(r"&lt;"), "<"),
        (re.compile(r"&gt;"), ">"),
    )
    _REGEX_13A_TOK = (
        (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
        (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
        (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
        (re.compile(r"([0-9])(-)"), r"\1 \2 "),
    )
    # The EFFECTIVE char set of sacrebleu's TokenizerZh._is_chinese_char (and the
    # reference's copy of it, sacre_bleu.py:153-164): its range table compares
    # python strings, and the two "UTF16" entries are 5-char literals, so the
    # real behavior is [U+2001-U+2A6D] (not CJK Ext B, which is never matched)
    # plus the BMP blocks. Derived by brute-forcing every code point against the
    # oracle; parity requires the quirk, not the nominal block list.
    _ZH_CHAR = re.compile(
        "(["
        "\u2001-\u2a6d"  # quirk: the "\u20000"-"\u2a6d6" string-compare entry
        "\u2e80-\u2fdf"  # CJK radicals + Kangxi radicals
        "\u2ff0-\u303f"  # ideographic description + CJK punctuation
        "\u3100-\u312f"  # bopomofo
        "\u31a0-\u31ef"  # bopomofo extended + CJK strokes
        "\u3200-\u4db5"  # enclosed CJK + compatibility + Ext A
        "\u4e00-\u9fbb"  # CJK Unified Ideographs
        "\uf900-\ufa2d\ufa30-\ufa6a\ufa70-\ufad9"  # compatibility ideographs
        "\ufe10-\ufe1f\ufe30-\ufe4f"  # vertical/compatibility forms
        "\uff00-\uffef"  # full-width forms
        "])"
    )

    def __init__(self, tokenize: str = "13a", lowercase: bool = False) -> None:
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
        self.tokenize_name = tokenize
        self.lowercase = lowercase
        if tokenize == "intl" and not _REGEX_AVAILABLE:
            raise ModuleNotFoundError(
                "`intl` tokenization requires the `regex` package (unicode property classes)."
            )

    def __call__(self, line: str) -> Sequence[str]:
        if self.lowercase:
            line = line.lower()
        if self.tokenize_name == "none":
            return line.split()
        if self.tokenize_name == "13a":
            return self._tokenize_13a(line)
        if self.tokenize_name == "char":
            return self._tokenize_char(line)
        if self.tokenize_name == "intl":
            return self._tokenize_intl(line)
        if self.tokenize_name == "zh":
            return self._tokenize_zh(line)
        raise ValueError(f"Unsupported tokenizer {self.tokenize_name}")

    @classmethod
    def _tokenize_13a(cls, line: str) -> Sequence[str]:
        for pattern, replacement in cls._REGEX_13A:
            line = pattern.sub(replacement, line)
        norm = f" {line} "
        for pattern, replacement in cls._REGEX_13A_TOK:
            norm = pattern.sub(replacement, norm)
        return norm.split()

    @staticmethod
    def _tokenize_char(line: str) -> Sequence[str]:
        # every char is a token; whitespace chars drop out (sacrebleu semantics)
        return [ch for ch in line if not ch.isspace()]

    @staticmethod
    def _tokenize_intl(line: str) -> Sequence[str]:
        # mteval-v14 international: split punctuation off non-digit neighbors,
        # then isolate symbols — rule order follows sacrebleu's TokenizerV14
        import regex

        line = regex.sub(r"(\P{N})(\p{P})", r"\1 \2 ", line)
        line = regex.sub(r"(\p{P})(\P{N})", r" \1 \2", line)
        line = regex.sub(r"(\p{S})", r" \1 ", line)
        return line.split()

    @classmethod
    def _tokenize_zh(cls, line: str) -> Sequence[str]:
        # Isolate every CJK character, then run the non-Chinese remainder
        # through the 13a language-dependent regexes. No segmenter needed
        # (reference sacre_bleu.py:203-229). Unlike 13a, zh applies NO space
        # padding around the line (sacrebleu calls TokenizerRegexp directly),
        # so leading ".5" stays one token here.
        norm = cls._ZH_CHAR.sub(r" \1 ", line.strip())
        for pattern, replacement in cls._REGEX_13A_TOK:
            norm = pattern.sub(replacement, norm)
        return norm.split()


def sacre_bleu_score(
    translate_corpus: Sequence[str],
    reference_corpus: Sequence[Sequence[str]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
) -> Array:
    """BLEU with a sacrebleu tokenizer. Parity: reference ``sacre_bleu_score:220+``."""
    import jax.numpy as jnp

    tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)
    translate_corpus_ = [translate_corpus] if isinstance(translate_corpus, str) else list(translate_corpus)
    reference_corpus_ = [
        [ref] if isinstance(ref, str) else list(ref) for ref in reference_corpus
    ]
    if len(translate_corpus_) != len(reference_corpus_):
        raise ValueError(f"Corpus has different size {len(translate_corpus_)} != {len(reference_corpus_)}")

    numerator = jnp.zeros(n_gram)
    denominator = jnp.zeros(n_gram)
    trans_len = jnp.asarray(0.0)
    ref_len = jnp.asarray(0.0)
    trans_len, ref_len, numerator, denominator = _bleu_score_update(
        translate_corpus_, reference_corpus_, numerator, denominator, trans_len, ref_len, n_gram,
        tokenizer=tokenizer,
    )
    return _bleu_score_compute(trans_len, ref_len, numerator, denominator, n_gram, smooth)
