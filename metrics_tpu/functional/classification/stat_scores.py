"""True/false positive/negative counting — the backbone of classification metrics.

Parity: reference ``torchmetrics/functional/classification/stat_scores.py``
(_stat_scores :28, _stat_scores_update :76, _stat_scores_compute :148,
_reduce_stat_scores :183, stat_scores :240). Same reduce/mdmc_reduce/ignore_index
semantics and output shapes.

TPU notes: all counting is one fused elementwise+reduce per statistic (XLA fuses the
compare/multiply/sum chain into a single kernel); the canonical (N, C[, X]) layout
keeps reductions along contiguous axes. ``ignore_index`` column removal uses static
slicing (python-int index), so everything traces under jit.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


def _del_column(data: Array, idx: int) -> Array:
    return jnp.concatenate([data[:, :idx], data[:, idx + 1:]], axis=1)


def _stat_scores(preds: Array, target: Array, reduce: Optional[str] = "micro") -> Tuple[Array, Array, Array, Array]:
    """Count tp/fp/tn/fn over canonical (N, C[, X]) binary tensors.

    Output shapes (parity with reference :48-56): (N,C): micro->(), macro->(C,),
    samples->(N,); (N,C,X): micro->(N,), macro->(N,C), samples->(N,X).
    """
    if reduce == "micro":
        dim = (0, 1) if preds.ndim == 2 else (1, 2)
    elif reduce == "macro":
        dim = 0 if preds.ndim == 2 else 2
    else:  # samples
        dim = 1

    true_pred = target == preds
    false_pred = target != preds
    pos_pred = preds == 1
    neg_pred = preds == 0

    tp = jnp.sum(true_pred & pos_pred, axis=dim)
    fp = jnp.sum(false_pred & pos_pred, axis=dim)
    tn = jnp.sum(true_pred & neg_pred, axis=dim)
    fn = jnp.sum(false_pred & neg_pred, axis=dim)
    i64 = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return tp.astype(i64), fp.astype(i64), tn.astype(i64), fn.astype(i64)


def _stat_scores_update(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = None,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Canonicalize inputs and count statistics. Parity: reference ``:76-145``."""
    preds, target, _ = _input_format_classification(
        preds, target, threshold=threshold, num_classes=num_classes, multiclass=multiclass, top_k=top_k
    )

    if ignore_index is not None and not 0 <= ignore_index < preds.shape[1]:
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {preds.shape[1]} classes")
    if ignore_index is not None and preds.shape[1] == 1:
        raise ValueError("You can not use `ignore_index` with binary data.")

    if preds.ndim == 3:
        if not mdmc_reduce:
            raise ValueError(
                "When your inputs are multi-dimensional multi-class, you have to set the `mdmc_reduce` parameter"
            )
        if mdmc_reduce == "global":
            preds = jnp.swapaxes(preds, 1, 2).reshape(-1, preds.shape[1])
            target = jnp.swapaxes(target, 1, 2).reshape(-1, target.shape[1])

    if ignore_index is not None and reduce != "macro":
        preds = _del_column(preds, ignore_index)
        target = _del_column(target, ignore_index)

    tp, fp, tn, fn = _stat_scores(preds, target, reduce=reduce)

    if ignore_index is not None and reduce == "macro":
        tp = tp.at[..., ignore_index].set(-1)
        fp = fp.at[..., ignore_index].set(-1)
        tn = tn.at[..., ignore_index].set(-1)
        fn = fn.at[..., ignore_index].set(-1)

    return tp, fp, tn, fn


def _stat_scores_compute(tp: Array, fp: Array, tn: Array, fn: Array) -> Array:
    """Stack [tp, fp, tn, fn, support] along the last dim. Parity: ``:148-180``."""
    outputs = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    return jnp.where(outputs < 0, -1, outputs)


def _reduce_stat_scores(
    numerator: Array,
    denominator: Array,
    weights: Optional[Array],
    average: Optional[str],
    mdmc_average: Optional[str],
    zero_division: int = 0,
) -> Array:
    """Weighted num/denom reduction with zero-division and ignored-class (-1) masking.

    Parity: reference ``:183-237``.
    """
    numerator = numerator.astype(jnp.float32) if not jnp.issubdtype(numerator.dtype, jnp.floating) else numerator
    denominator = denominator.astype(numerator.dtype) if not jnp.issubdtype(denominator.dtype, jnp.floating) else denominator
    zero_div_mask = denominator == 0
    ignore_mask = denominator < 0

    weights = jnp.ones_like(denominator) if weights is None else weights.astype(denominator.dtype)
    numerator = jnp.where(zero_div_mask, float(zero_division), numerator)
    denominator = jnp.where(zero_div_mask | ignore_mask, 1.0, denominator)
    weights = jnp.where(ignore_mask, 0.0, weights)

    if average not in (AverageMethod.MICRO, AverageMethod.NONE, None):
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)

    scores = weights * (numerator / denominator)
    scores = jnp.where(jnp.isnan(scores), float(zero_division), scores)

    if mdmc_average == MDMCAverageMethod.SAMPLEWISE:
        scores = jnp.mean(scores, axis=0)
        ignore_mask = jnp.sum(ignore_mask, axis=0).astype(bool)

    if average in (AverageMethod.NONE, None):
        scores = jnp.where(ignore_mask, jnp.nan, scores)
    else:
        scores = jnp.sum(scores)
    return scores


def stat_scores(
    preds: Array,
    target: Array,
    reduce: str = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = None,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Compute [tp, fp, tn, fn, support]. Parity: reference ``stat_scores:240-397``."""
    if reduce not in ["micro", "macro", "samples"]:
        raise ValueError(f"The `reduce` {reduce} is not valid.")
    if mdmc_reduce not in [None, "samplewise", "global"]:
        raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
    if reduce == "macro" and (not num_classes or num_classes < 1):
        raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_reduce,
        top_k=top_k,
        threshold=threshold,
        num_classes=num_classes,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _stat_scores_compute(tp, fp, tn, fn)
