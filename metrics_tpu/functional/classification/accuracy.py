"""Accuracy (incl. top-k and subset accuracy).

Parity: reference ``torchmetrics/functional/classification/accuracy.py`` (_mode :29,
_accuracy_update :64, _accuracy_compute :117, _subset_accuracy_update :207,
accuracy :259-419). Same average/mdmc_average/subset semantics.

TPU note: the reference drops absent classes with boolean-mask indexing
(``numerator[~cond]`` — dynamic shapes, jit-hostile). Here absent classes are marked
with a -1 denominator instead, which ``_reduce_stat_scores`` already treats as
"ignored" (weight 0, renormalised) — numerically identical, fully static shapes.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import _reduce_stat_scores, _stat_scores_update
from metrics_tpu.utils.checks import _check_classification_inputs, _input_format_classification, _input_squeeze
from metrics_tpu.utils.enums import AverageMethod, DataType, MDMCAverageMethod

Array = jax.Array


def _check_subset_validity(mode: DataType) -> bool:
    return mode in (DataType.MULTILABEL, DataType.MULTIDIM_MULTICLASS)


def _mode(
    preds: Array,
    target: Array,
    threshold: float,
    top_k: Optional[int],
    num_classes: Optional[int],
    multiclass: Optional[bool],
) -> DataType:
    return _check_classification_inputs(
        preds, target, threshold=threshold, top_k=top_k, num_classes=num_classes, multiclass=multiclass
    )


def _accuracy_update(
    preds: Array,
    target: Array,
    reduce: Optional[str],
    mdmc_reduce: Optional[str],
    threshold: float,
    num_classes: Optional[int],
    top_k: Optional[int],
    multiclass: Optional[bool],
    ignore_index: Optional[int],
    mode: DataType,
) -> Tuple[Array, Array, Array, Array]:
    if mode == DataType.MULTILABEL and top_k:
        raise ValueError("You can not use the `top_k` parameter to calculate accuracy for multi-label inputs.")
    preds, target = _input_squeeze(jnp.asarray(preds), jnp.asarray(target))
    return _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_reduce,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )


def _accuracy_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    mdmc_average: Optional[str],
    mode: DataType,
) -> Array:
    simple_average = (AverageMethod.MICRO, AverageMethod.SAMPLES)
    if (mode == DataType.BINARY and average in simple_average) or mode == DataType.MULTILABEL:
        numerator = tp + tn
        denominator = tp + tn + fp + fn
    else:
        numerator = tp
        denominator = tp + fn

    if average == AverageMethod.MACRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        # absent classes (tp+fp+fn==0): mark ignored via -1 denominator (static-shape
        # equivalent of the reference's boolean-mask drop)
        cond = (tp + fp + fn) == 0
        numerator = jnp.where(cond, 0, numerator)
        denominator = jnp.where(cond, -1, denominator)

    if average == AverageMethod.NONE and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        meaningless = (tp | fn | fp) == 0
        numerator = jnp.where(meaningless, -1, numerator)
        denominator = jnp.where(meaningless, -1, denominator)

    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else (tp + fn),
        average=average,
        mdmc_average=mdmc_average,
    )


def _subset_accuracy_update(
    preds: Array,
    target: Array,
    threshold: float,
    top_k: Optional[int],
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Tuple[Array, Array]:
    # num_classes/multiclass forward to the input layer: inferring the class
    # count from data values is impossible under jit (the TPU contract), so
    # subset accuracy must accept the same static hints as the stat-score path
    preds, target = _input_squeeze(jnp.asarray(preds), jnp.asarray(target))
    preds, target, mode = _input_format_classification(
        preds, target, threshold=threshold, top_k=top_k, num_classes=num_classes, multiclass=multiclass
    )

    if mode == DataType.MULTILABEL and top_k:
        raise ValueError("You can not use the `top_k` parameter to calculate accuracy for multi-label inputs.")

    if mode == DataType.MULTILABEL:
        correct = jnp.sum(jnp.all(preds == target, axis=1))
        total = jnp.asarray(target.shape[0])
    elif mode == DataType.MULTICLASS:
        correct = jnp.sum(preds * target)
        total = jnp.sum(target)
    elif mode == DataType.MULTIDIM_MULTICLASS:
        sample_correct = jnp.sum(preds * target, axis=(1, 2))
        correct = jnp.sum(sample_correct == target.shape[2])
        total = jnp.asarray(target.shape[0])
    else:
        correct, total = jnp.asarray(0), jnp.asarray(0)
    return correct, total


def _subset_accuracy_compute(correct: Array, total: Array) -> Array:
    return correct.astype(jnp.float32) / total


def accuracy(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = "global",
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    subset_accuracy: bool = False,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Compute accuracy. Parity: reference ``accuracy:259-419``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import accuracy
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> print(f"{float(accuracy(preds, target)):.4f}")
        0.7500
    """
    allowed_average = ["micro", "macro", "weighted", "samples", "none", None]
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    if average in ["macro", "weighted", "none", None] and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    allowed_mdmc_average = [None, "samplewise", "global"]
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")
    if top_k is not None and (not isinstance(top_k, int) or top_k <= 0):
        raise ValueError(f"The `top_k` should be an integer larger than 0, got {top_k}")

    preds, target = _input_squeeze(jnp.asarray(preds), jnp.asarray(target))
    mode = _mode(preds, target, threshold, top_k, num_classes, multiclass)
    reduce = "macro" if average in ["weighted", "none", None] else average

    if subset_accuracy and _check_subset_validity(mode):
        correct, total = _subset_accuracy_update(preds, target, threshold, top_k, num_classes, multiclass)
        return _subset_accuracy_compute(correct, total)
    tp, fp, tn, fn = _accuracy_update(
        preds, target, reduce, mdmc_average, threshold, num_classes, top_k, multiclass, ignore_index, mode
    )
    return _accuracy_compute(tp, fp, tn, fn, average, mdmc_average, mode)
