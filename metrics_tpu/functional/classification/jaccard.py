"""Jaccard index (IoU).

Parity: reference ``torchmetrics/functional/classification/jaccard.py``
(_jaccard_from_confmat :23, jaccard_index :69). The reference's post-hoc class
removal for ``ignore_index`` becomes a mask + renormalised mean (static shapes).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.confusion_matrix import _confusion_matrix_update
from metrics_tpu.parallel.collectives import reduce

Array = jax.Array


def _jaccard_from_confmat(
    confmat: Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
    absent_score: float = 0.0,
    reduction: str = "elementwise_mean",
) -> Array:
    if ignore_index is not None and 0 <= ignore_index < num_classes:
        confmat = confmat.at[ignore_index].set(0.0)

    intersection = jnp.diag(confmat)
    union = jnp.sum(confmat, axis=0) + jnp.sum(confmat, axis=1) - intersection

    scores = intersection.astype(jnp.float32) / union.astype(jnp.float32)
    scores = jnp.where(union == 0, absent_score, scores)

    if ignore_index is not None and 0 <= ignore_index < num_classes:
        scores = jnp.concatenate([scores[:ignore_index], scores[ignore_index + 1:]])
    return reduce(scores, reduction=reduction)


def jaccard_index(
    preds: Array,
    target: Array,
    ignore_index: Optional[int] = None,
    absent_score: float = 0.0,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    reduction: str = "elementwise_mean",
) -> Array:
    """Compute the Jaccard index. Parity: reference ``jaccard_index:69-151``."""
    if num_classes is None:
        num_classes = int(max(jnp.max(preds), jnp.max(target))) + 1 if preds.ndim == target.ndim else preds.shape[1]
        num_classes = max(2, num_classes)
    confmat = _confusion_matrix_update(preds, target, num_classes, threshold)
    return _jaccard_from_confmat(confmat, num_classes, ignore_index, absent_score, reduction)
