"""Confusion matrix (binary / multiclass / multilabel).

Parity: reference ``torchmetrics/functional/classification/confusion_matrix.py``
(_confusion_matrix_update :25, _confusion_matrix_compute :56, confusion_matrix :119).

TPU note: the bincount over ``target*C + preds`` goes through the kernel
dispatcher (``utils/data.py::_bincount`` → ``metrics_tpu/ops/kernels``): a
scatter-free streaming Pallas histogram on TPU, XLA's fixed-length
``jnp.bincount`` scatter-add elsewhere; ``minlength`` is static so shapes stay
fixed under jit either way.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.data import _bincount
from metrics_tpu.utils.enums import DataType
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


def _confusion_matrix_update(
    preds: Array, target: Array, num_classes: int, threshold: float = 0.5, multilabel: bool = False
) -> Array:
    preds = jnp.asarray(preds)
    # integer label inputs cannot infer the class count from data under jit —
    # forward the ctor's num_classes as the formatter hint. Float inputs must
    # NOT get it: the CM meaning of num_classes=2 is a 2x2 matrix over BINARY
    # data, which the formatter would reject as a 2-class hint. The one-hot
    # detour integer hints take yields identical bincounts.
    is_int = not jnp.issubdtype(preds.dtype, jnp.floating)
    preds, target, mode = _input_format_classification(
        preds, target, threshold,
        num_classes=num_classes if is_int else None,
        multiclass=False if (multilabel and is_int) else None,
    )
    if multilabel:
        # user-declared multilabel layout: the canonical (N, C) indicators ARE
        # the per-label predictions — argmax would collapse them to one class
        unique_mapping = jnp.ravel(2 * target + preds + 4 * jnp.arange(num_classes))
        minlength = 4 * num_classes
    else:
        if mode not in (DataType.BINARY, DataType.MULTILABEL):
            preds = jnp.argmax(preds, axis=1)
            target = jnp.argmax(target, axis=1)
        unique_mapping = jnp.ravel(target) * num_classes + jnp.ravel(preds)
        minlength = num_classes ** 2

    bins = _bincount(unique_mapping, minlength)
    if multilabel:
        return bins.reshape(num_classes, 2, 2)
    return bins.reshape(num_classes, num_classes)


def _confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument average needs to one of the following: {allowed_normalize}")
    if normalize is not None and normalize != "none":
        confmat = confmat.astype(jnp.float32) if not jnp.issubdtype(confmat.dtype, jnp.floating) else confmat
        if normalize == "true":
            confmat = confmat / jnp.sum(confmat, axis=1, keepdims=True)
        elif normalize == "pred":
            confmat = confmat / jnp.sum(confmat, axis=0, keepdims=True)
        elif normalize == "all":
            confmat = confmat / jnp.sum(confmat)
        confmat = jnp.where(jnp.isnan(confmat), 0.0, confmat)
    return confmat


def confusion_matrix(
    preds: Array,
    target: Array,
    num_classes: int,
    normalize: Optional[str] = None,
    threshold: float = 0.5,
    multilabel: bool = False,
) -> Array:
    """Compute the (C,C) (or (C,2,2) multilabel) confusion matrix. Parity: ``:119-186``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import confusion_matrix
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> confusion_matrix(preds, target, num_classes=2).tolist()
        [[2, 0], [1, 1]]
    """
    confmat = _confusion_matrix_update(preds, target, num_classes, threshold, multilabel)
    return _confusion_matrix_compute(confmat, normalize)
