"""Matthews correlation coefficient.

Parity: reference ``torchmetrics/functional/classification/matthews_corrcoef.py``
(_matthews_corrcoef_compute :22, matthews_corrcoef :44).
"""
import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.confusion_matrix import _confusion_matrix_update

Array = jax.Array

_matthews_corrcoef_update = _confusion_matrix_update


def _matthews_corrcoef_compute(confmat: Array) -> Array:
    tk = jnp.sum(confmat, axis=1).astype(jnp.float32)
    pk = jnp.sum(confmat, axis=0).astype(jnp.float32)
    c = jnp.trace(confmat).astype(jnp.float32)
    s = jnp.sum(confmat).astype(jnp.float32)
    return (c * s - jnp.sum(tk * pk)) / (jnp.sqrt(s ** 2 - jnp.sum(pk * pk)) * jnp.sqrt(s ** 2 - jnp.sum(tk * tk)))


def matthews_corrcoef(preds: Array, target: Array, num_classes: int, threshold: float = 0.5) -> Array:
    """Compute MCC. Parity: reference ``matthews_corrcoef:44-89``."""
    confmat = _matthews_corrcoef_update(preds, target, num_classes, threshold)
    return _matthews_corrcoef_compute(confmat)
