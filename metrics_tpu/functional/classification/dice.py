"""Dice score.

Parity: reference ``torchmetrics/functional/classification/dice.py:54``
(``dice_score``). The reference loops over classes in Python with
data-dependent branches (``(target == i).any()``, ``torch.is_nonzero``); here
the whole thing is one vectorized one-hot comparison over a static class axis —
jit-safe, no host round-trips, and the per-class tp/fp/fn reduce on device.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.parallel.collectives import reduce
from metrics_tpu.utils.data import to_categorical

Array = jax.Array


def dice_score(
    preds: Array,
    target: Array,
    bg: bool = False,
    nan_score: float = 0.0,
    no_fg_score: float = 0.0,
    reduction: str = "elementwise_mean",
) -> Array:
    """Compute dice score from prediction scores.

    Args:
        preds: estimated probabilities with a class axis: ``(N, C)`` or ``(N, C, ...)``
        target: ground-truth labels ``(N, ...)``
        bg: whether to also compute dice for the background class (index 0)
        nan_score: score to return when the denominator (2*tp+fp+fn) is zero
        no_fg_score: score to return for a class absent from ``target``
        reduction: ``'elementwise_mean'`` | ``'sum'`` | ``'none'``

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import dice_score
        >>> preds = jnp.asarray([[0.1, 0.9], [0.8, 0.2]])
        >>> target = jnp.asarray([1, 0])
        >>> print(f"{float(dice_score(preds, target)):.4f}")
        1.0000
    """
    if preds.ndim < 2:
        raise ValueError(
            "`dice_score` expects `preds` with a class dimension at axis 1 "
            f"(probabilities of shape (N, C, ...)), got shape {preds.shape}."
        )
    num_classes = preds.shape[1]
    if preds.ndim == target.ndim + 1:
        preds = to_categorical(preds, argmax_dim=1)

    start = 0 if bg else 1
    classes = jnp.arange(start, num_classes)
    shape = (-1,) + (1,) * preds.ndim
    p = preds[None] == classes.reshape(shape)
    t = target[None] == classes.reshape(shape)
    axes = tuple(range(1, p.ndim))
    tp = jnp.sum(p & t, axis=axes)
    fp = jnp.sum(p & ~t, axis=axes)
    fn = jnp.sum(~p & t, axis=axes)
    support = jnp.sum(t, axis=axes)

    denom = (2 * tp + fp + fn).astype(jnp.float32)
    scores = jnp.where(denom > 0, 2.0 * tp / jnp.maximum(denom, 1.0), nan_score)
    scores = jnp.where(support > 0, scores, no_fg_score)
    return reduce(scores, reduction=reduction)
