"""Hamming distance.

Parity: reference ``torchmetrics/functional/classification/hamming_distance.py``
(_hamming_distance_update :23, _hamming_distance_compute :45, hamming_distance :63).
"""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _input_format_classification

Array = jax.Array


def _hamming_distance_update(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Tuple[Array, int]:
    # num_classes/multiclass are this build's static-shape hints (not in the
    # reference signature): integer label inputs under jit cannot infer the
    # class count from data values
    preds, target, _ = _input_format_classification(
        preds, target, threshold=threshold, num_classes=num_classes, multiclass=multiclass
    )
    correct = jnp.sum(preds == target)
    total = preds.size
    return correct, total


def _hamming_distance_compute(correct: Array, total: Union[int, Array]) -> Array:
    return 1 - correct.astype(jnp.float32) / total


def hamming_distance(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """Compute the average Hamming distance / loss. Parity: reference ``:63-107``
    (plus this build's optional static num_classes/multiclass hints for jit)."""
    correct, total = _hamming_distance_update(preds, target, threshold, num_classes, multiclass)
    return _hamming_distance_compute(correct, total)
