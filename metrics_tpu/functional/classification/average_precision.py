"""Average precision score.

Parity: reference ``torchmetrics/functional/classification/average_precision.py``
(_average_precision_update :28, _average_precision_compute :57,
_average_precision_compute_with_precision_recall :100, average_precision :147).
"""
import warnings
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.precision_recall_curve import (
    _precision_recall_curve_compute,
    _precision_recall_curve_update,
)
from metrics_tpu.utils.data import _bincount

Array = jax.Array


def _average_precision_update(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
) -> Tuple[Array, Array, int, Optional[int]]:
    preds, target, num_classes, pos_label = _precision_recall_curve_update(preds, target, num_classes, pos_label)
    if average == "micro":
        if preds.ndim == target.ndim:
            preds = jnp.ravel(preds)
            target = jnp.ravel(target)
            num_classes = 1
        else:
            raise ValueError("Cannot use `micro` average with multi-class input")
    return preds, target, num_classes, pos_label


def _average_precision_compute(
    preds: Array,
    target: Array,
    num_classes: int,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    sample_weights: Optional[Sequence] = None,
) -> Union[List[Array], Array]:
    precision, recall, _ = _precision_recall_curve_compute(preds, target, num_classes, pos_label)
    if average == "weighted":
        if preds.ndim == target.ndim and target.ndim > 1:
            weights = jnp.sum(target, axis=0).astype(jnp.float32)
        else:
            weights = _bincount(target, num_classes).astype(jnp.float32)
        weights = weights / jnp.sum(weights)
    else:
        weights = None
    return _average_precision_compute_with_precision_recall(precision, recall, num_classes, average, weights)


def _average_precision_compute_with_precision_recall(
    precision: Union[Array, List[Array]],
    recall: Union[Array, List[Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Union[List[Array], Array]:
    if num_classes == 1:
        return -jnp.sum((recall[1:] - recall[:-1]) * precision[:-1])

    res = [-jnp.sum((r[1:] - r[:-1]) * p[:-1]) for p, r in zip(precision, recall)]

    if average in ("macro", "weighted"):
        res_t = jnp.stack(res)
        if bool(jnp.any(jnp.isnan(res_t))):
            warnings.warn("Average precision score for one or more classes was `nan`. Ignoring these classes "
                          f"in {average}-average", UserWarning)
        if average == "macro":
            return jnp.nanmean(res_t)
        weights = jnp.where(jnp.isnan(res_t), 0.0, weights)
        weights = weights / jnp.sum(weights)
        return jnp.nansum(res_t * weights)
    if average in (None, "none"):
        return res
    allowed_average = ("micro", "macro", "weighted", "none", None)
    raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")


def average_precision(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    sample_weights: Optional[Sequence] = None,
) -> Union[List[Array], Array]:
    """Compute average precision. Parity: reference ``average_precision:147-211``."""
    preds, target, num_classes, pos_label = _average_precision_update(preds, target, num_classes, pos_label, average)
    return _average_precision_compute(preds, target, num_classes, pos_label, average, sample_weights)
