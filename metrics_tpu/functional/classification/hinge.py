"""Hinge loss (binary, Crammer-Singer, one-vs-all).

Parity: reference ``torchmetrics/functional/classification/hinge.py``
(MulticlassMode :27, _check_shape_and_type_consistency_hinge :37, _hinge_update :73,
_hinge_compute :125, hinge :146). Boolean-mask indexing becomes take_along_axis /
where-masking (static shapes).
"""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _input_squeeze
from metrics_tpu.utils.data import to_onehot
from metrics_tpu.utils.enums import DataType, EnumStr

Array = jax.Array


class MulticlassMode(EnumStr):
    """Possible multiclass modes of hinge."""

    CRAMMER_SINGER = "crammer-singer"
    ONE_VS_ALL = "one-vs-all"


def _check_shape_and_type_consistency_hinge(preds: Array, target: Array) -> DataType:
    if target.ndim > 1:
        raise ValueError(f"The `target` should be one dimensional, got `target` with shape={target.shape}.")
    if preds.ndim == 1:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        mode = DataType.BINARY
    elif preds.ndim == 2:
        if preds.shape[0] != target.shape[0]:
            raise ValueError(
                "The `preds` and `target` should have the same shape in the first dimension,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        mode = DataType.MULTICLASS
    else:
        raise ValueError(f"The `preds` should be one or two dimensional, got `preds` with shape={preds.shape}.")
    return mode


def _hinge_update(
    preds: Array,
    target: Array,
    squared: bool = False,
    multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
) -> Tuple[Array, Array]:
    preds, target = _input_squeeze(jnp.asarray(preds), jnp.asarray(target))
    mode = _check_shape_and_type_consistency_hinge(preds, target)

    if mode == DataType.MULTICLASS:
        target_oh = to_onehot(target, max(2, preds.shape[1])).astype(bool)

    if mode == DataType.MULTICLASS and (multiclass_mode is None or multiclass_mode == MulticlassMode.CRAMMER_SINGER):
        # margin = preds at the true class minus the best wrong-class score
        true_scores = jnp.take_along_axis(preds, target.reshape(-1, 1).astype(jnp.int32), axis=1)[:, 0]
        wrong_best = jnp.max(jnp.where(target_oh, -jnp.inf, preds), axis=1)
        margin = true_scores - wrong_best
    elif mode == DataType.BINARY or multiclass_mode == MulticlassMode.ONE_VS_ALL:
        if mode == DataType.BINARY:
            t = target.astype(bool)
        else:
            t = target_oh
        margin = jnp.where(t, preds, -preds)
    else:
        raise ValueError(
            "The `multiclass_mode` should be either None / 'crammer-singer' / MulticlassMode.CRAMMER_SINGER"
            "(default) or 'one-vs-all' / MulticlassMode.ONE_VS_ALL,"
            f" got {multiclass_mode}."
        )

    measures = jnp.clip(1 - margin, 0, None)
    if squared:
        measures = measures ** 2

    total = jnp.asarray(target.shape[0])
    return jnp.sum(measures, axis=0), total


def _hinge_compute(measure: Array, total: Array) -> Array:
    return measure / total


def hinge_loss(
    preds: Array,
    target: Array,
    squared: bool = False,
    multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
) -> Array:
    """Compute mean hinge loss. Parity: reference ``hinge_loss:158-232``."""
    measure, total = _hinge_update(preds, target, squared=squared, multiclass_mode=multiclass_mode)
    return _hinge_compute(measure, total)


def hinge(
    preds: Array,
    target: Array,
    squared: bool = False,
    multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
) -> Array:
    """Deprecated alias of :func:`hinge_loss`. Parity: reference ``hinge:235-263``."""
    from metrics_tpu.utils.prints import rank_zero_warn

    rank_zero_warn("`hinge` was renamed to `hinge_loss` and it will be removed.", DeprecationWarning)
    return hinge_loss(preds, target, squared=squared, multiclass_mode=multiclass_mode)
