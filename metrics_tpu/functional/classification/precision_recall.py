"""Precision and Recall.

Parity: reference ``torchmetrics/functional/classification/precision_recall.py``
(_precision_compute :23, precision :76, _recall_compute :219, recall :268,
precision_recall :440). Absent-class masking uses the static-shape -1-denominator
trick (see ``accuracy.py`` in this package) instead of boolean-mask indexing.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import _reduce_stat_scores, _stat_scores_update
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


def _mask_absent_classes(numerator: Array, denominator: Array, tp: Array, fp: Array, fn: Array,
                         average: Optional[str], mdmc_average: Optional[str]) -> Tuple[Array, Array]:
    if average == AverageMethod.MACRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        cond = (tp + fp + fn) == 0
        numerator = jnp.where(cond, 0, numerator)
        denominator = jnp.where(cond, -1, denominator)
    if average == AverageMethod.NONE and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        meaningless = (tp | fn | fp) == 0
        numerator = jnp.where(meaningless, -1, numerator)
        denominator = jnp.where(meaningless, -1, denominator)
    return numerator, denominator


def _precision_compute(tp: Array, fp: Array, fn: Array, average: str, mdmc_average: Optional[str]) -> Array:
    numerator, denominator = _mask_absent_classes(tp, tp + fp, tp, fp, fn, average, mdmc_average)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != "weighted" else (tp + fn),
        average=average,
        mdmc_average=mdmc_average,
    )


def _recall_compute(tp: Array, fp: Array, fn: Array, average: str, mdmc_average: Optional[str]) -> Array:
    numerator, denominator = _mask_absent_classes(tp, tp + fn, tp, fp, fn, average, mdmc_average)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else (tp + fn),
        average=average,
        mdmc_average=mdmc_average,
    )


def _validate_average_args(average: str, mdmc_average: Optional[str], num_classes: Optional[int],
                           ignore_index: Optional[int]) -> None:
    allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    allowed_mdmc_average = (None, "samplewise", "global")
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if average in ("macro", "weighted", "none", None) and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")


def precision(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """Compute precision = TP / (TP + FP). Parity: reference ``precision:76-216``."""
    _validate_average_args(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds, target, reduce=reduce, mdmc_reduce=mdmc_average, threshold=threshold,
        num_classes=num_classes, top_k=top_k, multiclass=multiclass, ignore_index=ignore_index,
    )
    return _precision_compute(tp, fp, fn, average, mdmc_average)


def recall(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """Compute recall = TP / (TP + FN). Parity: reference ``recall:268-408``."""
    _validate_average_args(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds, target, reduce=reduce, mdmc_reduce=mdmc_average, threshold=threshold,
        num_classes=num_classes, top_k=top_k, multiclass=multiclass, ignore_index=ignore_index,
    )
    return _recall_compute(tp, fp, fn, average, mdmc_average)


def precision_recall(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Tuple[Array, Array]:
    """Compute precision and recall in one pass. Parity: reference ``:440-581``."""
    _validate_average_args(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds, target, reduce=reduce, mdmc_reduce=mdmc_average, threshold=threshold,
        num_classes=num_classes, top_k=top_k, multiclass=multiclass, ignore_index=ignore_index,
    )
    return (
        _precision_compute(tp, fp, fn, average, mdmc_average),
        _recall_compute(tp, fp, fn, average, mdmc_average),
    )
