"""KL divergence.

Parity: reference ``torchmetrics/functional/classification/kl_divergence.py``
(_kld_update :24, _kld_compute :50, kl_divergence :77).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.data import METRIC_EPS

Array = jax.Array


def _kld_update(p: Array, q: Array, log_prob: bool) -> Tuple[Array, int]:
    _check_same_shape(p, q)
    if p.ndim != 2 or q.ndim != 2:
        raise ValueError(f"Expected both p and q distribution to be 2D but got {p.ndim} and {q.ndim} respectively")

    total = p.shape[0]
    if log_prob:
        measures = jnp.sum(jnp.exp(p) * (p - q), axis=-1)
    else:
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        q = q / jnp.sum(q, axis=-1, keepdims=True)
        q = jnp.clip(q, METRIC_EPS, None)
        measures = jnp.sum(p * jnp.log(p / q), axis=-1)
    return measures, total


def _kld_compute(measures: Array, total: Array, reduction: Optional[str] = "mean") -> Array:
    if reduction == "sum":
        return jnp.sum(measures)
    if reduction == "mean":
        return jnp.sum(measures) / total
    if reduction is None or reduction == "none":
        return measures
    return measures / total


def kl_divergence(p: Array, q: Array, log_prob: bool = False, reduction: Optional[str] = "mean") -> Array:
    """Compute D_KL(P||Q). Parity: reference ``kl_divergence:77-112``."""
    measures, total = _kld_update(p, q, log_prob)
    return _kld_compute(measures, jnp.asarray(total), reduction)
