"""F-beta / F1 scores.

Parity: reference ``torchmetrics/functional/classification/f_beta.py``
(_safe_divide :25, _fbeta_compute :31, fbeta :115, f1 :225). The reference's in-place
``denom[denom==0]=1`` and boolean-mask drops become ``jnp.where`` masking (static
shapes, jit-safe, numerically identical).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import _reduce_stat_scores, _stat_scores_update
from metrics_tpu.utils.enums import AverageMethod as AvgMethod
from metrics_tpu.utils.enums import MDMCAverageMethod

Array = jax.Array


def _safe_divide(num: Array, denom: Array) -> Array:
    """Division that treats 0/0 as 0. Parity: reference ``f_beta.py:25-28``."""
    num = num.astype(jnp.float32) if not jnp.issubdtype(num.dtype, jnp.floating) else num
    denom = denom.astype(num.dtype)
    return num / jnp.where(denom == 0.0, 1.0, denom)


def _fbeta_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    beta: float,
    ignore_index: Optional[int],
    average: str,
    mdmc_average: Optional[str],
) -> Array:
    if average == AvgMethod.MICRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        mask = tp >= 0
        precision = _safe_divide(jnp.sum(jnp.where(mask, tp, 0)).astype(jnp.float32),
                                 jnp.sum(jnp.where(mask, tp + fp, 0)))
        recall = _safe_divide(jnp.sum(jnp.where(mask, tp, 0)).astype(jnp.float32),
                              jnp.sum(jnp.where(mask, tp + fn, 0)))
    else:
        precision = _safe_divide(tp.astype(jnp.float32), tp + fp)
        recall = _safe_divide(tp.astype(jnp.float32), tp + fn)

    num = (1 + beta ** 2) * precision * recall
    denom = beta ** 2 * precision + recall
    denom = jnp.where(denom == 0.0, 1.0, denom)

    if average == AvgMethod.NONE and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        meaningless = (tp | fn | fp) == 0
        if ignore_index is not None:
            meaningless = meaningless | (jnp.arange(meaningless.shape[-1]) == ignore_index)
        num = jnp.where(meaningless, -1.0, num)
        denom = jnp.where(meaningless, -1.0, denom)
    elif ignore_index is not None:
        if average not in (AvgMethod.MICRO, AvgMethod.SAMPLES) and mdmc_average == MDMCAverageMethod.SAMPLEWISE:
            num = num.at[..., ignore_index].set(-1.0)
            denom = denom.at[..., ignore_index].set(-1.0)
        elif average not in (AvgMethod.MICRO, AvgMethod.SAMPLES):
            num = num.at[ignore_index].set(-1.0)
            denom = denom.at[ignore_index].set(-1.0)

    if average == AvgMethod.MACRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        cond = ((tp + fp + fn) == 0) | ((tp + fp + fn) == -3)
        num = jnp.where(cond, 0.0, num)
        denom = jnp.where(cond, -1.0, denom)

    return _reduce_stat_scores(
        numerator=num,
        denominator=denom,
        weights=None if average != AvgMethod.WEIGHTED else (tp + fn),
        average=average,
        mdmc_average=mdmc_average,
    )


def fbeta(
    preds: Array,
    target: Array,
    beta: float = 1.0,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """Compute F-beta. Parity: reference ``fbeta:115-222``."""
    allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    if average in ("macro", "weighted", "none", None) and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    allowed_mdmc_average = (None, "samplewise", "global")
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds, target, reduce=reduce, mdmc_reduce=mdmc_average, threshold=threshold,
        num_classes=num_classes, top_k=top_k, multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _fbeta_compute(tp, fp, tn, fn, beta, ignore_index, average, mdmc_average)


def f1(
    preds: Array,
    target: Array,
    beta: float = 1.0,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """F1 = F-beta with beta=1. Parity: reference ``f1:225-331``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import f1_score
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> print(f"{float(f1_score(preds, target)):.4f}")
        0.7500
    """
    return fbeta(preds, target, 1.0, average, mdmc_average, ignore_index, num_classes, threshold, top_k, multiclass)


f1_score = f1
