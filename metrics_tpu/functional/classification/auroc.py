"""Area under the ROC curve.

Parity: reference ``torchmetrics/functional/classification/auroc.py``
(_auroc_update :27, _auroc_compute :51, auroc :186). Binary max_fpr uses the same
bucketize+lerp partial-AUC with McClish correction.
"""
import warnings
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.auc import _auc_compute_without_check
from metrics_tpu.functional.classification.roc import roc
from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.data import _bincount
from metrics_tpu.utils.enums import AverageMethod, DataType

Array = jax.Array


def _auroc_update(preds: Array, target: Array) -> Tuple[Array, Array, DataType]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _, _, mode = _input_format_classification(preds, target)

    if mode == DataType.MULTIDIM_MULTICLASS:
        n_classes = preds.shape[1]
        preds = jnp.swapaxes(preds, 0, 1).reshape(n_classes, -1).T
        target = jnp.ravel(target)
    if mode == DataType.MULTILABEL and preds.ndim > 2:
        n_classes = preds.shape[1]
        preds = jnp.swapaxes(preds, 0, 1).reshape(n_classes, -1).T
        target = jnp.swapaxes(target, 0, 1).reshape(n_classes, -1).T
    return preds, target, mode


def _auroc_compute(
    preds: Array,
    target: Array,
    mode: DataType,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    sample_weights: Optional[Sequence] = None,
) -> Array:
    if mode == DataType.BINARY:
        num_classes = 1

    if max_fpr is not None:
        if not isinstance(max_fpr, float) or not 0 < max_fpr <= 1:
            raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")
        if mode != DataType.BINARY:
            raise ValueError(
                f"Partial AUC computation not available in multilabel/multiclass setting,"
                f" 'max_fpr' must be set to `None`, received `{max_fpr}`."
            )

    if mode == DataType.MULTILABEL:
        if average == AverageMethod.MICRO:
            fpr, tpr, _ = roc(jnp.ravel(preds), jnp.ravel(target), 1, pos_label, sample_weights)
        elif num_classes:
            output = [
                roc(preds[:, i], target[:, i], num_classes=1, pos_label=1, sample_weights=sample_weights)
                for i in range(num_classes)
            ]
            fpr = [o[0] for o in output]
            tpr = [o[1] for o in output]
        else:
            raise ValueError("Detected input to be `multilabel` but you did not provide `num_classes` argument")
    else:
        if mode != DataType.BINARY:
            if num_classes is None:
                raise ValueError("Detected input to `multiclass` but you did not provide `num_classes` argument")
            if average == AverageMethod.WEIGHTED and len(jnp.unique(target)) < num_classes:
                # classes with 0 observations are dropped (weight would be 0)
                target_bool_mat = jnp.zeros((len(target), num_classes), dtype=bool)
                target_bool_mat = target_bool_mat.at[jnp.arange(len(target)), target.astype(jnp.int32)].set(True)
                class_observed = jnp.sum(target_bool_mat, axis=0) > 0
                for c in range(num_classes):
                    if not bool(class_observed[c]):
                        warnings.warn(f"Class {c} had 0 observations, omitted from AUROC calculation", UserWarning)
                keep = jnp.nonzero(class_observed)[0]
                preds = preds[:, keep]
                target_bool_mat = target_bool_mat[:, keep]
                target = jnp.nonzero(target_bool_mat)[1]
                num_classes = int(len(keep))
                if num_classes == 1:
                    raise ValueError("Found 1 non-empty class in `multiclass` AUROC calculation")
        fpr, tpr, _ = roc(preds, target, num_classes, pos_label, sample_weights)

    if max_fpr is None or max_fpr == 1:
        if mode == DataType.MULTILABEL and average == AverageMethod.MICRO:
            pass
        elif num_classes != 1:
            auc_scores = [_auc_compute_without_check(x, y, 1.0) for x, y in zip(fpr, tpr)]
            if average == AverageMethod.NONE:
                return jnp.stack(auc_scores)
            if average == AverageMethod.MACRO:
                return jnp.mean(jnp.stack(auc_scores))
            if average == AverageMethod.WEIGHTED:
                if mode == DataType.MULTILABEL:
                    support = jnp.sum(target, axis=0)
                else:
                    support = _bincount(jnp.ravel(target), num_classes)
                return jnp.sum(jnp.stack(auc_scores) * support / jnp.sum(support))
            allowed_average = (AverageMethod.NONE.value, AverageMethod.MACRO.value, AverageMethod.WEIGHTED.value)
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )
        return _auc_compute_without_check(fpr, tpr, 1.0)

    max_area = jnp.asarray(max_fpr, dtype=fpr.dtype)
    # add a point at max_fpr by linear interpolation
    stop = int(jnp.searchsorted(fpr, max_area, side="right"))
    weight = (max_area - fpr[stop - 1]) / (fpr[stop] - fpr[stop - 1])
    interp_tpr = tpr[stop - 1] + weight * (tpr[stop] - tpr[stop - 1])
    tpr = jnp.concatenate([tpr[:stop], interp_tpr.reshape(1)])
    fpr = jnp.concatenate([fpr[:stop], max_area.reshape(1)])

    partial_auc = _auc_compute_without_check(fpr, tpr, 1.0)
    min_area = 0.5 * max_area ** 2
    return 0.5 * (1 + (partial_auc - min_area) / (max_area - min_area))


def auroc(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    sample_weights: Optional[Sequence] = None,
) -> Array:
    """Compute AUROC. Parity: reference ``auroc:186-254``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import auroc
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> print(f"{float(auroc(preds, target)):.4f}")
        0.7500
    """
    preds, target, mode = _auroc_update(preds, target)
    return _auroc_compute(preds, target, mode, num_classes, pos_label, average, max_fpr, sample_weights)
