"""Top-label calibration error (ECE / l2 / max norms).

Parity: reference ``torchmetrics/functional/classification/calibration_error.py``
(_ce_compute :22, _ce_update :78, calibration_error :113).

TPU note: the reference loops over bins with boolean masking (``:48-56``); here the
binning is one ``searchsorted`` + ONE fused three-column histogram through the
kernel dispatcher (``metrics_tpu/ops/kernels``): count, confidence-sum and
accuracy-sum accumulate per bin in a single pass — a streaming Pallas one-hot
× MXU contraction on TPU, one stacked XLA segment-sum elsewhere. Static
shapes, jit-safe.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.ops.kernels import histogram_accumulate
from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.enums import DataType

Array = jax.Array


def _ce_compute(
    confidences: Array,
    accuracies: Array,
    bin_boundaries: Array,
    norm: str = "l1",
    debias: bool = False,
) -> Array:
    if norm not in {"l1", "l2", "max"}:
        raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")

    n_bins = bin_boundaries.shape[0] - 1
    # bin i covers (b_i, b_{i+1}]; conf == 0 lands in no bin (parity with the
    # reference's strict ``gt(lower)``) — searchsorted(left) - 1 gives -1 there.
    idx = jnp.searchsorted(bin_boundaries, confidences, side="left") - 1
    valid = idx >= 0
    idx = jnp.clip(idx, 0, n_bins - 1)
    w = valid.astype(confidences.dtype)

    # one fused histogram pass for all three per-bin sums (kernel dispatcher:
    # Pallas on TPU, stacked segment-sum under XLA) — the weight columns share
    # the single one-hot/scatter of `idx`
    cols = jnp.stack([w, confidences * w, accuracies * w], axis=-1)
    sums = histogram_accumulate(idx, n_bins, weights=cols)
    count_bin, conf_sum, acc_sum = sums[:, 0], sums[:, 1], sums[:, 2]

    n = confidences.shape[0]
    prop_bin = count_bin / n
    safe = jnp.maximum(count_bin, 1.0)
    conf_bin = jnp.where(count_bin > 0, conf_sum / safe, 0.0)
    acc_bin = jnp.where(count_bin > 0, acc_sum / safe, 0.0)
    # pad to bin_boundaries length for parity with reference's zeros_like(boundaries)
    pad = bin_boundaries.shape[0] - n_bins
    conf_bin = jnp.concatenate([conf_bin, jnp.zeros(pad, conf_bin.dtype)])
    acc_bin = jnp.concatenate([acc_bin, jnp.zeros(pad, acc_bin.dtype)])
    prop_bin = jnp.concatenate([prop_bin, jnp.zeros(pad, prop_bin.dtype)])

    if norm == "l1":
        ce = jnp.sum(jnp.abs(acc_bin - conf_bin) * prop_bin)
    elif norm == "max":
        ce = jnp.max(jnp.abs(acc_bin - conf_bin))
    else:  # l2
        ce = jnp.sum((acc_bin - conf_bin) ** 2 * prop_bin)
        if debias:
            debias_bins = (acc_bin * (acc_bin - 1) * prop_bin) / (prop_bin * n - 1)
            ce = ce + jnp.sum(jnp.nan_to_num(debias_bins))
        ce = jnp.where(ce > 0, jnp.sqrt(jnp.maximum(ce, 0.0)), 0.0)
    return ce


def _ce_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _, _, mode = _input_format_classification(preds, target)

    if mode == DataType.BINARY:
        confidences, accuracies = preds, target
    elif mode == DataType.MULTICLASS:
        confidences = jnp.max(preds, axis=1)
        predictions = jnp.argmax(preds, axis=1)
        accuracies = predictions == target
    elif mode == DataType.MULTIDIM_MULTICLASS:
        flat = jnp.moveaxis(preds, 1, -1).reshape(-1, preds.shape[1])
        confidences = jnp.max(flat, axis=1)
        predictions = jnp.argmax(flat, axis=1)
        accuracies = predictions == jnp.ravel(target)
    else:
        raise ValueError(
            f"Calibration error is not well-defined for data with size {preds.shape} and targets {target.shape}."
        )
    return confidences.astype(jnp.float32), accuracies.astype(jnp.float32)


def calibration_error(preds: Array, target: Array, n_bins: int = 15, norm: str = "l1") -> Array:
    """Compute top-label calibration error. Parity: reference ``:113-166``."""
    if norm not in ("l1", "l2", "max"):
        raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")
    if not isinstance(n_bins, int) or n_bins <= 0:
        raise ValueError(f"Expected argument `n_bins` to be a int larger than 0 but got {n_bins}")
    confidences, accuracies = _ce_update(preds, target)
    bin_boundaries = jnp.linspace(0, 1, n_bins + 1, dtype=jnp.float32)
    return _ce_compute(confidences, accuracies, bin_boundaries, norm=norm)
