"""Peak signal-to-noise ratio.

Parity: reference ``torchmetrics/functional/image/psnr.py`` (_psnr_compute :21,
_psnr_update :52, psnr :86).
"""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.parallel.collectives import reduce
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


def _psnr_compute(
    sum_squared_error: Array,
    n_obs: Array,
    data_range: Array,
    base: float = 10.0,
    reduction: str = "elementwise_mean",
) -> Array:
    psnr_base_e = 2 * jnp.log(data_range) - jnp.log(sum_squared_error / n_obs)
    psnr_vals = psnr_base_e * (10 / jnp.log(jnp.asarray(base)))
    return reduce(psnr_vals, reduction=reduction)


def _psnr_update(
    preds: Array,
    target: Array,
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Tuple[Array, Array]:
    if dim is None:
        sum_squared_error = jnp.sum((preds - target) ** 2)
        n_obs = jnp.asarray(target.size)
        return sum_squared_error, n_obs

    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=dim)
    dim_list = [dim] if isinstance(dim, int) else list(dim)
    if not dim_list:
        n_obs = jnp.asarray(target.size)
    else:
        n = 1
        for d in dim_list:
            n *= target.shape[d]
        n_obs = jnp.broadcast_to(jnp.asarray(n), sum_squared_error.shape)
    return sum_squared_error, n_obs


def psnr(
    preds: Array,
    target: Array,
    data_range: Optional[float] = None,
    base: float = 10.0,
    reduction: str = "elementwise_mean",
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Array:
    """Compute PSNR. Parity: reference ``psnr:86-141``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import psnr
        >>> preds = jnp.asarray([[0.0, 1.0], [1.0, 0.0]])
        >>> target = jnp.asarray([[0.0, 1.0], [1.0, 1.0]])
        >>> print(f"{float(psnr(preds, target, data_range=1.0)):.4f}")
        6.0206
    """
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if dim is None and reduction != "elementwise_mean":
        rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")

    if data_range is None:
        if dim is not None:
            raise ValueError("The `data_range` must be given when `dim` is not None.")
        data_range = jnp.max(target) - jnp.min(target)
    else:
        data_range = jnp.asarray(float(data_range))
    sum_squared_error, n_obs = _psnr_update(preds, target, dim=dim)
    return _psnr_compute(sum_squared_error, n_obs, data_range, base=base, reduction=reduction)
