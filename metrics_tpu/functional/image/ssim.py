"""Structural similarity index measure.

Parity: reference ``torchmetrics/functional/image/ssim.py`` (_gaussian :24,
_gaussian_kernel :42, _ssim_update :70, _ssim_compute :93, ssim :182).

TPU notes: the 5-way stacked depthwise convolution (mu_x, mu_y, x^2, y^2, x*y in one
conv, reference :146-148) maps to a single ``lax.conv_general_dilated`` with
``feature_group_count=C`` — one fused conv kernel per call. The gaussian window is
separable; XLA constant-folds the tiny kernel. Deviation: reflect padding is applied
height-with-pad_h / width-with-pad_w (the reference's F.pad call swaps them, which
only matters for non-square kernels).
"""
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from metrics_tpu.parallel.collectives import reduce
from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _gaussian(kernel_size: int, sigma: float, dtype) -> Array:
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-((dist / sigma) ** 2) / 2)
    return (gauss / jnp.sum(gauss))[None, :]  # (1, kernel_size)


def _gaussian_kernel(channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype) -> Array:
    kernel_x = _gaussian(kernel_size[0], sigma[0], dtype)
    kernel_y = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel = kernel_x.T @ kernel_y  # (kh, kw)
    return jnp.broadcast_to(kernel, (channel, 1, kernel_size[0], kernel_size[1]))


def _depthwise_conv2d(x: Array, kernel: Array, channels: int) -> Array:
    return lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=channels,
    )


def _ssim_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _ssim_compute(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: str = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_contrast_sensitivity: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    if len(kernel_size) != 2 or len(sigma) != 2:
        raise ValueError(
            "Expected `kernel_size` and `sigma` to have the length of two."
            f" Got kernel_size: {len(kernel_size)} and sigma: {len(sigma)}."
        )
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")

    if data_range is None:
        data_range = jnp.maximum(jnp.max(preds) - jnp.min(preds), jnp.max(target) - jnp.min(target))

    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2

    channel = preds.shape[1]
    dtype = preds.dtype
    kernel = _gaussian_kernel(channel, kernel_size, sigma, dtype)
    pad_h = (kernel_size[0] - 1) // 2
    pad_w = (kernel_size[1] - 1) // 2

    pad_cfg = ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w))
    preds_p = jnp.pad(preds, pad_cfg, mode="reflect")
    target_p = jnp.pad(target, pad_cfg, mode="reflect")

    # one conv over the 5-way stacked batch (mu_x, mu_y, E[x^2], E[y^2], E[xy])
    input_list = jnp.concatenate([preds_p, target_p, preds_p * preds_p, target_p * target_p, preds_p * target_p])
    outputs = _depthwise_conv2d(input_list, kernel, channel)
    b = preds.shape[0]
    mu_pred, mu_target = outputs[:b], outputs[b:2 * b]
    e_pred_sq, e_target_sq, e_pred_target = outputs[2 * b:3 * b], outputs[3 * b:4 * b], outputs[4 * b:]

    mu_pred_sq = mu_pred ** 2
    mu_target_sq = mu_target ** 2
    mu_pred_target = mu_pred * mu_target

    sigma_pred_sq = e_pred_sq - mu_pred_sq
    sigma_target_sq = e_target_sq - mu_target_sq
    sigma_pred_target = e_pred_target - mu_pred_target

    upper = 2 * sigma_pred_target + c2
    lower = sigma_pred_sq + sigma_target_sq + c2

    ssim_idx = ((2 * mu_pred_target + c1) * upper) / ((mu_pred_sq + mu_target_sq + c1) * lower)
    # the reference crops the border region out of the final map (ssim.py:158)
    ssim_idx = ssim_idx[..., pad_h:-pad_h, pad_w:-pad_w] if pad_h and pad_w else ssim_idx

    if return_contrast_sensitivity:
        contrast_sensitivity = upper / lower
        contrast_sensitivity = (
            contrast_sensitivity[..., pad_h:-pad_h, pad_w:-pad_w] if pad_h and pad_w else contrast_sensitivity
        )
        return reduce(ssim_idx, reduction), reduce(contrast_sensitivity, reduction)
    return reduce(ssim_idx, reduction)


def ssim(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: str = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
) -> Array:
    """Compute SSIM. Parity: reference ``ssim:182-242``."""
    preds, target = _ssim_update(jnp.asarray(preds), jnp.asarray(target))
    return _ssim_compute(preds, target, kernel_size, sigma, reduction, data_range, k1, k2)
