"""Image gradients (1-step finite differences).

Parity: reference ``torchmetrics/functional/image/gradients.py`` (image_gradients :48).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _image_gradients_validate(img: Array) -> None:
    if not isinstance(img, (jax.Array,)):
        import numpy as np

        if not isinstance(img, np.ndarray):
            raise TypeError(f"The `img` expects a value of <Array> type but got {type(img)}")
    if img.ndim != 4:
        raise RuntimeError(f"The `img` expects a 4D tensor but got {img.ndim}D tensor")


def _compute_image_gradients(img: Array) -> Tuple[Array, Array]:
    batch_size, channels, height, width = img.shape
    dy = img[..., 1:, :] - img[..., :-1, :]
    dx = img[..., :, 1:] - img[..., :, :-1]
    dy = jnp.concatenate([dy, jnp.zeros((batch_size, channels, 1, width), dtype=img.dtype)], axis=2)
    dx = jnp.concatenate([dx, jnp.zeros((batch_size, channels, height, 1), dtype=img.dtype)], axis=3)
    return dy, dx


def image_gradients(img: Array) -> Tuple[Array, Array]:
    """Compute (dy, dx) finite-difference gradients of an (N, C, H, W) image.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import image_gradients
        >>> img = jnp.arange(16.0).reshape(1, 1, 4, 4)
        >>> dy, dx = image_gradients(img)
        >>> dy[0, 0, 0].tolist()
        [4.0, 4.0, 4.0, 4.0]
    """
    img = jnp.asarray(img)
    _image_gradients_validate(img)
    return _compute_image_gradients(img)
