"""Multi-scale SSIM.

Parity: reference ``torchmetrics/functional/image/ms_ssim.py``
(_get_normalized_sim_and_cs :25, _multiscale_ssim_compute :42,
multiscale_structural_similarity_index_measure :133). Downsampling uses a 2x2
average pool via ``lax.reduce_window``.
"""
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from metrics_tpu.functional.image.ssim import _ssim_compute, _ssim_update

Array = jax.Array


def _avg_pool2d(x: Array) -> Array:
    out = lax.reduce_window(x, 0.0, lax.add, (1, 1, 2, 2), (1, 1, 2, 2), "VALID")
    return out / 4.0


def _get_normalized_sim_and_cs(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int],
    sigma: Sequence[float],
    reduction: str,
    data_range: Optional[float],
    k1: float,
    k2: float,
    normalize: Optional[str] = None,
) -> Tuple[Array, Array]:
    sim, contrast_sensitivity = _ssim_compute(
        preds, target, kernel_size, sigma, reduction, data_range, k1, k2, return_contrast_sensitivity=True
    )
    if normalize == "relu":
        sim = jax.nn.relu(sim)
        contrast_sensitivity = jax.nn.relu(contrast_sensitivity)
    return sim, contrast_sensitivity


def _multiscale_ssim_compute(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: str = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
) -> Array:
    sim_list: List[Array] = []
    cs_list: List[Array] = []

    if preds.shape[-1] < 2 ** len(betas) or preds.shape[-2] < 2 ** len(betas):
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)}, the image height and width dimensions must be"
            f" larger than or equal to {2 ** len(betas)}."
        )
    _betas_div = max(1, (len(betas) - 1)) ** 2
    if preds.shape[-2] // _betas_div <= kernel_size[0] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[0]},"
            f" the image height must be larger than {(kernel_size[0] - 1) * _betas_div}."
        )
    if preds.shape[-1] // _betas_div <= kernel_size[1] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[1]},"
            f" the image width must be larger than {(kernel_size[1] - 1) * _betas_div}."
        )

    for _ in range(len(betas)):
        sim, contrast_sensitivity = _get_normalized_sim_and_cs(
            preds, target, kernel_size, sigma, reduction, data_range, k1, k2, normalize
        )
        sim_list.append(sim)
        cs_list.append(contrast_sensitivity)
        preds = _avg_pool2d(preds)
        target = _avg_pool2d(target)

    sim_stack = jnp.stack(sim_list)
    cs_stack = jnp.stack(cs_list)

    if normalize == "simple":
        sim_stack = (sim_stack + 1) / 2
        cs_stack = (cs_stack + 1) / 2

    betas_arr = jnp.asarray(betas)
    sim_stack = sim_stack ** betas_arr
    cs_stack = cs_stack ** betas_arr
    return jnp.prod(cs_stack[:-1]) * sim_stack[-1]


def multiscale_structural_similarity_index_measure(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: str = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
) -> Array:
    """Compute MS-SSIM. Parity: reference ``:133-205``."""
    if not isinstance(betas, tuple) or not all(isinstance(beta, float) for beta in betas):
        raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
    if normalize and normalize not in ("relu", "simple"):
        raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
    preds, target = _ssim_update(jnp.asarray(preds), jnp.asarray(target))
    return _multiscale_ssim_compute(
        preds, target, kernel_size, sigma, reduction, data_range, k1, k2, betas, normalize
    )


ms_ssim = multiscale_structural_similarity_index_measure
