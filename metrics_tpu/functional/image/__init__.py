from metrics_tpu.functional.image.gradients import image_gradients
from metrics_tpu.functional.image.ms_ssim import multiscale_structural_similarity_index_measure
from metrics_tpu.functional.image.psnr import psnr
from metrics_tpu.functional.image.ssim import ssim
