"""Functional metrics (L3): stateless, pure, jit-able.

Parity: reference ``torchmetrics/functional/__init__.py`` (~76 exports; grows as
domains land).
"""
from metrics_tpu.functional.classification.accuracy import accuracy
from metrics_tpu.functional.classification.auc import auc
from metrics_tpu.functional.classification.auroc import auroc
from metrics_tpu.functional.classification.average_precision import average_precision
from metrics_tpu.functional.classification.calibration_error import calibration_error
from metrics_tpu.functional.classification.cohen_kappa import cohen_kappa
from metrics_tpu.functional.classification.confusion_matrix import confusion_matrix
from metrics_tpu.functional.classification.f_beta import f1, f1_score, fbeta
from metrics_tpu.functional.classification.dice import dice_score
from metrics_tpu.functional.classification.hamming_distance import hamming_distance
from metrics_tpu.functional.classification.hinge import hinge, hinge_loss
from metrics_tpu.functional.classification.jaccard import jaccard_index
from metrics_tpu.functional.classification.kl_divergence import kl_divergence
from metrics_tpu.functional.classification.matthews_corrcoef import matthews_corrcoef
from metrics_tpu.functional.classification.precision_recall import precision, precision_recall, recall
from metrics_tpu.functional.classification.precision_recall_curve import precision_recall_curve
from metrics_tpu.functional.classification.roc import roc
from metrics_tpu.functional.classification.specificity import specificity
from metrics_tpu.functional.audio.pesq import pesq
from metrics_tpu.functional.audio.pit import pit, pit_permutate
from metrics_tpu.functional.audio.sdr import (
    scale_invariant_signal_distortion_ratio,
    sdr,
    si_sdr,
    signal_distortion_ratio,
)
from metrics_tpu.functional.audio.snr import (
    scale_invariant_signal_noise_ratio,
    si_snr,
    signal_noise_ratio,
    snr,
)
from metrics_tpu.functional.audio.stoi import stoi
from metrics_tpu.functional.classification.stat_scores import stat_scores
from metrics_tpu.functional.image.gradients import image_gradients
from metrics_tpu.functional.image.ms_ssim import multiscale_structural_similarity_index_measure
from metrics_tpu.functional.image.psnr import psnr
from metrics_tpu.functional.image.ssim import ssim
from metrics_tpu.functional.pairwise.cosine import pairwise_cosine_similarity
from metrics_tpu.functional.pairwise.euclidean import pairwise_euclidean_distance
from metrics_tpu.functional.pairwise.linear import pairwise_linear_similarity
from metrics_tpu.functional.pairwise.manhatten import pairwise_manhatten_distance
from metrics_tpu.functional.regression.cosine_similarity import cosine_similarity
from metrics_tpu.functional.regression.explained_variance import explained_variance
from metrics_tpu.functional.regression.mean_absolute_error import mean_absolute_error
from metrics_tpu.functional.regression.mean_absolute_percentage_error import (
    mean_absolute_percentage_error,
)
from metrics_tpu.functional.regression.mean_squared_error import mean_squared_error
from metrics_tpu.functional.regression.mean_squared_log_error import mean_squared_log_error
from metrics_tpu.functional.regression.pearson import pearson_corrcoef
from metrics_tpu.functional.regression.r2 import r2_score
from metrics_tpu.functional.regression.spearman import spearman_corrcoef
from metrics_tpu.functional.regression.symmetric_mean_absolute_percentage_error import (
    symmetric_mean_absolute_percentage_error,
)
from metrics_tpu.functional.regression.tweedie_deviance import tweedie_deviance_score
from metrics_tpu.functional.retrieval.average_precision import retrieval_average_precision
from metrics_tpu.functional.retrieval.fall_out import retrieval_fall_out
from metrics_tpu.functional.retrieval.hit_rate import retrieval_hit_rate
from metrics_tpu.functional.retrieval.ndcg import retrieval_normalized_dcg
from metrics_tpu.functional.retrieval.precision import retrieval_precision
from metrics_tpu.functional.retrieval.r_precision import retrieval_r_precision
from metrics_tpu.functional.retrieval.recall import retrieval_recall
from metrics_tpu.functional.retrieval.reciprocal_rank import retrieval_reciprocal_rank
from metrics_tpu.functional.text.bert import bert_score
from metrics_tpu.functional.text.bleu import bleu_score
from metrics_tpu.functional.text.cer import char_error_rate
from metrics_tpu.functional.text.chrf import chrf_score
from metrics_tpu.functional.text.mer import match_error_rate
from metrics_tpu.functional.text.rouge import rouge_score
from metrics_tpu.functional.text.sacre_bleu import sacre_bleu_score
from metrics_tpu.functional.text.squad import squad
from metrics_tpu.functional.text.ter import translation_edit_rate
from metrics_tpu.functional.text.wer import wer, word_error_rate
from metrics_tpu.functional.text.wil import word_information_lost
from metrics_tpu.functional.text.wip import word_information_preserved

iou = jaccard_index  # deprecated alias (reference functional/iou.py)

__all__ = [
    "accuracy",
    "bert_score",
    "bleu_score",
    "char_error_rate",
    "chrf_score",
    "cosine_similarity",
    "match_error_rate",
    "rouge_score",
    "sacre_bleu_score",
    "squad",
    "stoi",
    "translation_edit_rate",
    "wer",
    "word_error_rate",
    "word_information_lost",
    "word_information_preserved",
    "explained_variance",
    "mean_absolute_error",
    "mean_absolute_percentage_error",
    "mean_squared_error",
    "mean_squared_log_error",
    "pairwise_cosine_similarity",
    "pairwise_euclidean_distance",
    "pairwise_linear_similarity",
    "pairwise_manhatten_distance",
    "pearson_corrcoef",
    "pesq",
    "pit",
    "pit_permutate",
    "r2_score",
    "retrieval_average_precision",
    "retrieval_fall_out",
    "retrieval_hit_rate",
    "retrieval_normalized_dcg",
    "retrieval_precision",
    "retrieval_r_precision",
    "retrieval_recall",
    "retrieval_reciprocal_rank",
    "scale_invariant_signal_distortion_ratio",
    "scale_invariant_signal_noise_ratio",
    "sdr",
    "si_sdr",
    "si_snr",
    "snr",
    "signal_distortion_ratio",
    "signal_noise_ratio",
    "spearman_corrcoef",
    "symmetric_mean_absolute_percentage_error",
    "tweedie_deviance_score",
    "auc",
    "auroc",
    "average_precision",
    "calibration_error",
    "cohen_kappa",
    "confusion_matrix",
    "f1",
    "f1_score",
    "fbeta",
    "hamming_distance",
    "dice_score",
    "hinge",
    "hinge_loss",
    "image_gradients",
    "iou",
    "multiscale_structural_similarity_index_measure",
    "psnr",
    "ssim",
    "jaccard_index",
    "kl_divergence",
    "matthews_corrcoef",
    "precision",
    "precision_recall",
    "precision_recall_curve",
    "recall",
    "roc",
    "specificity",
    "stat_scores",
]
