"""Functional metrics (L3): stateless, pure, jit-able.

Parity: reference ``torchmetrics/functional/__init__.py`` (~76 exports; grows as
domains land).
"""
from metrics_tpu.functional.classification.accuracy import accuracy
from metrics_tpu.functional.classification.f_beta import f1, f1_score, fbeta
from metrics_tpu.functional.classification.hamming_distance import hamming_distance
from metrics_tpu.functional.classification.precision_recall import precision, precision_recall, recall
from metrics_tpu.functional.classification.specificity import specificity
from metrics_tpu.functional.classification.stat_scores import stat_scores

__all__ = [
    "accuracy",
    "f1",
    "f1_score",
    "fbeta",
    "hamming_distance",
    "precision",
    "precision_recall",
    "recall",
    "specificity",
    "stat_scores",
]
