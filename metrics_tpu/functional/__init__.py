"""Functional metrics (L3): stateless, pure, jit-able.

Parity: reference ``torchmetrics/functional/__init__.py`` (~76 exports; grows as
domains land).
"""
from metrics_tpu.functional.classification.accuracy import accuracy
from metrics_tpu.functional.classification.auc import auc
from metrics_tpu.functional.classification.auroc import auroc
from metrics_tpu.functional.classification.average_precision import average_precision
from metrics_tpu.functional.classification.calibration_error import calibration_error
from metrics_tpu.functional.classification.cohen_kappa import cohen_kappa
from metrics_tpu.functional.classification.confusion_matrix import confusion_matrix
from metrics_tpu.functional.classification.f_beta import f1, f1_score, fbeta
from metrics_tpu.functional.classification.hamming_distance import hamming_distance
from metrics_tpu.functional.classification.hinge import hinge
from metrics_tpu.functional.classification.jaccard import jaccard_index
from metrics_tpu.functional.classification.kl_divergence import kl_divergence
from metrics_tpu.functional.classification.matthews_corrcoef import matthews_corrcoef
from metrics_tpu.functional.classification.precision_recall import precision, precision_recall, recall
from metrics_tpu.functional.classification.precision_recall_curve import precision_recall_curve
from metrics_tpu.functional.classification.roc import roc
from metrics_tpu.functional.classification.specificity import specificity
from metrics_tpu.functional.classification.stat_scores import stat_scores

iou = jaccard_index  # deprecated alias (reference functional/iou.py)

__all__ = [
    "accuracy",
    "auc",
    "auroc",
    "average_precision",
    "calibration_error",
    "cohen_kappa",
    "confusion_matrix",
    "f1",
    "f1_score",
    "fbeta",
    "hamming_distance",
    "hinge",
    "iou",
    "jaccard_index",
    "kl_divergence",
    "matthews_corrcoef",
    "precision",
    "precision_recall",
    "precision_recall_curve",
    "recall",
    "roc",
    "specificity",
    "stat_scores",
]
