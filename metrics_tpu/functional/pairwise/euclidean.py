"""Pairwise euclidean distance.

Parity: reference ``torchmetrics/functional/pairwise/euclidean.py:40``. Uses the
x^2 + y^2 - 2xy expansion so the heavy term is a single MXU matmul.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_euclidean_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x_norm = jnp.sum(x * x, axis=1, keepdims=True)
    y_norm = jnp.sum(y * y, axis=1)
    distance = x_norm + y_norm - 2 * (x @ y.T)
    distance = jnp.sqrt(jnp.clip(distance, 0.0, None))
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_euclidean_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise euclidean distance between rows of x (and y).

    Example:
        >>> from metrics_tpu.functional import pairwise_euclidean_distance
        >>> import jax.numpy as jnp
        >>> x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.asarray([[1.0, 0.0]])
        >>> [[f"{float(v):.4f}" for v in row] for row in pairwise_euclidean_distance(x, y)]
        [['2.0000'], ['4.4721']]
    """
    distance = _pairwise_euclidean_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
