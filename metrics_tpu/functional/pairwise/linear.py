"""Pairwise linear similarity (dot product).

Parity: reference ``torchmetrics/functional/pairwise/linear.py:39``.
"""
from typing import Optional

import jax

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_linear_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = x @ y.T
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_linear_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise dot-product similarity between rows of x (and y).

    Example:
        >>> from metrics_tpu.functional import pairwise_linear_similarity
        >>> import jax.numpy as jnp
        >>> x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.asarray([[1.0, 0.0]])
        >>> [[f"{float(v):.4f}" for v in row] for row in pairwise_linear_similarity(x, y)]
        [['1.0000'], ['3.0000']]
    """
    distance = _pairwise_linear_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
