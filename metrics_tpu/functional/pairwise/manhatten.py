"""Pairwise manhatten (L1) distance.

Parity: reference ``torchmetrics/functional/pairwise/manhatten.py:39`` (incl. the
reference's spelling).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_manhatten_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_manhatten_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise L1 distance between rows of x (and y).

    Example:
        >>> from metrics_tpu.functional import pairwise_manhatten_distance
        >>> import jax.numpy as jnp
        >>> x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.asarray([[1.0, 0.0]])
        >>> [[f"{float(v):.4f}" for v in row] for row in pairwise_manhatten_distance(x, y)]
        [['2.0000'], ['6.0000']]
    """
    distance = _pairwise_manhatten_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
