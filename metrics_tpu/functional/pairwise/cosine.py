"""Pairwise cosine similarity.

Parity: reference ``torchmetrics/functional/pairwise/cosine.py:45``. The row-normalised
matmul maps straight onto the MXU.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_cosine_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x = x / jnp.linalg.norm(x, axis=1, keepdims=True)
    y = y / jnp.linalg.norm(y, axis=1, keepdims=True)
    distance = x @ y.T
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_cosine_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise cosine similarity between rows of x (and y).

    Example:
        >>> from metrics_tpu.functional import pairwise_cosine_similarity
        >>> import jax.numpy as jnp
        >>> x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.asarray([[1.0, 0.0]])
        >>> [[f"{float(v):.4f}" for v in row] for row in pairwise_cosine_similarity(x, y)]
        [['0.4472'], ['0.6000']]
    """
    distance = _pairwise_cosine_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
