"""Shared pairwise helpers.

Parity: reference ``torchmetrics/functional/pairwise/helpers.py`` (_check_input :18,
_reduce_distance_matrix :44).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _check_input(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Tuple[Array, Array, bool]:
    x = jnp.asarray(x, dtype=jnp.float32) if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be a 2D tensor of shape `[N, d]` but got {x.shape}")
    if y is not None:
        y = jnp.asarray(y, dtype=x.dtype) if not jnp.issubdtype(jnp.asarray(y).dtype, jnp.floating) else jnp.asarray(y)
        if y.ndim != 2 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "Expected argument `y` to be a 2D tensor of shape `[M, d]` where"
                " `d` should be same as the last dimension of `x`"
            )
        zero_diagonal = False if zero_diagonal is None else zero_diagonal
    else:
        y = x
        zero_diagonal = True if zero_diagonal is None else zero_diagonal
    return x, y, zero_diagonal


def _zero_diagonal(distance: Array, zero_diagonal: bool) -> Array:
    if zero_diagonal:
        n = min(distance.shape)
        distance = distance.at[jnp.arange(n), jnp.arange(n)].set(0)
    return distance


def _reduce_distance_matrix(distmat: Array, reduction: Optional[str] = None) -> Array:
    if reduction == "mean":
        return jnp.mean(distmat, axis=-1)
    if reduction == "sum":
        return jnp.sum(distmat, axis=-1)
    if reduction is None or reduction == "none":
        return distmat
    raise ValueError(f"Expected reduction to be one of `['mean', 'sum', None]` but got {reduction}")
