"""Device-native grouped retrieval compute.

The reference computes retrieval metrics with a Python loop over query groups
(``torchmetrics/retrieval/retrieval_metric.py:124-153``) — one host iteration
and one device sync per query. Here the whole corpus is handled on device:

  1. ONE stable lexsort puts every query's documents contiguous, best-first
     (key: query index, then descending prediction);
  2. per-query metrics are ``jax.ops.segment_*`` reductions over rank/cumsum
     arrays — no data-dependent shapes, everything jit-compatible;
  3. ONE device->host transfer returns the per-query values.

SURVEY §7.2(7): ``get_group_indexes``' python dict loop becomes segment ops.
At 10k queries this removes 10k round-trips over the TPU tunnel.
"""
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

KINDS = ("map", "mrr", "precision", "recall", "r_precision", "hit_rate", "fall_out", "ndcg")


@partial(jax.jit, static_argnames=("kind", "k"))
def _segment_scores(
    preds: Array, target: Array, indexes: Array, *, kind: str, k: Optional[int]
) -> Tuple[Array, Array, Array]:
    """Per-query scores for the whole corpus in one fused device computation.

    Returns ``(values, empty, valid)``, each of shape ``(N,)`` (the static
    query-capacity bound = number of documents): ``valid[q]`` flags segments
    that exist, ``empty[q]`` flags degenerate queries (no positive target —
    no NEGATIVE for fall-out), ``values[q]`` is the metric for valid,
    non-degenerate queries.
    """
    n = preds.shape[0]
    f32 = jnp.float32

    order = jnp.lexsort((-preds, indexes))
    t = target[order].astype(f32)
    idx = indexes[order]

    start = jnp.concatenate([jnp.ones((1,), bool), idx[1:] != idx[:-1]])
    seg = jnp.cumsum(start) - 1  # dense 0-based query id, in sorted order
    pos = jnp.arange(n)
    seg_start = jax.lax.cummax(jnp.where(start, pos, 0))
    rank = (pos - seg_start + 1).astype(f32)  # 1-based rank within the query

    rel = (t > 0).astype(f32)
    sum_seg = partial(jax.ops.segment_sum, segment_ids=seg, num_segments=n)
    n_docs = sum_seg(jnp.ones_like(rel))
    n_rel = sum_seg(rel)
    valid = n_docs > 0
    if kind == "fall_out":
        empty = valid & (n_docs - n_rel == 0)
    else:
        empty = valid & (n_rel == 0)

    # effective cutoff: explicit k, else the query's own document count
    # (the reference's per-group ``preds.shape[-1]`` default)
    kk = jnp.full((n,), float(k), f32) if k is not None else n_docs
    in_k = rank <= kk[seg]

    if kind in ("precision", "recall", "hit_rate"):
        hits = sum_seg(rel * in_k)
        if kind == "precision":
            values = hits / jnp.maximum(kk, 1.0)
        elif kind == "recall":
            values = hits / jnp.maximum(n_rel, 1.0)
        else:
            values = (hits > 0).astype(f32)
    elif kind == "fall_out":
        neg = 1.0 - rel
        values = sum_seg(neg * in_k) / jnp.maximum(sum_seg(neg), 1.0)
    elif kind == "r_precision":
        in_r = rank <= n_rel[seg]
        values = sum_seg(rel * in_r) / jnp.maximum(n_rel, 1.0)
    elif kind == "map":
        # within-query cumulative relevant count: global cumsum minus the
        # cumsum carried in from before this query's first document
        c = jnp.cumsum(rel)
        carried = c[seg_start] - rel[seg_start]
        cum_rel = c - carried
        values = sum_seg(jnp.where(rel > 0, cum_rel / rank, 0.0)) / jnp.maximum(n_rel, 1.0)
    elif kind == "mrr":
        first = jax.ops.segment_min(
            jnp.where(rel > 0, rank, jnp.inf), seg, num_segments=n
        )
        values = jnp.where(jnp.isfinite(first), 1.0 / jnp.maximum(first, 1.0), 0.0)
    elif kind == "ndcg":
        from metrics_tpu.functional.retrieval.ndcg import log2_position_discounts

        discount = log2_position_discounts(n)
        dcg = sum_seg(jnp.where(in_k, t * discount[pos - seg_start], 0.0))
        # ideal ordering: same segments, documents by descending relevance
        order2 = jnp.lexsort((-target.astype(f32), indexes))
        t2 = target[order2].astype(f32)
        idcg = sum_seg(jnp.where(in_k, t2 * discount[pos - seg_start], 0.0))
        values = jnp.where(idcg > 0, dcg / jnp.maximum(idcg, 1e-38), 0.0)
    else:
        raise ValueError(f"unknown retrieval kind: {kind}")

    return values, empty, valid


def segment_retrieval_mean(
    preds: Array,
    target: Array,
    indexes: Array,
    *,
    kind: str,
    k: Optional[int] = None,
    empty_target_action: str = "neg",
) -> Array:
    """Mean-over-queries retrieval score, fully on device.

    ``empty_target_action`` follows the reference: degenerate queries raise
    (``error``), score 1 (``pos``), score 0 (``neg``), or drop out of the mean
    (``skip``). The ``error`` check stays in-graph as data: eager compute fetches
    the (result, flag) pair in ONE transfer and raises host-side; under jit —
    where raising is impossible — it defers like the runtime's value checks
    (``utils/checks.py``): the result is NaN-poisoned and a deferred errcode is
    emitted when a ``deferred_value_checks`` context is open.
    """
    values, empty, valid = _segment_scores(preds, target, indexes, kind=kind, k=k)
    if empty_target_action == "skip":
        keep, fill = valid & ~empty, 0.0
    elif empty_target_action == "pos":
        keep, fill = valid, 1.0
    else:  # "neg", and "error" (which inspects the empty flag below)
        keep, fill = valid, 0.0
    values = jnp.where(empty, fill, values)
    count = jnp.sum(keep)
    total = jnp.sum(jnp.where(keep, values, 0.0))
    result = jnp.where(count > 0, total / jnp.maximum(count, 1), 0.0)
    if empty_target_action != "error":
        return result

    from metrics_tpu.utils.checks import (
        _CODE_EMPTY_QUERY_RETRIEVAL,
        _is_tracer,
        defer_value_check,
        deferred_message,
    )

    any_empty = jnp.any(empty)
    if _is_tracer(result) or _is_tracer(any_empty):
        defer_value_check(any_empty, _CODE_EMPTY_QUERY_RETRIEVAL)
        return jnp.where(any_empty, jnp.float32(jnp.nan), result)
    import numpy as np

    fetched = np.asarray(jnp.stack([result, any_empty.astype(result.dtype)]))  # ONE transfer
    if fetched[1]:
        raise ValueError(deferred_message(_CODE_EMPTY_QUERY_RETRIEVAL))
    return jnp.asarray(fetched[0], result.dtype)


def batched_group_scores(
    preds: Array,
    target: Array,
    counts: Array,
    *,
    kind: str,
    k: Optional[int] = None,
    empty_target_action: str = "neg",
) -> Tuple[Array, Array, Array]:
    """Every group's score from the stacked ragged buffers, batched (ISSUE 18).

    ``preds``/``target`` are the ``(G, capacity)`` stacked capacity buffers,
    ``counts`` the ``(G,)`` TRUE row totals. This is the per-group read
    (:func:`grouped_query_score`) vmapped over the resident set — the body of
    the ragged engine's compiled AGGREGATE — returning per-group vectors the
    engine folds on device:

    * ``value`` ``(G,)`` — the group's score, with degenerate groups already
      holding the action's fill (``skip`` groups hold 0 but are masked out);
    * ``keep`` ``(G,)`` bool — groups that enter the corpus mean.  Empty
      groups (``count == 0``) and overflowed groups (``count > capacity``)
      ride this mask: both drop out exactly as in the eager corpus path
      (overflow additionally raises host-side off the count vector, before
      any folded value escapes);
    * ``flag`` ``(G,)`` bool — degenerate groups under
      ``empty_target_action="error"`` (all-False otherwise); the host finish
      raises the deferred value check when any is set.

    Fold ``value`` masked by ``keep`` with a sum kernel and divide by the
    kept count and the result is bit-identical to
    :func:`segment_retrieval_mean` over the concatenated corpus: per-group
    segment math is byte-identical (same ``_segment_scores`` body), and the
    masked fold is the same ``sum(where(keep, value, 0))`` expression.
    """
    cap = int(preds.shape[1])
    f32 = jnp.float32
    counts = jnp.asarray(counts, jnp.int32)

    def one(p: Array, t: Array, c: Array) -> Tuple[Array, Array]:
        row_valid = jnp.arange(cap) < jnp.minimum(c, cap)
        indexes = jnp.where(row_valid, 0, 1).astype(jnp.int32)
        values, empty, _ = _segment_scores(
            jnp.asarray(p, f32), jnp.asarray(t, f32), indexes, kind=kind, k=k
        )
        return values[0], empty[0]

    value, empty = jax.vmap(one)(preds, target, counts)
    valid = (counts > 0) & (counts <= cap)
    empty = empty & valid
    if empty_target_action == "skip":
        keep, fill = valid & ~empty, 0.0
    elif empty_target_action == "pos":
        keep, fill = valid, 1.0
    else:  # "neg", and "error" (host finish inspects the flag vector)
        keep, fill = valid, 0.0
    value = jnp.where(empty, jnp.float32(fill), value)
    flag = empty if empty_target_action == "error" else jnp.zeros_like(empty)
    return value, keep, flag


def grouped_query_score(
    preds: Array,
    target: Array,
    count: Array,
    *,
    kind: str,
    k: Optional[int] = None,
    empty_target_action: str = "neg",
) -> Array:
    """ONE query's score from its ragged capacity buffers (ISSUE 17).

    ``preds``/``target`` are a group's ``(capacity,)`` rows, ``count`` the
    TRUE row total (may exceed capacity — overflow). The valid prefix maps to
    segment 0 of :func:`_segment_scores` (pad rows get segment key 1), so the
    per-kind math is byte-identical to the corpus path. Fully traceable: this
    is the body of the ragged engine's compiled per-group read.

    Sentinel values (the per-group read has no mean to hide in):
    ``count == 0`` -> 0.0 (no rows — same as the eager metric's empty
    compute); degenerate query -> the action's value, with ``skip`` and
    ``error`` scoring NaN (``error`` also defers the runtime value check,
    exactly like :func:`segment_retrieval_mean` under jit); overflow -> NaN
    (rows past capacity were dropped, any score would be fabricated).
    """
    cap = preds.shape[0]
    f32 = jnp.float32
    count = jnp.asarray(count, jnp.int32)
    filled = jnp.minimum(count, cap)
    row_valid = jnp.arange(cap) < filled
    indexes = jnp.where(row_valid, 0, 1).astype(jnp.int32)
    values, empty, _ = _segment_scores(
        jnp.asarray(preds, f32), jnp.asarray(target, f32), indexes, kind=kind, k=k
    )
    value, is_empty = values[0], empty[0] & (count > 0)
    if empty_target_action == "pos":
        fill = jnp.float32(1.0)
    elif empty_target_action == "neg":
        fill = jnp.float32(0.0)
    else:  # "skip" and "error": no defined per-group value
        fill = jnp.float32(jnp.nan)
    value = jnp.where(is_empty, fill, value)
    value = jnp.where(count == 0, 0.0, value)
    value = jnp.where(count > cap, jnp.float32(jnp.nan), value)
    if empty_target_action != "error":
        return value

    from metrics_tpu.utils.checks import (
        _CODE_EMPTY_QUERY_RETRIEVAL,
        _is_tracer,
        defer_value_check,
        deferred_message,
    )

    if _is_tracer(value) or _is_tracer(is_empty):
        defer_value_check(is_empty, _CODE_EMPTY_QUERY_RETRIEVAL)
        return value
    import numpy as np

    fetched = np.asarray(jnp.stack([value, is_empty.astype(value.dtype)]))  # ONE transfer
    if fetched[1]:
        raise ValueError(deferred_message(_CODE_EMPTY_QUERY_RETRIEVAL))
    return jnp.asarray(fetched[0], value.dtype)
