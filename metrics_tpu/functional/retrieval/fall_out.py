"""Retrieval fall-out@k.

Parity: reference ``torchmetrics/functional/retrieval/fall_out.py``.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_fall_out(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Fraction of the non-relevant documents retrieved in the top k."""
    preds, target = _check_retrieval_functional_inputs(preds, target)
    k = preds.shape[-1] if k is None else k
    if not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
    target = 1 - target
    if not int(jnp.sum(target)):
        return jnp.asarray(0.0)
    relevant = jnp.sum(target[jnp.argsort(-preds, stable=True)][:k]).astype(jnp.float32)
    return relevant / jnp.sum(target)
