"""Retrieval R-precision.

Parity: reference ``torchmetrics/functional/retrieval/r_precision.py``.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_r_precision(preds: Array, target: Array) -> Array:
    """Precision at R where R = number of relevant documents."""
    preds, target = _check_retrieval_functional_inputs(preds, target)
    relevant_number = int(jnp.sum(target))
    if not relevant_number:
        return jnp.asarray(0.0)
    relevant = jnp.sum(target[jnp.argsort(-preds, stable=True)][:relevant_number]).astype(jnp.float32)
    return relevant / relevant_number
