"""Retrieval hit rate@k.

Parity: reference ``torchmetrics/functional/retrieval/hit_rate.py``.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_hit_rate(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """1.0 if any of the top-k documents is relevant."""
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if k is None:
        k = preds.shape[-1]
    if not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
    relevant = jnp.sum(target[jnp.argsort(-preds, stable=True)][:k])
    return (relevant > 0).astype(jnp.float32)
