"""Retrieval recall@k.

Parity: reference ``torchmetrics/functional/retrieval/recall.py``.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_recall(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Fraction of the relevant documents retrieved in the top k."""
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if k is None:
        k = preds.shape[-1]
    if not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
    if not int(jnp.sum(target)):
        return jnp.asarray(0.0)
    relevant = jnp.sum(target[jnp.argsort(-preds, stable=True)][:k]).astype(jnp.float32)
    return relevant / jnp.sum(target)
