"""Retrieval average precision.

Parity: reference ``torchmetrics/functional/retrieval/average_precision.py``.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_average_precision(preds: Array, target: Array) -> Array:
    """AP of a single query's predictions.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_average_precision
        >>> preds = jnp.asarray([0.2, 0.9, 0.7])
        >>> target = jnp.asarray([1, 0, 1])
        >>> print(f"{float(retrieval_average_precision(preds, target)):.4f}")
        0.5833
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not int(jnp.sum(target)):
        return jnp.asarray(0.0)
    target = target[jnp.argsort(-preds, stable=True)]
    # positions (1-based) of relevant docs; precision@pos averaged over relevant docs
    positions = jnp.arange(1, target.shape[0] + 1, dtype=jnp.float32)
    rel = target > 0
    cum_rel = jnp.cumsum(rel.astype(jnp.float32))
    prec_at_rel = jnp.where(rel, cum_rel / positions, 0.0)
    return jnp.sum(prec_at_rel) / jnp.sum(rel)
