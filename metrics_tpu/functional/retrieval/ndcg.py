"""Retrieval normalized discounted cumulative gain.

Parity: reference ``torchmetrics/functional/retrieval/ndcg.py``.
"""
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs

Array = jax.Array


def log2_position_discounts(n: int) -> Array:
    """``1 / log2(rank + 1)`` for 1-based ranks ``1..n``.

    Position discounts are a static-shape constant: computing them in f64
    numpy at trace time gives exactly-rounded values, where XLA's f32 log2
    approximation costs ~1e-5 absolute in the final nDCG. Shared by the
    per-query functional below and the fused segment engine (``_segment.py``).
    """
    return jnp.asarray(1.0 / np.log2(np.arange(n) + 2.0), dtype=jnp.float32)


def _dcg(target: Array) -> Array:
    return jnp.sum(target * log2_position_discounts(target.shape[-1]), axis=-1)


def retrieval_normalized_dcg(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """nDCG@k with (possibly graded) relevance targets."""
    preds, target = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target=True)
    k = preds.shape[-1] if k is None else k
    if not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
    sorted_target = target[jnp.argsort(-preds, stable=True)][:k]
    ideal_target = jnp.sort(target)[::-1][:k]
    ideal_dcg = _dcg(ideal_target)
    target_dcg = _dcg(sorted_target)
    return jnp.where(ideal_dcg == 0, 0.0, target_dcg / jnp.where(ideal_dcg == 0, 1.0, ideal_dcg))
