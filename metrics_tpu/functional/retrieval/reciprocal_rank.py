"""Retrieval mean reciprocal rank.

Parity: reference ``torchmetrics/functional/retrieval/reciprocal_rank.py``.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_reciprocal_rank(preds: Array, target: Array) -> Array:
    """RR = 1 / rank of the first relevant document.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_reciprocal_rank
        >>> preds = jnp.asarray([0.2, 0.9, 0.7])
        >>> target = jnp.asarray([1, 0, 1])
        >>> print(f"{float(retrieval_reciprocal_rank(preds, target)):.4f}")
        0.5000
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not int(jnp.sum(target)):
        return jnp.asarray(0.0)
    target = target[jnp.argsort(-preds, stable=True)]
    first = jnp.argmax(target > 0)
    return 1.0 / (first + 1.0)
