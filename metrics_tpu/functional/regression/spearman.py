"""Spearman rank correlation.

Parity: reference ``torchmetrics/functional/regression/spearman.py``
(_find_repeats :21, _rank_data :35, _spearman_corrcoef_update :54,
_spearman_corrcoef_compute :76, spearman_corrcoef :98).

TPU note: the reference assigns mean ranks to ties with a python loop over repeated
values (``:46-50``); here tie groups are resolved with one sort + segment-mean —
static shapes, fully vectorized, jit-safe.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _rank_data(data: Array) -> Array:
    """Ranks (1-based); ties get the mean of their ranks. Vectorized segment-mean."""
    n = data.size
    idx = jnp.argsort(data, stable=True)
    srt = data[idx]
    # group ids over sorted data: increments where the value changes
    change = jnp.concatenate([jnp.asarray([0], dtype=jnp.int32), (srt[1:] != srt[:-1]).astype(jnp.int32)])
    gid = jnp.cumsum(change)
    pos = jnp.arange(1, n + 1, dtype=data.dtype)
    group_sum = jax.ops.segment_sum(pos, gid, num_segments=n)
    group_cnt = jax.ops.segment_sum(jnp.ones_like(pos), gid, num_segments=n)
    mean_rank_sorted = (group_sum / jnp.maximum(group_cnt, 1))[gid]
    rank = jnp.zeros(n, dtype=data.dtype).at[idx].set(mean_rank_sorted)
    return rank


def _spearman_corrcoef_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    if not jnp.issubdtype(preds.dtype, jnp.floating) or not jnp.issubdtype(
        target.dtype, jnp.floating
    ):
        # reference contract (spearman.py:28-31): ranking integer data is
        # almost always an input mistake — require floats explicitly
        raise TypeError(
            "Expected `preds` and `target` both to be floating point tensors, but got"
            f" {preds.dtype} and {target.dtype}."
        )
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    preds = jnp.squeeze(preds)
    target = jnp.squeeze(target)
    if preds.ndim > 1 or target.ndim > 1:
        raise ValueError("Expected both predictions and target to be 1 dimensional tensors.")
    return preds, target


def _spearman_corrcoef_compute(preds: Array, target: Array, eps: float = 1e-6) -> Array:
    preds = _rank_data(preds)
    target = _rank_data(target)

    preds_diff = preds - jnp.mean(preds)
    target_diff = target - jnp.mean(target)

    cov = jnp.mean(preds_diff * target_diff)
    preds_std = jnp.sqrt(jnp.mean(preds_diff * preds_diff))
    target_std = jnp.sqrt(jnp.mean(target_diff * target_diff))

    corrcoef = cov / (preds_std * target_std + eps)
    return jnp.clip(corrcoef, -1.0, 1.0)


def spearman_corrcoef(preds: Array, target: Array) -> Array:
    """Compute Spearman's rank correlation coefficient."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    # widen sub-f32 floats for the ranking math; integer inputs fall through
    # to the _update TypeError (reference contract: floats required)
    if jnp.issubdtype(preds.dtype, jnp.floating) and preds.dtype not in (jnp.float32, jnp.float64):
        preds = preds.astype(jnp.float32)
    if jnp.issubdtype(target.dtype, jnp.floating) and target.dtype not in (jnp.float32, jnp.float64):
        target = target.astype(jnp.float32)
    preds, target = _spearman_corrcoef_update(preds, target)
    return _spearman_corrcoef_compute(preds, target)
