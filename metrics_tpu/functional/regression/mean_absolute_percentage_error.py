"""MAPE.

Parity: reference
``torchmetrics/functional/regression/mean_absolute_percentage_error.py``.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array

_EPSILON = 1.17e-06


def _mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = _EPSILON
) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    abs_diff = jnp.abs(preds - target)
    abs_per_error = abs_diff / jnp.clip(jnp.abs(target), epsilon, None)
    sum_abs_per_error = jnp.sum(abs_per_error)
    return sum_abs_per_error, target.size


def _mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs: Array) -> Array:
    return sum_abs_per_error / num_obs


def mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Compute mean absolute percentage error."""
    sum_abs_per_error, num_obs = _mean_absolute_percentage_error_update(jnp.asarray(preds), jnp.asarray(target))
    return _mean_absolute_percentage_error_compute(sum_abs_per_error, num_obs)
