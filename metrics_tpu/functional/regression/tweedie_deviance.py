"""Tweedie deviance score.

Parity: reference ``torchmetrics/functional/regression/tweedie_deviance.py``
(_tweedie_deviance_score_update :22, _tweedie_deviance_score_compute :81,
tweedie_deviance_score :102). Deviation: the Poisson branch uses ``xlogy`` so that
``target == 0`` contributes 0 (the reference's ``target * log(target/preds)``
produces NaN there; sklearn uses xlogy too).
"""
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.scipy.special import xlogy

from metrics_tpu.utils.checks import _check_same_shape, defer_value_check, register_deferred_message

Array = jax.Array

_CODE_DOMAIN = register_deferred_message(
    "Tweedie deviance inputs violate the positivity domain for the chosen `power`."
)


def _tweedie_deviance_score_update(preds: Array, targets: Array, power: float = 0.0) -> Tuple[Array, Array]:
    _check_same_shape(preds, targets)

    if 0 < power < 1:
        raise ValueError(f"Deviance Score is not defined for power={power}.")

    eager = not isinstance(preds, jax.core.Tracer) and not isinstance(targets, jax.core.Tracer)
    if not eager and power != 0:
        # traced under a compiled forward step: emit the domain check in-graph
        # (single conservative predicate; the eager branches below carry the
        # precise per-power messages)
        if power == 1 or 1 < power < 2:
            bad = jnp.any(preds <= 0) | jnp.any(targets < 0)
        elif power < 0:
            bad = jnp.any(preds <= 0)
        else:
            bad = jnp.any(preds <= 0) | jnp.any(targets <= 0)
        defer_value_check(bad, _CODE_DOMAIN)
    if power == 0:
        deviance_score = (targets - preds) ** 2
    elif power == 1:
        if eager and (bool(jnp.any(preds <= 0)) or bool(jnp.any(targets < 0))):
            raise ValueError(
                f"For power={power}, 'preds' has to be strictly positive and 'targets' cannot be negative."
            )
        deviance_score = 2 * (xlogy(targets, targets / preds) + preds - targets)
    elif power == 2:
        if eager and (bool(jnp.any(preds <= 0)) or bool(jnp.any(targets <= 0))):
            raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")
        deviance_score = 2 * (jnp.log(preds / targets) + targets / preds - 1)
    else:
        if power < 0:
            if eager and bool(jnp.any(preds <= 0)):
                raise ValueError(f"For power={power}, 'preds' has to be strictly positive.")
        elif 1 < power < 2:
            if eager and (bool(jnp.any(preds <= 0)) or bool(jnp.any(targets < 0))):
                raise ValueError(
                    f"For power={power}, 'targets' has to be strictly positive and 'preds' cannot be negative."
                )
        else:
            if eager and (bool(jnp.any(preds <= 0)) or bool(jnp.any(targets <= 0))):
                raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")

        term_1 = jnp.maximum(targets, 0.0) ** (2 - power) / ((1 - power) * (2 - power))
        term_2 = targets * preds ** (1 - power) / (1 - power)
        term_3 = preds ** (2 - power) / (2 - power)
        deviance_score = 2 * (term_1 - term_2 + term_3)

    sum_deviance_score = jnp.sum(deviance_score)
    num_observations = jnp.asarray(deviance_score.size)
    return sum_deviance_score, num_observations


def _tweedie_deviance_score_compute(sum_deviance_score: Array, num_observations: Array) -> Array:
    return sum_deviance_score / num_observations


def tweedie_deviance_score(preds: Array, targets: Array, power: float = 0.0) -> Array:
    """Compute the Tweedie deviance score for the given power."""
    sum_deviance_score, num_observations = _tweedie_deviance_score_update(
        jnp.asarray(preds), jnp.asarray(targets), power=power
    )
    return _tweedie_deviance_score_compute(sum_deviance_score, num_observations)
