"""MSE / RMSE.

Parity: reference ``torchmetrics/functional/regression/mean_squared_error.py``.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _mean_squared_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff)
    return sum_squared_error, target.size


def _mean_squared_error_compute(sum_squared_error: Array, n_obs: Array, squared: bool = True) -> Array:
    mse = sum_squared_error / n_obs
    return mse if squared else jnp.sqrt(mse)


def mean_squared_error(preds: Array, target: Array, squared: bool = True) -> Array:
    """Compute MSE (or RMSE with squared=False).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import mean_squared_error
        >>> target = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> print(f"{float(mean_squared_error(preds, target)):.4f}")
        0.8750
    """
    sum_squared_error, n_obs = _mean_squared_error_update(jnp.asarray(preds), jnp.asarray(target))
    return _mean_squared_error_compute(sum_squared_error, n_obs, squared=squared)
