"""MAE.

Parity: reference ``torchmetrics/functional/regression/mean_absolute_error.py``.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _mean_absolute_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    sum_abs_error = jnp.sum(jnp.abs(preds - target))
    return sum_abs_error, target.size


def _mean_absolute_error_compute(sum_abs_error: Array, n_obs: Array) -> Array:
    return sum_abs_error / n_obs


def mean_absolute_error(preds: Array, target: Array) -> Array:
    """Compute mean absolute error."""
    sum_abs_error, n_obs = _mean_absolute_error_update(jnp.asarray(preds), jnp.asarray(target))
    return _mean_absolute_error_compute(sum_abs_error, n_obs)
