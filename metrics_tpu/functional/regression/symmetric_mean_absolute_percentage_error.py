"""SMAPE.

Parity: reference
``torchmetrics/functional/regression/symmetric_mean_absolute_percentage_error.py``.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array

_EPSILON = 1.17e-06


def _symmetric_mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = _EPSILON
) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    abs_diff = jnp.abs(preds - target)
    abs_per_error = abs_diff / jnp.clip(jnp.abs(target) + jnp.abs(preds), epsilon, None)
    sum_abs_per_error = 2 * jnp.sum(abs_per_error)
    return sum_abs_per_error, target.size


def _symmetric_mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs: Array) -> Array:
    return sum_abs_per_error / num_obs


def symmetric_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Compute symmetric mean absolute percentage error."""
    sum_abs_per_error, num_obs = _symmetric_mean_absolute_percentage_error_update(
        jnp.asarray(preds), jnp.asarray(target)
    )
    return _symmetric_mean_absolute_percentage_error_compute(sum_abs_per_error, num_obs)
