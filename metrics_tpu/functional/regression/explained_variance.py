"""Explained variance.

Parity: reference ``torchmetrics/functional/regression/explained_variance.py``
(_explained_variance_update :20, _explained_variance_compute :41). In-place boolean
masking becomes nested ``jnp.where`` (static shapes).
"""
from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _explained_variance_update(preds: Array, target: Array) -> Tuple[int, Array, Array, Array, Array]:
    _check_same_shape(preds, target)
    n_obs = preds.shape[0]
    diff = target - preds
    sum_error = jnp.sum(diff, axis=0)
    sum_squared_error = jnp.sum(diff * diff, axis=0)
    sum_target = jnp.sum(target, axis=0)
    sum_squared_target = jnp.sum(target * target, axis=0)
    return n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target


def _explained_variance_compute(
    n_obs: Array,
    sum_error: Array,
    sum_squared_error: Array,
    sum_target: Array,
    sum_squared_target: Array,
    multioutput: str = "uniform_average",
) -> Array:
    diff_avg = sum_error / n_obs
    numerator = sum_squared_error / n_obs - diff_avg * diff_avg
    target_avg = sum_target / n_obs
    denominator = sum_squared_target / n_obs - target_avg * target_avg

    nonzero_numerator = numerator != 0
    nonzero_denominator = denominator != 0
    output_scores = jnp.where(
        nonzero_numerator & nonzero_denominator,
        1.0 - numerator / jnp.where(nonzero_denominator, denominator, 1.0),
        jnp.where(nonzero_numerator & ~nonzero_denominator, 0.0, jnp.ones_like(diff_avg)),
    )

    if multioutput == "raw_values":
        return output_scores
    if multioutput == "uniform_average":
        return jnp.mean(output_scores)
    if multioutput == "variance_weighted":
        denom_sum = jnp.sum(denominator)
        return jnp.sum(denominator / denom_sum * output_scores)
    raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {multioutput}")


def explained_variance(
    preds: Array, target: Array, multioutput: str = "uniform_average"
) -> Union[Array, Sequence[Array]]:
    """Compute explained variance."""
    n_obs, sum_error, ss_error, sum_target, ss_target = _explained_variance_update(
        jnp.asarray(preds), jnp.asarray(target)
    )
    return _explained_variance_compute(
        jnp.asarray(n_obs), sum_error, ss_error, sum_target, ss_target, multioutput
    )
