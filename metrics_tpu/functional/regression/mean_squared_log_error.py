"""MSLE.

Parity: reference ``torchmetrics/functional/regression/mean_squared_log_error.py``.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _mean_squared_log_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    sum_squared_log_error = jnp.sum((jnp.log1p(preds) - jnp.log1p(target)) ** 2)
    return sum_squared_log_error, target.size


def _mean_squared_log_error_compute(sum_squared_log_error: Array, n_obs: Array) -> Array:
    return sum_squared_log_error / n_obs


def mean_squared_log_error(preds: Array, target: Array) -> Array:
    """Compute mean squared log error."""
    sum_squared_log_error, n_obs = _mean_squared_log_error_update(jnp.asarray(preds), jnp.asarray(target))
    return _mean_squared_log_error_compute(sum_squared_log_error, n_obs)
