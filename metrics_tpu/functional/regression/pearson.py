"""Pearson correlation coefficient (streaming Chan-style statistics).

Parity: reference ``torchmetrics/functional/regression/pearson.py``
(_pearson_corrcoef_update :22, _pearson_corrcoef_compute :64, pearson_corrcoef :85).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _pearson_corrcoef_update(
    preds: Array,
    target: Array,
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    n_prior: Array,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """One streaming-statistics step over a batch."""
    _check_same_shape(preds, target)
    preds = jnp.squeeze(preds)
    target = jnp.squeeze(target)
    if preds.ndim > 1 or target.ndim > 1:
        raise ValueError("Expected both predictions and target to be 1 dimensional tensors.")

    n_obs = preds.size
    mx_new = (n_prior * mean_x + jnp.mean(preds) * n_obs) / (n_prior + n_obs)
    my_new = (n_prior * mean_y + jnp.mean(target) * n_obs) / (n_prior + n_obs)
    n_new = n_prior + n_obs
    var_x = var_x + jnp.sum((preds - mx_new) * (preds - mean_x))
    var_y = var_y + jnp.sum((target - my_new) * (target - mean_y))
    corr_xy = corr_xy + jnp.sum((preds - mx_new) * (target - mean_y))
    return mx_new, my_new, var_x, var_y, corr_xy, n_new


def _pearson_corrcoef_compute(var_x: Array, var_y: Array, corr_xy: Array, nb: Array) -> Array:
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    corr_xy = corr_xy / (nb - 1)
    corrcoef = jnp.squeeze(corr_xy / jnp.sqrt(var_x * var_y))
    return jnp.clip(corrcoef, -1.0, 1.0)


def pearson_corrcoef(preds: Array, target: Array) -> Array:
    """Compute the Pearson correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pearson_corrcoef
        >>> target = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> print(f"{float(pearson_corrcoef(preds, target)):.4f}")
        0.9202
    """
    preds = jnp.asarray(preds, dtype=jnp.float32) if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating) else jnp.asarray(preds)
    target = jnp.asarray(target, dtype=preds.dtype) if not jnp.issubdtype(jnp.asarray(target).dtype, jnp.floating) else jnp.asarray(target)
    zero = jnp.zeros([], dtype=preds.dtype)
    _, _, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, zero, zero, zero, zero, zero, zero
    )
    return _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)
