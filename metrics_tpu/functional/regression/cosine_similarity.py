"""Cosine similarity over the batch dim.

Parity: reference ``torchmetrics/functional/regression/cosine_similarity.py``.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _cosine_similarity_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    return preds.astype(jnp.float32), target.astype(jnp.float32)


def _cosine_similarity_compute(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    dot_product = jnp.sum(preds * target, axis=-1)
    preds_norm = jnp.linalg.norm(preds, axis=-1)
    target_norm = jnp.linalg.norm(target, axis=-1)
    similarity = dot_product / (preds_norm * target_norm)
    if reduction == "sum":
        return jnp.sum(similarity)
    if reduction == "mean":
        return jnp.mean(similarity)
    return similarity


def cosine_similarity(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """Compute cosine similarity rowwise with sum/mean/none reduction."""
    preds, target = _cosine_similarity_update(jnp.asarray(preds), jnp.asarray(target))
    return _cosine_similarity_compute(preds, target, reduction)
