"""Signal distortion ratio (SDR) and scale-invariant SDR.

Parity: reference ``torchmetrics/functional/audio/sdr.py`` (signal_distortion_ratio
:49, scale_invariant_signal_distortion_ratio :188). The reference delegates the
Toeplitz filter solve to the native ``fast_bss_eval`` package; here the same
"SDR — Medium Rare" algorithm (Scheibler 2021) is implemented natively in jnp:
FFT auto-/cross-correlations, an explicit (L, L) Toeplitz system solved on device,
coherence -> dB. Everything is batched/jit-safe; the solve maps to XLA's native LU.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _normalize(x: Array) -> Array:
    return x / jnp.clip(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12, None)


def _compute_autocorr_crosscorr(target: Array, preds: Array, corr_len: int) -> Tuple[Array, Array]:
    """FFT-based autocorrelation of target and cross-correlation target->preds."""
    import math

    n = target.shape[-1]
    n_fft = int(2 ** math.ceil(math.log2(n + corr_len)))  # shapes are static under jit
    t_fft = jnp.fft.rfft(target, n=n_fft, axis=-1)
    p_fft = jnp.fft.rfft(preds, n=n_fft, axis=-1)
    acf = jnp.fft.irfft(t_fft * jnp.conj(t_fft), n=n_fft, axis=-1)[..., :corr_len]
    xcorr = jnp.fft.irfft(jnp.conj(t_fft) * p_fft, n=n_fft, axis=-1)[..., :corr_len]
    return acf, xcorr


def _toeplitz(c: Array) -> Array:
    """Symmetric Toeplitz matrix from first column ``c`` (batched over leading dims)."""
    n = c.shape[-1]
    idx = jnp.abs(jnp.arange(n)[:, None] - jnp.arange(n)[None, :])
    return c[..., idx]


def signal_distortion_ratio(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> Array:
    """SDR with an optimal length-L distortion filter. Parity: reference ``:49-186``."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    _check_same_shape(preds, target)

    if not jnp.issubdtype(preds.dtype, jnp.floating):
        preds = preds.astype(jnp.float32)
    if preds.dtype == jnp.float16 or preds.dtype == jnp.bfloat16:
        preds = preds.astype(jnp.float32)
    target = target.astype(preds.dtype)

    if zero_mean:
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
        target = target - jnp.mean(target, axis=-1, keepdims=True)

    preds = _normalize(preds)
    target = _normalize(target)

    acf, xcorr = _compute_autocorr_crosscorr(target, preds, corr_len=filter_length)

    if load_diag is not None:
        acf = acf.at[..., 0].add(load_diag)

    # direct Toeplitz solve (use_cg_iter kept for API parity; direct LU on the MXU is
    # already fast for L=512 and more accurate than truncated CG)
    r_mat = _toeplitz(acf)
    sol = jnp.linalg.solve(r_mat, xcorr[..., None])[..., 0]

    coh = jnp.einsum("...l,...l->...", xcorr, sol)
    ratio = coh / (1 - coh)
    return 10.0 * jnp.log10(ratio)


def sdr(preds: Array, target: Array, **kwargs) -> Array:
    """Deprecated alias of signal_distortion_ratio."""
    from metrics_tpu.utils.prints import rank_zero_warn

    rank_zero_warn("`sdr` was renamed to `signal_distortion_ratio` and it will be removed.", DeprecationWarning)
    return signal_distortion_ratio(preds, target, **kwargs)


def scale_invariant_signal_distortion_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SI-SDR. Parity: reference ``:188-240``."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype if jnp.issubdtype(preds.dtype, jnp.floating) else jnp.float32).eps

    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)

    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + eps) / (
        jnp.sum(target ** 2, axis=-1, keepdims=True) + eps
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    val = (jnp.sum(target_scaled ** 2, axis=-1) + eps) / (jnp.sum(noise ** 2, axis=-1) + eps)
    return 10 * jnp.log10(val)


def si_sdr(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """Deprecated alias of scale_invariant_signal_distortion_ratio."""
    from metrics_tpu.utils.prints import rank_zero_warn

    rank_zero_warn(
        "`si_sdr` was renamed to `scale_invariant_signal_distortion_ratio` and it will be removed.",
        DeprecationWarning,
    )
    return scale_invariant_signal_distortion_ratio(preds, target, zero_mean)
