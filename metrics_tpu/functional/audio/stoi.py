"""Stateless functional STOI — native on-device DSP (no pystoi dependency).

Parity target: reference ``torchmetrics/functional/audio/stoi.py:28``, which
*requires* the native ``pystoi`` package and runs the DSP per-signal on the
host. This build implements the STOI algorithm (Taal et al., "An Algorithm for
Intelligibility Prediction of Time-Frequency Weighted Noisy Speech", IEEE TASL
2011 — the spec pystoi transcribes) directly in jnp with static shapes, so it
runs jitted/vmapped on TPU and needs no host round-trips:

* polyphase resampling to the 10 kHz model rate (scipy ``resample_poly``
  semantics: kaiser-5.0 windowed-sinc, one dilated/strided conv on device);
* silent-frame removal (40 dB dynamic range on the clean signal's windowed
  frame energies) with static shapes: frames are compacted by a stable
  argsort-gather and overlap-added into a fixed-size buffer, with the kept
  count carried as data;
* 256-sample hann frames / 512-pt rFFT / 15 one-third-octave bands (150 Hz
  lowest center), framed with pystoi's EXCLUSIVE convention
  (``range(0, len - N, hop)`` — see ``_frame``);
* 30-frame sliding segments; standard mode clips the normalized degraded
  segment at -15 dB SDR and averages band correlations, extended mode (ESTOI,
  Jensen & Taal 2016) row+column-normalizes each segment.

Dynamic frame counts are handled branch-free (validity masks), so the whole
metric is one compiled program per (length, fs, extended) signature. Fewer
than 30 frames after silent-frame removal returns 1e-5 (pystoi's contract).

Oracle coverage: ``tests/audio/test_stoi_native.py`` checks the resampler
against ``scipy.signal.resample_poly``, the full pipeline against an
independent host numpy implementation, the perfect-intelligibility fixed
point, SNR monotonicity, and (gated) pystoi itself when installed.
"""
from functools import lru_cache, partial
from math import gcd
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array

FS = 10_000          # internal model rate (Hz)
N_FRAME = 256        # analysis window
NFFT = 512
HOP = N_FRAME // 2
NUM_BANDS = 15
MIN_FREQ = 150.0     # center frequency of the lowest third-octave band
N_SEG = 30           # frames per intermediate-intelligibility segment
BETA = -15.0         # lower SDR clip bound (dB)
DYN_RANGE = 40.0     # silent-frame energy range (dB)
_EPS = float(np.finfo(np.float32).eps)


@lru_cache(maxsize=None)
def _resample_plan(fs_in: int, fs_out: int):
    """(taps, up, down, n_pre_remove) for scipy-style resample_poly, or None."""
    g = gcd(fs_in, fs_out)
    up, down = fs_out // g, fs_in // g
    if up == down:
        return None
    max_rate = max(up, down)
    f_c = 1.0 / max_rate
    half_len = 10 * max_rate
    m = np.arange(-half_len, half_len + 1, dtype=np.float64)
    h = f_c * np.sinc(f_c * m) * np.kaiser(2 * half_len + 1, 5.0)
    h /= h.sum()          # firwin lowpass scaling: unit DC response
    h *= up               # resample_poly gain compensation
    # align the output grid the way scipy does: left-pad the filter so the
    # first kept output sample sits on the input's t=0
    n_pre_pad = (down - half_len % down) % down
    n_pre_remove = (half_len + n_pre_pad) // down
    h = np.concatenate([np.zeros(n_pre_pad), h])
    # cache HOST arrays: a jnp constant materialised inside a jit trace is a
    # tracer, and caching it would leak it into later traces
    return np.asarray(h, np.float32), up, down, n_pre_remove


def _resample(x: Array, fs_in: int, fs_out: int) -> Array:
    """Polyphase resample along the last axis (scipy resample_poly semantics)."""
    plan = _resample_plan(int(fs_in), int(fs_out))
    if plan is None:
        return x
    taps, up, down, n_pre_remove = plan
    n_in = x.shape[-1]
    n_out = -(-n_in * up // down)  # ceil
    lead = x.shape[:-1]
    k = taps.shape[0]
    # upfirdn(h, x, up, down) = full convolution of the zero-stuffed signal
    # with the taps, kept every `down` samples: out[j] = y_full[m_j],
    # m_j = (n_pre_remove + j) * down, y_full[m] = sum_i x[i] * taps[m - i*up].
    # The obvious single-op form (conv with lhs_dilation=up + window stride
    # `down`) MISCOMPILES on XLA:CPU (observed on this build: wrong samples,
    # not merely reordered); materialising the stuffed signal instead costs
    # up * n_in memory (100x at 44.1kHz). So:
    # HIGHEST precision everywhere below: on TPU the default matmul/conv
    # precision is bf16 passes, whose ~8-bit mantissa visibly shifts
    # third-octave envelopes and resampled samples. The pin lives ON THE OPS,
    # not in a global flag, so the metric is precision-safe however the
    # caller configures jax.
    _hi = jax.lax.Precision.HIGHEST
    if up == 1:
        # pure decimation: a plain strided conv (no dilation anywhere) is
        # exact and minimal
        lhs = x.reshape((-1, 1, n_in))
        rhs = jnp.asarray(taps[::-1].reshape((1, 1, k)))
        y = jax.lax.conv_general_dilated(
            lhs, rhs, window_strides=(down,), padding=((k - 1, k - 1),),
            precision=_hi,
        )
        y = y[..., n_pre_remove:n_pre_remove + n_out]
        return y.reshape(lead + (n_out,))
    # rational rate: evaluate the polyphase sum directly as a gather + batched
    # contraction. Each output j touches only the <= k//up + 1 real input
    # samples under its tap window (index/weight matrices are host-side
    # numpy, exact integers), so compute AND memory are O(n_out * k/up) —
    # the true polyphase cost, independent of `up`.
    t_cols = k // up + 1
    j = np.arange(n_out)
    m = (n_pre_remove + j) * down
    i_lo = np.maximum(0, -(-(m - k + 1) // up))          # ceil((m-k+1)/up)
    ii = i_lo[:, None] + np.arange(t_cols)[None, :]       # (n_out, T) input idx
    tap_idx = m[:, None] - ii * up
    valid = (tap_idx >= 0) & (tap_idx < k) & (ii < n_in)
    weights = np.where(valid, taps[np.clip(tap_idx, 0, k - 1)], 0.0).astype(np.float32)
    gathered = x[..., jnp.asarray(np.clip(ii, 0, n_in - 1))]   # (..., n_out, T)
    y = jnp.einsum("...jt,jt->...j", gathered, jnp.asarray(weights), precision=_hi)
    return y.reshape(lead + (n_out,))


@lru_cache(maxsize=None)
def _third_octave_matrix() -> Tuple[np.ndarray, int]:
    """(NUM_BANDS, NFFT//2+1) 0/1 band matrix on the 10 kHz rFFT grid (host)."""
    f = np.linspace(0, FS, NFFT + 1)[: NFFT // 2 + 1]
    k = np.arange(NUM_BANDS, dtype=np.float64)
    freq_low = MIN_FREQ * 2.0 ** ((2 * k - 1) / 6)
    freq_high = MIN_FREQ * 2.0 ** ((2 * k + 1) / 6)
    obm = np.zeros((NUM_BANDS, f.size))
    for i in range(NUM_BANDS):
        fl = int(np.argmin(np.square(f - freq_low[i])))
        fh = int(np.argmin(np.square(f - freq_high[i])))
        obm[i, fl:fh] = 1.0
    return np.asarray(obm, np.float32), f.size


@lru_cache(maxsize=None)
def _hann_window() -> np.ndarray:
    # the trimmed hanning pystoi/matlab use: hanning(N+2)[1:-1] (host array;
    # a jnp constant built under a trace would be a leakable tracer)
    return np.asarray(np.hanning(N_FRAME + 2)[1:-1], np.float32)


def _frame(x: Array) -> Array:
    """(frames, N_FRAME) strided view at HOP — pystoi's EXCLUSIVE convention.

    pystoi/MATLAB frame with ``range(0, len - N, hop)`` (``pystoi/utils.py``
    stft and remove_silent_frames): ``ceil((len - N) / hop)`` frames, which
    DROPS the final frame whenever ``(len - N) % hop == 0`` (always true for
    the post-silence-removal OLA buffer, whose length is an exact hop
    multiple). The seed used the inclusive ``(len - N) // hop + 1`` count —
    measured up to ~1.3e-2 score difference vs pystoi on the test corpus
    (ADVICE r5 medium #2); this build adopts the upstream convention so the
    gated pystoi parity test compares like for like.
    """
    n_frames = max(0, -(-(x.shape[-1] - N_FRAME) // HOP))
    offs = jnp.arange(n_frames)[:, None] * HOP + jnp.arange(N_FRAME)[None, :]
    return x[offs]


def _stoi_single(deg: Array, clean: Array, fs: int, extended: bool) -> Array:
    """STOI of one (degraded, clean) pair, fully in-trace, static shapes."""
    deg = _resample(deg, fs, FS)
    clean = _resample(clean, fs, FS)
    if clean.shape[-1] <= N_FRAME:
        raise ValueError(
            f"STOI needs more than {N_FRAME} samples at {FS} Hz after resampling "
            f"(pystoi's exclusive framing yields zero frames otherwise); "
            f"got {clean.shape[-1]} (input rate {fs} Hz)."
        )
    w = jnp.asarray(_hann_window())

    # ---- silent-frame removal (clean-signal energies, 40 dB range) ----------
    clean_frames = _frame(clean) * w          # (F, N_FRAME)
    deg_frames = _frame(deg) * w
    n_f = clean_frames.shape[0]
    energies = 20.0 * jnp.log10(jnp.linalg.norm(clean_frames, axis=-1) + _EPS)
    keep = energies > (jnp.max(energies) - DYN_RANGE)
    n_kept = jnp.sum(keep.astype(jnp.int32))
    # stable compaction: kept frames first, original order preserved
    order = jnp.argsort(~keep, stable=True)
    valid = jnp.arange(n_f) < n_kept
    clean_kept = jnp.where(valid[:, None], clean_frames[order], 0.0)
    deg_kept = jnp.where(valid[:, None], deg_frames[order], 0.0)
    # overlap-add reconstruction into a fixed-size buffer (hann @ 50% overlap)
    n_buf = (n_f - 1) * HOP + N_FRAME
    offs = jnp.arange(n_f)[:, None] * HOP + jnp.arange(N_FRAME)[None, :]
    clean_sil = jnp.zeros((n_buf,), clean.dtype).at[offs].add(clean_kept)
    deg_sil = jnp.zeros((n_buf,), deg.dtype).at[offs].add(deg_kept)

    # ---- STFT -> third-octave band envelopes --------------------------------
    obm = jnp.asarray(_third_octave_matrix()[0])
    spec_c = jnp.fft.rfft(_frame(clean_sil) * w, n=NFFT)   # (F, NFFT/2+1)
    spec_d = jnp.fft.rfft(_frame(deg_sil) * w, n=NFFT)
    # band matmuls at HIGHEST for the same reason as the resampler conv
    _hi = jax.lax.Precision.HIGHEST
    x_tob = jnp.sqrt(jnp.matmul(jnp.abs(spec_c) ** 2, obm.T, precision=_hi))  # clean    (F, 15)
    y_tob = jnp.sqrt(jnp.matmul(jnp.abs(spec_d) ** 2, obm.T, precision=_hi))  # degraded (F, 15)

    # ---- 30-frame sliding segments ------------------------------------------
    # exclusive framing of the OLA buffer gives n_f - 1 spectral frames; of
    # those, only the first n_kept - 1 come from kept audio (pystoi's stft of
    # the exact-length reconstructed signal has n_kept - 1 frames)
    n_spec = x_tob.shape[0]
    n_seg = n_spec - N_SEG + 1
    if n_seg < 1:
        return jnp.float32(1e-5)
    seg_ix = jnp.arange(n_seg)[:, None] + jnp.arange(N_SEG)[None, :]
    x_seg = jnp.transpose(x_tob[seg_ix], (0, 2, 1))         # (S, 15, N_SEG)
    y_seg = jnp.transpose(y_tob[seg_ix], (0, 2, 1))
    # frames past the compacted signal are synthetic zeros: a segment is real
    # only when all its N_SEG frames come from kept audio
    seg_ok = (jnp.arange(n_seg) + N_SEG) <= n_kept - 1
    n_valid = jnp.sum(seg_ok.astype(jnp.float32))

    if extended:
        def row_col_norm(s):
            s = s - jnp.mean(s, axis=-1, keepdims=True)
            s = s / (jnp.linalg.norm(s, axis=-1, keepdims=True) + _EPS)
            s = s - jnp.mean(s, axis=-2, keepdims=True)
            return s / (jnp.linalg.norm(s, axis=-2, keepdims=True) + _EPS)

        per_seg = jnp.sum(row_col_norm(x_seg) * row_col_norm(y_seg), axis=(1, 2)) / N_SEG
        total = jnp.sum(jnp.where(seg_ok, per_seg, 0.0))
        score = total / jnp.maximum(n_valid, 1.0)
    else:
        # normalize the degraded segment's energy per band to the clean one,
        # clip at -BETA dB SDR, then per-band Pearson correlation
        alpha = jnp.linalg.norm(x_seg, axis=-1, keepdims=True) / (
            jnp.linalg.norm(y_seg, axis=-1, keepdims=True) + _EPS
        )
        y_prime = jnp.minimum(y_seg * alpha, x_seg * (1.0 + 10.0 ** (-BETA / 20.0)))
        xc = x_seg - jnp.mean(x_seg, axis=-1, keepdims=True)
        yc = y_prime - jnp.mean(y_prime, axis=-1, keepdims=True)
        xc = xc / (jnp.linalg.norm(xc, axis=-1, keepdims=True) + _EPS)
        yc = yc / (jnp.linalg.norm(yc, axis=-1, keepdims=True) + _EPS)
        per_seg = jnp.sum(xc * yc, axis=(1, 2))             # sum over bands
        total = jnp.sum(jnp.where(seg_ok, per_seg, 0.0))
        score = total / (jnp.maximum(n_valid, 1.0) * NUM_BANDS)

    # pystoi contract: fewer than N_SEG frames after silence removal -> 1e-5
    return jnp.where(n_valid > 0, score, jnp.float32(1e-5))


@partial(jax.jit, static_argnames=("fs", "extended"))
def _stoi_batch(deg: Array, clean: Array, fs: int, extended: bool) -> Array:
    if deg.ndim == 1:
        return _stoi_single(deg, clean, fs, extended)
    flat_d = deg.reshape((-1, deg.shape[-1]))
    flat_c = clean.reshape((-1, clean.shape[-1]))
    out = jax.vmap(lambda d, c: _stoi_single(d, c, fs, extended))(flat_d, flat_c)
    return out.reshape(deg.shape[:-1])


def stoi(preds: Any, target: Any, fs: int, extended: bool = False, keep_same_device: bool = False) -> Array:
    """Short-time objective intelligibility.

    Args:
        preds: estimated (degraded) signal, shape ``[..., time]``.
        target: reference (clean) signal, shape ``[..., time]``.
        fs: sampling frequency in Hz.
        extended: use the extended (ESTOI) variant.
        keep_same_device: accepted for reference API compatibility; scores are
            returned as device arrays either way.

    Unlike the reference (which refuses to run without the host-side
    ``pystoi`` package, ``torchmetrics/audio/stoi.py:23``), the DSP is native
    jnp: jitted, vmapped over leading dims, TPU-resident end to end.
    """
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    return _stoi_batch(preds, target, int(fs), bool(extended))
