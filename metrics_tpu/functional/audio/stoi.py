"""Stateless functional STOI.

Parity: reference ``torchmetrics/functional/audio/stoi.py:28`` — the DSP runs
in the native ``pystoi`` package on the host (same backend the reference
wraps); scores return as device arrays. Input ``[..., time]`` -> ``[...]``.
"""
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.imports import _PYSTOI_AVAILABLE

Array = jax.Array


def stoi(preds: Any, target: Any, fs: int, extended: bool = False, keep_same_device: bool = False) -> Array:
    """Short-time objective intelligibility.

    Args:
        preds: estimated signal, shape ``[..., time]``.
        target: reference signal, shape ``[..., time]``.
        fs: sampling frequency in Hz.
        extended: use the extended (ESTOI) variant.
        keep_same_device: accepted for reference API compatibility; scores are
            returned as device arrays either way.
    """
    if not _PYSTOI_AVAILABLE:
        raise ModuleNotFoundError(
            "STOI metric requires that pystoi is installed. Either install as `pip install pystoi`."
        )
    from pystoi import stoi as stoi_backend

    preds_np = np.asarray(preds)
    target_np = np.asarray(target)
    _check_same_shape(preds_np, target_np)

    if preds_np.ndim == 1:
        return jnp.asarray(stoi_backend(target_np, preds_np, fs, extended=extended), dtype=jnp.float32)
    flat_p = preds_np.reshape(-1, preds_np.shape[-1])
    flat_t = target_np.reshape(-1, target_np.shape[-1])
    scores = np.empty(flat_p.shape[0], dtype=np.float32)
    for b in range(flat_p.shape[0]):
        scores[b] = stoi_backend(flat_t[b], flat_p[b], fs, extended=extended)
    return jnp.asarray(scores.reshape(preds_np.shape[:-1]))
