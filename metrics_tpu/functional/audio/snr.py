"""Signal-to-noise ratio family.

Parity: reference ``torchmetrics/functional/audio/snr.py`` (signal_noise_ratio :24,
scale_invariant_signal_noise_ratio :78) and deprecated ``snr``/``si_snr`` aliases.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


def signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SNR = 10 log10(P_signal / P_noise).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import signal_noise_ratio
        >>> target = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        >>> preds = jnp.asarray([1.1, 2.1, 2.9, 4.2])
        >>> print(f"{float(signal_noise_ratio(preds, target)):.4f}")
        26.3202
    """
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps

    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)

    noise = target - preds
    snr_value = (jnp.sum(target ** 2, axis=-1) + eps) / (jnp.sum(noise ** 2, axis=-1) + eps)
    return 10 * jnp.log10(snr_value)


def snr(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """Deprecated alias of signal_noise_ratio."""
    rank_zero_warn("`snr` was renamed to `signal_noise_ratio` and it will be removed.", DeprecationWarning)
    return signal_noise_ratio(preds, target, zero_mean)


def scale_invariant_signal_noise_ratio(preds: Array, target: Array) -> Array:
    """SI-SNR (scale-invariant SDR with zero-mean).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import scale_invariant_signal_noise_ratio
        >>> target = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        >>> preds = jnp.asarray([1.1, 2.1, 2.9, 4.2])
        >>> print(f"{float(scale_invariant_signal_noise_ratio(preds, target)):.4f}")
        20.3551
    """
    from metrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio

    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=True)


def si_snr(preds: Array, target: Array) -> Array:
    """Deprecated alias of scale_invariant_signal_noise_ratio."""
    rank_zero_warn(
        "`si_snr` was renamed to `scale_invariant_signal_noise_ratio` and it will be removed.", DeprecationWarning
    )
    return scale_invariant_signal_noise_ratio(preds, target)
