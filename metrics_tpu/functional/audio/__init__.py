from metrics_tpu.functional.audio.pesq import pesq
from metrics_tpu.functional.audio.pit import pit, pit_permutate
from metrics_tpu.functional.audio.sdr import (
    scale_invariant_signal_distortion_ratio,
    sdr,
    si_sdr,
    signal_distortion_ratio,
)
from metrics_tpu.functional.audio.snr import (
    scale_invariant_signal_noise_ratio,
    si_snr,
    signal_noise_ratio,
    snr,
)
from metrics_tpu.functional.audio.stoi import stoi
