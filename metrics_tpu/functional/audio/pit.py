"""Permutation-invariant training (PIT) metric wrapper.

Parity: reference ``torchmetrics/functional/audio/pit.py``
(_find_best_perm_by_linear_sum_assignment :29, _find_best_perm_by_exhuastive_method
:57, pit :101, pit_permutate :190).

TPU notes: the (spk x spk) metric matrix is built with two vmapped metric calls (no
python pair loop); the exhaustive best-permutation search is a static gather over the
precomputed permutation table — fully jit-safe for the typical 2-4 speaker case.
The scipy Hungarian path is kept for large speaker counts (host-side, eager only).
"""
from itertools import permutations
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array

_ps_cache: Dict[int, np.ndarray] = {}


def _perm_table(spk_num: int) -> np.ndarray:
    if spk_num not in _ps_cache:
        _ps_cache[spk_num] = np.asarray(list(permutations(range(spk_num)))).T  # [spk, perm]
    return _ps_cache[spk_num]


def _find_best_perm_by_linear_sum_assignment(metric_mtx: Array, eval_max: bool) -> Tuple[Array, Array]:
    from scipy.optimize import linear_sum_assignment

    mmtx = np.asarray(metric_mtx)
    best_perm = jnp.asarray([linear_sum_assignment(pwm, eval_max)[1] for pwm in mmtx])
    best_metric = jnp.mean(
        jnp.take_along_axis(metric_mtx, best_perm[:, :, None], axis=2), axis=(-1, -2)
    )
    return best_metric, best_perm


def _find_best_perm_by_exhuastive_method(metric_mtx: Array, eval_max: bool) -> Tuple[Array, Array]:
    batch_size, spk_num = metric_mtx.shape[:2]
    ps = jnp.asarray(_perm_table(spk_num))  # [spk, perm]
    perm_num = ps.shape[-1]
    bps = jnp.broadcast_to(ps[None, ...], (batch_size, spk_num, perm_num))
    metric_of_ps_details = jnp.take_along_axis(metric_mtx, bps, axis=2)
    metric_of_ps = jnp.mean(metric_of_ps_details, axis=1)  # [batch, perm]
    if eval_max:
        best_indexes = jnp.argmax(metric_of_ps, axis=1)
        best_metric = jnp.max(metric_of_ps, axis=1)
    else:
        best_indexes = jnp.argmin(metric_of_ps, axis=1)
        best_metric = jnp.min(metric_of_ps, axis=1)
    best_perm = ps.T[best_indexes, :]
    return best_metric, best_perm


def pit(
    preds: Array, target: Array, metric_func: Callable, eval_func: str = "max", **kwargs: Any
) -> Tuple[Array, Array]:
    """Best-permutation metric over speakers. Parity: reference ``pit:101-188``."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    _check_same_shape(preds, target)
    if eval_func not in ["max", "min"]:
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    spk_num = target.shape[1]
    # pairwise metric matrix: metric_mtx[b, i, j] = metric(preds[:, j], target[:, i]);
    # the loop is over the (small, static) speaker count — each entry is a batched call
    cols = []
    for i in range(spk_num):
        rows = []
        for j in range(spk_num):
            rows.append(metric_func(preds[:, j], target[:, i], **kwargs))
        cols.append(jnp.stack(rows, axis=1))
    metric_mtx = jnp.stack(cols, axis=1)  # [batch, spk(target), spk(pred)]

    eval_max = eval_func == "max"
    if spk_num > 3 and not isinstance(metric_mtx, jax.core.Tracer):
        best_metric, best_perm = _find_best_perm_by_linear_sum_assignment(metric_mtx, eval_max)
    else:
        best_metric, best_perm = _find_best_perm_by_exhuastive_method(metric_mtx, eval_max)
    return best_metric, best_perm


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Reorder predictions according to the best permutation. Parity: ``:190-204``."""
    return jnp.take_along_axis(preds, perm[..., None], axis=1)
