"""Stateless functional PESQ.

Parity: reference ``torchmetrics/functional/audio/pesq.py:28`` — the ITU P.862
DSP runs in the native ``pesq`` package on the host (it is a standardized C
implementation, same as the reference uses); only the resulting scores live on
device. Input ``[..., time]`` -> scores of shape ``[...]``.
"""
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.imports import _PESQ_AVAILABLE

Array = jax.Array


def pesq(preds: Any, target: Any, fs: int, mode: str, keep_same_device: bool = False) -> Array:
    """Perceptual evaluation of speech quality.

    Args:
        preds: estimated signal, shape ``[..., time]``.
        target: reference signal, shape ``[..., time]``.
        fs: sampling frequency (8000 or 16000 Hz).
        mode: ``'wb'`` (wide-band) or ``'nb'`` (narrow-band).
        keep_same_device: accepted for reference API compatibility; scores are
            returned as device arrays either way.
    """
    if not _PESQ_AVAILABLE:
        raise ModuleNotFoundError(
            "PESQ metric requires that pesq is installed. Either install as `pip install pesq`."
        )
    import pesq as pesq_backend

    if fs not in (8000, 16000):
        raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
    if mode not in ("wb", "nb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
    preds_np = np.asarray(preds)
    target_np = np.asarray(target)
    _check_same_shape(preds_np, target_np)

    if preds_np.ndim == 1:
        return jnp.asarray(pesq_backend.pesq(fs, target_np, preds_np, mode), dtype=jnp.float32)
    flat_p = preds_np.reshape(-1, preds_np.shape[-1])
    flat_t = target_np.reshape(-1, target_np.shape[-1])
    scores = np.empty(flat_p.shape[0], dtype=np.float32)
    for b in range(flat_p.shape[0]):
        scores[b] = pesq_backend.pesq(fs, flat_t[b], flat_p[b], mode)
    return jnp.asarray(scores.reshape(preds_np.shape[:-1]))
