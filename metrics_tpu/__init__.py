"""metrics_tpu — TPU-native machine-learning metrics (JAX/XLA/pjit/Pallas).

A brand-new framework with the capabilities of TorchMetrics v0.7 (reference:
``getgaurav2/metrics``), redesigned TPU-first: metrics are pytree states + pure
``init/update/merge/compute`` functions, distributed sync lowers to XLA collectives
(psum/all_gather) over named mesh axes, and a MetricCollection syncs in one fused
collective bundle inside the training step.
"""
import logging

_logger = logging.getLogger("metrics_tpu")
_logger.addHandler(logging.StreamHandler())
_logger.setLevel(logging.INFO)

__version__ = "0.1.0"

from metrics_tpu.aggregation import (  # noqa: E402
    BaseAggregator,
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    SumMetric,
)
from metrics_tpu.classification import (  # noqa: E402
    AUC,
    AUROC,
    F1,
    Accuracy,
    AveragePrecision,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    CalibrationError,
    CohenKappa,
    ConfusionMatrix,
    F1Score,
    FBeta,
    HammingDistance,
    Hinge,
    HingeLoss,
    IoU,
    JaccardIndex,
    KLDivergence,
    MatthewsCorrcoef,
    MatthewsCorrCoef,
    Precision,
    PrecisionRecallCurve,
    Recall,
    ROC,
    Specificity,
    StatScores,
)
from metrics_tpu.collections import MetricCollection  # noqa: E402
from metrics_tpu.regression import (  # noqa: E402
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
)
from metrics_tpu.metric import CompositionalMetric, Metric  # noqa: E402
from metrics_tpu.audio import (  # noqa: E402
    PIT,
    SDR,
    SNR,
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    SignalDistortionRatio,
    SignalNoiseRatio,
)
from metrics_tpu.image import (  # noqa: E402
    FID,
    IS,
    KID,
    LPIPS,
    PSNR,
    SSIM,
    FrechetInceptionDistance,
    InceptionScore,
    KernelInceptionDistance,
    MultiScaleStructuralSimilarityIndexMeasure,
)
from metrics_tpu.parallel import MeshConfig, metric_axis  # noqa: E402
from metrics_tpu.wrappers import (  # noqa: E402
    BootStrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
)
from metrics_tpu import functional  # noqa: E402

__all__ = [
    "AUC",
    "AUROC",
    "Accuracy",
    "AveragePrecision",
    "BaseAggregator",
    "BinnedAveragePrecision",
    "BootStrapper",
    "MetricTracker",
    "MinMaxMetric",
    "MultioutputWrapper",
    "BinnedPrecisionRecallCurve",
    "BinnedRecallAtFixedPrecision",
    "CalibrationError",
    "CatMetric",
    "CohenKappa",
    "CompositionalMetric",
    "ConfusionMatrix",
    "CosineSimilarity",
    "ExplainedVariance",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "PearsonCorrCoef",
    "R2Score",
    "SpearmanCorrCoef",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
    "F1",
    "F1Score",
    "FBeta",
    "FID",
    "FrechetInceptionDistance",
    "IS",
    "InceptionScore",
    "KID",
    "KernelInceptionDistance",
    "LPIPS",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PIT",
    "PSNR",
    "PermutationInvariantTraining",
    "SDR",
    "SNR",
    "SSIM",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
    "HammingDistance",
    "Hinge",
    "HingeLoss",
    "IoU",
    "JaccardIndex",
    "KLDivergence",
    "MatthewsCorrCoef",
    "MatthewsCorrcoef",
    "MaxMetric",
    "MeanMetric",
    "MeshConfig",
    "Metric",
    "MetricCollection",
    "MinMetric",
    "Precision",
    "PrecisionRecallCurve",
    "ROC",
    "Recall",
    "Specificity",
    "StatScores",
    "SumMetric",
    "functional",
    "metric_axis",
    "__version__",
]
