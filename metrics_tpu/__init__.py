"""metrics_tpu — TPU-native machine-learning metrics (JAX/XLA/pjit/Pallas).

A brand-new framework with the capabilities of TorchMetrics v0.7 (reference:
``getgaurav2/metrics``), redesigned TPU-first: metrics are pytree states + pure
``init/update/merge/compute`` functions, distributed sync lowers to XLA collectives
(psum/all_gather) over named mesh axes, and a MetricCollection syncs in one fused
collective bundle inside the training step.
"""
import logging

_logger = logging.getLogger("metrics_tpu")
_logger.addHandler(logging.StreamHandler())
_logger.setLevel(logging.INFO)

__version__ = "0.1.0"

from metrics_tpu.aggregation import (  # noqa: E402
    BaseAggregator,
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    SumMetric,
)
from metrics_tpu.classification import (  # noqa: E402
    F1,
    Accuracy,
    F1Score,
    FBeta,
    HammingDistance,
    Precision,
    Recall,
    Specificity,
    StatScores,
)
from metrics_tpu.collections import MetricCollection  # noqa: E402
from metrics_tpu.metric import CompositionalMetric, Metric  # noqa: E402
from metrics_tpu.parallel import MeshConfig, metric_axis  # noqa: E402
from metrics_tpu import functional  # noqa: E402

__all__ = [
    "Accuracy",
    "BaseAggregator",
    "CatMetric",
    "CompositionalMetric",
    "F1",
    "F1Score",
    "FBeta",
    "HammingDistance",
    "MaxMetric",
    "MeanMetric",
    "MeshConfig",
    "Metric",
    "MetricCollection",
    "MinMetric",
    "Precision",
    "Recall",
    "Specificity",
    "StatScores",
    "SumMetric",
    "functional",
    "metric_axis",
    "__version__",
]
