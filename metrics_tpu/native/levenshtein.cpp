// Native Levenshtein (edit distance) kernel for the text metric family.
//
// The reference implements edit distance in pure Python
// (torchmetrics/functional/text/helper.py:64-306); for corpus-scale WER/CER the
// host-side DP loop dominates, so this build runs it natively. Tokens are
// pre-mapped to int32 ids by the Python layer (works for words and characters
// alike); the batch entry point walks packed (offsets, data) arrays so one FFI
// call scores a whole corpus.
//
// Built with: g++ -O3 -shared -fPIC levenshtein.cpp -o _levenshtein.so
#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

extern "C" {

// Edit distance between a[0..n) and b[0..m), two-row DP, O(min(n,m)) memory.
int64_t edit_distance_i32(const int32_t* a, int64_t n, const int32_t* b, int64_t m) {
    if (n == 0) return m;
    if (m == 0) return n;
    if (m > n) { std::swap(a, b); std::swap(n, m); }
    std::vector<int64_t> prev(m + 1), cur(m + 1);
    for (int64_t j = 0; j <= m; ++j) prev[j] = j;
    for (int64_t i = 1; i <= n; ++i) {
        cur[0] = i;
        const int32_t ai = a[i - 1];
        for (int64_t j = 1; j <= m; ++j) {
            const int64_t sub = prev[j - 1] + (ai != b[j - 1]);
            cur[j] = std::min(sub, std::min(prev[j], cur[j - 1]) + 1);
        }
        std::swap(prev, cur);
    }
    return prev[m];
}

// Beam-limited edit distance between hypothesis a[0..n) and reference b[0..m),
// replicating tercom's pruning (sacrebleu lib_ter; reference helper.py:131-137):
// row i only evaluates columns within `beam` of the pseudo-diagonal
// floor(i * m/n), with the beam widened to ceil(ratio/2 + W) when the length
// ratio m/n exceeds 2W. The last row is evaluated to the end. Cells outside
// the beam stay at "infinity". NOTE: asymmetric (no operand swap) — the beam
// is defined relative to the hypothesis axis, exactly as tercom does it.
int64_t edit_distance_beam_i32(const int32_t* a, int64_t n, const int32_t* b, int64_t m,
                               int64_t beam_width) {
    if (n == 0) return m;
    if (m == 0) return n;
    const double ratio = static_cast<double>(m) / static_cast<double>(n);
    int64_t beam = beam_width;
    if (static_cast<double>(beam_width) < ratio / 2.0) {
        beam = static_cast<int64_t>(std::ceil(ratio / 2.0 + beam_width));
    }
    const int64_t INF = INT64_C(1) << 40;
    std::vector<int64_t> prev(m + 1, INF), cur(m + 1, INF);
    for (int64_t j = 0; j <= m; ++j) prev[j] = j;
    for (int64_t i = 1; i <= n; ++i) {
        std::fill(cur.begin(), cur.end(), INF);
        const int64_t diag = static_cast<int64_t>(std::floor(static_cast<double>(i) * ratio));
        const int64_t lo = std::max(INT64_C(0), diag - beam);
        const int64_t hi = (i == n) ? m + 1 : std::min(m + 1, diag + beam);
        const int32_t ai = a[i - 1];
        for (int64_t j = lo; j < hi; ++j) {
            if (j == 0) {
                cur[0] = prev[0] + 1;
                continue;
            }
            const int64_t sub = prev[j - 1] + (ai != b[j - 1]);
            cur[j] = std::min(sub, std::min(prev[j], cur[j - 1]) + 1);
        }
        std::swap(prev, cur);
    }
    return prev[m];
}

// Batch edit distance over packed sequences.
// a_data/b_data hold all tokens back to back; a_off/b_off are n_pairs+1 offsets.
void edit_distance_batch_i32(const int32_t* a_data, const int64_t* a_off,
                             const int32_t* b_data, const int64_t* b_off,
                             int64_t n_pairs, int64_t* out) {
    for (int64_t i = 0; i < n_pairs; ++i) {
        out[i] = edit_distance_i32(a_data + a_off[i], a_off[i + 1] - a_off[i],
                                   b_data + b_off[i], b_off[i + 1] - b_off[i]);
    }
}

}  // extern "C"
