// Native Levenshtein (edit distance) kernel for the text metric family.
//
// The reference implements edit distance in pure Python
// (torchmetrics/functional/text/helper.py:64-306); for corpus-scale WER/CER the
// host-side DP loop dominates, so this build runs it natively. Tokens are
// pre-mapped to int32 ids by the Python layer (works for words and characters
// alike); the batch entry point walks packed (offsets, data) arrays so one FFI
// call scores a whole corpus.
//
// Built with: g++ -O3 -shared -fPIC levenshtein.cpp -o _levenshtein.so
#include <algorithm>
#include <cstdint>
#include <vector>

extern "C" {

// Edit distance between a[0..n) and b[0..m), two-row DP, O(min(n,m)) memory.
int64_t edit_distance_i32(const int32_t* a, int64_t n, const int32_t* b, int64_t m) {
    if (n == 0) return m;
    if (m == 0) return n;
    if (m > n) { std::swap(a, b); std::swap(n, m); }
    std::vector<int64_t> prev(m + 1), cur(m + 1);
    for (int64_t j = 0; j <= m; ++j) prev[j] = j;
    for (int64_t i = 1; i <= n; ++i) {
        cur[0] = i;
        const int32_t ai = a[i - 1];
        for (int64_t j = 1; j <= m; ++j) {
            const int64_t sub = prev[j - 1] + (ai != b[j - 1]);
            cur[j] = std::min(sub, std::min(prev[j], cur[j - 1]) + 1);
        }
        std::swap(prev, cur);
    }
    return prev[m];
}

// Batch edit distance over packed sequences.
// a_data/b_data hold all tokens back to back; a_off/b_off are n_pairs+1 offsets.
void edit_distance_batch_i32(const int32_t* a_data, const int64_t* a_off,
                             const int32_t* b_data, const int64_t* b_off,
                             int64_t n_pairs, int64_t* out) {
    for (int64_t i = 0; i < n_pairs; ++i) {
        out[i] = edit_distance_i32(a_data + a_off[i], a_off[i + 1] - a_off[i],
                                   b_data + b_off[i], b_off[i + 1] - b_off[i]);
    }
}

}  // extern "C"
