"""Case-insensitive string enums used by the input-format layer.

Parity: reference ``torchmetrics/utilities/enums.py:18-83`` (EnumStr, DataType,
AverageMethod, MDMCAverageMethod). Values and member names mirror the reference so user
code ports verbatim; implementation is plain Python (host-side only, never traced).
"""
from enum import Enum
from typing import Optional


class EnumStr(str, Enum):
    """String enum with case-insensitive ``from_str`` lookup."""

    @classmethod
    def from_str(cls, value: str) -> Optional["EnumStr"]:
        try:
            return cls[value.replace("-", "_").upper()]
        except KeyError:
            return None

    def __eq__(self, other) -> bool:
        if other is None:
            return False
        if isinstance(other, Enum):
            return self.value.lower() == other.value.lower()
        return self.value.lower() == str(other).lower()

    def __hash__(self) -> int:
        return hash(self.value.lower())


class DataType(EnumStr):
    """Inferred type of classification inputs."""

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    """Reduction over classes."""

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """Reduction for multidim-multiclass inputs."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"
