"""Checkpoint/resume helpers for metric states (orbax-backed).

Parity: reference checkpointing goes through ``nn.Module.state_dict``
(``torchmetrics/metric.py:514-552``) with the distributed subtlety that saving while
synced writes *global* state and ``unsync()`` restores rank-local accumulation
(tested in reference ``tests/bases/test_ddp.py:135-241``). Here the state pytree is
saved directly; ``save_metric_state(metric, synced=True)`` snapshots the merged state
without disturbing the metric's local accumulation (merge is pure — no
snapshot/restore dance needed).
"""
import os
import pickle
from typing import Any, Dict, Optional, Union

import jax
import numpy as np

from metrics_tpu.collections import MetricCollection
from metrics_tpu.metric import Metric
from metrics_tpu.utils.imports import _ORBAX_AVAILABLE


def _to_numpy_tree(state: Any) -> Any:
    return jax.tree.map(lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, state)


def _to_jax_tree(state: Any) -> Any:
    import jax.numpy as jnp

    return jax.tree.map(lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, state)


def save_metric_state(
    metric: Union[Metric, MetricCollection],
    path: str,
    synced: bool = False,
    axis_name: Optional[str] = None,
) -> None:
    """Save a metric's (or collection's) state pytree to ``path``.

    With ``synced=True`` the saved state is the cross-device merged state computed
    functionally (local accumulation is untouched). Uses orbax when available,
    otherwise a numpy pickle.
    """
    if isinstance(metric, MetricCollection):
        state: Dict[str, Any] = {k: m._pack_state() for k, m in metric.items(keep_base=True)}
        if synced:
            state = metric.sync_states(state, axis_name)
    else:
        state = metric._pack_state()
        if synced:
            state = metric.sync_states(state, axis_name)
    state = _to_numpy_tree(state)

    if _ORBAX_AVAILABLE:
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(os.path.abspath(path), state, force=True)
    else:
        with open(path, "wb") as f:
            pickle.dump(state, f)


def load_metric_state(metric: Union[Metric, MetricCollection], path: str) -> None:
    """Restore a metric's (or collection's) state pytree from ``path``."""
    if _ORBAX_AVAILABLE and os.path.isdir(path):
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            state = ckptr.restore(os.path.abspath(path))
    else:
        with open(path, "rb") as f:
            state = pickle.load(f)
    state = _to_jax_tree(state)

    if isinstance(metric, MetricCollection):
        for k, m in metric.items(keep_base=True):
            m._load_state(state[k])
    else:
        metric._load_state(state)
