"""Framework exceptions.

Parity: reference ``torchmetrics/utilities/exceptions.py`` (TorchMetricsUserError).
"""


class MetricsTPUUserError(Exception):
    """Error raised on illegal use of the metric runtime (protocol violations)."""


# Short public alias used throughout the package.
UserError = MetricsTPUUserError
