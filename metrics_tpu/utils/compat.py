"""Version polyfills for the JAX APIs this package and its tests rely on.

The repo targets the public ``jax.shard_map`` entry point (promoted from
``jax.experimental.shard_map`` with ``check_rep`` renamed to ``check_vma``).
Older runtimes — like the 0.4.x container this build must also run in — only
ship the experimental path, so 48 call sites across the runtime, bench and
test tree would die on ``AttributeError``/``TypeError``. Instead of forking
every call site, install one adapter at package import: same keyword surface
as the modern API, delegating to whichever implementation exists.

Import-order note: this module must be imported before any ``jax.shard_map``
use (``metrics_tpu/__init__.py`` does it first thing), and is idempotent.
"""
import jax

__all__ = [
    "distributed_client",
    "install_enable_x64_polyfill",
    "install_shard_map_polyfill",
]


def distributed_client():
    """The live ``jax.distributed`` client handle, or None.

    THE side-effect-free "is the multi-process runtime up" probe (ISSUE 15):
    ``jax.process_count()`` and friends lazily initialize an XLA backend,
    after which ``jax.distributed.initialize`` refuses to run — the internal
    client handle is the only tell that touches nothing. The private-API
    knowledge lives HERE once (``engine/snapshot.py`` and
    ``engine/fleet/runtime.py`` both consult it); if the internals move,
    every caller degrades to the single-process answer instead of crashing.
    """
    try:
        from jax._src import distributed as _jdist

        return getattr(_jdist.global_state, "client", None)
    except Exception:  # pragma: no cover - internals moved; assume single-proc
        return None


def install_shard_map_polyfill() -> None:
    """Expose ``jax.shard_map`` with the modern keyword surface, if absent.

    Gate on the KEYWORD SURFACE, not mere attribute presence: the 0.5.x line
    already publishes ``jax.shard_map`` but still spells the replication check
    ``check_rep`` — call sites passing ``check_vma=`` would die there too.
    """
    import inspect

    native = getattr(jax, "shard_map", None)
    if native is not None:
        try:
            if "check_vma" in inspect.signature(native).parameters:
                return
        except (TypeError, ValueError):  # C-accelerated / wrapped: assume modern
            return
        _impl, _rep_kw = native, "check_rep"
    else:
        from jax.experimental.shard_map import shard_map as _impl

        _rep_kw = "check_rep"

    # positional-or-keyword params in the native order, and setdefault so an
    # explicit check_rep= from third-party code wins: the wrapper must stay
    # call-compatible with the API it shadows — other libraries in the same
    # process see this binding too
    def shard_map(f, mesh, in_specs, out_specs, check_vma=True, **kwargs):
        kwargs.setdefault(_rep_kw, check_vma)
        return _impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)

    shard_map.__doc__ = _impl.__doc__
    jax.shard_map = shard_map


def install_enable_x64_polyfill() -> None:
    """Expose the ``jax.enable_x64`` context manager, if absent.

    FID's compute-time f64 island (``image/fid.py``) uses the promoted
    spelling; older runtimes only have ``jax.experimental.enable_x64`` (same
    signature) and silently fall back to the float-float path without this.
    """
    if hasattr(jax, "enable_x64"):
        return
    from jax.experimental import enable_x64 as _experimental_enable_x64

    jax.enable_x64 = _experimental_enable_x64


install_shard_map_polyfill()
install_enable_x64_polyfill()
