"""Process-0-gated logging helpers.

Parity: reference ``torchmetrics/utilities/prints.py:21-49`` (rank_zero_warn/info/debug,
keyed on the LOCAL_RANK env var). TPU-native: keyed on ``jax.process_index()`` when the
JAX runtime is initialised, falling back to the env var before init.
"""
import logging
import os
import warnings
from functools import partial, wraps
from typing import Any, Callable

log = logging.getLogger("metrics_tpu")


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return int(os.environ.get("LOCAL_RANK", os.environ.get("JAX_PROCESS_INDEX", 0)))


def rank_zero_only(fn: Callable) -> Callable:
    """Run ``fn`` only on process 0."""

    @wraps(fn)
    def wrapped(*args: Any, **kwargs: Any) -> Any:
        if _process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped


@rank_zero_only
def _warn(*args: Any, **kwargs: Any) -> None:
    warnings.warn(*args, **kwargs)


@rank_zero_only
def _info(*args: Any, **kwargs: Any) -> None:
    log.info(*args, **kwargs)


@rank_zero_only
def _debug(*args: Any, **kwargs: Any) -> None:
    log.debug(*args, **kwargs)


rank_zero_warn = partial(_warn)
rank_zero_info = partial(_info)
rank_zero_debug = partial(_debug)
