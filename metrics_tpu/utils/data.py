"""Tensor utilities: dim-0 reductions, onehot/topk conversion, collection mapping.

Parity: reference ``torchmetrics/utilities/data.py:24-248`` (dim_zero_*, to_onehot,
select_topk, to_categorical, apply_to_collection, get_group_indexes, METRIC_EPS).
TPU-native notes: everything here is pure jnp and trace-safe except
``apply_to_collection`` (host-side pytree walk) and ``get_group_indexes`` (returns
host lists; the traced alternative is segment ops — see
``metrics_tpu/functional/retrieval``).
"""
from typing import Any, Callable, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

METRIC_EPS = 1e-6

Array = jax.Array


def is_batch_leaf(leaf: Any, n_rows: int) -> bool:
    """True when ``leaf`` carries the batch on its leading axis.

    THE single predicate behind the streaming engine's padding contract
    (``Metric.update_state_masked``, ``engine/bucketing.py``,
    ``engine/pipeline.py``, ``parallel/embedded.py`` all share it): anything
    array-shaped — numpy, jax arrays/tracers, ``ShapeDtypeStruct`` lowering
    templates — whose leading dimension equals the batch/mask length is
    batch-carried; everything else broadcasts. One definition, so the
    classification cannot drift between pad, upload, spec and update time.
    """
    shape = getattr(leaf, "shape", None)
    return shape is not None and len(shape) >= 1 and shape[0] == n_rows


def infer_batch_size(tree: Any) -> "int | None":
    """Leading dimension of the FIRST array-shaped leaf of ``tree`` — the
    companion of :func:`is_batch_leaf`: the batch size every other leaf is
    classified against. None when no leaf has a leading axis."""
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        if shape is not None and len(shape) >= 1:
            return int(shape[0])
    return None


def dim_zero_cat(x: Union[Array, List[Array]]) -> Array:
    """Concatenate a (possibly list of) array(s) along dim 0."""
    if isinstance(x, (list, tuple)):
        if len(x) == 0:
            return jnp.zeros((0,))
        x = [jnp.atleast_1d(v) for v in x]
        return jnp.concatenate(x, axis=0)
    return x


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def to_onehot(label_tensor: Array, num_classes: int) -> Array:
    """Convert integer labels ``(N, ...)`` to one-hot ``(N, C, ...)``.

    Parity: reference ``utilities/data.py:57-88``. Uses jax.nn.one_hot (lowered to a
    compare-iota on TPU, no scatter needed).
    """
    oh = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int32)
    # one_hot puts the class dim last; reference wants it at dim 1
    return jnp.moveaxis(oh, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary mask of the top-k entries along ``dim``.

    Parity: reference ``utilities/data.py:91-114``. Implemented with
    ``jax.lax.top_k`` (TPU-native sort network) + one-hot scatter-free mask.
    """
    if topk == 1:  # cheap argmax path
        idx = jnp.argmax(prob_tensor, axis=dim, keepdims=True)
        mask = jnp.zeros_like(prob_tensor, dtype=jnp.int32)
        return jnp.put_along_axis(mask, idx, 1, axis=dim, inplace=False)
    moved = jnp.moveaxis(prob_tensor, dim, -1)
    _, idx = jax.lax.top_k(moved, topk)
    mask = jnp.sum(jax.nn.one_hot(idx, moved.shape[-1], dtype=jnp.int32), axis=-2)
    return jnp.moveaxis(mask, -1, dim)


def to_categorical(tensor: Array, argmax_dim: int = 1) -> Array:
    """Probabilities/one-hot ``(N, C, ...)`` -> integer labels ``(N, ...)``.

    Parity: reference ``utilities/data.py:117-132``.
    """
    return jnp.argmax(tensor, axis=argmax_dim)


# array leaf types accepted everywhere metric inputs flow: jax arrays and
# host numpy arrays are interchangeable at every update() in the package
ARRAY_TYPES = (jax.Array, np.ndarray)


def apply_to_collection(
    data: Any,
    dtype: Union[type, tuple],
    function: Callable,
    *args: Any,
    **kwargs: Any,
) -> Any:
    """Recursively apply ``function`` to all ``dtype`` leaves of a collection.

    Parity: reference ``utilities/data.py:166-213``. Host-side only.
    """
    if isinstance(data, dtype):
        return function(data, *args, **kwargs)
    if isinstance(data, (list, tuple)):
        out = [apply_to_collection(d, dtype, function, *args, **kwargs) for d in data]
        return type(data)(out) if isinstance(data, tuple) else out
    if isinstance(data, dict):
        return {k: apply_to_collection(v, dtype, function, *args, **kwargs) for k, v in data.items()}
    return data


def get_group_indexes(indexes: Array) -> List[Array]:
    """Group positions by query id. Host-side; returns a list of index arrays.

    Parity: reference ``utilities/data.py:216-240``. The traced/TPU equivalent used by
    retrieval compute is ``jax.ops.segment_sum`` over ``indexes`` directly — this helper
    exists for API parity and eager use.
    """
    import numpy as np

    idx = np.asarray(indexes)
    groups: dict = {}
    for i, v in enumerate(idx.tolist()):
        groups.setdefault(v, []).append(i)
    return [jnp.asarray(v, dtype=jnp.int32) for v in groups.values()]


def _flatten(x: Sequence) -> list:
    return [item for sublist in x for item in sublist]


def _bincount(x: Array, minlength: int) -> Array:
    """Static-length bincount through the kernel dispatcher
    (``metrics_tpu/ops/kernels``). Actual lowering per backend: a streaming
    Pallas one-hot × int8 MXU-contraction accumulate under ``pallas`` AND the
    ``megastep`` tier (the megakernel fuses arena leaves, not this per-metric
    primitive, so both tiers share the Pallas histogram; exact while the row
    count stays below 2**24 — past that the dispatcher routes to the XLA
    scatter rather than risk an inexact f32 count), XLA's ``jnp.bincount``
    scatter-add of ones elsewhere — and always under the forced ``xla``
    reference backend. Backend selection, most specific wins:
    ``use_backend`` context > ``set_default_backend`` > the
    ``METRICS_TPU_KERNEL_BACKEND`` env var > ``"auto"``. Runnable example::

        from metrics_tpu.ops.kernels import use_backend
        with use_backend("pallas_interpret"):
            counts = _bincount(jnp.array([0, 2, 2, 5]), minlength=6)

    All paths keep ``jnp.bincount``'s exact semantics: negative indices clip
    to bin 0, indices ``>= minlength`` are dropped; int32 counts.
    """
    # function-level import: utils.data loads before the ops package during
    # package init, and the kernels only pull jax — no cycle, just laziness
    from metrics_tpu.ops.kernels import histogram_accumulate

    return histogram_accumulate(x, minlength)


def _stable_1d_sort(x: Array, descending: bool = False) -> Tuple[Array, Array]:
    """Stable sort returning (values, indices)."""
    key = -x if descending else x
    idx = jnp.argsort(key, stable=True)
    return x[idx], idx
