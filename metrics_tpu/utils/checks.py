"""Input-format & validation layer (L2) for classification inputs.

Parity: reference ``torchmetrics/utilities/checks.py:23-432``
(_input_format_classification :296, _check_classification_inputs :190,
_basic_input_validation :29, _check_shape_and_type_consistency :51, retrieval checks
:484-562). Same 6-way case taxonomy and identical canonical output contract: binary
``(N, C)``/``(N, C, X)`` int tensors + the inferred DataType.

TPU-native split (SURVEY.md §7.1): shape/dtype-driven branching resolves at **trace
time** (shapes are static under jit); value-dependent validation (``target.max() > 1``
etc.) runs only eagerly — inside jit it is skipped, and anything that *needs* a value
(inferring ``num_classes`` from ``target.max()``) raises a clear error asking for the
static argument instead.
"""
from typing import Any, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.data import select_topk, to_onehot
from metrics_tpu.utils.enums import DataType


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


class _ValueStats(NamedTuple):
    """Min/max of preds+target, fetched from device in ONE transfer.

    The eager validation path needs up to five value-dependent facts
    (target bounds twice, preds bounds, num_classes inference); issuing each as
    its own ``jnp.min``/``jnp.max`` + ``int(...)`` forces a separate blocking
    device→host round-trip — over a TPU tunnel that is ~4 RTTs per update. One
    fused reduction + one transfer replaces them all.
    """

    target_min: float
    target_max: float
    preds_min: float
    preds_max: float


@jax.jit
def _minmax_bundle(preds, target) -> jax.Array:
    pf = jnp.ravel(preds).astype(jnp.float32)
    tf = jnp.ravel(target).astype(jnp.float32)
    return jnp.stack([jnp.min(tf), jnp.max(tf), jnp.min(pf), jnp.max(pf)])


def _compute_value_stats(preds, target) -> Optional[_ValueStats]:
    """None under trace (checks are skipped there); else one fused device fetch."""
    if _is_tracer(preds) or _is_tracer(target):
        return None
    try:
        vals = np.asarray(_minmax_bundle(preds, target))
    except jax.errors.TracerArrayConversionError:
        # inputs are CONCRETE but an ambient trace is active (closed-over
        # constants inside a scan/fori_loop/jit body): the stats computation
        # stages into that trace, so value checks defer exactly as they do
        # for traced inputs
        return None
    return _ValueStats(float(vals[0]), float(vals[1]), float(vals[2]), float(vals[3]))


# --------------------------------------------------------- deferred (in-graph) checks
#
# Eager value checks can't raise inside a trace. When the metric runtime compiles
# a whole forward step (metric.py _build_forward_step), it opens a
# ``deferred_value_checks`` context: the check sites below then EMIT int32 error
# codes as part of the graph instead of being skipped. The compiled step returns
# max(codes); the facade accumulates it on-device (async, no transfer) and raises
# the corresponding message at the next compute()/sync() — CUDA-style deferred
# error reporting, with zero steady-state host round-trips.

_DEFERRED_MESSAGES: dict = {}
_DEFERRED_ACTIVE: List[Any] = []  # stack of code-collector lists


def register_deferred_message(message: str) -> int:
    """Allocate a stable error code for a deferred-check message."""
    code = len(_DEFERRED_MESSAGES) + 1
    _DEFERRED_MESSAGES[code] = message
    return code


def deferred_message(code: int) -> str:
    return _DEFERRED_MESSAGES.get(code, f"invalid input detected (code {code})")


class deferred_value_checks:
    """Context manager: collect traced error codes from value-check sites."""

    def __init__(self) -> None:
        self.codes: List[Any] = []

    def __enter__(self) -> "deferred_value_checks":
        _DEFERRED_ACTIVE.append(self.codes)
        return self

    def __exit__(self, *exc: Any) -> None:
        _DEFERRED_ACTIVE.pop()

    def combined(self):
        """Fold collected codes into one int32 scalar (0 = all inputs valid)."""
        out = jnp.int32(0)
        for c in self.codes:
            out = jnp.maximum(out, c)
        return out


def defer_value_check(bad, code: int) -> None:
    """Emit ``code`` when the traced predicate ``bad`` holds (no-op outside the
    deferred-checks context)."""
    if _DEFERRED_ACTIVE:
        _DEFERRED_ACTIVE[-1].append(jnp.where(bad, jnp.int32(code), jnp.int32(0)))


_CODE_TARGET_NEG = register_deferred_message("The `target` has to be a non-negative tensor.")
_CODE_PREDS_NEG = register_deferred_message("If `preds` are integers, they have to be non-negative.")
_CODE_TARGET_GT1_MC_FALSE = register_deferred_message(
    "If you set `multiclass=False`, then `target` should not exceed 1."
)
_CODE_PREDS_GT1_MC_FALSE = register_deferred_message(
    "If you set `multiclass=False` and `preds` are integers, then `preds` should not exceed 1."
)
_CODE_TARGET_NOT_BINARY = register_deferred_message(
    "If `preds` and `target` are of shape (N, ...) and `preds` are floats, `target` should be binary."
)
_CODE_TARGET_GE_IMPLIED = register_deferred_message(
    "The highest label in `target` should be smaller than the size of the `C` dimension of `preds`."
)
_CODE_TARGET_GE_NUM_CLASSES = register_deferred_message(
    "The highest label in `target` should be smaller than `num_classes`."
)
_CODE_TARGET_NOT_BINARY_RETRIEVAL = register_deferred_message("`target` must contain `binary` values")
_CODE_EMPTY_QUERY_RETRIEVAL = register_deferred_message(
    "`compute` method was provided with a query with no positive target."
)


def _is_floating(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def _check_same_shape(preds, target) -> None:
    if jnp.shape(preds) != jnp.shape(target):
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape, "
            f"got {jnp.shape(preds)} and {jnp.shape(target)}."
        )


def _basic_input_validation(
    preds, target, threshold: float, multiclass: Optional[bool], stats: Optional[_ValueStats] = None
) -> None:
    """Value-dependent sanity checks — eager path only (skipped under trace)."""
    if _is_floating(target):
        raise ValueError("The `target` has to be an integer tensor.")
    if stats is None:
        stats = _compute_value_stats(preds, target)
    preds_float = _is_floating(preds)
    if stats is None:
        # traced: emit deferred in-graph codes instead (no-op outside the context)
        defer_value_check(jnp.min(target) < 0, _CODE_TARGET_NEG)
        if not preds_float:
            defer_value_check(jnp.min(preds) < 0, _CODE_PREDS_NEG)
        if multiclass is False:
            defer_value_check(jnp.max(target) > 1, _CODE_TARGET_GT1_MC_FALSE)
            if not preds_float:
                defer_value_check(jnp.max(preds) > 1, _CODE_PREDS_GT1_MC_FALSE)
        return
    if stats.target_min < 0:
        raise ValueError("The `target` has to be a non-negative tensor.")
    if not preds_float and stats.preds_min < 0:
        raise ValueError("If `preds` are integers, they have to be non-negative.")
    if jnp.shape(preds)[0] != jnp.shape(target)[0]:
        raise ValueError("The `preds` and `target` should have the same first dimension.")
    if multiclass is False and stats.target_max > 1:
        raise ValueError("If you set `multiclass=False`, then `target` should not exceed 1.")
    if multiclass is False and not preds_float and stats.preds_max > 1:
        raise ValueError("If you set `multiclass=False` and `preds` are integers, then `preds` should not exceed 1.")


def _check_shape_and_type_consistency(preds, target, stats: Optional[_ValueStats] = None) -> Tuple[DataType, int]:
    """Infer the input case from shapes/dtypes only (trace-safe)."""
    preds_float = _is_floating(preds)
    p_shape, t_shape = jnp.shape(preds), jnp.shape(target)

    if preds.ndim == target.ndim:
        if p_shape != t_shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={p_shape} and `target` with shape={t_shape}."
            )
        if preds_float and stats is None and not (_is_tracer(preds) or _is_tracer(target)):
            stats = _compute_value_stats(preds, target)
        if preds_float and stats is not None and stats.target_max > 1:
            raise ValueError(
                "If `preds` and `target` are of shape (N, ...) and `preds` are floats, `target` should be binary."
            )
        if preds_float and stats is None:
            defer_value_check(jnp.max(target) > 1, _CODE_TARGET_NOT_BINARY)
        if preds.ndim == 1 and preds_float:
            case = DataType.BINARY
        elif preds.ndim == 1 and not preds_float:
            case = DataType.MULTICLASS
        elif preds.ndim > 1 and preds_float:
            case = DataType.MULTILABEL
        else:
            case = DataType.MULTIDIM_MULTICLASS
        implied_classes = int(np.prod(p_shape[1:])) if len(p_shape) > 1 else 1
    elif preds.ndim == target.ndim + 1:
        if not preds_float:
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if p_shape[2:] != t_shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
        implied_classes = p_shape[1]
        case = DataType.MULTICLASS if preds.ndim == 2 else DataType.MULTIDIM_MULTICLASS
    else:
        raise ValueError(
            "Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be (N, ...)"
            " and `preds` should be (N, C, ...)."
        )
    return case, implied_classes


def _check_num_classes_binary(num_classes: int, multiclass: Optional[bool]) -> None:
    if num_classes > 2:
        raise ValueError("Your data is binary, but `num_classes` is larger than 2.")
    if num_classes == 2 and not multiclass:
        raise ValueError(
            "Your data is binary and `num_classes=2`, but `multiclass` is not True."
            " Set it to True if you want to transform binary data to multi-class format."
        )
    if num_classes == 1 and multiclass:
        raise ValueError(
            "You have binary data and have set `multiclass=True`, but `num_classes` is 1."
            " Either set `multiclass=None`(default) or set `num_classes=2`"
            " to transform binary data to multi-class format."
        )


def _check_num_classes_mc(
    preds, target, num_classes: int, multiclass: Optional[bool], implied_classes: int,
    stats: Optional[_ValueStats] = None,
) -> None:
    if num_classes == 1 and multiclass is not False:
        raise ValueError(
            "You have set `num_classes=1`, but predictions are integers."
            " If you want to convert (multi-dimensional) multi-class data with 2 classes"
            " to binary/multi-label, set `multiclass=False`."
        )
    if num_classes > 1:
        if multiclass is False and implied_classes != num_classes:
            raise ValueError(
                "You have set `multiclass=False`, but the implied number of classes "
                " (from shape of inputs) does not match `num_classes`."
            )
        if stats is None and not (_is_tracer(preds) or _is_tracer(target)):
            stats = _compute_value_stats(preds, target)
        if stats is not None and num_classes <= int(stats.target_max):
            raise ValueError("The highest label in `target` should be smaller than `num_classes`.")
        if stats is None:
            defer_value_check(jnp.max(target) >= num_classes, _CODE_TARGET_GE_NUM_CLASSES)
        if jnp.shape(preds) != jnp.shape(target) and num_classes != implied_classes:
            raise ValueError("The size of C dimension of `preds` does not match `num_classes`.")


def _check_num_classes_ml(num_classes: int, multiclass: Optional[bool], implied_classes: int) -> None:
    if multiclass and num_classes != 2:
        raise ValueError(
            "Your have set `multiclass=True`, but `num_classes` is not equal to 2."
            " If you are trying to transform multi-label data to 2 class multi-dimensional"
            " multi-class, you should set `num_classes` to either 2 or None."
        )
    if not multiclass and num_classes != implied_classes:
        raise ValueError("The implied number of classes (from shape of inputs) does not match num_classes.")


def _check_top_k(top_k: int, case: DataType, implied_classes: int, multiclass: Optional[bool], preds_float: bool) -> None:
    if case == DataType.BINARY:
        raise ValueError("You can not use `top_k` parameter with binary data.")
    if not isinstance(top_k, int) or top_k <= 0:
        raise ValueError("The `top_k` has to be an integer larger than 0.")
    if not preds_float:
        raise ValueError("You have set `top_k`, but you do not have probability predictions.")
    if multiclass is False:
        raise ValueError("If you set `multiclass=False`, you can not set `top_k`.")
    if case == DataType.MULTILABEL and multiclass:
        raise ValueError(
            "If you want to transform multi-label data to 2 class multi-dimensional"
            "multi-class data using `multiclass=True`, you can not use `top_k`."
        )
    if top_k >= implied_classes:
        raise ValueError("The `top_k` has to be strictly smaller than the `C` dimension of `preds`.")


def _check_classification_inputs(
    preds,
    target,
    threshold: float,
    num_classes: Optional[int],
    multiclass: Optional[bool],
    top_k: Optional[int],
    stats: Optional[_ValueStats] = None,
) -> DataType:
    """Full input validation; returns the inferred case. Parity: ``checks.py:190-281``."""
    if stats is None:
        stats = _compute_value_stats(preds, target)
    _basic_input_validation(preds, target, threshold, multiclass, stats=stats)
    case, implied_classes = _check_shape_and_type_consistency(preds, target, stats=stats)

    if jnp.shape(preds) != jnp.shape(target):
        if multiclass is False and implied_classes != 2:
            raise ValueError(
                "You have set `multiclass=False`, but have more than 2 classes in your data,"
                " based on the C dimension of `preds`."
            )
        if stats is not None and int(stats.target_max) >= implied_classes:
            raise ValueError(
                "The highest label in `target` should be smaller than the size of the `C` dimension of `preds`."
            )
        if stats is None:
            defer_value_check(jnp.max(target) >= implied_classes, _CODE_TARGET_GE_IMPLIED)

    if num_classes:
        if case == DataType.BINARY:
            _check_num_classes_binary(num_classes, multiclass)
        elif case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
            _check_num_classes_mc(preds, target, num_classes, multiclass, implied_classes, stats=stats)
        elif case == DataType.MULTILABEL:
            _check_num_classes_ml(num_classes, multiclass, implied_classes)

    if top_k is not None:
        _check_top_k(top_k, case, implied_classes, multiclass, _is_floating(preds))

    return case


def _input_squeeze(preds, target):
    """Remove excess size-1 dims (all but the leading N). Parity: ``checks.py:284-293``."""
    if jnp.shape(preds)[0] == 1:
        preds = jnp.expand_dims(jnp.squeeze(preds), 0)
        target = jnp.expand_dims(jnp.squeeze(target), 0)
    else:
        preds, target = jnp.squeeze(preds), jnp.squeeze(target)
    return preds, target


def _input_format_classification(
    preds,
    target,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array, DataType]:
    """Canonicalize classification inputs to binary ``(N, C)``/``(N, C, X)`` tensors.

    Parity: reference ``checks.py:296-432`` — identical case handling, thresholding,
    topk selection and one-hot layout. Trace-safe given static ``num_classes`` (needed
    under jit when labels must be one-hotted; eagerly it is inferred from data like the
    reference).
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds, target = _input_squeeze(preds, target)
    if preds.dtype in (jnp.float16, jnp.bfloat16):
        preds = preds.astype(jnp.float32)

    stats = _compute_value_stats(preds, target)
    case = _check_classification_inputs(
        preds, target, threshold=threshold, num_classes=num_classes, multiclass=multiclass, top_k=top_k,
        stats=stats,
    )

    if case in (DataType.BINARY, DataType.MULTILABEL) and not top_k:
        preds = (preds >= threshold).astype(jnp.int32) if _is_floating(preds) else preds
        num_classes = num_classes if not multiclass else 2

    if case == DataType.MULTILABEL and top_k:
        preds = select_topk(preds, top_k)

    if case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) or multiclass:
        if _is_floating(preds):
            num_classes = preds.shape[1]
            preds = select_topk(preds, top_k or 1)
        else:
            if not num_classes:
                if stats is None:
                    raise ValueError(
                        "Cannot infer `num_classes` from data inside jit; pass `num_classes` explicitly."
                    )
                num_classes = int(max(stats.preds_max, stats.target_max)) + 1
            preds = to_onehot(preds, max(2, num_classes))
        target = to_onehot(target, max(2, int(num_classes) if num_classes else 2))

        if multiclass is False:
            preds, target = preds[:, 1, ...], target[:, 1, ...]

    if (case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) and multiclass is not False) or multiclass:
        target = target.reshape(target.shape[0], target.shape[1], -1)
        preds = preds.reshape(preds.shape[0], preds.shape[1], -1)
    else:
        target = target.reshape(target.shape[0], -1)
        preds = preds.reshape(preds.shape[0], -1)

    if preds.ndim > 2 and preds.shape[-1] == 1:
        preds, target = jnp.squeeze(preds, -1), jnp.squeeze(target, -1)

    return preds.astype(jnp.int32), target.astype(jnp.int32), case


def _input_format_classification_one_hot(
    num_classes: int,
    preds,
    target,
    threshold: float = 0.5,
    multilabel: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Legacy one-hot transposed format ``(C, N*X)``. Parity: ``checks.py:435-481``."""
    if preds.ndim == target.ndim + 1:
        preds = jnp.argmax(preds, axis=1) if not multilabel else preds
    if preds.ndim == target.ndim and _is_floating(preds):
        preds = (preds >= threshold).astype(jnp.int32)
    if preds.ndim == target.ndim and not multilabel:
        preds = to_onehot(preds, num_classes)
        target = to_onehot(target, num_classes)
    elif preds.ndim == target.ndim:
        # multilabel: (N, C, ...) already
        pass
    preds = jnp.moveaxis(preds, 1, 0).reshape(num_classes, -1)
    target = jnp.moveaxis(target, 1, 0).reshape(num_classes, -1)
    return preds, target


def _check_retrieval_functional_inputs(
    preds, target, allow_non_binary_target: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """Flatten + validate retrieval (preds float, target bool/int) pairs.

    Parity: reference ``checks.py:443-481`` (_check_retrieval_functional_inputs).
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.shape(preds) != jnp.shape(target):
        raise ValueError("`preds` and `target` must be of the same shape")
    if preds.ndim == 0 or preds.size == 0:
        raise ValueError("`preds` and `target` must be non-empty and non-scalar tensors")
    if not (jnp.issubdtype(target.dtype, jnp.bool_) or jnp.issubdtype(target.dtype, jnp.integer)
            or (allow_non_binary_target and jnp.issubdtype(target.dtype, jnp.floating))):
        raise ValueError("`target` must be a tensor of booleans or integers")
    if not _is_floating(preds):
        raise ValueError("`preds` must be a tensor of floats")
    if not allow_non_binary_target and not _is_tracer(target) and target.size and int(jnp.max(target)) > 1:
        raise ValueError("`target` must contain `binary` values")
    if not allow_non_binary_target and _is_tracer(target) and target.size:
        defer_value_check(jnp.max(target) > 1, _CODE_TARGET_NOT_BINARY_RETRIEVAL)
    preds = jnp.ravel(preds).astype(jnp.float32)
    target = jnp.ravel(target)
    target = target.astype(jnp.float32) if allow_non_binary_target else target.astype(jnp.int32)
    return preds, target


def _check_retrieval_inputs(
    indexes,
    preds,
    target,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Validate (indexes, preds, target) triplets. Parity: ``checks.py:484-540``.

    ``ignore_index`` drops entries whose target equals it (eager boolean mask).
    """
    indexes = jnp.asarray(indexes)
    if jnp.shape(indexes) != jnp.shape(preds) or jnp.shape(preds) != jnp.shape(jnp.asarray(target)):
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    preds, target = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target)
    indexes = jnp.ravel(indexes).astype(jnp.int32)
    if ignore_index is not None:
        keep = target != ignore_index
        indexes, preds, target = indexes[keep], preds[keep], target[keep]
    return indexes, preds, target
