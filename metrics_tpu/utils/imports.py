"""Runtime capability probing for optional dependencies.

Parity: reference ``torchmetrics/utilities/imports.py:24-93`` (_module_available +
_X_AVAILABLE flags gating optional domains). The TPU build gates on the packages baked
into its own environment (transformers for BERTScore, nltk for ROUGE, etc.); anything
missing degrades to a clear ImportError at metric construction, never at package import.
"""
import importlib.util
from functools import lru_cache


@lru_cache(maxsize=None)
def _module_available(module_path: str) -> bool:
    """True if ``module_path`` (dotted) can be imported without importing it."""
    try:
        parts = module_path.split(".")
        probe = parts[0]
        if importlib.util.find_spec(probe) is None:
            return False
        for part in parts[1:]:
            probe = f"{probe}.{part}"
            if importlib.util.find_spec(probe) is None:
                return False
        return True
    except (ModuleNotFoundError, ValueError):
        return False


_JAX_AVAILABLE = _module_available("jax")
_FLAX_AVAILABLE = _module_available("flax")
_OPTAX_AVAILABLE = _module_available("optax")
_ORBAX_AVAILABLE = _module_available("orbax.checkpoint")
_TRANSFORMERS_AVAILABLE = _module_available("transformers")
_TORCH_AVAILABLE = _module_available("torch")
_SKLEARN_AVAILABLE = _module_available("sklearn")
_SCIPY_AVAILABLE = _module_available("scipy")
_NLTK_AVAILABLE = _module_available("nltk")
_ROUGE_SCORE_AVAILABLE = _module_available("rouge_score")
_REGEX_AVAILABLE = _module_available("regex")
_PESQ_AVAILABLE = _module_available("pesq")
_PYSTOI_AVAILABLE = _module_available("pystoi")
_PYCOCOTOOLS_AVAILABLE = _module_available("pycocotools")
