"""STOI module metric — native on-device DSP.

Parity: reference ``torchmetrics/audio/stoi.py:23`` (which *requires* the
host-side ``pystoi`` package and raises without it). This build implements the
STOI/ESTOI DSP natively in jnp (``functional/audio/stoi.py``), so the module
always works and the per-update scores run jitted on the accelerator.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric

Array = jax.Array


class STOI(Metric):
    """Short-time objective intelligibility (averaged over updates)."""

    is_differentiable = False
    higher_is_better = True

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.fs = fs
        self.extended = extended
        self.add_state("sum_stoi", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        from metrics_tpu.functional.audio.stoi import stoi as stoi_fn

        scores = stoi_fn(preds, target, self.fs, extended=self.extended)
        self.sum_stoi = self.sum_stoi + jnp.sum(scores)
        self.total = self.total + scores.size

    def compute(self) -> Array:
        return self.sum_stoi / self.total
