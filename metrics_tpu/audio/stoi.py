"""STOI module metric (wraps the native ``pystoi`` package, host-side DSP).

Parity: reference ``torchmetrics/audio/stoi.py:23``.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.utils.imports import _PYSTOI_AVAILABLE

Array = jax.Array


class STOI(Metric):
    """Short-time objective intelligibility."""

    is_differentiable = False
    higher_is_better = True

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not _PYSTOI_AVAILABLE:
            raise ModuleNotFoundError(
                "STOI metric requires that pystoi is installed. Either install as `pip install pystoi`."
            )
        self.fs = fs
        self.extended = extended
        self.add_state("sum_stoi", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        from metrics_tpu.functional.audio.stoi import stoi as stoi_fn

        scores = stoi_fn(preds, target, self.fs, extended=self.extended)
        self.sum_stoi = self.sum_stoi + jnp.sum(scores)
        self.total = self.total + scores.size

    def compute(self) -> Array:
        return self.sum_stoi / self.total
