"""SNR module metrics.

Parity: reference ``torchmetrics/audio/snr.py:23,114,140`` (SignalNoiseRatio,
deprecated SNR, ScaleInvariantSignalNoiseRatio).
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio.snr import scale_invariant_signal_noise_ratio, signal_noise_ratio
from metrics_tpu.metric import Metric
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class SignalNoiseRatio(Metric):
    """Signal-to-noise ratio, averaged over samples."""

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean
        self.add_state("sum_snr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        snr_batch = signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_snr = self.sum_snr + jnp.sum(snr_batch)
        self.total = self.total + snr_batch.size

    def compute(self) -> Array:
        return self.sum_snr / self.total


class SNR(SignalNoiseRatio):
    """Deprecated alias. Parity: reference ``snr.py:114``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SNR
        >>> target = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        >>> preds = jnp.asarray([1.1, 2.1, 2.9, 4.2])
        >>> snr = SNR()
        >>> print(f"{float(snr(preds, target)):.4f}")
        26.3202
    """

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        rank_zero_warn("`SNR` was renamed to `SignalNoiseRatio` and it will be removed.", DeprecationWarning)
        super().__init__(*args, **kwargs)


class ScaleInvariantSignalNoiseRatio(Metric):
    """Scale-invariant SNR, averaged over samples.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ScaleInvariantSignalNoiseRatio
        >>> target = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        >>> preds = jnp.asarray([1.1, 2.1, 2.9, 4.2])
        >>> si_snr = ScaleInvariantSignalNoiseRatio()
        >>> print(f"{float(si_snr(preds, target)):.4f}")
        20.3551
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_si_snr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        si_snr_batch = scale_invariant_signal_noise_ratio(preds=preds, target=target)
        self.sum_si_snr = self.sum_si_snr + jnp.sum(si_snr_batch)
        self.total = self.total + si_snr_batch.size

    def compute(self) -> Array:
        return self.sum_si_snr / self.total
