"""PIT module metric.

Parity: reference ``torchmetrics/audio/pit.py:22`` (states :96-97).
"""
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio.pit import pit
from metrics_tpu.metric import Metric
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class PermutationInvariantTraining(Metric):
    """Permutation-invariant evaluation of any sample-level audio metric.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PermutationInvariantTraining
        >>> from metrics_tpu.functional import si_snr
        >>> n = jnp.arange(64.0)
        >>> preds = jnp.stack([jnp.sin(n/3) + 0.2*jnp.cos(n/7), jnp.cos(n/5) + 0.2*jnp.sin(n/9)])[None]
        >>> target = jnp.stack([jnp.cos(n/5), jnp.sin(n/3)])[None]  # speakers swapped
        >>> pit = PermutationInvariantTraining(si_snr, eval_func="max")
        >>> print(f"{float(pit(preds, target)):.4f}")
        14.2851
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        metric_func: Callable,
        eval_func: str = "max",
        **kwargs: Any,
    ) -> None:
        base_kwargs: Dict[str, Any] = {
            k: kwargs.pop(k)
            for k in ("compute_on_step", "dist_sync_on_step", "sync_axis", "dist_sync_fn", "process_group")
            if k in kwargs
        }
        super().__init__(**base_kwargs)
        self.metric_func = metric_func
        self.eval_func = eval_func
        self.kwargs = kwargs
        self.add_state("sum_pit_metric", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        pit_metric = pit(preds, target, self.metric_func, self.eval_func, **self.kwargs)[0]
        self.sum_pit_metric = self.sum_pit_metric + jnp.sum(pit_metric)
        self.total = self.total + pit_metric.size

    def compute(self) -> Array:
        return self.sum_pit_metric / self.total


class PIT(PermutationInvariantTraining):
    """Deprecated alias. Parity: reference ``audio/pit.py`` naming history."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        rank_zero_warn(
            "`PIT` was renamed to `PermutationInvariantTraining` and it will be removed.", DeprecationWarning
        )
        super().__init__(*args, **kwargs)
