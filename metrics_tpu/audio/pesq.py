"""PESQ module metric (wraps the native ``pesq`` package, host-side DSP).

Parity: reference ``torchmetrics/audio/pesq.py:23``. PESQ is a standardized ITU
P.862 C implementation — like the reference, the heavy DSP stays in the native
package (host-side); the metric runtime averages scores on device.
"""
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utils.imports import _PESQ_AVAILABLE

Array = jax.Array


class PESQ(Metric):
    """Perceptual evaluation of speech quality (narrow/wide band)."""

    is_differentiable = False
    higher_is_better = True

    def __init__(self, fs: int, mode: str, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not _PESQ_AVAILABLE:
            raise ModuleNotFoundError(
                "PESQ metric requires that pesq is installed. Either install as `pip install pesq`."
            )
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        self.fs = fs
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        self.mode = mode
        self.add_state("sum_pesq", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        import pesq as pesq_backend

        preds_np = np.asarray(preds)
        target_np = np.asarray(target)
        if preds_np.ndim == 1:
            score = pesq_backend.pesq(self.fs, target_np, preds_np, self.mode)
            self.sum_pesq = self.sum_pesq + score
            self.total = self.total + 1
        else:
            for p, t in zip(preds_np.reshape(-1, preds_np.shape[-1]), target_np.reshape(-1, target_np.shape[-1])):
                score = pesq_backend.pesq(self.fs, t, p, self.mode)
                self.sum_pesq = self.sum_pesq + score
                self.total = self.total + 1

    def compute(self) -> Array:
        return self.sum_pesq / self.total
