"""PESQ module metric (wraps the native ``pesq`` package, host-side DSP).

Parity: reference ``torchmetrics/audio/pesq.py:23``. PESQ is a standardized ITU
P.862 C implementation — like the reference, the heavy DSP stays in the native
package (host-side); the metric runtime averages scores on device.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.utils.imports import _PESQ_AVAILABLE

Array = jax.Array


class PESQ(Metric):
    """Perceptual evaluation of speech quality (narrow/wide band)."""

    is_differentiable = False
    higher_is_better = True

    def __init__(self, fs: int, mode: str, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not _PESQ_AVAILABLE:
            raise ModuleNotFoundError(
                "PESQ metric requires that pesq is installed. Either install as `pip install pesq`."
            )
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        self.fs = fs
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        self.mode = mode
        self.add_state("sum_pesq", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        from metrics_tpu.functional.audio.pesq import pesq as pesq_fn

        scores = pesq_fn(preds, target, self.fs, self.mode)
        self.sum_pesq = self.sum_pesq + jnp.sum(scores)
        self.total = self.total + scores.size

    def compute(self) -> Array:
        return self.sum_pesq / self.total
