"""Deprecated SI_SNR alias class.

Parity: reference ``torchmetrics/audio/si_snr.py:22`` (renamed to
``ScaleInvariantSignalNoiseRatio`` in v0.7; alias warns on construction).
"""
from typing import Any

from metrics_tpu.audio.snr import ScaleInvariantSignalNoiseRatio
from metrics_tpu.utils.prints import rank_zero_warn


class SI_SNR(ScaleInvariantSignalNoiseRatio):
    def __init__(self, **kwargs: Any) -> None:
        rank_zero_warn(
            "`SI_SNR` was renamed to `ScaleInvariantSignalNoiseRatio` and it will be removed.",
            DeprecationWarning,
        )
        super().__init__(**kwargs)
