from metrics_tpu.audio.pit import PIT, PermutationInvariantTraining
from metrics_tpu.audio.sdr import (
    SDR,
    ScaleInvariantSignalDistortionRatio,
    SignalDistortionRatio,
)
from metrics_tpu.audio.snr import SNR, ScaleInvariantSignalNoiseRatio, SignalNoiseRatio

__all__ = [
    "PIT",
    "PermutationInvariantTraining",
    "SDR",
    "SNR",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
]

# deprecated aliases of the scale-invariant metrics (reference audio/si_sdr.py:22,
# si_snr.py:22)
SI_SDR = ScaleInvariantSignalDistortionRatio
SI_SNR = ScaleInvariantSignalNoiseRatio

# optional native-DSP metrics: modules always import; construction raises a clear
# ModuleNotFoundError when the backing package is absent (reference pattern)
from metrics_tpu.audio.pesq import PESQ  # noqa: E402,F401
from metrics_tpu.audio.stoi import STOI  # noqa: E402,F401

__all__ += ["PESQ", "STOI"]
