from metrics_tpu.audio.pit import PIT, PermutationInvariantTraining
from metrics_tpu.audio.sdr import (
    SDR,
    ScaleInvariantSignalDistortionRatio,
    SignalDistortionRatio,
)
from metrics_tpu.audio.snr import SNR, ScaleInvariantSignalNoiseRatio, SignalNoiseRatio

__all__ = [
    "PIT",
    "PermutationInvariantTraining",
    "SDR",
    "SI_SDR",
    "SI_SNR",
    "SNR",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
]

# deprecated alias classes of the scale-invariant metrics (warn on construction;
# reference audio/si_sdr.py:22, si_snr.py:22)
from metrics_tpu.audio.si_sdr import SI_SDR  # noqa: E402
from metrics_tpu.audio.si_snr import SI_SNR  # noqa: E402

# optional native-DSP metrics: modules always import; construction raises a clear
# ModuleNotFoundError when the backing package is absent (reference pattern)
from metrics_tpu.audio.pesq import PESQ  # noqa: E402,F401
from metrics_tpu.audio.stoi import STOI  # noqa: E402,F401

__all__ += ["PESQ", "STOI"]
