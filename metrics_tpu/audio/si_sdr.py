"""Deprecated SI_SDR alias class.

Parity: reference ``torchmetrics/audio/si_sdr.py:22`` (renamed to
``ScaleInvariantSignalDistortionRatio`` in v0.7; alias warns on construction).
"""
from typing import Any

from metrics_tpu.audio.sdr import ScaleInvariantSignalDistortionRatio
from metrics_tpu.utils.prints import rank_zero_warn


class SI_SDR(ScaleInvariantSignalDistortionRatio):
    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        rank_zero_warn(
            "`SI_SDR` was renamed to `ScaleInvariantSignalDistortionRatio` and it will be removed.",
            DeprecationWarning,
        )
        super().__init__(zero_mean=zero_mean, **kwargs)
