"""SDR module metrics.

Parity: reference ``torchmetrics/audio/sdr.py:23,150,195`` (SignalDistortionRatio,
deprecated SDR, ScaleInvariantSignalDistortionRatio).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio.sdr import (
    scale_invariant_signal_distortion_ratio,
    signal_distortion_ratio,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class SignalDistortionRatio(Metric):
    """SDR with optimal distortion filter, averaged over samples.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SignalDistortionRatio
        >>> n = jnp.arange(64.0)
        >>> target = jnp.sin(n / 4)[None]
        >>> preds = target + 0.1 * jnp.cos(n / 3)[None]
        >>> sdr = SignalDistortionRatio()
        >>> print(f"{float(sdr(preds, target)):.2f}")
        28.53
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        use_cg_iter: Optional[int] = None,
        filter_length: int = 512,
        zero_mean: bool = False,
        load_diag: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag
        self.add_state("sum_sdr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sdr_batch = signal_distortion_ratio(
            preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
        )
        self.sum_sdr = self.sum_sdr + jnp.sum(sdr_batch)
        self.total = self.total + sdr_batch.size

    def compute(self) -> Array:
        return self.sum_sdr / self.total


class SDR(SignalDistortionRatio):
    """Deprecated alias. Parity: reference ``sdr.py:150``."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        rank_zero_warn("`SDR` was renamed to `SignalDistortionRatio` and it will be removed.", DeprecationWarning)
        super().__init__(*args, **kwargs)


class ScaleInvariantSignalDistortionRatio(Metric):
    """SI-SDR, averaged over samples."""

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean
        self.add_state("sum_si_sdr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        si_sdr_batch = scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_si_sdr = self.sum_si_sdr + jnp.sum(si_sdr_batch)
        self.total = self.total + si_sdr_batch.size

    def compute(self) -> Array:
        return self.sum_si_sdr / self.total
