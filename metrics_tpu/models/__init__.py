"""Embedded models used by metric families (FID/IS/KID inception, BERTScore encoder).

The reference delegates these to third-party packages (torch-fidelity, transformers);
here they are Flax modules sharded under the caller's mesh.
"""
from metrics_tpu.models.inception import InceptionFeatureExtractor, InceptionV3
