"""InceptionV3 (FID variant) in Flax — the embedded feature extractor for FID/IS/KID.

Parity target: reference ``torchmetrics/image/fid.py:38-55`` (NoTrainInceptionV3 via
torch-fidelity, pool3 2048-d features + 1008-way logits head). The reference
downloads pretrained weights at construction (``fid.py:242``); this build has no
network egress, so the module exposes ``load_params(path)`` for weights converted to
an ``.npz``/pytree checkpoint, and otherwise initialises randomly with a loud warning
(feature geometry, sharding and all downstream math are identical either way).

TPU notes: all convs are NHWC (the TPU-native layout), run under the caller's mesh —
sharding the batch dim data-parallel shards the inception forward with zero code
changes. BatchNorm is folded to inference scale/bias (no running stats to carry).
"""
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

Array = jax.Array


class BasicConv2d(nn.Module):
    """Conv + (inference) BatchNorm + ReLU."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "VALID"

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = nn.Conv(self.features, self.kernel, self.strides, padding=self.padding, use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=True, epsilon=0.001)(x)
        return nn.relu(x)


def _max_pool(x: Array, window: int, stride: int) -> Array:
    return nn.max_pool(x, (window, window), (stride, stride), padding="VALID")


def _avg_pool_same(x: Array, window: int = 3) -> Array:
    # torch-fidelity's FID variant patches the branch poolings to
    # avg_pool2d(..., count_include_pad=False): border windows divide by the
    # number of REAL pixels, not the full window area. Without this every
    # pooled border pixel deviates from the reference features.
    return nn.avg_pool(x, (window, window), (1, 1), padding="SAME", count_include_pad=False)


class InceptionA(nn.Module):
    pool_features: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b1 = BasicConv2d(64, (1, 1))(x)
        b2 = BasicConv2d(48, (1, 1))(x)
        b2 = BasicConv2d(64, (5, 5), padding="SAME")(b2)
        b3 = BasicConv2d(64, (1, 1))(x)
        b3 = BasicConv2d(96, (3, 3), padding="SAME")(b3)
        b3 = BasicConv2d(96, (3, 3), padding="SAME")(b3)
        b4 = _avg_pool_same(x)
        b4 = BasicConv2d(self.pool_features, (1, 1))(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    @nn.compact
    def __call__(self, x: Array) -> Array:
        b1 = BasicConv2d(384, (3, 3), strides=(2, 2))(x)
        b2 = BasicConv2d(64, (1, 1))(x)
        b2 = BasicConv2d(96, (3, 3), padding="SAME")(b2)
        b2 = BasicConv2d(96, (3, 3), strides=(2, 2))(b2)
        b3 = _max_pool(x, 3, 2)
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        c7 = self.channels_7x7
        b1 = BasicConv2d(192, (1, 1))(x)
        b2 = BasicConv2d(c7, (1, 1))(x)
        b2 = BasicConv2d(c7, (1, 7), padding="SAME")(b2)
        b2 = BasicConv2d(192, (7, 1), padding="SAME")(b2)
        b3 = BasicConv2d(c7, (1, 1))(x)
        b3 = BasicConv2d(c7, (7, 1), padding="SAME")(b3)
        b3 = BasicConv2d(c7, (1, 7), padding="SAME")(b3)
        b3 = BasicConv2d(c7, (7, 1), padding="SAME")(b3)
        b3 = BasicConv2d(192, (1, 7), padding="SAME")(b3)
        b4 = _avg_pool_same(x)
        b4 = BasicConv2d(192, (1, 1))(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    @nn.compact
    def __call__(self, x: Array) -> Array:
        b1 = BasicConv2d(192, (1, 1))(x)
        b1 = BasicConv2d(320, (3, 3), strides=(2, 2))(b1)
        b2 = BasicConv2d(192, (1, 1))(x)
        b2 = BasicConv2d(192, (1, 7), padding="SAME")(b2)
        b2 = BasicConv2d(192, (7, 1), padding="SAME")(b2)
        b2 = BasicConv2d(192, (3, 3), strides=(2, 2))(b2)
        b3 = _max_pool(x, 3, 2)
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    pool_mode: str = "avg"

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b1 = BasicConv2d(320, (1, 1))(x)
        b2 = BasicConv2d(384, (1, 1))(x)
        b2 = jnp.concatenate(
            [BasicConv2d(384, (1, 3), padding="SAME")(b2), BasicConv2d(384, (3, 1), padding="SAME")(b2)], axis=-1
        )
        b3 = BasicConv2d(448, (1, 1))(x)
        b3 = BasicConv2d(384, (3, 3), padding="SAME")(b3)
        b3 = jnp.concatenate(
            [BasicConv2d(384, (1, 3), padding="SAME")(b3), BasicConv2d(384, (3, 1), padding="SAME")(b3)], axis=-1
        )
        if self.pool_mode == "max":
            b4 = nn.max_pool(x, (3, 3), (1, 1), padding="SAME")
        else:
            b4 = _avg_pool_same(x)
        b4 = BasicConv2d(192, (1, 1))(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    """FID-variant InceptionV3. Input: (N, 299, 299, 3) in [0, 1] floats or uint8.

    Returns a dict of the standard FID feature taps: '64', '192', '768', '2048',
    'logits_unbiased' — matching the reference's feature-size selector
    (``torchmetrics/image/fid.py:164-180``).
    """

    num_classes: int = 1008

    @nn.compact
    def __call__(self, x: Array) -> Dict[str, Array]:
        # torch-fidelity normalisation is (x - 128) / 128 on the 0..255 scale
        # (NOT the symmetric 2x/255 - 1): uint8 255 maps to 0.9921875. Floats
        # are taken as [0, 1] and quantised by truncation — the same
        # `(imgs * 255).byte()` rule torchmetrics applies before this graph —
        # so both input kinds produce identical features.
        if x.dtype == jnp.uint8:
            x = x.astype(jnp.float32)
        else:
            x = jnp.floor(x * 255.0)
        x = (x - 128.0) / 128.0

        out: Dict[str, Array] = {}
        x = BasicConv2d(32, (3, 3), strides=(2, 2))(x)
        x = BasicConv2d(32, (3, 3))(x)
        x = BasicConv2d(64, (3, 3), padding="SAME")(x)
        x = _max_pool(x, 3, 2)
        out["64"] = jnp.mean(x, axis=(1, 2))

        x = BasicConv2d(80, (1, 1))(x)
        x = BasicConv2d(192, (3, 3))(x)
        x = _max_pool(x, 3, 2)
        out["192"] = jnp.mean(x, axis=(1, 2))

        x = InceptionA(pool_features=32)(x)
        x = InceptionA(pool_features=64)(x)
        x = InceptionA(pool_features=64)(x)
        x = InceptionB()(x)
        out["768"] = jnp.mean(x, axis=(1, 2))

        x = InceptionC(channels_7x7=128)(x)
        x = InceptionC(channels_7x7=160)(x)
        x = InceptionC(channels_7x7=160)(x)
        x = InceptionC(channels_7x7=192)(x)
        x = InceptionD()(x)
        x = InceptionE(pool_mode="avg")(x)
        x = InceptionE(pool_mode="max")(x)
        pooled = jnp.mean(x, axis=(1, 2))
        out["2048"] = pooled
        out["logits_unbiased"] = nn.Dense(self.num_classes, use_bias=False)(pooled)
        return out


# output width of each feature tap (used by FID/IS/KID to size streaming buffers)
FEATURE_DIMS = {"64": 64, "192": 192, "768": 768, "2048": 2048, "logits_unbiased": 1008}


class InceptionFeatureExtractor:
    """Stateful convenience wrapper: jitted inception forward returning one tap.

    Weights: pass ``params`` (a flax param pytree, e.g. converted from
    torch-fidelity's checkpoint) or a path via ``load_params``. Without params the
    net is randomly initialised — fine for pipeline/sharding tests, meaningless for
    real FID values (warned once).
    """

    def __init__(
        self,
        feature: str = "2048",
        params: Optional[Any] = None,
        input_size: int = 299,
        seed: int = 0,
    ) -> None:
        from metrics_tpu.utils.prints import rank_zero_warn

        self.feature = str(feature)
        self.module = InceptionV3()
        if params is None:
            rank_zero_warn(
                "No pretrained InceptionV3 params provided (no network egress in this build);"
                " using random initialisation. Pass `params=` (converted torch-fidelity"
                " weights) for meaningful FID/IS/KID values.",
                UserWarning,
            )
            dummy = jnp.zeros((1, input_size, input_size, 3), dtype=jnp.float32)
            # jit the init: un-jitted flax init executes the whole net eagerly,
            # one dispatch round-trip per op (~minutes over a tunnelled TPU)
            params = jax.jit(self.module.init)(jax.random.PRNGKey(seed), dummy)
        self.params = params
        self._forward = jax.jit(lambda p, x: self.module.apply(p, x)[self.feature])

    @staticmethod
    def load_params(path: str) -> Any:
        import pickle

        with open(path, "rb") as f:
            return pickle.load(f)

    def __call__(self, imgs: Array) -> Array:
        if imgs.ndim == 4 and imgs.shape[1] == 3 and imgs.shape[-1] != 3:
            imgs = jnp.transpose(imgs, (0, 2, 3, 1))  # NCHW -> NHWC
        return self._forward(self.params, imgs)
