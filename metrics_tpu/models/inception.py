"""InceptionV3 (FID variant) in Flax — the embedded feature extractor for FID/IS/KID.

Parity target: reference ``torchmetrics/image/fid.py:38-55`` (NoTrainInceptionV3 via
torch-fidelity, pool3 2048-d features + 1008-way logits head). The reference
downloads pretrained weights at construction (``fid.py:242``); this build has no
network egress, so the module exposes ``load_params(path)`` for weights converted to
an ``.npz``/pytree checkpoint, and otherwise initialises randomly with a loud warning
(feature geometry, sharding and all downstream math are identical either way).

TPU notes: all convs are NHWC (the TPU-native layout), run under the caller's mesh —
sharding the batch dim data-parallel shards the inception forward with zero code
changes. BatchNorm is folded to inference scale/bias (no running stats to carry).
"""
from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

Array = jax.Array


class BasicConv2d(nn.Module):
    """Conv + (inference) BatchNorm + ReLU."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "VALID"
    # flax's standard mixed-precision knob: inputs AND params are cast to this
    # dtype for the computation (param storage stays param_dtype=f32)
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = nn.Conv(self.features, self.kernel, self.strides, padding=self.padding,
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=True, epsilon=0.001, dtype=self.dtype)(x)
        return nn.relu(x)


# alias for the blocks' dtype-bound `BasicConv2d = partial(_BasicConv2d, ...)`
# rebinding (flax submodule names come from the CLASS, so they stay stable)
_BasicConv2d = BasicConv2d


def _max_pool(x: Array, window: int, stride: int) -> Array:
    return nn.max_pool(x, (window, window), (stride, stride), padding="VALID")


def _avg_pool_same(x: Array, window: int = 3) -> Array:
    # torch-fidelity's FID variant patches the branch poolings to
    # avg_pool2d(..., count_include_pad=False): border windows divide by the
    # number of REAL pixels, not the full window area. Without this every
    # pooled border pixel deviates from the reference features.
    return nn.avg_pool(x, (window, window), (1, 1), padding="SAME", count_include_pad=False)


class InceptionA(nn.Module):
    pool_features: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        BasicConv2d = partial(_BasicConv2d, dtype=self.dtype)
        b1 = BasicConv2d(64, (1, 1))(x)
        b2 = BasicConv2d(48, (1, 1))(x)
        b2 = BasicConv2d(64, (5, 5), padding="SAME")(b2)
        b3 = BasicConv2d(64, (1, 1))(x)
        b3 = BasicConv2d(96, (3, 3), padding="SAME")(b3)
        b3 = BasicConv2d(96, (3, 3), padding="SAME")(b3)
        b4 = _avg_pool_same(x)
        b4 = BasicConv2d(self.pool_features, (1, 1))(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        BasicConv2d = partial(_BasicConv2d, dtype=self.dtype)
        b1 = BasicConv2d(384, (3, 3), strides=(2, 2))(x)
        b2 = BasicConv2d(64, (1, 1))(x)
        b2 = BasicConv2d(96, (3, 3), padding="SAME")(b2)
        b2 = BasicConv2d(96, (3, 3), strides=(2, 2))(b2)
        b3 = _max_pool(x, 3, 2)
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        BasicConv2d = partial(_BasicConv2d, dtype=self.dtype)
        c7 = self.channels_7x7
        b1 = BasicConv2d(192, (1, 1))(x)
        b2 = BasicConv2d(c7, (1, 1))(x)
        b2 = BasicConv2d(c7, (1, 7), padding="SAME")(b2)
        b2 = BasicConv2d(192, (7, 1), padding="SAME")(b2)
        b3 = BasicConv2d(c7, (1, 1))(x)
        b3 = BasicConv2d(c7, (7, 1), padding="SAME")(b3)
        b3 = BasicConv2d(c7, (1, 7), padding="SAME")(b3)
        b3 = BasicConv2d(c7, (7, 1), padding="SAME")(b3)
        b3 = BasicConv2d(192, (1, 7), padding="SAME")(b3)
        b4 = _avg_pool_same(x)
        b4 = BasicConv2d(192, (1, 1))(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        BasicConv2d = partial(_BasicConv2d, dtype=self.dtype)
        b1 = BasicConv2d(192, (1, 1))(x)
        b1 = BasicConv2d(320, (3, 3), strides=(2, 2))(b1)
        b2 = BasicConv2d(192, (1, 1))(x)
        b2 = BasicConv2d(192, (1, 7), padding="SAME")(b2)
        b2 = BasicConv2d(192, (7, 1), padding="SAME")(b2)
        b2 = BasicConv2d(192, (3, 3), strides=(2, 2))(b2)
        b3 = _max_pool(x, 3, 2)
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    pool_mode: str = "avg"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        BasicConv2d = partial(_BasicConv2d, dtype=self.dtype)
        b1 = BasicConv2d(320, (1, 1))(x)
        b2 = BasicConv2d(384, (1, 1))(x)
        b2 = jnp.concatenate(
            [BasicConv2d(384, (1, 3), padding="SAME")(b2), BasicConv2d(384, (3, 1), padding="SAME")(b2)], axis=-1
        )
        b3 = BasicConv2d(448, (1, 1))(x)
        b3 = BasicConv2d(384, (3, 3), padding="SAME")(b3)
        b3 = jnp.concatenate(
            [BasicConv2d(384, (1, 3), padding="SAME")(b3), BasicConv2d(384, (3, 1), padding="SAME")(b3)], axis=-1
        )
        if self.pool_mode == "max":
            b4 = nn.max_pool(x, (3, 3), (1, 1), padding="SAME")
        else:
            b4 = _avg_pool_same(x)
        b4 = BasicConv2d(192, (1, 1))(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    """FID-variant InceptionV3. Input: (N, 299, 299, 3) in [0, 1] floats or uint8.

    Returns a dict of the standard FID feature taps: '64', '192', '768', '2048',
    'logits_unbiased' — matching the reference's feature-size selector
    (``torchmetrics/image/fid.py:164-180``).
    """

    num_classes: int = 1008
    # when set (e.g. jnp.bfloat16) every layer computes in this dtype (flax's
    # standard mixed-precision knob; param STORAGE stays f32). Halves the
    # activation/weight HBM traffic — measured ~30% faster fwd on v5e at ~0.3%
    # relative feature noise — and doubles batch headroom; tap means and the
    # downstream statistics still accumulate in f32, and the input scaling is
    # exact (uint8 values are exactly representable in bf16)
    compute_dtype: Optional[Any] = None
    # expects params transformed by ``fold_preprocess_into_params``: the
    # (x-128)/128 input normalisation is absorbed into the first conv's kernel
    # and BN mean (exact — the first conv is VALID, so every window is full),
    # removing one full-image elementwise pass from the compiled forward
    preprocess_folded: bool = False
    # expects params transformed by ``pad_stem_params(lanes=...)``: the stem
    # convs (32/32/64/80 output channels — under-filling the 128-lane MXU; the
    # per-layer attribution table shows them at 0.19-0.37 structural tile
    # efficiency, ~21% of ideal time on ~10% of FLOPs) are widened with zero
    # channels so every stem GEMM runs at full lane width; padded channels stay
    # exactly zero through BN (scale=0) and relu, and the '64' tap slices back
    # to the logical width, so features are unchanged
    stem_lanes: Optional[int] = None
    # consume a POST-STEM activation (N, H', W', 192) instead of images: the
    # preprocessing + 5 stem convs + 2 pools are skipped entirely (run them
    # with ``stem_apply``, e.g. channel-tensor-sharded under a mesh) and only
    # the trunk taps ('768', '2048', 'logits_unbiased') are returned. Flax
    # auto-names are per-class counters, so the trunk blocks keep their
    # canonical names (InceptionA_0, ...) and the same params apply — filter
    # the stem layers out with ``split_stem_variables`` first.
    stem_input: bool = False

    @nn.compact
    def __call__(self, x: Array) -> Dict[str, Array]:
        dt = self.compute_dtype
        BasicConv2d = partial(_BasicConv2d, dtype=dt)
        lanes = self.stem_lanes

        def st(features: int) -> int:
            # stem width under MXU padding (features already >= lanes unchanged)
            return features if lanes is None or features >= lanes else lanes

        def tap_mean(v: Array) -> Array:
            # the taps are consumed by f32/float-float statistics: accumulate
            # the spatial mean in f32 even when activations run bf16
            return jnp.mean(v.astype(jnp.float32), axis=(1, 2))

        out: Dict[str, Array] = {}
        if self.stem_input:
            if dt is not None:
                x = x.astype(dt)
        else:
            # torch-fidelity normalisation is (x - 128) / 128 on the 0..255
            # scale (NOT the symmetric 2x/255 - 1): uint8 255 maps to
            # 0.9921875. Floats are taken as [0, 1] and quantised by
            # truncation — the same `(imgs * 255).byte()` rule torchmetrics
            # applies before this graph — so both input kinds produce
            # identical features. With ``preprocess_folded`` the conv consumes
            # the raw 0..255 scale (values exactly representable in bf16) and
            # the affine lives in the params.
            if x.dtype == jnp.uint8:
                x = x.astype(jnp.float32)
            else:
                x = jnp.floor(x * 255.0)
            if not self.preprocess_folded:
                x = (x - 128.0) / 128.0
            if dt is not None:
                x = x.astype(dt)

            x = BasicConv2d(st(32), (3, 3), strides=(2, 2))(x)
            x = BasicConv2d(st(32), (3, 3))(x)
            x = BasicConv2d(st(64), (3, 3), padding="SAME")(x)
            x = _max_pool(x, 3, 2)
            out["64"] = tap_mean(x[..., :64] if lanes is not None else x)

            x = BasicConv2d(st(80), (1, 1))(x)
            x = BasicConv2d(192, (3, 3))(x)
            x = _max_pool(x, 3, 2)
            out["192"] = tap_mean(x)

        x = InceptionA(pool_features=32, dtype=dt)(x)
        x = InceptionA(pool_features=64, dtype=dt)(x)
        x = InceptionA(pool_features=64, dtype=dt)(x)
        x = InceptionB(dtype=dt)(x)
        out["768"] = tap_mean(x)

        x = InceptionC(channels_7x7=128, dtype=dt)(x)
        x = InceptionC(channels_7x7=160, dtype=dt)(x)
        x = InceptionC(channels_7x7=160, dtype=dt)(x)
        x = InceptionC(channels_7x7=192, dtype=dt)(x)
        x = InceptionD(dtype=dt)(x)
        x = InceptionE(pool_mode="avg", dtype=dt)(x)
        x = InceptionE(pool_mode="max", dtype=dt)(x)
        pooled = tap_mean(x)
        out["2048"] = pooled
        out["logits_unbiased"] = nn.Dense(self.num_classes, use_bias=False, dtype=dt)(
            pooled.astype(dt) if dt is not None else pooled
        ).astype(pooled.dtype)
        return out


# output width of each feature tap (used by FID/IS/KID to size streaming buffers)
FEATURE_DIMS = {"64": 64, "192": 192, "768": 768, "2048": 2048, "logits_unbiased": 1008}


def _replace_in(variables: Any, collection: str, layer: str, sub: str, updates: Dict[str, Array]) -> Any:
    """Copy-on-write update of ``variables[collection][layer][sub]`` leaves."""
    new = dict(variables)
    coll = dict(new[collection])
    lay = dict(coll[layer])
    leaf = dict(lay[sub])
    leaf.update(updates)
    lay[sub] = leaf
    coll[layer] = lay
    new[collection] = coll
    return new


def fold_preprocess_into_params(variables: Any) -> Any:
    """Absorb the ``(x - 128) / 128`` input affine into the first conv's params.

    Exact linear algebra (the first conv is VALID — every window is full, so
    ``conv(W, (x-128)/128) = conv(W/128, x) - Σ_hwi W`` per output channel, and
    the constant offset moves into the following BatchNorm's running mean):
    ``kernel' = W / 128``, ``mean' = mean + Σ_hwi W``. Consume the result with
    ``InceptionV3(preprocess_folded=True)``; features agree with the unfolded
    graph to f32 rounding. Pure — the input pytree is not mutated.
    """
    k = variables["params"]["BasicConv2d_0"]["Conv_0"]["kernel"]
    mean = variables["batch_stats"]["BasicConv2d_0"]["BatchNorm_0"]["mean"]
    out = _replace_in(variables, "params", "BasicConv2d_0", "Conv_0", {"kernel": k / 128.0})
    return _replace_in(
        out, "batch_stats", "BasicConv2d_0", "BatchNorm_0",
        {"mean": mean + jnp.sum(k, axis=(0, 1, 2))},
    )


# (layer, pad_input_channels, pad_output_channels) for the stem under MXU
# padding; BasicConv2d_0's input is the 3-channel image (never padded) and
# BasicConv2d_4's 192 output already exceeds the lane width
_STEM_PAD = (
    ("BasicConv2d_0", False, True),
    ("BasicConv2d_1", True, True),
    ("BasicConv2d_2", True, True),
    ("BasicConv2d_3", True, True),
    ("BasicConv2d_4", True, False),
)


def pad_stem_params(variables: Any, lanes: int = 128) -> Any:
    """Zero-pad the stem conv/BN params to ``lanes`` output channels.

    The padded channels are exact zeros end to end: kernel output slices are 0,
    BN runs them through ``scale=0, bias=0, mean=0, var=1`` (still 0), relu
    keeps 0, and the next conv's padded *input* slices carry zero weights — so
    the logical computation is unchanged while every stem GEMM presents full
    MXU lane width. Consume with ``InceptionV3(stem_lanes=lanes)``. Pure.
    """
    out = variables
    for layer, pad_in, pad_out in _STEM_PAD:
        kernel = out["params"][layer]["Conv_0"]["kernel"]
        kh, kw, cin, cout = kernel.shape
        pin = (lanes - cin) if (pad_in and cin < lanes) else 0
        pout = (lanes - cout) if (pad_out and cout < lanes) else 0
        if pin or pout:
            kernel = jnp.pad(kernel, ((0, 0), (0, 0), (0, pin), (0, pout)))
            out = _replace_in(out, "params", layer, "Conv_0", {"kernel": kernel})
        if pout:
            bn = out["params"][layer]["BatchNorm_0"]
            out = _replace_in(out, "params", layer, "BatchNorm_0", {
                "scale": jnp.pad(bn["scale"], (0, pout)),
                "bias": jnp.pad(bn["bias"], (0, pout)),
            })
            st = out["batch_stats"][layer]["BatchNorm_0"]
            out = _replace_in(out, "batch_stats", layer, "BatchNorm_0", {
                "mean": jnp.pad(st["mean"], (0, pout)),
                "var": jnp.pad(st["var"], (0, pout), constant_values=1.0),
            })
    return out


def random_inception_params(
    input_size: int = 299, seed: int = 0, fast: bool = False
) -> Any:
    """Random canonical InceptionV3 variables (the no-pretrained-weights path).

    ``fast=True`` fills the ``jax.eval_shape`` tree with host RNG instead of
    compiling the flax init (~16 s on CPU) — deterministic per seed, fine for
    pipeline/sharding/parity tests, meaningless for real FID values. BN
    ``var`` leaves land in [0.5, 1.5] so ``rsqrt(var + eps)`` stays benign.
    """
    m = InceptionV3()
    dummy = jnp.zeros((1, input_size, input_size, 3), dtype=jnp.float32)
    if not fast:
        return jax.jit(m.init)(jax.random.PRNGKey(seed), dummy)
    import numpy as np

    abstract = jax.eval_shape(m.init, jax.random.PRNGKey(seed), dummy)
    rng = np.random.RandomState(seed)

    def fill(path, leaf):
        name = jax.tree_util.keystr(path)
        if "var" in name:
            return jnp.asarray(rng.uniform(0.5, 1.5, leaf.shape).astype(leaf.dtype))
        if "scale" in name:
            return jnp.asarray(
                (1.0 + 0.1 * rng.standard_normal(leaf.shape)).astype(leaf.dtype)
            )
        if len(leaf.shape) >= 2:  # conv kernels / dense: fan-in scaled so the
            # activations stay O(1) through 11 blocks (precision parity tests
            # compare against analytic bounds — exploding magnitudes would
            # drown them)
            fan_in = float(np.prod(leaf.shape[:-1]))
            std = (2.0 / fan_in) ** 0.5
            return jnp.asarray(
                (std * rng.standard_normal(leaf.shape)).astype(leaf.dtype)
            )
        return jnp.asarray(
            (0.1 * rng.standard_normal(leaf.shape)).astype(leaf.dtype)
        )

    return jax.tree_util.tree_map_with_path(fill, abstract)


# the 5 top-level stem layers, in application order, with (strides, padding).
# Everything before the '192' tap lives here; everything after is "trunk".
STEM_LAYERS = (
    "BasicConv2d_0", "BasicConv2d_1", "BasicConv2d_2",
    "BasicConv2d_3", "BasicConv2d_4",
)
_STEM_SPECS = (
    ((2, 2), "VALID"),
    ((1, 1), "VALID"),
    ((1, 1), "SAME"),
    ((1, 1), "VALID"),
    ((1, 1), "VALID"),
)


def split_stem_variables(variables: Any) -> Tuple[Any, Any]:
    """Split a canonical variables tree into ``(stem_vars, trunk_vars)``.

    ``stem_vars`` holds the 5 stem conv/BN layers (consumed by ``stem_apply``);
    ``trunk_vars`` is everything else (consumed by
    ``InceptionV3(stem_input=True).apply``). Pure; leaves are shared, not
    copied.
    """
    stem: Dict[str, Any] = {}
    trunk: Dict[str, Any] = {}
    for coll, layers in variables.items():
        s = {k: v for k, v in layers.items() if k in STEM_LAYERS}
        t = {k: v for k, v in layers.items() if k not in STEM_LAYERS}
        if s:
            stem[coll] = s
        if t:
            trunk[coll] = t
    return stem, trunk


def _conv_bn_relu(
    x: Array,
    kernel: Array,
    scale: Array,
    bias: Array,
    mean: Array,
    var: Array,
    strides: Tuple[int, int],
    padding: str,
    dt: Optional[Any],
) -> Array:
    """One BasicConv2d, functionally — bitwise the flax module's op sequence
    (lax conv NHWC/HWIO, then flax BatchNorm's ``(x - mean) * (rsqrt(var + eps)
    * scale) + bias`` with eps=0.001, then relu)."""
    if dt is not None:
        x = x.astype(dt)
        kernel, scale, bias, mean, var = (
            a.astype(dt) for a in (kernel, scale, bias, mean, var)
        )
    y = jax.lax.conv_general_dilated(
        x, kernel, strides, padding, dimension_numbers=("NHWC", "HWIO", "NHWC")
    )
    y = (y - mean) * (jax.lax.rsqrt(var + 0.001) * scale) + bias
    return jax.nn.relu(y)


def stem_apply(
    stem_variables: Any,
    x: Array,
    *,
    compute_dtype: Optional[Any] = None,
    preprocess_folded: bool = False,
    stem_lanes: Optional[int] = None,
    gather_axis: Optional[str] = None,
) -> Tuple[Array, Dict[str, Array]]:
    """Pure functional stem: preprocessing + 5 stem convs + 2 pools + taps.

    Returns ``(post_stem, {'64': ..., '192': ...})`` where ``post_stem`` feeds
    ``InceptionV3(stem_input=True)``. Bitwise-matches the module stem on the
    same params (same primitive sequence): the module/``stem_apply`` split is a
    pure refactor of the graph, not an approximation.

    ``gather_axis``: when called inside ``shard_map`` with the conv kernels
    sharded over their OUTPUT-channel dim (and the BN vectors over dim 0), each
    layer computes its local channel slice and ``all_gather(..., tiled=True)``
    restores the full channel order before the next layer — the tensor-parallel
    stem of the model host. The gather is the only collective this function
    emits.
    """
    params = stem_variables["params"]
    stats = stem_variables["batch_stats"]
    if x.dtype == jnp.uint8:
        x = x.astype(jnp.float32)
    else:
        x = jnp.floor(x * 255.0)
    if not preprocess_folded:
        x = (x - 128.0) / 128.0
    if compute_dtype is not None:
        x = x.astype(compute_dtype)

    def tap_mean(v: Array) -> Array:
        return jnp.mean(v.astype(jnp.float32), axis=(1, 2))

    taps: Dict[str, Array] = {}
    for i, (layer, (strides, padding)) in enumerate(zip(STEM_LAYERS, _STEM_SPECS)):
        bn = params[layer]["BatchNorm_0"]
        st = stats[layer]["BatchNorm_0"]
        x = _conv_bn_relu(
            x, params[layer]["Conv_0"]["kernel"], bn["scale"], bn["bias"],
            st["mean"], st["var"], strides, padding, compute_dtype,
        )
        if gather_axis is not None:
            x = jax.lax.all_gather(x, gather_axis, axis=-1, tiled=True)
        if i == 2:
            x = _max_pool(x, 3, 2)
            taps["64"] = tap_mean(x[..., :64] if stem_lanes is not None else x)
        elif i == 4:
            x = _max_pool(x, 3, 2)
            taps["192"] = tap_mean(x)
    return x, taps


def resolve_feature_extractor(
    metric_name: str,
    feature: Any,
    params: Optional[Any],
    mesh: Optional[Any],
    mesh_axis: Any,
    valid: Tuple[str, ...],
    model_host: Optional[Any] = None,
) -> Tuple[Callable, Optional[int]]:
    """Shared FID/IS/KID ctor logic: a callable passes through (``mesh`` is
    rejected — we can't shard an opaque callable; wrap it with
    ``parallel.shard_batch_forward`` yourself), a tap name builds the built-in
    extractor (optionally mesh-sharded). Returns ``(extractor, feature_dim)``
    with ``feature_dim=None`` for callables.

    ``model_host``: route the forward through the resident embedded-model
    serving path (``engine.model_host``, ISSUE 19) instead of a per-metric
    monolithic extractor — ``True`` builds/shares the registry host for this
    (tap, params, mesh, precision, buckets) identity, a ``ModelHostConfig``
    customises it, a ``ModelHost`` instance is used as-is. Metrics sharing an
    identity share ONE resident model (params shared, not copied). The
    returned extractor carries the host as ``extractor.model_host``.
    """
    if model_host is not None and model_host is not False:
        if callable(feature) and not isinstance(feature, (str, int)):
            raise ValueError(
                f"{metric_name}(model_host=...) only applies to the built-in "
                f"InceptionV3 (feature in {valid}); wrap your callable with "
                "engine.model_host.ModelHost yourself."
            )
        from metrics_tpu.engine.model_host import (
            ModelHost, ModelHostConfig, inception_host,
        )

        if isinstance(model_host, ModelHost):
            host = model_host
        else:
            config = (
                model_host if isinstance(model_host, ModelHostConfig)
                else ModelHostConfig(mesh=mesh, mesh_axis=mesh_axis)
            )
            host = inception_host(str(feature), params, config=config)

        def extractor(imgs: Array) -> Array:
            return jnp.asarray(host.infer(imgs))

        extractor.model_host = host
        return extractor, FEATURE_DIMS[str(feature)]
    if callable(feature):
        if mesh is not None:
            raise ValueError(
                f"{metric_name}(mesh=...) only applies to the built-in InceptionV3 "
                f"(feature in {valid}). For a callable `feature`, shard it yourself "
                "with metrics_tpu.parallel.shard_batch_forward(fn, mesh) and pass "
                "the wrapped callable."
            )
        return feature, None
    if str(feature) not in valid:
        raise ValueError(
            f"Input to argument `feature` must be one of {valid}, but got {feature}."
        )
    extractor = InceptionFeatureExtractor(
        feature=str(feature), params=params, mesh=mesh, mesh_axis=mesh_axis
    )
    return extractor, FEATURE_DIMS[str(feature)]


class InceptionFeatureExtractor:
    """Stateful convenience wrapper: jitted inception forward returning one tap.

    Weights: pass ``params`` (a flax param pytree, e.g. converted from
    torch-fidelity's checkpoint) or a path via ``load_params``. Without params the
    net is randomly initialised — fine for pipeline/sharding tests, meaningless for
    real FID values (warned once).

    ``compute_dtype=jnp.bfloat16`` runs every layer in bf16 (flax layer
    ``dtype``; the stored params remain a single f32 master, cast on the fly
    inside the compiled forward). Measured ~30% faster on v5e with ~0.3%
    relative feature noise and half the activation memory
    (``tests/image/test_bf16_inception.py``); tap means and the downstream
    FID/IS/KID statistics still accumulate in f32. The reference pipeline has
    no analogue (torch-fidelity runs f32); keep the default for strict-parity
    FID values, opt in for throughput/memory::

        ext = InceptionFeatureExtractor(feature="2048", compute_dtype=jnp.bfloat16)
        fid = FID(feature=ext, feature_dim=2048)

    ``mesh=`` runs the forward batch-parallel over the mesh's ``mesh_axis``
    (params replicated, batch sharded via ``parallel.embedded.shard_batch_forward``)
    — the TPU-native analogue of the reference's per-process inception + feature
    all_gather (``torchmetrics/image/fid.py:250-262``). Features come back as a
    global array batch-sharded over the axis; FID's streaming statistics consume
    them distributed. Sharded == single-device parity:
    ``tests/parallel/test_sharded_embedded.py``.
    """

    def __init__(
        self,
        feature: str = "2048",
        params: Optional[Any] = None,
        input_size: int = 299,
        seed: int = 0,
        compute_dtype: Optional[Any] = None,
        mesh: Optional[Any] = None,
        mesh_axis: Any = "dp",
        fold_preprocess: bool = False,
        stem_lanes: Optional[int] = None,
    ) -> None:
        from metrics_tpu.utils.prints import rank_zero_warn

        self.feature = str(feature)
        self.compute_dtype = compute_dtype
        self.fold_preprocess = bool(fold_preprocess)
        self.stem_lanes = stem_lanes
        # the CANONICAL module defines the public param tree (what `params=`,
        # `load_params` and the weight converter produce); the forward module
        # may differ (folded preprocess / MXU-padded stem) and consumes params
        # transformed on the fly inside the compiled forward — the transforms
        # are a handful of pads/sums that XLA folds into the first layers, so
        # rebinding ``ext.params`` (the documented contract) still takes effect.
        # Both transforms default OFF: they are exact only to f32 rounding
        # (~5e-6 feature drift), and a metric library's default path must be
        # bit-identical run to run — the TPU bench/fast path opts in.
        canonical = InceptionV3(compute_dtype=compute_dtype)
        self.module = InceptionV3(
            compute_dtype=compute_dtype,
            preprocess_folded=self.fold_preprocess,
            stem_lanes=stem_lanes,
        )
        if params is None:
            rank_zero_warn(
                "No pretrained InceptionV3 params provided (no network egress in this build);"
                " using random initialisation. Pass `params=` (converted torch-fidelity"
                " weights) for meaningful FID/IS/KID values.",
                UserWarning,
            )
            dummy = jnp.zeros((1, input_size, input_size, 3), dtype=jnp.float32)
            # jit the init: un-jitted flax init executes the whole net eagerly,
            # one dispatch round-trip per op (~minutes over a tunnelled TPU);
            # params initialise in param_dtype (f32) regardless of compute_dtype
            params = jax.jit(canonical.init)(jax.random.PRNGKey(seed), dummy)
        # params stay a single f32 master (public; rebinding ext.params takes
        # effect — the forward reads it per call): the flax layers' `dtype`
        # cast the weights on the fly, which XLA fuses into the consuming ops
        self.params = params

        def fwd(p: Any, x: Array) -> Array:
            if self.fold_preprocess:
                p = fold_preprocess_into_params(p)
            if self.stem_lanes is not None:
                p = pad_stem_params(p, self.stem_lanes)
            return self.module.apply(p, x)[self.feature].astype(jnp.float32)
        if mesh is not None:
            from metrics_tpu.parallel.embedded import shard_batch_forward

            # out_axis=None: the per-shard features are all_gathered INSIDE the
            # compiled forward (the reference's feature-gather semantics,
            # fid.py:250-262) and leave replicated — eager consumers never
            # touch a live-sharded array (XLA's in-process CPU collectives
            # deadlock when an eager op implicitly re-shards one)
            self._forward = shard_batch_forward(
                fwd, mesh, mesh_axis, out_axis=None, replicated_argnums=(0,)
            )
        else:
            self._forward = jax.jit(fwd)

    @staticmethod
    def load_params(path: str) -> Any:
        import pickle

        with open(path, "rb") as f:
            return pickle.load(f)

    def __call__(self, imgs: Array) -> Array:
        if imgs.ndim == 4 and imgs.shape[1] == 3 and imgs.shape[-1] != 3:
            imgs = jnp.transpose(imgs, (0, 2, 3, 1))  # NCHW -> NHWC
        return self._forward(self.params, imgs)
