"""Perceptual feature backbones for LPIPS — Flax VGG16 and AlexNet.

Parity target: the ``lpips`` package nets the reference embeds
(``torchmetrics/image/lpip_similarity.py:30-41,123`` — ``lpips.LPIPS(net=...)``
wraps torchvision VGG16/AlexNet feature stacks sliced at the standard
perceptual taps, plus learned per-channel linear weights). This build has no
egress, so weights arrive via ``tools/convert_weights.py lpips`` (offline
conversion of a torch ``lpips.LPIPS`` state dict); the graphs here mirror the
torch definitions exactly and are parity-tested tap-by-tap in
``tests/tools/test_lpips_graph_parity.py``.

TPU notes: NHWC layout, plain conv/relu/maxpool stacks — XLA fuses these well;
batch-dim sharding under the caller's mesh shards the whole forward.
"""
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

Array = jax.Array

# the lpips ScalingLayer constants: images in [-1, 1] are shifted/scaled
# per-channel (RGB) before the backbone
_LPIPS_SHIFT = (-0.030, -0.088, -0.188)
_LPIPS_SCALE = (0.458, 0.448, 0.450)


def _scale_input(x: Array) -> Array:
    shift = jnp.asarray(_LPIPS_SHIFT, dtype=x.dtype)
    scale = jnp.asarray(_LPIPS_SCALE, dtype=x.dtype)
    return (x - shift) / scale


class VGG16Features(nn.Module):
    """VGG16 feature stack, returning the five LPIPS taps.

    Taps: relu1_2 (64ch), relu2_2 (128), relu3_3 (256), relu4_3 (512),
    relu5_3 (512) — the slices the ``lpips`` package cuts torchvision's
    ``vgg16().features`` into.
    """

    @nn.compact
    def __call__(self, x: Array) -> List[Array]:
        x = _scale_input(x)
        taps: List[Array] = []
        # (convs per block, channels); tap after each block's last relu
        for n_convs, ch in ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512)):
            if taps:  # pool between blocks, not before the first
                x = nn.max_pool(x, (2, 2), (2, 2), padding="VALID")
            for _ in range(n_convs):
                x = nn.relu(nn.Conv(ch, (3, 3), padding="SAME")(x))
            taps.append(x)
        return taps


class AlexNetFeatures(nn.Module):
    """AlexNet feature stack, returning the five LPIPS taps (relu1..relu5)."""

    @nn.compact
    def __call__(self, x: Array) -> List[Array]:
        x = _scale_input(x)
        taps: List[Array] = []
        x = nn.relu(nn.Conv(64, (11, 11), strides=(4, 4), padding=((2, 2), (2, 2)))(x))
        taps.append(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="VALID")
        x = nn.relu(nn.Conv(192, (5, 5), padding=((2, 2), (2, 2)))(x))
        taps.append(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="VALID")
        x = nn.relu(nn.Conv(384, (3, 3), padding=((1, 1), (1, 1)))(x))
        taps.append(x)
        x = nn.relu(nn.Conv(256, (3, 3), padding=((1, 1), (1, 1)))(x))
        taps.append(x)
        x = nn.relu(nn.Conv(256, (3, 3), padding=((1, 1), (1, 1)))(x))
        taps.append(x)
        return taps


_BACKBONES: Dict[str, Any] = {"vgg": VGG16Features, "alex": AlexNetFeatures}


class LPIPSFeatureNet:
    """Jitted LPIPS backbone: ``imgs (N,H,W,3) or (N,3,H,W) -> list of taps``.

    Carries the converted per-layer linear weights (``.weights``) alongside the
    backbone params; ``metrics_tpu.image.LPIPS`` consumes both.
    """

    def __init__(
        self,
        net_type: str = "alex",
        params: Optional[Any] = None,
        seed: int = 0,
        input_size: int = 64,
    ) -> None:
        from metrics_tpu.utils.prints import rank_zero_warn

        if net_type not in _BACKBONES:
            raise ValueError(f"Argument `net_type` must be one of {tuple(_BACKBONES)}, but got {net_type}.")
        self.net_type = net_type
        self.module = _BACKBONES[net_type]()
        self.weights: Optional[List[Array]] = None
        if isinstance(params, (str, bytes)):
            params = self.load_params(params)
        if isinstance(params, dict) and "variables" in params:
            if params.get("net_type") not in (None, net_type):
                raise ValueError(
                    f"Converted LPIPS checkpoint is for net_type={params.get('net_type')!r},"
                    f" but this net is {net_type!r}."
                )
            self.weights = [jnp.asarray(w) for w in params.get("weights", [])] or None
            params = params["variables"]
        if params is None:
            rank_zero_warn(
                "No pretrained LPIPS params provided (no network egress in this build);"
                " using random initialisation. Convert the `lpips` package weights with"
                " `python tools/convert_weights.py lpips ...` for meaningful values.",
                UserWarning,
            )
            dummy = jnp.zeros((1, input_size, input_size, 3), dtype=jnp.float32)
            # jit the init: un-jitted flax init executes the whole net eagerly,
            # one dispatch round-trip per op (~minutes over a tunnelled TPU)
            params = jax.jit(self.module.init)(jax.random.PRNGKey(seed), dummy)
        self.params = params
        self._forward = jax.jit(lambda p, x: self.module.apply(p, x))

    @staticmethod
    def load_params(path: Any) -> Any:
        import pickle

        with open(path, "rb") as f:
            return pickle.load(f)

    def __call__(self, imgs: Array) -> List[Array]:
        if imgs.ndim == 4 and imgs.shape[1] == 3 and imgs.shape[-1] != 3:
            imgs = jnp.transpose(imgs, (0, 2, 3, 1))  # NCHW -> NHWC
        return self._forward(self.params, imgs)
