"""Mesh/axis configuration — the ``process_group`` analogue.

The reference passes a ``process_group`` handle down to ``gather_all_tensors``
(``torchmetrics/metric.py:88``, ``utilities/distributed.py:96``). On TPU the analogue
is a *named mesh axis*: metrics synchronise over one axis of a ``jax.sharding.Mesh``
(usually the data-parallel axis), and "subgroups" are sub-axes of the same mesh.

Two ways to tell a metric its axis:
  1. explicitly: ``Accuracy(sync_axis="dp")``
  2. ambiently: ``with metric_axis("dp"): ...`` around the shard_map'd step.
"""
import contextlib
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

_LOCAL = threading.local()


def current_metric_axis() -> Optional[str]:
    """The ambient sync axis name, if one was set via ``metric_axis``/``set_metric_axis``."""
    return getattr(_LOCAL, "axis", None)


def set_metric_axis(axis: Optional[str]) -> None:
    _LOCAL.axis = axis


@contextlib.contextmanager
def metric_axis(axis: Optional[str]):
    """Context manager: all metric syncs inside use collectives over ``axis``."""
    prev = current_metric_axis()
    set_metric_axis(axis)
    try:
        yield
    finally:
        set_metric_axis(prev)


@dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh description for the metrics runtime.

    ``axis_names``/``shape`` describe the full device mesh; ``sync_axis`` names
    the axis (or tuple of axes) metric states are reduced over. Build with
    ``.make_mesh()``.
    """

    shape: Tuple[int, ...] = (1,)
    axis_names: Tuple[str, ...] = ("dp",)
    sync_axis: "str | Tuple[str, ...]" = "dp"
    devices: Optional[Sequence] = field(default=None, compare=False)

    def make_mesh(self) -> jax.sharding.Mesh:
        devs = self.devices if self.devices is not None else jax.devices()
        n = int(np.prod(self.shape))
        if len(devs) < n:
            raise ValueError(f"mesh shape {self.shape} needs {n} devices, have {len(devs)}")
        arr = np.asarray(devs[:n]).reshape(self.shape)
        return jax.sharding.Mesh(arr, self.axis_names)

    @classmethod
    def data_parallel(cls, n_devices: Optional[int] = None, axis: str = "dp") -> "MeshConfig":
        n = n_devices if n_devices is not None else len(jax.devices())
        return cls(shape=(n,), axis_names=(axis,), sync_axis=axis)

    @classmethod
    def multi_slice(
        cls,
        n_slices: int,
        chips_per_slice: Optional[int] = None,
        *,
        slice_axis: str = "dcn",
        chip_axis: str = "ici",
    ) -> "MeshConfig":
        """Two-level (DCN, ICI) layout for multi-slice TPU deployments.

        The outer axis spans slices connected over the data-center network,
        the inner axis spans chips within a slice on ICI. Metric sync uses the
        TUPLE axis — XLA lowers one logical collective over both levels and
        schedules the slice-local reduction on ICI before crossing DCN, so the
        slow network carries one already-reduced buffer per slice. This is the
        reference's multi-node ``process_group`` analogue
        (``SURVEY.md`` §2.2/§5: "mesh (ICI, and DCN for multi-slice)").

        On real hardware pass device order grouped by slice (the default
        ``jax.devices()`` order already is); on a virtual mesh any order
        models the topology.
        """
        if chips_per_slice is None:
            if len(jax.devices()) % n_slices:
                raise ValueError(
                    f"{len(jax.devices())} devices do not split into {n_slices} equal slices;"
                    " pass chips_per_slice explicitly"
                )
            chips_per_slice = len(jax.devices()) // n_slices
        return cls(
            shape=(n_slices, chips_per_slice),
            axis_names=(slice_axis, chip_axis),
            sync_axis=(slice_axis, chip_axis),
        )
