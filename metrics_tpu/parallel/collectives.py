"""State synchronisation over a named mesh axis — the ``gather_all_tensors`` analogue.

Parity: reference ``torchmetrics/utilities/distributed.py`` —
  * ``gather_all_tensors`` (:96)  -> ``all_gather_stack``/``all_gather_cat`` via
    ``jax.lax.all_gather`` (XLA schedules the collective; no barrier, no separate
    shape-gather: shapes are static under jit, which deletes the reference's
    2-collectives-per-state overhead at :123-145).
  * ``reduce`` (:21) and ``class_reduce`` (:43) -> same-named helpers below (pure jnp).

Beyond parity: ``fused_axis_sync`` merges ALL sum/min/max counter states of a whole
MetricCollection into one flat buffer per reduction and issues a single ``psum``
bundle — O(1) collectives where the reference issues O(metrics x states)
(``metric.py:240-245``).

Quantized sync (ISSUE 10, EQuARX-style): a leaf whose metric declares
``sync_precision="q8_block"`` rides the collective as BLOCK-SCALED INT8 —
per-:data:`Q8_BLOCK`-element absmax scales computed in-trace, int8 codes
packed 4-per-u32-word, scales bitcast alongside into the SAME u32 carrier the
cat/None leaves already share. The decode dequantizes every shard's
contribution and folds the sum locally in f32, so a quantized sum is exact in
the combine and bounded only by the per-shard rounding:
``|err| <= sum_over_shards(block_absmax / 254)`` per element (plus a
denormal-flush floor — see :func:`q8_sum_error_bound`, the oracle every
quantized gate checks against). Eligibility is strict: only float 'sum'
leaves ever quantize; integer counters keep the bit-exact digit rider and
cat/None/custom leaves keep the verbatim carrier. Payload: ``9 * ceil(n/32)``
u32 words per quantized leaf vs ``n`` words exact — ~3.6x fewer bytes on the
wire (:func:`sync_payload_bytes` is the shared accounting).
"""
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from metrics_tpu.utils.data import METRIC_EPS

Array = jax.Array

#: cross-chip collective ops as they appear in compiled HLO text — the ONE
#: pattern every gate asserting collective placement uses (``make mesh-smoke``,
#: ``__graft_entry__``'s deferred-engine dryrun, the mesh engine tests): the
#: deferred-sync steady step must match ZERO of these, the step-sync step and
#: the boundary merge at least one.
HLO_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|all-to-all|collective-permute|reduce-scatter)(?:-start)?\("
)

# an axis spec: one mesh-axis name or a tuple of names (multi-axis collectives)
AxisSpec = Union[str, Tuple[str, ...]]


def _axis_names(axis_name: Any) -> Tuple[Any, ...]:
    """Normalize an axis spec (single name or tuple of names — multi-axis
    collectives like ``("dp", "grp")`` are first-class in XLA) to a tuple."""
    return tuple(axis_name) if isinstance(axis_name, (tuple, list)) else (axis_name,)


def in_mapped_context(axis_name: Optional[AxisSpec]) -> bool:
    """True if every axis in ``axis_name`` is bound by an enclosing shard_map/pmap."""
    if axis_name is None:
        return False
    names = _axis_names(axis_name)
    if not names:
        return False
    try:
        from jax._src.core import get_axis_env

        env = get_axis_env()
        return all(bool(env.axis_exists(n)) for n in names)
    except Exception:
        return False


def axis_size_or_one(axis_name: Optional[AxisSpec]) -> int:
    if not in_mapped_context(axis_name):
        return 1
    from jax._src.core import get_axis_env

    env = get_axis_env()
    size = 1
    for n in _axis_names(axis_name):
        size *= int(env.axis_size(n))
    return size


def all_gather_cat(x: Array, axis_name: AxisSpec) -> Array:
    """Gather shards along dim 0 (the "cat" reduction): (n,...) -> (world*n, ...)."""
    return lax.all_gather(x, axis_name, tiled=True)


def all_gather_stack(x: Array, axis_name: AxisSpec) -> Array:
    """Gather shards stacked on a new leading dim: (...,) -> (world, ...).

    Matches the reference's post-sync layout for ``dist_reduce_fx=None`` tensor states
    (``metric.py:249-252``: stacked, for the metric's own custom merge at compute).
    """
    return lax.all_gather(x, axis_name, tiled=False)


_REDUCE_COLLECTIVES: Dict[str, Callable] = {
    "sum": lax.psum,
    "mean": lax.pmean,
    "min": lax.pmin,
    "max": lax.pmax,
}


def sync_axis_state(reduce_fx: Any, value: Array, axis_name: AxisSpec) -> Array:
    """Lower one state's ``dist_reduce_fx`` to the matching XLA collective."""
    if reduce_fx in _REDUCE_COLLECTIVES:
        return _REDUCE_COLLECTIVES[reduce_fx](value, axis_name)
    if reduce_fx == "cat":
        return all_gather_cat(value, axis_name)
    if reduce_fx is None:
        return all_gather_stack(value, axis_name)
    if callable(reduce_fx):
        # custom reduce: gather replicas then fold pairwise with the user fn
        gathered = all_gather_stack(value, axis_name)
        out = gathered[0]
        for i in range(1, gathered.shape[0]):
            out = reduce_fx(out, gathered[i])
        return out
    raise ValueError(f"unknown dist_reduce_fx: {reduce_fx!r}")


def fused_axis_sync(
    leaves: List[Tuple[Any, Array]],
    axis_name: AxisSpec,
    precisions: Optional[Sequence[Optional[str]]] = None,
) -> List[Array]:
    """Sync many (reduce_fx, value) state leaves with a minimal collective bundle.

    The floor is ONE all-reduce + ONE all-gather for a whole MetricCollection:

    * ALL 'sum' leaves ride a single f32 psum: f32 goes as-is, f16/bf16 upcast
      (exactly — both embed in f32), and integer counters are split into
      f32-exactly-summable bit parts sized by the STATIC world size
      (``_int_split_bits``), then reassembled with u32 wraparound arithmetic —
      bit-exact for every input, including negatives and i32 overflow, at any
      world size (parts shrink as the mesh grows). 'mean'/'min'/'max' leaves
      keep one collective per (reduction, dtype) — pmin/pmax on a converted
      carrier would round large-magnitude ints, and those reductions are rare
      in real collections.
    * ALL 'cat'/None/custom leaves share a single u32-carrier ``all_gather``:
      1/2-byte dtypes pad to a word boundary and pack 4/2-to-1, 8-byte dtypes
      split 1-to-2 — bitcasts are free, the padding is <=3 bytes per leaf.
      Per-leaf views are reassembled locally: (world, n, ...) -> (world*n, ...)
      for 'cat', (world, ...) for None, and a pairwise fold for callables.
    * QUANTIZED float 'sum' leaves (``precisions[i] == "q8_block"``) leave the
      psum bundle and ride the same u32 all_gather as block-scaled int8
      (codes packed 4-per-word + f32 scales); the decode dequantizes every
      shard's contribution and sums locally in f32 — bandwidth drops ~3.6x
      per quantized leaf, error bounded by :func:`q8_sum_error_bound`.

    ``precisions`` aligns with ``leaves``; None (or ``"exact"`` entries) keeps
    every leaf on the bit-exact paths above — nothing changes silently.

    Returns synced values in input order. A MetricCollection of K metrics with
    S states issues <=2 collectives (+ one per exotic reduction), not O(K*S)
    (the reference's pattern, ``metric.py:240-245``).
    """
    out: List[Optional[Array]] = [None] * len(leaves)
    sum_bucket: List[int] = []
    reduce_buckets: Dict[Tuple[str, Any], List[int]] = {}
    gather_bucket: List[int] = []
    q8_bucket: List[int] = []
    for i, (fx, v) in enumerate(leaves):
        dtype = jnp.asarray(v).dtype
        prec = (precisions[i] if precisions is not None else None) or "exact"
        if prec not in SYNC_PRECISIONS:
            raise ValueError(
                f"unknown sync precision {prec!r}; expected one of {SYNC_PRECISIONS}"
            )
        if prec == "q8_block":
            if fx != "sum" or _sum_rider(dtype) != "float":
                raise ValueError(
                    f"sync_precision='q8_block' needs a float 'sum' leaf, got "
                    f"dist_reduce_fx={fx!r} dtype={dtype} — counts, cat buffers and "
                    "min/max states must stay exact"
                )
            q8_bucket.append(i)
        elif fx == "sum" and _sum_rider(dtype) is not None:
            sum_bucket.append(i)
        elif fx in _REDUCE_COLLECTIVES:
            reduce_buckets.setdefault((fx, dtype), []).append(i)
        else:
            gather_bucket.append(i)

    if sum_bucket:
        world = axis_size_or_one(axis_name)
        bits = _int_split_bits(world)
        payloads = [_to_sum_rider(leaves[i][1], bits) for i in sum_bucket]
        sizes = [p.size for p in payloads]
        flat = jnp.concatenate(payloads) if len(payloads) > 1 else payloads[0]
        synced = lax.psum(flat, axis_name)
        off = 0
        for i, n in zip(sum_bucket, sizes):
            piece = lax.slice(synced, (off,), (off + n,))
            out[i] = _from_sum_rider(piece, leaves[i][1], bits)
            off += n

    for (fx, _dtype), idxs in reduce_buckets.items():
        vals = [jnp.ravel(jnp.asarray(leaves[i][1])) for i in idxs]
        sizes = [v.size for v in vals]
        flat = jnp.concatenate(vals) if len(vals) > 1 else vals[0]
        synced = _REDUCE_COLLECTIVES[fx](flat, axis_name)
        off = 0
        for i, n in zip(idxs, sizes):
            piece = lax.slice(synced, (off,), (off + n,))
            out[i] = piece.reshape(jnp.shape(leaves[i][1]))
            off += n

    if gather_bucket or q8_bucket:
        # gathers are layout-agnostic: every leaf packs into ONE u32 carrier
        # (free bitcasts; sub-word dtypes pad to a word boundary first).
        # Quantized sum leaves SHARE the carrier: codes + scales are just more
        # words, so however many leaves quantize, the collective count holds.
        payloads = [_to_carrier_u32(leaves[i][1]) for i in gather_bucket]
        payloads += [_q8_carrier(leaves[i][1]) for i in q8_bucket]
        sizes = [p.size for p in payloads]
        flat = jnp.concatenate(payloads) if len(payloads) > 1 else payloads[0]
        gathered = lax.all_gather(flat, axis_name, tiled=False)  # (world, words)
        world = gathered.shape[0]
        off = 0
        for i, n in zip(gather_bucket, sizes[: len(gather_bucket)]):
            fx, v = leaves[i]
            v = jnp.asarray(v)
            shape = v.shape
            raw = lax.slice(gathered, (0, off), (world, off + n))
            piece = _from_carrier_u32(raw, v.dtype, shape)
            off += n
            if fx == "cat":
                out[i] = piece.reshape((world * shape[0],) + shape[1:])
            elif fx is None:
                out[i] = piece
            elif callable(fx):
                acc = piece[0]
                for w in range(1, world):
                    acc = fx(acc, piece[w])
                out[i] = acc
            else:
                raise ValueError(f"unknown dist_reduce_fx: {fx!r}")
        for i, n in zip(q8_bucket, sizes[len(gather_bucket):]):
            raw = lax.slice(gathered, (0, off), (world, off + n))
            out[i] = _q8_sum_from_gathered(raw, leaves[i][1])
            off += n
    return out  # type: ignore[return-value]


# ------------------------------------------------ sum-rider encoding (one psum)

_INT_RIDERS = (jnp.int8, jnp.uint8, jnp.int16, jnp.uint16, jnp.int32, jnp.uint32)
_FLOAT_RIDERS = (jnp.float32, jnp.float16, jnp.bfloat16)


def _sum_rider(dtype: Any) -> Optional[str]:
    """How a 'sum' leaf of ``dtype`` rides the shared f32 psum (None = cannot)."""
    if any(dtype == d for d in _FLOAT_RIDERS):
        return "float"
    if any(dtype == d for d in _INT_RIDERS):
        return "int"
    return None


def _int_split_bits(world: int) -> int:
    """Bits per integer part so a psum over ``world`` devices stays exact in f32:
    each part < 2**bits, so part-sums < world * 2**bits <= 2**24."""
    import math

    headroom = max(1, int(math.ceil(math.log2(max(world, 1)))))
    return max(1, min(16, 24 - headroom))


def _to_sum_rider(v: Array, bits: int) -> Array:
    """Encode one 'sum' leaf as a flat f32 payload for the shared psum."""
    v = jnp.asarray(v)
    if _sum_rider(v.dtype) == "float":
        return jnp.ravel(v).astype(jnp.float32)
    # two's-complement bitpattern -> base-2**bits digits, each f32-exactly-summable
    u = jnp.ravel(v).astype(jnp.uint32) if v.dtype != jnp.uint32 else jnp.ravel(v)
    if v.dtype in (jnp.int8, jnp.int16, jnp.int32):
        u = lax.bitcast_convert_type(jnp.ravel(v).astype(jnp.int32), jnp.uint32)
    nparts = -(-32 // bits)
    mask = jnp.uint32((1 << bits) - 1)
    parts = [((u >> jnp.uint32(bits * p)) & mask).astype(jnp.float32) for p in range(nparts)]
    return jnp.concatenate(parts)


def _from_sum_rider(piece: Array, ref: Array, bits: int) -> Array:
    """Decode a psummed payload back to the leaf's dtype (u32 wraparound
    reconstruction == the native integer psum, overflow semantics included)."""
    ref = jnp.asarray(ref)
    shape = jnp.shape(ref)
    if _sum_rider(ref.dtype) == "float":
        return piece.reshape(shape).astype(ref.dtype)
    nparts = -(-32 // bits)
    n = piece.size // nparts
    total = jnp.zeros((n,), jnp.uint32)
    for p in range(nparts):
        part = lax.slice(piece, (p * n,), ((p + 1) * n,))
        total = total + (part.astype(jnp.uint32) << jnp.uint32(bits * p))
    if ref.dtype in (jnp.int8, jnp.int16, jnp.int32):
        return lax.bitcast_convert_type(total, jnp.int32).astype(ref.dtype).reshape(shape)
    return total.astype(ref.dtype).reshape(shape)


_CARRIERS = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def _to_carrier_u32(v: Array) -> Array:
    """Ravel one gather leaf into flat u32 words (free bitcasts; sub-word
    dtypes zero-pad to a word boundary and pack 4/2-to-1, 8-byte split 1-to-2)."""
    v = jnp.asarray(v)
    if v.dtype == jnp.bool_:
        v = v.astype(jnp.uint8)
    flat = jnp.ravel(v)
    itemsize = jnp.dtype(v.dtype).itemsize
    if itemsize == 4:
        return flat if v.dtype == jnp.uint32 else lax.bitcast_convert_type(flat, jnp.uint32)
    if itemsize == 8:
        return jnp.ravel(lax.bitcast_convert_type(flat, jnp.uint32))  # (n,) -> (n,2) -> (2n,)
    per = 4 // itemsize
    pad = (-flat.size) % per
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return lax.bitcast_convert_type(flat.reshape(-1, per), jnp.uint32)


def _from_carrier_u32(raw: Array, dtype: Any, shape: Tuple[int, ...]) -> Array:
    """Inverse of ``_to_carrier_u32`` for a gathered ``(world, words)`` slab:
    returns ``(world,) + shape`` in the leaf's dtype."""
    import math

    world = raw.shape[0]
    tgt = jnp.uint8 if dtype == jnp.bool_ else dtype
    itemsize = jnp.dtype(tgt).itemsize
    n_elems = math.prod(shape) if shape else 1
    if itemsize == 4:
        vals = raw if jnp.dtype(tgt) == jnp.uint32 else lax.bitcast_convert_type(raw, tgt)
    elif itemsize == 8:
        vals = lax.bitcast_convert_type(raw.reshape(world, -1, 2), tgt)
    else:
        small = lax.bitcast_convert_type(raw, _CARRIERS[itemsize])  # (world, words, per)
        vals = small.reshape(world, -1)[:, :n_elems]
        if jnp.dtype(tgt) != jnp.dtype(_CARRIERS[itemsize]):
            vals = lax.bitcast_convert_type(vals, tgt)
    vals = vals.reshape((world,) + tuple(shape))
    return vals.astype(jnp.bool_) if dtype == jnp.bool_ else vals


# ------------------------------------------- q8_block quantized rider (ISSUE 10)

#: elements per absmax-scale block of the block-scaled int8 codec. 32 keeps
#: scales local enough that a single-outlier block cannot poison its
#: neighbours' precision, is a multiple of the 4-codes-per-word packing, and
#: costs 1 scale word per 8 code words (payload = 9 * ceil(n/32) u32 words
#: per quantized leaf vs n words exact — ~3.6x fewer bytes).
Q8_BLOCK = 32

#: the declared sync precisions. "exact" is the default everywhere — nothing
#: quantizes unless a metric's policy says so (metric.py::set_sync_precision).
SYNC_PRECISIONS = ("exact", "q8_block")

#: blocks whose absmax sits below this flush to zero codes: the scale
#: absmax/127 would be subnormal there, and 1/scale overflows f32. The flush
#: error (<= absmax < Q8_FLUSH per element per shard) is folded into
#: :func:`q8_sum_error_bound`'s floor term.
Q8_FLUSH = 1.5e-36


def _q8_block_count(n: int, block: int = Q8_BLOCK) -> int:
    return -(-int(n) // int(block))


def q8_carrier_words(n: int, block: int = Q8_BLOCK) -> int:
    """u32 carrier words one quantized leaf of ``n`` elements contributes:
    block-padded int8 codes packed 4-per-word plus one f32 scale per block."""
    nb = _q8_block_count(n, block)
    return nb * (block // 4) + nb


def _q8_encode(v: Array, block: int = Q8_BLOCK) -> Tuple[Array, Array]:
    """One shard's block-scaled int8 encoding of a float leaf (in-trace):
    ``(codes int8 (nb*block,), scales f32 (nb,))``. ``|x - code*scale| <=
    scale/2`` per element (codes never clip: |x| <= absmax maps to exactly
    +-127); near-subnormal blocks flush to zero codes (see ``Q8_FLUSH``)."""
    flat = jnp.ravel(jnp.asarray(v)).astype(jnp.float32)
    nb = _q8_block_count(flat.size, block)
    pad = nb * block - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(nb, block)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.where(absmax >= Q8_FLUSH, absmax / 127.0, 0.0)
    inv = jnp.where(scales > 0, 1.0 / scales, 0.0)
    codes = jnp.clip(jnp.round(blocks * inv[:, None]), -127.0, 127.0).astype(jnp.int8)
    return codes.reshape(-1), scales


def _q8_carrier(v: Array, block: int = Q8_BLOCK) -> Array:
    """Encode one quantized sum leaf into flat u32 carrier words:
    ``[packed int8 codes | bitcast f32 scales]`` — the scales travel
    alongside the payload in the SAME collective."""
    codes, scales = _q8_encode(v, block)
    return jnp.concatenate([_to_carrier_u32(codes), _to_carrier_u32(scales)])


def _q8_sum_from_gathered(raw: Array, ref: Array, block: int = Q8_BLOCK) -> Array:
    """Decode a gathered ``(world, words)`` q8 slab back to the summed leaf:
    each shard's codes dequantize against its OWN scales and the
    contributions fold in f32 — the combine is exact, only the per-shard
    rounding remains (:func:`q8_sum_error_bound`)."""
    ref = jnp.asarray(ref)
    n = ref.size
    nb = _q8_block_count(n, block)
    ncodes = nb * block
    world = raw.shape[0]
    code_words = ncodes // 4
    codes = _from_carrier_u32(
        lax.slice(raw, (0, 0), (world, code_words)), jnp.int8, (ncodes,)
    )
    scales = _from_carrier_u32(
        lax.slice(raw, (0, code_words), (world, raw.shape[1])), jnp.float32, (nb,)
    )
    contrib = codes.astype(jnp.float32).reshape(world, nb, block) * scales[:, :, None]
    total = jnp.sum(contrib, axis=0).reshape(-1)[:n]
    return total.reshape(ref.shape).astype(ref.dtype)


def q8_roundtrip(v: Any, block: int = Q8_BLOCK) -> Any:
    """One shard's encode→decode round-trip (no collective): what a single
    quantized contribution loses — by construction identical to the W=1
    quantized sum, which the fuzz suite pins against the carrier path."""
    import numpy as np

    ref = jnp.asarray(v)
    codes, scales = _q8_encode(ref, block)
    vals = np.asarray(codes, np.float32).reshape(-1, block) * np.asarray(scales)[:, None]
    return np.asarray(vals.reshape(-1)[: ref.size], np.float32).reshape(np.shape(ref))


def q8_sum_error_bound(stacked: Any, block: int = Q8_BLOCK) -> Any:
    """Per-element |error| bound of the q8_block quantized sum of ``stacked``
    (leading axis = shard) vs the exact f32 sum — THE oracle every quantized
    gate checks against (fuzz suite, quant-smoke, the engine's bounded-error
    assertions). Per shard per element: ``scale/2`` (rounding) where the
    block quantizes, ``absmax`` (< ``Q8_FLUSH``) where it flushes; summed
    over shards. Host-side numpy; returns an array shaped like one shard."""
    import numpy as np

    arr = np.asarray(stacked, np.float32)
    world = arr.shape[0]
    flat = arr.reshape(world, -1)
    n = flat.shape[1]
    nb = _q8_block_count(n, block)
    padded = np.zeros((world, nb * block), np.float32)
    padded[:, :n] = flat
    absmax = np.abs(padded.reshape(world, nb, block)).max(axis=2)
    flushed = absmax < Q8_FLUSH
    per_block = np.where(flushed, absmax, absmax / 254.0)  # absmax/127/2
    per_elem = np.repeat(per_block, block, axis=1)[:, :n].sum(axis=0)
    return per_elem.reshape(arr.shape[1:])


# ------------------------------------------- payload accounting (shared source)


def fused_sync_plan(
    leaves: Sequence[Tuple[Any, Any, Optional[str]]], world: int, block: int = Q8_BLOCK
) -> Dict[str, Any]:
    """The analytic payload layout of one fused sync over ``leaves`` —
    ``(dist_reduce_fx, abstract/array leaf, precision)`` triples — on a
    ``world``-shard axis: how :func:`fused_axis_sync` buckets them and how
    many elements/words each collective moves per shard. The single source
    the bench's ``sync_payload_bytes``, the engine's payload counters, and
    the ``quantized-sync-policy-honored`` analysis rule all derive from (the
    rule's clean-twin fixture pins this against an actual trace)."""
    sum_elems = 0
    gather_words = 0
    q8_words = 0
    reduce_elems: Dict[Tuple[str, str], int] = {}
    quantized: List[int] = []
    bits = _int_split_bits(max(1, int(world)))
    nparts = -(-32 // bits)
    for i, (fx, leaf, prec) in enumerate(leaves):
        dt = getattr(leaf, "dtype", None)
        dtype = jnp.dtype(dt) if dt is not None else jnp.asarray(leaf).dtype
        shape = getattr(leaf, "shape", None)
        size = 1
        for d in (shape if shape is not None else jnp.shape(leaf)):
            size *= int(d)
        prec = prec or "exact"
        if prec == "q8_block" and fx == "sum" and _sum_rider(dtype) == "float":
            q8_words += q8_carrier_words(size, block)
            quantized.append(i)
        elif fx == "sum" and _sum_rider(dtype) is not None:
            sum_elems += size if _sum_rider(dtype) == "float" else size * nparts
        elif fx in _REDUCE_COLLECTIVES:
            key = (str(fx), dtype.name)
            reduce_elems[key] = reduce_elems.get(key, 0) + size
        else:
            itemsize = dtype.itemsize if dtype != jnp.bool_ else 1
            if itemsize >= 4:
                gather_words += size * (itemsize // 4)
            else:
                per = 4 // itemsize
                gather_words += -(-size // per)
    return {
        "sum_elems": sum_elems,
        "reduce_elems": reduce_elems,
        "gather_words": gather_words,
        "q8_words": q8_words,
        "quantized": quantized,
    }


def sync_payload_bytes(
    leaves: Sequence[Tuple[Any, Any, Optional[str]]], world: int, block: int = Q8_BLOCK
) -> int:
    """Bytes one shard contributes to the fused sync's collectives under the
    given per-leaf precisions (psum bundle f32 + reduce buckets + u32
    carrier). Compare against the same call with all-"exact" precisions for
    the quantization ratio — BENCH.sync_payload's headline."""
    plan = fused_sync_plan(leaves, world, block)
    nbytes = 4 * plan["sum_elems"] + 4 * (plan["gather_words"] + plan["q8_words"])
    for (_, dtype_name), elems in plan["reduce_elems"].items():
        nbytes += jnp.dtype(dtype_name).itemsize * elems
    return int(nbytes)


def hierarchical_fold_bytes(
    leaves: Sequence[Tuple[Any, Any, Optional[str]]],
    hosts: int,
    block: int = Q8_BLOCK,
) -> Dict[str, int]:
    """Per-leg byte accounting of the HIERARCHICAL fleet fold (ISSUE 20):
    each host first folds its own logical state exactly (the intra leg —
    device-local, never on the wire between hosts), then ONE representative
    per host enters the cross-host sync, whose q8-eligible leaves ride the
    q8_block codec under the same ``sync_precision`` policy the mesh
    boundary merge honors. ``leaves`` are the host-LOGICAL
    ``(dist_reduce_fx, abstract leaf, precision)`` triples (the engine's
    ``_fleet_leaf_info``); the cross legs reuse :func:`fused_sync_plan`
    verbatim, so this helper can never drift from the wire accounting the
    engine records. Cross-host wire bytes scale with ``hosts``, not with
    the stream count — the stream axis lives inside each leaf, folded
    before the wire."""
    intra = 0
    for _fx, leaf, _prec in leaves:
        dt = getattr(leaf, "dtype", None)
        dtype = jnp.dtype(dt) if dt is not None else jnp.asarray(leaf).dtype
        shape = getattr(leaf, "shape", None)
        size = 1
        for d in (shape if shape is not None else jnp.shape(leaf)):
            size *= int(d)
        intra += size * (dtype.itemsize if dtype != jnp.bool_ else 1)
    plan = fused_sync_plan(leaves, hosts, block)
    total = sync_payload_bytes(leaves, hosts, block)
    quant = 4 * plan["q8_words"]
    return {
        "intra_bytes": int(intra),
        "cross_exact_bytes": int(total - quant),
        "cross_quant_bytes": int(quant),
    }


def reduce(x: Array, reduction: str) -> Array:
    """Elementwise->scalar reduction. Parity: ``utilities/distributed.py:21-40``."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction == "none" or reduction is None:
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Class-averaged fraction num/denom with micro/macro/weighted/none reduction.

    Parity: ``utilities/distributed.py:43-87``.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    if class_reduction == "micro":
        fraction = jnp.sum(num) / (jnp.sum(denom) + METRIC_EPS)
    else:
        fraction = num / (denom + METRIC_EPS)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights / jnp.sum(weights)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between {valid_reduction}")
